"""Benchmark: per-epoch training time at Reddit scale.

Reproduces the reference's headline measurement — per-epoch wall-clock of
a 4-layer x 256 GraphSAGE with --enable-pipeline --use-pp on Reddit
(232,965 nodes / ~114.6M directed edges / 602 features / 41 classes;
reference README.md:93-94 reports 0.266 s/epoch on 2 GPUs) — on TPU,
using a synthetic graph with Reddit's shape statistics (the real dataset
needs a download this environment does not allow).

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ..., ...}
vs_baseline > 1 means faster than the reference's 0.266 s/epoch. Extra
keys: backend/device, MFU, estimated HBM + ICI traffic, and the
pipelined-vs-vanilla epoch-time comparison (the overlap evidence).

Backend init is hardened: the TPU backend is probed in a subprocess with
retry + backoff (a transient UNAVAILABLE from a stale chip holder must
not kill the run), and if the TPU never comes up the bench falls back to
CPU and still reports a (clearly labeled) number rather than rc=1.

The partition/build artifact is cached under partitions/ so repeat runs
skip the ~minutes of host-side preprocessing. Use --small for a quick
smoke-scale run, --parts N to shard over N devices.
"""

import argparse
import dataclasses
import json
import os
import subprocess
import sys
import time

import numpy as np

from pipegcn_tpu.obs.hw import peak_flops_for

BASELINE_EPOCH_S = 0.266  # reference README.md:93-94 (2x GPU)

# repo root: artifacts and result records anchor here, never the CWD
REPO = os.path.dirname(os.path.abspath(__file__))

# Cap on the wall-clock of ONE device dispatch. The axon tunnel has been
# observed to kill the TPU worker mid-run under long Execute calls
# (~80 s fused blocks died; ~20 s single epochs survived), so the bench
# adaptively drops to single-epoch dispatches when fused blocks would
# exceed this.
MAX_DISPATCH_S = 25.0

# Mid-run degradation ladder: when the TPU worker crashes AFTER a good
# probe (a failure mode round 1's init-only hardening did not cover),
# the bench re-execs itself one stage down rather than dying with rc=1.
#   stage 0: as requested
#   stage 1: minimal sampling (fused=1, 3 blocks, no comparison/sweep)
#   stage 2: --small smoke scale
#   stage 3: CPU fallback
_STAGE_FLAG = "--_stage"


class NonFiniteLoss(RuntimeError):
    """A training loss went non-finite mid-measurement: the run is
    diverged, and timing a diverged program measures the wrong program
    — abort IMMEDIATELY (the offshape-products NaN burned three full
    measurement blocks after the first NaN epoch, VERDICT r5) with a
    loud fault record and exit 3 instead of publishing green JSON."""

    def __init__(self, epoch: int, loss: float):
        super().__init__(
            f"non-finite loss {loss!r} at epoch {epoch}")
        self.epoch = epoch
        self.loss = loss


def _check_finite(loss: float, epoch: int) -> None:
    if not np.isfinite(loss):
        print(f"# NON-FINITE LOSS at epoch {epoch} — aborting the "
              f"measurement now (every further block would time a "
              f"diverged program)", file=sys.stderr)
        raise NonFiniteLoss(epoch, float(loss))


def _reexec_degraded(stage: int, reason: str) -> None:
    delay = min(30.0 * (2 ** stage), 120.0)
    print(f"# measurement crashed at stage {stage}: {reason}\n"
          f"# re-exec at stage {stage + 1} in {delay:.0f}s", file=sys.stderr)
    time.sleep(delay)
    argv = list(sys.argv)
    i = 0
    while i < len(argv):  # strip any previous stage flag (+ value token)
        if argv[i] == _STAGE_FLAG:
            del argv[i:i + 2]
        elif argv[i].startswith(_STAGE_FLAG + "="):
            del argv[i]
        else:
            i += 1
    os.execv(sys.executable,
             [sys.executable] + argv + [_STAGE_FLAG, str(stage + 1)])

# the peak-FLOPs table lives in pipegcn_tpu/obs/hw.py (shared with the
# report CLI's MFU computation)


def probe_backend(timeout_s: float) -> dict:
    """Try to initialize the default jax backend in a SUBPROCESS.

    A failed in-process `jax.devices()` poisons jax's backend cache for
    the life of the process, so probing must happen out-of-process; only
    after a probe succeeds does the parent import jax for real. Returns
    {"ok": bool, "detail": str}.
    """
    code = (
        "import jax, json, sys;"
        "ds = jax.devices();"
        "print(json.dumps({'n': len(ds), 'kind': ds[0].device_kind,"
        " 'platform': ds[0].platform}))"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return {"ok": False, "detail": f"probe timed out after {timeout_s}s"}
    if r.returncode == 0 and r.stdout.strip():
        return {"ok": True, "detail": r.stdout.strip().splitlines()[-1]}
    tail = (r.stderr or "").strip().splitlines()[-3:]
    return {"ok": False, "detail": " | ".join(tail) or f"rc={r.returncode}"}


def init_backend(max_tries: int, probe_timeout: float, force_cpu: bool) -> str:
    """Probe-with-retry; on persistent failure fall back to CPU.

    Returns the backend label ("tpu", "cpu", "cpu-fallback", ...). Round 1
    shipped no perf number because a single transient
    'UNAVAILABLE: TPU backend setup/compile error' at jax.devices()
    crashed the bench (BENCH_r01.json rc=1); this makes that path
    impossible: worst case is a CPU-labeled fallback measurement.

    NOTE: this environment's site hook pins JAX_PLATFORMS, so choosing
    CPU must happen via jax.config.update AFTER import (the caller does
    that when the returned label starts with "cpu") — the env var alone
    is silently overridden.
    """
    if force_cpu or os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        return "cpu"
    if probe_timeout:
        # explicit override: fixed per-attempt timeout, classic retry
        schedule = [(probe_timeout, 5.0)] * (max_tries or 3)
    else:
        # adaptive: one generous attempt (slow-but-healthy init gets
        # room), then cheap frequent polls for the rest of the budget.
        # Rounds 2-4 all CPU-degraded because 3 long probes sampled the
        # sporadic tunnel only 3 times in ~18 min; a dead tunnel fails
        # each 75 s probe fast, so polling every ~90 s samples the same
        # wall-clock ~6x more often (docs/PERF_NOTES.md tunnel notes).
        budget = float(os.environ.get("BENCH_TPU_WAIT_S", "900"))
        schedule = [(120.0, 15.0)]
        spent = 120.0
        while spent < budget:
            schedule.append((75.0, 15.0))
            spent += 90.0
        if max_tries:  # explicit --probe-tries caps the adaptive poll
            schedule = schedule[:max_tries]
    n = len(schedule)
    for attempt, (tmo, delay) in enumerate(schedule, 1):
        t0 = time.perf_counter()
        res = probe_backend(tmo)
        dt = time.perf_counter() - t0
        if res["ok"]:
            print(f"# backend probe ok (attempt {attempt}, {dt:.0f}s): "
                  f"{res['detail']}", file=sys.stderr)
            info = json.loads(res["detail"])
            return info["platform"]
        print(f"# backend probe FAILED (attempt {attempt}/{n}, "
              f"{dt:.0f}s): {res['detail']}", file=sys.stderr)
        if attempt < n:
            time.sleep(delay)
    print("# backend unavailable after all retries — falling back to CPU "
          "(numbers below are NOT a TPU measurement)", file=sys.stderr)
    return "cpu-fallback"


def persist_last_tpu(value, vs_baseline, extras, backend,
                     device_kind) -> None:
    """Atomically record a real-TPU headline to
    results/last_tpu_bench.json so a later degraded/CPU run can still
    surface the most recent real measurement. Called both for the
    final result AND for the best-so-far number right before the
    riskier lever/sweep compiles (a worker death must not lose an
    in-hand measurement)."""
    last_path = os.path.join(REPO, "results", "last_tpu_bench.json")
    try:
        import datetime

        os.makedirs(os.path.dirname(last_path), exist_ok=True)
        tmp = last_path + ".tmp"
        with open(tmp, "w") as f:
            rec = {
                "metric": "reddit_scale_epoch_time", "value": value,
                "unit": "s/epoch",
                "vs_baseline": vs_baseline,
                "backend": backend, "device": device_kind,
                # the config that PRODUCED the number (the candidate
                # pass may have taken the headline)
                "spmm_impl": extras["spmm_impl"],
                "dtype": extras["dtype"],
                "measured_utc": datetime.datetime.now(
                    datetime.timezone.utc).isoformat(),
            }
            if extras.get("headline_config"):
                rec["headline_config"] = extras["headline_config"]
                rec["block_group"] = 4
                rec["rem_dtype"] = "float8"
            if extras.get("tuning"):
                rec["tuning"] = extras["tuning"]
            json.dump(rec, f)
        os.replace(tmp, last_path)  # atomic: a mid-write kill must
        # not destroy the previous good record
    except OSError:
        pass


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true",
                    help="10k-node smoke config instead of Reddit scale")
    ap.add_argument("--parts", type=int, default=0,
                    help="partitions (default: all available devices)")
    ap.add_argument("--blocks", type=int, default=8,
                    help="timed samples; each sample is one dispatch of "
                         "--fused epochs (sample count is independent of "
                         "--fused so the median is equally stable)")
    ap.add_argument("--no-pipeline", action="store_true",
                    help="measure the vanilla (synchronous-halo) step as "
                         "the headline instead of the pipelined one")
    ap.add_argument("--no-compare", action="store_true",
                    help="skip the pipelined-vs-vanilla comparison run")
    ap.add_argument("--f32", action="store_true",
                    help="float32 compute (default bfloat16, the "
                         "TPU-native choice)")
    ap.add_argument("--fused", type=int, default=4,
                    help="epochs per dispatch (lax.scan); per-epoch time "
                         "= block time / fused")
    ap.add_argument("--spmm-impl", default="auto",
                    choices=["xla", "bucket", "block", "auto"])
    ap.add_argument("--block-tile", type=int, default=256,
                    help="dense-tile edge for the block kernel")
    from pipegcn_tpu.partition.partitioner import DEFAULT_CLUSTER_SIZE

    ap.add_argument("--cluster-size", type=int,
                    default=DEFAULT_CLUSTER_SIZE,
                    help="locality-cluster target size for the local "
                         "renumbering (docs/PERF_NOTES.md round-3 "
                         "addendum: measured sweep)")
    ap.add_argument("--block-nnz", type=int, default=0,
                    help="dense threshold override (0 = break-even)")
    ap.add_argument("--block-group", type=int, default=1,
                    help="union-gather group size for the block "
                         "kernel's dense path (1 = per-tile lists)")
    ap.add_argument("--bucket-merge", type=int, default=0,
                    help="merge bucket widths below 2^k into the 2^k "
                         "bucket (0 = full ladder) — the non-SpMM-floor "
                         "lever: fewer buckets, fewer fixed per-bucket "
                         "dispatch overheads")
    ap.add_argument("--reorder", default="auto",
                    choices=["auto", "none", "degree", "bfs",
                             "degree-bfs"],
                    help="per-partition node reordering baked into the "
                         "bench artifact (locality lever: contiguous "
                         "gather-index runs). 'auto' reuses an existing "
                         "artifact or takes the measured winner "
                         "(ops/tuner.choose_reorder)")
    ap.add_argument("--slab", default="auto",
                    choices=["auto", "on", "off"],
                    help="slab-gather streaming plans over contiguous "
                         "index runs in the bucket/block-remainder "
                         "tables ('auto' = the tuner's measured "
                         "reorder x slab winner)")
    ap.add_argument("--lane-pad", action="store_true",
                    help="zero-pad input features to the 128-lane "
                         "boundary (whole-tile feature reads; outputs "
                         "unchanged, layer-0 init draw differs)")
    ap.add_argument("--tune", action="store_true", dest="tune",
                    default=True, help=argparse.SUPPRESS)
    ap.add_argument("--no-tune", action="store_false", dest="tune",
                    help="with --spmm-impl auto: never run the live "
                         "micro-bench tuner; fall back to the "
                         "deterministic default when no persisted "
                         "tuning table is trusted")
    ap.add_argument("--tuner-samples", type=int, default=200_000,
                    help="edge budget for the tuner's sampled slice")
    ap.add_argument("--rem-dtype", default="none",
                    choices=["none", "bfloat16", "float8"],
                    help="gather-transport dtype for the remainder "
                         "(float8: e4m3/e5m2, f32 accumulation)")
    ap.add_argument("--rng-impl", default="threefry",
                    choices=["threefry", "rbg", "unsafe_rbg"],
                    help="dropout PRNG implementation (floor lever 1)")
    ap.add_argument("--dropout-bits", type=int, default=32,
                    choices=[8, 32],
                    help="dropout mask generation width (8 = one "
                         "random byte per element)")
    ap.add_argument("--halo-dtype", default="none",
                    choices=["none", "bfloat16", "float8"],
                    help="halo ppermute wire dtype (floor lever 2; "
                         "pipelined runs only)")
    ap.add_argument("--epoch-block", type=int, default=0,
                    help="megastep dispatch size override "
                         "(0 = --fused; floor lever 3)")
    ap.add_argument("--comm-prefetch", action="store_true",
                    help="issue the layer-0 halo collective at step "
                         "top (floor lever 4; no-op under the "
                         "headline's use_pp config)")
    ap.add_argument("--sweep-spmm", action="store_true",
                    help="also time every SpMM impl and report the winner")
    ap.add_argument("--probe-tries", type=int, default=0,
                    help="cap on probe attempts (0 = schedule-derived: "
                         "all attempts the BENCH_TPU_WAIT_S budget "
                         "allows, or 3 with --probe-timeout)")
    ap.add_argument("--probe-timeout", type=float, default=0.0,
                    help="per-attempt probe timeout; 0 = adaptive "
                         "schedule (one 120s attempt, then 75s polls "
                         "every ~90s across the BENCH_TPU_WAIT_S "
                         "budget, default 900s)")
    ap.add_argument("--cpu", action="store_true",
                    help="run on CPU without probing the TPU backend")
    ap.add_argument("--metrics-out", default="",
                    help="also append the headline result to this "
                         "metrics JSONL file through the obs sink "
                         "(schema: pipegcn_tpu/obs/schema.py; "
                         "summarize with python -m "
                         "pipegcn_tpu.cli.report)")
    ap.add_argument("--force-candidate", action="store_true",
                    help=argparse.SUPPRESS)  # CPU test hook for the
    # candidate-config pass (normally TPU-gated)
    ap.add_argument("--serve", action="store_true",
                    help="measure the online serving runtime instead of "
                         "training: open-loop load against the "
                         "compiled-once engine; headline metric is "
                         "sustained QPS with p50/p99 latency "
                         "(docs/SERVING.md)")
    ap.add_argument("--serve-secs", type=float, default=10.0,
                    help="seconds of open-loop serve load")
    ap.add_argument("--serve-qps", type=float, default=100.0,
                    help="target query arrival rate for --serve")
    ap.add_argument("--serve-max-batch", type=int, default=64,
                    help="top of the serve padded batch ladder")
    ap.add_argument("--serve-max-delay-ms", type=float, default=5.0,
                    help="max queueing delay before a partial serve "
                         "batch flushes")
    ap.add_argument("--serve-update-every", type=float, default=0.5,
                    help="seconds between synthetic feature-update "
                         "churn batches under --serve (0 disables)")
    ap.add_argument("--serve-refresh-every", type=float, default=0.5,
                    help="seconds between serve logits recomputes")
    ap.add_argument("--replicas", type=int, default=0,
                    help="with --serve: run an N-replica serving FLEET "
                         "(each replica its own process + mesh) behind "
                         "the failover router, with a mid-load "
                         "checkpoint hot-swap; headline metric is "
                         "aggregate QPS (near-linear in N). 0 = "
                         "single in-process engine")
    ap.add_argument("--serve-max-queue", type=int, default=0,
                    help="bound on queued query rows (overload sheds "
                         "tickets); 0 = unbounded")
    ap.add_argument("--traffic", type=str, default="",
                    help="with --serve: shaped arrival schedule "
                         "(constant | diurnal[:period[:floor]] | "
                         "flash-crowd[:mult[:t0[:t1]]] | trace:<path>); "
                         "empty = constant-rate Poisson")
    ap.add_argument("--autoscale", action="store_true",
                    help="with --serve: close the loop — run the fleet "
                         "under the scale policy (spawn/retire replicas "
                         "from window telemetry) with the graceful-"
                         "degradation admission ladder; headline shows "
                         "replica count tracking load (implies "
                         "--replicas 1 when unset)")
    ap.add_argument("--stream", action="store_true",
                    help="measure streaming-graph delta ingestion "
                         "instead of training throughput: per-delta "
                         "patch cost + forced-probe drift through the "
                         "live fit() loop, incremental-vs-full table "
                         "rebuild time, and the serving topology "
                         "refresh cost (docs/STREAMING.md)")
    ap.add_argument("--stream-deltas", type=int, default=6,
                    help="delta batches applied during the --stream "
                         "measurement")
    ap.add_argument("--stream-slack", type=float, default=0.10,
                    help="fractional padding headroom reserved for "
                         "in-place growth in the --stream build")
    ap.add_argument("--stream-journal-dir", type=str, default="",
                    help="persistent write-ahead delta journal for the "
                         "--stream measurement (stream/journal.py); "
                         "unset = ephemeral, non-resumable")
    ap.add_argument("--stream-resume", action="store_true",
                    help="resume a --stream measurement mid-schedule: "
                         "replay every journaled delta from "
                         "--stream-journal-dir against the rebuilt "
                         "nominal graph, then deliver only the "
                         "remaining scheduled deltas live")
    ap.add_argument(_STAGE_FLAG, type=int, default=0, dest="stage",
                    help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.stage >= 1:
        args.fused, args.blocks = 1, min(args.blocks, 3)
        args.no_compare, args.sweep_spmm = True, False
        # the most battle-tested kernel: a crash may have been a
        # kernel-specific issue rather than the tunnel
        args.spmm_impl = "bucket"
        # ...and the most battle-tested layout: the crash may have been
        # the reorder/slab path itself
        args.reorder, args.slab = "none", "off"
    if args.stage >= 2:
        args.small = True
        args.spmm_impl = "xla"
    if args.stage >= 3:
        args.cpu = True

    backend = init_backend(args.probe_tries, args.probe_timeout, args.cpu)

    global jax
    import jax

    if backend.startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")
    else:
        # the probe succeeded out-of-process, but the chip can still go
        # transiently UNAVAILABLE before the parent's own backend init —
        # guard the in-process init too, with the same CPU last resort
        try:
            jax.devices()
        except RuntimeError as exc:
            # a failed in-process init is cached for the process's life,
            # so there is no point retrying here — fall straight back
            print(f"# in-process backend init failed after a good probe: "
                  f"{exc}\n# falling back to CPU", file=sys.stderr)
            backend = "cpu-fallback"
            jax.config.update("jax_platforms", "cpu")

    from pipegcn_tpu.models import ModelConfig
    from pipegcn_tpu.parallel import Trainer, TrainConfig

    device_kind = jax.devices()[0].device_kind
    n_parts = args.parts or len(jax.devices())
    degraded = False
    if backend == "cpu-fallback" and not args.small:
        # A Reddit-scale CPU epoch is ~10 minutes — the artifact must
        # land in bounded time, so fall back to the small config with
        # minimal sampling. The JSON is clearly labeled
        # backend=cpu-fallback + degraded=true (a smoke-scale CPU
        # number proves the harness, not the perf).
        args.small = True
        args.fused, args.blocks, args.no_compare = 1, 3, True
        args.sweep_spmm = False
        degraded = True
        print("# cpu-fallback: degrading to the small config, 3 single-"
              "epoch blocks, no comparison run", file=sys.stderr)
    if args.small:
        hidden, n_layers = 64, 3
        spmm_chunk = None
    else:
        hidden, n_layers = 256, 4
        spmm_chunk = 2_097_152  # bound gathered messages to [2M, F]
        # ([2M, 602] f32 = 4.8 GB peak for the pp precompute gather)

    if getattr(args, "stream", False):
        # streaming needs the live host graph + parts the cached
        # artifact discards (the patcher mutates both in lockstep with
        # the device state), so it builds in memory and skips the
        # artifact path entirely. Crash-isolated like every scenario:
        # a worker death still gets the degraded re-exec ladder.
        try:
            result = _measure_stream(args, backend, device_kind,
                                     n_parts, degraded, hidden,
                                     n_layers)
        except Exception as exc:  # noqa: BLE001
            if args.stage >= 3 or backend.startswith("cpu"):
                raise
            _reexec_degraded(args.stage, repr(exc)[:300])
        return

    # Artifact naming/recipe live in partition.bench_artifact (shared
    # with the window-queue probe scripts); cluster granularity and
    # generator revision are part of the artifact identity (measured
    # sweep in docs/PERF_NOTES.md). load() sets cache_dir so derived
    # kernel tables cache under the artifact dir too.
    from pipegcn_tpu.partition.bench_artifact import (artifact_path,
                                                      ensure,
                                                      resolve_reorder)

    # anchored at the repo root like the probe scripts: bench invoked
    # from another CWD must reuse the same cached artifacts, not build
    # duplicates under ./partitions (ADVICE.md round 5)
    # --reorder auto resolves to a concrete layout first (reuse an
    # existing artifact, else the measured choose_reorder winner) —
    # the mode is artifact identity, so it must be pinned before ensure
    args.reorder_resolved = resolve_reorder(
        n_parts, args.cluster_size, args.small,
        os.path.join(REPO, "partitions"), args.reorder,
        log=lambda m: print(m, file=sys.stderr))
    part_path = artifact_path(n_parts, args.cluster_size,
                              small=args.small,
                              root=os.path.join(REPO, "partitions"),
                              reorder=args.reorder_resolved)
    t0 = time.perf_counter()
    sg = ensure(part_path, log=lambda m: print(m, file=sys.stderr))
    print(f"# partitions ready ({time.perf_counter()-t0:.1f}s)",
          file=sys.stderr)

    try:
        result = _measure(args, backend, device_kind, n_parts, degraded,
                          sg, hidden, n_layers, spmm_chunk)
    except NonFiniteLoss as exc:
        # divergence is a NUMERICS failure, not a worker crash — the
        # degraded re-exec ladder would just re-measure the same NaN
        # at lower quality. Loud fault record + red exit instead.
        print(f"# FATAL: {exc} — benchmark invalid; exiting 3",
              file=sys.stderr)
        if args.metrics_out:
            from pipegcn_tpu.obs import MetricsLogger

            try:
                with MetricsLogger(args.metrics_out) as ml:
                    ml.fault(kind="non-finite-loss", epoch=exc.epoch,
                             reason=str(exc), backend=backend)
            except OSError:
                pass
        sys.exit(3)
    except Exception as exc:  # noqa: BLE001 — worker crashes arrive as
        # JaxRuntimeError/RuntimeError/XlaRuntimeError; anything fatal
        # mid-measurement gets one shot at a degraded re-exec
        if args.stage >= 3 or backend.startswith("cpu"):
            raise
        _reexec_degraded(args.stage, repr(exc)[:300])
        return
    if result.get("loss") is None and not result.get("serve"):
        # the headline trained to a non-finite loss (the offshape-
        # products NaN class, VERDICT "Next round" item 1): the JSON
        # above is printed for diagnosis but the exit status must be
        # red — a benchmark of a diverged run is not a measurement
        print("# FINAL LOSS NON-FINITE — benchmark numbers are invalid; "
              "exiting 3", file=sys.stderr)
        sys.exit(3)


def _measure(args, backend, device_kind, n_parts, degraded, sg,
             hidden, n_layers, spmm_chunk):
    import jax

    from pipegcn_tpu.models import ModelConfig
    from pipegcn_tpu.parallel import Trainer, TrainConfig

    cfg = ModelConfig(
        layer_sizes=(sg.n_feat,) + (hidden,) * (n_layers - 1) + (sg.n_class,),
        use_pp=True, norm="layer", dropout=0.5,
        train_size=sg.n_train_global, spmm_chunk=spmm_chunk,
        dtype="float32" if args.f32 else "bfloat16",
        spmm_impl=args.spmm_impl,
        block_tile=args.block_tile,
        block_nnz=args.block_nnz or None,
        block_group=args.block_group,
        bucket_merge=args.bucket_merge,
        tune=args.tune,
        tuner_samples=args.tuner_samples,
        rem_dtype=args.rem_dtype,  # 'none' normalized by ModelConfig
        dropout_bits=args.dropout_bits,
        slab=args.slab,
        lane_pad=args.lane_pad,
    )
    if getattr(args, "serve", False):
        if getattr(args, "autoscale", False) \
                and getattr(args, "replicas", 0) == 0:
            args.replicas = 1  # autoscale needs the fleet path
        if getattr(args, "replicas", 0) > 0:
            return _measure_fleet(args, backend, device_kind, n_parts,
                                  degraded, sg, cfg)
        return _measure_serve(args, backend, device_kind, n_parts,
                              degraded, sg, cfg)

    blk = max(1, args.fused)

    def build_trainer(pipeline: bool) -> "Trainer":
        tcfg = TrainConfig(
            lr=0.01, n_epochs=args.blocks * blk,
            enable_pipeline=pipeline, seed=0, eval=False,
            fused_epochs=blk,
            rng_impl=args.rng_impl,
            # halo compression is pipelined-only (vanilla exchange is
            # differentiated and must stay exact)
            halo_dtype=args.halo_dtype if pipeline else "none",
            epoch_block=args.epoch_block,
            comm_prefetch=args.comm_prefetch,
        )
        return Trainer(sg, cfg, tcfg)

    def time_trainer(trainer, n_blocks: int, warmup_blocks: int = 1,
                     force_blk: int = 0):
        """Median per-epoch time over n_blocks dispatches of up-to-blk
        epochs; returns (median_epoch_s, last_loss, used_blk).

        Warmup always starts with single-epoch dispatches: the first
        compiles the step, and the next two measure a per-epoch time
        (min of the two, so one transient hiccup can't flip the
        decision) used to decide whether fused blocks would exceed
        MAX_DISPATCH_S per Execute (long dispatches have crashed the
        tunneled TPU worker); if they would, the timed blocks run
        unfused. `force_blk` skips the decision and reuses a prior
        run's block size so two runs being compared are methodologically
        identical. Warmup never lands in a timed sample."""
        e = 0

        def run_block(e0, k):
            if k == 1:
                loss = trainer.train_epoch(e0)
            else:
                loss = float(trainer.train_epochs(e0, k)[-1])
            jax.block_until_ready(trainer.state["params"])
            return loss

        t0 = time.perf_counter()
        _check_finite(run_block(e, 1), e)
        e += 1
        compile_s = time.perf_counter() - t0
        singles = []
        for _ in range(2 if blk > 1 and not force_blk else 1):
            t0 = time.perf_counter()
            _check_finite(run_block(e, 1), e)
            e += 1
            singles.append(time.perf_counter() - t0)
        single_s = min(singles)
        print(f"# warmup: compile+first {compile_s:.1f}s, "
              f"single epoch {single_s:.2f}s", file=sys.stderr)
        if force_blk:
            # reuse the caller's dispatch size, but never past the
            # dispatch cap: THIS trainer may be much slower than the one
            # force_blk was derived from (vanilla vs pipelined, sweep
            # impls), and a long Execute kills the tunneled worker
            my_blk = force_blk
            if my_blk > 1 and single_s * my_blk > MAX_DISPATCH_S:
                my_blk = max(1, int(MAX_DISPATCH_S // max(single_s, 1e-6)))
                print(f"# forced fused {force_blk} would make "
                      f"~{single_s * force_blk:.0f}s dispatches; clamping "
                      f"to {my_blk}", file=sys.stderr)
        else:
            my_blk = blk
            if my_blk > 1 and single_s * my_blk > MAX_DISPATCH_S:
                my_blk = max(1, int(MAX_DISPATCH_S // max(single_s, 1e-6)))
                print(f"# fused {blk} would make ~{single_s * blk:.0f}s "
                      f"dispatches; dropping to fused {my_blk}",
                      file=sys.stderr)
        if my_blk > 1:
            t0 = time.perf_counter()
            for _ in range(max(1, warmup_blocks)):
                _check_finite(run_block(e, my_blk), e + my_blk - 1)
                e += my_blk
            print(f"# fused-block warmup/compile "
                  f"({time.perf_counter()-t0:.1f}s)", file=sys.stderr)
        times = []
        loss = float("nan")
        for _ in range(n_blocks):
            t0 = time.perf_counter()
            loss = run_block(e, my_blk)
            e += my_blk
            times.append((time.perf_counter() - t0) / my_blk)
            # abort on the FIRST non-finite block, not after all of
            # them: a NaN run must stop burning TPU-window time
            _check_finite(loss, e - 1)
        return float(np.median(times)), loss, my_blk

    headline_pipeline = not args.no_pipeline
    t0 = time.perf_counter()
    trainer = build_trainer(headline_pipeline)
    print(f"# trainer setup ({time.perf_counter()-t0:.1f}s)", file=sys.stderr)

    epoch_s, loss, used_blk = time_trainer(trainer, args.blocks)
    print(f"# median epoch {epoch_s:.4f}s over {args.blocks} blocks of "
          f"{used_blk}, final loss {loss:.4f}", file=sys.stderr)

    # ---- derived metrics: MFU + bytes (from XLA's own cost model) -----
    extras = {
        "backend": backend,
        "device": device_kind,
        "n_parts": n_parts,
        "dtype": cfg.dtype,
        "spmm_impl": args.spmm_impl,
        "pipeline": headline_pipeline,
        "loss": round(loss, 4) if np.isfinite(loss) else None,
        "rng_impl": args.rng_impl,
        "halo_dtype": args.halo_dtype if headline_pipeline else "none",
        "epoch_block": args.epoch_block,
        "reorder": getattr(args, "reorder_resolved", args.reorder),
        "slab": args.slab,
    }
    if args.lane_pad:
        extras["lane_pad"] = True
    try:
        # how contiguous the resolved layout's gather streams actually
        # are — the number the reorder lever is supposed to move,
        # reported next to the anatomy's non-SpMM share
        tabs = trainer._bucket_tables or trainer._block_tables
        if tabs:
            from pipegcn_tpu.ops.bucket_spmm import gather_contiguity

            extras["gather_contiguity"] = gather_contiguity(
                tabs, sg.n_max + sg.halo_size)
    except Exception as exc:  # stats are best-effort diagnostics
        print(f"# gather_contiguity unavailable: {exc!r}",
              file=sys.stderr)
    if trainer.fallbacks:
        # the kernel fallback ladder fired mid-measurement: the number
        # was produced by the DOWNGRADED kernel, and the JSON must say so
        extras["kernel_fallbacks"] = [
            f"{f['from_impl']}->{f['to_impl']}" for f in trainer.fallbacks]
        extras["spmm_impl"] = trainer._current_impl()
    if degraded:
        extras["degraded"] = True
    if args.stage > 0:
        # this run is a crash-recovery re-exec with reduced sampling (and
        # at stage >= 2, reduced scale) — not comparable to a full run
        extras["degraded"] = True
        extras["stage"] = args.stage
    try:
        ca = trainer.step_cost_analysis()
        if ca:
            # cost_analysis describes the per-device SPMD module; scale
            # to whole-job totals so the labels mean what they say
            flops_epoch = ca.get("flops", 0.0) * n_parts
            hbm_bytes = ca.get("bytes accessed", 0.0) * n_parts
            extras["flops_per_epoch"] = round(flops_epoch)
            extras["est_hbm_bytes_per_epoch"] = round(hbm_bytes)
            peak = peak_flops_for(device_kind)
            if peak and flops_epoch:
                extras["mfu_pct"] = round(
                    100.0 * flops_epoch / (epoch_s * peak * n_parts), 2
                )
    except Exception as exc:  # cost analysis is best-effort diagnostics
        print(f"# cost analysis unavailable: {exc}", file=sys.stderr)
    extras["est_ici_bytes_per_epoch"] = trainer.est_ici_bytes_per_epoch()
    if getattr(trainer, "tuning", None):
        # the auto-tuner's decision + the full measured per-candidate
        # micro-bench table: WHY this kernel produced the number
        tu = trainer.tuning
        extras["tuning"] = {
            "winner": dict(tu["winner"]),
            "source": tu["source"],
            "stale_reason": tu.get("stale_reason"),
            "costs": list(tu.get("costs", [])),
        }

    # The headline number is in hand from here on: the optional extras
    # below must never discard it, so a crash there falls through to the
    # JSON print instead of the stage-degrading re-exec.
    try:
        if trainer._block_tables is not None:
            from pipegcn_tpu.ops.block_spmm import estimate_block_coverage

            w_hint = max(cfg.layer_sizes[:cfg.n_graph_layers])
            # CLI convention: 0 means "use the break-even default"
            extras["dense_coverage"] = round(estimate_block_coverage(
                sg, args.block_tile, w_hint,
                nnz_threshold=args.block_nnz or None
            ), 3)
            extras["dense_blocks"] = int(
                next(v for k, v in trainer._block_tables.items()
                     if k in ("blk_a", "blk_a_bits")).shape[1])

        # ---- overlap evidence: pipelined vs vanilla -------------------
        if not args.no_compare:
            del trainer  # free HBM before compiling the second program
            other = build_trainer(not headline_pipeline)
            # reuse the headline's dispatch size: comparing runs with
            # different fused-block amortization would contaminate the
            # speedup with per-dispatch overhead differences
            other_s, _, _ = time_trainer(other, max(3, args.blocks // 2),
                                         force_blk=used_blk)
            key = "vanilla_epoch_s" if headline_pipeline \
                else "pipelined_epoch_s"
            extras[key] = round(other_s, 4)
            pipe_s = epoch_s if headline_pipeline else other_s
            van_s = other_s if headline_pipeline else epoch_s
            extras["pipeline_speedup"] = round(van_s / pipe_s, 3)
            print(f"# pipelined {pipe_s:.4f}s vs vanilla {van_s:.4f}s "
                  f"(speedup {van_s / pipe_s:.3f}x)", file=sys.stderr)
            del other

        # ---- candidate-config pass ------------------------------------
        # The union-gather + fp8 stack (--block-group 4 --rem-dtype
        # float8) is parity/accuracy-validated but may not yet have a
        # chip measurement; when the headline ran at defaults on the
        # real chip, measure it too (one extra trainer build — the
        # kernel tables are disk-cached) and report the better of the
        # two as the headline, with BOTH measurements recorded.
        # Crash-isolated by the enclosing try: a failure here must
        # never cost the in-hand default number.
        if (((backend == "tpu" and not args.small)
             or args.force_candidate)
                and not extras.get("degraded")
                and args.spmm_impl in ("auto", "block")
                and args.block_group == 1 and args.rem_dtype == "none"):
            try:
                # free the headline trainer's HBM before compiling a
                # second full-scale program (the compare path already
                # deleted it; with --no-compare it is still resident
                # and two programs can OOM the chip)
                del trainer
            except UnboundLocalError:
                pass
            cand_cfg = dataclasses.replace(
                cfg, spmm_impl="block", block_group=4,
                rem_dtype="float8")
            t0 = time.perf_counter()
            tr_c = Trainer(sg, cand_cfg, TrainConfig(
                lr=0.01, n_epochs=args.blocks * blk,
                enable_pipeline=headline_pipeline, seed=0, eval=False,
                fused_epochs=blk))
            def adopt_candidate(name, tr_win, cand_s, cand_loss):
                nonlocal epoch_s
                epoch_s = cand_s
                extras["headline_config"] = name
                extras["spmm_impl"] = "block"
                # loss and ICI bytes described the default run too —
                # keep every published field's provenance the winner's.
                # The candidate trains fewer blocks than the default, so
                # record the basis alongside the loss.
                extras["loss"] = (round(cand_loss, 4)
                                  if np.isfinite(cand_loss) else None)
                extras["loss_blocks"] = max(3, args.blocks // 2)
                extras["est_ici_bytes_per_epoch"] = (
                    tr_win.est_ici_bytes_per_epoch())
                # coverage depends only on (sg, tile, threshold) — if
                # the default headline already published it, the value
                # is identical; only fill the gap when the default ran
                # a non-block kernel
                if (tr_win._block_tables is not None
                        and "dense_coverage" not in extras):
                    from pipegcn_tpu.ops.block_spmm import (
                        estimate_block_coverage)
                    w_hint = max(cfg.layer_sizes[:cfg.n_graph_layers])
                    extras["dense_coverage"] = round(
                        estimate_block_coverage(
                            sg, args.block_tile, w_hint,
                            nnz_threshold=args.block_nnz or None), 3)
                    extras["dense_blocks"] = int(
                        next(v for k, v in tr_win._block_tables.items()
                             if k in ("blk_a", "blk_a_bits")).shape[1])
                # the vanilla-vs-pipelined comparison (if it ran) was
                # measured on the DEFAULT config — relabel so no one
                # divides default vanilla time by the candidate headline
                for k in ("vanilla_epoch_s", "pipelined_epoch_s",
                          "pipeline_speedup"):
                    if k in extras:
                        extras[f"default_{k}"] = extras.pop(k)
                # the flops/bytes/mfu extras described the DEFAULT
                # program; recompute them from the winning one (fp8
                # transport exists precisely to change bytes moved)
                try:
                    ca = tr_win.step_cost_analysis()
                    if ca:
                        fl = ca.get("flops", 0.0) * n_parts
                        extras["flops_per_epoch"] = round(fl)
                        extras["est_hbm_bytes_per_epoch"] = round(
                            ca.get("bytes accessed", 0.0) * n_parts)
                        peak = peak_flops_for(device_kind)
                        if peak and fl:
                            extras["mfu_pct"] = round(
                                100.0 * fl / (cand_s * peak * n_parts),
                                2)
                except Exception as exc:
                    print(f"# candidate cost analysis unavailable: "
                          f"{exc}", file=sys.stderr)

            cand_s, cand_loss, _ = time_trainer(
                tr_c, max(3, args.blocks // 2), force_blk=used_blk)
            print(f"# candidate block-u4-float8: {cand_s:.4f}s/epoch "
                  f"(total {time.perf_counter()-t0:.0f}s)",
                  file=sys.stderr)
            extras["default_epoch_s"] = round(epoch_s, 4)
            extras["candidate_epoch_s"] = round(cand_s, 4)
            if cand_s < epoch_s:
                adopt_candidate("block-u4-float8", tr_c, cand_s,
                                cand_loss)
            del tr_c

            if backend == "tpu" and not args.small:
                # persist the best-so-far number before any further
                # risky compiles: a worker death must not lose an
                # in-hand measurement (same gates as the final persist)
                persist_last_tpu(
                    round(epoch_s, 4),
                    round(BASELINE_EPOCH_S / epoch_s, 3),
                    extras, backend, device_kind)

        # ---- non-SpMM-floor lever: bucket-width merging ---------------
        # The bucket kernel's fixed per-epoch floor scales with the
        # number of bucket segments it dispatches (one padded
        # gather+reduce per width rung); --bucket-merge k truncates the
        # width ladder below 2^k, trading padding FLOPs for fewer
        # fixed overheads. Measure the SAME bucket program with and
        # without merging and publish the delta — the floor attack's
        # before/after evidence. Crash-isolated like the candidate
        # pass: a failure here never costs the in-hand headline.
        if (((backend == "tpu" and not args.small)
             or args.force_candidate)
                and not extras.get("degraded")
                and args.bucket_merge == 0):
            lever = {}
            for name, merge in (("bucket", 0), ("bucket-m8", 8)):
                try:
                    t0 = time.perf_counter()
                    tr_m = Trainer(sg, dataclasses.replace(
                        cfg, spmm_impl="bucket", bucket_merge=merge,
                        block_group=1, rem_dtype=None), TrainConfig(
                            lr=0.01, n_epochs=args.blocks * blk,
                            enable_pipeline=headline_pipeline, seed=0,
                            eval=False, fused_epochs=blk))
                    m_s, _, _ = time_trainer(
                        tr_m, max(3, args.blocks // 2),
                        force_blk=used_blk)
                    lever[name] = round(m_s, 4)
                    print(f"# floor lever {name}: {m_s:.4f}s/epoch "
                          f"(total {time.perf_counter()-t0:.0f}s)",
                          file=sys.stderr)
                    del tr_m
                except Exception as exc:  # noqa: BLE001
                    lever[name] = None
                    print(f"# floor lever {name} failed: {exc!r}",
                          file=sys.stderr)
            extras["bucket_merge_lever"] = lever
            if lever.get("bucket") and lever.get("bucket-m8"):
                extras["bucket_merge_delta_s"] = round(
                    lever["bucket"] - lever["bucket-m8"], 4)

        # ---- non-SpMM floor levers: before/after per lever ------------
        # Each lever is measured against the headline config with exactly
        # one knob flipped, crash-isolated so one broken variant never
        # costs the others or the in-hand headline:
        #   rng-rbg       dropout PRNG threefry -> rbg
        #   dropout-bits8 8-bit mask draws instead of 32-bit
        #   halo-float8   fp8+amax halo wire (pipelined headline only)
        #   unfused       force_blk=1: the megastep win read backwards
        #                 (base IS the fused dispatch, so the delta is
        #                 unfused - base)
        #   prefetch-*    paired use_pp=False runs, since the layer-0
        #                 exchange the prefetch hoists does not exist
        #                 under the headline's use_pp=True config
        if (((backend == "tpu" and not args.small)
             or args.force_candidate)
                and not extras.get("degraded")
                and args.rng_impl == "threefry"
                and args.dropout_bits == 32
                and args.halo_dtype == "none"
                and args.epoch_block == 0
                and not args.comm_prefetch):
            floor = {"base": round(epoch_s, 4)}

            def _floor_lever(name, mkw=None, tkw=None, f_blk=0):
                try:
                    t0 = time.perf_counter()
                    c = dataclasses.replace(cfg, **mkw) if mkw else cfg
                    tr_l = Trainer(sg, c, TrainConfig(
                        lr=0.01, n_epochs=args.blocks * blk,
                        enable_pipeline=headline_pipeline, seed=0,
                        eval=False, fused_epochs=blk, **(tkw or {})))
                    s, _, _ = time_trainer(
                        tr_l, max(3, args.blocks // 2),
                        force_blk=f_blk or used_blk)
                    floor[name] = round(s, 4)
                    print(f"# floor lever {name}: {s:.4f}s/epoch "
                          f"(total {time.perf_counter()-t0:.0f}s)",
                          file=sys.stderr)
                    del tr_l
                except Exception as exc:  # noqa: BLE001
                    floor[name] = None
                    print(f"# floor lever {name} failed: {exc!r}",
                          file=sys.stderr)

            _floor_lever("rng-rbg", tkw=dict(rng_impl="rbg"))
            _floor_lever("dropout-bits8", mkw=dict(dropout_bits=8))
            # integrity plane at its worst-case cadence (a check every
            # boundary): digest capture/verify + static scrub +
            # Freivalds + the wire-checksum lane, all in ONE compile —
            # the guard is a trace-time choice, so the delta is pure
            # check cost, never recompile cost. Expect a NEGATIVE
            # delta (the lever spends time buying detection).
            _floor_lever("integrity-c1",
                         tkw=dict(integrity_check_every=1))
            if headline_pipeline:
                _floor_lever("halo-float8",
                             tkw=dict(halo_dtype="float8"))
            if used_blk > 1:
                _floor_lever("unfused", f_blk=1)
            if headline_pipeline:
                _floor_lever("prefetch-off", mkw=dict(use_pp=False))
                _floor_lever("prefetch-on", mkw=dict(use_pp=False),
                             tkw=dict(comm_prefetch=True))
            extras["floor_levers"] = floor
            # positive delta == the lever saves time vs its reference
            for dkey, ref, var in (
                    ("rng_impl_delta_s", "base", "rng-rbg"),
                    ("dropout_bits_delta_s", "base", "dropout-bits8"),
                    ("integrity_check_delta_s", "base",
                     "integrity-c1"),
                    ("halo_dtype_delta_s", "base", "halo-float8"),
                    ("epoch_block_delta_s", "unfused", "base"),
                    ("comm_prefetch_delta_s", "prefetch-off",
                     "prefetch-on")):
                if floor.get(ref) and floor.get(var):
                    extras[dkey] = round(floor[ref] - floor[var], 4)

        # ---- training-span pass (obs/trainspan.py) --------------------
        # Two questions, one crash-isolated block. (1) What do the
        # always-on spans SAY about this config: measured overlap
        # (overlap_spans), mean comm-wait share, per-rank straggler
        # gaps (bench is usually single-controller, so the straggler
        # map is often empty). (2) What do they COST: spans-on vs
        # spans-off epoch time published as train_traces_delta_s
        # (positive = tracing off is faster; expect ~0, the plane is
        # host-side bookkeeping). fit() drives both runs because the
        # span plane lives there — eval off, temp metrics sink, and
        # measure_comm_cost so the comm tail arms.
        if (((backend == "tpu" and not args.small)
             or args.force_candidate)
                and not extras.get("degraded")):
            import tempfile

            from pipegcn_tpu.obs import MetricsLogger
            from pipegcn_tpu.obs.metrics import read_metrics
            from pipegcn_tpu.obs.trainspan import fold_spans

            tspan_t = {}

            def _span_fit(name, traces):
                try:
                    t0 = time.perf_counter()
                    tr_s = Trainer(sg, cfg, TrainConfig(
                        lr=0.01, n_epochs=args.blocks * blk,
                        enable_pipeline=headline_pipeline, seed=0,
                        eval=False, fused_epochs=blk,
                        train_traces=traces))
                    path = os.path.join(
                        tempfile.mkdtemp(prefix="bench-tspan-"),
                        f"{name}.jsonl")
                    with MetricsLogger(path) as ml:
                        r = tr_s.fit(metrics=ml,
                                     log_fn=lambda *_a, **_k: None,
                                     measure_comm_cost=True)
                    tspan_t[name] = (round(r["epoch_time"], 4)
                                     if r.get("epoch_time") else None)
                    print(f"# train-span pass {name}: "
                          f"{tspan_t[name]}s/epoch "
                          f"(total {time.perf_counter()-t0:.0f}s)",
                          file=sys.stderr)
                    del tr_s
                    return path
                except Exception as exc:  # noqa: BLE001
                    tspan_t[name] = None
                    print(f"# train-span pass {name} failed: {exc!r}",
                          file=sys.stderr)
                    return None

            on_path = _span_fit("spans-on", True)
            if on_path:
                try:
                    fold = fold_spans(read_metrics(on_path))
                    if fold.get("overlap_spans") is not None:
                        extras["overlap_spans"] = round(
                            fold["overlap_spans"], 4)
                    shares = fold.get("comm_wait_share_by_rank") or {}
                    if shares:
                        extras["comm_wait_share"] = round(
                            sum(shares.values()) / len(shares), 4)
                    gaps = fold.get("straggler_gap_s_by_rank") or {}
                    if gaps:
                        extras["straggler_gap_s"] = {
                            f"r{r}": v for r, v in gaps.items()}
                except Exception as exc:  # noqa: BLE001
                    print(f"# train-span fold failed: {exc!r}",
                          file=sys.stderr)
            _span_fit("spans-off", False)
            if tspan_t.get("spans-on") and tspan_t.get("spans-off"):
                extras["train_traces_delta_s"] = round(
                    tspan_t["spans-on"] - tspan_t["spans-off"], 4)

        # ---- reorder x slab before/after pass -------------------------
        # The locality lever's evidence: the SAME bucket program timed
        # on (1) the unreordered artifact, (2) the reordered one, and
        # (3) the reordered one with slab-gather streaming plans.
        # reorder_delta_s / slab_delta_s isolate each lever's
        # contribution (positive = the lever saves time). Crash-isolated
        # per variant like the floor levers: one broken layout never
        # costs the others or the in-hand headline.
        if (((backend == "tpu" and not args.small)
             or args.force_candidate)
                and not extras.get("degraded")
                and args.slab == "auto" and not args.lane_pad):
            if backend == "tpu" and not args.small:
                # persist the in-hand number before risky compiles on
                # fresh table layouts
                persist_last_tpu(
                    round(epoch_s, 4),
                    round(BASELINE_EPOCH_S / epoch_s, 3),
                    extras, backend, device_kind)
            from pipegcn_tpu.partition.bench_artifact import (
                artifact_path as _apath, ensure as _ensure)

            rmode = getattr(args, "reorder_resolved", "none")
            if rmode == "none":
                rmode = "degree-bfs"
            rs = {}
            for name, mode, slab in (("none", "none", "off"),
                                     ("reorder", rmode, "off"),
                                     ("reorder-slab", rmode, "on")):
                try:
                    t0 = time.perf_counter()
                    sg_v = _ensure(
                        _apath(n_parts, args.cluster_size,
                               small=args.small,
                               root=os.path.join(REPO, "partitions"),
                               reorder=mode),
                        log=lambda m: print(m, file=sys.stderr))
                    tr_v = Trainer(sg_v, dataclasses.replace(
                        cfg, spmm_impl="bucket", slab=slab,
                        block_group=1, rem_dtype=None), TrainConfig(
                            lr=0.01, n_epochs=args.blocks * blk,
                            enable_pipeline=headline_pipeline, seed=0,
                            eval=False, fused_epochs=blk))
                    s, _, _ = time_trainer(
                        tr_v, max(3, args.blocks // 2),
                        force_blk=used_blk)
                    rs[name] = round(s, 4)
                    print(f"# reorder_slab {name}: {s:.4f}s/epoch "
                          f"(total {time.perf_counter()-t0:.0f}s)",
                          file=sys.stderr)
                    del tr_v, sg_v
                except Exception as exc:  # noqa: BLE001
                    rs[name] = None
                    print(f"# reorder_slab {name} failed: {exc!r}",
                          file=sys.stderr)
            extras["reorder_slab"] = rs
            if rs.get("none") and rs.get("reorder"):
                extras["reorder_delta_s"] = round(
                    rs["none"] - rs["reorder"], 4)
            if rs.get("reorder") and rs.get("reorder-slab"):
                extras["slab_delta_s"] = round(
                    rs["reorder"] - rs["reorder-slab"], 4)

        # ---- optional SpMM implementation sweep -----------------------
        if args.sweep_spmm:
            sweep = {}
            # (label, config overrides): the block kernel sweeps its
            # dense layouts and the fp8 remainder transport too —
            # sharing one artifact + warmed table caches, so each extra
            # entry costs one trainer build, not a rebuild of the world
            entries = [  # every knob EXPLICIT: entries must not
                # inherit the headline's --block-group/--rem-dtype
                ("xla", dict(spmm_impl="xla", block_group=1,
                             rem_dtype=None)),
                ("bucket", dict(spmm_impl="bucket", block_group=1,
                                rem_dtype=None)),
                ("block", dict(spmm_impl="block", block_group=1,
                               rem_dtype=None)),
                ("block-u4", dict(spmm_impl="block", block_group=4,
                                  rem_dtype=None)),
                ("block-u4-f8", dict(spmm_impl="block", block_group=4,
                                     rem_dtype="float8")),
                ("bucket-m8", dict(spmm_impl="bucket", block_group=1,
                                   bucket_merge=8, rem_dtype=None)),
            ]
            for impl, overrides in entries:
                try:
                    t0 = time.perf_counter()
                    tr = Trainer(sg,
                        dataclasses.replace(cfg, **overrides),
                        TrainConfig(lr=0.01, n_epochs=blk * 4,
                                    enable_pipeline=headline_pipeline,
                                    seed=0, eval=False, fused_epochs=blk))
                    s, _, _ = time_trainer(tr, 3, force_blk=used_blk)
                    sweep[impl] = round(s, 4)
                    print(f"# spmm sweep: {impl} {s:.4f}s/epoch "
                          f"(total {time.perf_counter()-t0:.0f}s)",
                          file=sys.stderr)
                    del tr
                except Exception as exc:
                    sweep[impl] = None
                    print(f"# spmm sweep: {impl} failed: {exc}",
                          file=sys.stderr)
            extras["spmm_sweep"] = sweep
            valid = {k: v for k, v in sweep.items() if v}
            if valid:
                extras["spmm_best"] = min(valid, key=valid.get)
    except Exception as exc:  # noqa: BLE001 — keep the headline number
        extras["extras_error"] = repr(exc)[:200]
        print(f"# optional comparison/sweep crashed ({exc!r}); "
              f"reporting the headline measurement alone", file=sys.stderr)

    metric = "reddit_scale_epoch_time" if not args.small else \
        "small_epoch_time"
    result = {
        "metric": metric,
        "value": round(epoch_s, 4),
        "unit": "s/epoch",
        "vs_baseline": round(BASELINE_EPOCH_S / epoch_s, 3),
        **extras,
    }
    # anchored at the repo root (bench may be invoked from any CWD)
    last_path = os.path.join(REPO, "results", "last_tpu_bench.json")
    if backend == "tpu" and metric == "reddit_scale_epoch_time" \
            and not extras.get("degraded"):
        # record the full-quality headline so a later degraded/CPU run
        # can still surface the most recent real-TPU measurement
        # (degraded re-exec stages are excluded: their reduced sampling
        # is not comparable to a full run)
        persist_last_tpu(result["value"], result["vs_baseline"], extras,
                         backend, device_kind)
    elif backend != "tpu":
        # a CPU-labeled number proves the harness, not the perf; attach
        # the last real-TPU headline (clearly labeled) for context
        try:
            with open(last_path) as f:
                result["last_tpu_measurement"] = json.load(f)
        except (OSError, ValueError):
            pass
    if args.metrics_out:
        # the same sink the trainer logs through: a run header (what
        # produced the number) + one "bench" event with the headline
        from pipegcn_tpu.obs import MetricsLogger, device_info

        try:
            with MetricsLogger(args.metrics_out) as ml:
                ml.run_header(config=vars(args), device=device_info(),
                              mesh={"n_parts": n_parts})
                ml.event("bench", **result)
        except OSError as exc:
            print(f"# metrics sink unavailable: {exc}", file=sys.stderr)
    print(json.dumps(result))
    return result


def _measure_stream(args, backend, device_kind, n_parts, degraded,
                    hidden, n_layers):
    """bench.py --stream: streaming-graph delta ingestion cost. Runs
    the PRODUCTION path — deltas scheduled through the live fit() loop
    (forced staleness probe per delta measures the drift each topology
    change induces), then times one incremental apply against a
    from-scratch build+table rebuild, and the serving-side topology
    refresh. The result carries `stream: true` so main() knows there is
    no headline training loss to gate on."""
    import tempfile

    import jax

    from pipegcn_tpu.graph.synthetic import (synthetic_delta_schedule,
                                             synthetic_graph)
    from pipegcn_tpu.models import ModelConfig
    from pipegcn_tpu.obs.metrics import MetricsLogger, read_metrics
    from pipegcn_tpu.ops.bucket_spmm import build_sharded_bucket_tables
    from pipegcn_tpu.parallel import Trainer, TrainConfig
    from pipegcn_tpu.partition.halo import ShardedGraph
    from pipegcn_tpu.partition.partitioner import partition_graph
    from pipegcn_tpu.serve import ServingEngine
    from pipegcn_tpu.stream import GraphPatcher, StreamPlan, save_deltas

    t0 = time.perf_counter()
    if args.small:
        g = synthetic_graph(num_nodes=10_000, avg_degree=12, n_feat=64,
                            n_class=16, seed=0)
    else:
        # Reddit shape statistics, same as the training headline
        g = synthetic_graph(num_nodes=232_965, avg_degree=492,
                            n_feat=602, n_class=41, seed=0)
    parts = partition_graph(g, n_parts)
    sg = ShardedGraph.build(g, parts, n_parts=n_parts,
                            slack=args.stream_slack)
    print(f"# stream: graph + sharded build "
          f"({time.perf_counter()-t0:.1f}s, slack "
          f"{args.stream_slack:.0%})", file=sys.stderr)

    # bucket is the kernel with the dirty-shard incremental table
    # rebuild — the code path this scenario exists to measure
    impl = "bucket" if args.spmm_impl == "auto" else args.spmm_impl
    cfg = ModelConfig(
        layer_sizes=(sg.n_feat,) + (hidden,) * (n_layers - 1)
        + (sg.n_class,),
        use_pp=False, norm="layer", dropout=0.0,
        train_size=sg.n_train_global,
        dtype="float32" if args.f32 else "bfloat16",
        spmm_impl=impl, tune=False,
    )
    n_warm = 3
    n_deltas = max(1, args.stream_deltas)
    tcfg = TrainConfig(lr=0.01, n_epochs=n_warm + n_deltas,
                       enable_pipeline=True, seed=0, eval=False,
                       fused_epochs=1, log_every=10_000)
    trainer = Trainer(sg, cfg, tcfg)
    patcher = GraphPatcher(g, sg, parts, slack=args.stream_slack)
    trainer.enable_stream(patcher)

    # delta sizing: ~0.05% of the edge set per batch (>= 8 edges), so
    # the patch cost is measured against realistic drip-feed churn
    epb = max(8, g.num_edges // 2000)
    batches = synthetic_delta_schedule(
        g, n_batches=n_deltas + 2, edges_per_batch=epb,
        dels_per_batch=max(4, epb // 2),
        nodes_per_batch=max(1, g.num_nodes // 10_000), seed=0)
    # optional durability: a persistent WAL journal makes the
    # measurement resumable mid-schedule — a killed run's applied
    # deltas replay from the journal, the remainder deliver live
    journal = None
    replay_stats = None
    if args.stream_journal_dir:
        from pipegcn_tpu.stream import DeltaJournal, replay_for_resume

        journal = DeltaJournal(args.stream_journal_dir)
    with tempfile.TemporaryDirectory(prefix="bench-stream-") as td:
        dpath = os.path.join(td, "deltas.jsonl")
        save_deltas(dpath, batches[:n_deltas])
        plan = StreamPlan.parse(f"{dpath}@{n_warm}:1")
        if journal is not None and args.stream_resume:
            wm = journal.last_seq()
            replay_stats = replay_for_resume(
                journal, wm, trainer.apply_graph_deltas, plan=plan)
            plan.skip_journaled(wm)
            print(f"# stream: resumed mid-schedule — replayed "
                  f"{replay_stats['replayed']} journaled delta(s) "
                  f"(+{replay_stats['rederived']} re-derived), "
                  f"{plan.remaining()} still scheduled",
                  file=sys.stderr)
        mpath = os.path.join(td, "metrics.jsonl")
        t0 = time.perf_counter()
        with MetricsLogger(mpath) as ml:
            trainer.fit(None, metrics=ml, stream_plan=plan,
                        journal=journal,
                        log_fn=lambda m: print(f"# {m}",
                                               file=sys.stderr))
        fit_s = time.perf_counter() - t0
        stream_recs = [r for r in read_metrics(mpath)
                       if r.get("event") == "stream"]
    print(f"# stream: fit with {len(stream_recs)} deltas "
          f"({fit_s:.1f}s)", file=sys.stderr)

    # one more delta, wall-clock timed end to end: host patch + dirty
    # table rebuild + device upload + carry flush
    t0 = time.perf_counter()
    rep = trainer.apply_graph_deltas(batches[n_deltas])
    jax.block_until_ready(trainer.data)
    inc_apply_ms = (time.perf_counter() - t0) * 1e3

    # the number incremental patching competes against: a from-scratch
    # ShardedGraph.build + full kernel-table rebuild of the SAME
    # post-delta graph. Host-side only — a real full rebuild would ALSO
    # pay a full device re-upload and (shapes changing) a recompile, so
    # this comparison is conservative in the incremental path's favor
    # at scale and can even flip at smoke scale, where the incremental
    # number's device upload dominates.
    t0 = time.perf_counter()
    sg_full = ShardedGraph.build(
        patcher.g, patcher.parts, n_parts=n_parts,
        min_n_max=sg.n_max, min_b_max=sg.b_max, min_e_max=sg.e_max)
    if impl == "bucket":
        build_sharded_bucket_tables(sg_full)
    full_rebuild_ms = (time.perf_counter() - t0) * 1e3
    del sg_full
    print(f"# stream: incremental apply {inc_apply_ms:.1f}ms vs full "
          f"host rebuild {full_rebuild_ms:.1f}ms", file=sys.stderr)

    # serving-side topology refresh: patched send-lists drive layer-0
    # cache invalidation + incremental halo re-exchange, no retracing
    engine = ServingEngine.for_trainer(trainer)
    warm_s = engine.warmup()
    rep2 = trainer.apply_graph_deltas(batches[n_deltas + 1])
    t0 = time.perf_counter()
    touched = engine.apply_graph_deltas(rep2)
    topo_apply_ms = (time.perf_counter() - t0) * 1e3
    t0 = time.perf_counter()
    refreshed = engine.refresh_boundary()
    jax.block_until_ready(engine._halo0)
    refresh_ms = (time.perf_counter() - t0) * 1e3
    print(f"# stream: serve topo apply {topo_apply_ms:.1f}ms "
          f"({touched} slots), boundary refresh {refresh_ms:.1f}ms "
          f"({refreshed} rows)", file=sys.stderr)

    patch_ms = [r["patch_ms"] for r in stream_recs]
    drifts = [r["drift"] for r in stream_recs
              if r.get("drift") is not None]
    rnd = lambda v, k=3: None if v is None else round(v, k)  # noqa: E731
    result = {
        "metric": "stream_patch_ms",
        "value": round(float(np.median(patch_ms)), 3) if patch_ms
        else None,
        "unit": "ms/delta",
        "stream": True,
        "backend": backend,
        "device": device_kind,
        "n_parts": n_parts,
        "dtype": cfg.dtype,
        "spmm_impl": impl,
        "slack": args.stream_slack,
        "n_deltas": len(stream_recs),
        "edges_per_delta": epb,
        "patch_ms_per_delta": [rnd(v) for v in patch_ms],
        "drift_per_delta": [rnd(v, 5) for v in drifts],
        "drift_max": rnd(max(drifts), 5) if drifts else None,
        "tables_rebuilt_per_delta": [r["tables_rebuilt"]
                                     for r in stream_recs],
        "repadded_count": sum(bool(r["repadded"])
                              for r in stream_recs),
        "slack_remaining": rep2.slack_remaining,
        # incremental = host patch + dirty tables + device upload +
        # carry flush; full = host build + tables ONLY (no re-upload,
        # no recompile) — conservative toward the full path
        "incremental_apply_ms": rnd(inc_apply_ms),
        "full_host_rebuild_ms": rnd(full_rebuild_ms),
        "full_vs_incremental": rnd(full_rebuild_ms / inc_apply_ms)
        if inc_apply_ms > 0 else None,
        "serve_topo_apply_ms": rnd(topo_apply_ms),
        "serve_refresh_ms": rnd(refresh_ms),
        "serve_touched_slots": touched,
        "serve_warmup_s": round(warm_s, 2),
        "topo_generation": engine.topo_generation,
        "trainer_topo_generation": int(getattr(trainer,
                                               "topo_generation", 0)),
        "journal_replayed": (replay_stats["replayed"]
                             + replay_stats["rederived"]
                             if replay_stats else 0),
        "journal_last_seq": (journal.last_seq()
                             if journal is not None else -1),
    }
    if degraded:
        result["degraded"] = True
    if args.stage > 0:
        result["degraded"] = True
        result["stage"] = args.stage
    if args.metrics_out:
        from pipegcn_tpu.obs import MetricsLogger as _ML, device_info

        try:
            with _ML(args.metrics_out) as ml:
                ml.run_header(config=vars(args), device=device_info(),
                              mesh={"n_parts": n_parts})
                ml.event("bench", **result)
        except OSError as exc:
            print(f"# metrics sink unavailable: {exc}", file=sys.stderr)
    print(json.dumps(result))
    return result


def _measure_serve(args, backend, device_kind, n_parts, degraded, sg,
                   cfg):
    """bench.py --serve: sustained QPS + latency of the online serving
    runtime under the open-loop load generator. The result carries
    `serve: true` so main() knows there is no training loss to gate on."""
    from pipegcn_tpu.parallel import Trainer, TrainConfig
    from pipegcn_tpu.serve import ServingEngine, run_serving_loop

    # serving measures the halo0-cache inference path with live feature
    # churn: use_pp folds raw features trainer-side (and disables
    # updates), so the serve leg runs without it; dropout is inert at
    # inference either way
    scfg = dataclasses.replace(cfg, use_pp=False, dropout=0.0)
    t0 = time.perf_counter()
    trainer = Trainer(sg, scfg, TrainConfig(
        lr=0.01, n_epochs=0, enable_pipeline=False, seed=0, eval=False))
    engine = ServingEngine.for_trainer(
        trainer, max_batch=args.serve_max_batch)
    warm_s = engine.warmup()
    print(f"# serve setup {time.perf_counter()-t0:.1f}s "
          f"(engine warm in {warm_s:.1f}s, ladder {engine.ladder})",
          file=sys.stderr)

    ml = None
    if args.metrics_out:
        from pipegcn_tpu.obs import MetricsLogger, device_info

        try:
            ml = MetricsLogger(args.metrics_out)
            ml.run_header(config=vars(args), device=device_info(),
                          mesh={"n_parts": n_parts})
        except OSError as exc:
            print(f"# metrics sink unavailable: {exc}", file=sys.stderr)
            ml = None

    summary = run_serving_loop(
        engine, duration_s=args.serve_secs, qps=args.serve_qps,
        max_delay_ms=args.serve_max_delay_ms,
        update_every_s=args.serve_update_every,
        refresh_every_s=args.serve_refresh_every,
        max_queue=args.serve_max_queue or None,
        seed=0, ml=ml)

    rnd = lambda v, k=3: None if v is None else round(v, k)  # noqa: E731
    result = {
        "metric": "serve_qps",
        "value": round(summary["qps"], 2),
        "unit": "q/s",
        "serve": True,
        "backend": backend,
        "device": device_kind,
        "n_parts": n_parts,
        "dtype": scfg.dtype,
        "spmm_impl": args.spmm_impl,
        "target_qps": args.serve_qps,
        "n_queries": summary["n_queries"],
        "duration_s": round(summary["duration_s"], 2),
        "p50_ms": rnd(summary["p50_ms"]),
        "p95_ms": rnd(summary["p95_ms"]),
        "p99_ms": rnd(summary["p99_ms"]),
        "batch_fill": rnd(summary["batch_fill"]),
        "cache_hit_rate": rnd(summary["cache_hit_rate"]),
        "staleness_age_max": summary["staleness_age_max"],
        "n_shed": summary["n_shed"],
        "conserved": summary["conserved"],
        "warmup_s": round(warm_s, 2),
    }
    if degraded:
        result["degraded"] = True
    if ml is not None:
        try:
            ml.event("bench", **result)
        finally:
            ml.close()
    print(json.dumps(result))
    return result


def _measure_fleet(args, backend, device_kind, n_parts, degraded, sg,
                   cfg):
    """bench.py --serve --replicas N: aggregate QPS of an N-replica
    serving fleet (each replica a full mesh in its own process) behind
    the failover router, with a mid-load checkpoint hot-swap so the
    headline carries the measured `param_swap_ms` blip. Near-linear
    aggregate QPS in N is the acceptance bar (docs/SERVING.md
    "Fleet")."""
    import glob
    import shutil
    import tempfile
    import threading

    from pipegcn_tpu.parallel import Trainer, TrainConfig
    from pipegcn_tpu.serve.fleet import FleetManager, run_fleet_loop
    from pipegcn_tpu.serve.router import Router
    from pipegcn_tpu.utils.checkpoint import save_checkpoint

    part_path = getattr(sg, "cache_dir", None)
    if not part_path:
        raise RuntimeError(
            "--replicas needs an on-disk partition artifact (bench "
            "always builds one; sg.cache_dir unset)")
    scfg = dataclasses.replace(cfg, use_pp=False, dropout=0.0)

    work_dir = tempfile.mkdtemp(prefix="bench-fleet-")
    ckpt_dir = os.path.join(work_dir, "ckpt")
    fleet_dir = os.path.join(work_dir, "fleet")

    # one driver-side trainer supplies the checkpoint the replicas
    # restore (generation 1) and hot-swap to (generation 2, published
    # mid-load): the zero-downtime refresh path, end to end
    t0 = time.perf_counter()
    trainer = Trainer(sg, scfg, TrainConfig(
        lr=0.01, n_epochs=0, enable_pipeline=False, seed=0, eval=False))
    save_checkpoint(ckpt_dir, trainer.host_state(), 1)
    print(f"# fleet setup: checkpoint generation 1 saved "
          f"({time.perf_counter()-t0:.1f}s)", file=sys.stderr)

    hidden = cfg.layer_sizes[1]
    n_layers = len(cfg.layer_sizes) - 1
    child_args = [
        "--partition-dir", os.path.dirname(os.path.abspath(part_path)),
        # the forwarded graph name IS the full artifact basename
        # (cluster suffix and all) — stop the replica's parser from
        # re-appending its default -c<suffix>
        "--graph-name", os.path.basename(part_path),
        "--local-reorder", "none",
        "--n-partitions", str(n_parts),
        "--checkpoint-dir", ckpt_dir,
        "--model", "graphsage",
        "--n-hidden", str(hidden),
        "--n-layers", str(n_layers),
        "--norm", "layer", "--dropout", "0.0",
        "--dtype", scfg.dtype,
        "--spmm-impl", args.spmm_impl,
        "--seed", "0",
        "--serve-max-batch", str(args.serve_max_batch),
        "--serve-report-every", "2.0",
        "--fleet-swap-poll", "0.3",
    ]
    env = dict(os.environ)
    if "xla_force_host_platform_device_count" not in \
            env.get("XLA_FLAGS", ""):
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n_parts}"
        ).strip()
    env.setdefault("PIPEGCN_PLATFORM", "cpu")
    env.setdefault("JAX_PLATFORMS", env["PIPEGCN_PLATFORM"])

    ml = None
    if args.metrics_out:
        from pipegcn_tpu.obs import MetricsLogger, device_info

        try:
            ml = MetricsLogger(args.metrics_out)
            ml.run_header(config=vars(args), device=device_info(),
                          mesh={"n_parts": n_parts,
                                "replicas": args.replicas})
        except OSError as exc:
            print(f"# metrics sink unavailable: {exc}", file=sys.stderr)
            ml = None

    manager = FleetManager(fleet_dir, args.replicas,
                           child_args=child_args, ml=ml, env=env,
                           log=lambda m: print(f"# {m}",
                                               file=sys.stderr))
    t0 = time.perf_counter()
    clients = manager.launch_all()
    print(f"# fleet: {args.replicas} replicas ready in "
          f"{time.perf_counter()-t0:.1f}s", file=sys.stderr)
    router = Router(clients, policy="least-queue")

    # publish generation 2 mid-load: every replica's watcher verifies
    # the digests and load_params-swaps without retracing
    def _publish_gen2():
        save_checkpoint(ckpt_dir, trainer.host_state(), 2)
        print("# fleet: checkpoint generation 2 published (hot-swap)",
              file=sys.stderr)

    timer = threading.Timer(max(args.serve_secs / 2, 1.0),
                            _publish_gen2)
    timer.daemon = True
    timer.start()

    num_nodes = int((np.asarray(sg.global_nid) >= 0).sum())
    # --autoscale: bounded queue + degradation ladder + scale policy;
    # cooldown of two report windows is the ramp rate on a short bench
    autoscaler = None
    ladder = None
    max_queue = args.serve_max_queue or None
    if getattr(args, "autoscale", False):
        from pipegcn_tpu.serve.autoscale import AutoscalePolicy
        from pipegcn_tpu.serve.batcher import AdmissionLadder

        max_queue = args.serve_max_queue or 4 * args.serve_max_batch
        ladder = AdmissionLadder()
        autoscaler = AutoscalePolicy(
            min_replicas=1,
            max_replicas=max(4, args.replicas),
            queue_high=max_queue // 2,
            queue_low=max(1, max_queue // 8),
            cooldown_s=4.0)
    try:
        summary = run_fleet_loop(
            manager, router, num_nodes=num_nodes,
            duration_s=args.serve_secs, qps=args.serve_qps,
            max_batch=args.serve_max_batch,
            max_delay_ms=args.serve_max_delay_ms,
            max_queue=max_queue,
            traffic=args.traffic or None,
            ladder=ladder, autoscaler=autoscaler,
            seed=0, ml=ml)
    finally:
        timer.cancel()
        manager.stop_all()

    # the measured swap blip lives in the replicas' own metrics files
    swap_ms = []
    for path in glob.glob(os.path.join(fleet_dir,
                                       "replica-m*-metrics.jsonl")):
        try:
            with open(path) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("event") == "fleet" \
                            and rec.get("kind") == "hot-swap":
                        swap_ms.append(float(rec.get("swap_ms", 0.0)))
        except OSError:
            pass

    rnd = lambda v, k=3: None if v is None else round(v, k)  # noqa: E731
    result = {
        "metric": "fleet_qps",
        "value": round(summary["qps"], 2),
        "unit": "q/s",
        "serve": True,
        "fleet": True,
        "replicas": args.replicas,
        "backend": backend,
        "device": device_kind,
        "n_parts": n_parts,
        "dtype": scfg.dtype,
        "target_qps": args.serve_qps,
        "n_queries": summary["n_queries"],
        "duration_s": round(summary["duration_s"], 2),
        "p50_ms": rnd(summary["p50_ms"]),
        "p95_ms": rnd(summary["p95_ms"]),
        "p99_ms": rnd(summary["p99_ms"]),
        "batch_fill": rnd(summary["batch_fill"]),
        "n_shed": summary["n_shed"],
        "n_failovers": summary["n_failovers"],
        "replicas_up": summary["replicas_up"],
        "per_replica_dispatched": summary["per_replica_dispatched"],
        "per_replica_queue_depth_max":
            summary["per_replica_queue_depth_max"],
        "param_generation": summary["param_generation"],
        "param_swap_ms": rnd(max(swap_ms), 1) if swap_ms else None,
        "n_hot_swaps": len(swap_ms),
        "conserved": summary["conserved"],
        "drained": summary["drained"],
    }
    if getattr(args, "traffic", ""):
        result["traffic"] = summary.get("traffic")
    if autoscaler is not None:
        result.update({
            "autoscale": summary.get("autoscale"),
            "replicas_active": summary.get("replicas_active"),
            "n_spawned": summary.get("n_spawned"),
            "n_retired": summary.get("n_retired"),
            "scale_events": summary.get("scale_events"),
            "shed_by_reason": summary.get("shed_by_reason"),
            "rung_max": summary.get("rung_max"),
        })
    if degraded:
        result["degraded"] = True
    if ml is not None:
        try:
            ml.event("bench", **result)
        finally:
            ml.close()
    print(json.dumps(result))
    shutil.rmtree(work_dir, ignore_errors=True)
    return result


if __name__ == "__main__":
    main()
