"""Benchmark: per-epoch training time at Reddit scale.

Reproduces the reference's headline measurement — per-epoch wall-clock of
a 4-layer x 256 GraphSAGE with --enable-pipeline --use-pp on Reddit
(232,965 nodes / ~114.6M directed edges / 602 features / 41 classes;
reference README.md:93-94 reports 0.266 s/epoch on 2 GPUs) — on TPU,
using a synthetic graph with Reddit's shape statistics (the real dataset
needs a download this environment does not allow).

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
vs_baseline > 1 means faster than the reference's 0.266 s/epoch.

The partition/build artifact is cached under partitions/ so repeat runs
skip the ~minutes of host-side preprocessing. Use --small for a quick
smoke-scale run, --parts N to shard over N devices.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

BASELINE_EPOCH_S = 0.266  # reference README.md:93-94 (2x GPU)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true",
                    help="10k-node smoke config instead of Reddit scale")
    ap.add_argument("--parts", type=int, default=0,
                    help="partitions (default: all available devices)")
    ap.add_argument("--epochs", type=int, default=12)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--no-pipeline", action="store_true")
    ap.add_argument("--f32", action="store_true",
                    help="float32 compute (default bfloat16, the "
                         "TPU-native choice)")
    ap.add_argument("--fused", type=int, default=4,
                    help="epochs per dispatch (lax.scan); per-epoch time "
                         "= block time / fused")
    args = ap.parse_args()

    import jax

    from pipegcn_tpu.graph import load_data
    from pipegcn_tpu.models import ModelConfig
    from pipegcn_tpu.parallel import Trainer, TrainConfig
    from pipegcn_tpu.partition import ShardedGraph, partition_graph

    n_parts = args.parts or len(jax.devices())
    if args.small:
        dataset = "synthetic:10000:20:64:16"
        hidden, n_layers = 64, 3
        spmm_chunk = None
        name = f"bench-small-{n_parts}"
    else:
        dataset = "synthetic-reddit"
        hidden, n_layers = 256, 4
        spmm_chunk = 2_097_152  # bound gathered messages to [2M, F]
        # ([2M, 602] f32 = 4.8 GB peak for the pp precompute gather)
        name = f"bench-reddit-{n_parts}"

    part_path = os.path.join("partitions", name)
    t0 = time.perf_counter()
    if ShardedGraph.exists(part_path):
        sg = ShardedGraph.load(part_path)
        print(f"# loaded cached partitions ({time.perf_counter()-t0:.1f}s)",
              file=sys.stderr)
    else:
        g = load_data(dataset)
        parts = partition_graph(g, n_parts, method="metis", obj="vol", seed=0)
        sg = ShardedGraph.build(g, parts, n_parts=n_parts)
        sg.save(part_path)
        print(f"# built partitions ({time.perf_counter()-t0:.1f}s)",
              file=sys.stderr)

    cfg = ModelConfig(
        layer_sizes=(sg.n_feat,) + (hidden,) * (n_layers - 1) + (sg.n_class,),
        use_pp=True, norm="layer", dropout=0.5,
        train_size=sg.n_train_global, spmm_chunk=spmm_chunk,
        dtype="float32" if args.f32 else "bfloat16",
    )
    tcfg = TrainConfig(
        lr=0.01, n_epochs=args.epochs,
        enable_pipeline=not args.no_pipeline, seed=0, eval=False,
        fused_epochs=args.fused,
    )
    t0 = time.perf_counter()
    trainer = Trainer(sg, cfg, tcfg)
    print(f"# trainer setup ({time.perf_counter()-t0:.1f}s)", file=sys.stderr)

    blk = max(1, args.fused)

    def run_block(e0):
        if blk == 1:
            loss = trainer.train_epoch(e0)
        else:
            loss = float(trainer.train_epochs(e0, blk)[-1])
        jax.block_until_ready(trainer.state["params"])
        return loss

    # warmup (compile + pipeline fill); epoch counts round UP to whole
    # blocks so every timed block reuses the same compiled scan length
    t0 = time.perf_counter()
    e = 0
    for _ in range(-(-args.warmup // blk) if args.warmup else 0):
        run_block(e)
        e += blk
    print(f"# warmup/compile ({time.perf_counter()-t0:.1f}s)",
          file=sys.stderr)

    times = []
    n_blocks = -(-args.epochs // blk)
    for _ in range(n_blocks):
        t0 = time.perf_counter()
        loss = run_block(e)
        e += blk
        times.append((time.perf_counter() - t0) / blk)
    epoch_s = float(np.median(times))
    print(f"# median epoch {epoch_s:.4f}s over {n_blocks} blocks of {blk}, "
          f"final loss {loss:.4f}", file=sys.stderr)

    metric = "reddit_scale_epoch_time" if not args.small else \
        "small_epoch_time"
    print(json.dumps({
        "metric": metric,
        "value": round(epoch_s, 4),
        "unit": "s/epoch",
        "vs_baseline": round(BASELINE_EPOCH_S / epoch_s, 3),
    }))


if __name__ == "__main__":
    main()
