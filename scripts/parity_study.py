"""Staleness accuracy-parity study (PipeGCN's central claim).

The paper's core claim is that epoch-stale boundary features/gradients do
not hurt final accuracy (reference README.md:97-98 reproduces Reddit
97.1% WITH pipelining). The round-1 synthetic configs saturated at 100%
in 10 epochs and could not discriminate; this study uses a deliberately
hard SBM graph (low homophily 0.45, 12 classes, 3% train labels, sparse
degree 5) whose accuracy plateaus around ~68%, and compares

    vanilla        — synchronous halo exchange every layer
    pipelined      — staleness-1 exchange (--enable-pipeline)
    pipelined+corr — staleness-1 + feat/grad EMA smoothing

over several seeds. Writes a markdown table to results/staleness_parity.md.

The study is RESUMABLE: each (variant, seed) unit trains in cheap
~--leg-epochs legs with a per-leg checkpoint under --state-dir, and the
markdown table is rewritten after every leg with whatever is complete so
far (incomplete units listed with their progress). A killed run — the
fate of every monolithic attempt at the degree-492 Reddit-shape config,
where one variant x seed is hours — resumes from its last leg instead of
from epoch 0. --time-budget bounds one invocation; repeated invocations
(e.g. from the tpu_window queue) advance the same study.

Usage:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/parity_study.py [--seeds 3] [--epochs 300] [--tpu]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

# runnable as `python scripts/parity_study.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


VARIANTS = {
    "vanilla": dict(enable_pipeline=False),
    "pipelined": dict(enable_pipeline=True),
    "pipelined+corr": dict(enable_pipeline=True, feat_corr=True,
                           grad_corr=True),
}


def _unit_key(name: str, seed: int) -> str:
    return f"{name.replace('+', '-')}_s{seed}"


def _load_progress(state_dir: str, key: str) -> dict:
    path = os.path.join(state_dir, key, "progress.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {"epochs_done": 0, "best_val": -1.0, "test_acc": -1.0}


def _save_progress(state_dir: str, key: str, prog: dict) -> None:
    d = os.path.join(state_dir, key)
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, "progress.json.tmp")
    with open(tmp, "w") as f:
        json.dump(prog, f)
    os.replace(tmp, os.path.join(d, "progress.json"))  # atomic: a
    # mid-write kill must not corrupt the resume point


def write_table(args, progress: dict) -> None:
    """Rewrite the markdown output from CURRENT state: aggregated
    mean +/- std over completed (variant, seed) units, plus a progress
    row per incomplete unit — a killed run still leaves a readable
    partial-results table behind."""
    lines = [
        f"# Staleness accuracy parity (hard synthetic, {args.model})",
        "",
        f"SBM graph: {args.nodes} nodes, avg degree {args.degree}, "
        f"{args.feat} feats, {args.classes} classes, homophily "
        f"{args.homophily}, {args.train_frac:.0%} train labels;",
        f"{args.model} 3x{args.hidden}, dropout 0.3, lr 3e-3, "
        f"{args.epochs} epochs, {args.parts} partitions, "
        f"{args.seeds} seeds; spmm_impl={args.spmm_impl}, "
        f"rem_dtype={args.rem_dtype}.",
        "",
        "| variant | best val (mean ± std) | test @ best val (mean ± std) |",
        "|---|---|---|",
    ]
    summary = {}
    pending = []
    for name in VARIANTS:
        done, part = [], []
        for seed in range(1, args.seeds + 1):
            p = progress[_unit_key(name, seed)]
            if p["epochs_done"] >= args.epochs:
                done.append((p["best_val"], p["test_acc"]))
            else:
                part.append((seed, p))
        if done:
            bv = np.array([r[0] for r in done])
            ts = np.array([r[1] for r in done])
            summary[name] = (bv.mean(), ts.mean(),
                             ts.std(), len(done))
            tag = "" if not part else \
                f" ({len(done)}/{args.seeds} seeds)"
            lines.append(
                f"| {name}{tag} | {bv.mean():.4f} ± {bv.std():.4f} "
                f"| {ts.mean():.4f} ± {ts.std():.4f} |")
        for seed, p in part:
            cur = (f", best val {p['best_val']:.4f} so far"
                   if p["best_val"] >= 0 else "")
            pending.append(f"- {name} seed {seed}: "
                           f"{p['epochs_done']}/{args.epochs} "
                           f"epochs{cur}")
    if pending:
        lines += ["", "Incomplete units (resumes from the last "
                      f"~{args.leg_epochs}-epoch leg checkpoint in "
                      f"`{args.state_dir}`):"] + pending
    if len(summary) == len(VARIANTS) and not pending:
        spread = max(s[1] for s in summary.values()) - \
            min(s[1] for s in summary.values())
        noise = max(max(s[2] for s in summary.values()), 1e-4)
        if spread <= 2 * noise:
            verdict = (
                "staleness-1 pipelining (with or without EMA "
                "correction) tracks the synchronous baseline within "
                "seed noise, the analogue of the reference's Reddit "
                "97.1%-with-pipelining reproduction (README.md:97-98).")
        else:
            verdict = (
                f"on this config ({args.train_frac:.0%} labels, "
                f"homophily {args.homophily}) staleness costs "
                f"~{spread:.3f} accuracy beyond seed noise (max std "
                f"{noise:.3f}) for this model family; the EMA "
                f"corrections recover part of it.")
        lines += [
            "",
            f"Max mean-test-accuracy spread across variants: "
            f"{spread:.4f} — " + verdict,
        ]
    elif summary:
        lines += ["", "Study in progress — verdict withheld until "
                      "every variant x seed completes."]
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        f.write("\n".join(lines) + "\n")
    os.replace(tmp, args.out)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=3)
    ap.add_argument("--epochs", type=int, default=300)
    ap.add_argument("--parts", type=int, default=8)
    ap.add_argument("--model", default="graphsage",
                    choices=["graphsage", "gcn", "gat"],
                    help="model family to study (the staleness claim "
                         "should hold for all of them)")
    ap.add_argument("--out", default="")
    ap.add_argument("--tpu", action="store_true",
                    help="run on the default (TPU) backend instead of CPU")
    # graph shape overrides: the default is the small hard-SBM config;
    # --nodes 232965 runs the Reddit-node-count long-horizon analogue
    # of the reference's 97.1%-with-pipelining reproduction
    ap.add_argument("--nodes", type=int, default=6000)
    ap.add_argument("--degree", type=int, default=5)
    ap.add_argument("--feat", type=int, default=6)
    ap.add_argument("--classes", type=int, default=12)
    ap.add_argument("--homophily", type=float, default=0.45)
    ap.add_argument("--train-frac", type=float, default=0.03)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--fused", type=int, default=25)
    ap.add_argument("--name", default="",
                    help="output suffix, e.g. 'reddit_scale'")
    ap.add_argument("--spmm-impl", default="xla",
                    choices=["xla", "bucket", "block", "auto"])
    ap.add_argument("--rem-dtype", default="none",
                    choices=["none", "bfloat16", "float8"],
                    help="gather-transport narrowing under study "
                         "(ModelConfig.rem_dtype)")
    ap.add_argument("--leg-epochs", type=int, default=150,
                    help="epochs per resumable leg: each leg ends in a "
                         "checkpoint + a rewritten partial table, so a "
                         "killed run loses at most one leg")
    ap.add_argument("--state-dir", default="",
                    help="leg checkpoints + progress files (default "
                         "results/parity_state<suffix>)")
    ap.add_argument("--time-budget", type=float, default=0.0,
                    help="seconds: stop cleanly (table written, resume "
                         "hint printed) before starting a leg past this "
                         "budget; 0 = run to completion")
    args = ap.parse_args()
    suffix = "" if args.model == "graphsage" else f"_{args.model}"
    if args.name:
        suffix += f"_{args.name}"
    if not args.out:
        args.out = f"results/staleness_parity{suffix}.md"
    if not args.state_dir:
        args.state_dir = f"results/parity_state{suffix}"

    import jax

    if not args.tpu:
        # the site hook pins JAX_PLATFORMS; config.update is the only
        # reliable way to select CPU
        jax.config.update("jax_platforms", "cpu")

    from pipegcn_tpu.graph import synthetic_graph
    from pipegcn_tpu.models import ModelConfig
    from pipegcn_tpu.parallel import Trainer, TrainConfig
    from pipegcn_tpu.partition import ShardedGraph, partition_graph
    from pipegcn_tpu.utils.checkpoint import (checkpoint_exists,
                                              load_checkpoint)

    g = synthetic_graph(num_nodes=args.nodes, avg_degree=args.degree,
                        n_feat=args.feat, n_class=args.classes,
                        homophily=args.homophily,
                        train_frac=args.train_frac, val_frac=0.2,
                        seed=0)
    parts = partition_graph(g, args.parts, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=args.parts)
    eval_graphs = {"val": (g, "val_mask"), "test": (g, "test_mask")}

    progress = {_unit_key(n, s): _load_progress(args.state_dir,
                                                _unit_key(n, s))
                for n in VARIANTS for s in range(1, args.seeds + 1)}
    t_start = time.time()
    leg = max(1, args.leg_epochs)

    for name, kw in VARIANTS.items():
        for seed in range(1, args.seeds + 1):
            key = _unit_key(name, seed)
            prog = progress[key]
            ckpt_dir = os.path.join(args.state_dir, key, "ckpt")
            while prog["epochs_done"] < args.epochs:
                if args.time_budget and \
                        time.time() - t_start > args.time_budget:
                    write_table(args, progress)
                    print(f"# time budget exhausted at {key} "
                          f"({prog['epochs_done']}/{args.epochs}); "
                          f"re-run to resume from {args.state_dir}",
                          file=sys.stderr)
                    return
                end = min(prog["epochs_done"] + leg, args.epochs)
                cfg = ModelConfig(
                    layer_sizes=(sg.n_feat, args.hidden, args.hidden,
                                 sg.n_class), norm="layer",
                    dropout=0.3, train_size=sg.n_train_global,
                    model=args.model, spmm_impl=args.spmm_impl,
                    rem_dtype=args.rem_dtype,
                )
                tcfg = TrainConfig(seed=seed, lr=3e-3, n_epochs=end,
                                   log_every=25,
                                   fused_epochs=min(args.fused, leg),
                                   **kw)
                t = Trainer(sg, cfg, tcfg)
                start_epoch = 0
                if prog["epochs_done"] > 0 and \
                        checkpoint_exists(ckpt_dir):
                    host_state, start_epoch = load_checkpoint(
                        ckpt_dir, t.host_state())
                    t.restore_state(host_state)
                res = t.fit(eval_graphs, log_fn=lambda *_: None,
                            sharded_eval=True,
                            start_epoch=start_epoch,
                            checkpoint_dir=ckpt_dir,
                            checkpoint_every=leg)
                # the leg's best merges into the unit's running best:
                # each fit() tracks only its own window
                if res["best_val"] > prog["best_val"]:
                    prog["best_val"] = float(res["best_val"])
                    prog["test_acc"] = float(res["test_acc"])
                prog["epochs_done"] = end
                _save_progress(args.state_dir, key, prog)
                write_table(args, progress)
                print(f"{name} seed={seed}: epoch {end}/{args.epochs}, "
                      f"best_val={prog['best_val']:.4f} "
                      f"test={prog['test_acc']:.4f}", file=sys.stderr)

    write_table(args, progress)
    with open(args.out) as f:
        print(f.read())


if __name__ == "__main__":
    main()
