"""Staleness accuracy-parity study (PipeGCN's central claim).

The paper's core claim is that epoch-stale boundary features/gradients do
not hurt final accuracy (reference README.md:97-98 reproduces Reddit
97.1% WITH pipelining). The round-1 synthetic configs saturated at 100%
in 10 epochs and could not discriminate; this study uses a deliberately
hard SBM graph (low homophily 0.45, 12 classes, 3% train labels, sparse
degree 5) whose accuracy plateaus around ~68%, and compares

    vanilla        — synchronous halo exchange every layer
    pipelined      — staleness-1 exchange (--enable-pipeline)
    pipelined+corr — staleness-1 + feat/grad EMA smoothing

over several seeds. Writes a markdown table to results/staleness_parity.md.

Usage:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/parity_study.py [--seeds 3] [--epochs 300] [--tpu]
"""

import argparse
import os
import sys

import numpy as np

# runnable as `python scripts/parity_study.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=3)
    ap.add_argument("--epochs", type=int, default=300)
    ap.add_argument("--parts", type=int, default=8)
    ap.add_argument("--model", default="graphsage",
                    choices=["graphsage", "gcn", "gat"],
                    help="model family to study (the staleness claim "
                         "should hold for all of them)")
    ap.add_argument("--out", default="")
    ap.add_argument("--tpu", action="store_true",
                    help="run on the default (TPU) backend instead of CPU")
    # graph shape overrides: the default is the small hard-SBM config;
    # --nodes 232965 runs the Reddit-node-count long-horizon analogue
    # of the reference's 97.1%-with-pipelining reproduction
    ap.add_argument("--nodes", type=int, default=6000)
    ap.add_argument("--degree", type=int, default=5)
    ap.add_argument("--feat", type=int, default=6)
    ap.add_argument("--classes", type=int, default=12)
    ap.add_argument("--homophily", type=float, default=0.45)
    ap.add_argument("--train-frac", type=float, default=0.03)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--fused", type=int, default=25)
    ap.add_argument("--name", default="",
                    help="output suffix, e.g. 'reddit_scale'")
    ap.add_argument("--spmm-impl", default="xla",
                    choices=["xla", "bucket", "block", "auto"])
    ap.add_argument("--rem-dtype", default="none",
                    choices=["none", "bfloat16", "float8"],
                    help="gather-transport narrowing under study "
                         "(ModelConfig.rem_dtype)")
    args = ap.parse_args()
    if not args.out:
        suffix = "" if args.model == "graphsage" else f"_{args.model}"
        if args.name:
            suffix += f"_{args.name}"
        args.out = f"results/staleness_parity{suffix}.md"

    import jax

    if not args.tpu:
        # the site hook pins JAX_PLATFORMS; config.update is the only
        # reliable way to select CPU
        jax.config.update("jax_platforms", "cpu")

    from pipegcn_tpu.graph import synthetic_graph
    from pipegcn_tpu.models import ModelConfig
    from pipegcn_tpu.parallel import Trainer, TrainConfig
    from pipegcn_tpu.partition import ShardedGraph, partition_graph

    g = synthetic_graph(num_nodes=args.nodes, avg_degree=args.degree,
                        n_feat=args.feat, n_class=args.classes,
                        homophily=args.homophily,
                        train_frac=args.train_frac, val_frac=0.2,
                        seed=0)
    parts = partition_graph(g, args.parts, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=args.parts)
    eval_graphs = {"val": (g, "val_mask"), "test": (g, "test_mask")}

    variants = {
        "vanilla": dict(enable_pipeline=False),
        "pipelined": dict(enable_pipeline=True),
        "pipelined+corr": dict(enable_pipeline=True, feat_corr=True,
                               grad_corr=True),
    }

    results = {name: [] for name in variants}
    for name, kw in variants.items():
        for seed in range(1, args.seeds + 1):
            cfg = ModelConfig(
                layer_sizes=(sg.n_feat, args.hidden, args.hidden,
                             sg.n_class), norm="layer",
                dropout=0.3, train_size=sg.n_train_global,
                model=args.model, spmm_impl=args.spmm_impl,
                rem_dtype=args.rem_dtype,
            )
            tcfg = TrainConfig(seed=seed, lr=3e-3, n_epochs=args.epochs,
                               log_every=25, fused_epochs=args.fused,
                               **kw)
            t = Trainer(sg, cfg, tcfg)
            res = t.fit(eval_graphs, log_fn=lambda *_: None,
                        sharded_eval=True)
            results[name].append((res["best_val"], res["test_acc"]))
            print(f"{name} seed={seed}: best_val={res['best_val']:.4f} "
                  f"test={res['test_acc']:.4f}", file=sys.stderr)

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    lines = [
        f"# Staleness accuracy parity (hard synthetic, {args.model})",
        "",
        f"SBM graph: {args.nodes} nodes, avg degree {args.degree}, "
        f"{args.feat} feats, {args.classes} classes, homophily "
        f"{args.homophily}, {args.train_frac:.0%} train labels;",
        f"{args.model} 3x{args.hidden}, dropout 0.3, lr 3e-3, "
        f"{args.epochs} epochs, {args.parts} partitions, "
        f"{args.seeds} seeds; spmm_impl={args.spmm_impl}, "
        f"rem_dtype={args.rem_dtype}.",
        "",
        "| variant | best val (mean ± std) | test @ best val (mean ± std) |",
        "|---|---|---|",
    ]
    summary = {}
    for name, rs in results.items():
        bv = np.array([r[0] for r in rs])
        ts = np.array([r[1] for r in rs])
        summary[name] = (bv.mean(), ts.mean())
        lines.append(
            f"| {name} | {bv.mean():.4f} ± {bv.std():.4f} "
            f"| {ts.mean():.4f} ± {ts.std():.4f} |"
        )
    spread = max(s[1] for s in summary.values()) - \
        min(s[1] for s in summary.values())
    stds = [np.array([r[1] for r in rs]).std() for rs in results.values()]
    noise = max(max(stds), 1e-4)
    if spread <= 2 * noise:
        verdict = (
            "staleness-1 pipelining (with or without EMA correction) "
            "tracks the synchronous baseline within seed noise, the "
            "analogue of the reference's Reddit 97.1%-with-pipelining "
            "reproduction (README.md:97-98)."
        )
    else:
        verdict = (
            f"on this config ({args.train_frac:.0%} labels, homophily "
            f"{args.homophily}) staleness costs ~{spread:.3f} accuracy "
            f"beyond seed noise (max std {noise:.3f}) for this model "
            f"family; the EMA corrections recover part of it."
        )
    lines += [
        "",
        f"Max mean-test-accuracy spread across variants: {spread:.4f} — "
        + verdict,
    ]
    with open(args.out, "w") as f:
        f.write("\n".join(lines) + "\n")
    print("\n".join(lines))


if __name__ == "__main__":
    main()
