#!/usr/bin/env python
"""Off-shape chip point for the auto-kernel policy (VERDICT r4 item 8).

The auto thresholds (_AUTO_BLOCK_MIN_EDGES / _AUTO_BLOCK_MIN_COVERAGE,
parallel/trainer.py) and the f8-transport lever were calibrated on ONE
graph family (synthetic-Reddit: 233k nodes, deg 492, F=602/256). This
benches a second family on chip — the ogbn-products shape (2.45M nodes,
deg ~51, F=100, 47 classes, hidden 128: reference
scripts/ogbn-products.sh + helper/utils.py:17-30) or the Yelp shape —
and records what `auto` resolves to there plus the measured
block/bucket/f8 ranking, so the policy rests on two shape points
instead of one.

Dispatch discipline follows scripts/gat_bench.py: single-epoch probe
(min of two), fused blocks sized under the tunnel's ~80 s execute
ceiling, device->host scalar read per dispatch.

Usage:
  python scripts/offshape_bench.py --shape products --build-only  # host
  python scripts/offshape_bench.py --shape products --impl auto
"""

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# dataset spec + reference model config per shape:
#   products: 2,449,029 nodes / avg deg ~51 / 100 feats / 47 classes;
#     3 layers x 128 hidden, dropout 0.3 (scripts/ogbn-products.sh)
#   yelp: 716,847 nodes / deg ~19 / 300 feats / 100 classes;
#     4 layers x 512 hidden, dropout 0.1 (scripts/yelp.sh)
SHAPES = {
    "products": ("synthetic:2449029:51:100:47", 128, 3, 0.3),
    "yelp": ("synthetic:716847:19:300:100", 512, 4, 0.1),
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shape", default="products", choices=sorted(SHAPES))
    ap.add_argument("--impl", default="auto",
                    choices=["auto", "block", "bucket"])
    ap.add_argument("--rem-dtype", default="float8",
                    choices=["none", "bfloat16", "float8"])
    ap.add_argument("--block-group", type=int, default=4)
    ap.add_argument("--epochs", type=int, default=8,
                    help="max fused-epoch block length")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--build-only", action="store_true",
                    help="build + cache the partition artifact (and "
                         "kernel tables) on the host, no measurement")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import jax

    if args.cpu or args.build_only:
        jax.config.update("jax_platforms", "cpu")

    from pipegcn_tpu.models import ModelConfig
    from pipegcn_tpu.parallel import Trainer, TrainConfig
    from pipegcn_tpu.partition import ShardedGraph

    dataset, hidden, n_layers, dropout = SHAPES[args.shape]
    part_path = os.path.join("partitions", f"offshape-{args.shape}-1-s1024")
    t0 = time.time()
    if ShardedGraph.exists(part_path):
        sg = ShardedGraph.load(part_path)
        print(f"# loaded cached artifact ({time.time()-t0:.0f}s)",
              file=sys.stderr)
    else:
        from pipegcn_tpu.graph import load_data
        from pipegcn_tpu.partition import (locality_clusters,
                                           partition_graph)

        g = load_data(dataset)
        parts = partition_graph(g, 1, seed=0)
        cluster = locality_clusters(g, target_size=1024, seed=0)
        sg = ShardedGraph.build(g, parts, n_parts=1, cluster=cluster)
        sg.save(part_path)
        print(f"# built artifact ({time.time()-t0:.0f}s)",
              file=sys.stderr)
    sg.cache_dir = part_path

    cfg = ModelConfig(
        layer_sizes=(sg.n_feat,) + (hidden,) * (n_layers - 1)
                    + (sg.n_class,),
        use_pp=True, norm="layer", dropout=dropout,
        train_size=sg.n_train_global, spmm_chunk=2_097_152,
        dtype="bfloat16", spmm_impl=args.impl,
        block_group=args.block_group, rem_dtype=args.rem_dtype,
    )
    tcfg = TrainConfig(lr=0.003,
                       n_epochs=3 + args.epochs * (args.reps + 2),
                       enable_pipeline=True, eval=False,
                       fused_epochs=args.epochs)
    t0 = time.time()
    tr = Trainer(sg, cfg, tcfg)
    resolved = ("block" if tr._block_tables is not None else
                "bucket" if tr._bucket_tables is not None else
                args.impl)
    print(f"# trainer init (tables) {time.time()-t0:.0f}s; "
          f"impl={args.impl} resolved={resolved}", file=sys.stderr)
    if args.build_only:
        print(f"# artifact + {resolved} tables cached at {part_path}")
        return

    from bench import MAX_DISPATCH_S

    def check_finite(losses, e_last):
        # abort on the FIRST non-finite intermediate loss: the
        # products-shape NaN burned every remaining measurement block
        # after epoch 0 went NaN (VERDICT r5) — a diverged run must
        # stop spending TPU-window time IMMEDIATELY, loudly, red
        bad = ~np.isfinite(np.asarray(losses, np.float64))
        if bad.any():
            j = int(np.argmax(bad))
            print(f"# NON-FINITE LOSS at epoch "
                  f"{e_last - len(losses) + 1 + j} — aborting the "
                  f"measurement (exit 3); diagnose with the numerics "
                  f"tripwire (docs/RESILIENCE.md 'Numerics')",
                  file=sys.stderr)
            sys.exit(3)

    t0 = time.perf_counter()
    losses = tr.train_epochs(0, 1)
    print(f"# compile+first {time.perf_counter()-t0:.0f}s "
          f"loss={float(losses[-1]):.4f}", file=sys.stderr)
    check_finite(losses, 0)
    singles = []
    for i in (1, 2):
        t0 = time.perf_counter()
        losses = tr.train_epochs(i, 1)
        singles.append(time.perf_counter() - t0)
        check_finite(losses, i)
    single = min(singles)
    print(f"# single epoch {single:.2f}s", file=sys.stderr)
    blk = max(1, min(args.epochs,
                     int(MAX_DISPATCH_S // max(single, 1e-6))))
    e = 3
    if blk > 1:
        t0 = time.perf_counter()
        losses = tr.train_epochs(e, blk)
        e += blk
        print(f"# fused-{blk} warmup/compile "
              f"{time.perf_counter()-t0:.0f}s", file=sys.stderr)
        check_finite(losses, e - 1)

    times = []
    for r in range(args.reps):
        t0 = time.perf_counter()
        losses = tr.train_epochs(e, blk)
        dt = time.perf_counter() - t0
        e += blk
        times.append(dt / blk)
        print(f"# block {r}: {dt:.2f}s -> {dt/blk:.3f} s/epoch "
              f"loss={float(losses[-1]):.4f}", file=sys.stderr)
        check_finite(losses, e - 1)

    final_loss = float(losses[-1])
    print(json.dumps({
        "metric": f"offshape_{args.shape}_{args.impl}_epoch_time"
                  + ("" if args.rem_dtype == "none"
                     else f"_{args.rem_dtype}"),
        "value": round(float(np.median(times)), 4),
        "unit": "s/epoch",
        "resolved_impl": resolved,
        "block_group": args.block_group,
        "hidden": hidden,
        "dispatch_epochs": blk,
        "backend": jax.default_backend(),
        "loss": round(final_loss, 4) if np.isfinite(final_loss) else None,
    }))
    if not np.isfinite(final_loss):
        # the known products-shape NaN (VERDICT "Next round" item 1)
        # must never again publish a green JSON: timing a diverged run
        # measures nothing
        print("# FINAL LOSS NON-FINITE — benchmark invalid; exiting 3",
              file=sys.stderr)
        sys.exit(3)


if __name__ == "__main__":
    main()
