# Hermetic smoke run on an 8-virtual-device CPU mesh (no dataset needed)
PIPEGCN_PLATFORM=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
python main.py \
  --dataset synthetic:2000:10:32:8 \
  --dropout 0.3 \
  --lr 0.01 \
  --n-partitions 4 \
  --n-epochs 60 \
  --n-layers 3 \
  --n-hidden 64 \
  --log-every 10 \
  --enable-pipeline \
  --use-pp
