#!/usr/bin/env python
"""Full-density, full-length convergence study (VERDICT round-3 item 3).

The reference's headline accuracy artifact is Reddit trained 3000
epochs to 97.10% test (reference README.md:91-99, train.py:377-400),
with PipeGCN's claim being that staleness-1 pipelining (and the
smoothing corrections) reach the same accuracy. Every prior study in
this repo ran at avg degree 6-16; Reddit's reality is ~492, where halo
ratios, staleness error and normalization statistics are qualitatively
different. This study runs THE comparison at full density:

  synthetic SBM graph at avg degree 492 (noise raised so the task has
  a real learning curve), P=4 partitions, 4x256 GraphSAGE + use_pp,
  3000 epochs; legs: vanilla | pipelined | pipelined+corrections.

P=4 runs on ONE device via TrainConfig.emulate_parts (vmap-with-
axis_name; bit-matches the real mesh — tests/test_trainer.py::
test_emulate_parts_matches_mesh), so the scarce single TPU chip can
carry it at chip speed; on CPU the same script limps for smoke tests.

Resumable: per-leg checkpoints + a jsonl history under --state-dir;
--time-budget makes a run stop cleanly mid-leg so tunnel windows can
be strung together (scripts/tpu_window.py queue). When every leg
reaches --epochs, writes the report with reference-format result
lines.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

LEGS = ("vanilla", "pipelined", "corrected")


def leg_tcfg(leg, args):
    from pipegcn_tpu.parallel import TrainConfig

    return TrainConfig(
        lr=args.lr, n_epochs=args.epochs, seed=0,
        enable_pipeline=leg != "vanilla",
        feat_corr=leg == "corrected", grad_corr=leg == "corrected",
        fused_epochs=args.fused, eval=False, emulate_parts=True,
    )


def run_leg(leg, sg, g, cfg, args, deadline):
    """Advance one leg toward args.epochs; returns (done, history)."""
    import jax

    from pipegcn_tpu.parallel import Trainer
    from pipegcn_tpu.utils.checkpoint import (
        checkpoint_exists, load_checkpoint, peek_epoch, save_checkpoint)

    sdir = os.path.join(args.state_dir, leg)
    hist_path = os.path.join(sdir, "history.jsonl")
    lhist_path = None
    if args.light_dir:
        os.makedirs(args.light_dir, exist_ok=True)
        lhist_path = os.path.join(args.light_dir,
                                  f"{leg}_history.jsonl")
    def write_rows(path, rows):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")

    history = []
    src = hist_path if os.path.exists(hist_path) else (
        lhist_path if lhist_path and os.path.exists(lhist_path)
        else None)
    if src:
        with open(src) as f:
            for l in f:
                if not l.strip():
                    continue
                try:
                    history.append(json.loads(l))
                except json.JSONDecodeError:
                    # the window queue SIGKILLs mid-append on timeout;
                    # a half-written trailing row must not wedge every
                    # later window — the checkpoint is the source of
                    # truth and rows >= start are truncated below
                    break

    # completed-leg fast path and exhausted-budget bail BEFORE Trainer
    # construction, which at full scale pays device upload + minutes of
    # kernel-table work per call. The LIGHT checkpoint (params+opt+norm
    # only, git-committable ~MBs) backs the full local one: gitignored
    # state did not survive the round-3->4 boundary, and losing hours
    # of full-scale training to a workspace wipe is not acceptable.
    light = os.path.join(args.light_dir, f"{leg}.npz") \
        if args.light_dir else None
    ck_epoch = peek_epoch(sdir)
    from_light = False
    if ck_epoch is None and light and os.path.exists(light):
        with np.load(light) as zz:
            ck_epoch = int(zz["__epoch__"])
        from_light = True
    start = (ck_epoch + 1) if ck_epoch is not None else 0
    if history and history[-1]["epoch"] >= start:
        history = [r for r in history if r["epoch"] < start]
        write_rows(hist_path, history)
        if lhist_path and os.path.exists(lhist_path):
            write_rows(lhist_path, history)
    if src == lhist_path and not os.path.exists(hist_path) and history:
        # re-seed the authoritative copy after a workspace wipe
        write_rows(hist_path, history)
    if lhist_path and src == hist_path and history:
        # seed/catch-up the survival mirror: --light-dir may be enabled
        # mid-study, and a gapped mirror would later become the
        # authoritative history after a wipe
        lrows = []
        if os.path.exists(lhist_path):
            with open(lhist_path) as f:
                lrows = [json.loads(l) for l in f if l.strip()]
        if len(lrows) < len(history):
            write_rows(lhist_path, history)
    if start >= args.epochs:
        return True, history
    if deadline and time.time() > deadline:
        return False, history

    # the CHECKPOINT is the source of truth for where to resume — a
    # kill between the history flush and the checkpoint save must not
    # wedge the study, so newer history rows are truncated instead
    t = Trainer(sg, cfg, leg_tcfg(leg, args))
    if checkpoint_exists(sdir):
        state, _ = load_checkpoint(sdir, t.state)
        t.state = state
    elif from_light:
        # params/opt/norm from the light checkpoint over a fresh
        # trainer: the staleness/EMA carries restart from zeros and
        # re-warm within ~an epoch (the staleness-exactness property).
        # The file stores replica 0 only (the psum'd update keeps every
        # part's copy identical); re-broadcast over the leading P axis
        import jax.numpy as jnp

        from pipegcn_tpu.utils.checkpoint import load_pytree

        subset = {k: t.state[k] for k in ("params", "opt", "norm")}
        tmpl0 = jax.tree_util.tree_map(lambda v: v[0], subset)
        r0 = load_pytree(light, tmpl0)
        restored = jax.tree_util.tree_map(
            lambda full, x: jnp.broadcast_to(x, full.shape)
            .astype(full.dtype), subset, r0)
        t.state = {**t.state, **restored}
        print(f"# [{leg}] light-resume at epoch {start} "
              "(staleness/EMA carries reset; re-warm ~1 epoch)",
              flush=True)
    print(f"# [{leg}] resuming at epoch {start}", flush=True)

    os.makedirs(sdir, exist_ok=True)
    hist_f = open(hist_path, "a")
    e = start
    while e < args.epochs:
        # an already-exhausted budget (e.g. the first full-scale window
        # spent it on the artifact build) must not commit to another
        # full eval_every chunk — the outer queue timeout would kill it
        # mid-chunk and lose the work since the last checkpoint
        if deadline and time.time() > deadline:
            print(f"# [{leg}] time budget reached at epoch {e}",
                  flush=True)
            hist_f.close()
            return False, history
        k = min(args.eval_every - (e % args.eval_every),
                args.epochs - e)
        # sub-chunk the dispatches: one overlong fused Execute can
        # crash the tunneled TPU worker. The deadline is re-checked per
        # sub-chunk so a window never commits to more than --fused
        # epochs past its budget — the outer queue timeout
        # (tpu_window.py) SIGKILLs, and everything since the last
        # checkpoint would be lost
        losses = None
        done_k = 0
        while done_k < k:
            kk = min(args.fused, k - done_k)
            losses = t.train_epochs(e + done_k, kk)
            done_k += kk
            if deadline and time.time() > deadline:
                break
        e += done_k
        rec = {"epoch": e - 1, "loss": round(float(losses[-1]), 5)}
        if e % args.eval_every == 0 or e == args.epochs:
            rec["val"] = round(t.evaluate(g, "val_mask"), 5)
            rec["test"] = round(t.evaluate(g, "test_mask"), 5)
        history.append(rec)
        hist_f.write(json.dumps(rec) + "\n")
        hist_f.flush()
        if lhist_path:
            with open(lhist_path, "a") as lf:
                lf.write(json.dumps(rec) + "\n")
        save_checkpoint(sdir, t.state, e - 1)
        if light:
            from pipegcn_tpu.utils.checkpoint import save_pytree

            os.makedirs(args.light_dir, exist_ok=True)
            # replica 0 only: every part's params/opt/norm copy is
            # identical by the psum'd update, so committing all P is
            # pure repo bloat
            save_pytree(
                light,
                jax.tree_util.tree_map(
                    lambda v: np.asarray(v[0]),
                    {k: t.state[k] for k in ("params", "opt", "norm")}),
                extra={"__epoch__": np.asarray(e - 1, np.int64)})
        # deadline-after-checkpoint: handled by the top-of-loop check
        # (e == args.epochs instead exits to the completion return)
    hist_f.close()
    print(f"# [{leg}] complete: {history[-1]}", flush=True)
    return True, history


def write_report(args, results, backend):
    lines = [
        "# Full-density convergence study "
        "(avg degree ~492, 3000 epochs)",
        "",
        f"Graph: {args.nodes} nodes / avg degree {args.degree} "
        f"(~{args.nodes * args.degree // 2} undirected edges), "
        f"{args.feat} features, {args.classes} classes, noise "
        f"{args.noise}, label noise {args.label_noise}, homophily "
        f"{args.homophily}. Model: "
        f"{args.layers}x{args.hidden} GraphSAGE + use_pp, bf16, "
        f"P={args.parts} (emulate_parts on {backend}). The reference's "
        "comparison "
        "(README.md:91-99) at the density its prior studies lacked.",
        "",
        "| leg | final loss | best val | test @ best val | "
        "final test |",
        "|---|---|---|---|---|",
    ]
    for leg in LEGS:
        h = results.get(leg)
        if not h:
            continue
        evals = [r for r in h if "val" in r]
        best = max(evals, key=lambda r: r["val"]) if evals else {}
        lines.append(
            f"| {leg} | {h[-1]['loss']:.4f} | "
            f"{best.get('val', float('nan')):.4f} | "
            f"{best.get('test', float('nan')):.4f} | "
            f"{evals[-1]['test'] if evals else float('nan'):.4f} |")
    # reference-format result lines (train.py:377-400 analogue)
    lines.append("")
    for leg in LEGS:
        h = results.get(leg)
        evals = [r for r in h if "val" in r] if h else []
        if evals:
            best = max(evals, key=lambda r: r["val"])
            lines.append(
                f"Final Test Result ({leg}) | Accuracy "
                f"{100 * best['test']:.2f}%")
    van = results.get("vanilla")
    pip = results.get("pipelined")
    if van and pip:
        bv = max((r for r in van if "val" in r),
                 key=lambda r: r["val"])["test"]
        bp = max((r for r in pip if "val" in r),
                 key=lambda r: r["val"])["test"]
        lines += [
            "",
            f"Pipelined - vanilla test delta: {100 * (bp - bv):+.2f} pp "
            "(reference reports parity within noise on Reddit, "
            "README.md:91-99).",
        ]
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        f.write("\n".join(lines) + "\n")
    print("\n".join(lines))


def graph_ident(args):
    """Every arg that shapes the generated graph or the build — cache
    and leg-state keys are only paths, so an edited config must be
    caught by comparing this, not silently trained across tasks."""
    return {k: getattr(args, k) for k in
            ("nodes", "degree", "feat", "classes", "noise",
             "label_noise", "homophily", "parts", "cluster_size")}


def check_task_identity(args):
    """Refuse to resume LEG state (checkpoints + history) recorded for
    a different task or training config — unlike the derived artifact
    cache (rebuilt in place on mismatch), thousands of trained epochs
    must never be silently mixed across tasks or auto-deleted. The
    stamp lives in BOTH --state-dir and --light-dir: after a workspace
    wipe only the light dir survives, and a light resume must be
    guarded just as strictly."""
    ident = {**graph_ident(args), "hidden": args.hidden,
             "layers": args.layers, "lr": args.lr}
    dirs = [args.state_dir] + ([args.light_dir] if args.light_dir
                               else [])
    for d in dirs:
        path = os.path.join(d, "task.json")
        if os.path.exists(path):
            with open(path) as f:
                prev = json.load(f)
            if prev != ident:
                raise RuntimeError(
                    f"{d} holds legs trained on {prev}, not the "
                    f"requested {ident}; point the study at a fresh "
                    "directory (or delete it) to start over")
        else:
            os.makedirs(d, exist_ok=True)
            with open(path, "w") as f:
                json.dump(ident, f)


def build_or_load_artifacts(args):
    """Generate (or load cached) full graph + ShardedGraph build.

    At 8k nodes the rebuild is seconds and caching is off by default;
    at full Reddit shape (232,965 nodes / ~114M directed edges) the
    SBM generation + partition + halo build is tens of host-minutes,
    so --cache-artifacts persists both (the ShardedGraph via its own
    artifact format, the eval graph as an npz) and per-window resumes
    only pay the disk read. ShardedGraph.load also re-arms the derived
    kernel-table disk cache (cache_dir), so block-table builds are
    paid once per cache too.
    """
    from pipegcn_tpu.graph import Graph, synthetic_graph
    from pipegcn_tpu.partition import ShardedGraph, partition_graph

    cache = os.path.join(args.state_dir, "artifacts") \
        if args.cache_artifacts else None
    gpath = os.path.join(cache, "eval_graph.npz") if cache else None
    ident = graph_ident(args)
    cfg_path = os.path.join(cache, "config.json") if cache else None
    if cache and ShardedGraph.exists(cache) and os.path.exists(gpath):
        t0 = time.time()
        cached_ident = None
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                cached_ident = json.load(f)
        if cached_ident != ident:
            # derived cache for a different config: rebuild in place
            # (an unattended queue must not wedge on a config edit;
            # cross-task LEG state is guarded separately by task.json,
            # which refuses rather than deletes)
            import shutil

            print(f"# cached artifacts at {cache} were built for "
                  f"{cached_ident}, not {ident} — rebuilding",
                  flush=True)
            shutil.rmtree(cache)
            return build_or_load_artifacts(args)
        sg = ShardedGraph.load(cache)
        with np.load(gpath) as z:
            g = Graph(num_nodes=int(z["num_nodes"]), src=z["src"],
                      dst=z["dst"],
                      ndata={k[3:]: z[k] for k in z.files
                             if k.startswith("nd_")})
        print(f"# loaded cached artifacts ({time.time() - t0:.1f}s)",
              flush=True)
        return g, sg

    t0 = time.time()
    g = synthetic_graph(
        num_nodes=args.nodes, avg_degree=args.degree, n_feat=args.feat,
        n_class=args.classes, homophily=args.homophily,
        noise=args.noise, label_noise=args.label_noise,
        train_frac=0.66, val_frac=0.1, seed=0)
    parts = partition_graph(g, args.parts, seed=0)
    cluster = None
    if args.cluster_size:
        from pipegcn_tpu.partition import locality_clusters

        cluster = locality_clusters(g, target_size=args.cluster_size,
                                    seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=args.parts,
                            cluster=cluster)
    print(f"# built artifacts ({time.time() - t0:.1f}s)", flush=True)
    if cache:
        # eval_graph.npz FIRST (atomically, tmp + rename), THEN
        # sg.save — whose manifest.json is written last and is the
        # existence guard. A kill anywhere in this sequence leaves
        # either no manifest (clean rebuild next window) or a fully
        # valid cache; never a truncated npz behind a valid manifest.
        os.makedirs(cache, exist_ok=True)
        tmp = gpath + ".tmp.npz"
        np.savez(tmp, num_nodes=np.int64(g.num_nodes), src=g.src,
                 dst=g.dst,
                 **{f"nd_{k}": v for k, v in g.ndata.items()})
        os.replace(tmp, gpath)
        with open(cfg_path, "w") as f:
            json.dump(ident, f)
        sg.save(cache)
        sg.cache_dir = cache
    return g, sg


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=8000)
    ap.add_argument("--degree", type=int, default=492)
    ap.add_argument("--feat", type=int, default=128)
    ap.add_argument("--classes", type=int, default=41)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--epochs", type=int, default=3000)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--noise", type=float, default=4.0)
    ap.add_argument("--label-noise", type=float, default=0.0,
                    help="fraction of labels flipped to a random other "
                         "class (accuracy ceiling ~1-p; full-density "
                         "studies need it — degree-492 aggregation "
                         "saturates clean SBM tasks at 100%)")
    ap.add_argument("--homophily", type=float, default=0.7)
    ap.add_argument("--fused", type=int, default=25,
                    help="epochs per fused device dispatch (long "
                         "dispatches have crashed the tunneled TPU "
                         "worker; eval intervals are sub-chunked to "
                         "this)")
    ap.add_argument("--eval-every", type=int, default=50)
    ap.add_argument("--time-budget", type=float, default=0,
                    help="seconds; stop cleanly (resumable) when hit")
    ap.add_argument("--parts", type=int, default=4,
                    help="partitions (emulated on one device); the "
                         "reference's Reddit headline uses 2 "
                         "(reference scripts/reddit.sh)")
    ap.add_argument("--cluster-size", type=int, default=0,
                    help="locality-cluster reorder target for the "
                         "block kernel (0 = none; full-scale runs "
                         "want the bench's 1024)")
    ap.add_argument("--cache-artifacts", action="store_true",
                    help="cache the graph + ShardedGraph build under "
                         "--state-dir so per-window resumes skip the "
                         "O(E) host rebuild (essential at full "
                         "Reddit scale)")
    ap.add_argument("--spmm-impl", default="xla",
                    help="aggregation kernel (bench.py surface); the "
                         "full-scale run needs 'auto' — the raw xla "
                         "gather path cannot hold [57M, 602] "
                         "activations on one chip")
    ap.add_argument("--spmm-chunk", type=int, default=0,
                    help="bound raw-path gathered messages to [chunk, "
                         "F] per pass (0 = unchunked; bench.py uses "
                         "2097152 at Reddit shape)")
    ap.add_argument("--block-group", type=int, default=1,
                    help="union-gather group size for the block "
                         "kernel's dense path")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--light-dir", default="",
                    help="git-TRACKED dir for compact per-leg "
                         "checkpoints (params+opt+norm, ~MBs) + "
                         "history mirrors; survives the workspace "
                         "wipe between driver rounds, unlike the "
                         "gitignored --state-dir. Resume from it "
                         "resets the staleness carries (~1-epoch "
                         "re-warm)")
    ap.add_argument("--state-dir",
                    default="results/convergence_state")
    ap.add_argument("--out",
                    default="results/convergence_fulldensity.md")
    args = ap.parse_args()

    # probe-with-fallback BEFORE any jax device work: with the tunnel
    # down an unprobed init hangs the interpreter (bench.py's solved
    # hazard; the site hook pins JAX_PLATFORMS, so CPU must be chosen
    # via jax.config.update after import)
    from bench import init_backend

    backend = init_backend(1, 60.0, args.cpu)
    import jax

    if backend.startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")

    from pipegcn_tpu.models import ModelConfig

    check_task_identity(args)
    deadline = time.time() + args.time_budget if args.time_budget else 0
    g, sg = build_or_load_artifacts(args)
    print(f"# graph: {g.num_nodes} nodes / {g.num_edges} directed "
          f"edges; halo {sg.halo_size} rows/device "
          f"({sg.halo_size / sg.n_max:.1%} of inner)", flush=True)
    cfg = ModelConfig(
        layer_sizes=(sg.n_feat,) + (args.hidden,) * (args.layers - 1)
        + (sg.n_class,),
        use_pp=True, norm="layer", dropout=0.5,
        train_size=sg.n_train_global, dtype="bfloat16",
        spmm_impl=args.spmm_impl,
        spmm_chunk=args.spmm_chunk or None,
        block_group=args.block_group)

    results = {}
    all_done = True
    for leg in LEGS:
        done, history = run_leg(leg, sg, g, cfg, args, deadline)
        results[leg] = history
        all_done = all_done and done
        if deadline and time.time() > deadline:
            break
    if all_done and all(results.get(l) for l in LEGS):
        write_report(args, results, jax.default_backend())
    else:
        print("# study incomplete — rerun to resume", flush=True)
        # nonzero exit so queue runners (scripts/tpu_window.py) retry
        # at the next window instead of marking the step done
        sys.exit(2)


if __name__ == "__main__":
    main()
