#!/usr/bin/env python
"""papers100M-class host pipeline demonstration at reduced scale.

The reference documents ogbn-papers100M (111M nodes, 1.6B directed raw
edges) as requiring a >=120 GB-RAM host (reference README.md:29-30,
helper/utils.py:17-30). This script demonstrates the RAM-bounded
replacements end to end on a papers100M-SHAPED synthetic graph:

  1. writes the OGB plain raw layout to disk (edge.npy [E,2] int64,
     node-feat.npy, node-label.npy, split/time/*.csv.gz) — so the real
     `load_ogb` code path runs, not a shortcut;
  2. `load_ogb(mmap=True)`: one-time chunked finalized-edge cache
     (mirror + self-loop normalize + in-degrees, int32 memmaps);
  3. `partition_graph` + `ShardedGraph.build_chunked` (bit-identical
     to build(), O(chunk) edge scratch) at --parts partitions;
  4. saves the artifact and reports peak RSS at each stage;
  5. optionally (--dryrun) jits ONE pipelined training step over a
     --parts-device virtual CPU mesh on the artifact.

Default scale: 1/10 papers100M — 11.1M nodes, 160M directed raw edges
(320M + self loops finalized), 128 features. Peak-RSS target: a small
multiple of the artifact itself (the O(E) scratch of the plain build
would add ~18 GB at this scale; the chunked build keeps it under
~1.5 GB).

Writes results/papers100m_scale.md.
"""

import argparse
import json
import os
import resource
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def gen_raw_layout(base: str, n_nodes: int, n_edges: int, n_feat: int,
                   n_class: int, chunk: int = 1 << 24) -> None:
    """Write the OGB plain raw layout with chunked generation (the
    generator itself must not blow RAM at 160M edges). Community
    structure comes from a power-law-ish src skew + locality windows so
    partitioning finds real cuts."""
    import gzip

    import numpy as np

    raw = os.path.join(base, "raw")
    os.makedirs(raw, exist_ok=True)
    rng = np.random.default_rng(0)

    edges = np.lib.format.open_memmap(
        os.path.join(raw, "edge.npy"), mode="w+", dtype=np.int64,
        shape=(n_edges, 2))
    for i0 in range(0, n_edges, chunk):
        m = min(chunk, n_edges - i0)
        # sources skewed to low ids (hub papers); dsts local windows
        # around the source (citation locality) with occasional jumps
        src = (rng.pareto(1.5, m) * (n_nodes / 50)).astype(np.int64) \
            % n_nodes
        jump = rng.random(m) < 0.1
        window = rng.integers(-500_000, 500_000, m)
        dst = np.where(jump, rng.integers(0, n_nodes, m),
                       (src + window) % n_nodes)
        edges[i0:i0 + m, 0] = src
        edges[i0:i0 + m, 1] = dst
    edges.flush()
    del edges

    feat = np.lib.format.open_memmap(
        os.path.join(raw, "node-feat.npy"), mode="w+", dtype=np.float32,
        shape=(n_nodes, n_feat))
    node_chunk = max(1, (1 << 26) // n_feat)
    for i0 in range(0, n_nodes, node_chunk):
        m = min(node_chunk, n_nodes - i0)
        feat[i0:i0 + m] = rng.standard_normal((m, n_feat),
                                              dtype=np.float32)
    feat.flush()
    del feat

    label = rng.integers(0, n_class, n_nodes).astype(np.float64)
    label[rng.random(n_nodes) < 0.5] = np.nan  # most papers unlabeled
    np.save(os.path.join(raw, "node-label.npy"), label)

    sdir = os.path.join(base, "split", "time")
    os.makedirs(sdir, exist_ok=True)
    labeled = np.nonzero(~np.isnan(label))[0]
    rng.shuffle(labeled)
    k = labeled.size
    for part, ids in (("train", labeled[:int(k * 0.8)]),
                      ("valid", labeled[int(k * 0.8):int(k * 0.9)]),
                      ("test", labeled[int(k * 0.9):])):
        with gzip.open(os.path.join(sdir, part + ".csv.gz"), "wt") as f:
            f.write("\n".join(map(str, ids.tolist())) + "\n")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=11_100_000)
    ap.add_argument("--edges", type=int, default=160_000_000,
                    help="directed raw edges before mirroring")
    ap.add_argument("--feat", type=int, default=128)
    ap.add_argument("--classes", type=int, default=172)
    ap.add_argument("--parts", type=int, default=64)
    ap.add_argument("--root", default=os.path.join(REPO, "partitions",
                                                   "papers_scale_data"))
    ap.add_argument("--out", default=os.path.join(REPO, "partitions",
                                                  "papers_scale"))
    ap.add_argument("--dryrun", action="store_true",
                    help="also run one pipelined step on a --parts-"
                         "device virtual CPU mesh")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from pipegcn_tpu.graph.datasets import load_ogb
    from pipegcn_tpu.partition import ShardedGraph, partition_graph

    stages = {}
    name = "ogbn-paperscale"
    base = os.path.join(args.root, name.replace("-", "_"))
    t0 = time.time()
    if not os.path.exists(os.path.join(base, "raw", "edge.npy")):
        gen_raw_layout(base, args.nodes, args.edges, args.feat,
                       args.classes)
    stages["gen"] = {"s": round(time.time() - t0, 1),
                     "peak_rss_gb": round(rss_gb(), 2)}
    print(f"# raw layout ready ({stages['gen']})", file=sys.stderr)

    t0 = time.time()
    g = load_ogb(name, args.root, mmap=True)
    stages["load"] = {"s": round(time.time() - t0, 1),
                      "peak_rss_gb": round(rss_gb(), 2)}
    print(f"# loaded: {g.num_nodes} nodes / {g.num_edges} finalized "
          f"edges ({stages['load']})", file=sys.stderr)

    t0 = time.time()
    # finalized edges are already mirrored: symmetric=True skips the
    # doubling mirror (the old scipy path's ~55 GB 1/10-scale peak)
    parts = partition_graph(g, args.parts, method="metis", obj="vol",
                            seed=0, symmetric=True)
    stages["partition"] = {"s": round(time.time() - t0, 1),
                           "peak_rss_gb": round(rss_gb(), 2)}
    print(f"# partitioned ({stages['partition']})", file=sys.stderr)

    t0 = time.time()
    sg = ShardedGraph.build_chunked(g, parts, n_parts=args.parts)
    stages["build_chunked"] = {"s": round(time.time() - t0, 1),
                               "peak_rss_gb": round(rss_gb(), 2)}
    print(f"# built: n_max={sg.n_max} e_max={sg.e_max} "
          f"halo={sg.halo_size} ({stages['build_chunked']})",
          file=sys.stderr)

    t0 = time.time()
    sg.save(args.out)
    stages["save"] = {"s": round(time.time() - t0, 1),
                      "peak_rss_gb": round(rss_gb(), 2)}

    result = {
        "nodes": g.num_nodes,
        "finalized_edges": g.num_edges,
        "parts": args.parts,
        "n_max": sg.n_max,
        "e_max": sg.e_max,
        "stages": stages,
    }
    print(json.dumps(result))
    md = [
        "# papers100M-scale host pipeline (1/10 scale)",
        "",
        f"Synthetic papers100M-shaped graph: {g.num_nodes:,} nodes, "
        f"{args.edges:,} directed raw edges -> {g.num_edges:,} finalized "
        f"(mirrored + self loops), {args.feat} features, "
        f"{args.parts} partitions.",
        "",
        "Reference analogue: >=120 GB-RAM host requirement for the real "
        "dataset (reference README.md:29-30). This pipeline memmaps the "
        "raw arrays, builds a finalized-edge cache once (chunked), and "
        "shards with build_chunked (bit-identical to build, O(chunk) "
        "edge scratch).",
        "",
        "| stage | wall (s) | cumulative peak RSS (GB) |",
        "|---|---|---|",
    ]
    for k, v in stages.items():
        md.append(f"| {k} | {v['s']} | {v['peak_rss_gb']} |")
    md += [
        "",
        "The cumulative-peak column (ru_maxrss) shows the RAM story:",
        "the memmap loader + finalized-edge cache stay chunk-bounded;",
        "the remaining peak belongs to the PARTITIONER (multilevel on",
        "the full finalized edge set) — build_chunked and the",
        "compressed save never exceed its high-water mark. The",
        "partitioner is the one stage that scales with E in RAM,",
        "matching where the reference spends its >=120 GB host",
        "(reference README.md:29-30). Round-4 reductions: chunked",
        "counting-sort CSR ingestion (no scipy COO doubling), a",
        "zero-copy implicit-weight level-0 view, int32 coarse weights,",
        "and level-by-level frees during uncoarsening took the",
        "1/10-scale partition peak from 54.9 GB to the table's value.",
        "",
    ]
    # dryrun results are produced rarely (--dryrun) and persisted
    # separately so this wholesale rewrite never clobbers them
    dj = os.path.join(REPO, "results", "papers_dryrun.json")
    if os.path.exists(dj):
        with open(dj) as f:
            md += [
                "64-virtual-device dryrun (structure-identical, reduced "
                "size for the 64-way XLA:CPU compile arena): one "
                "pipelined bucket-kernel training step jitted over the "
                "virtual mesh —",
                "`" + f.read().strip() + "`",
                "",
            ]
    with open(os.path.join(REPO, "results", "papers100m_scale.md"),
              "w") as f:
        f.write("\n".join(md))
    print("# wrote results/papers100m_scale.md", file=sys.stderr)

    if args.dryrun:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.parts}"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
        from pipegcn_tpu.models import ModelConfig
        from pipegcn_tpu.parallel import Trainer, TrainConfig

        cfg = ModelConfig(
            layer_sizes=(sg.n_feat, 128, 128, sg.n_class), n_linear=0,
            norm="layer", dropout=0.5, train_size=sg.n_train_global,
            spmm_impl="bucket", dtype="bfloat16",
        )
        t0 = time.time()
        tr = Trainer(sg, cfg, TrainConfig(lr=0.01, enable_pipeline=True,
                                          eval=False))
        loss = tr.train_epoch(0)
        rec = {"dryrun_devices": args.parts,
               "first_step_s": round(time.time() - t0, 1),
               "loss": float(loss),
               "peak_rss_gb": round(rss_gb(), 2)}
        with open(os.path.join(REPO, "results",
                               "papers_dryrun.json"), "w") as f:
            json.dump(rec, f)
        print(json.dumps(rec))


if __name__ == "__main__":
    main()
