#!/usr/bin/env python
"""40-partition virtual-mesh run of the reddit_multi_node.sh shape.

The reference demonstrates 40 partitions over 4 nodes x 10 GPUs
(reference scripts/reddit_multi_node.sh, main.py:52-53 mp.spawn per
node). This script reproduces that SHAPE on CPU virtual devices, two
ways:

  default    one SPMD process over a 40-device virtual mesh running the
             reddit_multi_node.sh model config (4 layers x 256 hidden,
             602 feats / 41 classes, inductive, use_pp, pipelined)
  --multihost  4 OS processes x 10 virtual devices each — the literal
             4-node launch path: jax.distributed.initialize rendezvous,
             node-rank 0 partitions, peers poll the artifact
             (pipegcn_tpu/cli/main.py:60-144)

Real datasets aren't downloadable here, so the graph is synthetic with
Reddit-like degree structure at a reduced node count (full Reddit on a
1-core CPU host would be hours per epoch; the mesh/collective program
is identical at any size — shapes only scale the arithmetic).

Writes results/multi_node_40part.md and MULTICHIP_40part.json.
"""

import argparse
import json
import os
import re
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# per-epoch eval lines: "Epoch N | Validation Accuracy X% | ..." in
# transductive reference format, "Epoch N | Accuracy X%" in inductive
# reference format (trainer.py _harvest_eval); "Test Accuracy" must
# not match
_ACC_RE = r"\| (?:Validation )?Accuracy ([0-9.]+)%"

# reddit_multi_node.sh flags, minus dataset size and node layout
MODEL_FLAGS = [
    "--dropout", "0.5", "--lr", "0.01", "--model", "graphsage",
    "--n-layers", "4", "--n-hidden", "256", "--log-every", "5",
    "--inductive", "--enable-pipeline", "--fix-seed", "--use-pp",
]


def run_single(dataset: str, epochs: int, part_dir: str,
               production_kernel: bool = False) -> dict:
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=40",
        "PYTHONPATH": REPO,
    }
    cmd = [sys.executable, os.path.join(REPO, "main.py"),
           "--dataset", dataset, "--n-partitions", "40",
           # all 40 parts on this one process: no jax.distributed
           # rendezvous (the 4x10 leg exercises that path)
           "--parts-per-node", "40",
           "--n-epochs", str(epochs), "--partition-dir", part_dir,
           *MODEL_FLAGS,
           # argparse keeps the last occurrence: make sure at least two
           # eval lines land inside the run, whatever the epoch count
           "--log-every", str(max(1, epochs // 2))]
    if production_kernel:
        # the benchmark-headline kernel stack at the multi-node shape:
        # hybrid block kernel in the union-gather layout with fp8
        # remainder transport (a low nnz threshold gives the small
        # per-shard graphs real dense tiles, like the dryrun gate)
        cmd += ["--spmm-impl", "block", "--block-group", "4",
                "--rem-dtype", "float8", "--block-nnz", "4"]
    t0 = time.time()
    r = subprocess.run(cmd, capture_output=True, text=True, env=env,
                       cwd=REPO)
    wall = time.time() - t0
    out = r.stdout + r.stderr
    if r.returncode != 0:
        print(out[-4000:], file=sys.stderr)
        raise SystemExit(f"single-process 40-part run failed rc={r.returncode}")
    accs = [float(m) for m in re.findall(_ACC_RE, out)]
    test = re.search(r"Test Result \| Accuracy ([0-9.]+)%", out)
    times = [float(m) for m in re.findall(r"Time\(s\) ([0-9.]+)", out)]
    return {
        "mode": ("single-process-production-kernel" if production_kernel
                 else "single-process"),
        "devices": 40,
        "dataset": dataset,
        "epochs": epochs,
        "wall_s": round(wall, 1),
        "epoch_s": round(times[-1], 4) if times else None,
        "val_acc_first": accs[0] if accs else None,
        "val_acc_last": accs[-1] if accs else None,
        "test_acc": float(test.group(1)) if test else None,
    }


def run_multihost(dataset: str, epochs: int, part_dir: str) -> dict:
    import shutil

    # always partition fresh: with a pre-cached artifact all 4 ranks
    # (time-sharing one core) reach their first collective execute in
    # near-lockstep after minutes of serialized compile, and the gloo
    # context rendezvous (hard 30s, not configurable from jax) times
    # out; the rank-0-partitions / peers-poll stagger of a cold start
    # reliably spreads the arrivals (and exercises the real multi-node
    # first-run path, reference main.py:32-40)
    # resolve against REPO like the child processes (cwd=REPO) do —
    # an invoker-cwd-relative rmtree would miss the real artifact
    part_dir = part_dir if os.path.isabs(part_dir) \
        else os.path.join(REPO, part_dir)
    shutil.rmtree(part_dir, ignore_errors=True)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    import tempfile

    procs = []
    logs = []
    t0 = time.time()
    for rank in range(4):
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=10",
            "PYTHONPATH": REPO,
        }
        # child stdout goes to a file, not a pipe: ranks are SPMD-
        # coupled, and a later rank blocking on a full unread pipe
        # would stall the collectives every rank is waiting in
        log = tempfile.NamedTemporaryFile("w+", suffix=f".rank{rank}",
                                          delete=False)
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(REPO, "main.py"),
             "--dataset", dataset, "--n-partitions", "40",
             "--parts-per-node", "10", "--node-rank", str(rank),
             "--master-addr", "127.0.0.1", "--port", str(port),
             "--n-epochs", str(epochs), "--partition-dir", part_dir,
             *MODEL_FLAGS,
             # no eval: with 4 processes time-sharing ONE core, the
             # evaluator's separately-compiled program gives each rank
             # a different arrival time at its first gloo collective
             # and the 30s context-init rendezvous (not configurable
             # from jax) times out. The TRAINING collectives are fine —
             # every rank compiles the same step program back-to-back.
             # Cross-rank agreement is asserted on the loss instead.
             "--no-eval"],
            stdout=log, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO))
    outs = []
    for p, log in zip(procs, logs):
        p.wait(timeout=3600)
        log.flush()
        with open(log.name) as f:
            outs.append(f.read())
        os.unlink(log.name)
    wall = time.time() - t0
    for rank, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            print(out[-4000:], file=sys.stderr)
            raise SystemExit(f"multihost rank {rank} failed "
                             f"rc={p.returncode}")
    # every process must report identical losses (one SPMD job); the
    # reference log line prints every 10 epochs under --fix-seed, so
    # epochs must be >= 10 (enforced in main())
    losses = [re.findall(r"Loss ([0-9.]+)", o) for o in outs]
    missing = [r for r, ls in enumerate(losses) if not ls]
    assert not missing, f"ranks {missing} logged no Loss lines"
    finals = {ls[-1] for ls in losses}
    assert len(finals) == 1, f"ranks disagree on final loss: {finals}"
    return {
        "mode": "multihost-4x10",
        "devices": 40,
        "processes": 4,
        "dataset": dataset,
        "epochs": epochs,
        "wall_s": round(wall, 1),
        "loss_first": float(losses[0][0]),
        "loss_last": float(losses[0][-1]),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=60000,
                    help="synthetic node count (40 shards of nodes/40)")
    ap.add_argument("--degree", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--mh-nodes", type=int, default=3000,
                    help="node count for the 4-process multihost leg")
    ap.add_argument("--mh-epochs", type=int, default=10,
                    help="must be >= 10: the multihost leg asserts on "
                         "the reference loss line, printed every 10 "
                         "epochs")
    ap.add_argument("--skip-multihost", action="store_true")
    ap.add_argument("--skip-single", action="store_true",
                    help="keep the single-process result already in "
                         "MULTICHIP_40part.json, run only multihost")
    ap.add_argument("--production-kernel", action="store_true",
                    help="run the single-process leg with the headline "
                         "kernel stack: block + union-gather group 4 + "
                         "fp8 remainder transport")
    ap.add_argument("--part-dir", default="partitions/multi40")
    args = ap.parse_args()
    if not args.skip_multihost and args.mh_epochs < 10:
        ap.error("--mh-epochs must be >= 10 (loss line cadence)")

    # merge-by-mode against the existing file so a --skip-* rerun of
    # one leg never discards the other leg's (expensive) result
    by_mode = {}
    json_path = os.path.join(REPO, "MULTICHIP_40part.json")
    if os.path.exists(json_path):
        with open(json_path) as f:
            by_mode = {r["mode"]: r for r in json.load(f)["runs"]}

    def flush():
        with open(json_path, "w") as f:
            json.dump({"runs": list(by_mode.values())}, f, indent=1)

    dataset = f"synthetic:{args.nodes}:{args.degree}:602:41"
    if not args.skip_single:
        r = run_single(dataset, args.epochs, args.part_dir,
                       production_kernel=args.production_kernel)
        by_mode[r["mode"]] = r
        print(json.dumps(r))
        flush()
    if not args.skip_multihost:
        mh_dataset = f"synthetic:{args.mh_nodes}:{args.degree}:602:41"
        r = run_multihost(mh_dataset, args.mh_epochs,
                          args.part_dir + "-mh")
        by_mode[r["mode"]] = r
        print(json.dumps(r))
        flush()
    results = list(by_mode.values())
    md = [
        "# 40-partition runs (reddit_multi_node.sh shape)",
        "",
        "Reference analogue: 40 partitions over 4 nodes x 10 GPUs",
        "(reference scripts/reddit_multi_node.sh). Same model config",
        "(4x256 GraphSAGE, inductive, use_pp, pipelined), synthetic",
        "Reddit-like graph at reduced node count (1-core CPU host;",
        "the SPMD program/collective structure is size-independent).",
        "",
        "| mode | devices | graph | epochs | wall (s) | progress |",
        "|---|---|---|---|---|---|",
    ]
    for r in results:
        if r.get("test_acc") is not None:
            prog = f"test acc {r['test_acc']}%"
        elif r.get("loss_last") is not None:
            prog = (f"loss {r['loss_first']} -> {r['loss_last']} "
                    "(all 4 ranks identical)")
        else:
            prog = f"{r.get('val_acc_first')}% -> {r.get('val_acc_last')}%"
        md.append(
            f"| {r['mode']} | {r['devices']} | {r['dataset']} "
            f"| {r['epochs']} | {r['wall_s']} | {prog} |")
    md.append("")
    with open(os.path.join(REPO, "results", "multi_node_40part.md"),
              "w") as f:
        f.write("\n".join(md))
    print("wrote results/multi_node_40part.md")


if __name__ == "__main__":
    main()
