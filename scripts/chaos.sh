#!/usr/bin/env bash
# Chaos lane: every fault-injection / recovery test (pytest marker
# `faults`), INCLUDING the multi-process drills tier-1 deselects (they
# are additionally marked `slow`): the two-coordinated-process kill
# drill (kill -9 one rank -> the survivor exits 75 with a loadable
# crash checkpoint, then a two-process --resume completes) and the
# cross-rank consensus drill (a rank-targeted nan trips one rank's
# sentinel, the whole pod rolls back in lockstep, post-recovery digests
# agree). See docs/RESILIENCE.md.
#
# A hard wall-clock cap (CHAOS_TIMEOUT_S, default 1800 s) guarantees a
# wedged drill kills the lane instead of the CI runner: hangs are the
# failure mode under test, so the harness itself must never hang.
set -euo pipefail
cd "$(dirname "$0")/.."
timeout -k 30 "${CHAOS_TIMEOUT_S:-1800}" \
    env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m faults \
    -p no:cacheprovider "$@"

# Numerics lane (docs/RESILIENCE.md "Numerics"): NaN tripwire
# provenance, loss-scale backoff/skip/regrow, kernel fallback ladder,
# and the products-shape NaN regression — tier-1-safe but run
# standalone here so a numerics regression fails the chaos lane even
# when someone trims the tier-1 selection.
timeout -k 30 "${CHAOS_TIMEOUT_S:-1800}" \
    env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m numerics \
    -p no:cacheprovider "$@"

# Elastic lane (docs/RESILIENCE.md "Elastic membership"): the
# supervisor unit suite plus the two drills run standalone — the
# crash-loop drill (kill@5 re-fires every generation; the supervisor
# must stop at --max-restarts leaving a clean resumable checkpoint)
# and the redistribution drill (kill -9 one of two supervised ranks ->
# the survivor is relaunched owning BOTH partitions from the last good
# checkpoint and completes every nominal epoch). Already inside the
# faults marker above; re-run -k elastic so an elastic regression is
# named even when the broad lane is trimmed.
timeout -k 30 "${CHAOS_TIMEOUT_S:-1800}" \
    env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m faults \
    -k "elastic" -p no:cacheprovider "$@"

# Serving lane (docs/SERVING.md): the serve kill drill — a live
# `python -m pipegcn_tpu.cli.serve` process is SIGTERM'd mid-load and
# must drain every accepted query and land a hard-flushed final
# `serving` record before exiting 0 — plus the tier-1-safe serving
# tests (padding-ladder no-recompile, incremental-freshness
# bit-identity, cache invalidation) run standalone so a serving
# regression fails the chaos lane even when someone trims the tier-1
# selection.
timeout -k 30 "${CHAOS_TIMEOUT_S:-1800}" \
    env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m serving \
    -p no:cacheprovider "$@"

# Stream lane (docs/STREAMING.md): the patched-vs-from-scratch-rebuild
# bit-identity oracle (CSR slabs, send-lists, halo slots, eval logits,
# on the xla AND incremental-bucket table paths), slack exhaustion ->
# loud re-pad, the zero-recompile pin, pipelined carry-row flush, the
# serving topology-delta freshness oracle (incremental == full
# boundary exchange bitwise), and CRC tamper rejection — tier-1-safe
# but run standalone so a streaming regression fails the chaos lane
# even when someone trims the tier-1 selection.
timeout -k 30 "${CHAOS_TIMEOUT_S:-1800}" \
    env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m stream \
    -p no:cacheprovider "$@"

# Fleet lane (docs/SERVING.md "Fleet"): the replica-kill drill — a
# two-replica `python -m pipegcn_tpu.cli.fleet` run SIGKILLs one
# replica mid-load (fault plan replica-kill@W:mK); the router must
# route every in-flight and subsequent batch to the survivor, lose
# zero accepted tickets (submitted == served + shed, all sheds
# explicit), land `fleet` fault + recovery records, and rejoin the
# relaunched replica — plus the tier-1-safe fleet unit tests (router
# failover/backoff, consistent-hash remap, load shedding, hot-swap
# walk-back). Re-run under the faults marker filtered to fleet so a
# fleet regression is named even when the broad lane is trimmed.
timeout -k 30 "${CHAOS_TIMEOUT_S:-1800}" \
    env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m "faults or fleet" \
    -k "fleet" -p no:cacheprovider "$@"

# Soak lane (docs/RESILIENCE.md "Storage faults"): the harness unit
# tests (schedule-composition determinism, invariant checkers, the
# full subprocess episode) plus a short fixed-seed real soak —
# 2 seeded episodes through scripts/soak.py, schedules covering
# terminal kills and storage faults, every per-episode invariant
# (checkpoint loadable, ledger monotonic+CRC-clean, metrics coverage
# gap-free, clean resume) checked for real. Summary JSON lands in
# results/soak/ for CI artifact upload. Deterministic: a red lane
# reproduces locally with the same command.
timeout -k 30 "${CHAOS_TIMEOUT_S:-1800}" \
    env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m soak \
    -p no:cacheprovider "$@"
timeout -k 30 "${CHAOS_TIMEOUT_S:-1800}" \
    python scripts/soak.py --seed 0 --episodes 2 --out-dir results/soak

# Forensics lane (docs/OBSERVABILITY.md "Postmortem & flight
# recorder"): breadcrumb ring semantics + bounded memory, the
# dump-on-hard-exit subprocess drill (a blackbox-r<k>.json must
# survive os._exit(75)), rule-engine verdicts per failure class on
# synthetic bundles, the explain CLI on a real CPU-mesh run, the
# supervisor fail-fast gate, and the two-process hang drill
# (hang@E:rN wedges one rank; the survivor's watchdog trips; BOTH
# ranks must leave black-box dumps and `pipegcn-debug explain` must
# return wedged-collective). The drill is marked faults+slow and so
# also rides the broad faults lane; re-run the marker standalone so a
# forensics regression is named even when the broad lane is trimmed.
timeout -k 30 "${CHAOS_TIMEOUT_S:-1800}" \
    env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m forensics \
    -p no:cacheprovider "$@"

# Autoscale lane (docs/SERVING.md "Autoscaling & overload"): the
# closed-loop autoscaling + traffic-realism suite — shaped arrival
# schedules (diurnal / flash-crowd / trace replay, Lewis-Shedler
# thinning, seeded determinism), AutoscalePolicy scale-up/down/
# cooldown/storm-brake transitions under a fake clock, the graceful-
# degradation ladder (brownout before blackout, per-reason shed
# accounting), the net-delay/net-drop/net-partition fault kinds
# through the router retry/backoff path, and spawn/retire consistent-
# hash ring remap. Fake-clock/fake-client based, tier-1-safe; run
# standalone so an autoscaling regression fails the chaos lane even
# when someone trims the tier-1 selection.
timeout -k 30 "${CHAOS_TIMEOUT_S:-1800}" \
    env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m autoscale \
    -p no:cacheprovider "$@"

# Monitor lane (docs/OBSERVABILITY.md "Live monitoring"): the live
# telemetry plane — metrics-stream discovery + tail-follow torn-line
# tolerance, edge-triggered SLO alert fire/dedupe/resolve under a
# fake clock, span lifecycle conservation + Perfetto flow stitching,
# the /metrics scrape-parity drill against a real HTTP server, and
# bench trend regression flags — tier-1-safe but run standalone so a
# telemetry regression fails the chaos lane even when someone trims
# the tier-1 selection.
timeout -k 30 "${CHAOS_TIMEOUT_S:-1800}" \
    env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m live \
    -p no:cacheprovider "$@"

# Trainspan lane (docs/OBSERVABILITY.md "Training traces"): the
# training-path distributed-tracing plane — per-rank span emission
# conservation + comm-tail geometry, tracesync clock-offset recovery
# on planted skew, span-fold overlap agreement with the profiler
# fold, straggler attribution, the straggler-skew alert
# fire/dedupe/resolve under a fake clock, timeline cross-rank flow
# stitching, the report span-overlap fallback, and the zero-recompile
# pin with spans hot. The two-process slow-rank drill (slow-rank@E:rN
# stalls one rank's dispatch; attribution must name it, the alert
# must fire, spans must survive) is marked faults+slow and so also
# rides the broad faults lane; run the marker standalone so a tracing
# regression is named even when the broad lane is trimmed.
timeout -k 30 "${CHAOS_TIMEOUT_S:-1800}" \
    env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m trainspan \
    -p no:cacheprovider "$@"

# Journal lane (docs/STREAMING.md "Durability & replay"): the
# crash-consistent streaming plane — WAL segment rotation/reopen
# round-trip, sealed-segment CRC tamper loudness, torn-tail heal,
# ENOSPC degrade-not-lose pending queue, the kill-mid-stream and
# journal-torn bitwise resume drills (journal replay + plan
# re-derivation must reproduce the doomed run's tables and losses
# bit-for-bit on both SpMM paths), router topo_generation skew
# routing, replica replay-before-readiness, and the two-process
# elastic drill (sigterm@E preempts the streaming child; the
# relaunched generation inherits a partition whose deltas it never
# applied live and must replay the journal to the fleet watermark
# before training, verified against a from-scratch rebuild). The
# elastic drill is marked faults+slow and so also rides the broad
# faults lane; run the marker standalone so a durability regression
# is named even when the broad lane is trimmed.
timeout -k 30 "${CHAOS_TIMEOUT_S:-1800}" \
    env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m journal \
    -p no:cacheprovider "$@"

# Integrity lane (docs/RESILIENCE.md "Silent data corruption"): the
# SDC defense plane — Fletcher digest host/device bit-parity, the
# seeded bitflip-detection matrix (every target class x kernel
# family detected within the configured cadence with the exact
# contracted `integrity` record), the halo wire-checksum lane, the
# quarantine marker round-trip, and `pipegcn-debug scrub` on a real
# run dir. The recurring-SDC two-process quarantine drill is marked
# slow and rides here too; run standalone so an integrity regression
# fails the chaos lane even when someone trims the tier-1 selection.
timeout -k 30 "${CHAOS_TIMEOUT_S:-1800}" \
    env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m integrity \
    -p no:cacheprovider "$@"
