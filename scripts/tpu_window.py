#!/usr/bin/env python
"""Unattended TPU-window harvester.

The axon tunnel is up only sporadically (observed: one ~45-minute
window in >12 h — docs/PERF_NOTES.md). This script polls the backend
in throwaway subprocesses and, the moment a window opens, runs the
staged measurement queue in priority order, logging everything to
results/tpu_window/. Each step is its own subprocess with a timeout;
the tunnel is re-probed between steps so a mid-queue outage stops the
run cleanly instead of hanging it.

Window preflight: each queue entry declares the on-disk artifacts it
needs (4th tuple element, glob patterns relative to the repo). The
moment a window opens the harvester verifies them and SKIPS entries
with missing artifacts — a loud `skipped` record in
results/tpu_window/window.jsonl plus a stderr line — instead of
burning scarce window minutes rebuilding partitions the host could
have built outside the window (two rounds of windows were lost to
exactly that). `--dry-run` prints the preflight verdicts and exits.

Usage: nohup python scripts/tpu_window.py [--poll-s 300] &
       python scripts/tpu_window.py --once   # single probe+queue pass
       python scripts/tpu_window.py --dry-run  # preflight only
"""

import argparse
import glob as _glob
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

LOG_DIR = os.path.join(REPO, "results", "tpu_window")

# heartbeat cadence while a queue entry runs — each beat lands a
# free-form record in results/tpu_window/window.jsonl so a live
# monitor (python -m pipegcn_tpu.cli.monitor results/tpu_window) can
# tell "step grinding, log growing" from "step hung" mid-window
HEARTBEAT_S = 30.0

# the bench-artifact the Reddit-shape probes all assume (built by
# scripts/build_bench_artifact.py or any prior bench run)
_BENCH_PART = "partitions/bench-reddit-1-c2-s1024"
# its degree-bfs-reordered twin (same graph, locality-aware node
# order): scripts/prewarm_tables.py --reorder degree-bfs builds it
# host-side while the tunnel is down
_BENCH_PART_R = "partitions/bench-reddit-1-c2-s1024-rdegree-bfs"

# (name, argv, timeout_s, requires) — priority order: most load-bearing
# first (round-5 order: VERDICT r4 items 1-3 lead). bench.py
# self-degrades on crashes; the microbench/gat steps are best-effort.
# `requires` are glob patterns (repo-relative) the preflight checks.
QUEUE = [
    # VERDICT r5 item 1: attribute the 0.518 s non-SpMM floor (ablate
    # dropout RNG / LayerNorm / fbuf assembly / dispatch amortization)
    ("epoch_anatomy",
     [sys.executable, "scripts/epoch_anatomy.py"],
     2400, [_BENCH_PART]),
    # VERDICT r5 item 3: decompose the remainder's 0.63 s (cast /
    # gather-traffic / ladder-structure / chunking shares + in-session
    # cliff anchor)
    ("rem_probe",
     [sys.executable, "scripts/rem_probe.py"],
     2400, [_BENCH_PART]),
    # round-8: non-SpMM floor levers measured before/after on chip —
    # the floor_levers pass inside bench.py flips one knob at a time
    # (rng-rbg, dropout-bits8, halo-float8, unfused-vs-megastep,
    # prefetch pair) against the same headline and publishes per-lever
    # *_delta_s keys in the BENCH json
    ("floor_levers",
     [sys.executable, "bench.py", "--no-compare", "--force-candidate"],
     3600, [_BENCH_PART]),
    # round-9: the reorder x slab layout levers measured before/after
    # on chip — bench.py's reorder_slab pass times the same shape
    # under none / degree-bfs / degree-bfs+slab and publishes
    # reorder_delta_s / slab_delta_s in the BENCH json. Preflight
    # demands the REORDERED artifact too: the degree-bfs layout is an
    # O(E) host-side build that must never burn window minutes
    # (prewarm_tables.py --reorder degree-bfs leaves it on disk).
    ("reorder_slab",
     [sys.executable, "bench.py", "--no-compare", "--reorder",
      "degree-bfs"],
     3600, [_BENCH_PART, _BENCH_PART_R]),
    # run the SpMM auto-tuner's micro-bench campaign ON CHIP and
    # persist tuning.json into the bench artifact: every later
    # spmm-impl=auto step in this queue (and future rounds reusing the
    # artifact) dispatches from a TPU-measured cost table instead of
    # live-tuning inside its own window budget. --retune evicts any
    # CPU-signed table; cheap (one sampled slice, 13 candidates).
    ("spmm_tune",
     [sys.executable, "scripts/prewarm_tables.py", "--impl", "auto",
      "--retune"],
     1800, [_BENCH_PART]),
    # calibrated-task convergence study (VERDICT item 2) THIRD so a
    # single ~45-min window covers the top-2 probes AND puts real
    # training hours on the accuracy claim (on chip this study is
    # minutes per leg; the budget bounds it per pass). Resumable via
    # per-leg checkpoints. (A round-5 attempt to grind it on the CPU
    # host was reverted: the xla-impl raw-gather epoch at 3.9M edges x
    # 4 emulated parts is ~minutes on one CPU core vs ~ms on chip.)
    ("convergence_study",
     [sys.executable, "scripts/convergence_study.py",
      "--noise", "32", "--homophily", "0.6", "--label-noise", "0.03",
      "--light-dir", "results/convergence_light/d492",
      "--time-budget", "1500"],
     2400, []),
    # refresh the headline + results/last_tpu_bench.json through the
    # measured auto-tuner table (persisted by spmm_tune above); also
    # runs the bucket-merge floor-lever before/after pass
    ("bench_auto_tuned",
     [sys.executable, "bench.py", "--no-compare"],
     3600, [_BENCH_PART]),
    # round-10: the online serving runtime measured on chip — open-loop
    # load against the compiled-once engine over the same bench
    # artifact + tuned kernel tables; headline is sustained QPS with
    # p50/p99 latency and live feature-update churn through the
    # incremental freshness path (docs/SERVING.md). Cheap: one
    # inference compile + 30 s of load.
    ("serve_bench",
     [sys.executable, "bench.py", "--serve", "--no-compare",
      "--serve-secs", "30", "--serve-qps", "200",
      "--metrics-out", "results/serve_bench_metrics.jsonl"],
     1800, [_BENCH_PART]),
    # round-17: closed-loop autoscaling measured on chip — a flash-
    # crowd arrival schedule (4x for the middle third) over the fleet
    # path with the AutoscalePolicy spawning/retiring replicas and the
    # degradation ladder browning out ahead of the hard queue wall;
    # headline is replica count tracking load (scale_events), p99
    # inside SLO outside the crowd edges, and a conservation-clean
    # shed_by_reason ledger (docs/SERVING.md "Autoscaling & overload").
    ("serve_autoscale_bench",
     [sys.executable, "bench.py", "--serve", "--no-compare",
      "--autoscale", "--traffic", "flash-crowd:4",
      "--serve-secs", "45", "--serve-qps", "150",
      "--metrics-out", "results/serve_autoscale_metrics.jsonl"],
     1800, [_BENCH_PART]),
    # round-13: streaming-graph delta ingestion measured on chip —
    # per-delta patch cost + forced-probe drift through the live fit()
    # loop, incremental-vs-full table rebuild, and the serving topology
    # refresh (docs/STREAMING.md). No artifact in `requires`: --stream
    # builds its graph in memory BY DESIGN (the patcher mutates the
    # live host graph the cached artifact discards), so its ~minutes of
    # host-side build are part of the scenario, bounded by the timeout.
    ("stream_bench",
     [sys.executable, "bench.py", "--stream", "--no-compare",
      "--stream-deltas", "6",
      "--metrics-out", "results/stream_bench_metrics.jsonl"],
     3600, []),
    # round-18: the integrity plane's per-check overhead measured on
    # chip — bench.py's floor-lever pass times the headline config
    # with --integrity-check-every 1 (worst-case cadence: digest
    # capture/verify + static scrub + Freivalds + the wire-checksum
    # lane every boundary) against the unguarded base and publishes
    # integrity_check_delta_s in the BENCH json; the guard is a
    # trace-time choice, so the delta is pure check cost, never
    # recompile cost (docs/RESILIENCE.md "Silent data corruption")
    ("integrity_overhead",
     [sys.executable, "bench.py", "--no-compare", "--force-candidate"],
     3600, [_BENCH_PART]),
    # round-19: the always-on training-span plane measured on chip —
    # bench.py's train-span pass drives two fit() runs (spans on vs
    # off) over the headline config and publishes the span-derived
    # verdicts (overlap_spans, comm_wait_share, per-rank
    # straggler_gap_s) plus the tracing cost train_traces_delta_s in
    # the BENCH json (expected ~0: the plane is host-side bookkeeping;
    # docs/OBSERVABILITY.md "Training traces")
    ("train_spans",
     [sys.executable, "bench.py", "--no-compare", "--force-candidate"],
     3600, [_BENCH_PART]),
    # VERDICT r5 item 8: second shape point for the auto-kernel policy
    ("offshape_products",
     [sys.executable, "scripts/offshape_bench.py", "--shape",
      "products", "--impl", "auto"],
     3600, []),
    ("offshape_products_bucket",
     [sys.executable, "scripts/offshape_bench.py", "--shape",
      "products", "--impl", "bucket"],
     3600, []),
    # the policy question is bucket-vs-BLOCK at this shape (auto
    # resolves to bucket there); block tables prewarmed host-side
    ("offshape_products_block",
     [sys.executable, "scripts/offshape_bench.py", "--shape",
      "products", "--impl", "block"],
     3600, []),
    # cheap GAT attribution (incl. the narrow-row gather-rate curve
    # that decides the el-packing-vs-Pallas-softmax question) BEFORE
    # the convergence legs, which absorb every remaining window second
    ("gat_microbench",
     [sys.executable, "scripts/gat_microbench.py"],
     2400, []),
    # VERDICT r3 item 3, full scale: the 97.1%-claim analogue at FULL
    # node count AND full degree (232,965 nodes x avg degree 492 =
    # Reddit's shape, reference README.md:91-99), P=2 like the
    # reference's scripts/reddit.sh. Epochs 1200 (was 3000): 3000 is
    # Reddit's schedule; the calibrated SBM separates variants by ~150
    # epochs at degree 6 (results/staleness_parity_reddit_scale.md)
    # and the label-noise ceiling bounds attainable accuracy — 1200
    # makes a COMPLETED 3-leg study realistic in sporadic ~45-min
    # windows (3 legs x 1200 x ~1.4 s/epoch ~ 1.4 h of chip time)
    # where an incomplete 3000-epoch one repeats round 4's failure.
    # Resumable + artifact-cached: each window advances it.
    ("convergence_full",
     [sys.executable, "scripts/convergence_study.py",
      "--nodes", "232965", "--degree", "492", "--feat", "602",
      "--classes", "41", "--parts", "2", "--cluster-size", "1024",
      "--noise", "32", "--homophily", "0.6", "--label-noise", "0.03",
      "--spmm-impl", "auto", "--spmm-chunk", "524288",
      "--block-group", "4", "--epochs", "1200",
      "--fused", "8", "--eval-every", "100",
      "--cache-artifacts", "--time-budget", "3600",
      "--light-dir", "results/convergence_light/full",
      "--state-dir", "results/convergence_state_full",
      "--out", "results/convergence_fullscale.md"],
     7200, []),
    # LAST: the raw-xla GAT compile crashed the remote compile helper
    # once (HTTP 500) around a tunnel death — quarantined at the tail
    # so a repeat cannot burn the load-bearing steps above
    ("gat_bench_small_f8",
     [sys.executable, "scripts/gat_bench.py",
      "--dataset", "synthetic:60000:30:602:41",
      "--rem-dtype", "float8"],
     3600, []),
    ("gat_bench_small_xla",
     [sys.executable, "scripts/gat_bench.py",
      "--dataset", "synthetic:60000:30:602:41", "--impl", "xla"],
     3600, []),
]


def probe(timeout_s: float = 60.0) -> bool:
    """Backend probe in a throwaway subprocess (an in-process failure
    poisons jax for the process's life — bench.py's pattern)."""
    code = ("import jax; d = jax.devices(); "
            "import sys; sys.exit(0 if d and d[0].platform != 'cpu' "
            "else 1)")
    try:
        r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                           timeout=timeout_s, capture_output=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def preflight(requires, repo: str = REPO) -> list:
    """Missing artifact patterns of one queue entry (glob-expanded,
    repo-relative); [] means the entry may run."""
    missing = []
    for pat in requires:
        full = pat if os.path.isabs(pat) else os.path.join(repo, pat)
        if not _glob.glob(full):
            missing.append(pat)
    return missing


def preflight_queue(queue=None, repo: str = REPO):
    """{name: missing} for every entry whose artifacts are absent —
    computed ONCE at window start so no window second is burned
    rebuilding what the host could have built offline."""
    queue = QUEUE if queue is None else queue
    return {name: miss for name, _, _, req in queue
            if (miss := preflight(req, repo))}


def _skip_record(name: str, missing: list) -> None:
    """Loud skip: stderr line + a durable `skipped` record in
    window.jsonl (free-form MetricsLogger event, fsynced)."""
    print(f"# {name}: SKIPPED — missing artifacts {missing} "
          f"(build them outside the window)", file=sys.stderr,
          flush=True)
    try:
        from pipegcn_tpu.obs import MetricsLogger

        os.makedirs(LOG_DIR, exist_ok=True)
        with MetricsLogger(os.path.join(LOG_DIR, "window.jsonl")) as ml:
            ml.event("skipped", step=name, missing=missing,
                     time_unix=time.time())
            ml.hard_flush()
    except Exception as exc:  # noqa: BLE001 — the queue must go on
        print(f"# could not write skipped record: {exc!r}",
              file=sys.stderr, flush=True)


def _window_logger():
    """MetricsLogger on results/tpu_window/window.jsonl, or None when
    the obs package can't import — the queue must run regardless."""
    try:
        from pipegcn_tpu.obs import MetricsLogger

        os.makedirs(LOG_DIR, exist_ok=True)
        return MetricsLogger(os.path.join(LOG_DIR, "window.jsonl"))
    except Exception as exc:  # noqa: BLE001
        print(f"# window.jsonl logger unavailable: {exc!r}",
              file=sys.stderr, flush=True)
        return None


def _run_step(name, argv, tmo, log, ml) -> str:
    """One queue entry under Popen with periodic heartbeats into
    window.jsonl (step name, elapsed, log growth) so the live monitor
    can see the window progressing; returns the status string."""
    t0 = time.time()
    with open(log, "w") as f:
        proc = subprocess.Popen(argv, cwd=REPO, stdout=f,
                                stderr=subprocess.STDOUT)
    next_beat = t0 + HEARTBEAT_S
    while True:
        rc = proc.poll()
        now = time.time()
        if rc is not None:
            return f"rc={rc}"
        if now - t0 > tmo:
            proc.kill()
            proc.wait()
            return "timeout"
        if ml is not None and now >= next_beat:
            next_beat = now + HEARTBEAT_S
            try:
                log_bytes = os.path.getsize(log)
            except OSError:
                log_bytes = 0
            ml.event("heartbeat", step=name,
                     elapsed_s=round(now - t0, 1),
                     log_bytes=log_bytes, time_unix=now)
            ml.hard_flush()
        time.sleep(min(1.0, max(0.0, next_beat - now)))


def _explain_failure(name: str, status: str, ml) -> None:
    """Auto-postmortem for a failed queue step: run the rule engine
    (pipegcn_tpu.obs.postmortem) over the window log dir — the step's
    log tail, window.jsonl and any black-box dumps the step's
    subprocesses left under results/ — and land the contracted
    `diagnosis` record in window.jsonl so the round review starts from
    a verdict, not a raw log."""
    try:
        from pipegcn_tpu.obs.postmortem import diagnose_run

        v = diagnose_run(LOG_DIR)
        print(f"# {name}: postmortem -> {v['verdict']} "
              f"(confidence {v['confidence']:.2f}): "
              f"{v['remediation']}", file=sys.stderr, flush=True)
        if ml is not None:
            ml.diagnosis(verdict=v["verdict"],
                         confidence=v["confidence"],
                         evidence=list(v["evidence"])[:6],
                         remediation=v["remediation"],
                         deterministic=v["deterministic"],
                         step=name, status=status,
                         time_unix=time.time())
            ml.hard_flush()
    except Exception as exc:  # noqa: BLE001 — advisory, never fatal
        print(f"# {name}: postmortem failed: {exc!r}", file=sys.stderr,
              flush=True)


def publish_trend() -> None:
    """Fold the round's artifacts into the bench trend verdict
    (obs/trend.py): results/tpu_window/trend.json + a window.jsonl
    record, so a regression vs the best-known headline is flagged the
    moment the window that caused it closes."""
    try:
        from pipegcn_tpu.obs.trend import format_trend, load_series, \
            trend

        t = trend(load_series(REPO))
        os.makedirs(LOG_DIR, exist_ok=True)
        with open(os.path.join(LOG_DIR, "trend.json"), "w") as f:
            json.dump(t, f, indent=2, sort_keys=True)
        ml = _window_logger()
        if ml is not None:
            ml.event("trend", regressed=t["regressed"],
                     flags=t["flags"], n_rounds=t["n_rounds"],
                     time_unix=time.time())
            ml.hard_flush()
            ml.close()
        print(format_trend(t), flush=True)
    except Exception as exc:  # noqa: BLE001 — advisory, never fatal
        print(f"# trend publish failed: {exc!r}", file=sys.stderr,
              flush=True)


def run_queue(skip: set) -> None:
    os.makedirs(LOG_DIR, exist_ok=True)
    # preflight the WHOLE queue at window open (artifacts do not
    # appear mid-window; one verdict per window keeps the log readable)
    skipped = preflight_queue()
    for name, miss in skipped.items():
        if name not in skip:
            _skip_record(name, miss)
    ml = _window_logger()
    try:
        for name, argv, tmo, _req in QUEUE:
            if name in skip:
                continue
            if name in skipped:
                continue  # skipped loudly above; not marked done
            if not probe():
                print(f"# tunnel died before {name}; stopping queue",
                      flush=True)
                return
            log = os.path.join(LOG_DIR, f"{name}.log")
            t0 = time.time()
            print(f"# {name}: starting (timeout {tmo}s)", flush=True)
            if ml is not None:
                ml.event("step_start", step=name, timeout_s=tmo,
                         time_unix=t0)
                ml.hard_flush()
            status = _run_step(name, argv, tmo, log, ml)
            if status == "rc=0":
                skip.add(name)
            print(f"# {name}: {status} ({time.time() - t0:.0f}s) "
                  f"-> {log}", flush=True)
            if ml is not None:
                ml.event("step_done", step=name, status=status,
                         elapsed_s=round(time.time() - t0, 1),
                         time_unix=time.time())
                ml.hard_flush()
            if status != "rc=0":
                _explain_failure(name, status, ml)
            with open(os.path.join(LOG_DIR, "status.json"), "w") as f:
                json.dump({"done": sorted(skip), "ts": time.time()}, f)
    finally:
        # verdict even on a mid-queue tunnel death: completed steps
        # already refreshed BENCH artifacts worth trending
        if ml is not None:
            ml.close()
        publish_trend()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--poll-s", type=float, default=300.0)
    ap.add_argument("--once", action="store_true")
    ap.add_argument("--dry-run", action="store_true",
                    help="print each entry's preflight verdict "
                         "(runnable vs missing artifacts) and exit "
                         "without probing the tunnel")
    args = ap.parse_args()
    if args.dry_run:
        skipped = preflight_queue()
        for name, _, _, req in QUEUE:
            if name in skipped:
                print(f"{name}: SKIP (missing {skipped[name]})")
            else:
                print(f"{name}: ok"
                      + (f" (requires {req})" if req else ""))
        sys.exit(1 if skipped else 0)
    done: set = set()
    status = os.path.join(LOG_DIR, "status.json")
    if os.path.exists(status):
        with open(status) as f:
            done = set(json.load(f).get("done", []))
    while True:
        if probe():
            print("# tunnel UP — running measurement queue", flush=True)
            run_queue(done)
            if all(name in done for name, _, _, _ in QUEUE):
                print("# queue complete", flush=True)
                return
        elif args.once:
            print("# tunnel down", flush=True)
            return
        if args.once:
            return
        time.sleep(args.poll_s)


if __name__ == "__main__":
    main()
