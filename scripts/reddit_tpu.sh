# The TPU-tuned Reddit configuration: same model/optimization as the
# reference reproduction (scripts/reddit.sh) plus the TPU-native
# extensions — bf16 compute, the auto-selected scatter-free aggregation
# kernel, cluster-renumbered local ids (dense tiles for the block
# kernel), fused epoch dispatches, and mesh-sharded evaluation.
python main.py \
  --dataset reddit \
  --dropout 0.5 \
  --lr 0.01 \
  --n-partitions "${N_PARTITIONS:-2}" \
  --n-epochs 3000 \
  --model graphsage \
  --n-layers 4 \
  --n-hidden 256 \
  --log-every 10 \
  --inductive \
  --enable-pipeline \
  --use-pp \
  --dtype bfloat16 \
  --spmm-impl auto \
  --local-reorder cluster \
  --fused-epochs 4 \
  --sharded-eval
