#!/usr/bin/env python
"""Glue for the metrics report CLI (`python scripts/report.py run.jsonl`),
equivalent to `python -m pipegcn_tpu.cli.report` — kept so the scripts/
directory exposes the whole tooling surface (README quick start).
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from pipegcn_tpu.cli.report import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
