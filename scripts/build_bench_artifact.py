#!/usr/bin/env python
"""Build (or rebuild) a bench partition artifact host-side.

Thin CLI over pipegcn_tpu.partition.bench_artifact.ensure() — the one
canonical recipe. Run while the chip queue is busy: the build is pure
host numpy.

Usage: python scripts/build_bench_artifact.py [--parts 1]
           [--cluster-size 1024] [--small]
"""

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--parts", type=int, default=1)
    ap.add_argument("--cluster-size", type=int, default=1024)
    ap.add_argument("--small", action="store_true")
    args = ap.parse_args()

    from pipegcn_tpu.partition.bench_artifact import artifact_path, ensure

    path = artifact_path(args.parts, args.cluster_size, small=args.small,
                         root=os.path.join(REPO, "partitions"))
    ensure(path, log=lambda m: print(m, flush=True))
    print(f"ready: {path}")


if __name__ == "__main__":
    main()
