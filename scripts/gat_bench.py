#!/usr/bin/env python
"""GAT epoch time at Reddit scale — attention-bucket kernel vs raw.

The GAT family used to run only on the raw-edge segment path (the
19.8 s/epoch-class regime, docs/PERF_NOTES.md); this measures the
scatter-free attention-bucket kernel (ops/gat_bucket.py) on the real
chip against the SAGE headline. Reuses the bench partition artifact
(and its cached tables after the first run).

Timing forces a device->host scalar read per dispatch (through the
axon tunnel block_until_ready does not synchronize); dispatches are
sized under the tunnel's observed ~80 s execute-crash threshold.

Usage: python scripts/gat_bench.py [--part partitions/bench-reddit-1-c2-s1024]
       [--impl bucket|xla] [--epochs 4] [--heads 4]
"""

import argparse
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--part",
                    default="partitions/bench-reddit-1-c2-s1024")
    ap.add_argument("--impl", default="bucket",
                    choices=["bucket", "xla"])
    ap.add_argument("--epochs", type=int, default=4,
                    help="timed fused-epoch block length")
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--rem-dtype", default="none",
                    choices=["none", "bfloat16", "float8"],
                    help="wide-gather transport narrowing "
                         "(ModelConfig.rem_dtype)")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from pipegcn_tpu.models import ModelConfig
    from pipegcn_tpu.parallel import Trainer, TrainConfig
    from pipegcn_tpu.partition import ShardedGraph

    sg = ShardedGraph.load(args.part)
    cfg = ModelConfig(
        # 3 graph layers like the SAGE headline (no use_pp for GAT)
        layer_sizes=(sg.n_feat, args.hidden, args.hidden, args.hidden,
                     sg.n_class),
        model="gat", n_heads=args.heads, norm="layer", dropout=0.5,
        train_size=sg.n_train_global, spmm_impl=args.impl,
        spmm_chunk=2_097_152, dtype="bfloat16",
        rem_dtype=args.rem_dtype,
    )
    tcfg = TrainConfig(lr=0.01, n_epochs=args.epochs * (args.reps + 2),
                       enable_pipeline=True, eval=False,
                       fused_epochs=args.epochs)
    t0 = time.time()
    tr = Trainer(sg, cfg, tcfg)
    print(f"# trainer init (tables) {time.time()-t0:.0f}s",
          file=sys.stderr)

    # train_epochs dispatches one fused scan of args.epochs epochs
    # (train_epoch would run ONE epoch and make the division below 4x
    # optimistic)
    t0 = time.time()
    losses = tr.train_epochs(0, args.epochs)
    print(f"# first block (compile) {time.time()-t0:.0f}s "
          f"loss={float(losses[-1]):.4f}", file=sys.stderr)

    times = []
    for r in range(args.reps):
        start = (r + 1) * args.epochs
        t0 = time.time()
        losses = tr.train_epochs(start, args.epochs)
        dt = time.time() - t0
        times.append(dt / args.epochs)
        print(f"# block {r}: {dt:.2f}s -> {dt/args.epochs:.3f} s/epoch "
              f"loss={float(losses[-1]):.4f}", file=sys.stderr)
    import json

    print(json.dumps({
        "metric": f"gat_{args.impl}_epoch_time"
                  + ("" if args.rem_dtype == "none"
                     else f"_{args.rem_dtype}"),
        "value": round(min(times), 4),
        "unit": "s/epoch",
        "heads": args.heads,
        "hidden": args.hidden,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
