#!/usr/bin/env python
"""GAT epoch time at Reddit scale — attention-bucket kernel vs raw.

The GAT family used to run only on the raw-edge segment path (the
19.8 s/epoch-class regime, docs/PERF_NOTES.md); this measures the
scatter-free attention-bucket kernel (ops/gat_bucket.py) on the real
chip against the SAGE headline. Reuses the bench partition artifact
(and its cached tables after the first run).

Timing forces a device->host scalar read per dispatch (through the
axon tunnel block_until_ready does not synchronize); dispatches are
sized under the tunnel's observed ~80 s execute-crash threshold.

Usage: python scripts/gat_bench.py [--part partitions/bench-reddit-1-c2-s1024]
       [--impl bucket|xla] [--epochs 4] [--heads 4]
"""

import argparse
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--part",
                    default="partitions/bench-reddit-1-c2-s1024")
    ap.add_argument("--dataset", default=None,
                    help="build (and cache) a dedicated artifact from "
                         "this dataset spec instead of --part — e.g. "
                         "synthetic:60000:30:602:41. The full "
                         "Reddit-scale GAT epoch exceeds the tunnel's "
                         "~80 s execute ceiling and crashes the worker "
                         "(results/tpu_window/gat_bench.log, round 4), "
                         "so chip rankings run at a reduced scale")
    ap.add_argument("--impl", default="bucket",
                    choices=["bucket", "xla"])
    ap.add_argument("--epochs", type=int, default=4,
                    help="timed fused-epoch block length")
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--rem-dtype", default="none",
                    choices=["none", "bfloat16", "float8"],
                    help="wide-gather transport narrowing "
                         "(ModelConfig.rem_dtype)")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from pipegcn_tpu.models import ModelConfig
    from pipegcn_tpu.parallel import Trainer, TrainConfig
    from pipegcn_tpu.partition import ShardedGraph
    from pipegcn_tpu.partition.bench_artifact import build_artifact, ensure

    log = lambda m: print(m, file=sys.stderr)  # noqa: E731
    if args.dataset:
        part_path = os.path.join(
            REPO, "partitions",
            "gat-" + args.dataset.replace(":", "_") + "-c-s1024")
        if ShardedGraph.exists(part_path):
            sg = ShardedGraph.load(part_path)
        else:
            sg = build_artifact(args.dataset, 1, 1024, part_path, log=log)
    else:
        # rebuilt if missing: partitions/ is not git-tracked and
        # vanishes between rounds
        if not os.path.isabs(args.part):
            args.part = os.path.join(REPO, args.part)
        sg = ensure(args.part, log=log)
    cfg = ModelConfig(
        # 3 graph layers like the SAGE headline (no use_pp for GAT)
        layer_sizes=(sg.n_feat, args.hidden, args.hidden, args.hidden,
                     sg.n_class),
        model="gat", n_heads=args.heads, norm="layer", dropout=0.5,
        train_size=sg.n_train_global, spmm_impl=args.impl,
        spmm_chunk=2_097_152, dtype="bfloat16",
        rem_dtype=args.rem_dtype,
    )
    tcfg = TrainConfig(lr=0.01,
                       n_epochs=2 + args.epochs * (args.reps + 2),
                       enable_pipeline=True, eval=False,
                       fused_epochs=args.epochs)
    t0 = time.time()
    tr = Trainer(sg, cfg, tcfg)
    print(f"# trainer init (tables) {time.time()-t0:.0f}s",
          file=sys.stderr)

    # bench.py's dispatch discipline: compile + time single epochs
    # (min of two, so one tunnel hiccup can't flip the decision), then
    # size fused blocks under the tunnel's execute-crash margin.
    # (A cold 4-epoch GAT dispatch crossed the ~80 s threshold and
    # crashed the worker — results/tpu_window/gat_bench.log, round 4.)
    from bench import MAX_DISPATCH_S

    t0 = time.perf_counter()
    losses = tr.train_epochs(0, 1)
    print(f"# compile+first {time.perf_counter()-t0:.0f}s "
          f"loss={float(losses[-1]):.4f}", file=sys.stderr)
    singles = []
    for i in (1, 2):
        t0 = time.perf_counter()
        losses = tr.train_epochs(i, 1)
        singles.append(time.perf_counter() - t0)
    single = min(singles)
    print(f"# single epoch {single:.2f}s", file=sys.stderr)
    blk = max(1, min(args.epochs,
                     int(MAX_DISPATCH_S // max(single, 1e-6))))
    e = 3
    if blk > 1:  # compile the blk-epoch fused program off the clock
        t0 = time.perf_counter()
        tr.train_epochs(e, blk)
        e += blk
        print(f"# fused-{blk} warmup/compile "
              f"{time.perf_counter()-t0:.0f}s", file=sys.stderr)

    times = []
    for r in range(args.reps):
        t0 = time.perf_counter()
        losses = tr.train_epochs(e, blk)
        dt = time.perf_counter() - t0
        e += blk
        times.append(dt / blk)
        print(f"# block {r}: {dt:.2f}s -> {dt/blk:.3f} s/epoch "
              f"loss={float(losses[-1]):.4f}", file=sys.stderr)
    import json

    print(json.dumps({
        "metric": f"gat_{args.impl}_epoch_time"
                  + ("" if args.rem_dtype == "none"
                     else f"_{args.rem_dtype}"),
        "value": round(float(np.median(times)), 4),
        "unit": "s/epoch",
        "heads": args.heads,
        "hidden": args.hidden,
        "dispatch_epochs": blk,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
