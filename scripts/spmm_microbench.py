"""Decompose the block-SpMM epoch cost on the real chip.

Loads the cached Reddit-scale bench artifact + block tables, then times
the device aggregation closure in three configurations — full hybrid,
dense-tiles-only, remainder-only — forward and forward+backward, at the
training feature width. This attributes the measured epoch time between
the MXU dense path, the slabbed gather remainder, and everything else
(the bench's per-epoch number minus 6x the SpMM cost).

Timing forces a device->host scalar read per call: through the axon
tunnel, block_until_ready alone does not synchronize (docs/PERF_NOTES).

Usage: python scripts/spmm_microbench.py [--part partitions/...]
"""

import argparse
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--part",
                    default="partitions/bench-reddit-1-c2-s1024")
    ap.add_argument("--width", type=int, default=256)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--block-nnz", type=int, default=0)
    ap.add_argument("--group", type=int, default=1,
                    help="union-gather group size (block_group); the "
                         "prewarmed u4/u8 table caches make this cheap")
    ap.add_argument("--probe-traffic", action="store_true",
                    help="table-surgery decomposition of the dense "
                         "term: F-tile reads vs A reads vs MXU")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from pipegcn_tpu.models import ModelConfig
    from pipegcn_tpu.parallel import Trainer, TrainConfig

    # rebuilt if missing: partitions/ is not git-tracked and vanishes
    # between rounds
    from pipegcn_tpu.partition.bench_artifact import ensure

    if not os.path.isabs(args.part):
        args.part = os.path.join(REPO, args.part)
    sg = ensure(args.part, log=lambda m: print(m, file=sys.stderr))
    cfg = ModelConfig(
        layer_sizes=(sg.n_feat, 256, 256, 256, sg.n_class),
        use_pp=True, norm="layer", dropout=0.5,
        train_size=sg.n_train_global, spmm_chunk=2_097_152,
        dtype="bfloat16", spmm_impl="block",
        block_nnz=args.block_nnz or None,
        block_group=args.group,
    )
    tr = Trainer(sg, cfg, TrainConfig(lr=0.01, n_epochs=1, eval=False))
    d = {k: v[0] for k, v in tr.data.items()}
    n_max = sg.n_max
    n_src = n_max + sg.halo_size

    rng = np.random.default_rng(0)
    fbuf = jnp.asarray(
        rng.standard_normal((n_src, args.width)).astype(np.float32)
    ).astype(jnp.bfloat16)

    from pipegcn_tpu.ops.block_spmm import make_device_block_spmm_fn

    def variant(name, keep):
        # The tables ride as jit ARGUMENTS, never closure constants:
        # jit embeds closed-over arrays into the HLO, and the axon
        # tunnel ships that HLO as one remote_compile HTTP body — GBs
        # of embedded tables exceed its length limit (HTTP 413). The
        # factory's host logic depends only on dict keys/shapes, so
        # re-invoking it under trace is sound (the Trainer passes the
        # same tables as shard_map operands for the same reason).
        dd = {k: v for k, v in d.items() if keep(k)}

        def apply(tables, in_deg, f):
            fn = make_device_block_spmm_fn(
                tables, in_deg, n_max, n_src, tr._block_tile,
                chunk_edges=cfg.spmm_chunk)
            return fn(f)

        fwd = jax.jit(apply)

        @jax.jit
        def grad(tables, in_deg, f):
            return jax.grad(lambda ff: apply(tables, in_deg, ff)
                            .astype(jnp.float32).sum())(f)

        def timed(g, label):
            g(dd, d["in_deg"], fbuf)  # compile
            float(jnp.sum(g(dd, d["in_deg"], fbuf)[0]))
            ts = []
            for _ in range(args.reps):
                t0 = time.perf_counter()
                float(jnp.sum(g(dd, d["in_deg"], fbuf)[0]))
                ts.append(time.perf_counter() - t0)
            print(f"{name:12s} {label:8s} {min(ts)*1e3:8.1f} ms",
                  file=sys.stderr)
            return min(ts)

        f = timed(fwd, "fwd")
        fb = timed(grad, "fwd+bwd")
        return f, fb

    is_dense = lambda k: k.startswith("blk_")
    is_rem = lambda k: k.startswith("blkrem_")
    aux = lambda k: not (is_dense(k) or is_rem(k))
    inv_only = lambda k: k.endswith("inv") or k.endswith("ginv")

    # ONE dense-keep predicate: the --probe-traffic deltas below are
    # only meaningful against the exact same program as this baseline
    dense_keep = lambda k: aux(k) or is_dense(k) \
        or (is_rem(k) and inv_only(k))

    full = variant("full", lambda k: True)
    dense = variant("dense-only", dense_keep)
    rem = variant("rem-only",
                  lambda k: aux(k) or is_rem(k)
                  or (is_dense(k) and (inv_only(k) or k in
                                       ("blk_a", "blk_a_bits"))))
    print(f"# per-SpMM (fwd+bwd avg ~ epoch has 3 fwd + 3 bwd):")
    print(f"full fwd {full[0]*1e3:.1f} ms, fwd+bwd {full[1]*1e3:.1f} ms; "
          f"dense fwd {dense[0]*1e3:.1f}, rem fwd {rem[0]*1e3:.1f}")
    est_epoch = 3 * full[1]
    print(f"# est SpMM-only epoch: {est_epoch:.3f}s")

    if args.probe_traffic:
        # Attribute the dense-only time between F-tile reads, A reads
        # and the MXU term by TABLE SURGERY: identical program shapes,
        # but every group entry points at tile/block 0, collapsing that
        # operand's distinct HBM traffic to one tile. (Numerics are
        # wrong on purpose; only time matters.) The F-tile delta decides
        # whether the union-gather reuse design (docs/PERF_NOTES.md
        # "F-tile reuse headroom") is worth building.
        prefixes = ("blk_fwd_g", "blk_bwd_g",
                    "blk_fwdu_g", "blk_bwdu_g")  # per-tile + grouped

        def surgery(name, zero_suffix):
            saved = {}
            for k in list(d.keys()):
                if k.startswith(prefixes):
                    if k.endswith(zero_suffix) and not k.endswith("ginv"):
                        saved[k] = d[k]
                        d[k] = jnp.zeros_like(d[k])
            try:
                return variant(name, dense_keep)
            finally:
                d.update(saved)

        tile0 = surgery("tile0-dense", "t")   # all F-tile reads -> tile 0
        # A-index matrices end with "b" in the per-tile layout, "a" in
        # the grouped one
        a0 = surgery("a0-dense", "a" if args.group > 1 else "b")
        print("# dense decomposition (fwd): "
              f"baseline {dense[0]*1e3:.1f} ms, "
              f"F-tile-collapsed {tile0[0]*1e3:.1f} ms "
              f"(F-read share {(dense[0]-tile0[0])*1e3:.1f} ms), "
              f"A-collapsed {a0[0]*1e3:.1f} ms "
              f"(A-read share {(dense[0]-a0[0])*1e3:.1f} ms)")

        # Unpack-transient probe: the same plan with A PRE-UNPACKED to
        # bf16 on the host — no device-side bit unpack, so the
        # [rows, K, T, S] elementwise transient (which XLA materializes
        # between HBM round-trips; it cannot fuse producers into a dot)
        # disappears, at the price of 16x the A-read bytes. (The
        # fused unpack+matmul kernel this probe once motivated lost
        # on-chip twice and was deleted — docs/PERF_NOTES.md "fused
        # block kernel: negative result".) Note the a0 surgery above
        # does NOT isolate this: collapsing indices to block 0 still
        # unpacks every slot.
        if "blk_a_bits" in d:
            packed_bits = d.pop("blk_a_bits")
            # np.unpackbits is the exact inverse of pack_a_blocks
            # (bitorder='little'); upload the narrow uint8 and cast to
            # bf16 eagerly on device (16x less tunnel traffic than a
            # host-widened array)
            d["blk_a"] = jnp.asarray(np.unpackbits(
                np.asarray(packed_bits), axis=-1, bitorder="little"
            )).astype(jnp.bfloat16)
            try:
                unp = variant("wide-A-dense", dense_keep)
            finally:
                del d["blk_a"]
                d["blk_a_bits"] = packed_bits
            print("# unpack probe (fwd): packed "
                  f"{dense[0]*1e3:.1f} ms vs pre-unpacked bf16 "
                  f"{unp[0]*1e3:.1f} ms (transient-minus-read delta "
                  f"{(dense[0]-unp[0])*1e3:.1f} ms)")
        else:
            unp = None

        # machine-readable record so the cost-model recalibration
        # (scripts/coverage_sweep.py --gather-rps/--fixed-s) can
        # consume the decomposition without log scraping
        import json

        rec = {
            "backend": jax.default_backend(),
            "group": args.group,
            "width": args.width,
            "full_fwd_s": full[0], "full_fwdbwd_s": full[1],
            "dense_fwd_s": dense[0], "dense_fwdbwd_s": dense[1],
            "rem_fwd_s": rem[0], "rem_fwdbwd_s": rem[1],
            "ftile_collapsed_fwd_s": tile0[0],
            "a_collapsed_fwd_s": a0[0],
            "est_spmm_epoch_s": est_epoch,
        }
        if unp is not None:
            rec["wide_a_fwd_s"] = unp[0]
        # keyed by backend/config so a CPU smoke run or a different
        # group/fused probe never clobbers the real TPU calibration
        # record
        tag = f"{jax.default_backend()}_g{args.group}"
        out_path = os.path.join(REPO, "results",
                                f"probe_traffic_{tag}.json")
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(rec, f, indent=1)
        print(f"# wrote {out_path}")


if __name__ == "__main__":
    main()
