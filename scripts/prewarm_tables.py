#!/usr/bin/env python
"""Pre-build + disk-cache kernel tables for a partition artifact.

Mostly host-side: run while the TPU tunnel is down so the next
bench/microbench on the real chip skips the minutes-long O(E) table
builds (docs/PERF_NOTES.md tunnel notes). One invocation per kernel
configuration; the cache key (Trainer._cached_tables) encodes
(impl, tile, width, nnz, group, merge).

--impl auto additionally runs the SpMM auto-tuner's micro-bench
campaign on the current backend (small sampled slice — the one part of
prewarm that does touch the device) and persists the tuning.json
sidecar into the artifact, then warms the winner's tables. Run it on
the backend you will train on: the table signature pins the backend,
so a CPU-prewarmed table is (correctly) rejected on TPU.

Usage: python scripts/prewarm_tables.py --impl block --group 4
       [--part partitions/bench-reddit-1-c2-s1024] [--block-nnz N]
       python scripts/prewarm_tables.py --impl auto   # tune + warm
"""

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--part",
                    default="partitions/bench-reddit-1-c2-s1024")
    ap.add_argument("--impl", default="block",
                    choices=["auto", "block", "bucket", "gat"])
    ap.add_argument("--group", type=int, default=1)
    ap.add_argument("--block-nnz", type=int, default=0)
    ap.add_argument("--bucket-merge", type=int, default=0)
    ap.add_argument("--tuner-samples", type=int, default=200_000)
    ap.add_argument("--retune", action="store_true",
                    help="with --impl auto: delete any persisted "
                         "tuning.json first and force a fresh "
                         "micro-bench campaign")
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--reorder", default="none",
                    choices=["none", "degree", "bfs", "degree-bfs"],
                    help="prewarm the locality-REORDERED layout of "
                         "--part instead (suffix -r<mode>); the O(E) "
                         "artifact build happens here, host-side, so "
                         "tpu_window's reorder_slab preflight passes")
    args = ap.parse_args()

    from pipegcn_tpu.models import ModelConfig
    from pipegcn_tpu.parallel import Trainer

    # rebuilt if missing: partitions/ is not git-tracked and vanishes
    # between rounds
    from pipegcn_tpu.partition.bench_artifact import ensure

    if not os.path.isabs(args.part):
        args.part = os.path.join(REPO, args.part)
    if args.reorder != "none" and not args.part.endswith(
            f"-r{args.reorder}"):
        from pipegcn_tpu.partition.partitioner import reorder_suffix

        args.part += reorder_suffix(args.reorder)
    sg = ensure(args.part, log=lambda m: print(m, file=sys.stderr))
    if args.retune and args.impl == "auto":
        from pipegcn_tpu.ops import tuner

        p = tuner.tuning_path(sg.cache_dir)
        if os.path.exists(p):
            os.remove(p)
            print(f"removed {p} (forcing re-tune)", file=sys.stderr)
    cfg = ModelConfig(
        model="gat" if args.impl == "gat" else "graphsage",
        layer_sizes=(sg.n_feat,) + (args.hidden,) * 3 + (sg.n_class,),
        use_pp=args.impl != "gat", norm="layer",
        train_size=sg.n_train_global,
        spmm_impl="bucket" if args.impl == "gat" else args.impl,
        block_nnz=args.block_nnz or None,
        block_group=args.group, bucket_merge=args.bucket_merge,
        tuner_samples=args.tuner_samples,
        dtype="bfloat16",
    )
    t0 = time.perf_counter()
    Trainer.prewarm_tables(sg, cfg)
    print(f"warmed {args.impl} tables (group={args.group}, "
          f"nnz={args.block_nnz or 'auto'}) "
          f"in {time.perf_counter() - t0:.1f}s")
    if args.impl == "auto":
        from pipegcn_tpu.ops import tuner

        rec, why = tuner.load_tuning(sg.cache_dir)
        if rec is not None:
            print(f"tuning.json winner: {rec['winner']['name']} "
                  f"(backend {rec['signature']['backend']})")
        else:
            print(f"no tuning.json persisted ({why})", file=sys.stderr)


if __name__ == "__main__":
    main()
