#!/usr/bin/env python
"""Pre-build + disk-cache kernel tables for a partition artifact.

Host-side only (no device work): run while the TPU tunnel is down so
the next bench/microbench on the real chip skips the minutes-long O(E)
table builds (docs/PERF_NOTES.md tunnel notes). One invocation per
kernel configuration; the cache key (Trainer._cached_tables) encodes
(impl, tile, width, nnz, group).

Usage: python scripts/prewarm_tables.py --impl block --group 4
       [--part partitions/bench-reddit-1-c2-s1024] [--block-nnz N]
"""

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--part",
                    default="partitions/bench-reddit-1-c2-s1024")
    ap.add_argument("--impl", default="block",
                    choices=["block", "bucket", "gat"])
    ap.add_argument("--group", type=int, default=1)
    ap.add_argument("--block-nnz", type=int, default=0)
    ap.add_argument("--fused", action="store_true",
                    help="also warm the sublane-repacked A cache for "
                         "the fused Pallas dense path (--block-fused)")
    ap.add_argument("--hidden", type=int, default=256)
    args = ap.parse_args()

    from pipegcn_tpu.models import ModelConfig
    from pipegcn_tpu.parallel import Trainer

    # rebuilt if missing: partitions/ is not git-tracked and vanishes
    # between rounds
    from pipegcn_tpu.partition.bench_artifact import ensure

    if not os.path.isabs(args.part):
        args.part = os.path.join(REPO, args.part)
    sg = ensure(args.part, log=lambda m: print(m, file=sys.stderr))
    cfg = ModelConfig(
        model="gat" if args.impl == "gat" else "graphsage",
        layer_sizes=(sg.n_feat,) + (args.hidden,) * 3 + (sg.n_class,),
        use_pp=args.impl != "gat", norm="layer",
        train_size=sg.n_train_global,
        spmm_impl="bucket" if args.impl == "gat" else args.impl,
        block_nnz=args.block_nnz or None,
        block_group=args.group, block_fused=args.fused,
        dtype="bfloat16",
    )
    t0 = time.perf_counter()
    Trainer.prewarm_tables(sg, cfg)
    print(f"warmed {args.impl} tables (group={args.group}, "
          f"nnz={args.block_nnz or 'auto'}) "
          f"in {time.perf_counter() - t0:.1f}s")


if __name__ == "__main__":
    main()
