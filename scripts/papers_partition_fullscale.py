#!/usr/bin/env python
"""FULL papers100M-shape partition (VERDICT round-3 item 6, at scale).

The reference needs a >=120 GB host for papers100M (reference
README.md:29-30), where METIS partitioning dominates. This script
drives the in-tree partitioner over the FULL shape — 111M nodes, 1.6B
raw directed edges (3.2B after the mirror the chunked CSR builder
applies) — and reports peak RSS + wall per stage. Edges only: the
feature/label arrays play no role in partitioning and would exceed
this host's free disk at full scale; the 1/10-scale run
(scripts/papers100m_scale.py, results/papers100m_scale.md) covers the
full load->partition->shard->save pipeline end-to-end.

Same edge distribution as gen_raw_layout (power-law src skew +
locality windows + jumps).

Usage: python scripts/papers_partition_fullscale.py
       [--nodes 111000000] [--edges 1600000000] [--parts 64]
"""

import argparse
import json
import os
import resource
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def gen_edges(path: str, n_nodes: int, n_edges: int,
              chunk: int = 1 << 24) -> None:
    rng = np.random.default_rng(0)
    edges = np.lib.format.open_memmap(
        path, mode="w+", dtype=np.int32, shape=(n_edges, 2))
    for i0 in range(0, n_edges, chunk):
        m = min(chunk, n_edges - i0)
        src = (rng.pareto(1.5, m) * (n_nodes / 50)).astype(np.int64) \
            % n_nodes
        jump = rng.random(m) < 0.1
        window = rng.integers(-500_000, 500_000, m)
        dst = np.where(jump, rng.integers(0, n_nodes, m),
                       (src + window) % n_nodes)
        edges[i0:i0 + m, 0] = src.astype(np.int32)
        edges[i0:i0 + m, 1] = dst.astype(np.int32)
        if i0 % (chunk * 8) == 0:
            print(f"# gen {i0 / n_edges:.0%}", flush=True)
    edges.flush()
    del edges


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=111_000_000)
    ap.add_argument("--edges", type=int, default=1_600_000_000)
    ap.add_argument("--parts", type=int, default=64)
    ap.add_argument("--path",
                    default=os.path.join(REPO, "partitions",
                                         "papers_full_edges.npy"))
    ap.add_argument("--out",
                    default=os.path.join(REPO, "results",
                                         "papers_full_partition.json"))
    args = ap.parse_args()

    from pipegcn_tpu.graph.csr import Graph
    from pipegcn_tpu.partition.partitioner import partition_graph

    stages = {}
    t0 = time.time()
    if not os.path.exists(args.path):
        gen_edges(args.path, args.nodes, args.edges)
    stages["gen"] = {"s": round(time.time() - t0, 1),
                     "peak_rss_gb": round(rss_gb(), 2)}
    print(f"# edges ready ({stages['gen']})", flush=True)

    edges = np.load(args.path, mmap_mode="r")
    g = Graph(num_nodes=args.nodes, src=edges[:, 0], dst=edges[:, 1])

    t0 = time.time()
    # symmetric=False: the chunked CSR builder applies the mirror, so
    # the in-RAM adjacency is the full finalized ~2x raw edge count
    parts = partition_graph(g, args.parts, method="metis", obj="vol",
                            seed=0)
    stages["partition"] = {"s": round(time.time() - t0, 1),
                           "peak_rss_gb": round(rss_gb(), 2)}
    sizes = np.bincount(parts, minlength=args.parts)
    rec = {
        "nodes": args.nodes,
        "raw_edges": args.edges,
        "mirrored_adjacency_entries": 2 * args.edges,
        "parts": args.parts,
        "balance": round(float(sizes.max() / sizes.mean()), 4),
        "stages": stages,
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
