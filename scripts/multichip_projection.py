"""Project multi-chip epoch time for the Reddit-scale benchmark.

Real hardware here is ONE v5e chip, so multi-chip numbers cannot be
measured; this tool produces the next-best thing — a real P-way METIS
partition of the benchmark graph and, from it, the measured quantities
that determine multi-chip performance:

  - per-device inner nodes / edges (compute balance),
  - halo sizes and per-epoch ICI traffic (Trainer.est_ici_bytes_per_epoch,
    the exact gather/ppermute volumes of the pipelined step),
  - dense-tile coverage per device (the block kernel's regime survives
    partitioning or it doesn't),
  - a projected epoch time from the round-4 probe-CALIBRATED cost
    model (2.14 us/dense-block, 230M padded slab rows/s, measured aux
    + non-SpMM floor; validated at +2.7% against the fp8 single-chip
    headline — results/tpu_bench.md) — scaled by the MAX-loaded
    device, plus the ICI time at v5e's 2x 400 GB/s links (pipelined:
    overlapped, so counted only as a floor check).

Writes results/multichip_projection.md.

Usage:
  JAX_PLATFORMS=cpu python scripts/multichip_projection.py [--parts 8]
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--parts", type=int, default=8)
    ap.add_argument("--dataset", default="synthetic-reddit")
    ap.add_argument("--out", default="results/multichip_projection.md")
    ap.add_argument("--part-dir", default="partitions/projection")
    args = ap.parse_args()

    if args.dataset != "synthetic-reddit":
        print("# WARNING: epoch-model constants (BLOCK_S/ROW_RATE/"
              "AUX_S/FIXED_S, N1_ROWS) are probe-calibrated on the "
              "synthetic-reddit P=1 chip run; aux/floor scaling for "
              f"'{args.dataset}' is extrapolation, not calibration",
              file=sys.stderr)

    import jax

    jax.config.update("jax_platforms", "cpu")
    os.environ.setdefault(
        "XLA_FLAGS",
        f"--xla_force_host_platform_device_count={args.parts}")

    from pipegcn_tpu.graph import load_data
    from pipegcn_tpu.ops.block_spmm import (DENSE_A_BYTE_BUDGET,
                                            _part_block_stats,
                                            budget_block_cap)
    from pipegcn_tpu.partition import (ShardedGraph, locality_clusters,
                                       partition_graph)

    path = f"{args.part_dir}-{args.parts}"
    t0 = time.time()
    if ShardedGraph.exists(path):
        sg = ShardedGraph.load(path)
        print(f"# loaded cached projection partitions "
              f"({time.time()-t0:.0f}s)", file=sys.stderr)
    else:
        g = load_data(args.dataset)
        parts = partition_graph(g, args.parts, method="metis", obj="vol",
                                seed=0)
        cluster = locality_clusters(g, seed=0)
        sg = ShardedGraph.build(g, parts, n_parts=args.parts,
                                cluster=cluster)
        sg.save(path)
        print(f"# built projection partitions ({time.time()-t0:.0f}s)",
              file=sys.stderr)

    P = sg.num_parts
    inner = sg.inner_count.astype(np.int64)
    edges = sg.edge_count.astype(np.int64)
    halos = []        # halo EDGE endpoints (edges sourced from halo)
    halo_rows = []    # UNIQUE halo rows resident in the fbuf
    for r in range(P):
        e = int(sg.edge_count[r])
        src = sg.edge_src[r][:e]
        halos.append(int((src >= sg.n_max).sum()))
        halo_rows.append(int(np.unique(src[src >= sg.n_max]).size))
    send = sg.send_counts.sum(axis=1).astype(np.int64)

    # ICI volume of the pipelined step: per layer, each device sends its
    # boundary rows (send lists) and receives its halo rows, in the
    # compute dtype, forward + backward; 3 graph layers exchange (use_pp
    # skips layer 0). Width 256, bf16.
    width, isz, n_exch = 256, 2, 3
    tx_bytes = send * width * isz * n_exch * 2  # fwd feats + bwd grads

    # Probe-CALIBRATED per-device epoch model (round 4: fitted to the
    # measured table-surgery decomposition, validated at +2.7% on the
    # fp8 single-chip headline — scripts/coverage_sweep.model_epoch,
    # results/tpu_bench.md). Production transport: fp8 remainder.
    BLOCK_S, ROW_RATE, PAD = 2.14e-6, 230e6, 1.25
    AUX_S, FIXED_S = 0.066, 0.518
    N1_ROWS = 232_965          # P=1 fbuf rows (no halo at P=1)
    N_SLABS = 1                # fp8: one 256-byte slab at width 256
    tile = 256
    thr = max(1, (tile * tile) // 602)
    n_src_tiles = -(-(sg.n_max + sg.halo_size) // tile)
    # cap at the HBM byte budget exactly as the real plan builder does —
    # uncapped counts would project dense capacity the budgeted plan
    # spills to the remainder
    cap = budget_block_cap(DENSE_A_BYTE_BUDGET, tile)
    stats = [_part_block_stats(sg, r, tile, n_src_tiles, thr,
                               max_blocks=cap)
             for r in range(P)]
    cov = np.array([st[0] for st in stats])
    dense_blocks = np.array([st[1] for st in stats])

    rem_edges = edges * (1 - cov)
    rows_d = inner + np.asarray(halo_rows, np.int64)
    t_rem = 3 * rem_edges * PAD * N_SLABS / ROW_RATE
    t_dense = 3 * dense_blocks * BLOCK_S
    # shared SpMM prep scales with the fbuf rows each device holds
    t_aux = 3 * AUX_S * rows_d / N1_ROWS
    # the 0.518 s non-SpMM floor's scaling is bracketed until the
    # epoch-anatomy ablation attributes it: optimistic = scales with
    # inner rows (norms/dropout/linears), pessimistic = scales with
    # total fbuf rows (assembly/concat over inner+halo)
    floor_opt = FIXED_S * inner / N1_ROWS
    floor_pess = FIXED_S * rows_d / N1_ROWS
    t_ici = tx_bytes / 400e9                        # per-direction link
    t_dev = t_rem + t_dense + t_aux + floor_pess
    t_dev_opt = t_rem + t_dense + t_aux + floor_opt
    proj = float(t_dev.max())
    proj_opt = float(t_dev_opt.max())

    lines = [
        f"# Multi-chip projection ({P}-way METIS, {args.dataset})",
        "",
        "One v5e chip is available; this projects the multi-chip epoch "
        "from a REAL partition of the benchmark graph plus the round-4 "
        "probe-CALIBRATED cost model (fitted to the measured "
        "table-surgery decomposition; +2.7% on the fp8 single-chip "
        "headline — results/tpu_bench.md), fp8 remainder transport. "
        "The sharded program itself is validated on the virtual CPU "
        "mesh (dryrun_multichip, tests/). Per-device epoch column uses "
        "the PESSIMISTIC floor scaling (fbuf rows); the optimistic "
        "(inner-rows) bound is reported below the table.",
        "",
        "| device | inner nodes | edges | halo rows (unique) | send rows/layer | "
        "dense cov | est ICI MB/epoch | est epoch s |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in range(P):
        lines.append(
            f"| {r} | {inner[r]:,} | {edges[r]:,} | {halo_rows[r]:,} "
            f"| {send[r]:,} | {cov[r]:.2f} | {tx_bytes[r]/2**20:.0f} "
            f"| {t_dev[r]:.3f} |")
    lines += [
        "",
        f"Projected epoch (max device, comm overlapped, pessimistic "
        f"floor): **{proj:.3f} s**; optimistic floor: {proj_opt:.3f} s"
        + (f" — vs 1.2963 s measured single-chip, "
           f"{1.2963/proj:.1f}-{1.2963/proj_opt:.1f}x scaling at P={P}."
           if args.dataset == "synthetic-reddit" else "."),
        f"Worst-case exposed-ICI floor if NOTHING overlapped: "
        f"{float(t_ici.max()):.4f} s "
        f"({100*float(t_ici.max())/proj:.1f}% of the projected epoch) — "
        "the pipelined design exists to hide exactly this term "
        "(results/overlap_study.md shows all pipelined exchanges leave "
        "the critical path).",
        "",
        f"Reference baseline: 0.266 s/epoch on 2 GPUs; the projection "
        f"crosses it at P={P} if {proj:.3f} <= 0.266 "
        f"({'yes' if proj <= 0.266 else 'no'}).",
    ]
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        f.write("\n".join(lines) + "\n")
    print("\n".join(lines))


if __name__ == "__main__":
    main()
