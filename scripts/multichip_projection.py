"""Project multi-chip epoch time for the Reddit-scale benchmark.

Real hardware here is ONE v5e chip, so multi-chip numbers cannot be
measured; this tool produces the next-best thing — a real P-way METIS
partition of the benchmark graph and, from it, the measured quantities
that determine multi-chip performance:

  - per-device inner nodes / edges (compute balance),
  - halo sizes and per-epoch ICI traffic (Trainer.est_ici_bytes_per_epoch,
    the exact gather/ppermute volumes of the pipelined step),
  - dense-tile coverage per device (the block kernel's regime survives
    partitioning or it doesn't),
  - a projected epoch time from the v5e-calibrated cost model
    (docs/PERF_NOTES.md): slab-gather remainder at 390M rows/s, dense
    F-tile+A reads at 819 GB/s, MXU at 50% peak — scaled by the
    MAX-loaded device, plus the ICI time at v5e's 2x 400 GB/s links
    (pipelined: overlapped, so counted only as a floor check).

Writes results/multichip_projection.md.

Usage:
  JAX_PLATFORMS=cpu python scripts/multichip_projection.py [--parts 8]
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--parts", type=int, default=8)
    ap.add_argument("--dataset", default="synthetic-reddit")
    ap.add_argument("--out", default="results/multichip_projection.md")
    ap.add_argument("--part-dir", default="partitions/projection")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    os.environ.setdefault(
        "XLA_FLAGS",
        f"--xla_force_host_platform_device_count={args.parts}")

    from pipegcn_tpu.graph import load_data
    from pipegcn_tpu.ops.block_spmm import (DENSE_A_BYTE_BUDGET,
                                            _part_block_stats,
                                            budget_block_cap)
    from pipegcn_tpu.partition import (ShardedGraph, locality_clusters,
                                       partition_graph)

    path = f"{args.part_dir}-{args.parts}"
    t0 = time.time()
    if ShardedGraph.exists(path):
        sg = ShardedGraph.load(path)
        print(f"# loaded cached projection partitions "
              f"({time.time()-t0:.0f}s)", file=sys.stderr)
    else:
        g = load_data(args.dataset)
        parts = partition_graph(g, args.parts, method="metis", obj="vol",
                                seed=0)
        cluster = locality_clusters(g, seed=0)
        sg = ShardedGraph.build(g, parts, n_parts=args.parts,
                                cluster=cluster)
        sg.save(path)
        print(f"# built projection partitions ({time.time()-t0:.0f}s)",
              file=sys.stderr)

    P = sg.num_parts
    inner = sg.inner_count.astype(np.int64)
    edges = sg.edge_count.astype(np.int64)
    halos = []
    for r in range(P):
        e = int(sg.edge_count[r])
        src = sg.edge_src[r][:e]
        halos.append(int((src >= sg.n_max).sum()))
    send = sg.send_counts.sum(axis=1).astype(np.int64)

    # ICI volume of the pipelined step: per layer, each device sends its
    # boundary rows (send lists) and receives its halo rows, in the
    # compute dtype, forward + backward; 3 graph layers exchange (use_pp
    # skips layer 0). Width 256, bf16.
    width, isz, n_exch = 256, 2, 3
    tx_bytes = send * width * isz * n_exch * 2  # fwd feats + bwd grads

    # v5e-calibrated per-device epoch cost (docs/PERF_NOTES.md) —
    # coverage and dense-block counts from one O(E) pass per device
    GATHER_RPS, HBM_BPS, MXU = 390e6, 819e9, 0.5 * 197e12
    tile = 256
    thr = max(1, (tile * tile) // 602)
    n_src_tiles = -(-(sg.n_max + sg.halo_size) // tile)
    # cap at the HBM byte budget exactly as the real plan builder does —
    # uncapped counts would project dense capacity the budgeted plan
    # spills to the remainder
    cap = budget_block_cap(DENSE_A_BYTE_BUDGET, tile)
    stats = [_part_block_stats(sg, r, tile, n_src_tiles, thr,
                               max_blocks=cap)
             for r in range(P)]
    cov = np.array([st[0] for st in stats])
    dense_blocks = np.array([st[1] for st in stats])

    rem_edges = edges * (1 - cov)
    t_rem = rem_edges * 2 * 6 / GATHER_RPS         # 2 slabs, 6 SpMMs
    t_dense = dense_blocks * 6 * (
        (tile * width * isz + tile * tile / 8) / HBM_BPS
        + 2 * tile * tile * width / MXU)
    t_ici = tx_bytes / 400e9                        # per-direction link
    t_dev = t_rem + t_dense
    # calibration: the same cost model predicts 1.12 s for the P=1
    # configuration that MEASURES 1.59 s on the chip (docs/PERF_NOTES),
    # so projections are scaled by that measured/model ratio
    CALIB = 1.59 / 1.12
    t_dev = t_dev * CALIB
    proj = float(t_dev.max())

    lines = [
        f"# Multi-chip projection ({P}-way METIS, {args.dataset})",
        "",
        "One v5e chip is available; this projects the multi-chip epoch "
        "from a REAL partition of the benchmark graph plus the "
        "v5e-calibrated cost model (docs/PERF_NOTES.md), scaled by the "
        "model's measured single-chip miss (x1.42: it predicts 1.12 s "
        "where the chip measures 1.59 s). The sharded program itself is "
        "validated on the virtual CPU mesh (dryrun_multichip, tests/).",
        "",
        "| device | inner nodes | edges | halo rows | send rows/layer | "
        "dense cov | est ICI MB/epoch | est epoch s |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in range(P):
        lines.append(
            f"| {r} | {inner[r]:,} | {edges[r]:,} | {halos[r]:,} "
            f"| {send[r]:,} | {cov[r]:.2f} | {tx_bytes[r]/2**20:.0f} "
            f"| {t_dev[r]:.3f} |")
    lines += [
        "",
        f"Projected epoch (max device, comm overlapped): **{proj:.3f} s**"
        + (f" vs 1.59 s measured single-chip — {1.59/proj:.1f}x scaling "
           f"at P={P}." if args.dataset == "synthetic-reddit" else "."),
        f"Worst-case exposed-ICI floor if NOTHING overlapped: "
        f"{float(t_ici.max()):.4f} s "
        f"({100*float(t_ici.max())/proj:.1f}% of the projected epoch) — "
        "the pipelined design exists to hide exactly this term "
        "(results/overlap_study.md shows all pipelined exchanges leave "
        "the critical path).",
        "",
        f"Reference baseline: 0.266 s/epoch on 2 GPUs; the projection "
        f"crosses it at P={P} if {proj:.3f} <= 0.266 "
        f"({'yes' if proj <= 0.266 else 'no'}).",
    ]
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        f.write("\n".join(lines) + "\n")
    print("\n".join(lines))


if __name__ == "__main__":
    main()
