#!/usr/bin/env python
"""Attribute the non-SpMM epoch floor by config ablation on the chip.

The probe-traffic decomposition (results/probe_traffic_tpu_g1.json)
puts the SpMM terms at 0.982 s of the measured 1.5006 s epoch; the
remaining 0.518 s floor covers linears, norms, dropout RNG, fbuf
assembly and dispatch. This script times the SAME production config
with one ingredient removed at a time — the deltas attribute the
floor to its parts so the next kernel/layout lever targets the right
term (the reference has no analogue; this is perf tooling for the
driver headline, reference README.md:93-94).

Variants: baseline (block-u4-float8, the headline config) |
dropout=0 (no RNG, no mask traffic) | norm=None (no LayerNorm
fwd/bwd) | n_linear tail only dispatch floor probe: fused=1 vs 4.

The ablation clock itself lives in pipegcn_tpu/obs/anatomy.py
(`time_config` / `time_variants`) next to the structural HLO
attribution (`step_anatomy`, the CLI's --anatomy flag); this script is
the chip-window wrapper that picks the headline config's variants and
writes results/epoch_anatomy.json.

Usage: python scripts/epoch_anatomy.py [--part ...] [--reps 3]
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--part",
                    default="partitions/bench-reddit-1-c2-s1024")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--blk", type=int, default=4)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--out", default="results/epoch_anatomy.json")
    args = ap.parse_args()

    from bench import init_backend

    backend = init_backend(1, 60.0, args.cpu)
    import dataclasses

    import jax

    if backend.startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")

    from pipegcn_tpu.models import ModelConfig
    from pipegcn_tpu.parallel import TrainConfig

    # partitions/ is not git-tracked and vanishes between rounds;
    # ensure() rebuilds host-side (no jax) rather than failing the step
    from pipegcn_tpu.partition.bench_artifact import ensure

    if not os.path.isabs(args.part):
        args.part = os.path.join(REPO, args.part)
    sg = ensure(args.part, log=lambda m: print(m, file=sys.stderr))
    base = ModelConfig(
        layer_sizes=(sg.n_feat, 256, 256, 256, sg.n_class),
        use_pp=True, norm="layer", dropout=0.5,
        train_size=sg.n_train_global, spmm_chunk=2_097_152,
        dtype="bfloat16", spmm_impl="block", block_group=4,
        rem_dtype="float8")
    tcfg = TrainConfig(lr=0.01, n_epochs=200, enable_pipeline=True,
                       eval=False, fused_epochs=args.blk, seed=0)

    variants = [
        ("baseline", base, tcfg),
        ("dropout0", dataclasses.replace(base, dropout=0.0), tcfg),
        ("no-norm", dataclasses.replace(base, norm=None), tcfg),
        # combined leg: if its delta ~= dropout0 + no-norm deltas the
        # floor decomposes additively and the un-ablatable rest
        # (linears/loss/opt/assembly) is baseline - combined - dispatch
        ("dropout0-no-norm",
         dataclasses.replace(base, dropout=0.0, norm=None), tcfg),
        # fast-RNG lever: if this recovers most of the dropout0 delta,
        # --rng-impl rbg is a production win with dropout kept at 0.5
        ("rbg", base, dataclasses.replace(tcfg, rng_impl="rbg")),
        ("fused1", base, dataclasses.replace(tcfg, fused_epochs=1)),
    ]
    from pipegcn_tpu.obs.anatomy import time_config

    rec = {"backend": jax.default_backend()}
    base_s = None
    for name, cfg, tc in variants:
        blk = tc.fused_epochs
        s, setup, comp = time_config(sg, cfg, tc, args.reps, blk)
        rec[name] = round(s, 4)
        delta = "" if base_s is None else f" (delta {s - base_s:+.4f})"
        base_s = base_s if base_s is not None else s
        print(f"# {name}: {s:.4f} s/epoch{delta} "
              f"(setup {setup:.0f}s compile {comp:.0f}s)", flush=True)

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
