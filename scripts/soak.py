#!/usr/bin/env python
"""Seeded full-stack chaos soak driver (resilience/soak.py).

    python scripts/soak.py --seed 0 --episodes 5

Each episode composes a deterministic fault schedule (terminal kills /
sigterms / crashes on checkpoint boundaries, in-process faults, the
storage kinds enospc / torn-write / ro-dir / slow-fs, a streaming
delta) from the episode seed, runs an elastic-supervised trainer and a
final clean --resume, and checks the six invariants documented in
resilience/soak.py — the sixth runs the automated postmortem
(obs/postmortem.py) over every episode and demands the right verdict
(clean-exit on green, a schedule-consistent class on red); the summary
reports the matched fraction as ``diagnosis_accuracy``. Same seed ->
same schedules -> same verdict.

The storage-fault acceptance proof (epoch 5 lands AFTER seed-0
episode 0's kill@4, so the armed window spans the epoch-6 checkpoint
save in the relaunched generation — a fault entry at-or-before a
terminal fault's epoch is retired by the resume's skip_before and
never arms):

    python scripts/soak.py --seed 0 --episodes 1 --force-fault enospc@5

Exit status: 0 when every episode is green, 1 otherwise. The per-
episode records land in <out-dir>/soak-seed<seed>.json and (schema-
contracted ``soak`` events) soak-seed<seed>.jsonl.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from pipegcn_tpu.resilience.soak import SoakConfig, run_soak  # noqa: E402


def main(argv=None) -> int:
    d = SoakConfig()
    ap = argparse.ArgumentParser(
        description="seeded chaos soak over the elastic trainer")
    ap.add_argument("--seed", type=int, default=d.seed)
    ap.add_argument("--episodes", type=int, default=d.episodes)
    ap.add_argument("--n-epochs", type=int, default=d.n_epochs)
    ap.add_argument("--checkpoint-every", type=int,
                    default=d.checkpoint_every)
    ap.add_argument("--out-dir", default=d.out_dir)
    ap.add_argument("--dataset", default=d.dataset)
    ap.add_argument("--force-fault", action="append", default=[],
                    help="fault entry prepended verbatim to EVERY "
                         "episode's schedule (repeatable), e.g. "
                         "'enospc@4'")
    ap.add_argument("--serve", action="store_true",
                    help="add the serving-fleet ticket-conservation "
                         "drill to each episode")
    ap.add_argument("--autoscale", action="store_true",
                    help="add the closed-loop autoscale drill (flash-"
                         "crowd + replica-kill + mid-crowd net-"
                         "partition; invariant #7) to each episode")
    ap.add_argument("--integrity", action="store_true",
                    help="add the silent-data-corruption drill (one "
                         "seeded bitflip per episode, pipeline on, "
                         "--integrity-check-every; invariant #8)")
    ap.add_argument("--integrity-every", type=int,
                    default=d.integrity_every,
                    help="integrity-check cadence used by the SDC "
                         "drill")
    ap.add_argument("--max-restarts", type=int, default=d.max_restarts)
    ap.add_argument("--episode-timeout", type=float,
                    default=d.episode_timeout_s)
    ap.add_argument("--keep-dirs", action="store_true",
                    help="keep green episode dirs (red ones are "
                         "always kept)")
    a = ap.parse_args(argv)
    cfg = SoakConfig(
        seed=a.seed, episodes=a.episodes, n_epochs=a.n_epochs,
        checkpoint_every=a.checkpoint_every, out_dir=a.out_dir,
        dataset=a.dataset, force_faults=tuple(a.force_fault),
        serve=a.serve, autoscale=a.autoscale,
        integrity=a.integrity, integrity_every=a.integrity_every,
        max_restarts=a.max_restarts,
        episode_timeout_s=a.episode_timeout, keep_dirs=a.keep_dirs)
    summary = run_soak(cfg)
    return 0 if summary["verdict"] == "green" else 1


if __name__ == "__main__":
    sys.exit(main())
