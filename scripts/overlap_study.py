"""Comm/compute overlap evidence (VERDICT round-1 item 3).

PipeGCN's reason to exist is hiding halo-exchange latency behind compute
(reference feature_buffer.py:153-163; README.md:93-94 reports exposed
comm ~5.9% of epoch on 2 GPUs). In this framework the pipelined step
carries last epoch's halo blocks in the step state, so the current
epoch's ppermutes have no consumer inside the step and XLA is free to
schedule them behind the GEMMs/aggregations.

This study quantifies that on an N-device mesh (virtual CPU devices by
default — the one real TPU chip cannot run a >1-device mesh, so the
multi-device scheduling evidence comes from the CPU backend; the
single-chip pipelined-vs-vanilla delta at Reddit scale is reported by
bench.py separately):

  vanilla epoch      — halo exchange is a data dependency of every layer
  pipelined epoch    — same collectives, dependency broken by staleness
  collectives alone  — Trainer.measure_comm's standalone cost

If the pipelined epoch time is ~= vanilla - collective cost, the
exchange is being hidden; if it's ~= vanilla, XLA serialized it.
Writes results/overlap_study.md.

Usage:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/overlap_study.py [--parts 8] [--tpu]
"""

import argparse
import contextlib
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _collective_matmul_deps(hlo: str):
    """Count collective-permutes in the optimized HLO whose results are
    (transitively) consumed by a dot — i.e. whose latency sits on the
    critical path into compute. Pipelined programs should have ZERO:
    their fresh exchanges flow only into the output carry, so any
    scheduler may hide them behind the epoch's compute; vanilla
    programs' exchanges all feed the layer matmuls.

    Works on the def-use structure (backend-independent), fusion bodies
    included via a contains-dot check per called computation."""
    import re

    comp_has_dot = {}
    name = None
    for line in hlo.splitlines():
        m = re.match(r"\s*(?:ENTRY )?%?([\w.\-]+)\s*\([^)]*\)\s*->", line)
        if m:
            name = m.group(1)
            comp_has_dot.setdefault(name, False)
        if name and re.search(r"\bdot\(", line):
            comp_has_dot[name] = True

    instr = {}       # name -> (op, [operand names], line)
    users = {}       # name -> [user names]
    for line in hlo.splitlines():
        # result type is either a plain shape or a tuple type with
        # spaces — async ops like collective-permute-start return
        # '(bf16[..], bf16[..])', which a bare \S+ would fail to span
        m = re.match(
            r"\s*(?:ROOT )?%?([\w.\-]+) = (?:\([^=]*?\)|\S+) "
            r"([\w\-]+)\((.*)", line)
        if not m:
            continue
        nm, op, rest = m.groups()
        operands = re.findall(r"%([\w.\-]+)", rest)
        instr[nm] = (op, operands, line)
        for o in operands:
            users.setdefault(o, []).append(nm)

    n_coll, n_feeding = 0, 0
    for nm, (op, _, line) in instr.items():
        if not op.startswith("collective-permute"):
            continue
        if op == "collective-permute-done":
            continue  # counted via its start
        n_coll += 1
        # BFS through users; fusions count as dots if their body has one
        seen, stack, feeds = set(), [nm], False
        while stack and not feeds:
            cur = stack.pop()
            for u in users.get(cur, []):
                if u in seen:
                    continue
                seen.add(u)
                uop, _, uline = instr[u]
                if uop == "dot":
                    feeds = True
                    break
                if uop == "fusion":
                    cm = re.search(r"calls=%?([\w.\-]+)", uline)
                    if cm and comp_has_dot.get(cm.group(1)):
                        feeds = True
                        break
                stack.append(u)
        n_feeding += int(feeds)
    return n_coll, n_feeding


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--parts", type=int, default=8)
    ap.add_argument("--nodes", type=int, default=60_000)
    ap.add_argument("--degree", type=int, default=30)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--epochs", type=int, default=24)
    ap.add_argument("--out", default="results/overlap_study.md")
    ap.add_argument("--tpu", action="store_true")
    args = ap.parse_args()

    import jax

    if not args.tpu:
        jax.config.update("jax_platforms", "cpu")

    from pipegcn_tpu.graph import synthetic_graph
    from pipegcn_tpu.models import ModelConfig
    from pipegcn_tpu.parallel import Trainer, TrainConfig
    from pipegcn_tpu.parallel.halo import identity_collectives
    from pipegcn_tpu.partition import ShardedGraph, partition_graph

    g = synthetic_graph(num_nodes=args.nodes, avg_degree=args.degree,
                        n_feat=64, n_class=16, homophily=0.5, seed=0)
    parts = partition_graph(g, args.parts, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=args.parts)
    halo_frac = sg.halo_size / max(sg.n_max, 1)
    print(f"# {args.parts} parts, n_max {sg.n_max}, halo {sg.halo_size} "
          f"({halo_frac:.1%} of inner)", file=sys.stderr)

    cfg = ModelConfig(
        layer_sizes=(sg.n_feat, args.hidden, args.hidden, sg.n_class),
        use_pp=False, norm="layer", dropout=0.3,
        train_size=sg.n_train_global, dtype="bfloat16",
    )

    def run(pipeline: bool, identity: bool = False):
        guard = identity_collectives() if identity \
            else contextlib.nullcontext()
        with guard:
            return _run_timed(pipeline, identity)

    def _run_timed(pipeline: bool, identity: bool = False):
        t = Trainer(sg, cfg, TrainConfig(
            lr=1e-2, n_epochs=args.epochs, enable_pipeline=pipeline,
            seed=0, eval=False))
        base = t._epoch_rng_base()
        rng0 = jax.random.fold_in(base, 0)
        # one AOT compile serves both the HLO inspection and the timed
        # epochs (calling through t.train_epoch would compile a second
        # time via the jit cache)
        import jax.numpy as jnp

        scale = jnp.float32(t.loss_scaler.scale)
        compiled = t._step.lower(t.state, t.data, rng0,
                                 scale).compile()
        hlo = compiled.as_text()
        state = t.state
        state, _ = compiled(state, t.data, rng0, scale)
        jax.block_until_ready(state["params"])
        times = []
        for e in range(1, args.epochs):
            rng = jax.random.fold_in(base, e)
            t0 = time.perf_counter()
            state, _ = compiled(state, t.data, rng, scale)
            jax.block_until_ready(state["params"])
            times.append(time.perf_counter() - t0)
        t.state = state
        # identity legs would time elided no-op collectives — skip
        comm = t.measure_comm() if pipeline and not identity else None
        return float(np.median(times)), comm, hlo

    pipe_s, comm, pipe_hlo = run(True)
    van_s, _, van_hlo = run(False)
    # exposed-wait legs: the SAME programs traced with the ring
    # ppermutes replaced by identity (shapes intact) — the timing
    # delta is the comm wait the scheduler failed to hide, i.e. the
    # reference's per-epoch Comm(s) semantics (train.py:366-371)
    pipe_id_s, _, _ = run(True, identity=True)
    van_id_s, _, _ = run(False, identity=True)
    exposed_pipe = max(0.0, pipe_s - pipe_id_s)
    exposed_van = max(0.0, van_s - van_id_s)
    overlap_pct = (100.0 * (1.0 - exposed_pipe / exposed_van)
                   if exposed_van > 0 else float("nan"))
    pipe_dep = _collective_matmul_deps(pipe_hlo)
    van_dep = _collective_matmul_deps(van_hlo)
    coll_s = comm["comm"] + comm["bgrad"]  # fwd ring + cotangent ring

    backend = jax.default_backend()
    lines = [
        "# Comm/compute overlap study",
        "",
        f"{args.parts}-device mesh ({backend}), "
        f"{args.nodes} nodes / avg degree {args.degree}, "
        f"3-layer x {args.hidden} GraphSAGE bf16, halo "
        f"{halo_frac:.1%} of inner rows, median over {args.epochs - 1} "
        "epochs.",
        "",
        "## Structural evidence (optimized HLO def-use)",
        "",
        "Whether a scheduler CAN hide an exchange is a property of the",
        "program's dependency structure: a collective whose result feeds",
        "a matmul is on the critical path; one that only feeds the",
        "next-epoch staleness carry can be scheduled entirely behind the",
        "epoch's compute (the functional analogue of the reference's",
        "thread-based async transfer, feature_buffer.py:153-163).",
        "",
        "| program | collective-permutes | feeding a dot (blocking) |",
        "|---|---|---|",
        f"| vanilla | {van_dep[0]} | {van_dep[1]} |",
        f"| pipelined | {pipe_dep[0]} | {pipe_dep[1]} |",
        "",
        ("All pipelined exchanges are OFF the critical path (zero dot "
         "consumers): XLA's latency-hiding scheduler is free to run "
         "them behind compute on TPU, so the design target is the "
         "reference's <6% exposed comm (README.md:93-94) with headroom "
         "to 0%."
         if pipe_dep[0] > 0 and pipe_dep[1] == 0 and van_dep[1] > 0 else
         "WARNING: measured dependency counts do NOT show the expected "
         "pattern (vanilla blocking > 0, pipelined blocking == 0) — "
         "either the dataflow regressed or the HLO parser missed ops; "
         "investigate before citing this study."),
        "",
        "## Wall-clock on the virtual CPU mesh (context only)",
        "",
        "| measurement | s/epoch |",
        "|---|---|",
        f"| vanilla (synchronous halo) | {van_s:.4f} |",
        f"| pipelined (staleness-1) | {pipe_s:.4f} |",
        f"| halo collectives alone | {coll_s:.4f} |",
        f"| vanilla, permutes->identity | {van_id_s:.4f} |",
        f"| pipelined, permutes->identity | {pipe_id_s:.4f} |",
        "",
        "## Exposed wait (timing-derived, reference Comm(s) semantics)",
        "",
        "Re-tracing each program with the ring ppermutes replaced by",
        "identity (same shapes, zero traffic) and differencing the",
        "epoch times yields the comm wait each schedule actually",
        "EXPOSES — the reference's per-epoch Comm(s)",
        "(helper/timer/comm_timer.py, train.py:366-371) — rather than",
        "the standalone collective cost measure_comm reports:",
        "",
        "| program | exposed comm s/epoch | % of epoch |",
        "|---|---|---|",
        f"| vanilla | {exposed_van:.4f} | "
        f"{100.0 * exposed_van / van_s:.1f}% |",
        f"| pipelined | {exposed_pipe:.4f} | "
        f"{100.0 * exposed_pipe / pipe_s:.1f}% |",
        "",
        f"**Overlap: {overlap_pct:.1f}%** of the vanilla exposed wait "
        "is hidden by the pipelined schedule (reference reports ~94% "
        "hidden, i.e. 5.9% exposed, on 2 GPUs — README.md:93-94). "
        "CPU-mesh caveat: collectives here are intra-process copies, "
        "so both exposures are small and noisy; the same two identity "
        "legs run unchanged on a real multi-chip mesh (--tpu), where "
        "this becomes the headline overlap metric.",
        "",
        f"On XLA:CPU the collectives are intra-process copies "
        f"({100.0 * coll_s / van_s:.1f}% of the vanilla epoch), far "
        "below the staleness carry's own bookkeeping cost, so CPU",
        "wall-clock cannot demonstrate the hiding — the structural",
        "table above is the meaningful evidence. The real TPU",
        "environment has ONE chip (P=1 has no collectives to hide);",
        "bench.py reports the single-chip pipelined-vs-vanilla delta",
        "at Reddit scale separately.",
    ]
    out_dir = os.path.dirname(args.out)
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    with open(args.out, "w") as f:
        f.write("\n".join(lines) + "\n")
    print("\n".join(lines))


if __name__ == "__main__":
    main()
