#!/usr/bin/env python
"""Attribute the GAT bucket kernel's epoch between its passes.

Full-scale GAT measures 38.4 s/epoch (fp8) vs the SAGE headline's
1.30 s (results/gat_tpu_bench.md) — ~6x slower per gather pass than
the SAGE bucket kernel on the same formulation. This times, on one
graph: (a) GAT forward (2 gather passes/edge-slot), (b) GAT
fwd+bwd (6 passes), (c) the SAGE bucket mean kernel fwd / fwd+bwd
(1 / 3 passes) as the rate reference. The per-pass ratio decides the
fix: if GAT passes run at bucket rates, the cost is pass COUNT (pack
el into the z slab, stats into one table); if they are intrinsically
slower, the [r, D, H] attention elementwise or scan structure is the
target.

Tables ride as jit ARGUMENTS (axon remote-compile 413 lesson,
scripts/spmm_microbench.py).

Usage: python scripts/gat_microbench.py [--dataset synthetic:60000:30:602:41]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="synthetic:60000:30:602:41")
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--rem-dtype", default="float8")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    from bench import init_backend

    backend = init_backend(1, 60.0, args.cpu)
    import jax
    import jax.numpy as jnp

    if backend.startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")

    from pipegcn_tpu.models import ModelConfig
    from pipegcn_tpu.parallel import Trainer, TrainConfig
    from pipegcn_tpu.partition import (ShardedGraph, locality_clusters,
                                       partition_graph)
    from pipegcn_tpu.graph import load_data

    part_path = os.path.join(
        "partitions",
        "gat-" + args.dataset.replace(":", "_") + "-c-s1024")
    if ShardedGraph.exists(part_path):
        sg = ShardedGraph.load(part_path)
    else:
        g = load_data(args.dataset)
        parts = partition_graph(g, 1, seed=0)
        cluster = locality_clusters(g, target_size=1024, seed=0)
        sg = ShardedGraph.build(g, parts, n_parts=1, cluster=cluster)
        sg.save(part_path)
        sg.cache_dir = part_path

    H, dh = args.heads, args.hidden // args.heads
    R = sg.n_max + sg.halo_size
    n_dst = sg.n_max
    rd = None if args.rem_dtype in ("none", "") else args.rem_dtype

    # --- GAT tables through the trainer cache ---------------------------
    gat_cfg = ModelConfig(
        layer_sizes=(sg.n_feat, args.hidden, args.hidden, sg.n_class),
        model="gat", n_heads=H, train_size=sg.n_train_global,
        spmm_impl="bucket", spmm_chunk=2_097_152, dtype="bfloat16",
        rem_dtype=rd)
    tr = Trainer(sg, gat_cfg, TrainConfig(lr=0.01, n_epochs=1,
                                          eval=False))
    gat_d = {k: v[0] for k, v in tr.data.items()
             if k.startswith("gat_")}

    from pipegcn_tpu.ops.gat_bucket import make_device_gat_fn

    rng = np.random.default_rng(0)
    z = jnp.asarray(rng.standard_normal((R, H, dh)).astype(np.float32)
                    ).astype(jnp.bfloat16)
    el = jnp.asarray(rng.standard_normal((R, H)).astype(np.float32))
    er = jnp.asarray(rng.standard_normal((n_dst, H)).astype(np.float32))

    def timed(g_fn, ops, label):
        g_fn(*ops)  # compile
        float(jnp.sum(g_fn(*ops)[0]))
        ts = []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            float(jnp.sum(g_fn(*ops)[0]))
            ts.append(time.perf_counter() - t0)
        print(f"# {label:16s} {min(ts)*1e3:9.1f} ms", flush=True)
        return min(ts)

    def gat_apply(tables, zz, ee, rr):
        fn = make_device_gat_fn(tables, n_dst, R, H,
                                gat_cfg.leaky_slope,
                                chunk_edges=gat_cfg.spmm_chunk,
                                rem_dtype=rd)
        return fn(zz, ee, rr)

    gat_fwd = jax.jit(gat_apply)

    @jax.jit
    def gat_both(tables, zz, ee, rr):
        def loss(zz_, ee_, rr_):
            return gat_apply(tables, zz_, ee_, rr_).sum()
        return jax.grad(loss, argnums=(0, 1, 2))(zz, ee, rr)

    rec = {"backend": jax.default_backend(), "rem_dtype": args.rem_dtype,
           "edges": int(sg.edge_count.sum())}
    rec["gat_fwd_s"] = timed(gat_fwd, (gat_d, z, el, er), "gat fwd")
    rec["gat_fwdbwd_s"] = timed(gat_both, (gat_d, z, el, er),
                                "gat fwd+bwd")

    # --- SAGE bucket mean kernel on the same graph (rate reference) ----
    sage_cfg = ModelConfig(
        layer_sizes=(sg.n_feat, args.hidden, args.hidden, sg.n_class),
        train_size=sg.n_train_global, spmm_impl="bucket",
        spmm_chunk=2_097_152, dtype="bfloat16", rem_dtype=rd)
    tr2 = Trainer(sg, sage_cfg, TrainConfig(lr=0.01, n_epochs=1,
                                            eval=False))
    buck_d = {k: v[0] for k, v in tr2.data.items()
              if k.startswith("bkt_")}
    if buck_d:
        from pipegcn_tpu.ops.bucket_spmm import (
            make_device_bucket_spmm_fn)

        fbuf = jnp.asarray(rng.standard_normal((R, args.hidden))
                           .astype(np.float32)).astype(jnp.bfloat16)
        in_deg = tr2.data["in_deg"][0]

        def bucket_apply(tables, ind, f):
            fn = make_device_bucket_spmm_fn(
                tables, ind, R, rem_dtype=rd)
            return fn(f)

        b_fwd = jax.jit(bucket_apply)

        @jax.jit
        def b_both(tables, ind, f):
            return jax.grad(
                lambda ff: bucket_apply(tables, ind, ff)
                .astype(jnp.float32).sum())(f)

        rec["bucket_fwd_s"] = timed(
            b_fwd, (buck_d, in_deg, fbuf), "bucket fwd")
        rec["bucket_fwdbwd_s"] = timed(
            lambda t, i, f: (b_both(t, i, f),),
            (buck_d, in_deg, fbuf), "bucket fwd+bwd")
        # per-pass rates: gat fwd = 2 passes, fwd+bwd = 6;
        # bucket fwd = 1, fwd+bwd = 3
        rec["gat_pass_s"] = rec["gat_fwdbwd_s"] / 6
        rec["bucket_pass_s"] = rec["bucket_fwdbwd_s"] / 3
        print(f"# per-pass: gat {rec['gat_pass_s']*1e3:.1f} ms vs "
              f"bucket {rec['bucket_pass_s']*1e3:.1f} ms "
              f"(x{rec['gat_pass_s']/rec['bucket_pass_s']:.1f})",
              flush=True)

    # --- narrow-row gather-rate curve ----------------------------------
    # The attention kernel's per-edge el/stat gathers fetch 8-16 B rows
    # (H=4 bf16/f32) — far below the 256 B slab the SAGE cliff analysis
    # covered. If the request rate collapses at sub-32 B rows, the GAT
    # fix is packing el/stats into the wide z slabs (one request per
    # edge total), not a different softmax. M matches this graph's
    # edge count so the numbers read directly as per-pass seconds.
    M = int(sg.edge_count.sum())
    idx = jnp.asarray(rng.integers(0, R, size=M).astype(np.int32))

    @jax.jit
    def flat_gather(tbl, ii):
        return (jnp.take(tbl, ii, axis=0).astype(jnp.float32).sum(0),)

    rec["narrow_gather"] = {}
    for elems, dt, tag_w in ((4, jnp.bfloat16, "8B"),
                             (4, jnp.float32, "16B"),
                             (16, jnp.bfloat16, "32B"),
                             (64, jnp.bfloat16, "128B"),
                             (128, jnp.bfloat16, "256B")):
        tbl = jnp.asarray(
            rng.standard_normal((R, elems)).astype(np.float32)).astype(dt)
        t = timed(flat_gather, (tbl, idx), f"gather {tag_w}-rows")
        rec["narrow_gather"][tag_w] = {
            "s": t, "rows_per_s": M / t if t > 0 else None}

    tag = f"{jax.default_backend()}_{args.rem_dtype}"
    out = os.path.join(REPO, "results", f"gat_microbench_{tag}.json")
    with open(out, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
