#!/usr/bin/env python
"""Attribute the remainder (slab-gather) SpMM term on the real chip.

Round 4 measured the remainder at ~230M padded slab rows/s inside the
full program — ~60% of the isolated row-gather cliff rate (~400M rows/s
at 256-byte rows, docs/PERF_NOTES.md). This probe decomposes the gap by
running the production remainder (bucket ladder over the Reddit-scale
block plan's spill edges) in surgical variants, same shapes throughout:

  anchor   flat jnp.take of the same number of padded rows at the same
           row width — the cliff-rate anchor, measured in-session
  rem      production path: transport_cast(bf16->fp8) + bucket ladder
  nocast   ladder only, fbuf pre-cast outside the jit (cast share)
  idx0     index mats zeroed — every gather hits row 0, collapsing the
           gather's HBM traffic but keeping launches/pads/sums/concat
           (structure share)
  noinv    inv_perm zeroed (the final restore-order gather's share)
  chunk-*  chunk_edges sweep (scan-chunking overhead share)
  bf16     the 2-slab bf16 transport for reference

Verdict logic: if `rem` per-row rate ~= `anchor` rate, the 60% figure
was contention with the dense path inside the full program (fix =
program-level reordering); if `rem` is itself slow and `idx0` is fast,
it's genuine gather traffic (fix = Pallas slab-gather with pipelined
DMA, docs/PERF_NOTES.md design); if `idx0` is also slow, it's ladder
structure (launches/pad/concat — fix = fewer/merged buckets).

Replaces: the timing side of the reference's aggregation hot loop
(module/layer.py:47-49) — this is framework diagnostics, no reference
counterpart.

Usage: python scripts/rem_probe.py [--part partitions/...]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--part",
                    default="partitions/bench-reddit-1-c2-s1024")
    ap.add_argument("--width", type=int, default=256)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--group", type=int, default=4)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from pipegcn_tpu.models import ModelConfig
    from pipegcn_tpu.ops.bucket_spmm import (bucket_aggregate,
                                             transport_cast,
                                             transport_dtypes)
    from pipegcn_tpu.parallel import Trainer, TrainConfig

    # partitions/ is not git-tracked and vanishes between rounds;
    # ensure() rebuilds host-side (no jax) rather than failing the step
    from pipegcn_tpu.partition.bench_artifact import ensure

    if not os.path.isabs(args.part):
        args.part = os.path.join(REPO, args.part)
    sg = ensure(args.part, log=lambda m: print(m, file=sys.stderr))
    cfg = ModelConfig(
        layer_sizes=(sg.n_feat, 256, 256, 256, sg.n_class),
        use_pp=True, norm="layer", dropout=0.5,
        train_size=sg.n_train_global, spmm_chunk=2_097_152,
        dtype="bfloat16", spmm_impl="block", block_group=args.group,
        rem_dtype="float8",
    )
    tr = Trainer(sg, cfg, TrainConfig(lr=0.01, n_epochs=1, eval=False))
    d = {k: v[0] for k, v in tr.data.items()}
    n_src = sg.n_max + sg.halo_size
    fp8, _ = transport_dtypes("float8")

    keys = sorted(k for k in d
                  if k.startswith("blkrem_fwd_") and not k.endswith("inv"))
    mats = [d[k] for k in keys]
    inv = d["blkrem_fwd_inv"]
    # real gathered rows per call: bucket tables are row-padded to
    # shared caps; padded rows gather the sentinel, so they cost a
    # request too — count the full table extent
    padded_rows = int(sum(int(m.shape[0]) * int(m.shape[1])
                          for m in mats))
    print(f"# remainder fwd tables: {len(mats)} buckets, "
          f"{padded_rows/1e6:.1f}M padded rows/SpMM", file=sys.stderr)

    rng = np.random.default_rng(0)
    fbuf = jnp.asarray(
        rng.standard_normal((n_src, args.width)).astype(np.float32)
    ).astype(jnp.bfloat16)
    fbuf8 = transport_cast(fbuf, fp8)
    zero_mats = [jnp.zeros_like(m) for m in mats]
    zero_inv = jnp.zeros_like(inv)

    def timed(fn, ops, label, rows):
        jfn = jax.jit(fn)
        float(jnp.sum(jfn(*ops)))  # compile + settle
        float(jnp.sum(jfn(*ops)))
        ts = []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            float(jnp.sum(jfn(*ops)))
            ts.append(time.perf_counter() - t0)
        t = min(ts)
        print(f"{label:12s} {t*1e3:8.1f} ms  "
              f"{rows/t/1e6:7.0f} M rows/s", file=sys.stderr)
        return t

    res = {"backend": jax.default_backend(), "group": args.group,
           "padded_rows": padded_rows}

    # cliff-rate anchor: one flat gather of the same row count from the
    # same fp8 buffer (random uniform indices — same cache behavior
    # class as the ladder's shuffled neighbor ids)
    flat_idx = jnp.asarray(
        rng.integers(0, n_src, size=padded_rows).astype(np.int32))

    def anchor(f8, idx):
        return jnp.take(f8, idx, axis=0).astype(jnp.float32).sum(0)

    res["anchor_s"] = timed(anchor, (fbuf8, flat_idx), "anchor",
                            padded_rows)
    # sorted-index anchor: if ascending requests run much faster than
    # random ones, locality-ordering bucket rows at table build (free,
    # host-side) is a production lever worth a follow-up
    res["anchor_sorted_s"] = timed(
        anchor, (fbuf8, jnp.sort(flat_idx)), "anchor-sort", padded_rows)

    def rem(f, ms, iv):
        return bucket_aggregate(transport_cast(f, fp8), ms, iv,
                                chunk_edges=cfg.spmm_chunk)

    def rem_pre(f8, ms, iv):
        return bucket_aggregate(f8, ms, iv, chunk_edges=cfg.spmm_chunk)

    res["rem_s"] = timed(rem, (fbuf, mats, inv), "rem", padded_rows)
    res["nocast_s"] = timed(rem_pre, (fbuf8, mats, inv), "nocast",
                            padded_rows)
    res["idx0_s"] = timed(rem_pre, (fbuf8, zero_mats, inv), "idx0",
                          padded_rows)
    res["noinv_s"] = timed(rem_pre, (fbuf8, mats, zero_inv), "noinv",
                           padded_rows)

    for ce in (None, 8_388_608):
        def rem_c(f8, ms, iv, ce=ce):
            return bucket_aggregate(f8, ms, iv, chunk_edges=ce)

        res[f"chunk_{ce or 'def'}_s"] = timed(
            rem_c, (fbuf8, mats, inv), f"chunk-{ce or 'def'}",
            padded_rows)

    def rem_bf16(f, ms, iv):
        return bucket_aggregate(f, ms, iv, chunk_edges=cfg.spmm_chunk)

    # bf16 gathers 2 slabs per row
    res["bf16_s"] = timed(rem_bf16, (fbuf, mats, inv), "bf16",
                          2 * padded_rows)

    out = os.path.join(REPO, "results",
                       f"rem_probe_{jax.default_backend()}.json")
    with open(out, "w") as f:
        json.dump(res, f, indent=1)
    print(f"# wrote {out}")


if __name__ == "__main__":
    main()
