#!/usr/bin/env python
"""papers100M-scale end-to-end: partition -> shard -> one pipelined step.

VERDICT r4 item 6: the full-scale 64-part partition existed as metadata
only; no training step had ever run on a full-scale artifact. This
script runs the whole pipeline at the reference's papers100M shape —
111M nodes, 1.6B raw edges (3.2B mirrored), 64 partitions (reference
helper/utils.py:17-30; BASELINE.json multi-host grid) — bounded to one
host's RAM/disk, in resumable stages:

  1. edges    [E, 2] int32 memmap (power-law src + locality windows +
              jumps, the round-4 generator)
  2. parts    64-way METIS-class multilevel partition (native HEM/FM),
              saved this time (round 4's 4-hour result was wiped with
              the workspace)
  3. artifact ShardedGraph.build_chunked -> v3 mmap layout. Features
              are NOT stored (57 GB at F=128 exceeds this host's free
              disk next to the edges): the artifact holds a width-1
              placeholder plus real labels/masks/degrees/topology, and
              the step synthesizes rank features deterministically at
              load (SequentialRunner feat_fn).
  4. step     ONE pipelined training step over all 64 ranks via
              SequentialRunner(compact_halo=True, keep_carry=False) —
              exact epoch-0 semantics (stale buffers are zeros), peak
              RSS = one rank. The cross-rank carry for ALL ranks is
              inherently distributed state (P x layers x 2 x [H, F]),
              which is why multi-epoch full-scale training needs the
              real multi-host mesh, not more host RAM.

Each stage skips itself when its output exists; results/papers_dryrun
.json records per-stage wall + peak RSS.

Usage: nice -n 19 python scripts/papers_full_step.py [--nodes N]
       [--edges E] [--parts 64] [--smoke]   (--smoke = 1/100 scale)
"""

import argparse
import json
import os
import resource
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_FEAT, N_CLASS = 128, 172
TRAIN_FRAC = 0.01


def rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def gen_edges(path, n_nodes, n_edges, chunk=1 << 24):
    """Round-4 distribution (scripts/papers_partition_fullscale.py):
    pareto src skew, 90% +-500k locality window, 10% jumps.
    Written to a temp name and renamed: the skip-if-exists resume must
    never accept a half-filled file."""
    rng = np.random.default_rng(0)
    tmp = path + ".tmp.npy"
    edges = np.lib.format.open_memmap(
        tmp, mode="w+", dtype=np.int32, shape=(n_edges, 2))
    window = max(min(500_000, n_nodes // 8), 1)
    for i0 in range(0, n_edges, chunk):
        m = min(chunk, n_edges - i0)
        src = (rng.pareto(1.5, m) * (n_nodes / 50)).astype(np.int64) \
            % n_nodes
        jump = rng.random(m) < 0.1
        win = rng.integers(-window, window, m)
        dst = np.where(jump, rng.integers(0, n_nodes, m),
                       (src + win) % n_nodes)
        edges[i0:i0 + m, 0] = src.astype(np.int32)
        edges[i0:i0 + m, 1] = dst.astype(np.int32)
    edges.flush()
    del edges
    os.replace(tmp, path)


class _Mirror:
    """Lazy mirrored view over the [E, 2] memmap: rows [0, E) read
    column a, rows [E, 2E) column b — build_chunked touches only
    contiguous slices, so the doubled edge list never hits disk."""

    def __init__(self, edges, a, b):
        self._e = edges
        self._a, self._b = a, b
        self.shape = (2 * edges.shape[0],)
        self.dtype = edges.dtype

    def __len__(self):
        return self.shape[0]

    def __getitem__(self, sl):
        e = self._e.shape[0]
        start, stop, step = sl.indices(self.shape[0])
        assert step == 1
        parts = []
        if start < e:
            parts.append(self._e[start:min(stop, e), self._a])
        if stop > e:
            parts.append(self._e[max(start - e, 0):stop - e, self._b])
        return np.concatenate(parts) if len(parts) > 1 else parts[0]


def node_hash(i0, i1):
    nid = np.arange(i0, i1, dtype=np.uint64)
    x = nid * np.uint64(0x9E3779B97F4A7C15)
    x ^= x >> np.uint64(29)
    x *= np.uint64(0xBF58476D1CE4E5B9)
    x ^= x >> np.uint64(32)
    return x


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=111_000_000)
    ap.add_argument("--edges", type=int, default=1_600_000_000)
    ap.add_argument("--parts", type=int, default=64)
    ap.add_argument("--smoke", action="store_true",
                    help="1/100 scale pipeline check")
    ap.add_argument("--work-dir", default=os.path.join(REPO, "partitions",
                                                       "papers_full"))
    ap.add_argument("--out", default=os.path.join(REPO, "results",
                                                  "papers_dryrun.json"))
    args = ap.parse_args()
    if args.smoke:
        args.nodes //= 100
        args.edges //= 100
        args.work_dir += "_smoke"
        args.out = os.path.join(REPO, "results",
                                "papers_dryrun_smoke.json")

    os.makedirs(args.work_dir, exist_ok=True)
    rec = {}
    if os.path.exists(args.out):
        with open(args.out) as f:
            rec = json.load(f)  # keep extras (balance, step_loss, ...)
        if "first_step_s" in rec and "round3_150k_dryrun" not in rec:
            # the round-3 record measured a 150k-node stand-in; nest it
            # so its step time / RSS can't read as full-scale numbers
            legacy = {k: rec.pop(k) for k in
                      ("dryrun_devices", "first_step_s", "loss",
                       "peak_rss_gb", "note") if k in rec}
            rec["round3_150k_dryrun"] = legacy
    rec.update({
        "nodes": args.nodes, "raw_edges": args.edges,
        "mirrored_adjacency_entries": 2 * args.edges,
        "parts": args.parts, "n_feat": N_FEAT, "n_class": N_CLASS,
    })
    stages = rec.setdefault("stages", {})

    def record(name, t0, **extra):
        stages[name] = {"s": round(time.time() - t0, 1),
                        "peak_rss_gb": round(rss_gb(), 2)}
        rec.update(extra)
        with open(args.out, "w") as f:
            json.dump(rec, f, indent=1)
        print(f"# stage {name}: {stages[name]}", flush=True)
        return rec

    # ---- stage 1: edges ---------------------------------------------
    epath = os.path.join(args.work_dir, "edges.npy")
    if not os.path.exists(epath):
        t0 = time.time()
        gen_edges(epath, args.nodes, args.edges)
        record("gen", t0)
    edges = np.load(epath, mmap_mode="r")

    # ---- stage 2: partition -----------------------------------------
    from pipegcn_tpu.graph.csr import Graph
    from pipegcn_tpu.partition.partitioner import partition_graph

    ppath = os.path.join(args.work_dir, "parts.npy")
    if not os.path.exists(ppath):
        t0 = time.time()
        g_raw = Graph(num_nodes=args.nodes, src=edges[:, 0],
                      dst=edges[:, 1])
        # refine_iters=3 (default 10): round 4 measured the default at
        # ~4 h / 78 GB at this scale for a 1.05 balance; the
        # trainability chain needs the partition to exist more than it
        # needs the last FM sweeps (quality evidence:
        # results/partition_quality.md, run at defaults)
        parts = partition_graph(g_raw, args.parts, method="metis",
                                obj="vol", seed=0, refine_iters=3)
        sizes = np.bincount(parts, minlength=args.parts)
        np.save(ppath + ".tmp.npy", parts.astype(np.int16))
        os.replace(ppath + ".tmp.npy", ppath)
        record("partition", t0,
               balance=round(float(sizes.max() / sizes.mean()), 4))
        del g_raw, parts
    parts = np.load(ppath).astype(np.int32)

    # ---- stage 3: sharded artifact (v3 mmap) ------------------------
    from pipegcn_tpu.partition.halo import ShardedGraph

    apath = os.path.join(args.work_dir, "artifact")
    if not ShardedGraph.exists(apath):
        t0 = time.time()
        n = args.nodes
        nd_dir = os.path.join(args.work_dir, "ndata")
        os.makedirs(nd_dir, exist_ok=True)

        def memmapped(name, dtype, shape, fill):
            # temp-then-rename: skip-if-exists must never accept a
            # half-filled file after an interruption
            p = os.path.join(nd_dir, name + ".npy")
            if not os.path.exists(p):
                arr = np.lib.format.open_memmap(
                    p + ".tmp.npy", mode="w+", dtype=dtype, shape=shape)
                for i0 in range(0, n, 1 << 22):
                    i1 = min(i0 + (1 << 22), n)
                    arr[i0:i1] = fill(i0, i1)
                arr.flush()
                del arr
                os.replace(p + ".tmp.npy", p)
            return np.load(p, mmap_mode="r")

        # labels/splits from a node-id hash (deterministic, no storage
        # beyond the artifact); features are synthesized at step time
        label = memmapped(
            "label", np.int64, (n,),
            lambda a, b: (node_hash(a, b) % np.uint64(N_CLASS))
            .astype(np.int64))
        hsplit = lambda a, b: (node_hash(a, b) >> np.uint64(32)) \
            .astype(np.float64) / 2**32
        train_mask = memmapped("train", bool, (n,),
                               lambda a, b: hsplit(a, b) < TRAIN_FRAC)
        val_mask = memmapped(
            "val", bool, (n,),
            lambda a, b: (hsplit(a, b) >= TRAIN_FRAC)
            & (hsplit(a, b) < 2 * TRAIN_FRAC))
        test_mask = memmapped(
            "test", bool, (n,),
            lambda a, b: (hsplit(a, b) >= 2 * TRAIN_FRAC)
            & (hsplit(a, b) < 3 * TRAIN_FRAC))
        feat = memmapped("feat1", np.float32, (n, 1),
                         lambda a, b: np.zeros((b - a, 1), np.float32))
        if not os.path.exists(os.path.join(nd_dir, "in_deg.npy")):
            # in-degree of the mirrored graph, chunked
            deg = np.zeros(n, np.int64)
            for i0 in range(0, args.edges, 1 << 24):
                sl = slice(i0, min(i0 + (1 << 24), args.edges))
                deg += np.bincount(edges[sl, 0], minlength=n)
                deg += np.bincount(edges[sl, 1], minlength=n)
        in_deg = memmapped("in_deg", np.float32, (n,),
                           lambda a, b: deg[a:b].astype(np.float32))

        g = Graph(
            num_nodes=n,
            src=_Mirror(edges, 0, 1),
            dst=_Mirror(edges, 1, 0),
            ndata={"feat": feat, "label": label,
                   "train_mask": train_mask, "val_mask": val_mask,
                   "test_mask": test_mask, "in_deg": in_deg},
        )
        sg = ShardedGraph.build_chunked(g, parts, n_parts=args.parts)
        # trim_edges: the pareto-hub rank sets e_max ~2.7x the mean
        # edge count, so the padded [64, e_max] stack alone is ~69 GB —
        # more than this host's free disk; trimmed per-rank storage is
        # ~26 GB and is all the sequential step reads anyway
        sg.save(apath, mmap=True, trim_edges=True)
        record("artifact", t0)
        del sg, g
    sg = ShardedGraph.load(apath)
    print(f"# artifact: P={sg.num_parts} n_max={sg.n_max} "
          f"b_max={sg.b_max} e_max={sg.e_max} "
          f"halo(uniform)={sg.halo_size}", flush=True)

    # ---- stage 4: one pipelined step --------------------------------
    import jax

    jax.config.update("jax_platforms", "cpu")

    from pipegcn_tpu.models import ModelConfig
    from pipegcn_tpu.parallel import SequentialRunner, TrainConfig

    t0 = time.time()
    cfg = ModelConfig(
        layer_sizes=(N_FEAT, 128, 128, N_CLASS),
        use_pp=False, norm="layer", dropout=0.5,
        train_size=sg.n_train_global, spmm_impl="bucket",
        # f32 on the CPU host: bf16 is emulated (upcast per op) there
        # and measurably slower; the TPU path keeps bf16
        spmm_chunk=8_388_608, dtype="float32",
    )
    # rbg dropout keys: the threefry mask generation dominated CPU
    # epoch cost in the anatomy smoke (~2x); rbg is the same
    # production lever the TPU floor work uses (TrainConfig.rng_impl)
    tcfg = TrainConfig(lr=0.01, enable_pipeline=True, eval=False,
                       seed=0, rng_impl="rbg")

    def feat_fn(r):
        rng = np.random.default_rng(1000 + r)
        return rng.standard_normal((sg.n_max, N_FEAT)).astype(np.float32)

    run = SequentialRunner(
        sg, cfg, tcfg, feat_fn=feat_fn, compact_halo=True,
        keep_carry=False,
        log=lambda s: print(f"# {s} ({time.time()-t0:.0f}s, "
                            f"rss {rss_gb():.1f} GB)", flush=True))
    print(f"# compact halo: {run.H} rows (vs uniform {sg.halo_size}, "
          f"{sg.halo_size / max(run.H, 1):.1f}x)", flush=True)
    loss = run.run_epoch(
        0, state_path=os.path.join(args.work_dir, "step_state.pkl"))
    rec = record(
        "step", t0,
        step_loss=round(float(loss), 4),
        loss_at_init_expected=round(float(np.log(N_CLASS)), 4),
        compact_halo_rows=int(run.H),
        uniform_halo_rows=int(sg.halo_size),
        note=(
            "full pipelined step over the real 64-part artifact via "
            "SequentialRunner (compact halo, one-shot epoch-0 semantics "
            "— exactness vs the mesh trainer pinned by tests/"
            "test_sequential.py); features synthesized per rank at "
            "load, topology/labels/splits from the saved v3 artifact"))
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
