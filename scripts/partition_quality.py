#!/usr/bin/env python
"""Partitioner quality benchmark (VERDICT round-3 item 5).

The reference rides real METIS through its customized DGL fork
(reference helper/utils.py:132-144); this repo's in-tree multilevel
partitioner (native/partitioner.cpp) replaces it, so its quality needs
quantifying — partition quality directly multiplies ICI bytes and
remainder-gather work at P>1.

No METIS binary exists in this environment, so the benchmark uses
self-contained ground truths instead of a side-by-side run:

  A. 2D grid graphs — the P-way strip cut is analytic ((P-1)*n edges);
     METIS-class partitioners land within ~1.05-1.3x of the optimal
     bisection on grids, so the ratio is an absolute quality scale.
  B. Planted-partition graphs — k communities with a known expected
     inter-community edge count; a good partitioner recovers ~the
     planted cut.
  C. The bench Reddit-shape graph (232,965 nodes / ~114.6M directed
     edges): halo rows per device and estimated ICI bytes at
     P in {2, 8, 40} (--bench-graph; slow, run in background).

Writes/updates results/partition_quality.md.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def grid_graph(n):
    from pipegcn_tpu.graph.csr import Graph

    ii, jj = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    nid = ii * n + jj
    right = np.stack([nid[:, :-1].ravel(), nid[:, 1:].ravel()])
    down = np.stack([nid[:-1, :].ravel(), nid[1:, :].ravel()])
    und = np.concatenate([right, down], axis=1)
    src = np.concatenate([und[0], und[1]])
    dst = np.concatenate([und[1], und[0]])
    return Graph(num_nodes=n * n, src=src, dst=dst)


def planted_graph(k, nodes_per, deg_in, deg_out, seed=0):
    """k communities; expected planted (undirected) cut =
    k * nodes_per * deg_out / 2 inter-community edges."""
    from pipegcn_tpu.graph.csr import Graph

    rng = np.random.default_rng(seed)
    n = k * nodes_per
    comm = np.repeat(np.arange(k), nodes_per)
    e_in = k * nodes_per * deg_in // 2
    e_out = k * nodes_per * deg_out // 2
    # intra: both endpoints in one community
    c = rng.integers(0, k, e_in)
    s = rng.integers(0, nodes_per, e_in) + c * nodes_per
    d = rng.integers(0, nodes_per, e_in) + c * nodes_per
    # inter: endpoints in distinct communities
    cs = rng.integers(0, k, e_out)
    cd = (cs + rng.integers(1, k, e_out)) % k
    s2 = rng.integers(0, nodes_per, e_out) + cs * nodes_per
    d2 = rng.integers(0, nodes_per, e_out) + cd * nodes_per
    src = np.concatenate([s, d, s2, d2])
    dst = np.concatenate([d, s, d2, s2])
    return Graph(num_nodes=n, src=src, dst=dst), comm, e_out


def halo_rows_per_device(src, dst, parts, P, chunk=20_000_000):
    """Distinct foreign source rows each device receives (the per-layer
    exchange payload), computed chunked over the edge list."""
    pair_sets = [None] * P
    for i in range(0, src.shape[0], chunk):
        s, d = src[i:i + chunk], dst[i:i + chunk]
        ps, pd = parts[s], parts[d]
        m = ps != pd
        key = pd[m].astype(np.int64) * parts.shape[0] + s[m]
        for r in range(P):
            sel = key[key // parts.shape[0] == r] % parts.shape[0]
            u = np.unique(sel)
            pair_sets[r] = u if pair_sets[r] is None else \
                np.union1d(pair_sets[r], u)
    return np.array([0 if u is None else u.shape[0] for u in pair_sets])


def bench_graph_section(P_list, f_hidden=256, n_exchange_layers=3):
    from pipegcn_tpu.graph.datasets import load_data
    from pipegcn_tpu.partition.partitioner import (
        partition_graph, edge_cut, comm_volume)

    g = load_data("synthetic-reddit")
    rows = []
    for P in P_list:
        t0 = time.time()
        parts = partition_graph(g, P, seed=0)
        t_part = time.time() - t0
        cut = edge_cut(g, parts)
        vol = comm_volume(g, parts)
        halo = halo_rows_per_device(np.asarray(g.src), np.asarray(g.dst),
                                    parts, P)
        # per-epoch ICI estimate: every exchanged layer moves each halo
        # row's features fwd + its cotangent bwd, bf16
        ici = int(halo.sum()) * f_hidden * 2 * 2 * n_exchange_layers
        rows.append(dict(P=P, cut=int(cut), vol=int(vol),
                         halo_min=int(halo.min()), halo_max=int(halo.max()),
                         halo_mean=float(halo.mean()),
                         est_ici_bytes_per_epoch=ici,
                         partition_s=round(t_part, 1)))
        print(f"# bench-shape P={P}: cut={cut} vol={vol} "
              f"halo/device mean={halo.mean():.0f} "
              f"max={halo.max()} t={t_part:.0f}s", flush=True)
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bench-graph", action="store_true",
                    help="also run the Reddit-shape halo/ICI section "
                         "(slow: partitions a 114M-edge graph 3x)")
    ap.add_argument("--parts", type=int, nargs="*", default=[2, 8, 40])
    ap.add_argument("--out", default="results/partition_quality.md")
    ap.add_argument("--json", default="results/partition_quality.json")
    args = ap.parse_args()

    from pipegcn_tpu import native
    from pipegcn_tpu.partition.partitioner import (
        partition_graph, edge_cut, comm_volume)
    from pipegcn_tpu.graph import synthetic_graph

    assert native.available(), "native partitioner must build"
    report = {}

    # ---- A: grid ground truth ---------------------------------------
    g = grid_graph(256)
    grid_rows = []
    for P in (2, 8):
        t0 = time.time()
        parts = partition_graph(g, P, seed=0)
        cut = edge_cut(g, parts) // 2
        opt = (P - 1) * 256
        sizes = np.bincount(parts, minlength=P)
        grid_rows.append(dict(P=P, cut=int(cut), strip_opt=opt,
                              ratio=round(cut / opt, 2),
                              vol=int(comm_volume(g, parts)),
                              balance=round(float(sizes.max() / sizes.mean()), 3),
                              t=round(time.time() - t0, 1)))
    report["grid"] = grid_rows

    # ---- B: planted partition ---------------------------------------
    g, comm, e_out = planted_graph(k=8, nodes_per=8000, deg_in=14,
                                   deg_out=1)
    t0 = time.time()
    parts = partition_graph(g, 8, seed=0)
    cut = edge_cut(g, parts) // 2
    # agreement with the planted communities up to relabeling: fraction
    # of nodes in their partition's majority community
    agree = 0
    for p in range(8):
        sel = comm[parts == p]
        if sel.size:
            agree += int(np.bincount(sel, minlength=8).max())
    report["planted"] = dict(
        planted_cut=int(e_out), cut=int(cut),
        ratio=round(cut / e_out, 3),
        majority_agreement=round(agree / comm.shape[0], 4),
        t=round(time.time() - t0, 1))

    # ---- B2: clustered synthetic (power-law-ish, homophilous) -------
    g = synthetic_graph(num_nodes=60000, avg_degree=30, n_feat=8,
                        n_class=4, homophily=0.8, seed=0)
    sy_rows = []
    for method in ("metis", "random"):
        t0 = time.time()
        parts = partition_graph(g, 8, seed=0, method=method)
        sy_rows.append(dict(method=method,
                            cut=int(edge_cut(g, parts)),
                            vol=int(comm_volume(g, parts)),
                            t=round(time.time() - t0, 1)))
    report["clustered"] = sy_rows

    # ---- C: bench Reddit-shape halo/ICI -----------------------------
    if args.bench_graph:
        report["bench_shape"] = bench_graph_section(args.parts)

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.json, "w") as f:
        json.dump(report, f, indent=1)

    # round-3 baselines, measured on this host before the FM upgrade
    # (greedy-only refinement, single initial partition, 2048-node
    # coarsening floor) — the deltas the upgrade bought
    r3_grid = {2: (488, 763), 8: (3015, 4849)}

    lines = [
        "# Partitioner quality benchmark",
        "",
        "In-tree multilevel partitioner (native/partitioner.cpp: HEM",
        "coarsening, multi-start initial partition, greedy + FM",
        "hill-climbing refinement) vs self-contained ground truths — no",
        "METIS binary exists in this environment, so absolute quality",
        "is measured against analytic optima instead of side-by-side",
        "(replaces reference helper/utils.py:132-144).",
        "",
        "## A. 256x256 grid (analytic strip cut = (P-1)*256)",
        "",
        "| P | cut | strip-opt | ratio | round-3 cut | vol | round-3 vol | balance |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in grid_rows:
        o_c, o_v = r3_grid[r["P"]]
        lines.append(
            f"| {r['P']} | {r['cut']} | {r['strip_opt']} | "
            f"x{r['ratio']} | {o_c} (x{o_c / r['strip_opt']:.2f}) | "
            f"{r['vol']} | {o_v} | {r['balance']} |")
    pl = report["planted"]
    cl = report["clustered"]
    lines += [
        "",
        "METIS-class partitioners land ~1.05-1.3x of the optimal grid",
        "bisection; the FM upgrade moved P=2 from 1.91x to "
        f"{grid_rows[0]['ratio']}x and P=8 below the strip bound "
        "(square tiles beat strips).",
        "",
        "## B. Planted 8-community graph (64k nodes, known structure)",
        "",
        f"- planted inter-community edges: {pl['planted_cut']}",
        f"- achieved cut: {pl['cut']} (x{pl['ratio']} of planted)",
        f"- majority-community agreement: "
        f"{100 * pl['majority_agreement']:.2f}%",
        "",
        "## B2. Clustered synthetic (60k nodes / 1.8M edges, P=8)",
        "",
        "| method | edge cut | comm volume | time |",
        "|---|---|---|---|",
    ]
    for r in cl:
        lines.append(f"| {r['method']} | {r['cut']} | {r['vol']} | "
                     f"{r['t']}s |")
    lines += [
        "",
        "(round-3 greedy-only partitioner on this graph: cut 1,163,980 /",
        "vol 321,438 — the FM upgrade cut both by >20% at ~2x the",
        "runtime.)",
    ]
    if "bench_shape" in report:
        lines += [
            "",
            "## C. Bench Reddit-shape graph "
            "(232,965 nodes / 114.6M directed edges)",
            "",
            "Halo rows = distinct foreign source rows a device receives",
            "per layer exchange; est ICI assumes bf16, 3 exchanged",
            "layers, fwd+bwd.",
            "",
            "| P | edge cut | comm vol | halo rows/device "
            "(mean / max) | est ICI bytes/epoch | partition time |",
            "|---|---|---|---|---|---|",
        ]
        for r in report["bench_shape"]:
            lines.append(
                f"| {r['P']} | {r['cut']:,} | {r['vol']:,} | "
                f"{r['halo_mean']:,.0f} / {r['halo_max']:,} | "
                f"{r['est_ici_bytes_per_epoch'] / 1e9:.2f} GB | "
                f"{r['partition_s']}s |")
    with open(args.out, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"# wrote {args.out}")


if __name__ == "__main__":
    main()
