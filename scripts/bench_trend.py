#!/usr/bin/env python
"""Bench trend report over the repo's BENCH_r*.json / MULTICHIP_*.json
measurement series (pipegcn_tpu/obs/trend.py).

    python scripts/bench_trend.py [--root DIR] [--tol 0.05] \
        [--json] [--strict]

Prints the per-lever delta table with best-known-headline regression
flags; --json emits the verdict dict instead; --strict exits 3 when
the verdict regressed (for window automation / CI lanes).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from pipegcn_tpu.obs.trend import format_trend, load_series, trend


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="bench trend over BENCH_r*.json / MULTICHIP_*.json")
    p.add_argument("--root", default=None,
                   help="directory holding the artifacts "
                        "(default: the repo root)")
    p.add_argument("--tol", type=float, default=0.05,
                   help="fractional regression tolerance vs best-known")
    p.add_argument("--json", action="store_true",
                   help="emit the verdict dict as JSON")
    p.add_argument("--strict", action="store_true",
                   help="exit 3 when the verdict regressed")
    args = p.parse_args(argv)

    root = args.root or os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    t = trend(load_series(root), tol=args.tol)
    if args.json:
        print(json.dumps(t, indent=2, sort_keys=True))
    else:
        print(format_trend(t))
    return 3 if (args.strict and t["regressed"]) else 0


if __name__ == "__main__":
    sys.exit(main())
