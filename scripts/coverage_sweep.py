#!/usr/bin/env python
"""Structural dense-coverage sweep: cluster granularity x nnz threshold.

The block kernel's epoch splits between the dense MXU term and the
slabbed remainder; VERDICT round 2 asks for remainder < 50% of the
epoch. Which (locality cluster target_size, block_nnz) maximizes the
edges captured in budget-capped dense tiles is a purely STRUCTURAL
question — this sweep answers it host-side so scarce TPU windows only
measure the top candidates.

For each cluster granularity it rebuilds the single-part Reddit-scale
layout (local ids sorted by cluster), then reports, per nnz threshold:
budget-capped dense coverage, dense block count, remainder edges, and
the v5e cost model's epoch projection (docs/PERF_NOTES.md rates).

Writes results/coverage_sweep.md.
"""

import argparse
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def model_epoch(dense_edges, rem_edges, dense_blocks, tile, width=256,
                block_s=2.14e-6, row_rate=230e6, pad=1.25,
                rem_bytes_per_feat=2, aux_s=0.066, fixed_s=0.518,
                layer_pairs=3):
    """Probe-CALIBRATED v5e epoch model (round 4).

    Fitted to the measured table-surgery decomposition
    (results/probe_traffic_tpu_g1.json, one v5e, Reddit-scale layout,
    38,744 blocks / 22.5M remainder edges):
      - dense fwd+bwd 116 ms -> `block_s` ~ 2.14 us/block (per layer
        pair, aux split evenly) — an EMPIRICAL unit absorbing the
        unpack transient + scheduling, ~5x the naive read+MXU sum the
        round-3 model used (its 0.53 s miss);
      - remainder fwd+bwd 277 ms -> `row_rate` ~ 230M padded slab
        rows/s (well under the 390-460M isolated-gather cliff rate);
      - `aux_s`: per-layer-pair shared prep (dense-only + rem-only -
        full = 66 ms); `fixed_s`: measured epoch minus SpMM epoch
        (1.5006 - 0.982 = 0.518 s: linears, norms, dropout RNG, fbuf
        assembly, dispatch).
    Validation: predicts the float8 headline config at 1.331 s vs
    1.2963 measured (+2.7%). `rem_bytes_per_feat`: 2 = bf16 transport,
    1 = fp8 (--rem-dtype float8)."""
    n_slabs = max(1, (width * rem_bytes_per_feat) // 256)
    t_dense = layer_pairs * dense_blocks * block_s
    t_rem = layer_pairs * rem_edges * pad * n_slabs / row_rate
    return (t_dense + t_rem + layer_pairs * aux_s + fixed_s,
            t_dense, t_rem)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="synthetic-reddit")
    ap.add_argument("--tile", type=int, default=256)
    ap.add_argument("--cluster-sizes", type=int, nargs="+",
                    default=[4096, 1024, 512])
    ap.add_argument("--nnz", type=int, nargs="+",
                    default=[0, 64, 108, 160])
    ap.add_argument("--out", default="results/coverage_sweep.md")
    ap.add_argument("--block-s", type=float, default=2.14e-6,
                    help="empirical dense cost per block per layer "
                         "pair (probe-calibrated)")
    ap.add_argument("--row-rate", type=float, default=230e6,
                    help="remainder padded slab rows/s "
                         "(probe-calibrated)")
    ap.add_argument("--aux-s", type=float, default=0.066,
                    help="shared SpMM prep per layer pair")
    ap.add_argument("--rem-bytes-per-feat", type=int, default=2,
                    help="2 = bf16 transport, 1 = fp8 (--rem-dtype)")
    ap.add_argument("--fixed-s", type=float, default=0.518,
                    help="non-SpMM epoch floor (measured epoch minus "
                         "probe SpMM epoch)")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from pipegcn_tpu.graph import load_data
    from pipegcn_tpu.ops.block_spmm import (DENSE_A_BYTE_BUDGET,
                                            _part_block_stats,
                                            budget_block_cap)
    from pipegcn_tpu.partition import ShardedGraph, locality_clusters
    from pipegcn_tpu.partition.partitioner import partition_graph

    g = load_data(args.dataset)
    parts = partition_graph(g, 1, seed=0)
    tile = args.tile
    cap = budget_block_cap(DENSE_A_BYTE_BUDGET, tile)

    rows = []
    for tsize in args.cluster_sizes:
        t0 = time.time()
        cluster = locality_clusters(g, target_size=tsize, seed=0)
        sg = ShardedGraph.build(g, parts, n_parts=1, cluster=cluster)
        n_src_tiles = -(-(sg.n_max + sg.halo_size) // tile)
        build_s = time.time() - t0
        seen_thr = set()
        for thr0 in args.nnz:
            thr = thr0 or max(1, (tile * tile) // 602)
            if thr in seen_thr:  # 0 resolves to the break-even, which
                continue         # may duplicate an explicit entry
            seen_thr.add(thr)
            cov, n_dense, dense_e, tot_e = _part_block_stats(
                sg, 0, tile, n_src_tiles, thr, max_blocks=cap)
            rem_e = tot_e - dense_e
            t_ep, t_d, t_r = model_epoch(
                dense_e, rem_e, n_dense, tile,
                block_s=args.block_s, row_rate=args.row_rate,
                aux_s=args.aux_s,
                rem_bytes_per_feat=args.rem_bytes_per_feat,
                fixed_s=args.fixed_s)
            rows.append((tsize, thr, cov, n_dense, rem_e, t_ep, t_d, t_r,
                         build_s))
            print(f"tsize={tsize} thr={thr}: cov={cov:.3f} "
                  f"blocks={n_dense} rem={rem_e/1e6:.1f}M "
                  f"model={t_ep:.3f}s (dense {t_d:.3f} rem {t_r:.3f})",
                  file=sys.stderr)

    lines = [
        "# Dense-coverage structural sweep (tile=%d, budget-capped)"
        % tile,
        "",
        f"Dataset {args.dataset}; 1 partition; budget cap {cap} "
        "bit-packed blocks. Cost model rates from docs/PERF_NOTES.md "
        "(projection only — TPU measurement picks among the top rows).",
        "",
        "| cluster target | nnz thr | coverage | dense blocks "
        "| remainder edges | model epoch (s) | dense (s) | rem (s) |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for (tsize, thr, cov, n_dense, rem_e, t_ep, t_d, t_r, _) in rows:
        lines.append(
            f"| {tsize} | {thr} | {cov:.3f} | {n_dense} "
            f"| {rem_e/1e6:.1f}M | {t_ep:.3f} | {t_d:.3f} | {t_r:.3f} |")
    best = min(rows, key=lambda r: r[5])
    lines += ["",
              f"Model-best: cluster target {best[0]}, thr {best[1]} -> "
              f"{best[5]:.3f} s/epoch projected (remainder share "
              f"{best[7]/best[5]:.0%})."]
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        f.write("\n".join(lines) + "\n")
    print("\n".join(lines[-3:]))


if __name__ == "__main__":
    main()
