#!/usr/bin/env python
"""Structural dense-coverage sweep: cluster granularity x nnz threshold.

The block kernel's epoch splits between the dense MXU term and the
slabbed remainder; VERDICT round 2 asks for remainder < 50% of the
epoch. Which (locality cluster target_size, block_nnz) maximizes the
edges captured in budget-capped dense tiles is a purely STRUCTURAL
question — this sweep answers it host-side so scarce TPU windows only
measure the top candidates.

For each cluster granularity it rebuilds the single-part Reddit-scale
layout (local ids sorted by cluster), then reports, per nnz threshold:
budget-capped dense coverage, dense block count, remainder edges, and
the v5e cost model's epoch projection (docs/PERF_NOTES.md rates).

Writes results/coverage_sweep.md.
"""

import argparse
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def model_epoch(dense_edges, rem_edges, dense_blocks, tile, width=256,
                gather_rps=390e6, hbm_bps=819e9, mxu_frac=0.5,
                rem_bytes_per_feat=2, union_dedupe=1.0, fixed_s=0.0):
    """v5e epoch model (docs/PERF_NOTES.md): 6 SpMMs of dense A+F-tile
    reads + MXU, remainder at the slab-gather rate, x1.5-ladder pad
    ~1.25 on the remainder. The rates are FLAGS so the model can be
    recalibrated against --probe-traffic decompositions (the round-3
    session-1 projection at defaults missed the measured 1.5182 by
    0.53 s — results/tpu_bench.md). `rem_bytes_per_feat`: 2 = bf16,
    1 = fp8 transport (--rem-dtype float8); `union_dedupe`: F-tile
    read factor of the union-gather layout (measured 0.33 at
    --block-group 4); `fixed_s`: non-SpMM epoch floor."""
    MXU = mxu_frac * 197e12
    isz = 2  # activations bf16 (dense path)
    t_dense = dense_blocks * 6 * (
        (tile * width * isz * union_dedupe + tile * tile / 8) / hbm_bps
        + 2 * tile * tile * width / MXU)
    n_slabs = max(1, (width * rem_bytes_per_feat) // 256)
    t_rem = rem_edges * 1.25 * n_slabs * 6 / gather_rps
    return t_dense + t_rem + fixed_s, t_dense, t_rem


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="synthetic-reddit")
    ap.add_argument("--tile", type=int, default=256)
    ap.add_argument("--cluster-sizes", type=int, nargs="+",
                    default=[4096, 1024, 512])
    ap.add_argument("--nnz", type=int, nargs="+",
                    default=[0, 64, 108, 160])
    ap.add_argument("--out", default="results/coverage_sweep.md")
    ap.add_argument("--gather-rps", type=float, default=390e6)
    ap.add_argument("--hbm-bps", type=float, default=819e9)
    ap.add_argument("--mxu-frac", type=float, default=0.5)
    ap.add_argument("--rem-bytes-per-feat", type=int, default=2,
                    help="2 = bf16 transport, 1 = fp8 (--rem-dtype)")
    ap.add_argument("--union-dedupe", type=float, default=1.0,
                    help="F-tile factor of the union-gather layout "
                         "(0.33 measured at --block-group 4)")
    ap.add_argument("--fixed-s", type=float, default=0.0,
                    help="non-SpMM epoch floor (recalibrate from the "
                         "probe-traffic decomposition)")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from pipegcn_tpu.graph import load_data
    from pipegcn_tpu.ops.block_spmm import (DENSE_A_BYTE_BUDGET,
                                            _part_block_stats,
                                            budget_block_cap)
    from pipegcn_tpu.partition import ShardedGraph, locality_clusters
    from pipegcn_tpu.partition.partitioner import partition_graph

    g = load_data(args.dataset)
    parts = partition_graph(g, 1, seed=0)
    tile = args.tile
    cap = budget_block_cap(DENSE_A_BYTE_BUDGET, tile)

    rows = []
    for tsize in args.cluster_sizes:
        t0 = time.time()
        cluster = locality_clusters(g, target_size=tsize, seed=0)
        sg = ShardedGraph.build(g, parts, n_parts=1, cluster=cluster)
        n_src_tiles = -(-(sg.n_max + sg.halo_size) // tile)
        build_s = time.time() - t0
        seen_thr = set()
        for thr0 in args.nnz:
            thr = thr0 or max(1, (tile * tile) // 602)
            if thr in seen_thr:  # 0 resolves to the break-even, which
                continue         # may duplicate an explicit entry
            seen_thr.add(thr)
            cov, n_dense, dense_e, tot_e = _part_block_stats(
                sg, 0, tile, n_src_tiles, thr, max_blocks=cap)
            rem_e = tot_e - dense_e
            t_ep, t_d, t_r = model_epoch(
                dense_e, rem_e, n_dense, tile,
                gather_rps=args.gather_rps, hbm_bps=args.hbm_bps,
                mxu_frac=args.mxu_frac,
                rem_bytes_per_feat=args.rem_bytes_per_feat,
                union_dedupe=args.union_dedupe, fixed_s=args.fixed_s)
            rows.append((tsize, thr, cov, n_dense, rem_e, t_ep, t_d, t_r,
                         build_s))
            print(f"tsize={tsize} thr={thr}: cov={cov:.3f} "
                  f"blocks={n_dense} rem={rem_e/1e6:.1f}M "
                  f"model={t_ep:.3f}s (dense {t_d:.3f} rem {t_r:.3f})",
                  file=sys.stderr)

    lines = [
        "# Dense-coverage structural sweep (tile=%d, budget-capped)"
        % tile,
        "",
        f"Dataset {args.dataset}; 1 partition; budget cap {cap} "
        "bit-packed blocks. Cost model rates from docs/PERF_NOTES.md "
        "(projection only — TPU measurement picks among the top rows).",
        "",
        "| cluster target | nnz thr | coverage | dense blocks "
        "| remainder edges | model epoch (s) | dense (s) | rem (s) |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for (tsize, thr, cov, n_dense, rem_e, t_ep, t_d, t_r, _) in rows:
        lines.append(
            f"| {tsize} | {thr} | {cov:.3f} | {n_dense} "
            f"| {rem_e/1e6:.1f}M | {t_ep:.3f} | {t_d:.3f} | {t_r:.3f} |")
    best = min(rows, key=lambda r: r[5])
    lines += ["",
              f"Model-best: cluster target {best[0]}, thr {best[1]} -> "
              f"{best[5]:.3f} s/epoch projected (remainder share "
              f"{best[7]/best[5]:.0%})."]
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        f.write("\n".join(lines) + "\n")
    print("\n".join(lines[-3:]))


if __name__ == "__main__":
    main()
