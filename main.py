"""Drop-in launcher: `python main.py <reference flags>` runs PipeGCN-TPU
with the reference's CLI surface (so the reference's scripts/*.sh work
unchanged — reference main.py:8-63, minus the process spawning that SPMD
makes unnecessary)."""

from pipegcn_tpu.cli.main import cli_entry

if __name__ == "__main__":
    cli_entry()
