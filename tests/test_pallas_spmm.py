"""Pallas SpMM kernel correctness (interpret mode on CPU; the same kernel
compiles for TPU)."""

import numpy as np
import jax.numpy as jnp
import pytest

from pipegcn_tpu.graph import karate_club, synthetic_graph
from pipegcn_tpu.ops.pallas_spmm import PallasSpmm, build_row_ptr
from pipegcn_tpu.ops.spmm import spmm_mean
from pipegcn_tpu.partition import ShardedGraph, partition_graph


def _csr_sorted(g):
    order = np.argsort(g.dst, kind="stable")
    return g.src[order].astype(np.int32), g.dst[order].astype(np.int32)


def test_row_ptr():
    dst = np.array([0, 0, 1, 3, 3, 3], dtype=np.int32)
    rp = build_row_ptr(dst, 4)
    np.testing.assert_array_equal(rp, [0, 2, 3, 3, 6])


@pytest.mark.parametrize("n_feat", [8, 128])
def test_pallas_matches_xla(n_feat):
    g = karate_club(n_feat=n_feat)
    src, dst = _csr_sorted(g)
    n = g.num_nodes
    deg = g.ndata["in_deg"].astype(np.float32)
    fbuf = jnp.asarray(
        np.random.default_rng(0).normal(size=(n, n_feat)).astype(np.float32)
    )
    plan = PallasSpmm(src, dst, deg, n_out=n, n_src_rows=n, n_feat=n_feat,
                      interpret=True)
    assert plan.applicable
    got = plan(fbuf)
    want = spmm_mean(fbuf, jnp.asarray(src), jnp.asarray(dst),
                     jnp.asarray(deg), n)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_pallas_on_sharded_layout_with_padding():
    """Kernel must handle ShardedGraph's padded layout: sentinel-dst pad
    edges (ignored via row_ptr), padded rows, halo source indices."""
    g = synthetic_graph(num_nodes=200, avg_degree=6, n_feat=16, n_class=3,
                        seed=4)
    parts = partition_graph(g, 2, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=2)
    r = 0
    n_src = sg.n_max + sg.halo_size
    # build the full fbuf as the trainer would (inner + halos via numpy)
    fbuf = np.zeros((n_src, 16), np.float32)
    fbuf[: sg.n_max] = sg.feat[r]
    for dist in range(1, 2):
        q = (r - dist) % 2
        blk = sg.feat[q][sg.send_idx[q, dist - 1]]
        blk[~sg.send_mask[q, dist - 1]] = 0
        s = sg.n_max + (dist - 1) * sg.b_max
        fbuf[s : s + sg.b_max] = blk

    plan = PallasSpmm(sg.edge_src[r], sg.edge_dst[r], sg.in_deg[r],
                      n_out=sg.n_max, n_src_rows=n_src, n_feat=16,
                      interpret=True)
    got = np.asarray(plan(jnp.asarray(fbuf)))
    want = np.asarray(
        spmm_mean(jnp.asarray(fbuf), jnp.asarray(sg.edge_src[r]),
                  jnp.asarray(sg.edge_dst[r]), jnp.asarray(sg.in_deg[r]),
                  sg.n_max)
    )
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_trainer_pallas_matches_xla():
    """Full training parity: spmm_impl='pallas' must reproduce the XLA
    path's losses (same seed, no dropout) including gradients through
    the custom VJP transpose."""
    from pipegcn_tpu.models import ModelConfig
    from pipegcn_tpu.parallel import Trainer, TrainConfig

    g = synthetic_graph(num_nodes=400, avg_degree=8, n_feat=12, n_class=4,
                        seed=11)
    parts = partition_graph(g, 4, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=4)

    def make(impl):
        cfg = ModelConfig(layer_sizes=(12, 16, 4), dropout=0.0,
                          train_size=sg.n_train_global, spmm_impl=impl)
        return Trainer(sg, cfg, TrainConfig(seed=3))

    tx, tp = make("xla"), make("pallas")
    assert tp._pallas_tables is not None
    for e in range(4):
        lx = tx.train_epoch(e)
        lp = tp.train_epoch(e)
        np.testing.assert_allclose(lx, lp, rtol=2e-4)


def test_spmm_impl_auto_rejects_oversized():
    from pipegcn_tpu.models import ModelConfig
    from pipegcn_tpu.parallel import Trainer, TrainConfig

    g = synthetic_graph(num_nodes=300, avg_degree=6, n_feat=8, n_class=3,
                        seed=5)
    parts = partition_graph(g, 2, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=2)
    # hidden width 200_000 would blow the VMEM budget -> auto falls back
    cfg = ModelConfig(layer_sizes=(8, 200_000, 3), dropout=0.0,
                      train_size=sg.n_train_global, spmm_impl="auto")
    t = Trainer(sg, cfg, TrainConfig(seed=0))
    assert t._pallas_tables is None


def test_applicability_gate():
    g = karate_club(n_feat=8)
    src, dst = _csr_sorted(g)
    deg = g.ndata["in_deg"].astype(np.float32)
    # absurd fbuf row count -> exceeds VMEM budget -> not applicable
    plan = PallasSpmm(src, dst, deg, n_out=g.num_nodes,
                      n_src_rows=50_000_000, n_feat=8, interpret=True)
    assert not plan.applicable
