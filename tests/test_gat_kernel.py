"""Attention-bucket GAT kernel (ops/gat_bucket.py): exact parity with
the raw-edge segment formulation — forward, all three VJP outputs, the
full training step across devices, and the bf16/chunked variants."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pipegcn_tpu.graph import synthetic_graph
from pipegcn_tpu.models import ModelConfig
from pipegcn_tpu.ops.gat_bucket import (
    build_sharded_gat_tables,
    make_device_gat_fn,
)
from pipegcn_tpu.parallel import Trainer, TrainConfig
from pipegcn_tpu.partition import ShardedGraph, partition_graph


@pytest.fixture(scope="module")
def graph():
    return synthetic_graph(num_nodes=350, avg_degree=7, n_feat=10,
                           n_class=4, seed=17)


def _raw_reference(es, ed, n_dst, slope=0.2):
    """Segment-op edge softmax — the formulation _gat_layer uses on the
    raw-edge path, reduced to the (z, el, er) kernel boundary."""

    def raw(z, el, er):
        l = jax.nn.leaky_relu(el[es] + er[ed], slope)
        m = jax.ops.segment_max(l, ed, n_dst)
        m = jnp.where(jnp.isfinite(m), m, 0.0)
        ex = jnp.exp(l - m[ed])
        s = jax.ops.segment_sum(ex, ed, n_dst)
        alpha = ex / jnp.maximum(s[ed], 1e-16)
        return jax.ops.segment_sum(z[es] * alpha[..., None], ed, n_dst)

    return raw


def _kernel_and_raw(graph, n_parts=1, H=4, dh=8, seed=0):
    sg = ShardedGraph.build(graph, partition_graph(graph, n_parts,
                                                   seed=0),
                            n_parts=n_parts)
    tables = build_sharded_gat_tables(sg)
    rng = np.random.default_rng(seed)
    per_dev = []
    for r in range(sg.num_parts):
        d = {k: jnp.asarray(v[r]) for k, v in tables.items()}
        n_dst, R = sg.n_max, sg.n_max + sg.halo_size
        gat = make_device_gat_fn(d, n_dst, R, H, 0.2)
        e = int(sg.edge_count[r])
        real = sg.edge_dst[r][:e] < n_dst
        es = jnp.asarray(sg.edge_src[r][:e][real])
        ed = jnp.asarray(sg.edge_dst[r][:e][real])
        raw = _raw_reference(es, ed, n_dst)
        z = jnp.asarray(rng.normal(size=(R, H, dh)).astype(np.float32))
        el = jnp.asarray(rng.normal(size=(R, H)).astype(np.float32))
        er = jnp.asarray(rng.normal(size=(n_dst, H)).astype(np.float32))
        per_dev.append((gat, raw, z, el, er))
    return per_dev


def test_kernel_forward_matches_raw(graph):
    for gat, raw, z, el, er in _kernel_and_raw(graph, n_parts=2):
        np.testing.assert_allclose(gat(z, el, er), raw(z, el, er),
                                   rtol=1e-5, atol=1e-5)


def test_kernel_vjp_matches_raw(graph):
    for gat, raw, z, el, er in _kernel_and_raw(graph, n_parts=2, seed=3):
        ct = jnp.asarray(np.random.default_rng(7).normal(
            size=(er.shape[0], z.shape[1], z.shape[2])
        ).astype(np.float32))
        g1 = jax.grad(lambda *a: (gat(*a) * ct).sum(), argnums=(0, 1, 2))(
            z, el, er)
        g2 = jax.grad(lambda *a: (raw(*a) * ct).sum(), argnums=(0, 1, 2))(
            z, el, er)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_kernel_handles_zero_degree_rows():
    """Rows with no in-edges must emit exactly 0 (and no NaN anywhere):
    a star graph's leaves plus isolated self-loop-only nodes."""
    g = synthetic_graph(num_nodes=60, avg_degree=2, n_feat=6, n_class=3,
                        seed=5)
    for gat, raw, z, el, er in _kernel_and_raw(g, n_parts=1, H=2, dh=4):
        out = gat(z, el, er)
        assert np.isfinite(np.asarray(out)).all()
        np.testing.assert_allclose(out, raw(z, el, er), rtol=1e-5,
                                   atol=1e-5)


def _gat_trainer(graph, n_parts, impl, *, dtype="float32", chunk=None,
                 **tkw):
    sg = ShardedGraph.build(graph, partition_graph(graph, n_parts,
                                                   seed=0),
                            n_parts=n_parts)
    cfg = ModelConfig(
        layer_sizes=(sg.n_feat, 16, sg.n_class), model="gat", n_heads=4,
        norm="layer", dropout=0.0, train_size=sg.n_train_global,
        spmm_impl=impl, dtype=dtype, spmm_chunk=chunk,
    )
    return Trainer(sg, cfg, TrainConfig(**tkw))


def test_training_bucket_matches_xla(graph):
    """The whole pipelined training step — halo exchange, staleness,
    grad psum — produces identical losses through the attention-bucket
    kernel and the raw-edge path."""
    t_raw = _gat_trainer(graph, 4, "xla", seed=3, enable_pipeline=True)
    t_fast = _gat_trainer(graph, 4, "bucket", seed=3,
                          enable_pipeline=True)
    assert t_fast._gat_tables is not None
    assert t_fast._edges_trimmed
    for epoch in range(4):
        l_raw = t_raw.train_epoch(epoch)
        l_fast = t_fast.train_epoch(epoch)
        np.testing.assert_allclose(l_raw, l_fast, rtol=1e-4)


def test_auto_resolves_to_attention_bucket(graph):
    t = _gat_trainer(graph, 2, "auto", seed=1)
    assert t._gat_tables is not None


def test_training_bucket_bf16_finite_and_converges(graph):
    t = _gat_trainer(graph, 4, "bucket", dtype="bfloat16", seed=5,
                     enable_pipeline=True)
    losses = [t.train_epoch(e) for e in range(8)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_training_bucket_chunked_matches_unchunked(graph):
    losses = {}
    for chunk in (None, 400):
        t = _gat_trainer(graph, 2, "bucket", chunk=chunk, seed=2)
        losses[chunk] = [t.train_epoch(e) for e in range(3)]
    np.testing.assert_allclose(losses[None], losses[400], rtol=1e-5)


def test_sharded_eval_gat_transductive_and_inductive(graph):
    """A GAT trainer on the attention-bucket kernel trims its raw edge
    list; the sharded evaluator must aggregate through the attention
    tables (transductive reuse AND a foreign inductive graph) and match
    the host full-graph eval."""
    t = _gat_trainer(graph, 4, "bucket", seed=3)
    assert t._edges_trimmed
    for e in range(3):
        t.train_epoch(e)
    full = t.evaluate(graph, "val_mask")
    sharded = t.evaluate(graph, "val_mask", sharded=True)
    assert full == pytest.approx(sharded, abs=1e-9)
    eg = synthetic_graph(num_nodes=260, avg_degree=6, n_feat=10,
                         n_class=4, seed=23)
    full_i = t.evaluate(eg, "val_mask")
    sharded_i = t.evaluate(eg, "val_mask", sharded=True)
    assert full_i == pytest.approx(sharded_i, abs=1e-9)


def test_slab_layout_invariant():
    """Every slab must cover whole heads or lie inside one head — for
    ANY (H, dh, itemsize), including non-power-of-2 shapes like the
    bf16 H=7, dh=24 case where naive halving would straddle heads."""
    from pipegcn_tpu.ops.gat_bucket import _slab_layout

    for H in (1, 2, 3, 4, 7, 8):
        for dh in (3, 8, 24, 64, 96, 200):
            for itemsize in (2, 4):
                F = H * dh
                slab, n_slabs = _slab_layout(F, dh, itemsize)
                assert slab * n_slabs == F, (H, dh, itemsize)
                assert slab % dh == 0 or dh % slab == 0, (H, dh, itemsize)
                if slab % dh == 0:
                    assert H % (slab // dh) == 0, (H, dh, itemsize)


def test_kernel_float8_transport_tolerance(graph):
    """rem_dtype='float8' on the attention kernel: z travels e4m3
    through the forward and both backward contractions, cotangents
    e5m2; results stay within fp8 quantization error of full
    precision, and softmax structure (normalization) is exact."""
    sg = ShardedGraph.build(graph, partition_graph(graph, 1, seed=0),
                            n_parts=1)
    tables = build_sharded_gat_tables(sg)
    d = {k: jnp.asarray(v[0]) for k, v in tables.items()}
    n_dst, R = sg.n_max, sg.n_max + sg.halo_size
    H, dh = 4, 8
    gat32 = make_device_gat_fn(d, n_dst, R, H, 0.2)
    gat8 = make_device_gat_fn(d, n_dst, R, H, 0.2, rem_dtype="float8")
    rng = np.random.default_rng(11)
    z = jnp.asarray(rng.normal(size=(R, H, dh)).astype(np.float32))
    el = jnp.asarray(rng.normal(size=(R, H)).astype(np.float32))
    er = jnp.asarray(rng.normal(size=(n_dst, H)).astype(np.float32))
    o32 = np.asarray(gat32(z, el, er))
    o8 = np.asarray(gat8(z, el, er))
    err = np.abs(o8 - o32) / (np.abs(o32) + 1e-3)
    assert np.median(err) < 0.04
    assert np.isfinite(o8).all()
    ct = jnp.asarray(rng.normal(size=o32.shape).astype(np.float32))
    g8 = jax.grad(lambda *a: (gat8(*a) * ct).sum(), argnums=(0, 1, 2))(
        z, el, er)
    g32 = jax.grad(lambda *a: (gat32(*a) * ct).sum(), argnums=(0, 1, 2))(
        z, el, er)
    for a, b in zip(g8, g32):
        a, b = np.asarray(a), np.asarray(b)
        assert np.isfinite(a).all()
        gerr = np.abs(a - b) / (np.abs(b) + 1e-2)
        assert np.median(gerr) < 0.15


def test_training_gat_float8_converges(graph):
    """Whole-trainer GAT with fp8 attention transport: tracks the
    full-precision run early and keeps converging."""
    parts = partition_graph(graph, 2, seed=0)
    sg = ShardedGraph.build(graph, parts, n_parts=2)
    losses = {}
    for rd in (None, "float8"):
        cfg = ModelConfig(model="gat", layer_sizes=(10, 16, 4),
                          norm="layer", dropout=0.0, n_heads=4,
                          train_size=sg.n_train_global,
                          spmm_impl="bucket", rem_dtype=rd)
        t = Trainer(sg, cfg, TrainConfig(seed=4, enable_pipeline=True))
        losses[rd] = [t.train_epoch(e) for e in range(15)]
    l32, l8 = np.asarray(losses[None]), np.asarray(losses["float8"])
    assert np.isfinite(l8).all()
    np.testing.assert_allclose(l8[:4], l32[:4], rtol=0.1, atol=0.05)
    assert l8[-1] < l8[0] * 0.8
