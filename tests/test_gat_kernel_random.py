"""Randomized parity sweep for the attention-bucket GAT kernel:
random (graph shape, heads, head dim) combinations — hub rows,
zero-degree rows, single-head, sub-slab head dims — against the
raw segment-op edge-softmax reference."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pipegcn_tpu.graph import synthetic_graph
from pipegcn_tpu.ops.gat_bucket import (
    build_sharded_gat_tables,
    make_device_gat_fn,
)
from pipegcn_tpu.partition import ShardedGraph, partition_graph


def _raw(es, ed, n_dst, slope=0.2):
    def f(z, el, er):
        l = jax.nn.leaky_relu(el[es] + er[ed], slope)
        m = jax.ops.segment_max(l, ed, n_dst)
        m = jnp.where(jnp.isfinite(m), m, 0.0)
        ex = jnp.exp(l - m[ed])
        s = jax.ops.segment_sum(ex, ed, n_dst)
        alpha = ex / jnp.maximum(s[ed], 1e-16)
        return jax.ops.segment_sum(z[es] * alpha[..., None], ed, n_dst)

    return f


@pytest.mark.parametrize("trial", range(8))
def test_randomized_gat_parity(trial):
    rng = np.random.default_rng(300 + trial)
    n = int(rng.integers(40, 260))
    deg = int(rng.integers(2, 9))
    H = int(rng.choice([1, 2, 4, 5]))
    dh = int(rng.choice([3, 8, 16, 33]))
    g = synthetic_graph(num_nodes=n, avg_degree=deg, n_feat=6,
                        n_class=3, seed=int(rng.integers(1e6)))
    sg = ShardedGraph.build(g, partition_graph(g, 1, seed=0), n_parts=1)
    tables = build_sharded_gat_tables(sg)
    d = {k: jnp.asarray(v[0]) for k, v in tables.items()}
    n_dst, R = sg.n_max, sg.n_max + sg.halo_size
    gat = make_device_gat_fn(d, n_dst, R, H, 0.2)
    e = int(sg.edge_count[0])
    real = sg.edge_dst[0][:e] < n_dst
    es = jnp.asarray(sg.edge_src[0][:e][real])
    ed = jnp.asarray(sg.edge_dst[0][:e][real])
    raw = _raw(es, ed, n_dst)
    z = jnp.asarray(rng.normal(size=(R, H, dh)).astype(np.float32))
    el = jnp.asarray(rng.normal(size=(R, H)).astype(np.float32))
    er = jnp.asarray(rng.normal(size=(n_dst, H)).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(gat(z, el, er)), np.asarray(raw(z, el, er)),
        rtol=2e-5, atol=2e-5,
        err_msg=f"n={n} H={H} dh={dh} deg={deg}")
    # gradients stay consistent on a random cotangent
    ct = jnp.asarray(rng.normal(size=(n_dst, H, dh)).astype(np.float32))
    g1 = jax.grad(lambda *a: (gat(*a) * ct).sum(), argnums=(0, 1, 2))(
        z, el, er)
    g2 = jax.grad(lambda *a: (raw(*a) * ct).sum(), argnums=(0, 1, 2))(
        z, el, er)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)
