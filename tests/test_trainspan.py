"""Training-path distributed-tracing tests (obs/trainspan.py +
trainer wiring + obs/live.py + obs/health.py + obs/timeline.py +
cli/report.py, docs/OBSERVABILITY.md "Training traces"):

  - TrainSpanPlane block emission: span conservation (counts match the
    sink), the compute span is the real dispatch->harvest window, and
    the armed comm tail sits back-to-back ENDING at the harvest
    barrier with grad_reduce last and halo cost apportioned by wire
    bytes;
  - estimate_offsets recovers planted per-rank clock skew from the
    tracesync barrier anchors (and from grad_reduce span ends when no
    tracesync landed);
  - fold_spans' interval-union overlap agrees with the profiler's
    fold_trace on a shared interval fixture — one overlap definition,
    two sources;
  - straggler attribution names the rank whose compute window started
    last ON THE ALIGNED CLOCK (a big wall-clock skew must not fool it);
  - the straggler-skew alert fires once on a sustained skew, stays
    silent while red, and resolves when attribution moves off the rank
    (fake clock, through LiveAggregator + AlertEngine);
  - the timeline renders train spans on a dedicated per-rank track and
    stitches each epoch's MATCHING collectives across ranks into
    "collective" flows on the aligned clock;
  - pipegcn-report derives a measured overlap verdict from spans with
    NO profiler capture window, plus the divergence tripwire;
  - the live snapshot + /metrics gauges surface the span verdicts;
  - the zero-recompile pin: spans on vs off leaves the jitted step
    cache identical (the plane is host-side bookkeeping only);
  - the two-process slow-rank drill (faults+slow): a real pipelined
    CPU-mesh run with slow-rank@E:r1 injected must attribute the
    straggle to rank 1, fire the alert, stitch cross-rank flows, and
    keep every span on disk.

Marker: trainspan (scripts/chaos.sh runs the lane standalone); the
drill is additionally faults + slow so tier-1 skips it."""

import collections
import io
import json
import os
import socket
import subprocess
import sys
import time

import pytest

from pipegcn_tpu.obs.health import AlertEngine, load_rules, prometheus_text
from pipegcn_tpu.obs.live import LiveAggregator
from pipegcn_tpu.obs.metrics import MetricsLogger, read_metrics
from pipegcn_tpu.obs.profiler import fold_trace
from pipegcn_tpu.obs.timeline import build_timeline
from pipegcn_tpu.obs.trainspan import (
    COMM_OPS,
    TrainSpanPlane,
    estimate_offsets,
    fold_spans,
    trace_id,
    train_spans,
)

pytestmark = pytest.mark.trainspan

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _records(buf):
    return [json.loads(line) for line in buf.getvalue().splitlines()]


# ---------------- emission: conservation + comm-tail geometry ---------


def test_block_span_conservation_and_comm_tail():
    """One block -> exactly the contracted spans: pre-arm a compute
    span + tracesync anchor only; post-arm additionally the comm tail
    back-to-back ending at the harvest barrier, grad_reduce LAST, halo
    cost split by wire bytes, every span tagged rank/generation."""
    clk = [100.0]
    buf = io.StringIO()
    ml = MetricsLogger(buf)
    plane = TrainSpanPlane(ml, rank=1, generation=2,
                           clock=lambda: clk[0],
                           now=lambda: clk[0] + 1000.0)

    # pre-arm: compute + tracesync, nothing else
    plane.block(epoch=0, chunk=1, dur_s=0.5, t_end=100.0)
    assert not plane.comm_armed
    recs = _records(buf)
    assert [r["event"] for r in recs] == ["span", "tracesync"]
    comp, sync = recs
    assert comp["op"] == "compute"
    assert comp["trace_id"] == trace_id(0) == "train-e0"
    assert comp["t_start"] == pytest.approx(1099.5)
    assert comp["dur_ms"] == pytest.approx(500.0)
    assert (comp["rank"], comp["generation"]) == (1, 2)
    assert (comp["epoch"], comp["epochs"]) == (0, 1)
    assert comp["comm_wait_s"] == 0.0
    assert comp["source"] == "r1"
    assert (sync["rank"], sync["epoch"]) == (1, 0)
    assert sync["t_anchor"] == pytest.approx(1100.0)
    assert sync["generation"] == 2

    # armed: the comm tail ends at the barrier, grad_reduce last
    plane.set_comm({"comm": 0.03, "reduce": 0.01, "bgrad": 0.02},
                   [(0, 100), (1, 300)], "bfloat16")
    assert plane.comm_armed
    plane.block(epoch=1, chunk=2, dur_s=0.5, t_end=101.0)
    spans = [r for r in _records(buf)[2:] if r["event"] == "span"]
    by_op = {}
    for r in spans:
        by_op.setdefault(r["op"], []).append(r)
    assert sorted(by_op) == ["bgrad_return", "compute", "grad_reduce",
                             "halo_exchange"]
    end = lambda r: r["t_start"] + r["dur_ms"] / 1e3  # noqa: E731
    barrier = 1101.0
    gr = by_op["grad_reduce"][0]
    assert end(gr) == pytest.approx(barrier)          # grad_reduce LAST
    assert gr["dur_ms"] == pytest.approx(20.0)        # reduce * chunk
    bg = by_op["bgrad_return"][0]
    assert end(bg) == pytest.approx(gr["t_start"])    # back-to-back
    assert bg["dur_ms"] == pytest.approx(40.0)
    halos = sorted(by_op["halo_exchange"], key=lambda r: r["layer"])
    # halo cost (0.03 * 2) apportioned 100:300 by wire bytes
    assert halos[0]["dur_ms"] == pytest.approx(15.0)
    assert halos[1]["dur_ms"] == pytest.approx(45.0)
    assert halos[0]["wire_bytes"] == 200              # bytes * chunk
    assert halos[1]["wire_bytes"] == 600
    assert all(h["dtype"] == "bfloat16" for h in halos)
    assert end(halos[1]) == pytest.approx(bg["t_start"])
    assert end(halos[0]) == pytest.approx(halos[1]["t_start"])
    for r in spans:
        assert (r["rank"], r["generation"]) == (1, 2)
        assert r["trace_id"] == "train-e1"

    # a window too short to hide the comm cost reads as exposed wait
    plane.block(epoch=3, chunk=1, dur_s=0.01, t_end=102.0)
    comp3 = [r for r in _records(buf) if r.get("op") == "compute"][-1]
    assert comp3["comm_wait_s"] == pytest.approx(0.05)

    # conservation: the plane's own counts match the sink exactly
    ml.close()
    sink_counts = collections.Counter(
        r["op"] for r in _records(buf) if r["event"] == "span")
    assert plane.counts == dict(sink_counts)
    assert plane.blocks == 3
    assert train_spans(_records(buf)) == [
        r for r in _records(buf) if r["event"] == "span"]


# ---------------- clock-offset recovery -------------------------------


def test_estimate_offsets_recovers_planted_skew():
    """Per-rank offsets recovered from tracesync anchors: three ranks
    share a barrier each epoch; their planted wall-clock skews come
    back (relative to the cross-rank median), and the grad_reduce
    span-end fallback recovers the same answer without tracesync."""
    planted = {0: 0.0, 1: 0.5, 2: -0.2}
    syncs, reduces = [], []
    for e in range(4):
        barrier = 1000.0 + e * 1.0
        for r, off in planted.items():
            syncs.append({"event": "tracesync", "rank": r, "epoch": e,
                          "t_anchor": barrier + off, "generation": 0})
            reduces.append({"event": "span", "trace_id": trace_id(e),
                            "span_id": f"s{e}{r}", "op": "grad_reduce",
                            "t_start": barrier + off - 0.01,
                            "dur_ms": 10.0, "status": "ok", "rank": r,
                            "epoch": e})
    got = estimate_offsets(syncs)
    for r, off in planted.items():
        assert got[r] == pytest.approx(off, abs=1e-9)
    # fallback path: no tracesync -> grad_reduce ends anchor the barrier
    got_fb = estimate_offsets(reduces)
    for r, off in planted.items():
        assert got_fb[r] == pytest.approx(off, abs=1e-9)
    # a single-rank run has no cross-rank barrier: no offsets
    assert estimate_offsets(syncs[:1]) == {}


# ---------------- overlap agrees with the profiler fold ---------------


def test_fold_spans_overlap_agrees_with_fold_trace():
    """One overlap definition, two sources: the span fold and the
    device-trace fold produce the SAME fraction on the same intervals
    (compute [0,10]s; halo [6,8] covered; grad_reduce [9,11] half
    exposed -> 3 of 4 comm seconds covered = 0.75)."""
    spans = [
        {"event": "span", "trace_id": "train-e0", "span_id": "a",
         "op": "compute", "t_start": 0.0, "dur_ms": 10_000.0,
         "status": "ok", "rank": 0, "epoch": 0},
        {"event": "span", "trace_id": "train-e0", "span_id": "b",
         "op": "halo_exchange", "t_start": 6.0, "dur_ms": 2_000.0,
         "status": "ok", "rank": 0, "epoch": 0},
        {"event": "span", "trace_id": "train-e0", "span_id": "c",
         "op": "grad_reduce", "t_start": 9.0, "dur_ms": 2_000.0,
         "status": "ok", "rank": 0, "epoch": 0},
    ]
    fold = fold_spans(spans)
    assert fold["overlap_spans"] == pytest.approx(0.75)

    events = [
        {"ph": "X", "pid": 1, "ts": 0.0, "dur": 10e6, "name": "fusion",
         "args": {"hlo_op": "op.c"}},
        {"ph": "X", "pid": 1, "ts": 6e6, "dur": 2e6, "name": "all-gather",
         "args": {"hlo_op": "op.h"}},
        {"ph": "X", "pid": 1, "ts": 9e6, "dur": 2e6, "name": "all-reduce",
         "args": {"hlo_op": "op.r"}},
    ]
    op_map = {"op.c": ("layer0/spmm", "fusion"),
              "op.h": ("halo_exchange", "all-gather"),
              "op.r": ("grad_reduce", "all-reduce")}
    meas = fold_trace(events, op_map)
    assert meas["overlap_fraction"] == pytest.approx(
        fold["overlap_spans"])


# ---------------- straggler attribution on the aligned clock ----------


def _two_rank_records(n_epochs=2, wall_off=5.0, lag=0.2, t0=1000.0):
    """Two ranks sharing barriers: rank 1's wall clock is `wall_off`
    seconds ahead AND its compute window starts `lag` seconds late
    (physically). Returns (recs0, recs1)."""
    out = {0: [], 1: []}
    for e in range(n_epochs):
        barrier = t0 + (e + 1) * 1.0
        for r, (off, dur) in {0: (0.0, 0.8),
                              1: (wall_off, 0.8 - lag)}.items():
            out[r].append({"event": "tracesync", "rank": r, "epoch": e,
                           "t_anchor": barrier + off, "generation": 0})
            for op, d0, d1 in (("compute", dur, 0.0),
                               ("halo_exchange", 0.2, 0.1),
                               ("grad_reduce", 0.1, 0.0)):
                rec = {"event": "span", "trace_id": trace_id(e),
                       "span_id": f"{op[0]}{e}r{r}", "op": op,
                       "t_start": barrier + off - d0,
                       "dur_ms": (d0 - d1) * 1e3, "status": "ok",
                       "rank": r, "epoch": e, "source": f"r{r}"}
                if op == "halo_exchange":
                    rec["layer"] = 0
                out[r].append(rec)
    return out[0], out[1]


def test_straggler_attribution_survives_clock_skew():
    """Rank 1 really starts 0.2 s late, but its wall clock is 5 s
    AHEAD: raw timestamps would blame it by 5.2 s (or, re-signed,
    exonerate it). The tracesync-aligned fold names rank 1 with the
    physical gap (median-of-two halves it to 0.1 s)."""
    recs0, recs1 = _two_rank_records()
    fold = fold_spans(recs0 + recs1)
    # offsets symmetric around the 2-rank median: the RELATIVE skew
    # is what alignment needs, and it equals the planted 5 s
    assert (fold["offsets"][1] - fold["offsets"][0]
            == pytest.approx(5.0, abs=1e-6))
    assert fold["straggler_rank"] == 1
    assert fold["straggler_max_gap_s"] == pytest.approx(0.1, abs=1e-6)
    assert fold["straggler_gap_s_by_rank"][1] == pytest.approx(
        0.1, abs=1e-6)
    for e, pe in fold["per_epoch"].items():
        assert pe["straggler_rank"] == 1
        assert pe["gap_s"] == pytest.approx(0.1, abs=1e-6)
    # both ranks' comm is fully inside their compute windows here
    assert fold["overlap_spans"] == pytest.approx(1.0)
    assert fold["comm_wait_s_by_rank"] == {0: 0.0, 1: 0.0}


# ---------------- straggler-skew alert: fire / dedupe / resolve -------


def _write_epoch(ml, e, step=0.1):
    ml.write({"event": "epoch", "epoch": e, "loss": 1.0, "grad_norm": 0.5,
              "step_time_s": step, "halo_bytes": 1000, "staleness_age": 1,
              "memory": None, "time_unix": time.time()})


def _write_skewed_epoch(ml, e, late_rank, t0=2000.0, lag=0.2):
    """Both ranks' compute spans for epoch `e` into one stream;
    `late_rank` starts `lag` late (gap = lag/2 vs the 2-rank median)."""
    barrier = t0 + (e + 1) * 1.0
    for r in (0, 1):
        dur = 0.8 - (lag if r == late_rank else 0.0)
        ml.span(trace_id(e), f"c{e}r{r}", "compute", barrier - dur,
                dur * 1e3, rank=r, epoch=e)
    _write_epoch(ml, e)


def test_straggler_skew_alert_fire_dedupe_resolve(tmp_path):
    """A sustained one-rank skew fires straggler-skew ONCE for source
    r1, stays silent while red, and resolves once attribution moves
    off the rank — the edge-triggered contract every other rule keeps."""
    d = tmp_path / "run"
    d.mkdir()
    fake = [7000.0]
    agg = LiveAggregator(str(d), clock=lambda: fake[0])
    rules = [r for r in load_rules(None) if r["rule"] == "straggler-skew"]
    assert rules and rules[0]["sustain"] == 3
    eng = AlertEngine(rules, clock=lambda: fake[0])

    ml = MetricsLogger(d / "train.jsonl")
    # median epoch time 0.1 s -> threshold factor(0.5) * 0.1 = 0.05 s;
    # the planted gap (0.2 / 2 = 0.1 s) clears it
    for e in range(3):
        _write_skewed_epoch(ml, e, late_rank=1)
    ml.hard_flush()
    agg.poll()
    edges = eng.evaluate(agg)
    assert [(x["state"], x["rule"], x["source"]) for x in edges] == [
        ("fire", "straggler-skew", "r1")]
    assert "rank 1" in edges[0]["message"]

    # still red -> dedup: no further edges
    _write_skewed_epoch(ml, 3, late_rank=1)
    ml.hard_flush()
    fake[0] += 1.0
    agg.poll()
    assert eng.evaluate(agg) == []
    assert eng.firing() == [{"rule": "straggler-skew", "source": "r1"}]

    # attribution moves off rank 1 -> resolve once
    _write_skewed_epoch(ml, 4, late_rank=0)
    ml.hard_flush()
    agg.poll()
    edges = eng.evaluate(agg)
    assert [(x["state"], x["rule"], x["source"]) for x in edges] == [
        ("resolve", "straggler-skew", "r1")]
    assert eng.evaluate(agg) == []
    assert (eng.n_fired, eng.n_resolved) == (1, 1)
    ml.close()


# ---------------- timeline: train track + cross-rank flows ------------


def test_timeline_train_track_and_collective_flows():
    """Train spans land on the dedicated per-rank "train" track on the
    ALIGNED clock, and each epoch's MATCHING collectives across ranks
    become one "collective" flow; compute spans ride no flow."""
    recs0, recs1 = _two_rank_records(n_epochs=2)
    obj = build_timeline([(0, recs0), (1, recs1)])
    evs = [e for e in obj["traceEvents"] if e.get("ph") != "M"]
    slices = [e for e in evs if e["ph"] == "X"]
    assert {e["tid"] for e in slices} == {6}
    names = {e["name"] for e in slices}
    assert names == {"compute", "halo_exchange", "grad_reduce"}
    # the train thread is labeled on both rank processes
    meta = [e for e in obj["traceEvents"] if e.get("ph") == "M"
            and e.get("name") == "thread_name"
            and e["args"]["name"] == "train"]
    assert {m["pid"] for m in meta} == {0, 1}

    flows = [e for e in evs if e["ph"] in ("s", "t", "f")]
    assert flows and all(e["cat"] == "collective" for e in flows)
    # one flow per (epoch, collective op): 2 epochs x (halo L0 +
    # grad_reduce) = 4 flows, each an s -> f pair spanning both pids
    by_id = collections.defaultdict(list)
    for e in flows:
        by_id[e["id"]].append(e)
    assert len(by_id) == 4
    for sites in by_id.values():
        assert [e["ph"] for e in sites] == ["s", "f"]
        assert {e["pid"] for e in sites} == {0, 1}
        # aligned clock: the matching collectives coincide despite the
        # planted 5 s wall skew
        assert sites[0]["ts"] == pytest.approx(sites[1]["ts"], abs=1e-3)
    # compute spans are slices only, never flow endpoints
    comm_ts = {e["ts"] for e in evs if e["ph"] == "X"
               and e["name"] in COMM_OPS}
    for e in flows:
        assert e["ts"] in comm_ts


# ---------------- report: span fallback without a profiler window -----


def test_report_span_fallback_and_divergence(tmp_path):
    """summarize_run derives the measured overlap verdict from spans
    with NO profile record, exposes the contracted --json keys, prints
    the span rows, and trips the divergence flag against the host
    estimate at the shared 0.25 threshold."""
    from pipegcn_tpu.cli.report import format_summary, summarize_run

    recs0, recs1 = _two_rank_records(n_epochs=2)
    records = ([{"event": "summary", "epoch_time_s": 1.0,
                 "comm_cost": {"comm": 0.1}}]
               + recs0 + recs1)
    assert not any(r.get("event") == "profile" for r in records)
    out = summarize_run(records)
    assert "measured_overlap_fraction" not in out
    assert out["overlap_spans"] == pytest.approx(1.0)
    assert out["comm_wait_share_by_rank"] == {"r0": 0.0, "r1": 0.0}
    assert out["straggler_rank"] == 1
    assert out["straggler_max_gap_s"] == pytest.approx(0.1, abs=1e-6)
    assert set(out["trace_clock_offsets"]) == {"r0", "r1"}
    # spans say 1.0, the standalone estimate says 0.1 -> divergence
    assert out["comm_fraction"] == pytest.approx(0.1)
    assert out["overlap_divergence"] is True

    text = format_summary("run", out)
    assert "overlap (spans)" in text and "100.00%" in text
    assert "comm wait share (spans)" in text
    assert "straggler (spans)" in text and "r1" in text
    assert "!! overlap divergence" in text
    # the summary dict IS the --json payload: keys are the contract
    json.dumps(out)


# ---------------- live snapshot + prometheus gauges -------------------


def test_live_snapshot_and_prometheus_gauges(tmp_path):
    """The live plane folds train spans into snapshot()["trainspan"]
    and exports the three contracted gauges with per-rank labels."""
    d = tmp_path / "run"
    d.mkdir()
    ml = MetricsLogger(d / "train.jsonl")
    recs0, recs1 = _two_rank_records(n_epochs=2)
    for rec in recs0 + recs1:
        ml.write(rec)
    ml.close()

    agg = LiveAggregator(str(d))
    agg.poll()
    ts = agg.trainspan()
    assert ts is not None and ts["overlap_spans"] == pytest.approx(1.0)
    snap = agg.snapshot()
    tsnap = snap["trainspan"]
    assert tsnap["overlap_spans"] == pytest.approx(1.0)
    assert tsnap["straggler_rank"] == 1
    assert tsnap["straggler_max_gap_s"] == pytest.approx(0.1, abs=1e-6)
    assert set(tsnap["comm_wait_share_by_rank"]) == {0, 1}
    assert set(tsnap["clock_offsets"]) == {0, 1}

    prom = {}
    for line in prometheus_text(agg, None).splitlines():
        if line and not line.startswith("#"):
            name, val = line.rsplit(" ", 1)
            prom[name] = float(val)
    assert prom["pipegcn_overlap_fraction"] == pytest.approx(1.0)
    assert prom['pipegcn_comm_wait_seconds{rank="0"}'] == 0.0
    assert prom['pipegcn_comm_wait_seconds{rank="1"}'] == 0.0
    assert prom['pipegcn_straggler_gap_seconds{rank="1"}'] == \
        pytest.approx(0.1, abs=1e-6)


# ---------------- zero-recompile pin ----------------------------------


def test_zero_recompile_with_spans_hot(tmp_path):
    """The span plane is host-side bookkeeping only: an identical fit
    with train traces ON compiles exactly the same number of step
    variants as with traces OFF — and the ON run really emitted the
    armed comm tail (the pin covers the hot path, not a dormant one)."""
    from pipegcn_tpu.graph import synthetic_graph
    from pipegcn_tpu.models import ModelConfig
    from pipegcn_tpu.parallel import Trainer, TrainConfig
    from pipegcn_tpu.partition import ShardedGraph, partition_graph

    g = synthetic_graph(num_nodes=200, avg_degree=6, n_feat=8,
                        n_class=3, seed=3)
    parts = partition_graph(g, 2, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=2)
    mcfg = ModelConfig(layer_sizes=(sg.n_feat, 8, sg.n_class),
                       norm="layer", dropout=0.0,
                       train_size=sg.n_train_global)

    def _fit(name, traces):
        t = Trainer(sg, mcfg, TrainConfig(
            lr=0.01, n_epochs=7, enable_pipeline=True, seed=0,
            eval=False, train_traces=traces))
        ml = MetricsLogger(tmp_path / f"{name}.jsonl")
        t.fit(None, log_fn=lambda *a, **k: None, metrics=ml,
              measure_comm_cost=True)
        ml.close()
        return t

    t_on = _fit("on", True)
    t_off = _fit("off", False)
    recs_on = read_metrics(tmp_path / "on.jsonl")
    ops = {r["op"] for r in train_spans(recs_on)}
    assert "compute" in ops and "grad_reduce" in ops  # plane was hot
    assert any(r.get("event") == "tracesync" for r in recs_on)
    assert not train_spans(read_metrics(tmp_path / "off.jsonl"))
    assert t_on._step._cache_size() == t_off._step._cache_size()


# ---------------- the two-process slow-rank drill ---------------------


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_rank(rank, port, tmp_path, extra, n_epochs):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PYTHONPATH": REPO,
        "PYTHONUNBUFFERED": "1",
    }
    cmd = [
        sys.executable, os.path.join(REPO, "main.py"),
        "--dataset", "synthetic:400:6:8:3",
        "--n-partitions", "2", "--parts-per-node", "1",
        "--node-rank", str(rank),
        "--master-addr", "127.0.0.1", "--port", str(port),
        "--n-epochs", str(n_epochs), "--n-hidden", "16",
        "--dropout", "0.0", "--log-every", "1000",
        "--fix-seed", "--seed", "7", "--no-eval",
        "--partition-dir", str(tmp_path / "parts"),
        "--model-dir", str(tmp_path / f"model{rank}"),
        "--results-dir", str(tmp_path / f"results{rank}"),
        "--metrics-out", str(tmp_path / "mx" / f"metrics{rank}.jsonl"),
    ] + extra
    return subprocess.Popen(cmd, env=env, cwd=REPO,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


@pytest.mark.faults
@pytest.mark.slow
def test_two_process_slow_rank_drill(tmp_path):
    """The real thing: a two-process pipelined CPU-mesh run with
    slow-rank@3..6:r1:500 injected. The always-on span plane must (a)
    survive to disk on both ranks, (b) attribute the straggle to rank
    1 on the tracesync-aligned clock, (c) fire the straggler-skew
    alert naming r1 through the live plane, and (d) stitch cross-rank
    collective flows in the timeline."""
    (tmp_path / "mx").mkdir()
    port = _free_port()
    # epochs 3..6 slow on rank 1: comm arming lands after epoch 5, so
    # epoch 6 carries comm spans AND a 500 ms straggle; the last
    # `sustain`(3) attributed dispatches (4, 5, 6) all name rank 1
    plan = ",".join(f"slow-rank@{e}:r1:500" for e in range(3, 7))
    extra = ["--enable-pipeline", "--fault-plan", plan]
    procs = [_spawn_rank(r, port, tmp_path, extra, n_epochs=7)
             for r in (0, 1)]
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            assert p.returncode == 0, out[-4000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    streams = [read_metrics(tmp_path / "mx" / f"metrics{r}.jsonl")
               for r in (0, 1)]
    merged = streams[0] + streams[1]

    # (a) spans survived on BOTH ranks, comm tail included
    for r, recs in enumerate(streams):
        ops = {s["op"] for s in train_spans(recs)}
        assert "compute" in ops, f"rank {r} lost its compute spans"
        assert "grad_reduce" in ops and "halo_exchange" in ops
        assert any(x.get("event") == "tracesync" for x in recs)

    # (b) attribution names the injected rank with a physical gap
    # (median-of-two halves the 500 ms sleep) on a same-host-aligned
    # clock (offsets must be ~0, not the sleep leaking into them)
    fold = fold_spans(merged)
    assert fold["straggler_rank"] == 1
    assert fold["straggler_gap_s_by_rank"][1] > 0.15
    for off in fold["offsets"].values():
        assert abs(off) < 0.2
    recent = [pe for _, pe in sorted(fold["per_epoch"].items())][-3:]
    assert all(pe["straggler_rank"] == 1 for pe in recent)

    # (c) the live plane fires straggler-skew for source r1
    agg = LiveAggregator(str(tmp_path / "mx"))
    agg.poll()
    eng = AlertEngine([r for r in load_rules(None)
                       if r["rule"] == "straggler-skew"])
    edges = eng.evaluate(agg)
    assert [(x["state"], x["source"]) for x in edges
            if x["rule"] == "straggler-skew"] == [("fire", "r1")]
    text = prometheus_text(agg, eng)
    assert 'pipegcn_straggler_gap_seconds{rank="1"}' in text

    # (d) the timeline stitches the epoch-6 collectives across ranks
    obj = build_timeline([(0, streams[0]), (1, streams[1])])
    flows = [e for e in obj["traceEvents"] if e.get("ph") in ("s", "f")
             and e.get("cat") == "collective"]
    by_id = collections.defaultdict(set)
    for e in flows:
        by_id[e["id"]].add(e["pid"])
    assert any(pids == {0, 1} for pids in by_id.values())

    # and the report's span verdict needs no profiler window
    from pipegcn_tpu.cli.report import summarize_run
    out = summarize_run(merged)
    assert out.get("overlap_spans") is not None
    assert out["straggler_rank"] == 1
