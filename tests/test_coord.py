"""Cross-rank coordination tests (resilience/coord.py).

Everything here is tier-1 (single process): the consensus word runs its
REAL jitted psum on the virtual-device mesh (a one-process reduction is
the identity, so encode/decode and the fit() wiring are exercised
without a pod), peer behavior is mocked at the Coordinator surface, and
the watchdog runs against a tmp directory with sub-second timeouts.
The real two-coordinated-process drills live in
tests/test_chaos_multiproc.py (marked slow; scripts/chaos.sh lane).
"""

import io
import json
import os
import time

import numpy as np
import pytest

import jax

from pipegcn_tpu.graph import synthetic_graph
from pipegcn_tpu.models import ModelConfig
from pipegcn_tpu.obs import MetricsLogger
from pipegcn_tpu.parallel import Trainer, TrainConfig
from pipegcn_tpu.partition import ShardedGraph, partition_graph
from pipegcn_tpu.resilience import (
    EXIT_PREEMPTED,
    Agreed,
    CoordConfig,
    Coordinator,
    DivergenceSentinel,
    FaultPlan,
    HeartbeatWatchdog,
    PeerLost,
    Preempted,
    SentinelConfig,
    digest_leaves,
)
from pipegcn_tpu.resilience import coord as coord_mod
from pipegcn_tpu.utils.checkpoint import load_checkpoint, peek_epoch

pytestmark = pytest.mark.faults


@pytest.fixture(scope="module")
def sharded():
    g = synthetic_graph(num_nodes=300, avg_degree=6, n_feat=8, n_class=3,
                        seed=2)
    return ShardedGraph.build(g, partition_graph(g, 2, seed=0), n_parts=2)


def _trainer(sg, **tkw):
    cfg = ModelConfig(layer_sizes=(sg.n_feat, 16, sg.n_class),
                      dropout=0.0, train_size=sg.n_train_global)
    tkw.setdefault("n_epochs", 10)
    tkw.setdefault("log_every", 50)
    return Trainer(sg, cfg, TrainConfig(**tkw))


def _records(buf):
    return [json.loads(line) for line in buf.getvalue().splitlines()]


# ---------------- rank-qualified fault plans ---------------------------


def test_fault_plan_rank_grammar():
    p = FaultPlan.parse("nan-loss@5:r1, sigterm@8:r0,hang@6:r1,desync@7:r1",
                        rank=1)
    assert p.remaining() == ["nan-loss@5:r1", "hang@6:r1", "desync@7:r1",
                             "sigterm@8:r0"]
    # rank-1 plan: its own entries fire, rank-0 ones are inert
    assert p.due_in("nan-loss", 0, 100) == 5
    assert p.due("hang", 6) and p.due("desync", 7)
    assert not p.due("sigterm", 100)
    # rank-0 plan: only the sigterm fires
    q = FaultPlan.parse("nan-loss@5:r1,sigterm@8:r0", rank=0)
    assert q.due_in("nan-loss", 0, 100) is None
    assert q.due("sigterm", 8)
    # unqualified entries fire on every rank
    r = FaultPlan.parse("crash@3", rank=7)
    assert r.due("crash", 3)
    with pytest.raises(ValueError, match=r"kind@epoch\[:rN\]"):
        FaultPlan.parse("nan-loss@5:x1")
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultPlan.parse("meteor@3:r0")


def test_fault_plan_new_kinds_are_boundary_kinds():
    # a resume at-or-past the epoch retires desync/hang like
    # sigterm/crash (they fired at the start of that epoch)
    p = FaultPlan.parse("desync@4:r1,hang@6:r1", rank=1)
    p.skip_before(6)
    assert p.remaining() == []


# ---------------- consensus word (real psum, one process) --------------


def test_consensus_word_roundtrip(sharded):
    t = _trainer(sharded)
    c = Coordinator(t.mesh, cfg=CoordConfig(), force_active=True)
    a = c.agree_step(trip_reason="non-finite loss nan at epoch 3")
    assert a.trip and a.trip_code == 1 and a.trip_rank == 0
    assert "rank 0" in a.trip_reason()
    a = c.agree_boundary(preempt=True)
    assert a.preempt and a.preempt_rank == 0 and not a.trip
    a = c.agree_step()  # healthy word: every bit clear
    assert not (a.trip or a.preempt or a.desync)
    assert a.n_ranks == 1
    c.barrier()  # no-op barrier completes


def test_consensus_inactive_is_local_noop(sharded):
    t = _trainer(sharded)
    c = Coordinator(t.mesh, cfg=CoordConfig(), rank=0, n_ranks=1)
    assert not c.active
    # no collective machinery is even built
    assert c._consensus is None
    a = c.agree_step(trip_reason="non-finite loss")
    assert a.trip and a.trip_rank == 0  # local decode, zero collectives
    c.check_peers()  # no watchdog, no raise
    c.start()
    assert c.watchdog is None
    c.stop()


def test_digest_leaves_and_desync_check(sharded):
    t = _trainer(sharded)
    host = jax.device_get(t.state["params"])
    d1 = digest_leaves(host)
    assert d1.dtype == np.uint32 and len(d1) > 0
    # deterministic, and sensitive to a single-leaf perturbation
    assert np.array_equal(d1, digest_leaves(host))
    import jax.tree_util as jtu

    bumped = jtu.tree_map(lambda a: np.asarray(a) * np.asarray(
        1.001, np.asarray(a).dtype), host)
    d2 = digest_leaves(bumped)
    assert not np.array_equal(d1, d2)

    c = Coordinator(t.mesh, cfg=CoordConfig(), force_active=True)
    # one process: broadcast0 returns our own digests -> no mismatch
    assert c.desync_check(host) is False
    assert c.last_desync_mismatch == 0
    # a diverged "rank 0 reference" surfaces as a local mismatch
    c._consensus.broadcast0 = lambda v: d2
    assert c.desync_check(host) is True
    assert c.last_desync_mismatch > 0


def test_resync_roundtrip(sharded, tmp_path):
    t = _trainer(sharded)
    c = Coordinator(t.mesh, cfg=CoordConfig(dir=str(tmp_path / "coord")),
                    force_active=True)
    c.resync(t, epoch=7)  # rank 0: writes the canonical state
    d = str(tmp_path / "coord" / "resync")
    assert peek_epoch(d) == 7
    host, ep = load_checkpoint(d, jax.device_get(t.state))
    assert ep == 7  # digest-verified load succeeded


# ---------------- heartbeat watchdog -----------------------------------


def test_watchdog_detects_silent_peer(tmp_path):
    wd = HeartbeatWatchdog(str(tmp_path), rank=0, n_ranks=2,
                           timeout_s=0.4, interval_s=0.05, grace_s=30.0,
                           log=lambda s: None)
    wd.start()
    try:
        wd.check()  # peers get a startup grace from watchdog start
        deadline = time.time() + 5.0
        while wd.lost is None and time.time() < deadline:
            time.sleep(0.05)
        assert wd.lost is not None and wd.lost[0] == 1
        with pytest.raises(PeerLost, match="peer rank 1"):
            wd.check()
    finally:
        wd.stop()
    # own heartbeat file existed while running, removed on stop
    assert not os.path.exists(wd.path_for(0))


def test_watchdog_beating_peer_never_trips(tmp_path):
    wd = HeartbeatWatchdog(str(tmp_path), rank=0, n_ranks=2,
                           timeout_s=0.5, interval_s=0.05,
                           log=lambda s: None)
    wd.start()
    try:
        end = time.time() + 1.2
        peer = wd.path_for(1)
        while time.time() < end:
            with open(peer, "a"):
                os.utime(peer, None)
            time.sleep(0.05)
        assert wd.lost is None
        wd.check()
    finally:
        wd.stop()


def test_watchdog_hard_deadline_fires_when_unhandled(tmp_path):
    fired = []
    wd = HeartbeatWatchdog(str(tmp_path), rank=0, n_ranks=2,
                           timeout_s=0.3, interval_s=0.05, grace_s=0.2,
                           on_deadline=lambda peer, age: fired.append(peer),
                           log=lambda s: None)
    wd.start()
    try:
        deadline = time.time() + 5.0
        while not fired and time.time() < deadline:
            time.sleep(0.05)
        assert fired == [1]
    finally:
        wd.stop()


def test_watchdog_disarm_blocks_hard_deadline(tmp_path):
    fired = []
    wd = HeartbeatWatchdog(str(tmp_path), rank=0, n_ranks=2,
                           timeout_s=0.3, interval_s=0.05, grace_s=0.3,
                           on_deadline=lambda *a: fired.append(a),
                           log=lambda s: None)
    wd.start()
    try:
        deadline = time.time() + 5.0
        while wd.lost is None and time.time() < deadline:
            time.sleep(0.05)
        wd.disarm()  # main thread took responsibility (check()/verdict)
        time.sleep(0.8)
        assert fired == []
    finally:
        wd.stop()


def test_coordinator_hard_deadline_emergency(sharded, tmp_path,
                                             monkeypatch):
    """The monitor-thread emergency: fault record + snapshot checkpoint
    + exit 75, without touching the (possibly wedged) device."""
    exits = []
    monkeypatch.setattr(coord_mod, "_hard_exit",
                        lambda code: exits.append(code))
    t = _trainer(sharded)
    buf = io.StringIO()
    c = Coordinator(t.mesh, cfg=CoordConfig(dir=str(tmp_path)),
                    metrics=MetricsLogger(buf), log=lambda s: None,
                    force_active=True)
    ck = str(tmp_path / "ck")
    c.set_checkpoint(ck, keep=2)
    c.note_snapshot(6, jax.device_get(t.state))
    c.note_progress(8)
    c._on_hard_deadline(1, 12.5)
    assert exits == [EXIT_PREEMPTED]
    assert peek_epoch(ck) == 6  # the HOST-side snapshot, digest-valid
    load_checkpoint(ck, jax.device_get(t.state))
    recs = _records(buf)
    f = next(r for r in recs if r["event"] == "fault")
    assert f["kind"] == "peer-lost" and f["peer_rank"] == 1
    assert f["hard_deadline"] is True and f["epoch"] == 8


# ---------------- consensus-driven lockstep actions in fit() -----------
# (the mocked single-process variant of the pod drills: a word with the
# trip/preempt bit set must invoke the SAME recovery actions a local
# fault would — that is what keeps a real pod in lockstep)


def test_consensus_trip_invokes_lockstep_rollback(sharded, monkeypatch):
    """A trip bit raised by a PEER (this rank's sentinel saw nothing)
    must roll back, back off the LR, and recover exactly like a local
    trip."""
    t = _trainer(sharded, enable_pipeline=True)
    lr0 = t.tcfg.lr
    c = Coordinator(t.mesh, cfg=CoordConfig(), force_active=True,
                    log=lambda s: None)
    orig = c.agree_step
    state = {"fired": False}

    def fake_agree_step(trip_reason=None, desync=False):
        a = orig(trip_reason=trip_reason, desync=desync)
        if not state["fired"] and c._progress_epoch >= 5 \
                and trip_reason is None:
            state["fired"] = True
            return Agreed(trip=True, trip_code=1, trip_rank=1, n_ranks=2)
        return a

    monkeypatch.setattr(c, "agree_step", fake_agree_step)
    buf = io.StringIO()
    logs = []
    t.fit(eval_graphs=None, log_fn=logs.append,
          metrics=MetricsLogger(buf),
          sentinel=DivergenceSentinel(SentinelConfig(snapshot_every=3)),
          coord=c)
    recs = _records(buf)
    faults = [r for r in recs if r["event"] == "fault"]
    assert [f["kind"] for f in faults] == ["divergence"]
    assert faults[0]["agreed"] is True and faults[0]["source_rank"] == 1
    assert faults[0]["rollback_epoch"] < 5
    assert any(r["event"] == "recovery" for r in recs)
    assert abs(t.tcfg.lr - lr0 * 0.5) < 1e-12  # backed off in lockstep
    assert t.last_epoch == t.tcfg.n_epochs
    assert any("consensus: rank 1 tripped" in line for line in logs)


def test_consensus_trip_without_local_sentinel(sharded, monkeypatch):
    """Mixed config safety: even with the LOCAL sentinel disabled, a
    peer's agreed trip must execute the rollback (defaults) — skipping
    it would desynchronize the pod."""
    t = _trainer(sharded)
    c = Coordinator(t.mesh, cfg=CoordConfig(), force_active=True,
                    log=lambda s: None)
    orig = c.agree_step
    state = {"fired": False}

    def fake_agree_step(trip_reason=None, desync=False):
        a = orig(trip_reason=trip_reason, desync=desync)
        if not state["fired"] and c._progress_epoch >= 4:
            state["fired"] = True
            return Agreed(trip=True, trip_code=4, trip_rank=1, n_ranks=2)
        return a

    monkeypatch.setattr(c, "agree_step", fake_agree_step)
    logs = []
    t.fit(eval_graphs=None, log_fn=logs.append, sentinel=None, coord=c)
    assert t.last_epoch == t.tcfg.n_epochs
    assert any("sentinel tripped" in line for line in logs)


def test_peer_preemption_propagates_and_checkpoints(sharded, tmp_path,
                                                    monkeypatch):
    """Satellite: the rank that RECEIVES a propagated preemption (never
    saw a signal itself) checkpoints and raises Preempted — the CLI
    maps it to exit 75 like a local one."""
    t = _trainer(sharded)
    c = Coordinator(t.mesh, cfg=CoordConfig(), force_active=True,
                    log=lambda s: None)
    orig = c.agree_boundary
    state = {"fired": False}

    def fake_agree_boundary(preempt=False, sdc_code=0):
        a = orig(preempt=preempt, sdc_code=sdc_code)
        if not state["fired"] and c._progress_epoch >= 6 and not preempt:
            state["fired"] = True
            return Agreed(preempt=True, preempt_rank=1, n_ranks=2)
        return a

    monkeypatch.setattr(c, "agree_boundary", fake_agree_boundary)
    ck = str(tmp_path / "ck")
    buf = io.StringIO()
    with pytest.raises(Preempted) as ei:
        t.fit(eval_graphs=None, log_fn=lambda s: None,
              metrics=MetricsLogger(buf), checkpoint_dir=ck, coord=c)
    assert "peer preemption (rank 1)" in str(ei.value)
    assert ei.value.epoch == 6
    assert peek_epoch(ck) == 6
    recs = _records(buf)
    f = next(r for r in recs if r["event"] == "fault")
    assert f["kind"] == "preemption" and f["agreed"] is True
    assert f["source_rank"] == 1


def test_peer_lost_nonzero_rank_saves_crash_checkpoint(sharded, tmp_path,
                                                       monkeypatch):
    """Satellite: on PeerLost, EVERY surviving rank saves (rank 0 may
    be the dead one) — here the process pretends to be rank 1 and must
    still write a digest-valid, loadable crash checkpoint."""
    t = _trainer(sharded)
    c = Coordinator(t.mesh, cfg=CoordConfig(), force_active=True,
                    log=lambda s: None)

    def fake_check_peers():
        if c._progress_epoch >= 4:
            raise PeerLost(0, 33.0)

    monkeypatch.setattr(c, "check_peers", fake_check_peers)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    ck = str(tmp_path / "ck")
    buf = io.StringIO()
    logs = []
    with pytest.raises(PeerLost, match="peer rank 0"):
        t.fit(eval_graphs=None, log_fn=logs.append,
              metrics=MetricsLogger(buf), checkpoint_dir=ck, coord=c)
    assert any("peer-lost checkpoint saved" in line for line in logs)
    assert peek_epoch(ck) == 4
    host, ep = load_checkpoint(ck, jax.device_get(t.state))
    assert ep == 4  # digest-verified
    recs = _records(buf)
    f = next(r for r in recs if r["event"] == "fault")
    assert f["kind"] == "peer-lost" and f["peer_rank"] == 0
    assert f["rank"] == 1


def test_cli_entry_maps_peer_lost_to_exit_75(monkeypatch):
    import pipegcn_tpu.cli.main as cli_main

    # PeerLost exits via os._exit (bypassing jax's atexit distributed
    # shutdown, whose barrier aborts with a dead peer); intercept it
    exits = []

    def fake_exit(code):
        exits.append(code)
        raise SystemExit(code)

    monkeypatch.setattr(cli_main.os, "_exit", fake_exit)
    monkeypatch.setattr(cli_main, "run",
                        lambda args: (_ for _ in ()).throw(
                            PeerLost(2, 40.0)))
    monkeypatch.setattr("sys.argv", ["prog", "--dataset", "x",
                                     "--checkpoint-dir", "ck"])
    with pytest.raises(SystemExit):
        cli_main.cli_entry()
    assert exits == [EXIT_PREEMPTED]


def test_desync_abort_is_resumable(sharded, tmp_path, monkeypatch):
    """Agreed desync without --desync-resync: fault record + Preempted
    (resumable exit 75), rank 0's state rides the crash checkpoint."""
    t = _trainer(sharded)
    c = Coordinator(t.mesh, cfg=CoordConfig(dir=str(tmp_path / "coord")),
                    force_active=True, log=lambda s: None)
    orig = c.agree_step
    state = {"fired": False}

    def fake_agree_step(trip_reason=None, desync=False):
        a = orig(trip_reason=trip_reason, desync=desync)
        if not state["fired"] and c._progress_epoch >= 3:
            state["fired"] = True
            return Agreed(desync=True, desync_rank=1, n_ranks=2)
        return a

    monkeypatch.setattr(c, "agree_step", fake_agree_step)
    ck = str(tmp_path / "ck")
    buf = io.StringIO()
    with pytest.raises(Preempted, match="desync"):
        t.fit(eval_graphs=None, log_fn=lambda s: None,
              metrics=MetricsLogger(buf), checkpoint_dir=ck, coord=c)
    assert peek_epoch(ck) is not None
    recs = _records(buf)
    kinds = [r["kind"] for r in recs if r["event"] == "fault"]
    assert kinds == ["desync"]


def test_desync_resync_recovers_in_fit(sharded, tmp_path, monkeypatch):
    """Agreed desync with resync enabled: rank 0 publishes its state,
    training continues to completion, recovery record emitted."""
    t = _trainer(sharded)
    c = Coordinator(t.mesh,
                    cfg=CoordConfig(dir=str(tmp_path / "coord"),
                                    desync_resync=True),
                    force_active=True, log=lambda s: None)
    orig = c.agree_step
    state = {"fired": False}

    def fake_agree_step(trip_reason=None, desync=False):
        a = orig(trip_reason=trip_reason, desync=desync)
        if not state["fired"] and c._progress_epoch >= 3:
            state["fired"] = True
            return Agreed(desync=True, desync_rank=1, n_ranks=2)
        return a

    monkeypatch.setattr(c, "agree_step", fake_agree_step)
    buf = io.StringIO()
    t.fit(eval_graphs=None, log_fn=lambda s: None,
          metrics=MetricsLogger(buf), coord=c)
    assert t.last_epoch == t.tcfg.n_epochs
    recs = _records(buf)
    assert any(r["event"] == "fault" and r["kind"] == "desync"
               for r in recs)
    assert any(r["event"] == "recovery" and r["kind"] == "desync"
               for r in recs)
    # rank 0 published the canonical state to the coordination dir
    assert peek_epoch(str(tmp_path / "coord" / "resync")) is not None


# ---------------- obs: rank fields + per-rank report -------------------


def test_fault_records_carry_rank(tmp_path):
    buf = io.StringIO()
    ml = MetricsLogger(buf)
    ml.fault(kind="divergence", epoch=3)
    ml.fault(kind="desync", epoch=5, rank=2, source_rank=1, agreed=True)
    ml.recovery(kind="divergence", epoch=7)
    recs = _records(buf)
    assert recs[0]["rank"] == 0  # autofilled (single process)
    assert recs[1]["rank"] == 2  # explicit wins
    assert recs[2]["rank"] == 0


def test_report_aggregates_faults_per_rank():
    from pipegcn_tpu.cli.report import format_summary, summarize_run

    records = [
        {"event": "fault", "kind": "divergence", "epoch": 5, "rank": 1,
         "agreed": True, "source_rank": 1},
        {"event": "fault", "kind": "divergence", "epoch": 5, "rank": 0,
         "agreed": True, "source_rank": 1},
        {"event": "fault", "kind": "peer-lost", "epoch": 9, "rank": 0,
         "peer_rank": 1},
        {"event": "recovery", "kind": "divergence", "epoch": 7,
         "rank": 0},
    ]
    s = summarize_run(records)
    assert s["n_faults"] == 3 and s["n_recoveries"] == 1
    assert s["fault_ranks"] == {"r0": 2, "r1": 1}
    assert s["fault_source_ranks"] == {"r1": 2}
    assert s["n_agreed_faults"] == 2
    text = format_summary("x.jsonl", s)
    assert "faults by rank" in text and "r0x2" in text
    assert "consensus source ranks" in text
