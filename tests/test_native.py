"""Native C++ component tests.

The native library is built on demand from bundled sources (g++ is part
of the supported toolchain); these tests exercise the ctypes surface and
check the multilevel partitioner beats the quality of random assignment
and respects the same invariants as the Python fallback.
"""

import numpy as np
import pytest

from pipegcn_tpu.graph import synthetic_graph
from pipegcn_tpu.partition.partitioner import (
    _sym_adj,
    comm_volume,
    edge_cut,
    partition_graph,
)

native = pytest.importorskip("pipegcn_tpu.native")

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library not buildable here"
)


@pytest.fixture(scope="module")
def graph():
    return synthetic_graph(
        num_nodes=3000, avg_degree=10, n_feat=8, n_class=4, seed=1
    )


def _native_parts(g, n_parts, obj="vol", seed=0):
    adj = _sym_adj(g)
    return native.native_partition(
        adj.indptr.astype(np.int64), adj.indices.astype(np.int32),
        n_parts, obj=obj, seed=seed,
    )


def test_partition_valid_and_balanced(graph):
    for k in (2, 4, 7):
        parts = _native_parts(graph, k)
        assert parts.shape == (graph.num_nodes,)
        assert parts.min() >= 0 and parts.max() < k
        sizes = np.bincount(parts, minlength=k)
        assert sizes.min() > 0
        # balance cap: 1.05 imbalance plus slack for integer rounding
        assert sizes.max() <= 1.10 * (graph.num_nodes / k) + 2


def test_partition_deterministic(graph):
    a = _native_parts(graph, 4, seed=7)
    b = _native_parts(graph, 4, seed=7)
    assert np.array_equal(a, b)


def test_partition_beats_random(graph):
    random_parts = partition_graph(graph, 4, method="random", seed=0)
    for obj, metric in (("cut", edge_cut), ("vol", comm_volume)):
        parts = _native_parts(graph, 4, obj=obj)
        assert metric(graph, parts) < 0.7 * metric(graph, random_parts)


def test_partition_graph_dispatches_to_native(graph, monkeypatch):
    """method='metis' must route through the native partitioner when it is
    available and produce identical output to a direct call."""
    via_api = partition_graph(graph, 4, method="metis", obj="vol", seed=3)
    direct = _native_parts(graph, 4, obj="vol", seed=3)
    assert np.array_equal(via_api, direct)


def test_python_fallback_when_disabled(graph, monkeypatch):
    monkeypatch.setenv("PIPEGCN_NATIVE", "0")
    # get_lib caches; bypass by checking the partition API still works with
    # the cached lib regardless, then the env var path on a fresh state
    import importlib

    import pipegcn_tpu.native as nat

    importlib.reload(nat)
    assert not nat.available()
    parts = partition_graph(graph, 4, method="metis", obj="vol", seed=0)
    sizes = np.bincount(parts, minlength=4)
    assert sizes.min() > 0
    importlib.reload(nat)  # restore for other tests


def test_single_partition(graph):
    parts = _native_parts(graph, 1)
    assert np.array_equal(parts, np.zeros(graph.num_nodes, np.int32))


def test_radix_argsort_matches_numpy_stable():
    rng = np.random.default_rng(11)
    for n, hi in ((0, 10), (1, 1), (1000, 50), (100_000, 2**40)):
        keys = rng.integers(0, hi, n, dtype=np.int64)
        got = native.radix_argsort(keys.astype(np.uint64))
        want = np.argsort(keys, kind="stable")
        assert np.array_equal(got, want), f"n={n} hi={hi}"


def test_build_native_sort_matches_numpy(graph, monkeypatch):
    """ShardedGraph.build must produce bit-identical artifacts with the
    native radix sort and the numpy fallback (sorts are both stable on
    the same fused keys)."""
    from pipegcn_tpu.partition import ShardedGraph, partition_graph
    from pipegcn_tpu.partition import halo as halo_mod

    parts = partition_graph(graph, 4, seed=0)

    # force the native path even below the size cutoff
    real = halo_mod._stable_argsort
    monkeypatch.setattr(
        halo_mod, "_stable_argsort",
        lambda k: native.radix_argsort(k.astype(np.uint64)))
    sg_native = ShardedGraph.build(graph, parts, n_parts=4)
    monkeypatch.setattr(
        halo_mod, "_stable_argsort",
        lambda k: np.argsort(k, kind="stable"))
    sg_numpy = ShardedGraph.build(graph, parts, n_parts=4)
    monkeypatch.setattr(halo_mod, "_stable_argsort", real)

    for name in ShardedGraph._ARRAYS:
        assert np.array_equal(getattr(sg_native, name),
                              getattr(sg_numpy, name)), name
