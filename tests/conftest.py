"""Test configuration: force JAX onto 8 virtual CPU devices so multi-device
sharding (the TPU analogue of the reference's localhost-gloo multiprocess
testing, SURVEY.md §4) is exercised without TPU hardware.

XLA_FLAGS must be set before the CPU backend initializes; the platform
choice is applied via jax.config (the environment's site hook pins
JAX_PLATFORMS, so the env var alone is not enough).
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")
