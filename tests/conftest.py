"""Test configuration: force JAX onto 8 virtual CPU devices so multi-device
sharding (the TPU analogue of the reference's localhost-gloo multiprocess
testing, SURVEY.md §4) is exercised without TPU hardware.

Must run before jax is imported anywhere in the test process.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"
