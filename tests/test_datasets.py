"""Real-dataset loader fixture tests.

The reddit/ogb/yelp loaders (graph/datasets.py) parse three different
raw on-disk layouts; the real archives can't be downloaded here, so each
test synthesizes a tiny byte-faithful replica of the layout in a tmpdir
(reddit_data.npz/reddit_graph.npz; OGB's raw/+split/ in BOTH flavors —
plain npy/csv.gz arrays and the papers100M compressed-npz; yelp's
GraphSAINT files), then asserts loader invariants and runs a 2-partition
training epoch end to end. Mirrors reference helper/utils.py:17-96.
"""

import gzip
import json
import os

import numpy as np
import pytest

from pipegcn_tpu.graph.datasets import is_multilabel, load_data, n_classes
from pipegcn_tpu.models import ModelConfig
from pipegcn_tpu.parallel import Trainer, TrainConfig
from pipegcn_tpu.partition import ShardedGraph, partition_graph

N = 40  # nodes in every fixture graph
E = 120


def _rand_edges(rng, n=N, e=E):
    return rng.integers(0, n, e), rng.integers(0, n, e)


def _check_canonical(g):
    """finalize() invariants every loader must deliver."""
    # exactly one self-loop per node
    loops = g.src == g.dst
    assert np.array_equal(np.sort(g.src[loops]), np.arange(g.num_nodes))
    assert "in_deg" in g.ndata
    assert g.ndata["in_deg"].min() >= 1.0
    for k in ("train_mask", "val_mask", "test_mask"):
        assert g.ndata[k].dtype == bool


def _train_two_parts(g):
    """2-partition end-to-end epoch (the reference's smallest config)."""
    parts = partition_graph(g, 2, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=2)
    cfg = ModelConfig(layer_sizes=(sg.n_feat, 8, sg.n_class), norm="layer",
                      dropout=0.0, train_size=sg.n_train_global)
    t = Trainer(sg, cfg, TrainConfig(seed=0, enable_pipeline=True))
    losses = [t.train_epoch(e) for e in range(2)]
    assert np.isfinite(losses).all()


# ---------------------------------------------------------------------
# reddit: reddit_data.npz + reddit_graph.npz (scipy sparse)

@pytest.fixture
def reddit_root(tmp_path):
    import scipy.sparse as sp

    rng = np.random.default_rng(0)
    d = tmp_path / "reddit"
    d.mkdir()
    feature = rng.standard_normal((N, 6)).astype(np.float32)
    label = rng.integers(0, 5, N)
    # node_types: 1=train, 2=val, 3=test (DGL raw convention)
    node_types = np.ones(N, np.int64)
    node_types[25:32] = 2
    node_types[32:] = 3
    np.savez(d / "reddit_data.npz", feature=feature, label=label,
             node_types=node_types)
    src, dst = _rand_edges(rng)
    adj = sp.coo_matrix((np.ones(E), (src, dst)), shape=(N, N))
    sp.save_npz(d / "reddit_graph.npz", adj.tocsr())
    return str(tmp_path)


def test_load_reddit(reddit_root):
    g = load_data("reddit", reddit_root)
    _check_canonical(g)
    assert g.num_nodes == N
    assert g.ndata["feat"].shape == (N, 6)
    assert not is_multilabel(g)
    assert n_classes(g) == 5
    assert g.ndata["train_mask"].sum() == 25
    assert g.ndata["val_mask"].sum() == 7
    assert g.ndata["test_mask"].sum() == 8
    _train_two_parts(g)


# ---------------------------------------------------------------------
# OGB: products flavor (plain arrays) and papers100M flavor (npz)

def _write_split(base, split_name):
    sdir = base / "split" / split_name
    sdir.mkdir(parents=True)
    idx = {"train": np.arange(0, 24), "valid": np.arange(24, 32),
           "test": np.arange(32, N)}
    for part, ids in idx.items():
        with gzip.open(sdir / f"{part}.csv.gz", "wt") as f:
            f.write("\n".join(str(i) for i in ids) + "\n")


@pytest.fixture
def products_root(tmp_path, request):
    """ogbn-products raw layout; param 'npy' or 'csv' picks the array
    flavor (_load_any probes npy first, then csv.gz)."""
    rng = np.random.default_rng(1)
    base = tmp_path / "ogbn_products"
    raw = base / "raw"
    raw.mkdir(parents=True)
    edges = np.stack(_rand_edges(rng), axis=1)
    feat = rng.standard_normal((N, 7)).astype(np.float32)
    label = rng.integers(0, 4, N).astype(np.float64)
    label[5] = np.nan  # an unlabeled node (appears in real OGB data)
    if request.param == "npy":
        np.save(raw / "edge.npy", edges)
        np.save(raw / "node-feat.npy", feat)
        np.save(raw / "node-label.npy", label)
    else:
        def _csv(fname, arr, fmt):
            with gzip.open(raw / fname, "wt") as f:
                np.savetxt(f, arr, delimiter=",", fmt=fmt)
        _csv("edge.csv.gz", edges, "%d")
        _csv("node-feat.csv.gz", feat, "%.6f")
        _csv("node-label.csv.gz", label, "%.1f")
    _write_split(base, "sales_ranking")
    return str(tmp_path)


@pytest.mark.parametrize("products_root", ["npy", "csv"], indirect=True)
def test_load_ogbn_products(products_root):
    g = load_data("ogbn-products", products_root)
    _check_canonical(g)
    assert g.num_nodes == N
    assert not is_multilabel(g)
    assert g.ndata["label"][5] == -1  # NaN label -> -1
    assert g.ndata["train_mask"].sum() == 24
    # directed raw edges are mirrored before self-loop normalization
    non_loop = g.src != g.dst
    fwd = set(zip(g.src[non_loop].tolist(), g.dst[non_loop].tolist()))
    assert all((b, a) in fwd for a, b in fwd)
    _train_two_parts(g)


def test_load_ogbn_products_csv_without_pandas(tmp_path, monkeypatch):
    """The csv.gz fallback must work when pandas is unavailable."""
    import sys

    rng = np.random.default_rng(4)
    base = tmp_path / "ogbn_products"
    raw = base / "raw"
    raw.mkdir(parents=True)
    edges = np.stack(_rand_edges(rng), axis=1)
    with gzip.open(raw / "edge.csv.gz", "wt") as f:
        np.savetxt(f, edges, delimiter=",", fmt="%d")
    np.save(raw / "node-feat.npy",
            rng.standard_normal((N, 5)).astype(np.float32))
    np.save(raw / "node-label.npy", rng.integers(0, 3, N).astype(np.float64))
    _write_split(base, "sales_ranking")
    monkeypatch.setitem(sys.modules, "pandas", None)  # import -> ImportError
    g = load_data("ogbn-products", str(tmp_path))
    assert g.num_nodes == N


@pytest.fixture
def papers_root(tmp_path):
    """ogbn-papers100M compressed-npz layout + 'time' split dir."""
    rng = np.random.default_rng(2)
    base = tmp_path / "ogbn_papers100m"
    raw = base / "raw"
    raw.mkdir(parents=True)
    src, dst = _rand_edges(rng)
    edge_index = np.stack([src, dst])  # [2, E] like the real archive
    feat = rng.standard_normal((N, 8)).astype(np.float16)  # real is f16
    np.savez(raw / "data.npz", edge_index=edge_index, node_feat=feat)
    label = rng.integers(0, 6, N).astype(np.float32)
    label[10:14] = np.nan  # most papers100M nodes are unlabeled
    np.savez(raw / "node-label.npz", node_label=label.reshape(-1, 1))
    _write_split(base, "time")
    return str(tmp_path)


def test_load_ogbn_papers100m(papers_root):
    g = load_data("ogbn-papers100M", papers_root)
    _check_canonical(g)
    assert g.num_nodes == N
    assert g.ndata["feat"].dtype == np.float32
    assert (g.ndata["label"][10:14] == -1).all()
    assert n_classes(g) == 6
    _train_two_parts(g)


def test_load_ogb_missing_split_raises(tmp_path):
    rng = np.random.default_rng(3)
    raw = tmp_path / "ogbn_products" / "raw"
    raw.mkdir(parents=True)
    np.save(raw / "edge.npy", np.stack(_rand_edges(rng), axis=1))
    np.save(raw / "node-feat.npy",
            rng.standard_normal((N, 4)).astype(np.float32))
    np.save(raw / "node-label.npy", rng.integers(0, 3, N).astype(np.float64))
    with pytest.raises(FileNotFoundError, match="split"):
        load_data("ogbn-products", str(tmp_path))


def test_load_ogb_missing_arrays_raises(tmp_path):
    raw = tmp_path / "ogbn_products" / "raw"
    raw.mkdir(parents=True)
    (tmp_path / "ogbn_products" / "split" / "sales_ranking").mkdir(
        parents=True)
    with pytest.raises(FileNotFoundError, match="missing"):
        load_data("ogbn-products", str(tmp_path))


# ---------------------------------------------------------------------
# yelp: GraphSAINT layout (multi-label, train-fit standardization)

@pytest.fixture
def yelp_root(tmp_path):
    import scipy.sparse as sp

    rng = np.random.default_rng(5)
    d = tmp_path / "yelp"
    d.mkdir()
    src, dst = _rand_edges(rng)
    adj = sp.coo_matrix((np.ones(E), (src, dst)), shape=(N, N))
    sp.save_npz(d / "adj_full.npz", adj.tocsr())
    feats = rng.standard_normal((N, 9)).astype(np.float64) * 3 + 1
    np.save(d / "feats.npy", feats)
    n_cls = 4
    class_map = {str(i): rng.integers(0, 2, n_cls).tolist() for i in range(N)}
    with open(d / "class_map.json", "w") as f:
        json.dump(class_map, f)
    role = {"tr": list(range(0, 24)), "va": list(range(24, 32)),
            "te": list(range(32, N))}
    with open(d / "role.json", "w") as f:
        json.dump(role, f)
    return str(tmp_path)


def test_load_yelp(yelp_root):
    g = load_data("yelp", yelp_root)
    _check_canonical(g)
    assert is_multilabel(g)
    assert n_classes(g) == 4
    assert g.ndata["label"].shape == (N, 4)
    # standardization was fit on TRAIN nodes only
    tr = g.ndata["feat"][g.ndata["train_mask"]]
    np.testing.assert_allclose(tr.mean(axis=0), 0.0, atol=1e-5)
    np.testing.assert_allclose(tr.std(axis=0), 1.0, atol=1e-5)
    assert abs(float(g.ndata["feat"].mean())) > 1e-8  # not global-fit
    _train_two_parts(g)


def test_yelp_overlapping_roles_rejected(yelp_root):
    d = os.path.join(yelp_root, "yelp")
    with open(os.path.join(d, "role.json")) as f:
        role = json.load(f)
    role["va"] = role["va"] + [0]  # node 0 is already train
    with open(os.path.join(d, "role.json"), "w") as f:
        json.dump(role, f)
    with pytest.raises(AssertionError):
        load_data("yelp", yelp_root)


@pytest.mark.parametrize("products_root", ["npy"], indirect=True)
def test_load_ogb_mmap_matches_plain_products(products_root):
    """The RAM-bounded finalized-edge cache path must load a graph
    equivalent to the in-RAM path: same edge multiset (checksum), same
    degrees, same node data — with memmapped src/dst/feat."""
    from pipegcn_tpu.graph.datasets import load_ogb
    from pipegcn_tpu.partition.halo import ShardedGraph

    ref = load_ogb("ogbn-products", products_root, mmap=False)
    mm = load_ogb("ogbn-products", products_root, mmap=True)
    assert isinstance(mm.src, np.memmap)
    assert isinstance(mm.ndata["feat"], np.memmap)
    assert mm.num_nodes == ref.num_nodes
    assert mm.num_edges == ref.num_edges
    assert ShardedGraph.edge_checksum(mm) == ShardedGraph.edge_checksum(ref)
    np.testing.assert_array_equal(mm.ndata["in_deg"], ref.ndata["in_deg"])
    np.testing.assert_array_equal(np.asarray(mm.ndata["feat"]),
                                  ref.ndata["feat"])
    np.testing.assert_array_equal(mm.ndata["label"], ref.ndata["label"])
    # second load hits the ready cache (meta.json short-circuit)
    mm2 = load_ogb("ogbn-products", products_root, mmap=True)
    assert mm2.num_edges == mm.num_edges


def test_load_ogb_mmap_matches_plain_papers(papers_root):
    from pipegcn_tpu.graph.datasets import load_ogb
    from pipegcn_tpu.partition.halo import ShardedGraph

    # load_data lowercases before dispatching to load_ogb
    ref = load_ogb("ogbn-papers100m", papers_root, mmap=False)
    mm = load_ogb("ogbn-papers100m", papers_root, mmap=True)
    assert isinstance(mm.src, np.memmap)
    assert mm.ndata["feat"].dtype == np.float32
    assert ShardedGraph.edge_checksum(mm) == ShardedGraph.edge_checksum(ref)
    np.testing.assert_array_equal(mm.ndata["in_deg"], ref.ndata["in_deg"])
    np.testing.assert_allclose(np.asarray(mm.ndata["feat"]),
                               ref.ndata["feat"])
