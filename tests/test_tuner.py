"""SpMM auto-tuner tests (ops/tuner.py + Trainer._resolve_auto).

The contract under test: spmm_impl='auto' resolves from a MEASURED
cost table — the artifact's persisted tuning.json when trusted, a live
micro-bench campaign otherwise — never from hand-coded shape
thresholds. Covers the cost-table persistence round-trip through both
artifact formats (v2 npz and v3 mmap), deterministic table-driven
dispatch on two distinct synthetic shapes, and the loud live-retune
fallback on stale/corrupt tables.
"""

import json
import os

import numpy as np
import pytest

from pipegcn_tpu.graph import synthetic_graph
from pipegcn_tpu.models import ModelConfig
from pipegcn_tpu.ops import tuner
from pipegcn_tpu.parallel import TrainConfig, Trainer
from pipegcn_tpu.partition import ShardedGraph, partition_graph

pytestmark = pytest.mark.tuning


@pytest.fixture(autouse=True)
def _fresh_memo():
    tuner.clear_memo()
    yield
    tuner.clear_memo()


def _sharded(num_nodes=400, avg_degree=8, n_feat=12, n_class=4,
             seed=11, n_parts=1, homophily=0.5):
    g = synthetic_graph(num_nodes=num_nodes, avg_degree=avg_degree,
                        n_feat=n_feat, n_class=n_class, seed=seed,
                        homophily=homophily)
    parts = partition_graph(g, n_parts, seed=0)
    return ShardedGraph.build(g, parts, n_parts=n_parts)


def _cfg(sg, **kw):
    kw.setdefault("spmm_impl", "auto")
    kw.setdefault("tuner_samples", 5000)
    return ModelConfig(layer_sizes=(sg.n_feat, 16, sg.n_class),
                       norm="layer", dropout=0.0,
                       train_size=sg.n_train_global, **kw)


def _trainer_width(cfg):
    # the width Trainer._resolve_auto keys the signature on
    return max(cfg.layer_sizes[:cfg.n_graph_layers])


# ---------------- candidate grid (pure) -------------------------------


def test_candidate_grid_full_and_pinned():
    full = tuner.candidate_grid()
    names = [c["name"] for c in full]
    assert len(names) == len(set(names))  # distinct labels
    assert "xla" in names
    # every {impl} x {rem} x {group} combination is present
    assert {"bucket", "bucket-bf16", "bucket-f8",
            "bucket-f8amax"} <= set(names)
    assert {"block", "block-u4", "block-u4-f8amax"} <= set(names)
    # pinning the transport dtype or group RESTRICTS the grid — the
    # tuner never overrides an explicit user choice
    pinned = tuner.candidate_grid(rem_dtype="float8", rem_amax=False)
    assert all(c["rem_dtype"] == "float8" for c in pinned
               if c["impl"] != "xla")
    grouped = tuner.candidate_grid(block_group=8)
    assert all(c["block_group"] == 8 for c in grouped
               if c["impl"] == "block")


def test_sample_slice_preserves_degree_distribution():
    sg = _sharded(num_nodes=2000, avg_degree=10, seed=7)
    sample, info = tuner.sample_slice(sg, edge_budget=3000)
    assert sample.num_parts == 1 and sample.halo_size == 0
    assert info["sample_edges"] == int(sample.edge_count[0])
    assert info["full_edges"] >= info["sample_edges"]
    assert info["scale"] >= 1.0
    # each sampled destination keeps its FULL in-edge list, so every
    # sampled in-degree exists in the source shard's distribution
    ec = int(sg.edge_count[0])
    full_deg = np.bincount(np.asarray(sg.edge_dst[0][:ec]),
                           minlength=sg.n_max)
    full_counts = set(full_deg[full_deg > 0].tolist())
    samp_dst = np.asarray(sample.edge_dst[0])
    samp_deg = np.bincount(samp_dst)
    assert set(samp_deg[samp_deg > 0].tolist()) <= full_counts


# ---------------- round-trip through the artifact ---------------------


@pytest.mark.parametrize("mmap", [False, True])
def test_cost_table_roundtrip_artifact(tmp_path, mmap):
    """Live tune -> tuning.json sidecar -> a fresh trainer over the
    reloaded artifact dispatches from the persisted table (source
    'artifact', identical winner) for BOTH artifact formats."""
    sg = _sharded(seed=11)
    path = str(tmp_path / ("art_v3" if mmap else "art_v2"))
    sg.save(path, mmap=mmap)

    sg1 = ShardedGraph.load(path)
    t1 = Trainer(sg1, _cfg(sg1), TrainConfig(seed=0))
    assert t1.tuning["source"] == "live"
    win = dict(t1.tuning["winner"])
    # the full measured table rode along: every candidate either timed
    # or recorded its failure — a crash is a result, not a gap
    costs = t1.tuning["costs"]
    assert costs and all(
        (c["spmm_fwdbwd_s"] is None) == (c["error"] is not None)
        for c in costs)
    ok = [c for c in costs if c["error"] is None]
    assert win["name"] == min(
        ok, key=lambda c: c["spmm_fwdbwd_s"])["name"]  # measured argmin
    assert os.path.exists(tuner.tuning_path(path))
    assert np.isfinite(t1.train_epoch(0))

    tuner.clear_memo()  # force the second trainer onto the DISK table
    sg2 = ShardedGraph.load(path)
    t2 = Trainer(sg2, _cfg(sg2), TrainConfig(seed=0))
    assert t2.tuning["source"] == "artifact"
    assert t2.tuning["stale_reason"] is None
    assert t2.tuning["winner"] == win
    assert t2._current_impl() == win["impl"]


# ---------------- table-driven dispatch (two shapes) ------------------


def _plant_table(path, sg, cfg, winner):
    """Persist a crafted tuning.json whose signature/checksum match
    what Trainer._resolve_auto computes for (sg, cfg)."""
    sig = tuner.signature_for(
        width=_trainer_width(cfg), block_tile=cfg.block_tile,
        bucket_merge=0, chunk_edges=cfg.spmm_chunk)
    rec = {
        "tuner_format": tuner.TUNER_FORMAT,
        "source_edge_checksum":
            int(sg.source_edge_checksum) & ((1 << 64) - 1),
        "signature": sig,
        "winner": winner,
        "costs": [dict(winner, spmm_fwdbwd_s=1e-4,
                       est_epoch_spmm_s=1e-3, error=None)],
    }
    tuner.save_tuning(path, rec)
    return rec


def test_table_driven_dispatch_two_shapes(tmp_path):
    """Two distinct shapes (reddit-ish dense-degree vs products-ish
    sparse-degree), each with a DIFFERENT planted measured winner: the
    dispatch must follow each table — proof there is no shape
    heuristic left to override the measurement."""
    shapes = {
        "reddit": (dict(num_nodes=500, avg_degree=20, seed=3),
                   {"name": "bucket-bf16", "impl": "bucket",
                    "rem_dtype": "bfloat16", "rem_amax": False,
                    "block_group": 1}),
        "products": (dict(num_nodes=600, avg_degree=5, seed=4),
                     {"name": "xla", "impl": "xla", "rem_dtype": None,
                      "rem_amax": False, "block_group": 1}),
    }
    for label, (shape, winner) in shapes.items():
        sg = _sharded(**shape)
        path = str(tmp_path / label)
        sg.save(path)
        sgl = ShardedGraph.load(path)
        cfg = _cfg(sgl)
        _plant_table(path, sgl, cfg, winner)
        t = Trainer(sgl, cfg, TrainConfig(seed=0))
        assert t.tuning["source"] == "artifact", label
        assert t._current_impl() == winner["impl"], label
        if winner["rem_dtype"]:
            # the tuner-chosen transport filled the unpinned default
            assert t.cfg.rem_dtype == winner["rem_dtype"], label
        assert np.isfinite(t.train_epoch(0)), label


# ---------------- stale / corrupt -> loud live fallback ---------------


def test_stale_and_corrupt_tables_fall_back_to_live(tmp_path):
    sg = _sharded(seed=21)
    path = str(tmp_path / "art")
    sg.save(path)

    # corrupt sidecar: live re-tune with the reason recorded
    with open(tuner.tuning_path(path), "w") as f:
        f.write("{not json")
    sg1 = ShardedGraph.load(path)
    t1 = Trainer(sg1, _cfg(sg1), TrainConfig(seed=0))
    assert t1.tuning["source"] == "live"
    assert "corrupt" in t1.tuning["stale_reason"]
    # the live result REPLACED the rot on disk
    rec, why = tuner.load_tuning(path)
    assert why is None and rec["winner"] == t1.tuning["winner"]

    # stale checksum (artifact rebuilt from a different graph): the
    # table is rejected with a loud reason and live tuning runs again
    rec["source_edge_checksum"] = (rec["source_edge_checksum"] + 1) \
        & ((1 << 64) - 1)
    tuner.save_tuning(path, rec)
    sg2 = ShardedGraph.load(path)
    t2 = Trainer(sg2, _cfg(sg2), TrainConfig(seed=0))
    assert t2.tuning["source"] == "live"
    assert "checksum" in t2.tuning["stale_reason"]

    # format drift is rejected the same way
    rec2, _ = tuner.load_tuning(path)
    rec2["tuner_format"] = tuner.TUNER_FORMAT + 1
    tuner.save_tuning(path, rec2)
    got, reason = tuner.load_tuning(path)
    assert got is None and "format" in reason


def test_multiprocess_never_live_tunes(tmp_path, monkeypatch):
    """Without a trusted table, a multi-process run must take the
    deterministic default (live timing noise would argmin different
    kernels per rank and desync the SPMD program)."""
    import jax

    sg = _sharded(seed=31)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.warns(UserWarning, match="deterministic default"):
        t = Trainer(sg, _cfg(sg), TrainConfig(seed=0))
    assert t.tuning["source"] == "default"
    assert t.tuning["winner"]["impl"] == tuner.DEFAULT_IMPL
    assert t.tuning["costs"] == []


def test_truncated_sidecar_degrades_to_live_retune(tmp_path):
    """Satellite torn-artifact check: a tuning.json cut off mid-record
    (torn write that landed, disk rot) must come back as
    (None, reason) from load_tuning — never an exception — and the
    trainer re-tunes live exactly as for the unparseable case."""
    sg = _sharded(seed=23)
    path = str(tmp_path / "art")
    sg.save(path)
    sg0 = ShardedGraph.load(path)
    Trainer(sg0, _cfg(sg0), TrainConfig(seed=0))  # live tune persists
    rec, why = tuner.load_tuning(path)
    assert why is None
    full = open(tuner.tuning_path(path)).read()
    with open(tuner.tuning_path(path), "w") as f:
        f.write(full[:len(full) // 2])
    got, reason = tuner.load_tuning(path)
    assert got is None and "corrupt" in reason
    sg1 = ShardedGraph.load(path)
    t1 = Trainer(sg1, _cfg(sg1), TrainConfig(seed=0))
    assert t1.tuning["source"] == "live"
    # and the live result heals the sidecar on disk
    rec2, why2 = tuner.load_tuning(path)
    assert why2 is None and rec2["winner"] == t1.tuning["winner"]


def test_tuning_record_schema_contract():
    """The trainer-emitted tuning dict must satisfy the contracted
    obs record kind (tests/test_obs.py pins the v4 field list)."""
    from pipegcn_tpu.obs.schema import validate_record

    sg = _sharded(seed=41)
    t = Trainer(sg, _cfg(sg, tune=False), TrainConfig(seed=0))
    tu = t.tuning
    validate_record({"event": "tuning", "winner": tu["winner"],
                     "source": tu["source"], "costs": tu["costs"],
                     "stale_reason": tu["stale_reason"]})
    # and it is JSON-serializable end to end (lands in metrics JSONL)
    json.dumps(tu["winner"]), json.dumps(tu["costs"])
