"""Sharded (mesh-parallel) and asynchronous evaluation.

The reference evaluates the full graph in a rank-0 background thread
(train.py:327-328, 377-389); here eval can run through the training
shard_map (no device holds the full graph) and is dispatched
asynchronously by fit(). These tests pin: sharded == single-device eval
on the same params (transductive reuse AND a freshly-partitioned eval
graph, incl. use_pp and multilabel), and async fit == sync fit.
"""

import numpy as np
import pytest

from pipegcn_tpu.graph import synthetic_graph
from pipegcn_tpu.graph.datasets import inductive_split
from pipegcn_tpu.models import ModelConfig
from pipegcn_tpu.parallel import Trainer, TrainConfig
from pipegcn_tpu.partition import ShardedGraph, partition_graph


def _trainer(g, n_parts=4, use_pp=False, norm="layer", dtype="float32",
             multilabel=False, pipeline=True, seed=3, spmm_impl="xla"):
    parts = partition_graph(g, n_parts, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=n_parts)
    n_out = sg.n_class
    cfg = ModelConfig(
        layer_sizes=(sg.n_feat, 16, 16, n_out), norm=norm, dropout=0.0,
        train_size=sg.n_train_global, use_pp=use_pp, dtype=dtype,
        spmm_impl=spmm_impl,
    )
    return Trainer(sg, cfg, TrainConfig(seed=seed,
                                        enable_pipeline=pipeline))


def test_sharded_eval_matches_full_transductive():
    g = synthetic_graph(num_nodes=400, avg_degree=8, n_feat=12, n_class=5,
                        seed=31)
    t = _trainer(g)
    for e in range(4):
        t.train_epoch(e)
    for mask in ("val_mask", "test_mask"):
        full = t.evaluate(g, mask)
        sharded = t.evaluate(g, mask, sharded=True)
        assert full == pytest.approx(sharded, abs=1e-9), mask
    # transductive: the evaluator must have reused the trainer's arrays
    ev = t._get_sharded_evaluator(g)
    assert ev.sg is t.sg and ev.data["feat"] is t.data["feat"]


def test_sharded_eval_through_kernel_tables_matches():
    """A trainer on the bucket kernel trims its device edge list; the
    transductive sharded evaluator must aggregate through the kernel
    tables (no edge re-upload) and still match single-device eval."""
    g = synthetic_graph(num_nodes=400, avg_degree=8, n_feat=12, n_class=5,
                        seed=33)
    t = _trainer(g, spmm_impl="bucket")
    assert t._edges_trimmed
    assert t.data["edge_src"].shape[-1] != t.sg.e_max  # dummies in place
    for e in range(3):
        t.train_epoch(e)
    full = t.evaluate(g, "val_mask")
    sharded = t.evaluate(g, "val_mask", sharded=True)
    assert full == pytest.approx(sharded, abs=1e-9)
    # no fresh edge upload happened: the evaluator holds the dummies
    ev = t._get_sharded_evaluator(g)
    assert ev._dev_data["edge_src"] is t.data["edge_src"]


def test_sharded_eval_through_block_tables_matches():
    """Block trainer with use_pp: the layer-0 precompute AND the
    per-layer aggregation run through the block tables, the raw edge
    arrays never reach the device, and sharded eval matches the
    single-device eval (whose pp aggregation uses the raw-edge path) to
    1e-9 — pinning the pp-through-block-tables numerics."""
    g = synthetic_graph(num_nodes=400, avg_degree=8, n_feat=12, n_class=5,
                        seed=36)
    t = _trainer(g, spmm_impl="block", use_pp=True)
    assert t._edges_trimmed
    assert t.data["edge_src"].shape[-1] != t.sg.e_max  # never uploaded
    for e in range(3):
        t.train_epoch(e)
    full = t.evaluate(g, "val_mask")
    sharded = t.evaluate(g, "val_mask", sharded=True)
    assert full == pytest.approx(sharded, abs=1e-9)
    ev = t._get_sharded_evaluator(g)
    assert ev._dev_data["edge_src"] is t.data["edge_src"]  # dummies reused


def test_sharded_eval_matches_full_use_pp_and_batchnorm():
    g = synthetic_graph(num_nodes=400, avg_degree=8, n_feat=12, n_class=5,
                        seed=32)
    t = _trainer(g, use_pp=True, norm="batch")
    for e in range(4):
        t.train_epoch(e)
    full = t.evaluate(g, "val_mask")
    sharded = t.evaluate(g, "val_mask", sharded=True)
    assert full == pytest.approx(sharded, abs=1e-9)


def test_sharded_eval_fresh_graph_inductive():
    """Eval graphs that differ from the training partitions (inductive
    val/test) must be partitioned + built on the mesh; results match
    single-device eval."""
    g = synthetic_graph(num_nodes=500, avg_degree=8, n_feat=12, n_class=5,
                        seed=33)
    train_g, val_g, test_g = inductive_split(g)
    t = _trainer(train_g, use_pp=True)
    for e in range(4):
        t.train_epoch(e)
    for eg, mask in ((val_g, "val_mask"), (test_g, "test_mask")):
        full = t.evaluate(eg, mask)
        sharded = t.evaluate(eg, mask, sharded=True)
        assert full == pytest.approx(sharded, abs=1e-9)
        ev = t._get_sharded_evaluator(eg)
        assert ev.sg is not t.sg  # really rebuilt


def test_sharded_eval_foreign_graph_through_bucket_tables():
    """A kernel-table trainer evaluating FOREIGN (inductive) graphs
    builds bucket tables for their shards and drops their raw edges —
    results must still match single-device eval."""
    g = synthetic_graph(num_nodes=500, avg_degree=8, n_feat=12, n_class=5,
                        seed=35)
    train_g, val_g, test_g = inductive_split(g)
    t = _trainer(train_g, use_pp=True, spmm_impl="bucket")
    for e in range(3):
        t.train_epoch(e)
    for eg, mask in ((val_g, "val_mask"), (test_g, "test_mask")):
        full = t.evaluate(eg, mask)
        sharded = t.evaluate(eg, mask, sharded=True)
        assert full == pytest.approx(sharded, abs=1e-9)
        ev = t._get_sharded_evaluator(eg)
        assert "bkt_fwd_inv" in ev._dev_data       # tables built
        assert ev._dev_data["edge_src"].shape[-1] == 8  # edges dropped


def test_sharded_eval_same_nodes_different_edges_rebuilds():
    """A graph sharing the training graph's node set but with different
    edges must NOT silently reuse the trainer's arrays (the edge
    checksum, not just the node cover, gates the fast path)."""
    from pipegcn_tpu.graph.csr import Graph, finalize

    g = synthetic_graph(num_nodes=300, avg_degree=8, n_feat=12, n_class=5,
                        seed=37)
    t = _trainer(g)
    t.train_epoch(0)
    # same nodes/features/labels, edges rewired
    rng = np.random.default_rng(1)
    g2 = Graph(src=rng.integers(0, 300, 1200),
               dst=rng.integers(0, 300, 1200),
               num_nodes=300, ndata={k: v for k, v in g.ndata.items()})
    g2 = finalize(g2)
    sharded = t.evaluate(g2, "val_mask", sharded=True)
    full = t.evaluate(g2, "val_mask")
    assert full == pytest.approx(sharded, abs=1e-9)
    assert t._get_sharded_evaluator(g2).sg is not t.sg


def test_sharded_eval_rewired_same_sums_rebuilds():
    """Adversarial checksum case: swap the destinations of two edges —
    node set, edge COUNT and endpoint SUMS all unchanged, so a linear
    checksum would collide; the mixed checksum must still force a
    rebuild."""
    from pipegcn_tpu.graph.csr import Graph, finalize
    from pipegcn_tpu.parallel.evaluator import _covers_exactly

    g = synthetic_graph(num_nodes=300, avg_degree=8, n_feat=12, n_class=5,
                        seed=38)
    t = _trainer(g)
    src, dst = g.src.copy(), g.dst.copy()
    non_loop = np.flatnonzero((src != dst))
    # pick a pair whose swap neither no-ops nor creates self-loops
    i = non_loop[0]
    j = next(j for j in non_loop[::-1]
             if dst[j] != dst[i] and src[i] != dst[j] and src[j] != dst[i])
    dst[i], dst[j] = dst[j], dst[i]  # re-pair endpoints
    g2 = Graph(src=src, dst=dst, num_nodes=g.num_nodes,
               ndata={k: v for k, v in g.ndata.items()})
    g2 = finalize(g2)
    assert g2.num_edges == g.num_edges
    assert not _covers_exactly(t.sg, g2)
    assert _covers_exactly(t.sg, g)


def test_sharded_eval_multilabel_micro_f1():
    g = synthetic_graph(num_nodes=400, avg_degree=8, n_feat=12, n_class=6,
                        multilabel=True, seed=34)
    t = _trainer(g, multilabel=True)
    for e in range(3):
        t.train_epoch(e)
    full = t.evaluate(g, "val_mask")
    sharded = t.evaluate(g, "val_mask", sharded=True)
    assert full == pytest.approx(sharded, abs=1e-9)


@pytest.mark.parametrize("sharded", [False, True])
def test_async_fit_matches_sync(sharded):
    """fit() with async eval must produce the same history accuracies,
    best val and test acc as blocking eval (same seeds -> same params at
    every dispatch point); only log timing differs."""
    g = synthetic_graph(num_nodes=400, avg_degree=8, n_feat=12, n_class=5,
                        seed=35, train_frac=0.3)
    eval_graphs = {"val": (g, "val_mask"), "test": (g, "test_mask")}
    results = {}
    for async_eval in (False, True):
        t = _trainer(g)
        t.tcfg = TrainConfig(seed=3, enable_pipeline=True, n_epochs=12,
                             log_every=4)
        results[async_eval] = t.fit(
            eval_graphs, log_fn=lambda *_: None,
            sharded_eval=sharded, async_eval=async_eval,
        )
    a, b = results[False], results[True]
    assert [h[2] for h in a["history"]] == [h[2] for h in b["history"]]
    assert a["best_val"] == b["best_val"]
    assert a["best_epoch"] == b["best_epoch"]
    assert a.get("test_acc") == b.get("test_acc")


def test_async_eval_does_not_block_loop():
    """The dispatch at a log boundary must return without waiting for
    the eval computation (jax async dispatch): the step timer never
    includes eval work. Structural check: pending harvests lag by one
    boundary and the final pending is flushed."""
    g = synthetic_graph(num_nodes=300, avg_degree=6, n_feat=10, n_class=4,
                        seed=36)
    t = _trainer(g)
    t.tcfg = TrainConfig(seed=3, enable_pipeline=True, n_epochs=9,
                         log_every=3)
    seen = []
    res = t.fit({"val": (g, "val_mask"), "test": (g, "test_mask")},
                log_fn=lambda m: seen.append(str(m)), async_eval=True)
    # three boundaries -> three history entries, all with accuracies
    accs = [h for h in res["history"] if h[2] is not None]
    assert len(accs) == 3


def test_sharded_eval_gcn_and_gat_match_full():
    """The sharded evaluator must agree with single-device full-graph
    eval for the extension model families too (gcn rides the kernel
    tables; gat rides the raw-edge path)."""
    g = synthetic_graph(num_nodes=400, avg_degree=8, n_feat=12, n_class=5,
                        seed=33)
    parts = partition_graph(g, 4, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=4)
    for model, extra in (("gcn", {"spmm_impl": "bucket"}),
                         ("gat", {"n_heads": 4})):
        cfg = ModelConfig(
            layer_sizes=(sg.n_feat, 16, 16, sg.n_class), model=model,
            norm="layer", dropout=0.0, train_size=sg.n_train_global,
            **extra,
        )
        t = Trainer(sg, cfg, TrainConfig(seed=5, enable_pipeline=True))
        for e in range(3):
            t.train_epoch(e)
        for mask in ("val_mask", "test_mask"):
            full = t.evaluate(g, mask)
            sharded = t.evaluate(g, mask, sharded=True)
            assert full == pytest.approx(sharded, abs=1e-6), (model, mask)


def test_sharded_eval_program_reused_no_retrace():
    """Round-10 serving satellite: the jitted sharded-eval forward is a
    cached program keyed on (shape, dtype, impl) in the trainer — a
    second evaluator over an identical-shape graph (e.g. the periodic
    eval cadence rebuilding its graph object) must NOT retrace.
    Pinned via the trace-time compile counter."""
    from pipegcn_tpu.parallel import evaluator as ev_mod

    g = synthetic_graph(num_nodes=400, avg_degree=8, n_feat=12, n_class=5,
                        seed=31)
    t = _trainer(g)
    t.train_epoch(0)
    a1 = t.evaluate(g, "val_mask", sharded=True)
    count_after_first = ev_mod.EVAL_TRACE_COUNT
    assert count_after_first >= 1
    # a NEW graph object with identical content (same seed/sizes) makes
    # a new ShardedEvaluator; it must reuse the compiled program
    g2 = synthetic_graph(num_nodes=400, avg_degree=8, n_feat=12,
                        n_class=5, seed=31)
    a2 = t.evaluate(g2, "val_mask", sharded=True)
    assert ev_mod.EVAL_TRACE_COUNT == count_after_first, \
        "identical-shape eval graph retraced the sharded eval program"
    assert a1 == pytest.approx(a2, abs=1e-9)
    # the two evaluators are distinct objects sharing one program
    ev_a = t._get_sharded_evaluator(g)
    ev_b = t._get_sharded_evaluator(g2)
    assert ev_a is not ev_b
    assert ev_a._run is ev_b._run
