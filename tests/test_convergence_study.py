"""Convergence-study driver script: resume semantics.

The study (scripts/convergence_study.py) strings scarce TPU windows
together via three nested persistence layers — full local checkpoints,
git-tracked light checkpoints (params+opt+norm replica 0), and
task-identity stamps. These tests pin the flows the round-5 handoff
depends on (the reference has no resume at all — train.py:242-400
restarts from scratch)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "convergence_study.py")


def run_study(tmp_path, epochs, extra=()):
    argv = [
        sys.executable, SCRIPT, "--cpu",
        "--nodes", "300", "--degree", "12", "--feat", "12",
        "--classes", "4", "--parts", "2", "--label-noise", "0.05",
        "--cache-artifacts", "--epochs", str(epochs),
        "--eval-every", "2", "--fused", "2",
        "--state-dir", str(tmp_path / "state"),
        "--light-dir", str(tmp_path / "light"),
        "--out", str(tmp_path / "report.md"), *extra,
    ]
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    return subprocess.run(argv, capture_output=True, text=True,
                          env=env, cwd=REPO, timeout=600)


@pytest.mark.slow
def test_light_checkpoint_wipe_resume(tmp_path):
    r1 = run_study(tmp_path, 4)
    assert r1.returncode == 0, r1.stdout[-2000:] + r1.stderr[-2000:]
    assert (tmp_path / "report.md").exists()
    assert (tmp_path / "light" / "vanilla.npz").exists()
    assert (tmp_path / "light" / "task.json").exists()

    # simulate the inter-round workspace wipe: gitignored state gone,
    # tracked light dir survives
    import shutil

    shutil.rmtree(tmp_path / "state")
    r2 = run_study(tmp_path, 6)
    assert r2.returncode == 0, r2.stdout[-2000:] + r2.stderr[-2000:]
    assert "light-resume at epoch 4" in r2.stdout
    # the report's history spans BOTH runs (mirror seeded the wiped
    # authoritative copy — no epochs lost)
    hist = [json.loads(l) for l in
            open(tmp_path / "state" / "vanilla" / "history.jsonl")]
    assert hist[0]["epoch"] < 4 <= hist[-1]["epoch"]


@pytest.mark.slow
def test_task_identity_guard(tmp_path):
    r1 = run_study(tmp_path, 2)
    assert r1.returncode == 0, r1.stdout[-2000:] + r1.stderr[-2000:]
    r2 = run_study(tmp_path, 2, extra=("--lr", "0.02"))
    assert r2.returncode != 0
    assert "holds legs trained on" in r2.stdout + r2.stderr
