"""Multi-host launch path (cli/main.py:60-144).

The real multi-host flow — jax.distributed.initialize + process-0-only
partitioning with peers polling the shared filesystem (the analogue of
reference main.py:32-59's node_rank-0 partition + spawn) — runs for
real in test_two_process_end_to_end (two coordinated CPU processes over
localhost, the TPU analogue of the reference's localhost-gloo trick);
the remaining tests pin its pieces cheaply: the node-count math driving
initialize(), _await_partition_artifact's success/timeout/mismatch
behavior, and prepare()'s process-role branches under mocked
process_count/process_index.
"""

import os
import threading
import time

import numpy as np
import pytest

import jax

from pipegcn_tpu.cli.main import (
    _await_partition_artifact,
    _maybe_init_distributed,
    prepare,
)
from pipegcn_tpu.cli.parser import create_parser
from pipegcn_tpu.graph import synthetic_graph
from pipegcn_tpu.partition import ShardedGraph, partition_graph


def _args(tmp_path, extra=()):
    return create_parser().parse_args([
        "--dataset", "synthetic:200:6:8:4",
        "--n-partitions", "2",
        "--partition-dir", str(tmp_path / "parts"),
        "--no-eval",
        *extra,
    ])


def _make_artifact(path, n_parts=2):
    g = synthetic_graph(num_nodes=200, avg_degree=6, n_feat=8, n_class=4,
                        seed=0)
    sg = ShardedGraph.build(g, partition_graph(g, n_parts, seed=0),
                            n_parts=n_parts)
    sg.save(path)
    return sg


# ---------------------------------------------------------------------
# _maybe_init_distributed: n_nodes = ceil(n_partitions / parts_per_node)

def test_distributed_init_called_with_node_math(monkeypatch):
    calls = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    args = create_parser().parse_args([
        "--dataset", "reddit", "--n-partitions", "40",
        "--parts-per-node", "10", "--node-rank", "3",
        "--master-addr", "10.0.0.7", "--port", "18118",
    ])
    _maybe_init_distributed(args)
    assert calls == [{
        "coordinator_address": "10.0.0.7:18118",
        "num_processes": 4,
        "process_id": 3,
        "initialization_timeout": 300,
    }]


def test_distributed_init_timeout_flag_and_clean_error(monkeypatch):
    """Satellite: an unreachable coordinator must fail with an
    actionable error naming the address — not hang forever or die with
    a bare RPC error."""
    calls = []

    def failing_init(**kw):
        calls.append(kw)
        raise RuntimeError("DEADLINE_EXCEEDED: rpc timed out")

    monkeypatch.setattr(jax.distributed, "initialize", failing_init)
    args = create_parser().parse_args([
        "--dataset", "reddit", "--n-partitions", "8",
        "--parts-per-node", "4", "--node-rank", "1",
        "--master-addr", "10.1.2.3", "--port", "9999",
        "--coordinator-timeout", "7",
    ])
    with pytest.raises(RuntimeError) as ei:
        _maybe_init_distributed(args)
    assert calls[0]["initialization_timeout"] == 7
    msg = str(ei.value)
    assert "10.1.2.3:9999" in msg
    assert "process 1/2" in msg
    assert "--coordinator-timeout" in msg


def test_distributed_init_skipped_single_host(monkeypatch):
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: pytest.fail("must not initialize"))
    args = create_parser().parse_args([
        "--dataset", "reddit", "--n-partitions", "8",
        "--parts-per-node", "8",
    ])
    _maybe_init_distributed(args)  # 1 node -> no-op


def test_distributed_init_rounds_up(monkeypatch):
    calls = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    args = create_parser().parse_args([
        "--dataset", "reddit", "--n-partitions", "11",
        "--parts-per-node", "4",
    ])
    _maybe_init_distributed(args)
    assert calls[0]["num_processes"] == 3  # ceil(11/4)


# ---------------------------------------------------------------------
# _await_partition_artifact

def test_await_artifact_already_there(tmp_path):
    p = str(tmp_path / "art")
    _make_artifact(p)
    sg = _await_partition_artifact(p, 2, timeout_s=1.0)
    assert sg.num_parts == 2


def test_await_artifact_appears_late(tmp_path):
    p = str(tmp_path / "art")

    def writer():
        time.sleep(0.5)
        _make_artifact(p)

    th = threading.Thread(target=writer)
    th.start()
    sg = _await_partition_artifact(p, 2, timeout_s=30.0, poll_s=0.05)
    th.join()
    assert sg.num_parts == 2


def test_await_artifact_timeout(tmp_path):
    with pytest.raises(TimeoutError, match="shared filesystem"):
        _await_partition_artifact(str(tmp_path / "never"), 2,
                                  timeout_s=0.2, poll_s=0.05)


def test_await_artifact_wrong_parts(tmp_path):
    p = str(tmp_path / "art")
    _make_artifact(p, n_parts=2)
    with pytest.raises(ValueError, match="2 parts, requested 4"):
        _await_partition_artifact(p, 4, timeout_s=1.0)


# ---------------------------------------------------------------------
# prepare(): process-role branches under mocked process topology

def test_prepare_process0_partitions_and_saves(tmp_path, monkeypatch):
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    args = _args(tmp_path)
    sg, eval_graphs = prepare(args)
    assert sg.num_parts == 2
    assert eval_graphs is None  # --no-eval
    # artifact saved for the peers to pick up ("-cs1024": the default
    # cluster local-reorder AND its granularity are part of the
    # artifact's cache key (cluster_suffix is self-describing)
    assert ShardedGraph.exists(
        os.path.join(args.partition_dir,
                     "synthetic:200:6:8:4-2-metis-vol-trans-cs1024"))


def test_prepare_nonzero_process_loads_artifact(tmp_path, monkeypatch):
    """A non-zero process must NEVER partition — it polls for process
    0's artifact."""
    art = str(tmp_path / "parts"
              / "synthetic:200:6:8:4-2-metis-vol-trans-cs1024")
    _make_artifact(art)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    import pipegcn_tpu.cli.main as cli_main
    monkeypatch.setattr(
        cli_main, "partition_graph",
        lambda *a, **k: pytest.fail("peer process must not partition"))
    sg, _ = prepare(_args(tmp_path))
    assert sg.num_parts == 2


def test_prepare_single_process_partitions(tmp_path, monkeypatch):
    monkeypatch.setattr(jax, "process_count", lambda: 1)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    sg, _ = prepare(_args(tmp_path))
    assert sg.num_parts == 2
    assert int(sg.inner_count.sum()) == 200


def test_two_process_end_to_end(tmp_path):
    """The real thing: two OS processes rendezvous through
    jax.distributed.initialize over localhost, each drives 2 of the 4
    partitions of ONE SPMD training job (process 0 partitions, process
    1 polls the shared artifact), and both finish with identical
    results files."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:  # free localhost port for the rendezvous
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "PYTHONPATH": repo,
    }
    procs = []
    for rank in (0, 1):
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(repo, "main.py"),
             "--dataset", "synthetic:600:8:12:4",
             "--n-partitions", "4", "--parts-per-node", "2",
             "--node-rank", str(rank),
             "--master-addr", "127.0.0.1", "--port", str(port),
             "--n-epochs", "6", "--n-hidden", "16", "--n-layers", "2",
             "--enable-pipeline", "--log-every", "3",
             "--fix-seed", "--seed", "3",
             "--partition-dir", str(tmp_path / "parts"),
             "--model-dir", str(tmp_path / f"model{rank}"),
             "--results-dir", str(tmp_path / f"results{rank}")],
            env=env, cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        ))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-2000:]}"
    # both ranks ran the SAME SPMD program: identical final results
    res = []
    for rank in (0, 1):
        d = tmp_path / f"results{rank}"
        files = list(d.glob("*.txt"))
        assert files, outs[rank][-1000:]
        res.append(files[0].read_text())
    assert res[0] == res[1]
    assert "Accuracy" in res[0]
