"""Tier-1 tests for the round-8 non-SpMM floor levers: cheap dropout
RNG (rng_impl / dropout_bits / dropout_reuse), compressed halo wire
transport (halo_dtype), megastep dispatch (epoch_block), and the
layer-0 comm prefetch — all on the virtual 8-device CPU mesh."""

import dataclasses

import numpy as np
import jax
import pytest

from pipegcn_tpu.graph import synthetic_graph
from pipegcn_tpu.models import ModelConfig
from pipegcn_tpu.parallel import Trainer, TrainConfig
from pipegcn_tpu.partition import ShardedGraph, partition_graph


@pytest.fixture(scope="module")
def sharded():
    g = synthetic_graph(num_nodes=400, avg_degree=8, n_feat=12,
                        n_class=4, seed=11)
    parts = partition_graph(g, 4, seed=0)
    return ShardedGraph.build(g, parts, n_parts=4)


def _mk(sg, *, dropout=0.0, use_pp=False, dropout_bits=32, **tkw):
    cfg = ModelConfig(layer_sizes=(sg.n_feat, 16, sg.n_class),
                      norm="layer", dropout=dropout, use_pp=use_pp,
                      train_size=sg.n_train_global,
                      dropout_bits=dropout_bits)
    return Trainer(sg, cfg, TrainConfig(**tkw))


# ---------------------------------------------------------------- RNG --

def test_rng_impl_deterministic_and_tracks_threefry(sharded):
    """Each PRNG impl is deterministic at a fixed seed (two fresh
    trainers produce identical loss sequences) and a short run stays
    finite with losses tracking the threefry run: the impls draw
    different mask streams, so per-epoch losses differ but must stay
    within dropout-noise tolerance and keep converging."""
    ref = None
    for impl in ("threefry", "rbg", "unsafe_rbg"):
        ta = _mk(sharded, dropout=0.3, seed=9, enable_pipeline=True,
                 rng_impl=impl)
        tb = _mk(sharded, dropout=0.3, seed=9, enable_pipeline=True,
                 rng_impl=impl)
        la = np.asarray([ta.train_epoch(e) for e in range(12)])
        lb = np.asarray([tb.train_epoch(e) for e in range(12)])
        np.testing.assert_allclose(la, lb, rtol=1e-6)  # deterministic
        assert np.isfinite(la).all()
        if ref is None:
            ref = la  # threefry baseline
        else:
            # measured spread on this graph is <= ~0.06 absolute; a
            # different mask stream must not change the trajectory class
            np.testing.assert_allclose(la[:5], ref[:5], rtol=0.1,
                                       atol=0.08)
        assert la[-1] < la[0] * 0.5  # converges


def test_rng_impls_draw_distinct_mask_streams(sharded):
    """threefry and rbg must actually produce different dropout masks:
    identical losses would mean the flag is dead."""
    lt = _mk(sharded, dropout=0.3, seed=9,
             rng_impl="threefry").train_epoch(1)
    lr = _mk(sharded, dropout=0.3, seed=9,
             rng_impl="rbg").train_epoch(1)
    assert abs(float(lt) - float(lr)) > 1e-6


def test_dropout_bits8_trains_and_validates(sharded):
    """8-bit mask draws: config validation rejects widths other than
    8/32, and the quantized keep-probability path converges."""
    with pytest.raises(ValueError, match="dropout_bits"):
        ModelConfig(layer_sizes=(12, 16, 4), dropout_bits=16)
    t = _mk(sharded, dropout=0.3, dropout_bits=8, seed=9,
            enable_pipeline=True)
    losses = [t.train_epoch(e) for e in range(12)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5


def test_dropout_reuse_reuses_masks_across_epochs(sharded):
    """dropout_reuse=N folds epoch//N into the dropout key: with frozen
    params (lr=0) epochs inside one reuse window see the same mask
    (identical loss), across windows a fresh one."""
    t = _mk(sharded, dropout=0.5, seed=9, lr=0.0, dropout_reuse=2)
    l0, l1, l2 = (float(t.train_epoch(e)) for e in range(3))
    assert l0 == pytest.approx(l1, rel=1e-6)  # same window, same mask
    assert abs(l2 - l0) > 1e-6  # next window redraws


# --------------------------------------------------- compressed halo --

@pytest.mark.parametrize("halo_dtype", ["bfloat16", "float8"])
def test_compressed_halo_keeps_staleness_semantics(sharded, halo_dtype):
    """Wire-only halo compression must not disturb the staleness-1
    carry protocol: epoch 0 consumes zero buffers (loss identical to
    the uncompressed pipelined run), and with frozen params the warm
    epochs reproduce the vanilla loss to wire precision — through the
    custom-VJP stale concat AND the unscaled-bgrad return path."""
    tu = _mk(sharded, seed=3, lr=0.0, enable_pipeline=True)
    lu = [tu.train_epoch(e) for e in range(4)]
    tc = _mk(sharded, seed=3, lr=0.0, enable_pipeline=True,
             halo_dtype=halo_dtype)
    lc = [tc.train_epoch(e) for e in range(4)]
    # epoch 0: carry is zeros — compression of the wire cannot change it
    np.testing.assert_allclose(lc[0], lu[0], rtol=1e-6)
    # warm epochs reproduce the vanilla frozen loss to wire precision
    lv = float(_mk(sharded, seed=3, lr=0.0).train_epoch(0))
    np.testing.assert_allclose(lc[2], lv, rtol=1e-3)
    np.testing.assert_allclose(lc[3], lv, rtol=1e-3)


@pytest.mark.parametrize("halo_dtype", ["bfloat16", "float8"])
def test_compressed_halo_training_tracks_f32_wire(sharded, halo_dtype):
    """Live training (bgrads cross the compressed wire every epoch)
    must track the f32-wire run closely; measured drift on this graph
    is <= ~2e-4 per epoch for fp8."""
    t0 = _mk(sharded, seed=3, enable_pipeline=True)
    tc = _mk(sharded, seed=3, enable_pipeline=True,
             halo_dtype=halo_dtype)
    l0 = np.asarray([t0.train_epoch(e) for e in range(10)])
    lc = np.asarray([tc.train_epoch(e) for e in range(10)])
    assert np.isfinite(lc).all()
    np.testing.assert_allclose(lc, l0, rtol=0.02, atol=0.01)
    assert lc[-1] < lc[0] * 0.5


def test_halo_dtype_requires_pipeline(sharded):
    """The vanilla exchange is differentiated and must stay exact:
    compression without enable_pipeline is a config error."""
    with pytest.raises(ValueError, match="enable_pipeline"):
        _mk(sharded, seed=3, halo_dtype="bfloat16").train_epoch(0)


def test_compressed_halo_reports_reduced_wire_bytes(sharded):
    """est_halo_bytes_per_epoch must reflect the wire dtype; the
    uncompressed estimate stays available for the metrics record."""
    t8 = _mk(sharded, seed=3, enable_pipeline=True, halo_dtype="float8")
    comp = t8.est_halo_bytes_per_epoch()
    unc = t8.est_halo_bytes_per_epoch(compressed=False)
    assert comp * 4 == unc  # f32 -> fp8 wire is 4x smaller
    t0 = _mk(sharded, seed=3, enable_pipeline=True)
    assert t0.est_halo_bytes_per_epoch() == unc


# ------------------------------------------- megastep + comm prefetch --

def test_epoch_block_megastep_matches_singles(sharded):
    """fit() under epoch_block=N dispatches N-epoch megasteps with one
    metrics harvest per block — numerically identical to single-epoch
    training (same per-epoch rng folds, pipelined carry included)."""
    ta = _mk(sharded, dropout=0.3, seed=9, enable_pipeline=True)
    la = [ta.train_epoch(e) for e in range(6)]
    tb = _mk(sharded, dropout=0.3, seed=9, enable_pipeline=True,
             n_epochs=6, epoch_block=3, log_every=100)
    tb.fit(log_fn=lambda m: None)
    lb = np.asarray(tb._last_metrics["loss"])
    np.testing.assert_allclose(la[3:], lb, rtol=1e-5)
    pa = jax.tree_util.tree_leaves(jax.device_get(ta.state["params"]))
    pb = jax.tree_util.tree_leaves(jax.device_get(tb.state["params"]))
    for a, b in zip(pa, pb):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


def test_comm_prefetch_bit_parity(sharded):
    """Hoisting the layer-0 exchange to step top must be a pure
    reordering: the exchanged tensor is parameter-independent, so
    losses and params match the non-prefetch run exactly."""
    t0 = _mk(sharded, seed=3, dropout=0.2, enable_pipeline=True)
    t1 = _mk(sharded, seed=3, dropout=0.2, enable_pipeline=True,
             comm_prefetch=True)
    l0 = [t0.train_epoch(e) for e in range(5)]
    l1 = [t1.train_epoch(e) for e in range(5)]
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    p0 = jax.tree_util.tree_leaves(jax.device_get(t0.state["params"]))
    p1 = jax.tree_util.tree_leaves(jax.device_get(t1.state["params"]))
    for a, b in zip(p0, p1):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-8)


def test_comm_prefetch_noop_under_use_pp(sharded):
    """use_pp precomputes the layer-0 aggregate, so there is no layer-0
    exchange to hoist: the flag must be inert, not crash."""
    t = _mk(sharded, seed=3, use_pp=True, enable_pipeline=True,
            comm_prefetch=True)
    losses = [t.train_epoch(e) for e in range(5)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


# ------------------------------------------------------------- tuner --

def test_tuner_signature_includes_floor_lever_knobs():
    """The persisted tuning-table signature must key on the new knobs
    so a table measured under one RNG/halo/dispatch regime is not
    trusted under another; defaults must keep old call sites stable."""
    from pipegcn_tpu.ops import tuner

    base = tuner.signature_for(width=16, block_tile=128, bucket_merge=0,
                               chunk_edges=0)
    assert base["rng_impl"] == "threefry"
    assert base["halo_dtype"] == "none"
    assert base["epoch_block"] == 0
    alt = tuner.signature_for(width=16, block_tile=128, bucket_merge=0,
                              chunk_edges=0, rng_impl="rbg",
                              halo_dtype="float8", epoch_block=8)
    assert alt != base
    assert (alt["rng_impl"], alt["halo_dtype"], alt["epoch_block"]) == \
        ("rbg", "float8", 8)
