"""Hybrid block-dense SpMM: unit parity vs dense reference (dense tiles
AND sparse remainder exercised), gradient parity vs the XLA path, and
trainer-level parity vs gather+segment-sum."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pipegcn_tpu.graph import synthetic_graph
from pipegcn_tpu.models import ModelConfig
from pipegcn_tpu.ops.block_spmm import (
    BlockPlan,
    make_block_spmm_fn,
    plan_to_arrays,
)
from pipegcn_tpu.ops.spmm import spmm_mean
from pipegcn_tpu.parallel import Trainer, TrainConfig
from pipegcn_tpu.partition import ShardedGraph, partition_graph


@pytest.fixture(scope="module")
def edges():
    rng = np.random.default_rng(9)
    n_out, n_src = 96, 130
    e = 1200
    src = rng.integers(0, n_src, e).astype(np.int64)
    dst = rng.integers(0, n_out, e).astype(np.int64)
    # concentrate edges into one (dst-tile, src-tile) block so the dense
    # path has real work at tile=16
    dst[:300] = rng.integers(0, 16, 300)
    src[:300] = rng.integers(16, 32, 300)
    mask = dst != 5  # row 5 has no edges
    return src[mask], dst[mask], n_out, n_src


def _ref_mean(src, dst, n_out, fbuf, deg):
    out = np.zeros((n_out, fbuf.shape[1]), np.float32)
    for s, d in zip(src, dst):
        out[d] += np.asarray(fbuf, np.float32)[s]
    return out / np.asarray(deg)[:, None]


def _make_fn(src, dst, n_out, n_src, deg, tile, nnz_threshold):
    plan = BlockPlan(src, dst, n_out, n_src, n_feat=8, tile=tile,
                     nnz_threshold=nnz_threshold)
    arrs = {k: jnp.asarray(v) for k, v in plan_to_arrays(plan).items()}
    return plan, make_block_spmm_fn(arrs, deg, n_out, n_src, tile)


@pytest.mark.parametrize("nnz_threshold", [4, 10**9])
def test_block_mean_matches_dense(edges, nnz_threshold):
    """Low threshold → dense tiles carry most edges; huge threshold →
    everything goes through the remainder (bucket) path. Both must agree
    with the dense reference."""
    src, dst, n_out, n_src = edges
    rng = np.random.default_rng(0)
    fbuf = rng.standard_normal((n_src, 8)).astype(np.float32)
    deg = jnp.asarray(
        np.maximum(np.bincount(dst, minlength=n_out), 1).astype(np.float32)
    )
    plan, fn = _make_fn(src, dst, n_out, n_src, deg, 16, nnz_threshold)
    if nnz_threshold == 4:
        assert plan.a_blocks.shape[0] > 0  # dense path actually exercised
        assert plan.rem_count < src.shape[0]
    else:
        assert plan.a_blocks.shape[0] == 0
    out = fn(jnp.asarray(fbuf))
    np.testing.assert_allclose(
        np.asarray(out), _ref_mean(src, dst, n_out, fbuf, deg),
        rtol=1e-5, atol=1e-5)
    assert np.abs(np.asarray(out)[5]).max() == 0.0  # zero-degree row


def test_block_fn_grad_matches_reference(edges):
    src, dst, n_out, n_src = edges
    rng = np.random.default_rng(2)
    fbuf = jnp.asarray(rng.standard_normal((n_src, 8)).astype(np.float32))
    deg = jnp.asarray(
        np.maximum(np.bincount(dst, minlength=n_out), 1).astype(np.float32)
    )
    _, fn = _make_fn(src, dst, n_out, n_src, deg, 16, 4)
    order = np.argsort(dst, kind="stable")
    es = jnp.asarray(src[order].astype(np.int32))
    ed = jnp.asarray(dst[order].astype(np.int32))

    v_a, g_a = jax.value_and_grad(lambda f: (fn(f) ** 2).sum())(fbuf)
    v_b, g_b = jax.value_and_grad(
        lambda f: (spmm_mean(f, es, ed, deg, n_out, None, True) ** 2).sum()
    )(fbuf)
    np.testing.assert_allclose(float(v_a), float(v_b), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g_a), np.asarray(g_b),
                               rtol=1e-4, atol=1e-5)


def test_trainer_block_matches_xla():
    g = synthetic_graph(num_nodes=300, avg_degree=7, n_feat=10, n_class=4,
                        seed=21)
    parts = partition_graph(g, 4, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=4)
    losses = {}
    for impl in ("xla", "block"):
        cfg = ModelConfig(layer_sizes=(10, 16, 4), norm="layer",
                          dropout=0.0, train_size=sg.n_train_global,
                          spmm_impl=impl)
        t = Trainer(sg, cfg, TrainConfig(seed=4, enable_pipeline=True))
        losses[impl] = [t.train_epoch(e) for e in range(6)]
    np.testing.assert_allclose(losses["xla"], losses["block"], rtol=2e-4)


def test_block_budget_spill_and_wide_counts_stay_exact():
    """A tight byte budget forces dense-block spills, and >127-fold
    duplicate edges force the wider A dtype's smaller cap (the rebuild
    path): every edge must still be aggregated exactly once — spilled
    blocks' high-degree rows must not overflow a stale remainder
    ladder."""
    from pipegcn_tpu.graph import synthetic_graph
    from pipegcn_tpu.graph.csr import Graph
    from pipegcn_tpu.ops.block_spmm import (
        build_sharded_block_tables,
        make_device_block_spmm_fn,
    )

    base = synthetic_graph(num_nodes=256, avg_degree=12, n_feat=6,
                           n_class=3, homophily=0.9, seed=11)
    # multigraph: repeat one hub edge 200x (forces bf16 A, isz=2)
    rng = np.random.default_rng(0)
    rep_src = np.full(200, int(base.src[0]), np.int64)
    rep_dst = np.full(200, int(base.dst[0]), np.int64)
    g = Graph(base.num_nodes,
              np.concatenate([base.src, rep_src]),
              np.concatenate([base.dst, rep_dst]),
              ndata={k: v for k, v in base.ndata.items()
                     if k != "in_deg"})
    parts = partition_graph(g, 1, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=1)

    # budget of ONE int8 tile at tile=16 -> heavy spills; wide counts
    # then halve the cap during the dtype rebuild
    tables, tile = build_sharded_block_tables(
        sg, tile=16, n_feat_hint=6, byte_budget=16 * 16 * 2)
    assert tables["blk_a"].dtype != np.int8  # the wide-dtype path ran

    fbuf_rows = sg.n_max + sg.halo_size
    fbuf = rng.standard_normal((fbuf_rows, 6)).astype(np.float32)
    d = {k: jnp.asarray(v[0]) for k, v in tables.items()}
    f = make_device_block_spmm_fn(
        d, jnp.asarray(sg.in_deg[0]), sg.n_max, fbuf_rows, tile)
    out = np.asarray(f(jnp.asarray(fbuf)))

    # dense reference over the padded edge list
    e = sg.edge_count[0]
    src, dst = sg.edge_src[0][:e], sg.edge_dst[0][:e]
    ref = np.zeros((sg.n_max, 6), np.float32)
    np.add.at(ref, dst, fbuf[src])
    ref /= sg.in_deg[0][:, None]
    np.testing.assert_allclose(out[:sg.n_max], ref, rtol=2e-2, atol=2e-2)


def test_trainer_block_clustered_matches_xla():
    """The intended production path: cluster-renumbered local ids feed
    the block-dense plan real dense tiles; training must still match the
    raw-edge XLA trainer loss-for-loss on the same layout."""
    from pipegcn_tpu.partition import locality_clusters

    g = synthetic_graph(num_nodes=600, avg_degree=10, n_feat=12,
                        n_class=4, homophily=0.9, seed=25)
    parts = partition_graph(g, 4, seed=0)
    cluster = locality_clusters(g, target_size=64, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=4, cluster=cluster)
    losses = {}
    for impl in ("xla", "block"):
        cfg = ModelConfig(layer_sizes=(12, 16, 4), norm="layer",
                          dropout=0.0, train_size=sg.n_train_global,
                          spmm_impl=impl, block_tile=32)
        t = Trainer(sg, cfg, TrainConfig(seed=4, enable_pipeline=True))
        losses[impl] = [t.train_epoch(e) for e in range(6)]
        if impl == "block":
            # the clustered layout must actually produce dense blocks
            tb = t._block_tables
            a_key = "blk_a_bits" if "blk_a_bits" in tb else "blk_a"
            assert tb[a_key].shape[1] > 0
    np.testing.assert_allclose(losses["xla"], losses["block"], rtol=2e-4)


def test_trainer_block_bf16_fused():
    g = synthetic_graph(num_nodes=300, avg_degree=7, n_feat=10, n_class=4,
                        seed=23)
    parts = partition_graph(g, 4, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=4)
    cfg = ModelConfig(layer_sizes=(10, 16, 16, 4), norm="layer",
                      dropout=0.2, train_size=sg.n_train_global,
                      spmm_impl="block", dtype="bfloat16", use_pp=True)
    t = Trainer(sg, cfg, TrainConfig(seed=4, enable_pipeline=True,
                                     feat_corr=True, grad_corr=True))
    losses = list(t.train_epochs(0, 4)) + list(t.train_epochs(4, 16))
    assert np.isfinite(losses).all()
    assert np.mean(losses[-4:]) < np.mean(losses[:4])


def test_bitpacked_a_parity_and_selection():
    """Simple graphs (0/1 edge multiplicity) ship A bit-packed: the
    sharded builder must emit blk_a_bits (uint8, S//8 wide), the cap
    must reflect the 8x cheaper encoding, and the device unpack must be
    numerically identical to the unpacked plan."""
    from pipegcn_tpu.ops.block_spmm import (
        build_sharded_block_tables,
        make_device_block_spmm_fn,
        pack_a_blocks,
    )

    rng = np.random.default_rng(3)
    n = 256
    # simple clustered graph: unique (src, dst) pairs only
    src = rng.integers(0, n, 4000)
    dst = rng.integers(0, n, 4000)
    src[:3000] = rng.integers(0, 64, 3000)
    dst[:3000] = rng.integers(0, 64, 3000)
    pairs = np.unique(np.stack([src, dst], 1), axis=0)
    src, dst = pairs[:, 0], pairs[:, 1]

    from pipegcn_tpu.graph.csr import Graph

    feat = rng.standard_normal((n, 8)).astype(np.float32)
    g = Graph(n, src, dst, ndata={
        "feat": feat,
        "label": np.zeros(n, np.int64),
        "train_mask": np.ones(n, bool),
        "val_mask": np.zeros(n, bool),
        "test_mask": np.zeros(n, bool),
    })
    parts = partition_graph(g, 1, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=1)

    tables, tile = build_sharded_block_tables(
        sg, tile=16, n_feat_hint=8, byte_budget=1 << 16)
    assert "blk_a_bits" in tables and "blk_a" not in tables
    a_bits = tables["blk_a_bits"]
    assert a_bits.dtype == np.uint8 and a_bits.shape[-1] == tile // 8

    fbuf_rows = sg.n_max + sg.halo_size
    fbuf = rng.standard_normal((fbuf_rows, 8)).astype(np.float32)
    d = {k: jnp.asarray(v[0]) for k, v in tables.items()}
    fn = make_device_block_spmm_fn(
        d, jnp.asarray(sg.in_deg[0]), sg.n_max, fbuf_rows, tile)
    out = np.asarray(fn(jnp.asarray(fbuf)))

    e = sg.edge_count[0]
    ref = _ref_mean(sg.edge_src[0][:e], sg.edge_dst[0][:e], sg.n_max,
                    fbuf, sg.in_deg[0])
    np.testing.assert_allclose(out[:sg.n_max], ref, rtol=1e-5, atol=1e-5)

    # pack/unpack round-trip on a raw block tensor
    a = (rng.random((3, 16, 16)) < 0.3).astype(np.float32)
    packed = pack_a_blocks(a)
    import jax.numpy as jnp2
    from pipegcn_tpu.ops.block_spmm import _unpack_bits

    unpacked = np.asarray(_unpack_bits(jnp2.asarray(packed), 16,
                                       jnp2.float32))
    np.testing.assert_array_equal(unpacked, a)


def test_group_union_extends_short_ladder():
    """An explicitly passed union-width ladder that tops out below the
    device's max union size is extended, not a hard failure — direct
    BlockPlan callers may reuse a group=1 layout's K-class ladder."""
    from pipegcn_tpu.ops.block_spmm import _group_union

    # one group of 4 key tiles referencing 6 distinct other-tiles:
    # union size 6 > ladder max 2
    keys = np.array([0, 1, 2, 3, 0, 1], np.int64)
    others = np.array([0, 1, 2, 3, 4, 5], np.int64)
    classes, inv, counts, widths = _group_union(
        keys, others, n_key_tiles=4, n_other_tiles=6, group=4,
        n_blocks_pad=6, widths=[1, 2])
    assert widths[-1] >= 6  # ladder extended to cover the union
    total_rows = sum(c for c in counts)
    assert total_rows == 1  # the single group landed in some class
    # every block is placed: the widest class holds all 6 union slots
    a_idx, t_mat = classes[-1]
    assert (t_mat[0] != 6).sum() == 6


@pytest.mark.parametrize("group", [2, 4])
def test_block_grouped_union_matches_dense(edges, group):
    """Union-gather layout (block_group > 1): consecutive dst tiles
    share one gathered source-tile union. Must agree exactly with the
    dense reference — and with the per-tile (group=1) path's gradients."""
    src, dst, n_out, n_src = edges
    rng = np.random.default_rng(3)
    fbuf = jnp.asarray(rng.standard_normal((n_src, 8)).astype(np.float32))
    deg = jnp.asarray(
        np.maximum(np.bincount(dst, minlength=n_out), 1).astype(np.float32)
    )
    plan = BlockPlan(src, dst, n_out, n_src, n_feat=8, tile=16,
                     nnz_threshold=4, group=group)
    assert plan.a_blocks.shape[0] > 0
    arrs = {k: jnp.asarray(v) for k, v in plan_to_arrays(plan).items()}
    assert "blk_fwdu_inv" in arrs  # grouped layout actually emitted
    fn = make_block_spmm_fn(arrs, deg, n_out, n_src, 16)
    out = fn(fbuf)
    np.testing.assert_allclose(
        np.asarray(out),
        _ref_mean(src, dst, n_out, np.asarray(fbuf), deg),
        rtol=1e-5, atol=1e-5)

    _, ref_fn = _make_fn(src, dst, n_out, n_src, deg, 16, 4)
    g_u = jax.grad(lambda f: (fn(f) ** 2).sum())(fbuf)
    g_r = jax.grad(lambda f: (ref_fn(f) ** 2).sum())(fbuf)
    np.testing.assert_allclose(np.asarray(g_u), np.asarray(g_r),
                               rtol=1e-5, atol=1e-6)


def test_trainer_block_grouped_matches_xla():
    """Trainer-level: the union-gather block kernel trains loss-for-loss
    with the raw-edge XLA path on a clustered layout, across devices
    (shared-cap padding + cross-device inv reoffsetting exercised)."""
    from pipegcn_tpu.partition import locality_clusters

    g = synthetic_graph(num_nodes=600, avg_degree=10, n_feat=12,
                        n_class=4, homophily=0.9, seed=25)
    parts = partition_graph(g, 4, seed=0)
    cluster = locality_clusters(g, target_size=64, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=4, cluster=cluster)
    losses = {}
    for impl, grp in (("xla", 1), ("block", 4)):
        cfg = ModelConfig(layer_sizes=(12, 16, 4), norm="layer",
                          dropout=0.0, train_size=sg.n_train_global,
                          spmm_impl=impl, block_tile=32, block_group=grp)
        t = Trainer(sg, cfg, TrainConfig(seed=4, enable_pipeline=True))
        losses[impl] = [t.train_epoch(e) for e in range(6)]
        if impl == "block":
            assert any(k.startswith("blk_fwdu_g") for k in t._block_tables)
    np.testing.assert_allclose(losses["xla"], losses["block"], rtol=2e-4)


@pytest.mark.parametrize("group", [1, 4])
def test_chunked_scan_path_matches(edges, group, monkeypatch):
    """Force _apply_classes' lax.scan chunking (tiny element budget) —
    the padded-tail/reshape/slice logic must not change results in
    either dense layout."""
    import pipegcn_tpu.ops.block_spmm as bsp

    src, dst, n_out, n_src = edges
    rng = np.random.default_rng(5)
    fbuf = jnp.asarray(rng.standard_normal((n_src, 8)).astype(np.float32))
    deg = jnp.asarray(
        np.maximum(np.bincount(dst, minlength=n_out), 1).astype(np.float32)
    )
    plan = BlockPlan(src, dst, n_out, n_src, n_feat=8, tile=16,
                     nnz_threshold=4, group=group)
    arrs = {k: jnp.asarray(v) for k, v in plan_to_arrays(plan).items()}
    fn = make_block_spmm_fn(arrs, deg, n_out, n_src, 16)
    # reference values (fwd AND grad) must trace BEFORE the patch:
    # fn is unjitted, so a later jax.grad(fn) would re-trace through
    # the patched chunk budget and compare the scan path to itself
    ref = np.asarray(fn(fbuf))
    g_ref = jax.grad(lambda f: (fn(f) ** 2).sum())(fbuf)
    monkeypatch.setattr(bsp, "_DENSE_CHUNK_ELEMS", 2048)
    fn_c = make_block_spmm_fn(arrs, deg, n_out, n_src, 16)
    np.testing.assert_allclose(np.asarray(fn_c(fbuf)), ref,
                               rtol=1e-6, atol=1e-6)
    g_c = jax.grad(lambda f: (fn_c(f) ** 2).sum())(fbuf)
    np.testing.assert_allclose(np.asarray(g_c), np.asarray(g_ref),
                               rtol=1e-5, atol=1e-6)


def test_trainer_headline_stack_fused():
    """The exact benchmark-headline configuration in one run: block
    kernel, union-gather group 4, fp8 remainder transport, bf16
    compute, use_pp, pipelined + corrections, fused-epoch scan."""
    from pipegcn_tpu.partition import locality_clusters

    g = synthetic_graph(num_nodes=600, avg_degree=10, n_feat=12,
                        n_class=4, homophily=0.9, seed=25)
    parts = partition_graph(g, 4, seed=0)
    cluster = locality_clusters(g, target_size=64, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=4, cluster=cluster)
    cfg = ModelConfig(layer_sizes=(12, 16, 16, 4), norm="layer",
                      dropout=0.2, train_size=sg.n_train_global,
                      spmm_impl="block", block_tile=32, block_group=4,
                      rem_dtype="float8", dtype="bfloat16", use_pp=True)
    t = Trainer(sg, cfg, TrainConfig(seed=4, enable_pipeline=True,
                                     feat_corr=True, grad_corr=True))
    # the grouped union-gather tables must actually be in play — zero
    # dense tiles would silently reduce this to a remainder-only run
    assert any(k.startswith("blk_fwdu_g") for k in t._block_tables)
    a_key = "blk_a_bits" if "blk_a_bits" in t._block_tables else "blk_a"
    assert t._block_tables[a_key].shape[1] > 0
    losses = list(t.train_epochs(0, 4)) + list(t.train_epochs(4, 16))
    assert np.isfinite(losses).all()
    assert np.mean(losses[-4:]) < np.mean(losses[:4])
