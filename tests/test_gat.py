"""GAT model family (framework extension): numpy attention-reference
parity, distributed-vs-single parity through the halo machinery, and
convergence."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pipegcn_tpu.graph import synthetic_graph
from pipegcn_tpu.models import ModelConfig, forward, init_params
from pipegcn_tpu.parallel import Trainer, TrainConfig
from pipegcn_tpu.partition import ShardedGraph, partition_graph


@pytest.fixture(scope="module")
def graph():
    return synthetic_graph(num_nodes=350, avg_degree=7, n_feat=10,
                           n_class=4, seed=17)


def _gat_setup(g, n_parts, *, dropout=0.0, **tkw):
    parts = partition_graph(g, n_parts, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=n_parts)
    cfg = ModelConfig(
        layer_sizes=(sg.n_feat, 16, sg.n_class), model="gat", n_heads=4,
        norm="layer", dropout=dropout, train_size=sg.n_train_global,
    )
    return Trainer(sg, cfg, TrainConfig(**tkw))


def test_gat_forward_matches_dense_reference(graph):
    """One mean-head GAT layer vs a numpy edge-softmax reference."""
    g = graph
    n = g.num_nodes
    f = g.ndata["feat"].shape[1]
    cfg = ModelConfig(layer_sizes=(f, 5), model="gat", n_heads=3,
                      norm=None, dropout=0.0, train_size=n)
    params = init_params(jax.random.PRNGKey(1), cfg)
    feat = g.ndata["feat"].astype(np.float32)

    order = np.argsort(g.dst, kind="stable")
    es_np, ed_np = g.src[order], g.dst[order]
    logits, _ = forward(params, cfg, jnp.asarray(feat),
                        jnp.asarray(es_np.astype(np.int32)),
                        jnp.asarray(ed_np.astype(np.int32)),
                        jnp.asarray(g.ndata["in_deg"].astype(np.float32)),
                        n, training=False)

    lp = {k: np.asarray(v, np.float64) for k, v in
          params["layers"][0].items()}
    h_, dh = 3, 5
    z = (feat.astype(np.float64) @ lp["w"]).reshape(n, h_, dh)
    el = (z * lp["a_src"]).sum(-1)
    er = (z * lp["a_dst"]).sum(-1)
    e = el[es_np] + er[ed_np]
    e = np.where(e > 0, e, 0.2 * e)
    out = np.zeros((n, h_, dh))
    for d in range(n):
        sel = ed_np == d
        if not sel.any():
            continue
        w = np.exp(e[sel] - e[sel].max(axis=0))
        w /= w.sum(axis=0)
        out[d] = (z[es_np[sel]] * w[:, :, None]).sum(axis=0)
    ref = out.mean(axis=1) + lp["b"]
    np.testing.assert_allclose(np.asarray(logits), ref, rtol=2e-4,
                               atol=2e-4)


def test_gat_distributed_matches_single_device(graph):
    t1 = _gat_setup(graph, 1, seed=3)
    t4 = _gat_setup(graph, 4, seed=3)
    for epoch in range(4):
        l1, l4 = t1.train_epoch(epoch), t4.train_epoch(epoch)
        assert np.isfinite(l1)
        np.testing.assert_allclose(l1, l4, rtol=3e-4)


def test_gat_pipelined_converges(graph):
    t = _gat_setup(graph, 4, dropout=0.2, seed=9, enable_pipeline=True,
                   n_epochs=40, log_every=10)
    res = t.fit(eval_graphs={"val": (graph, "val_mask"),
                             "test": (graph, "test_mask")},
                log_fn=lambda m: None)
    assert res["best_val"] > 0.75


def test_gat_config_validation():
    with pytest.raises(ValueError, match="GraphSAGE-only"):
        ModelConfig(layer_sizes=(4, 8, 2), model="gat", use_pp=True)
    with pytest.raises(ValueError, match="divisible"):
        ModelConfig(layer_sizes=(4, 10, 2), model="gat", n_heads=4)


def test_gat_chunked_matches_unchunked(graph):
    """cfg.spmm_chunk bounds the edge intermediates; results identical."""
    g = graph
    parts = partition_graph(g, 2, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=2)
    losses = {}
    for chunk in (None, 500):
        cfg = ModelConfig(layer_sizes=(sg.n_feat, 16, sg.n_class),
                          model="gat", n_heads=4, norm="layer",
                          dropout=0.0, train_size=sg.n_train_global,
                          spmm_chunk=chunk)
        t = Trainer(sg, cfg, TrainConfig(seed=2))
        losses[chunk] = [t.train_epoch(e) for e in range(3)]
    np.testing.assert_allclose(losses[None], losses[500], rtol=1e-5)


def test_gat_rejects_table_impls_and_bad_heads():
    with pytest.raises(ValueError, match="does not apply to gat"):
        ModelConfig(layer_sizes=(4, 8, 2), model="gat", spmm_impl="block")
    with pytest.raises(ValueError, match="n_heads"):
        ModelConfig(layer_sizes=(4, 8, 2), model="gat", n_heads=0)


def test_gat_multilabel_bce():
    g = synthetic_graph(num_nodes=300, avg_degree=7, n_feat=10, n_class=5,
                        multilabel=True, seed=19)
    parts = partition_graph(g, 4, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=4)
    cfg = ModelConfig(layer_sizes=(sg.n_feat, 16, sg.n_class),
                      model="gat", n_heads=4, norm="layer", dropout=0.1,
                      train_size=sg.n_train_global)
    t = Trainer(sg, cfg, TrainConfig(seed=2, enable_pipeline=True))
    losses = [t.train_epoch(e) for e in range(8)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    f1 = t.evaluate(g, "val_mask")
    assert 0.0 <= f1 <= 1.0
