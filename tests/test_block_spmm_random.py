"""Randomized parity sweep over the block kernel's layout space.

The fixed-seed tests pin known shapes; this sweeps random (graph,
tile, threshold, group) combinations — including degenerate ones
(single-tile outputs, groups wider than the tile count, dense-empty
grouped plans, hub rows) — against the dense reference. Every
configuration must aggregate exactly."""

import numpy as np
import jax.numpy as jnp
import pytest

from pipegcn_tpu.ops.block_spmm import (
    BlockPlan,
    make_block_spmm_fn,
    plan_to_arrays,
)


def _ref(src, dst, n_out, fbuf, deg):
    out = np.zeros((n_out, fbuf.shape[1]), np.float32)
    np.add.at(out, dst, np.asarray(fbuf, np.float32)[src])
    return out / deg[:, None]


@pytest.mark.parametrize("trial", range(12))
def test_randomized_layout_parity(trial):
    rng = np.random.default_rng(100 + trial)
    n_out = int(rng.integers(8, 200))
    n_src = n_out + int(rng.integers(0, 80))
    e = int(rng.integers(1, 4000))
    tile = int(rng.choice([8, 16, 32]))
    thr = int(rng.choice([1, 3, 8, 10 ** 9]))
    group = int(rng.choice([1, 2, 4, 7]))
    f = int(rng.choice([4, 8, 16]))
    src = rng.integers(0, n_src, e).astype(np.int64)
    dst = rng.integers(0, n_out, e).astype(np.int64)
    if trial % 3 == 0:  # hub row + clustered corner
        dst[: e // 2] = rng.integers(0, max(1, n_out // 8), e // 2)
        src[: e // 2] = rng.integers(0, max(1, n_src // 8), e // 2)
    deg = np.maximum(np.bincount(dst, minlength=n_out), 1).astype(
        np.float32)
    plan = BlockPlan(src, dst, n_out, n_src, n_feat=f, tile=tile,
                     nnz_threshold=thr, group=group)
    arrs = {k: jnp.asarray(v) for k, v in plan_to_arrays(plan).items()}
    fn = make_block_spmm_fn(arrs, jnp.asarray(deg), n_out, n_src, tile)
    fbuf = rng.standard_normal((n_src, f)).astype(np.float32)
    out = np.asarray(fn(jnp.asarray(fbuf)))
    np.testing.assert_allclose(out, _ref(src, dst, n_out, fbuf, deg),
                               rtol=2e-5, atol=2e-5,
                               err_msg=f"n_out={n_out} tile={tile} "
                                       f"thr={thr} group={group}")
