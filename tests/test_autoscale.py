"""Closed-loop autoscaling + traffic realism (serve/autoscale.py,
serve/loadgen.py shapes, serve/batcher.py AdmissionLadder,
docs/SERVING.md "Autoscaling & overload").

These tests pin the round-17 contracts:
  - shaped arrival schedules: Lewis-Shedler thinning against the
    RateShape grammar (constant / diurnal / flash-crowd / trace
    replay), seeded determinism (same seed -> bitwise-identical
    schedule), the constant path bit-identical to the legacy draw,
    no coordinated omission (the schedule is fixed up front), and the
    mixed update/query marking leaving the query bitstream unchanged;
  - AutoscalePolicy under a fake clock: sustained-queue /
    immediate-shed / p99-SLO / alert-edge scale-up triggers, the
    idle scale-down, cooldown + storm-brake refusals carrying the
    trigger evidence, max-replicas refusal, the silent min-replicas
    hold, and the one-replica-per-decision ramp;
  - the graceful-degradation ladder: pure rung mapping, transition
    counting, effective-bound tightening, brownout-before-blackout
    through MicroBatcher with per-reason shed accounting and the
    conservation invariant intact;
  - the net-delay / net-drop / net-partition fault-plan kinds: parse
    grammar, single-shot due_member_arg, and the NetFaultInjector
    gate driving the router's retry/backoff path against slow,
    lossy, and partitioned (then healed) replicas;
  - consistent-hash ring remap on spawn/retire membership changes:
    only the joining/leaving replica's arcs move;
  - the contracted schema-v12 `autoscale` record round-trip.
"""

import json

import numpy as np
import pytest

from pipegcn_tpu.obs.metrics import MetricsLogger, read_metrics
from pipegcn_tpu.obs.schema import validate_record
from pipegcn_tpu.resilience import FaultPlan
from pipegcn_tpu.serve.autoscale import (
    AutoscalePolicy,
    NetFaultInjector,
    ScaleDecision,
)
from pipegcn_tpu.serve.batcher import AdmissionLadder, MicroBatcher
from pipegcn_tpu.serve.loadgen import (
    OpenLoopGenerator,
    RateShape,
    thinned_arrivals,
)
from pipegcn_tpu.serve.router import Router

pytestmark = pytest.mark.autoscale


class FakeTime:
    """Injectable clock whose sleep() advances it (no real waiting)."""

    def __init__(self, t=0.0):
        self.t = float(t)

    def clock(self):
        return self.t

    def sleep(self, s):
        self.t += max(float(s), 0.0)


# ---------------- traffic shapes ---------------------------------------


def test_rate_shape_parse_grammar():
    s = RateShape.parse("diurnal:20:0.5", qps=40.0, duration_s=10.0)
    assert s.kind == "diurnal" and s.period_s == 20.0 and s.floor == 0.5
    s = RateShape.parse("flash-crowd:6:0.2:0.5", qps=40.0,
                        duration_s=10.0)
    assert (s.kind == "flash-crowd" and s.mult == 6.0
            and s.t0_frac == 0.2 and s.t1_frac == 0.5)
    assert RateShape.parse(None, 40.0, 10.0).kind == "constant"
    assert RateShape.parse("", 40.0, 10.0).kind == "constant"
    for bad in ("sawtooth", "constant:3", "diurnal:1:2:3",
                "flash-crowd:4:0.7:0.4", "diurnal:abc"):
        with pytest.raises(ValueError):
            RateShape.parse(bad, 40.0, 10.0)


def test_rate_shape_rate_functions():
    d = RateShape("diurnal", 100.0, 10.0, period_s=10.0, floor=0.25)
    assert d.rate(0.0) == pytest.approx(25.0)     # trough at t=0
    assert d.rate(5.0) == pytest.approx(100.0)    # peak at period/2
    assert d.peak == pytest.approx(100.0)
    f = RateShape("flash-crowd", 50.0, 10.0, mult=4.0,
                  t0_frac=0.4, t1_frac=0.7)
    assert f.rate(1.0) == pytest.approx(50.0)
    assert f.rate(5.0) == pytest.approx(200.0)    # inside [4, 7)
    assert f.rate(8.0) == pytest.approx(50.0)
    assert f.peak == pytest.approx(200.0)
    assert f.crowd_window() == pytest.approx((4.0, 7.0))
    assert d.crowd_window() is None


def test_trace_shape_replay(tmp_path):
    p = tmp_path / "trace.json"
    p.write_text(json.dumps([[0.0, 10.0], [5.0, 100.0]]))
    s = RateShape.parse(f"trace:{p}", qps=0.0, duration_s=10.0)
    assert s.rate(2.0) == pytest.approx(10.0)
    assert s.rate(7.0) == pytest.approx(100.0)   # last value held
    assert s.peak == pytest.approx(100.0)
    rng = np.random.default_rng(0)
    arr = thinned_arrivals(s, 10.0, rng)
    first, second = (arr < 5.0).sum(), (arr >= 5.0).sum()
    # 10 qps for 5 s vs 100 qps for 5 s: the replay must be lopsided
    assert second > 4 * first


def test_thinning_flash_crowd_burst_statistics():
    shape = RateShape("flash-crowd", 50.0, 30.0, mult=4.0,
                      t0_frac=0.4, t1_frac=0.7)
    arr = thinned_arrivals(shape, 30.0, np.random.default_rng(1))
    t0, t1 = shape.crowd_window()
    in_crowd = ((arr >= t0) & (arr < t1)).sum()
    outside = len(arr) - in_crowd
    # expected 50*4*9 = 1800 inside vs 50*21 = 1050 outside; the
    # per-second RATE ratio must be ~ mult (loose: Poisson noise)
    rate_ratio = (in_crowd / (t1 - t0)) / (outside / (30.0 - (t1 - t0)))
    assert 3.0 < rate_ratio < 5.0
    assert np.all(np.diff(arr) >= 0)  # sorted: fixed up front, open loop


def test_thinned_arrivals_deterministic_per_seed():
    shape = RateShape("diurnal", 80.0, 12.0)
    a = thinned_arrivals(shape, 12.0, np.random.default_rng(7))
    b = thinned_arrivals(shape, 12.0, np.random.default_rng(7))
    c = thinned_arrivals(shape, 12.0, np.random.default_rng(8))
    np.testing.assert_array_equal(a, b)
    assert len(a) != len(c) or not np.array_equal(a, c)


def test_generator_constant_path_bit_identical_to_legacy():
    """traffic=None and traffic='constant' must both take the legacy
    homogeneous draw — bit-identical arrivals AND queries, so
    pre-shape seeds replay unchanged."""
    g0 = OpenLoopGenerator(100, 40.0, 5.0, seed=3)
    g1 = OpenLoopGenerator(100, 40.0, 5.0, seed=3, traffic="constant")
    np.testing.assert_array_equal(g0.arrivals, g1.arrivals)
    np.testing.assert_array_equal(g0.queries, g1.queries)
    assert not g0.is_update.any()


def test_generator_update_fraction_marks_without_perturbing_stream():
    g0 = OpenLoopGenerator(100, 40.0, 5.0, seed=3)
    g1 = OpenLoopGenerator(100, 40.0, 5.0, seed=3, update_fraction=0.3)
    # the update draw happens AFTER arrivals/queries: same bitstream
    np.testing.assert_array_equal(g0.arrivals, g1.arrivals)
    np.testing.assert_array_equal(g0.queries, g1.queries)
    frac = g1.is_update.mean()
    assert 0.15 < frac < 0.45
    g2 = OpenLoopGenerator(100, 40.0, 5.0, seed=3, update_fraction=0.3)
    np.testing.assert_array_equal(g1.is_update, g2.is_update)


def test_generator_shaped_deterministic():
    g1 = OpenLoopGenerator(100, 30.0, 8.0, seed=5,
                           traffic="flash-crowd:4")
    g2 = OpenLoopGenerator(100, 30.0, 8.0, seed=5,
                           traffic="flash-crowd:4")
    np.testing.assert_array_equal(g1.arrivals, g2.arrivals)
    np.testing.assert_array_equal(g1.queries, g2.queries)
    assert g1.shape.kind == "flash-crowd"


# ---------------- autoscale policy -------------------------------------


def _obs(p, window, *, q=0, shed=0.0, p99=None, n=1, alerts=()):
    return p.observe(window, q, shed, p99, n, alerts=alerts)


def test_policy_scale_up_on_sustained_queue_pressure():
    ft = FakeTime()
    p = AutoscalePolicy(queue_high=64, sustain_ticks=2, cooldown_s=10,
                        clock=ft.clock)
    d = _obs(p, 0, q=100)          # first hot window: a blip
    assert d.action == "hold"
    ft.t += 1
    d = _obs(p, 1, q=100)          # sustained: scale
    assert d.action == "scale-up" and d.reason == "queue-pressure"
    assert d.target == 2 and d.wants_scale
    assert d.evidence["queue_depth"] == 100
    assert p.n_up == 1


def test_policy_shed_rate_scales_immediately():
    p = AutoscalePolicy(shed_high=0.01, clock=FakeTime().clock)
    d = _obs(p, 0, q=0, shed=0.2)  # already dropping work: no sustain
    assert d.action == "scale-up" and d.reason == "shed-rate"


def test_policy_p99_slo_sustained():
    ft = FakeTime()
    p = AutoscalePolicy(p99_slo_ms=50.0, sustain_ticks=2,
                        clock=ft.clock)
    assert _obs(p, 0, p99=80.0).action == "hold"
    d = _obs(p, 1, p99=80.0)
    assert d.action == "scale-up" and d.reason == "p99-slo"
    # None p99 (no latency samples this window) resets the streak
    p2 = AutoscalePolicy(p99_slo_ms=50.0, sustain_ticks=2,
                         clock=ft.clock)
    _obs(p2, 0, p99=80.0)
    _obs(p2, 1, p99=None)
    assert _obs(p2, 2, p99=80.0).action == "hold"


def test_policy_alert_edge_scales_up():
    p = AutoscalePolicy(clock=FakeTime().clock)
    d = _obs(p, 0, alerts=("shed-rate",))
    assert d.action == "scale-up" and d.reason == "alert:shed-rate"
    # non-overload rules are not scale evidence
    p2 = AutoscalePolicy(clock=FakeTime().clock)
    assert _obs(p2, 0, alerts=("silent-source",)).action == "hold"


def test_policy_scale_down_after_idle_ticks():
    ft = FakeTime()
    p = AutoscalePolicy(queue_low=8, idle_ticks=3, cooldown_s=0.0,
                        clock=ft.clock)
    for w in range(2):
        assert _obs(p, w, q=0, n=3).action == "hold"
        ft.t += 1
    d = _obs(p, 2, q=0, n=3)
    assert d.action == "scale-down" and d.reason == "idle"
    assert d.target == 2 and p.n_down == 1
    # any shed breaks the idle streak even with an empty queue
    p2 = AutoscalePolicy(queue_low=8, idle_ticks=2, cooldown_s=0.0,
                         clock=ft.clock)
    _obs(p2, 0, q=0, n=3)
    _obs(p2, 1, q=0, shed=0.001, n=3)
    assert _obs(p2, 2, q=0, n=3).action == "hold"


def test_policy_min_replicas_holds_silently():
    ft = FakeTime()
    p = AutoscalePolicy(min_replicas=1, idle_ticks=1, cooldown_s=0.0,
                        clock=ft.clock)
    d = _obs(p, 0, q=0, n=1)
    assert d.action == "hold" and d.reason == "min-replicas"
    assert p.n_refused == 0  # the floor is not a refusal


def test_policy_max_replicas_refuses_with_trigger():
    p = AutoscalePolicy(max_replicas=2, shed_high=0.01,
                        clock=FakeTime().clock)
    d = _obs(p, 0, shed=0.5, n=2)
    assert d.action == "refuse" and d.reason == "max-replicas"
    assert d.evidence["trigger"] == "shed-rate"
    assert not d.wants_scale and p.n_refused == 1


def test_policy_cooldown_refuses_then_allows():
    ft = FakeTime()
    p = AutoscalePolicy(shed_high=0.01, cooldown_s=10.0,
                        clock=ft.clock)
    assert _obs(p, 0, shed=0.5).action == "scale-up"
    ft.t += 3.0
    d = _obs(p, 1, shed=0.5, n=2)
    assert d.action == "refuse" and d.reason == "cooldown"
    assert d.evidence["trigger"] == "shed-rate"
    ft.t += 10.0
    assert _obs(p, 2, shed=0.5, n=2).action == "scale-up"


def test_policy_storm_brake():
    ft = FakeTime()
    p = AutoscalePolicy(shed_high=0.01, cooldown_s=0.0,
                        storm_window_s=60.0, storm_threshold=2,
                        clock=ft.clock)
    assert _obs(p, 0, shed=0.5, n=1).action == "scale-up"
    ft.t += 1
    assert _obs(p, 1, shed=0.5, n=2).action == "scale-up"
    ft.t += 1
    d = _obs(p, 2, shed=0.5, n=3)
    assert d.action == "refuse" and d.reason == "storm-brake"
    # outside the window the breaker resets
    ft.t += 120.0
    assert _obs(p, 3, shed=0.5, n=3).action == "scale-up"


def test_policy_one_replica_per_decision():
    ft = FakeTime()
    p = AutoscalePolicy(shed_high=0.01, cooldown_s=0.0,
                        storm_threshold=100, clock=ft.clock)
    d = _obs(p, 0, shed=0.9, n=1)
    assert d.target == 2  # never jumps, however bad the telemetry
    ft.t += 1
    assert _obs(p, 1, shed=0.9, n=2).target == 3


def test_policy_scale_resets_hysteresis():
    ft = FakeTime()
    p = AutoscalePolicy(queue_high=10, sustain_ticks=2, cooldown_s=0.0,
                        clock=ft.clock)
    _obs(p, 0, q=50)
    assert _obs(p, 1, q=50).action == "scale-up"
    # the executed scale zeroed the streak: next hot window is tick 1
    assert _obs(p, 2, q=50, n=2).action == "hold"


def test_policy_validates_bounds():
    with pytest.raises(ValueError):
        AutoscalePolicy(min_replicas=0)
    with pytest.raises(ValueError):
        AutoscalePolicy(min_replicas=3, max_replicas=2)


# ---------------- degradation ladder -----------------------------------


def test_ladder_rung_mapping_and_transitions():
    lad = AdmissionLadder()
    assert lad.rung_for(0.0) == 0
    assert lad.rung_for(0.49) == 0
    assert lad.rung_for(0.5) == 1
    assert lad.rung_for(0.74) == 1
    assert lad.rung_for(0.9) == 2
    assert lad.observe(0, 100) == 0
    assert lad.observe(60, 100) == 1
    assert lad.observe(90, 100) == 2
    assert lad.observe(10, 100) == 0
    assert lad.n_transitions == 3


def test_ladder_effective_tightening():
    lad = AdmissionLadder()
    lad.observe(90, 100)  # rung 2
    eff_q, eff_d = lad.effective(100, 1.0)
    assert eff_q == 80 and eff_d == pytest.approx(0.25)
    assert lad.effective(None, None) == (None, None)
    lad.observe(0, 100)   # back to rest: no tightening
    assert lad.effective(100, 1.0) == (100, 1.0)


def test_ladder_validates_rungs():
    with pytest.raises(ValueError):
        AdmissionLadder(rungs=((0.5, 0.9, 0.5),))       # no rung 0
    with pytest.raises(ValueError):
        AdmissionLadder(rungs=((0.0, 1.0, 1.0),
                               (0.8, 0.9, 0.5),
                               (0.5, 0.8, 0.25)))       # unsorted
    with pytest.raises(ValueError):
        AdmissionLadder(rungs=((0.0, 0.0, 1.0),))       # zero bound


def test_batcher_brownout_before_blackout():
    ft = FakeTime()
    sheds = []
    b = MicroBatcher(lambda ids: np.zeros((ids.size, 2), np.float32),
                     max_batch=64, max_delay_ms=10_000.0,
                     clock=ft.clock, max_queue=10,
                     on_shed=lambda t, r: sheds.append(r),
                     admission_ladder=AdmissionLadder())
    for _ in range(8):
        t = b.submit(np.array([1]))
        assert not t.shed
    # depth 8 -> pressure 0.8 -> rung 2 tightens the bound to 8: the
    # next row is under the HARD wall (8+1 <= 10) but browns out
    t = b.submit(np.array([2]))
    assert t.shed and t.shed_reason == "brownout"
    assert b.rung == 2
    # past the hard wall itself: blackout keeps its own reason
    t = b.submit(np.array([3, 4, 5]))
    assert t.shed and t.shed_reason == "queue-full"
    assert sheds == ["brownout", "queue-full"]
    # conservation: submitted == served + shed + queued, always
    assert (b.n_submitted_rows
            == b.n_served_rows + b.n_shed_rows + b.queue_depth)
    b.drain()
    assert (b.n_submitted_rows
            == b.n_served_rows + b.n_shed_rows + b.queue_depth)
    assert b.n_served_rows == 8


def test_batcher_without_ladder_keeps_legacy_wall():
    ft = FakeTime()
    b = MicroBatcher(lambda ids: np.zeros((ids.size, 2), np.float32),
                     max_batch=64, max_delay_ms=10_000.0,
                     clock=ft.clock, max_queue=10)
    assert b.rung == 0
    for _ in range(10):
        assert not b.submit(np.array([1])).shed
    t = b.submit(np.array([1]))
    assert t.shed and t.shed_reason == "queue-full"


# ---------------- net-fault plan grammar -------------------------------


def test_fault_plan_net_kinds_parse_and_roundtrip():
    fp = FaultPlan.parse("net-delay@2:m1:250,net-drop@3:m0,"
                         "net-partition@5:2")
    assert fp.remaining() == ["net-delay@2:m1:250", "net-drop@3:m0",
                              "net-partition@5:2"]
    assert fp.due_member_arg("net-delay", 1) is None  # not yet due
    assert fp.due_member_arg("net-delay", 2) == (1, 250)
    assert fp.due_member_arg("net-delay", 2) is None  # single-shot
    assert fp.due_member_arg("net-drop", 4) == (0, 0)  # default member
    assert fp.due_member_arg("net-partition", 5) == (0, 2)


def test_fault_plan_net_kinds_reject_malformed():
    with pytest.raises(ValueError):
        FaultPlan.parse("net-frob@1")
    with pytest.raises(ValueError):
        FaultPlan.parse("net-drop@1:250")  # drop takes no argument


# ---------------- net-fault injector + router retry --------------------


class GatedClient:
    """Replica client double whose every query consults the injector
    gate first — the TcpReplicaClient.fault_gate seam, minus TCP."""

    def __init__(self, rid, net):
        self.rid = rid
        self.net = net
        self.n_queries = 0

    def query(self, ids):
        self.net.gate(self.rid, "query")
        self.n_queries += 1
        ids = np.asarray(ids)
        return np.stack([ids, ids * 2], axis=1).astype(np.float32)


def test_injector_partition_window_and_heal():
    ft = FakeTime()
    net = NetFaultInjector(clock=ft.clock, sleep=ft.sleep)
    net.partition(0, 5.0)
    assert net.partitioned(0) and not net.partitioned(1)
    with pytest.raises(ConnectionError):
        net.gate(0, "query")
    net.gate(1, "query")  # other replicas unaffected
    ft.t += 6.0
    assert not net.partitioned(0)
    net.gate(0, "query")  # healed: no raise
    assert net.n_gated == 1


def test_injector_drop_is_counted():
    net = NetFaultInjector(clock=FakeTime().clock)
    net.drop(0, n=2)
    for _ in range(2):
        with pytest.raises(ConnectionError):
            net.gate(0, "query")
    net.gate(0, "query")  # budget spent
    assert net.n_gated == 2


def test_injector_delay_sleeps_until_expiry():
    ft = FakeTime()
    net = NetFaultInjector(clock=ft.clock, sleep=ft.sleep)
    net.delay(0, 250.0, 10.0)
    t0 = ft.t
    net.gate(0, "query")
    assert ft.t - t0 == pytest.approx(0.25)
    ft.t = 20.0  # arming expired
    t0 = ft.t
    net.gate(0, "query")
    assert ft.t == t0


def test_router_fails_over_on_net_drop():
    ft = FakeTime()
    net = NetFaultInjector(clock=ft.clock, sleep=ft.sleep)
    clients = {0: GatedClient(0, net), 1: GatedClient(1, net)}
    r = Router(clients, clock=ft.clock, sleep=ft.sleep,
               retry_timeout_s=5.0)
    net.drop(0, n=1)
    out, rid = r.dispatch(np.array([5]))
    assert rid == 1 and r.n_failovers == 1
    assert not r.is_up(0)  # the drop marked it down eagerly
    # the manager's health probe heals it; traffic returns
    assert r.mark_up(0)
    _, rid = r.dispatch(np.array([6]))
    assert rid == 0


def test_router_full_partition_raises_fleet_unavailable():
    """A partition of the WHOLE fleet ends in FleetUnavailable fast —
    the caller sheds the batch explicitly instead of hanging on the
    retry budget once every replica is marked down."""
    from pipegcn_tpu.serve.router import FleetUnavailable

    ft = FakeTime()
    net = NetFaultInjector(clock=ft.clock, sleep=ft.sleep)
    clients = {0: GatedClient(0, net), 1: GatedClient(1, net)}
    r = Router(clients, clock=ft.clock, sleep=ft.sleep,
               retry_timeout_s=2.0)
    net.partition(0, 100.0)
    net.partition(1, 100.0)
    with pytest.raises(FleetUnavailable):
        r.dispatch(np.array([1]))
    assert r.up_replicas() == []       # both marked down eagerly
    assert ft.t < 2.0                  # short-circuit, not a timeout
    # the partition heals and the manager's probe marks them up:
    # dispatch works again with no replica restarted
    ft.t += 200.0
    r.mark_up(0), r.mark_up(1)
    _, rid = r.dispatch(np.array([3]))
    assert rid in (0, 1)


def test_router_survives_net_delay_within_budget():
    ft = FakeTime()
    net = NetFaultInjector(clock=ft.clock, sleep=ft.sleep)
    clients = {0: GatedClient(0, net)}
    r = Router(clients, clock=ft.clock, sleep=ft.sleep,
               retry_timeout_s=5.0)
    net.delay(0, 300.0, 10.0)
    out, rid = r.dispatch(np.array([7]))
    assert rid == 0 and out[0, 1] == 14.0
    assert ft.t == pytest.approx(0.3)  # slow, not dead: no failover
    assert r.n_failovers == 0


# ---------------- membership: ring remap on spawn/retire ---------------


class FakeTimeClient:
    """Minimal client for membership tests (never dispatched)."""

    def __init__(self, rid):
        self.rid = rid

    def query(self, ids):
        ids = np.asarray(ids)
        return np.stack([ids, ids * 2], axis=1).astype(np.float32)


def _hash_map(r, keys):
    return {k: r._pick(np.asarray([k]), set()) for k in keys}


def test_add_replica_remaps_only_new_arcs():
    c = {0: FakeTimeClient(0), 1: FakeTimeClient(1)}
    r = Router(c, policy="hash", sleep=lambda s: None)
    keys = range(400)
    before = _hash_map(r, keys)
    r.add_replica(2, FakeTimeClient(2))
    after = _hash_map(r, keys)
    moved = [k for k in keys if before[k] != after[k]]
    assert moved, "the new replica must take some arcs"
    assert all(after[k] == 2 for k in moved)
    assert len(moved) < len(list(keys)) / 2  # only ITS arcs moved
    assert r.has_replica(2) and r.is_up(2)


def test_remove_replica_remaps_only_its_arcs():
    c = {0: FakeTimeClient(0), 1: FakeTimeClient(1),
         2: FakeTimeClient(2)}
    r = Router(c, policy="hash", sleep=lambda s: None)
    keys = range(400)
    before = _hash_map(r, keys)
    r.remove_replica(2)
    after = _hash_map(r, keys)
    for k in keys:
        if before[k] != 2:
            assert after[k] == before[k]  # survivors' arcs untouched
        else:
            assert after[k] in (0, 1)
    assert not r.has_replica(2)
    # mark_down/mark_up on a retired rid are inert, not a resurrection
    assert r.mark_down(2) is False
    assert r.mark_up(2) is False
    assert 2 not in r.queue_depths()


# ---------------- schema: the autoscale record -------------------------


def test_autoscale_record_contract(tmp_path):
    path = tmp_path / "m.jsonl"
    ml = MetricsLogger(str(path))
    ml.autoscale("scale-up", "queue-pressure", 7, 2, 3,
                 {"queue_depth": 90, "shed_rate": 0.0,
                  "alerts": []})
    ml.autoscale("refuse", "cooldown", 8, 3, 3,
                 {"trigger": "shed-rate"})
    ml.close()
    recs = [r for r in read_metrics(str(path))
            if r.get("event") == "autoscale"]
    assert len(recs) == 2
    for r in recs:
        validate_record(r)
    assert recs[0]["action"] == "scale-up"
    assert recs[0]["target"] == 3
    assert recs[0]["evidence"]["queue_depth"] == 90
    assert recs[1]["reason"] == "cooldown"
    # a malformed record (evidence must be an object) is rejected
    with pytest.raises(ValueError):
        validate_record({"event": "autoscale", "action": "scale-up",
                         "reason": "x", "window": 1, "n_replicas": 1,
                         "target": 2, "evidence": "not-an-object"})


def test_scale_decision_surface():
    d = ScaleDecision("hold", 2, "steady", {})
    assert not d.wants_scale
    assert ScaleDecision("scale-down", 1, "idle", {}).wants_scale
