"""Serving fleet (pipegcn_tpu/serve/fleet.py + router.py,
docs/SERVING.md "Fleet").

These tests pin the round-12 fleet contracts:
  - router placement (least in-flight rows with id tiebreak; the
    consistent-hash ring's stability/spread and dead-arc-only remap),
    edge-triggered mark_down/mark_up, failover retry against
    survivors, and FleetUnavailable when nobody answers;
  - MicroBatcher's take/complete/shed split (the threaded dispatch
    path) and the conservation invariant
    submitted == served + shed + queue_depth;
  - the replica-kill@W[:mK] fault-plan grammar: parse, default member,
    single-shot due_member, boundary retirement on resume, rejection
    of malformed entries;
  - ReplicaServer over real TCP in-process: readiness file,
    incarnation-keyed heartbeat, query/health/stop ops, the final
    hard-flushed serving record;
  - the checkpoint hot-swap watcher: poll_checkpoint's hot-swap /
    swap-rejected fleet records, and ServingEngine.load_from_checkpoint
    against a real mesh — walk-back past a corrupt newest generation,
    per-generation fault dedupe, staleness bookkeeping;
  - run_fleet_loop end to end on fakes (hash policy, fake clock): a
    scripted replica-kill mid-load, failover to the survivor, zero
    accepted tickets lost, schema-valid serving records;
  - the two-process replica-kill drill (slow, chaos lane): SIGKILL a
    live replica subprocess mid-load; the router routes to the
    survivor, the supervisor relaunches + rejoins it, and the driver
    exits 0 with the conservation invariant intact.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from pipegcn_tpu.obs.metrics import MetricsLogger, read_metrics
from pipegcn_tpu.obs.schema import validate_record
from pipegcn_tpu.resilience import FaultPlan, corrupt_latest_checkpoint
from pipegcn_tpu.serve.batcher import MicroBatcher
from pipegcn_tpu.serve.fleet import (
    ReplicaError,
    ReplicaServer,
    TcpReplicaClient,
    _heartbeat_path,
    _read_ready,
    run_fleet_loop,
)
from pipegcn_tpu.serve.router import FleetUnavailable, Router

pytestmark = pytest.mark.fleet


# ---------------- fakes ------------------------------------------------


class FakeTime:
    """Injectable clock whose sleep() advances it (no real waiting)."""

    def __init__(self, t=0.0):
        self.t = float(t)

    def clock(self):
        return self.t

    def sleep(self, s):
        self.t += max(float(s), 0.0)


class FakeClient:
    """Replica client double: answers [ids, 2*ids] until killed."""

    def __init__(self, rid):
        self.rid = rid
        self.alive = True

    def query(self, ids):
        if not self.alive:
            raise ConnectionError(f"replica {self.rid} is dead")
        ids = np.asarray(ids)
        return np.stack([ids, ids * 2], axis=1).astype(np.float32)


class FakeManager:
    """The run_fleet_loop-facing surface of FleetManager, minus the
    subprocesses: kill_replica flips the fake client dead and the
    supervision poll is a no-op (no rejoin)."""

    def __init__(self, clients):
        self.n_replicas = len(clients)
        self.replicas = {rid: None for rid in clients}
        self.window = -1
        self._clients = clients

    def log(self, msg):
        pass

    def poll(self, router=None):
        pass

    def kill_replica(self, rid):
        self._clients[rid].alive = False


# ---------------- router: placement ------------------------------------


def test_router_least_queue_placement_and_counters():
    c = {0: FakeClient(0), 1: FakeClient(1)}
    r = Router(c, sleep=lambda s: None)
    out, rid = r.dispatch(np.array([5, 6]))
    assert rid == 0  # empty queues tie; ties break by replica id
    np.testing.assert_array_equal(out[:, 0], [5, 6])
    # the shallower queue wins
    with r._lock:
        r._inflight[0] = 10
    _, rid = r.dispatch(np.array([1]))
    assert rid == 1
    with r._lock:
        r._inflight[0] = 0
    assert r.n_dispatched == {0: 2, 1: 1}
    assert r.queue_depths() == {0: 0, 1: 0}
    assert r.n_failovers == 0 and r.n_retried_rows == 0


def test_router_hash_ring_stability_spread_and_remap():
    c = {0: FakeClient(0), 1: FakeClient(1), 2: FakeClient(2)}
    r = Router(c, policy="hash", sleep=lambda s: None)
    keys = list(range(200))
    owner = {k: r._hash_pick(k, set()) for k in keys}
    counts = {rid: sum(1 for v in owner.values() if v == rid)
              for rid in c}
    # 64 vnodes/replica keep the arcs reasonably even
    assert all(n > 20 for n in counts.values()), counts
    # a death remaps ONLY the dead replica's keys
    r.mark_down(1)
    owner2 = {k: r._hash_pick(k, set()) for k in keys}
    for k in keys:
        if owner[k] == 1:
            assert owner2[k] in (0, 2)
        else:
            assert owner2[k] == owner[k]
    # rejoin restores the original map exactly (stability)
    r.mark_up(1)
    assert {k: r._hash_pick(k, set()) for k in keys} == owner
    # dispatch routes by the batch's first node id
    _, rid = r.dispatch(np.array([17, 3]))
    assert rid == owner[17]


# ---------------- router: failover -------------------------------------


def test_router_failover_marks_down_retries_and_rejoins():
    ft = FakeTime()
    faults, fos = [], []
    c = {0: FakeClient(0), 1: FakeClient(1)}
    r = Router(c, retry_timeout_s=5.0, backoff_s=0.01,
               on_fault=lambda rid, reason: faults.append((rid, reason)),
               on_failover=lambda rid, n, att: fos.append((rid, n, att)),
               clock=ft.clock, sleep=ft.sleep)
    c[0].alive = False
    out, rid = r.dispatch(np.array([7]))  # picks 0, fails over to 1
    assert rid == 1
    np.testing.assert_array_equal(out[:, 1], [14])
    assert r.up_replicas() == [1]
    assert len(faults) == 1 and faults[0][0] == 0
    assert "dead" in faults[0][1]
    assert fos == [(1, 1, 2)]  # succeeded on attempt 2 with 1 row
    assert r.n_failovers == 1 and r.n_retried_rows == 1
    # mark_down is edge-triggered: no second fault for the same death
    assert r.mark_down(0, "again") is False
    assert len(faults) == 1
    # rejoin puts it back into rotation (up edge only once)
    c[0].alive = True
    assert r.mark_up(0) is True
    assert r.mark_up(0) is False
    assert r.up_replicas() == [0, 1]
    _, rid = r.dispatch(np.array([9]))
    assert rid == 0  # least-queue sees it again


def test_router_fleet_unavailable_when_all_down():
    ft = FakeTime()
    c = {0: FakeClient(0)}
    c[0].alive = False
    r = Router(c, retry_timeout_s=0.5, backoff_s=0.01,
               clock=ft.clock, sleep=ft.sleep)
    with pytest.raises(FleetUnavailable, match="no up replicas"):
        r.dispatch(np.array([1]))
    assert r.up_replicas() == []
    with pytest.raises(ValueError, match="unknown policy"):
        Router(c, policy="round-robin")
    with pytest.raises(ValueError, match="at least one"):
        Router({})


# ---------------- batcher: threaded dispatch split ---------------------


def test_batcher_take_complete_shed_conservation():
    now = [0.0]
    mb = MicroBatcher(run=None, max_batch=8, max_delay_ms=5.0,
                      ladder_min=2, clock=lambda: now[0])
    t1 = mb.submit(np.array([1, 2]))
    t2 = mb.submit(np.array([3]))
    assert mb.take_batch(now[0]) is None  # not due, not forced
    now[0] += 0.006
    take, ids = mb.take_batch(now[0])
    assert take == [t1, t2]
    np.testing.assert_array_equal(ids, [1, 2, 3])
    assert mb.queue_depth == 0 and not t1.done  # taken, not answered
    mb.complete_batch(take, np.stack([ids, ids], 1).astype(np.float32),
                      t_done=now[0])
    assert t1.done and t2.done and not t2.shed
    np.testing.assert_array_equal(t2.result[:, 0], [3])
    assert mb.n_served_rows == 3
    # a taken batch the fleet cannot answer is shed EXPLICITLY
    t3 = mb.submit(np.array([4, 5]))
    take, _ = mb.take_batch(now[0], force=True)
    mb.shed_batch(take, "fleet-down")
    assert t3.done and t3.shed and t3.shed_reason == "fleet-down"
    assert t3.result is None
    assert mb.n_shed_rows == 2 and mb.n_shed_tickets == 1
    # zero tickets silently lost, checkable from outside
    assert mb.n_submitted_rows == (mb.n_served_rows + mb.n_shed_rows
                                   + mb.queue_depth)


# ---------------- fault-plan grammar -----------------------------------


def test_fault_plan_replica_kill_grammar():
    fp = FaultPlan.parse("replica-kill@2:m1,replica-kill@4,kill@5:r1")
    assert "replica-kill@2:m1" in fp.remaining()
    assert "replica-kill@4" in fp.remaining()
    assert "kill@5:r1" in fp.remaining()
    # not due before its window
    assert fp.due_member("replica-kill", 1) is None
    # due at-or-after; consumed single-shot
    assert fp.due_member("replica-kill", 2) == 1
    assert fp.due_member("replica-kill", 3) is None
    # unqualified entry defaults to member 0
    assert fp.due_member("replica-kill", 4) == 0
    assert fp.due_member("replica-kill", 99) is None
    # the kill@E:rN entry is a different axis entirely
    assert "kill@5:r1" in fp.remaining()


def test_fault_plan_replica_kill_boundary_retired():
    fp = FaultPlan.parse("replica-kill@2:m1")
    fp.skip_before(2)  # a resume at window 2 already lived through it
    assert fp.due_member("replica-kill", 99) is None
    assert fp.remaining() == []


def test_fault_plan_replica_kill_rejects_malformed():
    with pytest.raises(ValueError, match="bad fault-plan entry"):
        FaultPlan.parse("replica-kill@x")
    with pytest.raises(ValueError, match="bad fault-plan entry"):
        FaultPlan.parse("replica-kill@2:m1:m2")
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultPlan.parse("replica-nuke@2")


# ---------------- replica server over real TCP -------------------------


class FakeEngine:
    """ServingEngine double for transport tests: logits [ids, 2*ids]."""

    fully_fresh = True
    staleness_age = 0

    def __init__(self):
        self.param_generation = 3
        self.param_staleness = 1

    def query(self, ids, stats=None):
        ids = np.asarray(ids)
        if stats is not None:
            stats.note_serve(int(ids.size), True, 0)
        return np.stack([ids, ids * 2], axis=1).astype(np.float32)


def test_replica_server_tcp_roundtrip(tmp_path):
    mpath = tmp_path / "replica.jsonl"
    ml = MetricsLogger(str(mpath))
    srv = ReplicaServer(FakeEngine(), str(tmp_path), 0, incarnation=5,
                        ml=ml, heartbeat_interval_s=0.05,
                        swap_poll_s=30.0, report_every_s=30.0,
                        log=lambda m: None)
    th = threading.Thread(target=srv.serve_forever, daemon=True)
    th.start()
    deadline = time.monotonic() + 30
    info = None
    while info is None and time.monotonic() < deadline:
        info = _read_ready(str(tmp_path), 0)
        time.sleep(0.01)
    assert info is not None, "replica never published readiness"
    assert info["incarnation"] == 5 and info["pid"] == os.getpid()
    cl = TcpReplicaClient("127.0.0.1", info["port"], 0)
    try:
        out, meta = cl.query(np.array([1, 2, 3]))
        assert out.dtype == np.float32 and out.shape == (3, 2)
        np.testing.assert_array_equal(out[:, 1], [2, 4, 6])
        assert meta["incarnation"] == 5
        assert meta["param_generation"] == 3
        assert meta["param_staleness"] == 1
        assert meta["hit"] is True
        h = cl.health()
        assert h["ok"] and h["replica"] == 0 and h["n_queries"] == 3
        # protocol errors surface as ReplicaError, connection survives
        with pytest.raises(ReplicaError, match="unknown op"):
            cl._rpc({"op": "bogus"})
        assert cl.health()["ok"]
        # the incarnation-keyed heartbeat is beating
        hb = _heartbeat_path(str(tmp_path), 0, 5)
        deadline = time.monotonic() + 10
        while not os.path.exists(hb) and time.monotonic() < deadline:
            time.sleep(0.01)
        assert os.path.exists(hb)
        cl.stop()
        th.join(timeout=10)
        assert not th.is_alive()
    finally:
        srv.request_stop()
        cl.close()
        ml.close()
    recs = read_metrics(mpath)
    serving = [r for r in recs if r.get("event") == "serving"]
    assert serving and serving[-1].get("final") is True
    assert serving[-1]["replica"] == 0
    assert serving[-1]["incarnation"] == 5
    for r in serving:
        validate_record(r)


# ---------------- checkpoint hot-swap watcher --------------------------


def test_poll_checkpoint_emits_hot_swap_records(tmp_path):
    reports = [
        {"swapped": True, "param_generation": 2, "param_staleness": 0,
         "swap_ms": 12.5},
        {"swapped": False, "reason": "no-newer-generation",
         "param_generation": 2, "param_staleness": 0},
        {"swapped": False, "reason": "newer-generation-corrupt",
         "param_generation": 2, "param_staleness": 1},
    ]

    class Eng:
        fully_fresh = True
        staleness_age = 0
        param_generation = -1
        param_staleness = 0

        def load_from_checkpoint(self, directory, ml=None):
            return reports.pop(0)

    mpath = tmp_path / "m.jsonl"
    with MetricsLogger(str(mpath)) as ml:
        srv = ReplicaServer(Eng(), str(tmp_path), 1, incarnation=2,
                            ml=ml, checkpoint_dir=str(tmp_path / "ckpt"),
                            log=lambda m: None)
        rep = srv.poll_checkpoint()
        assert rep is not None and rep["swapped"]
        assert srv.stats.param_generation == 2
        assert srv.poll_checkpoint() is None  # no-newer: silent
        assert srv.poll_checkpoint() is None  # corrupt: record, no swap
        # without a checkpoint dir the watcher is inert
        srv2 = ReplicaServer(Eng(), str(tmp_path), 3, ml=ml,
                             checkpoint_dir=None, log=lambda m: None)
        assert srv2.poll_checkpoint() is None
    fleet = [r for r in read_metrics(mpath) if r.get("event") == "fleet"]
    assert [r["kind"] for r in fleet] == ["hot-swap", "swap-rejected"]
    assert fleet[0]["replica"] == 1 and fleet[0]["incarnation"] == 2
    assert fleet[0]["param_generation"] == 2
    assert fleet[0]["swap_ms"] == pytest.approx(12.5)
    assert fleet[1]["reason"] == "newer-generation-corrupt"
    for r in fleet:
        validate_record(r)


@pytest.fixture(scope="module")
def swap_engine():
    """One small real mesh engine for the load_from_checkpoint tests
    (the only jax-compiling fixture in this module — keep it tiny)."""
    from pipegcn_tpu.graph import synthetic_graph
    from pipegcn_tpu.models import ModelConfig
    from pipegcn_tpu.parallel import Trainer, TrainConfig
    from pipegcn_tpu.partition import ShardedGraph, partition_graph
    from pipegcn_tpu.serve import ServingEngine

    g = synthetic_graph(num_nodes=240, avg_degree=6, n_feat=12,
                        n_class=4, seed=11)
    parts = partition_graph(g, 4, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=4)
    cfg = ModelConfig(layer_sizes=(sg.n_feat, 16, sg.n_class),
                      model="graphsage", norm="layer", dropout=0.0,
                      train_size=sg.n_train_global)
    t = Trainer(sg, cfg, TrainConfig(seed=3, n_epochs=0,
                                     enable_pipeline=False, eval=False))
    eng = ServingEngine.for_trainer(t, max_batch=16, ladder_min=8)
    return t, eng


def test_engine_hot_swap_walk_back_and_fault_dedupe(tmp_path,
                                                    swap_engine):
    from pipegcn_tpu.utils.checkpoint import save_checkpoint

    t, eng = swap_engine
    ckdir = str(tmp_path / "ckpt")
    mpath = tmp_path / "m.jsonl"
    ml = MetricsLogger(str(mpath))
    state = {"params": t.state["params"], "norm": t.state["norm"]}

    # empty directory: explicit no-checkpoint, nothing emitted
    rep = eng.load_from_checkpoint(ckdir, ml=ml)
    assert rep == {"swapped": False, "reason": "no-checkpoint",
                   "param_generation": -1, "param_staleness": 0}

    for e in (1, 2, 3):
        save_checkpoint(ckdir, state, epoch=e)
    corrupt_latest_checkpoint(ckdir)  # generation 3 is now garbage

    # walk-back: the newest generation fails verification, the newest
    # GOOD one (2) swaps in, and the walked-back fault is emitted
    with pytest.warns(UserWarning):
        rep = eng.load_from_checkpoint(ckdir, ml=ml)
    assert rep["swapped"] and rep["param_generation"] == 2
    assert rep["param_staleness"] == 1  # gen 3 published, not served
    assert rep["swap_ms"] >= 0.0
    assert eng.param_generation == 2

    # re-poll: nothing newer is READABLE; no re-swap, and the fault is
    # deduped per bad generation (not re-emitted every poll)
    with pytest.warns(UserWarning):
        rep = eng.load_from_checkpoint(ckdir, ml=ml)
    assert not rep["swapped"]
    assert rep["reason"] == "newer-generation-corrupt"
    assert rep["param_staleness"] == 1
    assert eng.param_generation == 2

    # a fresh good generation swaps in and clears the staleness
    save_checkpoint(ckdir, state, epoch=4)
    rep = eng.load_from_checkpoint(ckdir, ml=ml)
    assert rep["swapped"] and rep["param_generation"] == 4
    assert rep["param_staleness"] == 0
    ml.close()

    faults = [r for r in read_metrics(mpath) if r.get("event") == "fault"]
    assert [f["kind"] for f in faults] == ["serve-ckpt-corrupt"]
    assert faults[0]["epoch"] == 3
    validate_record(faults[0])


# ---------------- the fleet load loop (in-process, fakes) --------------


def test_run_fleet_loop_replica_kill_failover_conservation(tmp_path):
    ft = FakeTime()
    clients = {0: FakeClient(0), 1: FakeClient(1)}
    # hash placement spreads deterministically over both replicas; the
    # router keeps the real clock (only its failure backoff sleeps)
    router = Router(clients, policy="hash", retry_timeout_s=5.0,
                    backoff_s=0.001)
    mgr = FakeManager(clients)
    fp = FaultPlan.parse("replica-kill@2:m1")
    mpath = tmp_path / "loop.jsonl"
    with MetricsLogger(str(mpath)) as ml:
        summary = run_fleet_loop(
            mgr, router, num_nodes=100, duration_s=2.0, qps=300.0,
            max_batch=16, ladder_min=4, report_every_s=0.5,
            seed=1, ml=ml, fault_plan=fp,
            clock=ft.clock, sleep=ft.sleep)
    # the scripted kill fired at window 2 against replica 1
    assert summary["kills"] == [{"window": 2, "replica": 1}]
    # zero accepted tickets lost: served or explicitly shed, queue empty
    assert summary["conserved"] is True
    assert summary["drained"] is True
    assert summary["n_submitted"] == (summary["n_served"]
                                      + summary["n_shed"])
    assert summary["n_served"] > 0
    # batches that hashed to the dead replica failed over to survivor 0
    assert summary["n_failovers"] >= 1
    assert summary["n_retried_rows"] >= 1
    assert summary["replicas_up"] == 1
    assert summary["per_replica_dispatched"]["0"] > 0
    assert summary["per_replica_dispatched"]["1"] > 0
    assert set(summary["per_replica_queue_depth_max"]) == {"0", "1"}
    assert not summary["stopped_early"]
    # the aggregated serving records are schema-valid and accounted
    recs = [r for r in read_metrics(mpath)
            if r.get("event") == "serving"]
    assert len(recs) == summary["n_records"]
    assert recs[-1].get("final") is True
    for r in recs:
        validate_record(r)
        assert r["replicas_up"] in (1, 2)
    assert sum(r["shed"] for r in recs) == summary["n_shed"]


# ---------------- two-process replica-kill drill (chaos lane) ----------


@pytest.mark.slow
@pytest.mark.faults
def test_fleet_cli_replica_kill_drill(tmp_path):
    """SIGKILL one of two live replica meshes mid-load: the router must
    route to the survivor, the supervisor must relaunch + rejoin the
    dead slot (fleet fault + recovery records), and on SIGTERM the
    driver must drain with zero accepted tickets lost and exit 0."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    mpath = tmp_path / "metrics.jsonl"
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PYTHONPATH": repo,
        "PIPEGCN_PLATFORM": "cpu",
    }
    proc = subprocess.Popen(
        [sys.executable, "-m", "pipegcn_tpu.cli.fleet",
         "--dataset", "synthetic:600:8:16:4", "--n-partitions", "4",
         "--n-hidden", "16", "--n-layers", "2", "--fix-seed",
         "--partition-dir", str(tmp_path / "parts"), "--serve-build",
         "--metrics-out", str(mpath),
         "--replicas", "2",
         # hash placement: with near-zero CPU query latency the
         # least-queue tiebreak would starve replica 1; the ring
         # guarantees both replicas own arcs of the keyspace
         "--fleet-policy", "hash",
         "--serve-duration", "600", "--serve-qps", "60",
         "--serve-report-every", "0.5",
         "--fault-plan", "replica-kill@3:m1",
         "--fleet-retry-timeout", "15",
         "--fleet-ready-timeout", "240"],
        env=env, cwd=repo, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)

    def fleet_kinds():
        kinds = []
        if not mpath.exists():
            return kinds
        with open(mpath) as fh:
            for line in fh:
                try:
                    r = json.loads(line)
                except json.JSONDecodeError:
                    continue  # mid-write line
                if r.get("event") == "fleet":
                    kinds.append(r.get("kind"))
        return kinds

    try:
        deadline = time.monotonic() + 420
        while "replica-rejoin" not in fleet_kinds():
            assert proc.poll() is None, (
                "fleet driver exited before the rejoin:\n"
                + proc.communicate()[0][-3000:])
            assert time.monotonic() < deadline, (
                f"no replica-rejoin within the deadline "
                f"(fleet kinds so far: {fleet_kinds()})")
            time.sleep(0.5)
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=180)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, out[-3000:]

    tail = [ln for ln in out.splitlines() if '"fleet": true' in ln]
    assert tail, out[-3000:]
    summ = json.loads(tail[-1])
    # zero accepted tickets lost across a replica SIGKILL
    assert summ["conserved"] is True
    assert summ["drained"] is True
    assert summ["n_submitted"] == summ["n_served"] + summ["n_shed"]
    assert summ["n_served"] > 0
    assert summ["replicas"] == 2
    assert summ["kills"] and summ["kills"][0]["replica"] == 1
    # both replicas actually served load
    assert summ["per_replica_dispatched"]["0"] > 0
    assert summ["per_replica_dispatched"]["1"] > 0
    # the survivor absorbed retried rows, and the slot rejoined
    assert summ["replicas_up"] == 2

    recs = read_metrics(mpath)  # post-exit: every line complete
    kinds = [r["kind"] for r in recs if r.get("event") == "fleet"]
    for expect in ("replica-dead", "relaunch", "replica-rejoin",
                   "fleet-stop"):
        assert expect in kinds, kinds
    faults = [r for r in recs if r.get("event") == "fault"
              and r.get("kind") == "fleet"]
    assert faults and faults[0]["rank"] == 1
    recov = [r for r in recs if r.get("event") == "recovery"
             and r.get("kind") == "fleet"]
    assert recov and recov[0]["rank"] == 1
    for r in recs:
        if r.get("event") in ("fleet", "serving"):
            validate_record(r)
