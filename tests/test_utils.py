import numpy as np
import pytest

from pipegcn_tpu.graph import synthetic_graph
from pipegcn_tpu.models import ModelConfig
from pipegcn_tpu.parallel import Trainer, TrainConfig
from pipegcn_tpu.partition import ShardedGraph, partition_graph
from pipegcn_tpu.utils import load_pytree, save_pytree
from pipegcn_tpu.utils.timer import CommTimer


def test_adam_matches_torch_semantics():
    """The in-repo Adam must track torch.optim.Adam (the reference's
    optimizer, train.py:321-323) step for step, including L2 weight
    decay folded into the gradient."""
    import jax.numpy as jnp
    import torch

    from pipegcn_tpu.train.optim import adam_init, adam_update

    rng = np.random.default_rng(0)
    p0 = rng.standard_normal((7, 5)).astype(np.float32)
    grads = [rng.standard_normal((7, 5)).astype(np.float32)
             for _ in range(6)]
    lr, wd = 1e-2, 5e-4

    tp = torch.nn.Parameter(torch.tensor(p0))
    opt = torch.optim.Adam([tp], lr=lr, weight_decay=wd)
    for g in grads:
        opt.zero_grad()
        tp.grad = torch.tensor(g)
        opt.step()

    params = {"w": jnp.asarray(p0)}
    state = adam_init(params)
    for g in grads:
        params, state = adam_update({"w": jnp.asarray(g)}, state, params,
                                    lr=lr, weight_decay=wd)

    np.testing.assert_allclose(np.asarray(params["w"]),
                               tp.detach().numpy(), rtol=1e-5, atol=1e-6)


def test_comm_timer_spans_and_parity_semantics():
    t = CommTimer()
    with t.timer("forward_0"):
        pass
    with t.timer("backward_0"):
        pass
    assert t.tot_time() >= 0
    assert set(t.durations()) == {"forward_0", "backward_0"}
    # duplicate key raises (reference comm_timer.py:14-15)
    with pytest.raises(RuntimeError):
        with t.timer("forward_0"):
            pass
    t.clear()
    assert t.tot_time() == 0.0


def test_pytree_roundtrip(tmp_path):
    tree = {"a": np.arange(6).reshape(2, 3), "b": [np.ones(4), {"c": np.zeros(2)}]}
    p = str(tmp_path / "t.npz")
    save_pytree(p, tree)
    back = load_pytree(p, tree)
    np.testing.assert_array_equal(back["a"], tree["a"])
    np.testing.assert_array_equal(back["b"][1]["c"], tree["b"][1]["c"])
    # shape mismatch is rejected
    bad = {"a": np.zeros((3, 3)), "b": tree["b"]}
    with pytest.raises(ValueError):
        load_pytree(p, bad)
    # missing leaf is rejected
    with pytest.raises(KeyError):
        load_pytree(p, {"a": tree["a"], "zz": np.zeros(1)})


def test_measure_comm():
    g = synthetic_graph(num_nodes=300, avg_degree=6, n_feat=8, n_class=3,
                        seed=1)
    parts = partition_graph(g, 4, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=4)
    cfg = ModelConfig(layer_sizes=(8, 16, 3), dropout=0.0,
                      train_size=sg.n_train_global)
    t = Trainer(sg, cfg, TrainConfig(n_epochs=1))
    cost = t.measure_comm(repeats=2)
    assert cost["comm"] > 0 and cost["reduce"] > 0
    assert cost["comm"] < 5 and cost["reduce"] < 5
    # the cotangent return ring is measured for BOTH modes (vanilla
    # ships it through halo_exchange's VJP, pipelined through the
    # carry's return_blocks)
    assert 0 < cost["bgrad"] < 5


def test_checkpoint_peek_epoch(tmp_path):
    """peek_epoch reads the checkpoint epoch without a state template
    (templateless completed-leg detection, scripts/convergence_study.py)."""
    from pipegcn_tpu.utils.checkpoint import (
        peek_epoch, save_checkpoint)

    d = str(tmp_path / "ck")
    assert peek_epoch(d) is None
    state = {"params": {"w": np.ones((2, 2), np.float32)}}
    save_checkpoint(d, state, 41)
    assert peek_epoch(d) == 41


def test_checkpoint_bf16_roundtrip(tmp_path):
    """bf16 leaves survive npz save/load (stored as tagged uint16 views;
    np.savez would otherwise return raw void '|V2')."""
    import jax.numpy as jnp
    from pipegcn_tpu.utils.checkpoint import load_pytree, save_pytree

    tree = {
        "a": jnp.arange(6, dtype=jnp.bfloat16).reshape(2, 3) / 3,
        "b": {"c": np.ones((4,), np.float32)},
    }
    path = str(tmp_path / "ck.npz")
    save_pytree(path, tree)
    out = load_pytree(path, tree)
    assert out["a"].dtype == jnp.bfloat16.dtype
    np.testing.assert_array_equal(np.asarray(out["a"], np.float32),
                                  np.asarray(tree["a"], np.float32))
    np.testing.assert_array_equal(out["b"]["c"], tree["b"]["c"])
