"""Streaming-graph subsystem tests (docs/STREAMING.md).

The load-bearing contract is BIT-IDENTITY: after any sequence of delta
batches, the patched ShardedGraph (CSR slabs, send-lists, halo slots,
padded tables) must equal a from-scratch ``ShardedGraph.build`` of the
post-delta graph at the same padded dims — patching is an optimization,
never an approximation. On top of that: slack exhaustion must re-pad
LOUDLY (never silently corrupt), steady-state deltas must not recompile
anything, the pipelined comm carry must flush exactly the changed rows,
the serving topology-refresh path must reproduce a full boundary
exchange bitwise, and tampered delta files must be rejected at load.
"""

import os

import jax
import numpy as np
import pytest

from pipegcn_tpu.graph.synthetic import (synthetic_delta_schedule,
                                         synthetic_graph)
from pipegcn_tpu.models import ModelConfig
from pipegcn_tpu.parallel import Trainer, TrainConfig
from pipegcn_tpu.partition.halo import ShardedGraph
from pipegcn_tpu.partition.partitioner import partition_graph
from pipegcn_tpu.stream import (DeltaBatch, GraphPatcher, SlackExhausted,
                                StreamPlan, load_deltas, save_deltas)
from pipegcn_tpu.stream.patch import flush_masks

pytestmark = pytest.mark.stream

P = 4


def _stack(seed=6, n=240, slack=0.25, spmm="xla", model="graphsage",
           pipeline=False, n_epochs=6):
    g = synthetic_graph(num_nodes=n, avg_degree=6, n_feat=10, n_class=4,
                        seed=seed)
    parts = partition_graph(g, P)
    sg = ShardedGraph.build(g, parts, n_parts=P, slack=slack)
    cfg = ModelConfig(layer_sizes=(10, 12, 4), norm="layer",
                      dropout=0.0, model=model,
                      train_size=sg.n_train_global, spmm_impl=spmm)
    tcfg = TrainConfig(seed=3, enable_pipeline=pipeline,
                      n_epochs=n_epochs, log_every=10_000,
                      fused_epochs=1)
    t = Trainer(sg, cfg, tcfg)
    patcher = GraphPatcher(g, sg, parts, slack=slack)
    t.enable_stream(patcher)
    return g, parts, sg, cfg, tcfg, t, patcher


def _fresh_rebuild(patcher, sg, cfg, tcfg):
    """From-scratch oracle at the SAME padded dims as the patched
    state (bit-identity needs identical shapes)."""
    sg2 = ShardedGraph.build(
        patcher.g, patcher.parts, n_parts=P,
        min_n_max=patcher.sg.n_max, min_b_max=patcher.sg.b_max,
        min_e_max=patcher.sg.e_max)
    return Trainer(sg2, cfg, tcfg), sg2


def _assert_data_bit_identical(t, t2):
    d1 = jax.device_get(t.data)
    d2 = jax.device_get(t2.data)
    assert set(d1) == set(d2)
    for k in sorted(d1):
        a, b = np.asarray(d1[k]), np.asarray(d2[k])
        assert a.shape == b.shape, (k, a.shape, b.shape)
        assert a.dtype == b.dtype, (k, a.dtype, b.dtype)
        assert np.array_equal(a, b), (
            k, np.argwhere(a != b)[:5] if a.shape else (a, b))


# ---------------- bit-identity oracle --------------------------------


@pytest.mark.parametrize("spmm", ["xla", "bucket"])
def test_patched_tables_bit_identical_to_rebuild(spmm):
    """Every device table (CSR slabs, send-lists, halo routing, feats,
    masks, kernel tables) after two delta batches == a from-scratch
    build of the post-delta graph — on the raw-gather AND the
    dirty-shard incremental bucket-table path."""
    g, parts, sg, cfg, tcfg, t, patcher = _stack(spmm=spmm)
    n0 = g.num_nodes
    for b in synthetic_delta_schedule(g, n_batches=2, edges_per_batch=5,
                                      dels_per_batch=3,
                                      nodes_per_batch=2, seed=21):
        rep = t.apply_graph_deltas(b)
        assert not rep.repadded
        assert rep.touched_parts
    # new nodes landed: host graph grew in place, sg identity kept
    assert patcher.g.num_nodes == n0 + 4
    assert patcher.sg is t.sg
    t2, _ = _fresh_rebuild(patcher, sg, cfg, tcfg)
    _assert_data_bit_identical(t, t2)
    # eval parity on the patched graph: identical params through both
    # stacks must score identically (the forward pass IS the tables)
    t2.state = dict(t2.state)
    t2.state["params"] = t.state["params"]
    t2.state["norm"] = t.state["norm"]
    a1 = t.evaluate(patcher.g, "val_mask", sharded=True)
    a2 = t2.evaluate(patcher.g, "val_mask", sharded=True)
    assert a1 == a2
    # ...and training continues finite on the patched tables
    assert np.isfinite(t.train_epoch(0))


# ---------------- slack exhaustion -----------------------------------


def test_slack_exhaustion_is_loud_then_repads():
    """A batch past the reserved headroom raises SlackExhausted when
    re-padding is off, and re-pads LOUDLY (repadded=True, grown dims,
    still bit-identical) when it is allowed."""
    g, parts, sg, cfg, tcfg, t, patcher = _stack(slack=0.0)
    # a star of brand-new nodes wired to node 0 overflows any 0-slack
    # padding in one shot
    m = 12
    rng = np.random.default_rng(0)
    feats = rng.normal(size=(m, 10)).astype(np.float32)
    labels = np.zeros(m, dtype=np.int64)
    nbrs = tuple(np.array([0], dtype=np.int64) for _ in range(m))
    big = DeltaBatch(seq=0, add_edges=np.zeros((0, 2), np.int64),
                     del_edges=np.zeros((0, 2), np.int64),
                     node_feat=feats, node_label=labels, node_nbrs=nbrs)
    with pytest.raises(SlackExhausted):
        patcher.apply(big, allow_repad=False)
    rep = t.apply_graph_deltas(big)  # allow_repad=True path
    assert rep.repadded
    assert t.sg.n_max > sg.n_max or t.sg.e_max > sg.e_max \
        or t.sg.b_max > sg.b_max
    t2, _ = _fresh_rebuild(patcher, sg, cfg, tcfg)
    _assert_data_bit_identical(t, t2)
    assert np.isfinite(t.train_epoch(0))


# ---------------- zero-recompile pin ---------------------------------


def test_steady_state_delta_does_not_recompile():
    """A within-slack delta must leave the compiled step untouched:
    same jitted step object, every device-table shape/dtype unchanged
    (shape-stability + same callable == cache hit, no retrace)."""
    g, parts, sg, cfg, tcfg, t, patcher = _stack(slack=0.30)
    assert np.isfinite(t.train_epoch(0))
    step_before = t._step
    shapes_before = {k: (v.shape, str(v.dtype))
                     for k, v in t.data.items()}
    b = synthetic_delta_schedule(g, n_batches=1, edges_per_batch=6,
                                 dels_per_batch=2, nodes_per_batch=1,
                                 seed=3)[0]
    rep = t.apply_graph_deltas(b)
    assert not rep.repadded
    assert t._step is step_before
    shapes_after = {k: (v.shape, str(v.dtype))
                    for k, v in t.data.items()}
    assert shapes_after == shapes_before
    assert np.isfinite(t.train_epoch(1))


# ---------------- pipelined carry flush ------------------------------


def test_carry_flush_zeroes_exactly_the_changed_rows():
    """After a delta under the pipelined trainer, comm-carry rows whose
    send-list entries changed are zeroed (receiver side for halo/favg,
    sender side for bgrad/bavg) and every untouched row is bitwise
    preserved — a stale carry for a re-routed slot would inject another
    node's features."""
    g, parts, sg, cfg, tcfg, t, patcher = _stack(pipeline=True)
    for e in range(3):  # populate the staleness-1 carry
        assert np.isfinite(t.train_epoch(e))
    before = jax.device_get(t.state["comm"])
    b = synthetic_delta_schedule(g, n_batches=1, edges_per_batch=6,
                                 dels_per_batch=3, nodes_per_batch=1,
                                 seed=11)[0]
    rep = t.apply_graph_deltas(b)
    assert rep.changed_send is not None and rep.changed_send.any()
    recv, send = flush_masks(rep.changed_send, P, t.sg.b_max)
    masks = {"halo": recv, "favg": recv, "bgrad": send, "bavg": send}
    after = jax.device_get(t.state["comm"])
    flushed = 0
    for grp, bufs in after.items():
        if grp not in masks:
            continue
        m = masks[grp]
        for k, v in bufs.items():
            v = np.asarray(v)
            old = np.asarray(before[grp][k])
            assert np.all(v[m] == 0), (grp, k)
            assert np.array_equal(v[~m], old[~m]), (grp, k)
            flushed += int(m.sum())
    assert flushed > 0
    assert np.isfinite(t.train_epoch(3))


# ---------------- fit() integration ----------------------------------


def test_fit_applies_stream_plan_and_fault_grammar(tmp_path):
    """End to end through fit(): scheduled deltas land at their epochs,
    the graph-delta fault kind injects an unscheduled batch, every
    application emits a contracted v8 `stream` record with forced-probe
    drift, and the plan is fully consumed."""
    from pipegcn_tpu.obs.metrics import MetricsLogger, read_metrics
    from pipegcn_tpu.resilience.faults import FaultPlan

    g, parts, sg, cfg, tcfg, t, patcher = _stack(pipeline=True,
                                                 n_epochs=10)
    batches = synthetic_delta_schedule(g, n_batches=2,
                                       edges_per_batch=4,
                                       dels_per_batch=2,
                                       nodes_per_batch=1, seed=9)
    dpath = str(tmp_path / "deltas.jsonl")
    save_deltas(dpath, batches)
    plan = StreamPlan.parse(f"{dpath}@4:3")  # epochs 4, 7
    fp = FaultPlan.parse("graph-delta@9")
    mpath = str(tmp_path / "metrics.jsonl")
    with MetricsLogger(mpath) as m:
        t.fit(None, log_fn=lambda *_: None, metrics=m,
              stream_plan=plan, fault_plan=fp)
    recs = read_metrics(mpath)
    stream = [r for r in recs if r["event"] == "stream"]
    assert [r["epoch"] for r in stream] == [4, 7, 9]
    assert [r["seq"] for r in stream] == [0, 1, 2]
    assert all(r["drift"] is not None for r in stream)
    assert all(not r["repadded"] for r in stream)
    faults = [r for r in recs if r["event"] == "fault"]
    assert any(r.get("reason") == "graph-delta" for r in faults)
    assert plan.remaining() == 0


# ---------------- serving topology refresh ---------------------------


@pytest.mark.serving
@pytest.mark.parametrize("model", ["graphsage", "gcn"])
def test_serving_topology_delta_freshness_oracle(model):
    """The serving oracle: after a topology delta, the incremental path
    (changed-slot flush + dirty-row exchange) must reproduce a full
    boundary exchange BITWISE, with zero retraces, and query logits
    over every node (including new ones) must equal a from-scratch
    trainer+engine stack — for the SAGE and the GCN (in-deg pre-scale)
    send views."""
    from pipegcn_tpu.serve.engine import ServingEngine, trace_counts

    g, parts, sg, cfg, tcfg, t, patcher = _stack(model=model, n=260)
    eng = ServingEngine.for_trainer(t)
    eng.warmup()
    # a plain feature update first: both update paths coexist
    eng.apply_updates([3, 17], np.ones((2, 10), np.float32))
    eng.refresh_boundary()

    batches = synthetic_delta_schedule(g, n_batches=2,
                                       edges_per_batch=5,
                                       dels_per_batch=3,
                                       nodes_per_batch=2, seed=21)
    tc0 = dict(trace_counts())
    gen0 = eng.topo_generation
    for b in batches:
        rep = t.apply_graph_deltas(b)
        assert not rep.repadded
        eng.apply_graph_deltas(rep)
        eng.refresh_boundary()
        inc = np.asarray(eng._halo0)
        full = np.asarray(eng.full_boundary_exchange())
        assert np.array_equal(inc, full), np.argwhere(inc != full)[:5]
        eng.refresh()
    assert eng.topo_generation == gen0 + len(batches)
    assert dict(trace_counts()) == tc0, "topology deltas retraced"

    # fresh-stack logits oracle, every node incl. the 4 new ones
    sg2 = ShardedGraph.build(patcher.g, patcher.parts, n_parts=P,
                             min_n_max=sg.n_max, min_b_max=sg.b_max,
                             min_e_max=sg.e_max)
    t2 = Trainer(sg2, cfg, tcfg)
    eng2 = ServingEngine.for_trainer(t2)
    eng2._params, eng2._norm = eng._params, eng._norm
    eng2.apply_updates([3, 17], np.ones((2, 10), np.float32))
    eng2.refresh_boundary()
    eng2.refresh()
    q = np.arange(eng.num_global_nodes, dtype=np.int64)
    assert eng.num_global_nodes == g.num_nodes  # g mutated in place
    a = eng.query(q)
    b = eng2.query(q)
    assert np.array_equal(a, b)


def test_serving_repad_invalidates_engine():
    """A re-padding delta changes compiled shapes: the engine must
    refuse to limp along (RuntimeError directing a rebuild) and the
    trainer's engine cache must be cleared."""
    from pipegcn_tpu.serve.engine import ServingEngine

    g, parts, sg, cfg, tcfg, t, patcher = _stack(slack=0.0)
    eng = ServingEngine.for_trainer(t)
    eng.warmup()
    m = 12
    rng = np.random.default_rng(0)
    big = DeltaBatch(
        seq=0, add_edges=np.zeros((0, 2), np.int64),
        del_edges=np.zeros((0, 2), np.int64),
        node_feat=rng.normal(size=(m, 10)).astype(np.float32),
        node_label=np.zeros(m, dtype=np.int64),
        node_nbrs=tuple(np.array([0], np.int64) for _ in range(m)))
    rep = t.apply_graph_deltas(big)
    assert rep.repadded
    with pytest.raises(RuntimeError, match="rebuild"):
        eng.apply_graph_deltas(rep)
    assert not getattr(t, "_serving_engines", {})
    # a rebuilt engine serves the grown graph
    eng2 = ServingEngine.for_trainer(t)
    eng2.warmup()
    out = eng2.query(np.arange(g.num_nodes, dtype=np.int64))
    assert np.all(np.isfinite(out))


# ---------------- delta format guards --------------------------------


def test_delta_file_roundtrip_and_crc_tamper_rejected(tmp_path):
    """save/load round-trips both formats bit-exactly; a tampered
    payload (JSONL field edit, npz array bit-flip) fails CRC at load —
    a half-written or corrupted delta file must never patch a graph."""
    g = synthetic_graph(num_nodes=120, avg_degree=5, n_feat=6,
                        n_class=3, seed=1)
    batches = synthetic_delta_schedule(g, n_batches=3,
                                       edges_per_batch=4,
                                       dels_per_batch=2,
                                       nodes_per_batch=1, seed=2)
    for ext in ("jsonl", "npz"):
        path = str(tmp_path / f"d.{ext}")
        save_deltas(path, batches)
        loaded = load_deltas(path)
        assert [b.seq for b in loaded] == [b.seq for b in batches]
        for a, b in zip(loaded, batches):
            assert np.array_equal(a.add_edges, b.add_edges)
            assert np.array_equal(a.del_edges, b.del_edges)
            assert np.array_equal(a.node_feat, b.node_feat)

    # JSONL tamper: flip one digit inside a batch record
    jpath = str(tmp_path / "d.jsonl")
    with open(jpath) as f:
        lines = f.read().splitlines()
    import json as _json

    rec = _json.loads(lines[1])
    rec["add_edges"][0][0] += 1
    lines[1] = _json.dumps(rec)
    tampered = str(tmp_path / "tampered.jsonl")
    with open(tampered, "w") as f:
        f.write("\n".join(lines) + "\n")
    with pytest.raises(ValueError, match="CRC"):
        load_deltas(tampered)

    # npz tamper: rewrite one payload array, keep the stored CRC
    npath = str(tmp_path / "d.npz")
    z = dict(np.load(npath, allow_pickle=False))
    key = next(k for k in z if k.endswith("add_edges") and z[k].size)
    z[key] = z[key] + 1
    tampered_n = str(tmp_path / "tampered.npz")
    np.savez(tampered_n, **z)
    with pytest.raises(ValueError, match="CRC"):
        load_deltas(tampered_n)


def test_stream_plan_grammar_errors(tmp_path):
    """Malformed --stream-plan specs fail loudly at parse time."""
    g = synthetic_graph(num_nodes=60, avg_degree=4, n_feat=4,
                        n_class=2, seed=0)
    batches = synthetic_delta_schedule(g, n_batches=1,
                                       edges_per_batch=2,
                                       dels_per_batch=1,
                                       nodes_per_batch=0, seed=0)
    path = str(tmp_path / "d.jsonl")
    save_deltas(path, batches)
    with pytest.raises((ValueError, FileNotFoundError)):
        StreamPlan.parse(str(tmp_path / "missing.jsonl") + "@3")
    with pytest.raises(ValueError):
        StreamPlan.parse(f"{path}@notanepoch")
    with pytest.raises(ValueError):
        StreamPlan.parse(path)  # no @epoch
    plan = StreamPlan.parse(f"{path}@2")
    assert plan.remaining() == 1
    assert plan.due(1) == []
    assert len(plan.due(2)) == 1
    assert plan.remaining() == 0
