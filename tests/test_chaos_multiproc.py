"""Two-coordinated-process chaos drills (docs/RESILIENCE.md).

The real thing, following test_multihost.py::test_two_process_end_to_end's
localhost pattern: two OS processes rendezvous through
jax.distributed.initialize and drive ONE SPMD job, then one of them
misbehaves:

  kill drill       kill -9 one rank mid-epoch -> the survivor's
                   heartbeat watchdog converts the otherwise-infinite
                   collective hang into an emergency checkpoint +
                   resumable exit 75, and a two-process --resume run
                   completes
  consensus drill  --fault-plan nan-loss@5:r1 trips ONLY rank 1's
                   sentinel, yet BOTH ranks roll back to the same
                   snapshot epoch (fault consensus) and their
                   post-recovery param digests agree (desync checker)
  desync drill     --fault-plan desync@7:r1 silently perturbs rank 1's
                   params; the digest check catches it and
                   --desync-resync restores rank 0's state everywhere

Marked slow (several subprocess rendezvous) + faults: tier-1 skips
them; scripts/chaos.sh runs them under a hard timeout.

NOTE the asymmetry the drills respect: rank 0 hosts the jax
coordination service, so killing rank 0 makes the peers' jax runtime
hard-abort within milliseconds (no graceful path exists below us);
killing a NON-leader rank leaves the survivors blocked in gloo — the
~100 s silent hang our watchdog exists to convert into exit 75.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import zlib

import numpy as np
import pytest

from pipegcn_tpu.obs import read_metrics
from pipegcn_tpu.resilience import EXIT_PREEMPTED
from pipegcn_tpu.utils.checkpoint import latest_checkpoint_path, peek_epoch

pytestmark = [pytest.mark.faults, pytest.mark.slow]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_rank(rank, port, tmp_path, extra, n_epochs):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PYTHONPATH": REPO,
        "PYTHONUNBUFFERED": "1",
    }
    cmd = [
        sys.executable, os.path.join(REPO, "main.py"),
        "--dataset", "synthetic:400:6:8:3",
        "--n-partitions", "2", "--parts-per-node", "1",
        "--node-rank", str(rank),
        "--master-addr", "127.0.0.1", "--port", str(port),
        "--n-epochs", str(n_epochs), "--n-hidden", "16",
        "--dropout", "0.0", "--log-every", "1000",
        "--fix-seed", "--seed", "7", "--no-eval",
        "--partition-dir", str(tmp_path / "parts"),
        "--model-dir", str(tmp_path / f"model{rank}"),
        "--results-dir", str(tmp_path / f"results{rank}"),
        "--metrics-out", str(tmp_path / f"metrics{rank}.jsonl"),
    ] + extra
    return subprocess.Popen(cmd, env=env, cwd=REPO,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


def _epochs_flowing(mfile, n=5, timeout_s=180):
    """Block until `mfile` records >= n epoch events (compile is slow;
    epochs after that are fast)."""
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        try:
            if sum(1 for r in read_metrics(mfile)
                   if r.get("event") == "epoch") >= n:
                return True
        except (OSError, ValueError):
            pass
        time.sleep(0.5)
    return False


def _assert_checkpoint_digest_valid(ck_dir):
    """Every member of the newest generation matches its CRC32
    manifest — the digest utils/checkpoint.py verifies on load."""
    path = latest_checkpoint_path(ck_dir)
    assert path is not None, f"no checkpoint generation in {ck_dir}"
    with np.load(path) as z:
        man = json.loads(str(z["__digests__"][()]))
        for key, want in man.items():
            arr = np.ascontiguousarray(z[key])
            h = zlib.crc32(f"{arr.dtype.str}|{arr.shape}|".encode())
            got = zlib.crc32(arr.tobytes(), h) & 0xFFFFFFFF
            assert got == want, f"digest mismatch for {key} in {path}"


def _communicate(proc, timeout):
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        out = (out or "") + "\n<<TIMED OUT>>"
    return out


def test_two_process_kill_drill(tmp_path):
    """Acceptance: kill -9 the non-leader rank mid-epoch; the surviving
    rank exits 75 within the watchdog horizon with a loadable,
    digest-valid crash checkpoint, and a two-process --resume completes
    — no hang (the reference implementation hangs forever here)."""
    port = _free_port()
    ck = str(tmp_path / "ck")
    wd_timeout = 6.0
    flags = ["--checkpoint-dir", ck, "--checkpoint-every", "2000",
             "--watchdog-timeout", str(wd_timeout),
             "--sentinel-snapshot-every", "10"]
    procs = [_spawn_rank(r, port, tmp_path, flags, n_epochs=200000)
             for r in (0, 1)]
    try:
        assert _epochs_flowing(tmp_path / "metrics0.jsonl"), \
            "epochs never started flowing"
        # kill the NON-leader: the survivor then blocks inside a gloo
        # collective that can never complete (the hang under test)
        procs[1].send_signal(signal.SIGKILL)
        t_kill = time.time()
        out0 = _communicate(procs[0], timeout=wd_timeout * 10 + 60)
        elapsed = time.time() - t_kill
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert procs[0].returncode == EXIT_PREEMPTED, \
        f"rank 0 exited {procs[0].returncode} after {elapsed:.0f}s:\n" \
        f"{out0[-3000:]}"
    # the watchdog acted within its horizon (timeout + grace + slack),
    # far inside jax's ~100s coordination-service abort
    assert elapsed < wd_timeout * 5 + 30, f"took {elapsed:.0f}s"
    assert "watchdog" in out0
    # the emergency checkpoint is loadable and digest-valid
    saved = peek_epoch(ck)
    assert saved is not None and saved >= 0
    _assert_checkpoint_digest_valid(ck)
    recs = read_metrics(tmp_path / "metrics0.jsonl")
    assert any(r.get("event") == "fault" and r.get("kind") == "peer-lost"
               for r in recs)

    # ---- resume: a fresh two-process run completes the remainder ----
    port2 = _free_port()
    resume_flags = ["--checkpoint-dir", ck, "--resume",
                    "--skip-partition",
                    "--watchdog-timeout", str(wd_timeout)]
    procs2 = [_spawn_rank(r, port2, tmp_path, resume_flags,
                          n_epochs=saved + 5) for r in (0, 1)]
    outs = [_communicate(p, timeout=240) for p in procs2]
    for r, (p, out) in enumerate(zip(procs2, outs)):
        assert p.returncode == 0, \
            f"resume rank {r} exited {p.returncode}:\n{out[-3000:]}"
        assert f"resumed from {ck} at epoch {saved}" in out


def test_two_process_consensus_nan_drill(tmp_path):
    """Acceptance: nan-loss@5:r1 trips ONLY rank 1's sentinel, yet both
    ranks roll back to the SAME snapshot epoch in lockstep and finish;
    the desync checker (running through the same consensus channel)
    confirms their post-recovery params agree."""
    port = _free_port()
    flags = ["--fault-plan", "nan-loss@5:r1",
             "--sentinel-snapshot-every", "3",
             "--desync-check-every", "6",
             "--watchdog-timeout", "60"]
    procs = [_spawn_rank(r, port, tmp_path, flags, n_epochs=12)
             for r in (0, 1)]
    outs = [_communicate(p, timeout=240) for p in procs]
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, \
            f"rank {r} exited {p.returncode}:\n{out[-3000:]}"
    # rank 1 saw the injected nan; rank 0 learned of it via consensus
    assert "fault-injected nan loss at epoch 5" in outs[1]
    assert "consensus: rank 1 tripped" in outs[0]
    recs = [read_metrics(tmp_path / f"metrics{r}.jsonl") for r in (0, 1)]
    faults = [[x for x in rs if x.get("event") == "fault"] for rs in recs]
    for r in (0, 1):
        assert [f["kind"] for f in faults[r]] == ["divergence"], faults[r]
        assert faults[r][0]["agreed"] is True
        assert faults[r][0]["source_rank"] == 1
        assert faults[r][0]["rank"] == r
        assert any(x.get("event") == "recovery" for x in recs[r])
    # lockstep: both ranks rolled back to the SAME snapshot epoch
    assert faults[0][0]["rollback_epoch"] == \
        faults[1][0]["rollback_epoch"]
    # the desync checker ran (epochs 6 and 12) and stayed silent: the
    # post-recovery replicas agree bit-for-bit
    assert not any(x.get("kind") == "desync"
                   for rs in recs for x in rs
                   if x.get("event") == "fault")
    # every rank completed the nominal schedule, faulted epoch re-run
    for rs in recs:
        epochs = [x["epoch"] for x in rs if x.get("event") == "epoch"]
        assert set(epochs) == set(range(12))
        assert epochs.count(5) == 2


def test_two_process_desync_resync_drill(tmp_path):
    """Rank-targeted desync chaos: desync@7:r1 silently perturbs rank
    1's replica; the per-leaf digest agreement check catches it at the
    next cadence epoch and --desync-resync restores rank 0's state on
    every rank; training completes."""
    port = _free_port()
    flags = ["--fault-plan", "desync@7:r1",
             "--desync-check-every", "4", "--desync-resync",
             "--watchdog-timeout", "60"]
    procs = [_spawn_rank(r, port, tmp_path, flags, n_epochs=14)
             for r in (0, 1)]
    outs = [_communicate(p, timeout=240) for p in procs]
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, \
            f"rank {r} exited {p.returncode}:\n{out[-3000:]}"
    assert "fault-injected param desync at epoch 7" in outs[1]
    for out in outs:
        assert "resyncing every rank from rank 0" in out
    recs = [read_metrics(tmp_path / f"metrics{r}.jsonl") for r in (0, 1)]
    for r in (0, 1):
        fs = [x for x in recs[r] if x.get("event") == "fault"]
        assert [f["kind"] for f in fs] == ["desync"], fs
        assert fs[0]["agreed"] is True
        # rank 1 is the diverged one: its local digest mismatched
        assert fs[0]["local_mismatch"] is (r == 1)
        assert any(x.get("event") == "recovery"
                   and x.get("kind") == "desync" for x in recs[r])
        epochs = [x["epoch"] for x in recs[r]
                  if x.get("event") == "epoch"]
        assert sorted(epochs) == list(range(14))


def test_elastic_kill_redistribution_drill(tmp_path):
    """Acceptance (round 11): a SUPERVISED 2-rank run loses rank 1 to a
    hard SIGKILL (kill@6:r1 — no handlers, no checkpoint, the process
    just vanishes); the survivor's watchdog converts the dead
    collective into exit 75, the elastic supervisor replans both
    partitions onto the single survivor and relaunches it from the last
    good checkpoint — the run then completes EVERY nominal epoch with
    finite losses: membership gen 0 (2 members) -> gen 1 (1 member),
    no epoch gap, all automatic."""
    wd_timeout = 6.0
    backoff = 0.5
    grace_extra = 30.0
    n_epochs = 12
    ck = str(tmp_path / "ck")
    mfile = str(tmp_path / "metrics.jsonl")
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PYTHONPATH": REPO,
        "PYTHONUNBUFFERED": "1",
    }
    cmd = [
        sys.executable, "-m", "pipegcn_tpu.cli.elastic",
        "--max-restarts", "3", "--backoff-base", str(backoff),
        "--grace-extra", str(grace_extra),
        "--metrics-out", str(tmp_path / "sup.jsonl"),
        "--",
        "--dataset", "synthetic:400:6:8:3",
        "--n-partitions", "2", "--parts-per-node", "1",
        "--master-addr", "127.0.0.1",
        "--n-epochs", str(n_epochs), "--n-hidden", "16",
        "--dropout", "0.0", "--log-every", "1000",
        "--fix-seed", "--seed", "7", "--no-eval",
        "--partition-dir", str(tmp_path / "parts"),
        "--checkpoint-dir", ck, "--checkpoint-every", "2",
        "--watchdog-timeout", str(wd_timeout),
        "--fault-plan", "kill@6:r1",
        "--metrics-out", mfile,
    ]
    t0 = time.time()
    proc = subprocess.run(cmd, env=env, cwd=REPO, timeout=540,
                          capture_output=True, text=True)
    elapsed = time.time() - t0
    tail = (proc.stdout + proc.stderr)[-4000:]
    assert proc.returncode == 0, f"supervisor exited " \
        f"{proc.returncode} after {elapsed:.0f}s:\n{tail}"

    # ---- membership: gen 0 (2 members) -> gen 1 (the survivor) ----
    recs = [r for r in read_metrics(tmp_path / "sup.jsonl")
            if r.get("event") == "membership"]
    assert [r["generation"] for r in recs] == [0, 1], tail
    assert recs[0]["trigger"] == "start"
    assert recs[0]["assignment"]["parts"] == {"0": [0], "1": [1]}
    assert recs[1]["trigger"] == "rank-death"
    assert recs[1]["assignment"]["parts"] == {"0": [0, 1]}
    # the redistribution landed within the watchdog horizon plus one
    # backoff interval (the headline latency bound)
    horizon = wd_timeout * 5 + grace_extra
    assert 0.0 < recs[1]["restart_latency_s"] < horizon + backoff + 10, \
        recs[1]

    # ---- epoch continuity: rank 0's gen-0 records + the gen-1 solo
    # run cover every nominal epoch exactly once, losses finite ----
    epochs = {}
    for gen in (0, 1):
        p = tmp_path / f"metrics.g{gen}.m0.jsonl"
        assert p.exists(), f"missing {p}:\n{tail}"
        for x in read_metrics(p):
            if x.get("event") == "epoch":
                epochs.setdefault(x["epoch"], x["loss"])
    assert sorted(epochs) == list(range(n_epochs)), sorted(epochs)
    assert all(np.isfinite(v) for v in epochs.values())
    # the kill fired where scheduled: gen 0 stops short of epoch 6
    g0_epochs = [x["epoch"]
                 for x in read_metrics(tmp_path / "metrics.g0.m0.jsonl")
                 if x.get("event") == "epoch"]
    assert max(g0_epochs) < 6

    # ---- the handoff checkpoint is digest-valid and loadable ----
    _assert_checkpoint_digest_valid(ck)
    assert peek_epoch(ck) >= 6  # gen 1 kept checkpointing past resume
