"""GCN model family (framework extension beyond the reference's
GraphSAGE): dense-reference parity of the symmetric-normalized
convolution, distributed-vs-single-device parity through the halo
machinery, kernel-impl parity, and convergence."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pipegcn_tpu.graph import synthetic_graph
from pipegcn_tpu.models import ModelConfig, forward, init_params
from pipegcn_tpu.parallel import Trainer, TrainConfig
from pipegcn_tpu.partition import ShardedGraph, partition_graph


@pytest.fixture(scope="module")
def graph():
    return synthetic_graph(num_nodes=400, avg_degree=8, n_feat=12,
                           n_class=4, seed=13)


def _gcn_setup(g, n_parts, *, spmm_impl="xla", dropout=0.0, **tkw):
    parts = partition_graph(g, n_parts, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=n_parts)
    cfg = ModelConfig(
        layer_sizes=(sg.n_feat, 16, sg.n_class), model="gcn",
        norm="layer", dropout=dropout, train_size=sg.n_train_global,
        spmm_impl=spmm_impl,
    )
    return Trainer(sg, cfg, TrainConfig(**tkw))


def test_gcn_forward_matches_dense_reference(graph):
    """One GCN layer (no norm tail) against the numpy formula
    h' = W^T (D^-1/2 (A) D^-1/2 h) + b on the finalized graph (whose A
    already includes self-loops)."""
    g = graph
    n = g.num_nodes
    cfg = ModelConfig(layer_sizes=(g.ndata["feat"].shape[1], 5),
                      model="gcn", norm=None, dropout=0.0, train_size=n)
    params = init_params(jax.random.PRNGKey(0), cfg)
    feat = g.ndata["feat"].astype(np.float32)
    deg = g.ndata["in_deg"].astype(np.float64)

    order = np.argsort(g.dst, kind="stable")
    es = jnp.asarray(g.src[order].astype(np.int32))
    ed = jnp.asarray(g.dst[order].astype(np.int32))
    dj = jnp.asarray(deg.astype(np.float32))
    logits, _ = forward(params, cfg, jnp.asarray(feat), es, ed, dj, n,
                        training=False)

    a = np.zeros((n, n), np.float64)
    np.add.at(a, (g.dst, g.src), 1.0)
    norm_a = a / np.sqrt(deg)[:, None] / np.sqrt(deg)[None, :]
    w = np.asarray(params["layers"][0]["w"], np.float64)
    b = np.asarray(params["layers"][0]["b"], np.float64)
    ref = norm_a @ feat.astype(np.float64) @ w + b
    np.testing.assert_allclose(np.asarray(logits), ref, rtol=1e-4,
                               atol=1e-4)


def test_gcn_distributed_matches_single_device(graph):
    t1 = _gcn_setup(graph, 1, seed=3)
    t4 = _gcn_setup(graph, 4, seed=3)
    for epoch in range(4):
        l1, l4 = t1.train_epoch(epoch), t4.train_epoch(epoch)
        assert np.isfinite(l1)
        np.testing.assert_allclose(l1, l4, rtol=2e-4)


def test_gcn_pipelined_kernel_impls_agree(graph):
    losses = {}
    for impl in ("xla", "bucket", "block"):
        t = _gcn_setup(graph, 4, spmm_impl=impl, seed=5,
                       enable_pipeline=True)
        losses[impl] = [t.train_epoch(e) for e in range(5)]
    np.testing.assert_allclose(losses["xla"], losses["bucket"], rtol=2e-4)
    np.testing.assert_allclose(losses["xla"], losses["block"], rtol=2e-4)


def test_gcn_fit_converges(graph):
    t = _gcn_setup(graph, 4, dropout=0.3, seed=7, enable_pipeline=True,
                   n_epochs=40, log_every=10)
    res = t.fit(eval_graphs={"val": (graph, "val_mask"),
                             "test": (graph, "test_mask")},
                log_fn=lambda m: None)
    assert res["best_val"] > 0.8
    assert res["test_acc"] > 0.8


def test_gcn_rejects_use_pp():
    with pytest.raises(ValueError, match="GraphSAGE-only"):
        ModelConfig(layer_sizes=(4, 2), model="gcn", use_pp=True)


def test_gcn_bf16_tracks_f32(graph):
    losses = {}
    for dt in ("float32", "bfloat16"):
        parts = partition_graph(graph, 4, seed=0)
        sg = ShardedGraph.build(graph, parts, n_parts=4)
        cfg = ModelConfig(layer_sizes=(sg.n_feat, 16, sg.n_class),
                          model="gcn", norm="layer", dropout=0.0,
                          train_size=sg.n_train_global, dtype=dt)
        t = Trainer(sg, cfg, TrainConfig(seed=3, enable_pipeline=True))
        losses[dt] = [t.train_epoch(e) for e in range(8)]
    np.testing.assert_allclose(losses["float32"], losses["bfloat16"],
                               rtol=0.05, atol=0.05)


def test_gcn_float8_transport_converges(graph):
    """GCN + rem_dtype='float8': layer 0 aggregates RAW input features
    through the narrowed transport (no use_pp for GCN) — the
    saturating-cast path — and training must track full precision and
    keep converging."""
    import dataclasses

    parts = partition_graph(graph, 4, seed=0)
    sg = ShardedGraph.build(graph, parts, n_parts=4)
    base = ModelConfig(
        layer_sizes=(sg.n_feat, 16, sg.n_class), model="gcn",
        norm="layer", dropout=0.0, train_size=sg.n_train_global,
        spmm_impl="bucket",
    )
    losses = {}
    for rd in (None, "float8"):
        cfg = dataclasses.replace(base, rem_dtype=rd)
        t = Trainer(sg, cfg, TrainConfig(seed=4, enable_pipeline=True))
        losses[rd] = [t.train_epoch(e) for e in range(15)]
    l32, l8 = np.asarray(losses[None]), np.asarray(losses["float8"])
    assert np.isfinite(l8).all()
    np.testing.assert_allclose(l8[:4], l32[:4], rtol=0.1, atol=0.05)
    assert l8[-1] < l8[0] * 0.8
