"""Randomized parity sweep for the standalone bucket kernel: random
(graph, F, chunking, slab) combinations — wide rows spanning several
slabs, partial final slabs, hub rows, chunk boundaries — against the
dense reference."""

import numpy as np
import jax.numpy as jnp
import pytest

from pipegcn_tpu.ops.bucket_spmm import (
    _bucket_widths,
    bucket_aggregate,
    build_tables_for_edges,
)


@pytest.mark.parametrize("trial", range(10))
def test_randomized_bucket_parity(trial):
    rng = np.random.default_rng(500 + trial)
    n_out = int(rng.integers(10, 300))
    n_src = n_out + int(rng.integers(0, 100))
    e = int(rng.integers(1, 5000))
    f = int(rng.choice([1, 5, 17, 64, 70, 130]))
    chunk_edges = int(rng.choice([0, 64, 1000]))
    slab = int(rng.choice([0, 4, 16, 64]))
    src = rng.integers(0, n_src, e).astype(np.int64)
    dst = rng.integers(0, n_out, e).astype(np.int64)
    if trial % 2:
        dst[: e // 3] = int(rng.integers(0, n_out))  # hub row
    widths = _bucket_widths(
        int(np.bincount(dst, minlength=n_out).max(initial=1)))
    mats, inv, _ = build_tables_for_edges(src, dst, n_out, n_src, widths)
    fbuf = rng.standard_normal((n_src, f)).astype(np.float32)
    out = np.asarray(bucket_aggregate(
        jnp.asarray(fbuf), [jnp.asarray(m) for m in mats],
        jnp.asarray(inv), chunk_edges=chunk_edges or None,
        slab=slab or None))
    ref = np.zeros((n_out, f), np.float32)
    np.add.at(ref, dst, fbuf[src])
    np.testing.assert_allclose(
        out, ref, rtol=2e-5, atol=2e-5,
        err_msg=f"n_out={n_out} f={f} chunk={chunk_edges} slab={slab}")
