import numpy as np
import pytest

from pipegcn_tpu.graph import (
    Graph,
    karate_club,
    normalize_self_loops,
    synthetic_graph,
)
from pipegcn_tpu.graph.datasets import inductive_split, is_multilabel, n_classes


def test_self_loop_normalization():
    g = Graph(
        num_nodes=3,
        src=np.array([0, 0, 1, 2, 2]),
        dst=np.array([1, 0, 1, 2, 0]),
    )
    g2 = normalize_self_loops(g)
    # exactly one self loop per node, original non-loop edges kept
    loops = g2.src == g2.dst
    assert loops.sum() == 3
    assert g2.num_edges == 2 + 3  # (0->1), (2->0) kept + 3 loops


def test_degrees_and_csr():
    g = karate_club()
    deg = g.in_degrees()
    assert deg.sum() == g.num_edges
    indptr, src_sorted, eid = g.in_csr()
    assert indptr[-1] == g.num_edges
    # row i of CSR holds sources of in-edges of node i
    i = 5
    row = src_sorted[indptr[i] : indptr[i + 1]]
    expect = np.sort(g.src[g.dst == i])
    np.testing.assert_array_equal(np.sort(row), expect)


def test_subgraph():
    g = karate_club()
    nodes = np.arange(10)
    sub = g.node_subgraph(nodes)
    assert sub.num_nodes == 10
    sub.validate()
    # all subgraph edges exist in the original graph
    orig = set(zip(g.src.tolist(), g.dst.tolist()))
    for s, d in zip(sub.src, sub.dst):
        assert (nodes[s], nodes[d]) in orig


def test_synthetic_graph_shapes():
    g = synthetic_graph(num_nodes=500, avg_degree=8, n_feat=16, n_class=5, seed=1)
    g.validate()
    assert g.ndata["feat"].shape == (500, 16)
    assert n_classes(g) == 5
    assert not is_multilabel(g)
    masks = g.ndata["train_mask"] | g.ndata["val_mask"] | g.ndata["test_mask"]
    assert masks.all()
    assert (g.ndata["train_mask"] & g.ndata["val_mask"]).sum() == 0
    # one self loop per node
    assert (g.src == g.dst).sum() == 500


def test_synthetic_label_noise():
    """label_noise flips ~p of labels to a DIFFERENT class (the
    irreducible-error ceiling full-density convergence studies rely
    on); 0.0 is bit-identical to the pre-feature generator."""
    g0 = synthetic_graph(num_nodes=4000, avg_degree=6, n_feat=8,
                         n_class=7, seed=3)
    g0b = synthetic_graph(num_nodes=4000, avg_degree=6, n_feat=8,
                          n_class=7, seed=3, label_noise=0.0)
    assert (g0.ndata["label"] == g0b.ndata["label"]).all()
    gn = synthetic_graph(num_nodes=4000, avg_degree=6, n_feat=8,
                         n_class=7, seed=3, label_noise=0.25)
    flipped = (gn.ndata["label"] != g0.ndata["label"])
    frac = flipped.mean()
    assert 0.18 < frac < 0.32, frac  # ~Binomial(4000, .25)
    # flips always land on a different class, never out of range
    assert (gn.ndata["label"] >= 0).all()
    assert (gn.ndata["label"] < 7).all()
    # graph structure and features untouched
    assert (gn.src == g0.src).all()
    np.testing.assert_array_equal(gn.ndata["feat"], g0.ndata["feat"])


def test_synthetic_multilabel():
    g = synthetic_graph(num_nodes=200, n_class=6, multilabel=True, seed=2)
    assert is_multilabel(g)
    assert g.ndata["label"].shape == (200, 6)
    assert n_classes(g) == 6


def test_homophily_present():
    # the generator should produce assortative structure — most edges
    # intra-community — otherwise GNN tests on it are meaningless
    g = synthetic_graph(num_nodes=2000, avg_degree=10, n_class=4, seed=3)
    lab = g.ndata["label"]
    non_loop = g.src != g.dst
    frac_intra = (lab[g.src[non_loop]] == lab[g.dst[non_loop]]).mean()
    assert frac_intra > 0.6


def test_inductive_split():
    g = synthetic_graph(num_nodes=300, seed=4)
    train_g, val_g, test_g = inductive_split(g)
    assert train_g.num_nodes == g.ndata["train_mask"].sum()
    assert val_g.num_nodes == (g.ndata["train_mask"] | g.ndata["val_mask"]).sum()
    assert test_g.num_nodes == g.num_nodes
