import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pipegcn_tpu.graph import karate_club, synthetic_graph
from pipegcn_tpu.models import ModelConfig, forward, init_norm_state, init_params
from pipegcn_tpu.ops import spmm_mean, spmm_sum


@pytest.fixture(scope="module")
def small_graph():
    return karate_club(n_feat=8)


def _graph_arrays(g):
    """Full-graph edge arrays with one pad edge exercising the sentinel."""
    n = g.num_nodes
    src = np.concatenate([g.src, [0]]).astype(np.int32)
    dst = np.concatenate([g.dst, [n]]).astype(np.int32)  # sentinel
    return jnp.array(src), jnp.array(dst), jnp.array(
        g.ndata["in_deg"].astype(np.float32)
    )


def test_spmm_sum_matches_dense(small_graph):
    g = small_graph
    n = g.num_nodes
    src, dst, deg = _graph_arrays(g)
    x = jnp.array(np.random.default_rng(0).normal(size=(n, 8)).astype(np.float32))
    out = spmm_sum(x, src, dst, n)
    a = np.zeros((n, n), np.float32)
    np.add.at(a, (g.dst, g.src), 1.0)
    np.testing.assert_allclose(out, a @ np.asarray(x), rtol=1e-4, atol=1e-4)


def test_spmm_chunked_matches_unchunked(small_graph):
    g = small_graph
    n = g.num_nodes
    src, dst, deg = _graph_arrays(g)
    x = jnp.array(np.random.default_rng(1).normal(size=(n, 8)).astype(np.float32))
    full = spmm_mean(x, src, dst, deg, n)
    for chunk in (7, 64, 128):
        np.testing.assert_allclose(
            spmm_mean(x, src, dst, deg, n, chunk=chunk), full,
            rtol=1e-4, atol=1e-5,
        )


def test_spmm_gradient(small_graph):
    g = small_graph
    n = g.num_nodes
    src, dst, deg = _graph_arrays(g)
    x = jnp.ones((n, 4), jnp.float32)

    def f(x):
        return spmm_sum(x, src, dst, n).sum()

    grad = jax.grad(f)(x)
    # d/dx_u of sum over edges = out-degree of u (incl. pad edge's src 0
    # being dropped via the sentinel segment)
    np.testing.assert_allclose(
        np.asarray(grad)[:, 0], g.out_degrees().astype(np.float32), rtol=1e-5
    )


def _cfg(g, hidden=16, n_layers=3, **kw):
    n_class = int(g.ndata["label"].max()) + 1
    sizes = (g.ndata["feat"].shape[1],) + (hidden,) * (n_layers - 1) + (n_class,)
    kw.setdefault("train_size", int(g.ndata["train_mask"].sum()))
    return ModelConfig(layer_sizes=sizes, **kw)


def test_init_param_shapes_and_bounds(small_graph):
    cfg = _cfg(small_graph, norm="layer", n_linear=1)
    params = init_params(jax.random.PRNGKey(0), cfg)
    assert len(params["layers"]) == 3
    assert set(params["layers"][0]) == {"w1", "b1", "w2", "b2"}
    assert set(params["layers"][2]) == {"w", "b"}  # linear tail
    assert len(params["norms"]) == 2
    w1 = params["layers"][0]["w1"]
    bound = 1.0 / np.sqrt(w1.shape[0])
    assert float(jnp.abs(w1).max()) <= bound
    assert float(jnp.abs(w1).max()) > 0.5 * bound  # actually spread out


def test_train_eval_parity_no_dropout(small_graph):
    """With dropout=0 and a trivial comm (full graph as one shard), the
    training path must equal the eval path exactly."""
    g = small_graph
    n = g.num_nodes
    src, dst, deg = _graph_arrays(g)
    feat = jnp.array(g.ndata["feat"])
    cfg = _cfg(g, dropout=0.0, norm="layer")
    params = init_params(jax.random.PRNGKey(1), cfg)

    train_out, _ = forward(
        params, cfg, feat, src, dst, deg, n,
        training=True, rng=jax.random.PRNGKey(0),
        comm_update=lambda i, h: h,
    )
    eval_out, _ = forward(
        params, cfg, feat, src, dst, deg, n, training=False,
    )
    np.testing.assert_allclose(train_out, eval_out, rtol=1e-4, atol=1e-5)


def test_use_pp_parity(small_graph):
    """Training with precomputed concat input == eval recomputing the
    first-layer aggregation on the fly (module/layer.py:41-42 vs 58-60)."""
    g = small_graph
    n = g.num_nodes
    src, dst, deg = _graph_arrays(g)
    feat = jnp.array(g.ndata["feat"])
    cfg = _cfg(g, dropout=0.0, norm="layer", use_pp=True)
    params = init_params(jax.random.PRNGKey(2), cfg)

    ah = spmm_mean(feat, src, dst, deg, n)
    pp_input = jnp.concatenate([feat, ah], axis=1)
    train_out, _ = forward(
        params, cfg, pp_input, src, dst, deg, n,
        training=True, rng=jax.random.PRNGKey(0),
        comm_update=lambda i, h: h,
    )
    eval_out, _ = forward(
        params, cfg, feat, src, dst, deg, n, training=False,
        eval_pp_agg=True,
    )
    np.testing.assert_allclose(train_out, eval_out, rtol=1e-4, atol=1e-5)


def test_dropout_changes_output_and_is_seeded(small_graph):
    g = small_graph
    n = g.num_nodes
    src, dst, deg = _graph_arrays(g)
    feat = jnp.array(g.ndata["feat"])
    cfg = _cfg(g, dropout=0.5)
    params = init_params(jax.random.PRNGKey(3), cfg)

    def run(seed):
        out, _ = forward(
            params, cfg, feat, src, dst, deg, n,
            training=True, rng=jax.random.PRNGKey(seed),
            comm_update=lambda i, h: h,
        )
        return np.asarray(out)

    a, b, a2 = run(0), run(1), run(0)
    assert not np.allclose(a, b)
    np.testing.assert_array_equal(a, a2)


def test_sync_batch_norm_single_device(small_graph):
    """psum=identity SyncBN must match plain batch normalization when
    train_size equals the row count."""
    g = small_graph
    n = g.num_nodes
    src, dst, deg = _graph_arrays(g)
    feat = jnp.array(g.ndata["feat"])
    cfg = _cfg(g, dropout=0.0, norm="batch", train_size=n)
    params = init_params(jax.random.PRNGKey(4), cfg)
    state = init_norm_state(cfg)
    assert len(state) == 2

    out, new_state = forward(
        params, cfg, feat, src, dst, deg, n,
        training=True, rng=jax.random.PRNGKey(0),
        comm_update=lambda i, h: h, norm_state=state,
    )
    assert out.shape == (n, 2)
    # running stats moved toward the batch stats (momentum 0.1)
    assert not np.allclose(np.asarray(new_state[0]["mean"]), 0.0)
    # eval path consumes running stats without error
    eval_out, _ = forward(
        params, cfg, feat, src, dst, deg, n, training=False,
        norm_state=new_state,
    )
    assert eval_out.shape == (n, 2)


def test_gradients_flow_everywhere(small_graph):
    g = small_graph
    n = g.num_nodes
    src, dst, deg = _graph_arrays(g)
    feat = jnp.array(g.ndata["feat"])
    labels = jnp.array(g.ndata["label"])
    cfg = _cfg(g, dropout=0.0, norm="layer", n_linear=1)
    params = init_params(jax.random.PRNGKey(5), cfg)

    def loss_fn(p):
        logits, _ = forward(
            p, cfg, feat, src, dst, deg, n,
            training=True, rng=jax.random.PRNGKey(0),
            comm_update=lambda i, h: h,
        )
        onehot = jax.nn.one_hot(labels, logits.shape[-1])
        return -(jax.nn.log_softmax(logits) * onehot).sum()

    grads = jax.grad(loss_fn)(params)
    flat, _ = jax.tree_util.tree_flatten(grads)
    assert all(np.isfinite(np.asarray(x)).all() for x in flat)
    assert all(float(jnp.abs(x).max()) > 0 for x in flat)


def test_spmm_bf16_forward_and_grad_match_f32(small_graph):
    """bf16 spmm_mean: forward within bf16 tolerance of f32; the custom
    VJP accumulates the backward scatter in f32 (cotangents must closely
    match the f32 path, not bf16-accumulation error)."""
    import jax
    import jax.numpy as jnp
    from pipegcn_tpu.ops.spmm import spmm_mean

    g = small_graph
    n = g.num_nodes
    rng = np.random.default_rng(0)
    feat = rng.standard_normal((n, 8)).astype(np.float32)
    order = np.argsort(g.dst, kind="stable")
    es = jnp.asarray(g.src[order].astype(np.int32))
    ed = jnp.asarray(g.dst[order].astype(np.int32))
    deg = jnp.asarray(np.maximum(g.in_degrees(), 1).astype(np.float32))

    def loss32(f):
        return (spmm_mean(f, es, ed, deg, n, None, True) ** 2).sum()

    def loss16(f):
        return (spmm_mean(f.astype(jnp.bfloat16), es, ed, deg, n,
                          None, True) ** 2).sum()

    f32 = jnp.asarray(feat)
    v32, g32 = jax.value_and_grad(loss32)(f32)
    v16, g16 = jax.value_and_grad(loss16)(f32)
    np.testing.assert_allclose(v16, v32, rtol=0.03)
    np.testing.assert_allclose(np.asarray(g16), np.asarray(g32),
                               rtol=0.1, atol=0.02)

    # chunked path agrees with unchunked in bf16
    out_a = spmm_mean(f32.astype(jnp.bfloat16), es, ed, deg, n, None, True)
    out_b = spmm_mean(f32.astype(jnp.bfloat16), es, ed, deg, n, 7, True)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b),
                               rtol=1e-6)


def test_spmm_bf16_in_deg_cotangent_matches_f32(small_graph):
    """Differentiating through the degrees must give the true cotangent
    -(out*g).sum(-1)/deg on the bf16 custom-VJP path, matching f32
    autodiff (it used to silently return zeros)."""
    import jax
    import jax.numpy as jnp
    from pipegcn_tpu.ops.spmm import spmm_mean

    g = small_graph
    n = g.num_nodes
    rng = np.random.default_rng(3)
    feat = jnp.asarray(rng.standard_normal((n, 8)).astype(np.float32))
    order = np.argsort(g.dst, kind="stable")
    es = jnp.asarray(g.src[order].astype(np.int32))
    ed = jnp.asarray(g.dst[order].astype(np.int32))
    deg0 = jnp.asarray(np.maximum(g.in_degrees(), 1).astype(np.float32))

    def loss32(deg):
        return (spmm_mean(feat, es, ed, deg, n, None, True) ** 2).sum()

    def loss16(deg):
        return (spmm_mean(feat.astype(jnp.bfloat16), es, ed, deg, n,
                          None, True) ** 2).sum()

    gd32 = jax.grad(loss32)(deg0)
    gd16 = jax.grad(loss16)(deg0)
    assert float(jnp.abs(gd32).max()) > 0
    np.testing.assert_allclose(np.asarray(gd16), np.asarray(gd32),
                               rtol=0.1, atol=0.02)
