"""Degree-bucketed scatter-free SpMM: unit parity vs dense reference and
trainer-level parity vs the XLA gather+segment-sum path."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pipegcn_tpu.graph import synthetic_graph
from pipegcn_tpu.models import ModelConfig
from pipegcn_tpu.ops.bucket_spmm import (
    BucketPlan,
    bucket_aggregate,
    build_tables_for_edges,
    make_bucket_spmm_fn,
    _bucket_widths,
)
from pipegcn_tpu.parallel import Trainer, TrainConfig
from pipegcn_tpu.partition import ShardedGraph, partition_graph


@pytest.fixture(scope="module")
def edges():
    rng = np.random.default_rng(5)
    n_out, n_src = 120, 150
    e = 900
    src = rng.integers(0, n_src, e).astype(np.int64)
    dst = rng.integers(0, n_out, e).astype(np.int64)
    # a hub row and an isolated row to stress buckets
    dst[:100] = 7
    mask = dst != 11  # row 11 has no edges
    return src[mask], dst[mask], n_out, n_src


def _dense_sum(src, dst, n_out, n_src, fbuf):
    out = np.zeros((n_out, fbuf.shape[1]), np.float32)
    for s, d in zip(src, dst):
        out[d] += np.asarray(fbuf, np.float32)[s]
    return out


def test_bucket_aggregate_matches_dense(edges):
    src, dst, n_out, n_src = edges
    rng = np.random.default_rng(0)
    fbuf = rng.standard_normal((n_src, 16)).astype(np.float32)
    widths = _bucket_widths(int(np.bincount(dst, minlength=n_out).max()))
    mats, inv, counts = build_tables_for_edges(src, dst, n_out, n_src,
                                               widths)
    out = bucket_aggregate(jnp.asarray(fbuf),
                           [jnp.asarray(m) for m in mats],
                           jnp.asarray(inv))
    np.testing.assert_allclose(np.asarray(out),
                               _dense_sum(src, dst, n_out, n_src, fbuf),
                               rtol=1e-5, atol=1e-5)
    # zero-degree row stays zero
    assert np.abs(np.asarray(out)[11]).max() == 0.0


def test_bucket_aggregate_slabbed_matches(edges):
    # force the feature-slab path (production: F wider than 256 bytes /
    # itemsize; here slab=4 so F=10 spans 3 slabs incl. a partial one)
    src, dst, n_out, n_src = edges
    rng = np.random.default_rng(3)
    fbuf = rng.standard_normal((n_src, 10)).astype(np.float32)
    widths = _bucket_widths(int(np.bincount(dst, minlength=n_out).max()))
    mats, inv, counts = build_tables_for_edges(src, dst, n_out, n_src,
                                               widths)
    ref = _dense_sum(src, dst, n_out, n_src, fbuf)
    for chunk_edges in (None, 64):
        out = bucket_aggregate(jnp.asarray(fbuf),
                               [jnp.asarray(m) for m in mats],
                               jnp.asarray(inv), chunk_edges=chunk_edges,
                               slab=4)
        np.testing.assert_allclose(np.asarray(out), ref,
                                   rtol=1e-5, atol=1e-5)
    # default slab width activates on its own past 256 bytes per row
    wide = rng.standard_normal((n_src, 70)).astype(np.float32)
    out = bucket_aggregate(jnp.asarray(wide),
                           [jnp.asarray(m) for m in mats],
                           jnp.asarray(inv))
    np.testing.assert_allclose(
        np.asarray(out), _dense_sum(src, dst, n_out, n_src, wide),
        rtol=1e-5, atol=1e-5)


def test_bucket_aggregate_chunked_matches(edges):
    src, dst, n_out, n_src = edges
    rng = np.random.default_rng(1)
    fbuf = rng.standard_normal((n_src, 8)).astype(np.float32)
    widths = _bucket_widths(int(np.bincount(dst, minlength=n_out).max()))
    mats, inv, _ = build_tables_for_edges(src, dst, n_out, n_src, widths)
    jm = [jnp.asarray(m) for m in mats]
    a = bucket_aggregate(jnp.asarray(fbuf), jm, jnp.asarray(inv))
    b = bucket_aggregate(jnp.asarray(fbuf), jm, jnp.asarray(inv),
                         chunk_elems=64)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_bucket_mean_fn_grad_matches_reference(edges):
    """Forward and backward of the custom-VJP closure vs spmm_mean."""
    from pipegcn_tpu.ops.spmm import spmm_mean

    src, dst, n_out, n_src = edges
    rng = np.random.default_rng(2)
    fbuf = jnp.asarray(rng.standard_normal((n_src, 8)).astype(np.float32))
    deg = jnp.asarray(
        np.maximum(np.bincount(dst, minlength=n_out), 1).astype(np.float32)
    )
    plan = BucketPlan(src, dst, n_out, n_src)
    fn = make_bucket_spmm_fn(
        [jnp.asarray(m) for m in plan.fwd_mats], jnp.asarray(plan.fwd_inv),
        [jnp.asarray(m) for m in plan.bwd_mats], jnp.asarray(plan.bwd_inv),
        deg, n_src,
    )
    order = np.argsort(dst, kind="stable")
    es = jnp.asarray(src[order].astype(np.int32))
    ed = jnp.asarray(dst[order].astype(np.int32))

    v_a, g_a = jax.value_and_grad(lambda f: (fn(f) ** 2).sum())(fbuf)
    v_b, g_b = jax.value_and_grad(
        lambda f: (spmm_mean(f, es, ed, deg, n_out, None, True) ** 2).sum()
    )(fbuf)
    np.testing.assert_allclose(float(v_a), float(v_b), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g_a), np.asarray(g_b),
                               rtol=1e-4, atol=1e-5)


def test_trainer_bucket_matches_xla():
    g = synthetic_graph(num_nodes=300, avg_degree=7, n_feat=10, n_class=4,
                        seed=21)
    parts = partition_graph(g, 4, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=4)
    losses = {}
    for impl in ("xla", "bucket"):
        cfg = ModelConfig(layer_sizes=(10, 16, 4), norm="layer",
                          dropout=0.0, train_size=sg.n_train_global,
                          spmm_impl=impl)
        t = Trainer(sg, cfg, TrainConfig(seed=4, enable_pipeline=True))
        losses[impl] = [t.train_epoch(e) for e in range(6)]
    np.testing.assert_allclose(losses["xla"], losses["bucket"], rtol=2e-4)


def test_trainer_bucket_bf16_fused():
    g = synthetic_graph(num_nodes=300, avg_degree=7, n_feat=10, n_class=4,
                        seed=22)
    parts = partition_graph(g, 4, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=4)
    cfg = ModelConfig(layer_sizes=(10, 16, 16, 4), norm="layer",
                      dropout=0.2, train_size=sg.n_train_global,
                      spmm_impl="bucket", dtype="bfloat16", use_pp=True)
    t = Trainer(sg, cfg, TrainConfig(seed=4, enable_pipeline=True,
                                     feat_corr=True, grad_corr=True))
    losses = list(t.train_epochs(0, 4)) + list(t.train_epochs(4, 16))
    assert np.isfinite(losses).all()
    assert np.mean(losses[-4:]) < np.mean(losses[:4])


def test_ladder_prefix_lockstep():
    """ladder_prefix and _bucket_widths must come from the same
    progression: the sharded builders regenerate shared ladders by
    length and silently corrupt tables if the two ever diverge."""
    from pipegcn_tpu.ops.bucket_spmm import _bucket_widths, ladder_prefix

    for md in (1, 2, 5, 17, 492, 65536, 1_000_000):
        w = _bucket_widths(md)
        assert w == ladder_prefix(len(w))
        assert w[-1] >= md
        if len(w) > 1:
            assert w[-2] < md
        assert all(b > a for a, b in zip(w, w[1:]))
        # padding bound: each rung at most 1.5x the previous
        assert all(b <= max(a + 1, (a * 3) // 2) for a, b in zip(w, w[1:]))


def test_float8_transport_tolerance_and_slab_width():
    """rem_dtype='float8': e4m3 transport packs F=256 into ONE 256-byte
    gather row (no slabbing) and stays within fp8 quantization error of
    the f32 result; e5m2 cotangent transport likewise."""
    rng = np.random.default_rng(7)
    n_out, n_src, e = 60, 80, 700
    src = rng.integers(0, n_src, e).astype(np.int64)
    dst = rng.integers(0, n_out, e).astype(np.int64)
    deg = jnp.asarray(
        np.maximum(np.bincount(dst, minlength=n_out), 1).astype(np.float32))
    plan = BucketPlan(src, dst, n_out, n_src)
    f32_fn = make_bucket_spmm_fn(
        [jnp.asarray(m) for m in plan.fwd_mats], jnp.asarray(plan.fwd_inv),
        [jnp.asarray(m) for m in plan.bwd_mats], jnp.asarray(plan.bwd_inv),
        deg, n_src)
    f8_fn = make_bucket_spmm_fn(
        [jnp.asarray(m) for m in plan.fwd_mats], jnp.asarray(plan.fwd_inv),
        [jnp.asarray(m) for m in plan.bwd_mats], jnp.asarray(plan.bwd_inv),
        deg, n_src, rem_dtype="float8")
    fbuf = jnp.asarray(rng.standard_normal((n_src, 256)).astype(np.float32))
    o32 = np.asarray(f32_fn(fbuf))
    o8 = np.asarray(f8_fn(fbuf))
    # e4m3 has a 3-bit mantissa (~6% element error); mean-of-degree
    # aggregation keeps the relative error of the same order
    err = np.abs(o8 - o32) / (np.abs(o32) + 1e-3)
    assert np.median(err) < 0.03
    # the mean is dragged by near-zero outputs where relative error
    # diverges; 15% bounds it without being noise-brittle
    assert err.mean() < 0.15
    g32 = np.asarray(jax.grad(lambda f: (f32_fn(f) ** 2).sum())(fbuf))
    g8 = np.asarray(jax.grad(lambda f: (f8_fn(f) ** 2).sum())(fbuf))
    gerr = np.abs(g8 - g32) / (np.abs(g32) + 1e-3)
    assert np.median(gerr) < 0.1  # e5m2: 2-bit mantissa
    # zero-degree/no-edge rows stay exactly zero
    no_edge = np.setdiff1d(np.arange(n_out), dst)
    if no_edge.size:
        assert np.abs(o8[no_edge]).max() == 0.0


def test_transport_dtypes_mapping():
    from pipegcn_tpu.ops.bucket_spmm import transport_dtypes

    assert transport_dtypes(None) == (None, None)
    assert transport_dtypes("none") == (None, None)
    f, b = transport_dtypes("float8")
    assert f == jnp.float8_e4m3fn and b == jnp.float8_e5m2
    f, b = transport_dtypes("bfloat16")
    assert f == jnp.bfloat16 and b == jnp.bfloat16
    with pytest.raises(ValueError):
        transport_dtypes("int4")


def test_transport_cast_saturates_not_nan():
    """fp8 has no inf: an overflowing astype yields NaN — transport_cast
    must clamp to the finite max instead (raw layer-0 features can
    exceed e4m3's +-448)."""
    from pipegcn_tpu.ops.bucket_spmm import transport_cast

    x = jnp.asarray([1e4, -1e4, 3.0], jnp.float32)
    y = np.asarray(
        transport_cast(x, jnp.float8_e4m3fn).astype(jnp.float32))
    assert np.isfinite(y).all()
    assert y[0] == 448.0 and y[1] == -448.0
    # identity when no transport dtype
    assert transport_cast(x, None) is x
