"""CLI surface + end-to-end run tests (programmatic args, CPU mesh)."""

import os

import numpy as np
import pytest

from pipegcn_tpu.cli.main import derive_graph_name, result_file_name, run
from pipegcn_tpu.cli.parser import create_parser


def _args(tmp_path, extra):
    base = [
        "--dataset", "synthetic:600:8:16:4",
        "--n-partitions", "4",
        "--n-epochs", "25",
        "--n-layers", "2",
        "--n-hidden", "32",
        "--dropout", "0.2",
        "--log-every", "10",
        "--fix-seed", "--seed", "7",
        "--partition-dir", str(tmp_path / "partitions"),
        "--model-dir", str(tmp_path / "model"),
        "--results-dir", str(tmp_path / "results"),
    ]
    return create_parser().parse_args(base + extra)


def test_parser_reference_surface():
    """Every reference flag (helper/parser.py:4-71) parses, both
    spellings."""
    p = create_parser()
    a = p.parse_args([
        "--dataset", "reddit", "--graph_name", "x", "--model", "graphsage",
        "--dropout", "0.5", "--lr", "0.01", "--n_epochs", "3000",
        "--n-partitions", "2", "--n_hidden", "256", "--n-layers", "4",
        "--n_linear", "0", "--norm", "layer", "--weight_decay", "0",
        "--n-feat", "602", "--n_class", "41", "--n-train", "153431",
        "--skip-partition", "--partition_obj", "vol",
        "--partition-method", "metis", "--enable_pipeline", "--feat-corr",
        "--grad_corr", "--corr-momentum", "0.95", "--use_pp", "--inductive",
        "--fix_seed", "--seed", "1", "--log_every", "10", "--backend",
        "xla", "--port", "18118", "--master_addr", "127.0.0.1",
        "--node-rank", "0", "--parts_per_node", "10", "--no-eval",
    ])
    assert a.n_epochs == 3000 and a.enable_pipeline and not a.eval
    assert a.graph_name == "x"


def test_graph_name_and_result_file():
    a = create_parser().parse_args(
        ["--dataset", "reddit", "--n-partitions", "2", "--inductive",
         "--enable-pipeline", "--grad-corr"])
    assert derive_graph_name(a) == "reddit-2-metis-vol-induc"
    assert result_file_name(a).endswith("reddit_n2_p1_grad.txt")


def test_cli_end_to_end_transductive(tmp_path):
    res = run(_args(tmp_path, ["--enable-pipeline", "--use-pp"]))
    assert res["best_val"] > 0.7
    assert res["test_acc"] > 0.7
    # artifacts: partition cache, results file, model file
    assert os.path.exists(res["model_path"])
    rfile = result_file_name(_args(tmp_path, ["--enable-pipeline",
                                              "--use-pp"]))
    lines = open(rfile).read().strip().splitlines()
    assert len(lines) >= 2
    assert "Validation Accuracy" in lines[0]


def test_cli_inductive_and_skip_partition(tmp_path):
    args = _args(tmp_path, ["--inductive"])
    res1 = run(args)
    assert res1["best_val"] > 0.6
    # second run reuses the partition artifact
    args2 = _args(tmp_path, ["--inductive", "--skip-partition"])
    res2 = run(args2)
    assert res2["best_val"] > 0.6
    rfile = result_file_name(args)
    assert "Accuracy" in open(rfile).read()


def test_cli_checkpoint_resume(tmp_path):
    from pipegcn_tpu.utils.checkpoint import checkpoint_exists

    ckpt = str(tmp_path / "ckpt")
    args = _args(tmp_path, ["--checkpoint-dir", ckpt,
                            "--checkpoint-every", "10"])
    run(args)
    assert checkpoint_exists(ckpt)
    # rotation layout: epoch-stamped generations + a latest pointer
    assert os.path.exists(os.path.join(ckpt, "latest"))
    # resume picks up at the saved epoch and trains further
    args2 = _args(tmp_path, ["--checkpoint-dir", ckpt, "--resume",
                             "--skip-partition", "--n-epochs", "45"])
    res = run(args2)
    assert res["best_val"] > 0.6


def test_cli_rejects_bad_backend(tmp_path):
    with pytest.raises(NotImplementedError):
        run(_args(tmp_path, ["--backend", "nccl"]))
    with pytest.raises(ValueError):
        run(_args(tmp_path, ["--backend", "smoke"]))


def test_cli_rejects_bad_model(tmp_path):
    with pytest.raises(ValueError):
        run(_args(tmp_path, ["--model", "gin"]))


def test_cli_gcn_end_to_end(tmp_path):
    res = run(_args(tmp_path, ["--model", "gcn", "--enable-pipeline"]))
    assert res["best_val"] > 0.7
    # gcn + use_pp is rejected (SAGE-only precompute)
    with pytest.raises(ValueError, match="GraphSAGE-only"):
        run(_args(tmp_path, ["--model", "gcn", "--use-pp"]))


def test_cli_gat_end_to_end(tmp_path):
    res = run(_args(tmp_path, ["--model", "gat", "--n-heads", "4",
                               "--enable-pipeline"]))
    assert res["best_val"] > 0.7


def test_cli_checkpoint_resume_gat(tmp_path):
    """Checkpoint/resume is model-family agnostic (pytree npz): a GAT
    run resumes from its own attention-param state."""
    from pipegcn_tpu.utils.checkpoint import checkpoint_exists

    ckpt = str(tmp_path / "ckpt_gat")
    run(_args(tmp_path, ["--model", "gat", "--checkpoint-dir", ckpt,
                         "--checkpoint-every", "10"]))
    assert checkpoint_exists(ckpt)
    res = run(_args(tmp_path, ["--model", "gat", "--checkpoint-dir",
                               ckpt, "--resume", "--skip-partition",
                               "--n-epochs", "40"]))
    assert res["best_val"] > 0.6


def test_cli_crash_checkpoint(tmp_path, monkeypatch):
    """A crash mid-training saves the last completed state so --resume
    restarts from it (the reference's collectives just hang on failure,
    SURVEY.md aux subsystems)."""
    from pipegcn_tpu.parallel.trainer import Trainer

    ckpt = str(tmp_path / "ckpt_crash")
    orig = Trainer.train_epoch

    def boom(self, epoch):
        if epoch >= 12:
            raise RuntimeError("injected device loss")
        return orig(self, epoch)

    monkeypatch.setattr(Trainer, "train_epoch", boom)
    with pytest.raises(RuntimeError, match="injected"):
        run(_args(tmp_path, ["--checkpoint-dir", ckpt,
                             "--checkpoint-every", "100"]))
    from pipegcn_tpu.utils.checkpoint import checkpoint_exists

    assert checkpoint_exists(ckpt)
    monkeypatch.setattr(Trainer, "train_epoch", orig)
    res = run(_args(tmp_path, ["--checkpoint-dir", ckpt, "--resume",
                               "--skip-partition"]))
    assert res["best_val"] > 0.6
