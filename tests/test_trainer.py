"""Integration tests for the SPMD trainer on a virtual 8-device CPU mesh
(the analogue of the reference's localhost-gloo multiprocess testing,
SURVEY.md §4)."""

import dataclasses
import numpy as np
import jax
import pytest

from pipegcn_tpu.graph import synthetic_graph
from pipegcn_tpu.graph.datasets import inductive_split
from pipegcn_tpu.models import ModelConfig
from pipegcn_tpu.parallel import Trainer, TrainConfig
from pipegcn_tpu.partition import ShardedGraph, partition_graph


def _setup(g, n_parts, *, dropout=0.0, norm="layer", use_pp=False,
           n_linear=0, hidden=16, n_layers=2, dtype="float32", **tkw):
    parts = partition_graph(g, n_parts, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=n_parts)
    n_class = sg.n_class
    sizes = (sg.n_feat,) + (hidden,) * (n_layers - 1) + (n_class,)
    cfg = ModelConfig(
        layer_sizes=sizes, n_linear=n_linear, use_pp=use_pp, norm=norm,
        dropout=dropout, train_size=sg.n_train_global, dtype=dtype,
    )
    tcfg = TrainConfig(**tkw)
    return Trainer(sg, cfg, tcfg)


@pytest.fixture(scope="module")
def graph():
    return synthetic_graph(num_nodes=400, avg_degree=8, n_feat=12,
                           n_class=4, seed=11)


def test_vanilla_distributed_matches_single_device(graph):
    """SURVEY §7 step 5 gate: the P=4 vanilla run must match the P=1 run
    numerically (same init, same data, no dropout)."""
    t1 = _setup(graph, 1, seed=3)
    t4 = _setup(graph, 4, seed=3)
    for epoch in range(5):
        l1 = t1.train_epoch(epoch)
        l4 = t4.train_epoch(epoch)
        assert np.isfinite(l1) and np.isfinite(l4)
        np.testing.assert_allclose(l1, l4, rtol=2e-4)
    # params also agree
    p1 = jax.device_get(t1.state["params"])
    p4 = jax.device_get(t4.state["params"])
    flat1 = jax.tree_util.tree_leaves(p1)
    flat4 = jax.tree_util.tree_leaves(p4)
    for a, b in zip(flat1, flat4):
        np.testing.assert_allclose(a, b, rtol=5e-3, atol=1e-5)


def test_pipeline_epoch0_matches_vanilla_forward(graph):
    """At epoch 0 the pipelined forward concats zero buffers
    (reference feature_buffer.py:153-163) — its loss must differ from
    vanilla (halo contributions missing) but the *second* epoch consumes
    epoch 0's real features."""
    tv = _setup(graph, 4, seed=3)
    tp = _setup(graph, 4, seed=3, enable_pipeline=True)
    lv0 = tv.train_epoch(0)
    lp0 = tp.train_epoch(0)
    # epoch 0 pipelined sees zeros in halo slots -> different loss
    assert abs(lv0 - lp0) > 1e-6
    # convergence is preserved over a few epochs
    for e in range(1, 30):
        lv = tv.train_epoch(e)
        lp = tp.train_epoch(e)
    assert np.isfinite(lp)
    assert lp < lp0  # pipelined training reduces loss


def test_pipeline_staleness_exactness(graph):
    """Epoch e of the pipelined run must consume exactly epoch e-1's halo
    features: with frozen params (lr=0), epoch e's loss equals the
    vanilla loss from one epoch earlier once buffers are warm."""
    tv = _setup(graph, 4, seed=3, lr=0.0)
    tp = _setup(graph, 4, seed=3, lr=0.0, enable_pipeline=True)
    lv = [tv.train_epoch(e) for e in range(4)]
    lp = [tp.train_epoch(e) for e in range(4)]
    # with lr=0 params never change; vanilla loss is constant
    np.testing.assert_allclose(lv[0], lv[1], rtol=1e-5)
    # with 2 exchanged layers the stale buffers become exact after 2
    # epochs (layer i's halo is exact once its producer epoch was exact):
    # epoch >= 2 losses equal the vanilla loss under frozen params
    np.testing.assert_allclose(lp[2], lv[0], rtol=1e-4)
    np.testing.assert_allclose(lp[3], lv[0], rtol=1e-4)
    # epochs 0 (zero buffers) and 1 (half-warm) differ
    assert abs(lp[0] - lv[0]) > 1e-6
    assert abs(lp[1] - lv[0]) > 1e-6


def test_corrections_smoke(graph):
    t = _setup(graph, 4, seed=3, enable_pipeline=True, feat_corr=True,
               grad_corr=True, corr_momentum=0.95)
    losses = [t.train_epoch(e) for e in range(10)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[1]


def test_use_pp_trains_and_skips_layer0_comm(graph):
    t = _setup(graph, 4, seed=3, use_pp=True, enable_pipeline=True)
    # layer 0 must have no comm buffers
    assert "0" not in t.state["comm"]["halo"]
    losses = [t.train_epoch(e) for e in range(10)]
    assert losses[-1] < losses[0]
    # pp feature width doubled
    assert t.data["feat"].shape[-1] == 2 * t.sg.n_feat


def test_pipeline_with_dropout_use_pp_corrections(graph):
    """Regression: pipelined + dropout + use_pp + corrections (the probe
    cotangents are device-varying; unvarying probes fail shard_map's VMA
    check)."""
    t = _setup(graph, 4, seed=3, dropout=0.3, use_pp=True, n_layers=3,
               enable_pipeline=True, feat_corr=True, grad_corr=True)
    losses = [t.train_epoch(e) for e in range(8)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_fit_eval_convergence_transductive(graph):
    t = _setup(graph, 4, seed=3, dropout=0.1, n_epochs=60, log_every=20,
               hidden=32)
    res = t.fit(eval_graphs={"val": (graph, "val_mask"),
                             "test": (graph, "test_mask")},
                log_fn=lambda m: None)
    assert res["best_val"] > 0.75  # homophilous synthetic graph is easy
    assert res["test_acc"] > 0.75
    assert res["best_params"] is not None


def test_fit_inductive(graph):
    train_g, val_g, test_g = inductive_split(graph)
    t = _setup(train_g, 4, seed=3, n_epochs=40, log_every=20, hidden=32)
    res = t.fit(eval_graphs={"val": (val_g, "val_mask"),
                             "test": (test_g, "test_mask")},
                log_fn=lambda m: None)
    assert res["best_val"] > 0.7


def test_multilabel_bce(graph):
    g = synthetic_graph(num_nodes=300, avg_degree=8, n_feat=10, n_class=5,
                        multilabel=True, seed=13)
    t = _setup(g, 2, norm="layer", n_linear=1, n_layers=3)
    losses = [t.train_epoch(e) for e in range(15)]
    assert losses[-1] < losses[0]
    acc = t.evaluate(g, "val_mask")
    assert 0.0 <= acc <= 1.0


def test_sync_batch_norm_distributed_matches_single(graph):
    """SyncBN: P=4 must equal P=1 (psum makes stats global)."""
    t1 = _setup(graph, 1, norm="batch")
    t4 = _setup(graph, 4, norm="batch")
    for e in range(3):
        l1 = t1.train_epoch(e)
        l4 = t4.train_epoch(e)
        np.testing.assert_allclose(l1, l4, rtol=2e-3)


def test_bf16_mixed_precision_tracks_f32(graph):
    """bf16 compute path: losses track the f32 run closely for the first
    epochs and training converges; pipelined comm carry is bf16."""
    tf32 = _setup(graph, 4, seed=3, enable_pipeline=True)
    tb16 = _setup(graph, 4, seed=3, dtype="bfloat16", enable_pipeline=True)
    comm = jax.device_get(tb16.state["comm"])
    assert all(
        v.dtype == jax.numpy.bfloat16.dtype
        for grp in comm.values() for v in grp.values()
    )
    for epoch in range(8):
        l32 = tf32.train_epoch(epoch)
        l16 = tb16.train_epoch(epoch)
        assert np.isfinite(l16)
        np.testing.assert_allclose(l16, l32, rtol=0.05, atol=0.02)
    # keeps converging
    for epoch in range(8, 40):
        last = tb16.train_epoch(epoch)
    assert last < l16


def test_bf16_with_corrections_and_pp(graph):
    t = _setup(graph, 4, seed=5, dtype="bfloat16", use_pp=True,
               dropout=0.2, enable_pipeline=True, feat_corr=True,
               grad_corr=True)
    comm = jax.device_get(t.state["comm"])
    # EMA accumulators stay f32, transport is bf16
    assert all(v.dtype == np.float32 for v in comm["favg"].values())
    assert all(
        v.dtype == jax.numpy.bfloat16.dtype for v in comm["halo"].values()
    )
    losses = [t.train_epoch(e) for e in range(25)]
    assert np.isfinite(losses).all()
    assert np.mean(losses[-5:]) < np.mean(losses[2:7])


def test_fused_epochs_match_singles(graph):
    """train_epochs(k) must be numerically identical to k train_epoch
    calls (same per-epoch rng folds), pipelined carry included."""
    ta = _setup(graph, 4, seed=9, dropout=0.3, enable_pipeline=True)
    tb = _setup(graph, 4, seed=9, dropout=0.3, enable_pipeline=True)
    la = [ta.train_epoch(e) for e in range(6)]
    lb = list(tb.train_epochs(0, 3)) + list(tb.train_epochs(3, 3))
    np.testing.assert_allclose(la, lb, rtol=1e-5)
    pa = jax.tree_util.tree_leaves(jax.device_get(ta.state["params"]))
    pb = jax.tree_util.tree_leaves(jax.device_get(tb.state["params"]))
    for a, b in zip(pa, pb):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


def test_batchnorm_transductive_stays_finite(graph):
    """Transductive SyncBN sums over ALL rows but divides by n_train
    (reference semantics, sync_bn.py:19-20) — the overscaled mean can
    make the variance estimate negative; the clamp in
    _sync_batch_norm_train must keep training finite AND learning."""
    t = _setup(graph, 4, seed=5, dropout=0.5, norm="batch",
               enable_pipeline=True)
    assert t.sg.n_train_global < t.sg.inner_count.sum()  # transductive
    losses = [t.train_epoch(e) for e in range(20)]
    assert np.isfinite(losses).all()
    assert np.mean(losses[-5:]) < np.mean(losses[:5])


def test_fit_with_fused_epochs(graph):
    t = _setup(graph, 4, seed=3, n_epochs=40, log_every=20, hidden=32,
               fused_epochs=8)
    res = t.fit(eval_graphs={"val": (graph, "val_mask"),
                             "test": (graph, "test_mask")},
                log_fn=lambda m: None)
    assert res["best_val"] > 0.75
    assert len(res["history"]) == 2  # evals still at log_every boundaries


def test_prewarm_tables_guards_and_caches(tmp_path):
    """Host-side cache prewarm: refuses configs whose build would be
    discarded (no disk artifact / non-caching impl), writes the same
    npz a real Trainer would then load."""
    import os

    g = synthetic_graph(num_nodes=200, avg_degree=5, n_feat=8, n_class=3,
                        seed=0)
    sg = ShardedGraph.build(g, partition_graph(g, 2, seed=0), n_parts=2)
    cfg = ModelConfig(layer_sizes=(8, 16, 3), train_size=sg.n_train_global,
                      spmm_impl="bucket")
    with pytest.raises(ValueError, match="cache_dir"):
        Trainer.prewarm_tables(sg, cfg)  # in-memory artifact

    path = str(tmp_path / "art")
    sg.save(path)
    sg2 = ShardedGraph.load(path)
    with pytest.raises(ValueError, match="prewarm"):
        Trainer.prewarm_tables(
            sg2, dataclasses.replace(cfg, spmm_impl="xla"))
    # gat's setup only builds tables for auto/bucket — block is
    # rejected at config construction, so prewarm can never silently
    # warm nothing for it
    with pytest.raises(ValueError, match="gat"):
        dataclasses.replace(cfg, model="gat", spmm_impl="block")

    Trainer.prewarm_tables(sg2, cfg)
    assert os.path.exists(os.path.join(path, "bucket_tables.npz"))
    # the real trainer must LOAD the warmed cache, not rebuild: poison
    # the builder and construct
    import pipegcn_tpu.ops.bucket_spmm as bs

    orig = bs.build_sharded_bucket_tables
    try:
        def boom(*a, **k):
            raise AssertionError("cache miss: prewarmed tables not used")

        bs.build_sharded_bucket_tables = boom
        t = Trainer(sg2, cfg, TrainConfig(n_epochs=1, eval=False))
        assert t._bucket_tables is not None
    finally:
        bs.build_sharded_bucket_tables = orig


def test_fit_final_state_always_evaluated(graph):
    """log_every past n_epochs (or a final partial period) must not end
    the run unscored: fit always evaluates the final state."""
    t = _setup(graph, 2, seed=3, dropout=0.1, n_epochs=8, log_every=50,
               hidden=32)
    res = t.fit(eval_graphs={"val": (graph, "val_mask"),
                             "test": (graph, "test_mask")},
                log_fn=lambda m: None)
    assert res["best_params"] is not None
    assert res["best_epoch"] == 8
    assert res["best_val"] > 0.0


def test_float8_remainder_transport_converges(graph):
    """rem_dtype='float8' narrows only the gather transport (f32
    accumulation): training must track the full-precision run early
    and keep converging; the pp precompute is exempt (raw features)."""
    parts = partition_graph(graph, 4, seed=0)
    sg = ShardedGraph.build(graph, parts, n_parts=4)
    losses = {}
    for rd in (None, "float8"):
        cfg = ModelConfig(layer_sizes=(12, 16, 4), norm="layer",
                          dropout=0.0, train_size=sg.n_train_global,
                          spmm_impl="bucket", use_pp=True,
                          rem_dtype=rd)
        t = Trainer(sg, cfg, TrainConfig(seed=4, enable_pipeline=True))
        losses[rd] = [t.train_epoch(e) for e in range(20)]
    l32, l8 = np.asarray(losses[None]), np.asarray(losses["float8"])
    assert np.isfinite(l8).all()
    np.testing.assert_allclose(l8[:5], l32[:5], rtol=0.08, atol=0.03)
    assert l8[-1] < l8[0] * 0.7  # still converging
    # pp features (raw-feature precompute) must be exempt from the
    # narrowed transport: identical across the two configs
    cfg8 = ModelConfig(layer_sizes=(12, 16, 4), norm="layer",
                      dropout=0.0, train_size=sg.n_train_global,
                      spmm_impl="bucket", use_pp=True,
                      rem_dtype="float8")
    t8 = Trainer(sg, cfg8, TrainConfig(seed=4))
    t0 = Trainer(sg, dataclasses.replace(cfg8, rem_dtype=None),
                 TrainConfig(seed=4))
    np.testing.assert_array_equal(np.asarray(t8.data["feat"]),
                                  np.asarray(t0.data["feat"]))


def test_identity_collectives_switch(graph):
    """The exposed-wait measurement's trace-time switch
    (halo.identity_collectives): the step still compiles and runs with
    ring ppermutes replaced by identity (same shapes), the P>1 losses
    DIFFER from the real program's (the permutes were actually
    elided), and the flag restores on exit."""
    import pipegcn_tpu.parallel.halo as halo

    t_real = _setup(graph, 4, seed=3, enable_pipeline=True)
    real = [t_real.train_epoch(e) for e in range(3)]
    with halo.identity_collectives():
        assert halo._IDENTITY_COLLECTIVES
        t_id = _setup(graph, 4, seed=3, enable_pipeline=True)
        ident = [t_id.train_epoch(e) for e in range(3)]
    assert not halo._IDENTITY_COLLECTIVES
    assert np.isfinite(ident).all()
    # with each device keeping its own boundary rows, training history
    # must diverge from the true exchange
    assert not np.allclose(real, ident, rtol=1e-6)


def test_emulate_auto_resolves_and_unknown_impl_rejected(graph):
    """emulate_parts + spmm_impl='auto' resolves through the tuner
    path (tune=False here: no table and no live micro-bench means the
    loud deterministic default) and trains; an impl name outside the
    shipped set raises instead of silently falling back — there is no
    legacy dispatch path."""
    parts = partition_graph(graph, 4, seed=0)
    sg = ShardedGraph.build(graph, parts, n_parts=4)
    cfg = ModelConfig(layer_sizes=(12, 16, 4), norm="layer",
                      train_size=sg.n_train_global, spmm_impl="auto",
                      tune=False)
    tc = TrainConfig(seed=0, emulate_parts=True)
    with pytest.warns(UserWarning, match="deterministic default"):
        t = Trainer(sg, cfg, tc)
    assert t.tuning["source"] == "default"
    assert t._current_impl() == t.tuning["winner"]["impl"]
    assert np.isfinite(t.train_epoch(0))
    with pytest.raises(ValueError, match="unknown spmm_impl"):
        Trainer(sg, dataclasses.replace(cfg, spmm_impl="pallas"), tc)


def test_emulate_parts_matches_mesh(graph):
    """emulate_parts=True (vmap-with-axis_name on ONE device) must
    reproduce the real shard_map mesh run to float rounding — losses
    and eval — for vanilla AND pipelined+corrections, including use_pp
    and dropout (per-rank rng folds through axis_index identically)."""
    parts = partition_graph(graph, 4, seed=0)
    sg = ShardedGraph.build(graph, parts, n_parts=4)
    cfg = ModelConfig(layer_sizes=(12, 16, 4), norm="layer", dropout=0.3,
                      use_pp=True, train_size=sg.n_train_global)
    for pipe, corr in ((False, False), (True, True)):
        tc = TrainConfig(seed=4, enable_pipeline=pipe, feat_corr=corr,
                         grad_corr=corr)
        tm = Trainer(sg, cfg, tc)
        te = Trainer(sg, cfg,
                     dataclasses.replace(tc, emulate_parts=True))
        lm = [tm.train_epoch(e) for e in range(5)]
        le = [te.train_epoch(e) for e in range(5)]
        np.testing.assert_allclose(lm, le, rtol=1e-5)
        assert tm.evaluate(graph, "val_mask") == \
            te.evaluate(graph, "val_mask")
    # fused-epoch dispatch agrees too
    te2 = Trainer(sg, cfg, TrainConfig(seed=4, enable_pipeline=True,
                                       fused_epochs=4,
                                       emulate_parts=True))
    lf = te2.train_epochs(0, 4)
    tm2 = Trainer(sg, cfg, TrainConfig(seed=4, enable_pipeline=True))
    lr = [tm2.train_epoch(e) for e in range(4)]
    np.testing.assert_allclose(np.asarray(lf), lr, rtol=1e-5)
