"""Fault-tolerance tests: divergence sentinel rollback, preemption,
hardened checkpoints (digests / generations / fallback), and the
fault-injection harness (docs/RESILIENCE.md).

Everything here is marked `faults`; the in-process tests keep tier-1
cheap (one tiny shared graph, P=2), the subprocess kill/resume matrix
is additionally marked `slow`.
"""

import glob
import io
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from pipegcn_tpu.graph import synthetic_graph
from pipegcn_tpu.models import ModelConfig
from pipegcn_tpu.obs import MetricsLogger, read_metrics, validate_record
from pipegcn_tpu.parallel import Trainer, TrainConfig
from pipegcn_tpu.partition import ShardedGraph, partition_graph
from pipegcn_tpu.resilience import (
    EXIT_PREEMPTED,
    DivergenceError,
    DivergenceSentinel,
    FaultPlan,
    Preempted,
    PreemptionHandler,
    SentinelConfig,
    corrupt_latest_checkpoint,
)
from pipegcn_tpu.utils.checkpoint import (
    CheckpointCorrupt,
    checkpoint_exists,
    latest_checkpoint_path,
    load_checkpoint,
    peek_epoch,
    save_checkpoint,
)

pytestmark = pytest.mark.faults


@pytest.fixture(scope="module")
def sharded():
    g = synthetic_graph(num_nodes=300, avg_degree=6, n_feat=8, n_class=3,
                        seed=1)
    parts = partition_graph(g, 2, seed=0)
    return ShardedGraph.build(g, parts, n_parts=2)


def _trainer(sg, **tkw):
    cfg = ModelConfig(layer_sizes=(sg.n_feat, 16, sg.n_class),
                      dropout=0.0, train_size=sg.n_train_global)
    tkw.setdefault("n_epochs", 12)
    tkw.setdefault("log_every", 50)
    return Trainer(sg, cfg, TrainConfig(**tkw))


# ---------------- fault plan ------------------------------------------


def test_fault_plan_grammar_and_single_shot():
    p = FaultPlan.parse("nan-loss@5, sigterm@8,corrupt-ckpt@10")
    assert len(p) == 3
    assert p.remaining() == ["nan-loss@5", "sigterm@8", "corrupt-ckpt@10"]
    # in-block injection consumes the entry exactly once
    assert p.due_in("nan-loss", 4, 8) == 5
    assert p.due_in("nan-loss", 4, 8) is None
    # boundary faults fire at-or-after their epoch (fused blocks may
    # never visit the exact boundary)
    assert not p.due("sigterm", 7)
    assert p.due("sigterm", 9)
    assert not p.due("sigterm", 9)
    with pytest.raises(ValueError, match="kind@epoch"):
        FaultPlan.parse("nan-loss5")
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultPlan.parse("meteor@3")


def test_fault_plan_resume_skip():
    """A resumed run passes the same --fault-plan; lived-through entries
    must not re-fire (sigterm@8 fired at the START of epoch 8, so a
    resume at start_epoch=8 retires it — else it would preempt in a
    loop forever)."""
    p = FaultPlan.parse("nan-loss@5,sigterm@8,nan-loss@9")
    p.skip_before(8)
    assert p.remaining() == ["nan-loss@9"]
    # fresh runs (start_epoch 0) keep everything
    q = FaultPlan.parse("sigterm@0")
    q.skip_before(0)
    assert q.remaining() == ["sigterm@0"]


# ---------------- sentinel (unit) -------------------------------------


def test_sentinel_trip_conditions():
    s = DivergenceSentinel(SentinelConfig(warmup=3, loss_factor=10.0,
                                          grad_norm_max=100.0))
    for e in range(3):
        assert s.check(e, [1.0 - 0.1 * e], [1.0]) is None
    assert "non-finite loss" in s.check(3, [float("nan")], [1.0])
    assert "non-finite grad" in s.check(4, [0.5], [float("inf")])
    assert "grad norm" in s.check(5, [0.5], [250.0])
    # relative explosion against the healthy median (~0.9)
    assert "healthy median" in s.check(6, [50.0], [1.0])
    # tripped blocks never polluted the baseline; healthy ones pass
    assert s.check(7, [0.8], [1.0]) is None
    assert s.trips == 4


def test_sentinel_pre_warmup_never_trips_relative():
    s = DivergenceSentinel(SentinelConfig(warmup=5))
    # wild but finite early losses are warmup noise, not divergence
    assert s.check(0, [1e6], [1.0]) is None
    assert s.check(1, [3.0], [1.0]) is None


# ---------------- hardened checkpoints --------------------------------


def test_checkpoint_rotation_and_latest_pointer(tmp_path):
    d = str(tmp_path / "ck")
    state = {"params": {"w": np.ones((3, 4), np.float32)}}
    for ep in (10, 20, 30, 40):
        save_checkpoint(d, state, ep, keep=2)
    gens = sorted(os.path.basename(p)
                  for p in glob.glob(os.path.join(d, "state-*.npz")))
    assert gens == ["state-00000030.npz", "state-00000040.npz"]
    assert os.path.basename(latest_checkpoint_path(d)) == \
        "state-00000040.npz"
    assert peek_epoch(d) == 40
    _, ep = load_checkpoint(d, state)
    assert ep == 40
    # keep=0 disables pruning
    save_checkpoint(d, state, 50, keep=0)
    assert len(glob.glob(os.path.join(d, "state-*.npz"))) == 3


def test_corrupt_newest_generation_falls_back(tmp_path):
    """Acceptance: a corrupt newest generation is detected and load
    falls back to the previous good one."""
    d = str(tmp_path / "ck")
    state = {"params": {"w": np.arange(12, dtype=np.float32).reshape(3, 4)}}
    save_checkpoint(d, {"params": {"w": state["params"]["w"] * 0}}, 10)
    save_checkpoint(d, state, 20)
    corrupt_latest_checkpoint(d)
    with pytest.warns(UserWarning, match="falling back"):
        back, ep = load_checkpoint(d, state)
    assert ep == 10
    np.testing.assert_array_equal(back["params"]["w"],
                                  np.zeros((3, 4), np.float32))
    # peek_epoch lazily reads ONLY the epoch scalar, so a scribble in
    # another member may not surface there — it must not raise, and
    # full loads (above) are what verify
    assert peek_epoch(d) in (10, 20)


def test_digest_detects_silent_tamper(tmp_path):
    """A structurally valid npz whose leaf bytes changed (bit-rot,
    partial overwrite) must fail the per-leaf digest, not load."""
    d = str(tmp_path / "ck")
    state = {"params": {"w": np.arange(6, dtype=np.float32)}}
    save_checkpoint(d, state, 7)
    path = latest_checkpoint_path(d)
    z = dict(np.load(path))
    z["params/w"] = z["params/w"] + 1.0  # rewrite WITHOUT digest update
    np.savez_compressed(path, **z)
    with pytest.raises(CheckpointCorrupt, match="digest mismatch"):
        load_checkpoint(d, state)


def test_truncated_checkpoint_raises_checkpoint_corrupt(tmp_path):
    """Satellite: truncated/corrupt archives raise CheckpointCorrupt
    from peek_epoch/load_checkpoint instead of escaping as raw
    zipfile.BadZipFile/EOFError."""
    d = str(tmp_path / "ck")
    state = {"params": {"w": np.ones(4, np.float32)}}
    save_checkpoint(d, state, 5)
    for p in glob.glob(os.path.join(d, "state-*.npz")):
        with open(p, "r+b") as f:
            f.truncate(os.path.getsize(p) // 2)
    with pytest.raises(CheckpointCorrupt):
        peek_epoch(d)
    with pytest.raises(CheckpointCorrupt):
        load_checkpoint(d, state)
    # legacy single-file layout gets the same treatment
    d2 = str(tmp_path / "legacy")
    os.makedirs(d2)
    with open(os.path.join(d2, "state.npz"), "wb") as f:
        f.write(b"PK\x03\x04 not a real zip")
    assert checkpoint_exists(d2)
    with pytest.raises(CheckpointCorrupt):
        peek_epoch(d2)


def test_fault_recovery_schema_records():
    validate_record({"event": "fault", "kind": "divergence", "epoch": 5,
                     "reason": "nan", "retry": 1})
    validate_record({"event": "recovery", "kind": "divergence",
                     "epoch": 7, "retries": 2})
    with pytest.raises(ValueError, match="missing field 'kind'"):
        validate_record({"event": "fault", "epoch": 5})
    with pytest.raises(ValueError, match="expected integer"):
        validate_record({"event": "recovery", "kind": "x",
                         "epoch": "seven"})


# ---------------- sentinel in the trainer loop ------------------------


def test_sentinel_rollback_recovers_in_fit(sharded):
    t = _trainer(sharded, enable_pipeline=True)
    lr0 = t.tcfg.lr
    buf = io.StringIO()
    logs = []
    res = t.fit(eval_graphs=None, log_fn=logs.append,
                metrics=MetricsLogger(buf),
                sentinel=DivergenceSentinel(SentinelConfig(
                    snapshot_every=3, lr_backoff=0.5)),
                fault_plan=FaultPlan.parse("nan-loss@5"))
    recs = [json.loads(line) for line in buf.getvalue().splitlines()]
    faults = [r for r in recs if r["event"] == "fault"]
    assert [f["kind"] for f in faults] == ["divergence"]
    assert faults[0]["epoch"] == 5 and faults[0]["retry"] == 1
    assert any(r["event"] == "recovery" and r["kind"] == "divergence"
               for r in recs)
    # rollback target was an earlier snapshot, LR was backed off once
    assert faults[0]["rollback_epoch"] < 5
    assert abs(t.tcfg.lr - lr0 * 0.5) < 1e-12
    # the run still completed every epoch (the faulted one re-ran)
    epochs = [r["epoch"] for r in recs if r["event"] == "epoch"]
    assert max(epochs) == t.tcfg.n_epochs - 1
    assert epochs.count(5) == 2  # faulted + healthy retry
    assert t.last_epoch == t.tcfg.n_epochs
    # the healthy retry's loss is finite (the nan never re-fired)
    retried = [r["loss"] for r in recs
               if r["event"] == "epoch" and r["epoch"] == 5]
    assert not np.isfinite(retried[0]) and np.isfinite(retried[1])
    assert res is not None
    assert any("sentinel tripped" in line for line in logs)


def test_sentinel_gives_up_after_max_retries(sharded):
    t = _trainer(sharded)
    with pytest.raises(DivergenceError, match="retries were exhausted"):
        t.fit(eval_graphs=None, log_fn=lambda s: None,
              sentinel=DivergenceSentinel(SentinelConfig(
                  max_retries=1, snapshot_every=100)),
              fault_plan=FaultPlan.parse("nan-loss@4,nan-loss@4"))


# ---------------- preemption ------------------------------------------


def test_preemption_checkpoints_and_resumes(sharded, tmp_path):
    ck = str(tmp_path / "ck")
    t = _trainer(sharded)
    pre = PreemptionHandler()
    buf = io.StringIO()
    with pytest.raises(Preempted) as ei:
        t.fit(eval_graphs=None, log_fn=lambda s: None,
              metrics=MetricsLogger(buf), checkpoint_dir=ck,
              preemption=pre, fault_plan=FaultPlan.parse("sigterm@8"))
    assert ei.value.epoch == 8
    assert peek_epoch(ck) == 8
    recs = [json.loads(line) for line in buf.getvalue().splitlines()]
    assert any(r["event"] == "fault" and r["kind"] == "preemption"
               and r["epoch"] == 8 for r in recs)
    # resume with the SAME fault plan: skip_before retires sigterm@8
    import jax

    t2 = _trainer(sharded)
    host, start = load_checkpoint(ck, jax.device_get(t2.state))
    t2.restore_state(host)
    plan = FaultPlan.parse("sigterm@8")
    res = t2.fit(eval_graphs=None, log_fn=lambda s: None,
                 start_epoch=start, checkpoint_dir=ck,
                 preemption=PreemptionHandler(), fault_plan=plan)
    assert np.isfinite(res["history"][-1][1]) if res["history"] else True
    assert t2.last_epoch == t2.tcfg.n_epochs


def test_preemption_handler_request_flag():
    pre = PreemptionHandler()
    assert not pre.requested
    pre.request("SIGTERM")
    pre.request("SIGINT")  # first reason wins
    assert pre.requested and pre.reason == "SIGTERM"
    # flag-only use never needs signal installation; disabled install
    # is a no-op context
    with pre.installed(enabled=False) as h:
        assert h is pre


# ---------------- crash checkpoint path (satellite) -------------------


def test_crash_checkpoint_saves_and_resumes(sharded, tmp_path):
    """A mid-fit exception saves the last good state (on top of the
    periodic generations) and the run resumes from it."""
    ck = str(tmp_path / "ck")
    t = _trainer(sharded)
    logs = []
    with pytest.raises(RuntimeError, match="fault-injected crash"):
        t.fit(eval_graphs=None, log_fn=logs.append, checkpoint_dir=ck,
              checkpoint_every=4, fault_plan=FaultPlan.parse("crash@9"))
    assert any("crash checkpoint saved" in line for line in logs)
    # crash at the start of epoch 9 -> 9 epochs completed; the periodic
    # generations at 4 and 8 are still on disk beneath it
    assert peek_epoch(ck) == 9
    import jax

    t2 = _trainer(sharded)
    host, start = load_checkpoint(ck, jax.device_get(t2.state))
    assert start == 9
    t2.restore_state(host)
    res = t2.fit(eval_graphs=None, log_fn=lambda s: None,
                 start_epoch=start)
    assert t2.last_epoch == t2.tcfg.n_epochs
    assert res is not None


def test_crash_checkpoint_poisoned_buffer_skip(sharded, tmp_path,
                                               monkeypatch):
    """When the state cannot be materialized/saved (failed dispatch
    poisoned the donated buffers), the crash handler must skip the save
    — leaving the previous good checkpoint intact — and re-raise."""
    import jax

    import pipegcn_tpu.utils.checkpoint as ckpt_mod

    ck = str(tmp_path / "ck")
    t = _trainer(sharded)
    # a known-good generation that must survive the failed crash-save
    save_checkpoint(ck, jax.device_get(t.state), 2)

    def poisoned(*a, **k):
        raise RuntimeError("device buffers poisoned")

    monkeypatch.setattr(ckpt_mod, "save_checkpoint", poisoned)
    logs = []
    with pytest.raises(RuntimeError, match="fault-injected crash"):
        t.fit(eval_graphs=None, log_fn=logs.append, checkpoint_dir=ck,
              checkpoint_every=100, fault_plan=FaultPlan.parse("crash@5"))
    assert any("crash checkpoint failed" in line for line in logs)
    monkeypatch.undo()
    assert peek_epoch(ck) == 2  # the good generation survived


# ---------------- sequential runner guard -----------------------------


def test_sequential_divergence_guard(sharded):
    cfg = ModelConfig(layer_sizes=(sharded.n_feat, 16, sharded.n_class),
                      dropout=0.0, norm="layer",
                      train_size=sharded.n_train_global,
                      spmm_impl="bucket")
    tcfg = TrainConfig(n_epochs=2, enable_pipeline=True, eval=False)
    from pipegcn_tpu.parallel import SequentialRunner

    buf = io.StringIO()
    run = SequentialRunner(sharded, cfg, tcfg,
                           metrics=MetricsLogger(buf),
                           fault_plan=FaultPlan.parse("nan-loss@1"))
    assert np.isfinite(run.run_epoch(0))
    with pytest.raises(DivergenceError, match="non-finite loss"):
        run.run_epoch(1)
    recs = [json.loads(line) for line in buf.getvalue().splitlines()]
    assert any(r["event"] == "fault" and r["kind"] == "divergence"
               for r in recs)


# ---------------- CLI wiring ------------------------------------------


def _cli_args(tmp_path, extra):
    from pipegcn_tpu.cli.parser import create_parser

    base = [
        "--dataset", "synthetic:400:6:8:3",
        "--n-partitions", "2",
        "--n-epochs", "12",
        "--n-hidden", "16",
        "--dropout", "0.0",
        "--log-every", "50",
        "--fix-seed", "--seed", "7",
        "--no-eval",
        "--partition-dir", str(tmp_path / "partitions"),
        "--model-dir", str(tmp_path / "model"),
        "--results-dir", str(tmp_path / "results"),
    ]
    return create_parser().parse_args(base + extra)


def test_cli_resume_requires_checkpoint_dir(tmp_path):
    """Satellite: --resume without --checkpoint-dir errors; --resume
    with an empty checkpoint dir warns loudly and trains fresh."""
    from pipegcn_tpu.cli.main import run

    with pytest.raises(ValueError, match="--resume requires"):
        run(_cli_args(tmp_path, ["--resume"]))
    with pytest.warns(UserWarning, match="no checkpoint found"):
        res = run(_cli_args(tmp_path, [
            "--resume", "--checkpoint-dir", str(tmp_path / "empty_ck"),
            "--n-epochs", "3"]))
    assert res is not None


def test_cli_fault_plan_recovery_and_preemption(tmp_path):
    """Acceptance: --fault-plan nan-loss@5,sigterm@8 — the sentinel
    recovers epoch 5, the preemption produces a resumable checkpoint at
    8, and the resumed run completes the SAME total epoch count, all
    visible as fault/recovery events in the metrics JSONL."""
    from pipegcn_tpu.cli.main import run

    ck = str(tmp_path / "ck")
    mfile = str(tmp_path / "metrics.jsonl")
    flags = ["--checkpoint-dir", ck, "--checkpoint-every", "10",
             "--metrics-out", mfile, "--no-signal-handlers",
             "--sentinel-snapshot-every", "3",
             "--fault-plan", "nan-loss@5,sigterm@8"]
    with pytest.raises(Preempted):
        run(_cli_args(tmp_path, flags))
    assert peek_epoch(ck) == 8
    # resume (same plan, already-fired entries retire)
    run(_cli_args(tmp_path, flags + ["--resume", "--skip-partition"]))
    recs = read_metrics(mfile)
    kinds = [r["kind"] for r in recs if r["event"] == "fault"]
    assert "divergence" in kinds and "preemption" in kinds
    assert any(r["event"] == "recovery" for r in recs)
    # every epoch of the nominal schedule ran exactly once in the
    # final timeline (the faulted epoch appears once extra, pre-trip)
    epochs = [r["epoch"] for r in recs if r["event"] == "epoch"]
    assert set(epochs) == set(range(12))
    assert epochs.count(5) == 2


def test_cli_corrupt_ckpt_fault_then_fallback(tmp_path):
    """--fault-plan corrupt-ckpt@12: the NEWEST generation (the one
    `latest` points to) is scribbled after its save; the resume detects
    it via verification and falls back to the previous good generation
    (epoch 8), re-running 8..14."""
    from pipegcn_tpu.cli.main import run

    ck = str(tmp_path / "ck")
    run(_cli_args(tmp_path, [
        "--checkpoint-dir", ck, "--checkpoint-every", "4",
        "--fault-plan", "corrupt-ckpt@12"]))
    # generations at 4, 8, 12 exist; 12 (= latest) is scribbled
    assert len(glob.glob(os.path.join(ck, "state-*.npz"))) == 3
    with pytest.warns(UserWarning, match="falling back"):
        res = run(_cli_args(tmp_path, [
            "--checkpoint-dir", ck, "--resume", "--skip-partition",
            "--n-epochs", "14"]))
    assert res is not None


def test_await_partition_backoff(monkeypatch, capsys):
    """Satellite: the artifact wait polls with exponential backoff +
    jitter and logs progress."""
    import time as time_mod

    import pipegcn_tpu.cli.main as cli_main

    sleeps = []
    calls = {"n": 0}

    class FakeSG:
        num_parts = 4

    class FakeShardedGraph:
        @staticmethod
        def exists(path):
            calls["n"] += 1
            return calls["n"] > 4

        @staticmethod
        def load(path, parts=None):
            return FakeSG()

    monkeypatch.setattr(cli_main, "ShardedGraph", FakeShardedGraph)
    monkeypatch.setattr(time_mod, "sleep", lambda s: sleeps.append(s))
    sg = cli_main._await_partition_artifact("/nonexistent/p", 4,
                                            timeout_s=300.0, poll_s=2.0)
    assert sg.num_parts == 4
    assert len(sleeps) == 4
    # strictly growing (jitter never shrinks below the base) and capped
    assert sleeps[1] > sleeps[0] and sleeps[2] > sleeps[1]
    assert all(s <= 30.0 * 1.25 for s in sleeps)
    assert "waiting for partition artifact" in capsys.readouterr().out


# ---------------- subprocess chaos (exit codes) -----------------------


def _spawn_cli(tmp_path, extra, timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=2")
    cmd = [sys.executable, "-m", "pipegcn_tpu.cli.main",
           "--dataset", "synthetic:400:6:8:3",
           "--n-partitions", "2", "--n-epochs", "12",
           "--n-hidden", "16", "--dropout", "0.0",
           "--log-every", "50", "--fix-seed", "--seed", "7", "--no-eval",
           "--partition-dir", str(tmp_path / "partitions"),
           "--model-dir", str(tmp_path / "model"),
           "--results-dir", str(tmp_path / "results")] + extra
    return subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout,
                          cwd=os.path.dirname(os.path.dirname(
                              os.path.abspath(__file__))))


def test_cli_preemption_exit_code_subprocess(tmp_path):
    """Acceptance: a fault-injected SIGTERM exits with the distinct
    resumable status (75/EX_TEMPFAIL) after saving a checkpoint; the
    resumed process finishes the schedule and exits 0."""
    ck = str(tmp_path / "ck")
    mfile = str(tmp_path / "metrics.jsonl")
    flags = ["--checkpoint-dir", ck, "--metrics-out", mfile,
             "--fault-plan", "nan-loss@5,sigterm@8",
             "--sentinel-snapshot-every", "3"]
    r1 = _spawn_cli(tmp_path, flags)
    assert r1.returncode == EXIT_PREEMPTED, (r1.stdout, r1.stderr)
    assert "preempted at epoch 8" in r1.stdout
    assert peek_epoch(ck) == 8
    r2 = _spawn_cli(tmp_path, flags + ["--resume", "--skip-partition"])
    assert r2.returncode == 0, (r2.stdout, r2.stderr)
    recs = read_metrics(mfile)
    kinds = [r["kind"] for r in recs if r["event"] == "fault"]
    assert "divergence" in kinds and "preemption" in kinds
    epochs = [r["epoch"] for r in recs if r["event"] == "epoch"]
    assert set(epochs) == set(range(12))


@pytest.mark.slow
def test_cli_real_sigterm_kill_resume_matrix(tmp_path):
    """Chaos: deliver a REAL SIGTERM to a running trainer subprocess,
    assert the resumable exit, then resume and check the completed
    epoch schedule and finite numerics."""
    import signal
    import time

    ck = str(tmp_path / "ck")
    mfile = str(tmp_path / "metrics.jsonl")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=2")
    cmd = [sys.executable, "-m", "pipegcn_tpu.cli.main",
           "--dataset", "synthetic:400:6:8:3",
           "--n-partitions", "2", "--n-epochs", "4000",
           "--n-hidden", "16", "--dropout", "0.0",
           "--log-every", "1000", "--fix-seed", "--seed", "7", "--no-eval",
           "--partition-dir", str(tmp_path / "partitions"),
           "--model-dir", str(tmp_path / "model"),
           "--results-dir", str(tmp_path / "results"),
           "--checkpoint-dir", ck, "--metrics-out", mfile]
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True,
                            cwd=os.path.dirname(os.path.dirname(
                                os.path.abspath(__file__))))
    # wait until epochs are flowing (metrics file grows), then SIGTERM
    deadline = time.time() + 180
    while time.time() < deadline:
        if os.path.exists(mfile) and sum(
                1 for r in read_metrics(mfile)
                if r["event"] == "epoch") >= 5:
            break
        time.sleep(0.5)
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=120)
    assert proc.returncode == EXIT_PREEMPTED, out
    saved = peek_epoch(ck)
    assert saved is not None and saved >= 1
    recs = read_metrics(mfile)
    assert any(r["event"] == "fault" and r["kind"] == "preemption"
               for r in recs)
    # resume for a short remainder
    r2 = _spawn_cli(tmp_path, ["--checkpoint-dir", ck, "--resume",
                               "--skip-partition", "--metrics-out", mfile,
                               "--n-epochs", str(saved + 5)],
                    timeout=300)
    assert r2.returncode == 0, (r2.stdout, r2.stderr)
    epochs = sorted(set(r["epoch"] for r in read_metrics(mfile)
                        if r["event"] == "epoch"))
    assert epochs == list(range(saved + 5))
    losses = [r["loss"] for r in read_metrics(mfile)
              if r["event"] == "epoch"]
    assert np.isfinite(losses).all()
