"""SequentialRunner must reproduce the shard_map Trainer exactly.

The runner re-implements the pipelined step's collectives as host-side
routing (parallel/sequential.py); these tests pin its loss trajectory
against the mesh Trainer — same config, same seeds — which transitively
pins the halo/bgrad routing, the staleness carry, the EMA corrections,
and the host psum against the device implementations.
"""

import numpy as np
import pytest

from pipegcn_tpu.graph import synthetic_graph
from pipegcn_tpu.models import ModelConfig
from pipegcn_tpu.parallel import SequentialRunner, Trainer, TrainConfig
from pipegcn_tpu.partition import ShardedGraph, partition_graph


@pytest.fixture(scope="module")
def sharded():
    g = synthetic_graph(num_nodes=600, avg_degree=8, n_feat=12,
                        n_class=5, seed=3)
    parts = partition_graph(g, 4, seed=0)
    return ShardedGraph.build(g, parts, n_parts=4)


def _cfg(sg, **kw):
    kw.setdefault("dropout", 0.0)
    kw.setdefault("norm", "layer")
    return ModelConfig(layer_sizes=(sg.n_feat, 16, 16, sg.n_class),
                       train_size=sg.n_train_global,
                       spmm_impl="bucket", **kw)


@pytest.mark.parametrize("corr", [False, True])
def test_sequential_matches_trainer(sharded, corr):
    sg = sharded
    cfg = _cfg(sg)
    tcfg = TrainConfig(lr=0.01, n_epochs=5, enable_pipeline=True,
                       feat_corr=corr, grad_corr=corr, eval=False,
                       seed=2)
    tr = Trainer(sg, cfg, tcfg)
    mesh_losses = [tr.train_epoch(e) for e in range(5)]

    run = SequentialRunner(sg, cfg, tcfg)
    seq_losses = [run.run_epoch(e) for e in range(5)]

    # identical math; bf16 rounding + reduction order allow tiny drift
    np.testing.assert_allclose(seq_losses, mesh_losses,
                               rtol=2e-3, atol=2e-3)


def test_sequential_dropout_matches_trainer(sharded):
    """Dropout draws per-rank folded keys; the runner must fold the
    same (epoch, rank) chain as the mesh step."""
    sg = sharded
    cfg = _cfg(sg, dropout=0.5)
    tcfg = TrainConfig(lr=0.01, n_epochs=3, enable_pipeline=True,
                       eval=False, seed=7)
    tr = Trainer(sg, cfg, tcfg)
    mesh_losses = [tr.train_epoch(e) for e in range(3)]
    run = SequentialRunner(sg, cfg, tcfg)
    seq_losses = [run.run_epoch(e) for e in range(3)]
    np.testing.assert_allclose(seq_losses, mesh_losses,
                               rtol=2e-3, atol=2e-3)


def test_sequential_compact_halo_matches_trainer(sharded):
    """The compact per-distance halo layout drops only zero-feature,
    zero-edge pad rows, so with dropout=0 it must reproduce the mesh
    trainer exactly while using fewer halo slots."""
    sg = sharded
    cfg = _cfg(sg)
    tcfg = TrainConfig(lr=0.01, n_epochs=4, enable_pipeline=True,
                       feat_corr=True, grad_corr=True, eval=False,
                       seed=5)
    tr = Trainer(sg, cfg, tcfg)
    mesh_losses = [tr.train_epoch(e) for e in range(4)]
    run = SequentialRunner(sg, cfg, tcfg, compact_halo=True)
    assert run.H <= sg.halo_size
    seq_losses = [run.run_epoch(e) for e in range(4)]
    np.testing.assert_allclose(seq_losses, mesh_losses,
                               rtol=2e-3, atol=2e-3)


def test_sequential_one_shot_matches_epoch0(sharded):
    """keep_carry=False (the single-host full-scale mode) must produce
    the exact epoch-0 loss: staleness buffers are zeros at epoch 0
    whether or not a carry is kept."""
    sg = sharded
    cfg = _cfg(sg)
    tcfg = TrainConfig(lr=0.01, enable_pipeline=True, eval=False, seed=4)
    full = SequentialRunner(sg, cfg, tcfg)
    l_full = full.run_epoch(0)
    oneshot = SequentialRunner(sg, cfg, tcfg, compact_halo=True,
                               keep_carry=False)
    l_one = oneshot.run_epoch(0)
    np.testing.assert_allclose(l_one, l_full, rtol=1e-4, atol=1e-4)


def test_sequential_compact_halo_pad4_artifact():
    """pad_to=4 artifacts can have b_max below the compact layout's
    round-to-8 caps; the caps must clamp to b_max (regression for the
    _compact_send broadcast crash)."""
    # seed 1 chosen so the clamp BINDS: b_max=52, per-distance max
    # counts [49, 50] -> round-to-8 gives 56 > b_max (asserted below
    # so fixture drift can't silently un-bind the regression)
    g = synthetic_graph(num_nodes=300, avg_degree=6, n_feat=8,
                        n_class=4, seed=1)
    parts = partition_graph(g, 3, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=3, pad_to=4)
    raw_caps = np.asarray(sg.send_counts).max(axis=0)
    assert any(-(-int(c) // 8) * 8 > sg.b_max for c in raw_caps), \
        "fixture no longer exercises the cap clamp"
    cfg = ModelConfig(layer_sizes=(8, 12, 4), norm="layer", dropout=0.0,
                      train_size=sg.n_train_global, spmm_impl="bucket")
    tcfg = TrainConfig(lr=0.01, enable_pipeline=True, eval=False, seed=1)
    run = SequentialRunner(sg, cfg, tcfg, compact_halo=True)
    losses = [run.run_epoch(e) for e in range(2)]
    assert all(np.isfinite(losses))


def test_sequential_rejects_unsupported(sharded):
    sg = sharded
    with pytest.raises(ValueError, match="pipelined"):
        SequentialRunner(sg, _cfg(sg),
                         TrainConfig(enable_pipeline=False))
    with pytest.raises(ValueError, match="psum"):
        SequentialRunner(sg, _cfg(sg, norm="batch"),
                         TrainConfig(enable_pipeline=True))
