"""Storage-fault tests (resilience/storage.py + every writer's
io-degraded policy): the FaultyIO shim and grammar, checkpoint-save
degradation (previous generation stays authoritative), the metrics
ring buffer with re-drain, ledger durability, delta-file atomicity,
and the chaos-soak harness (resilience/soak.py).

Everything here is marked `faults` (+ `soak` for the harness tests);
the full subprocess episode is additionally `slow`. The unit tests
never start jax — the shim and the writers are pure host code.
"""

import errno
import io
import json
import os
import time

import numpy as np
import pytest

from pipegcn_tpu.obs import MetricsLogger
from pipegcn_tpu.resilience import FaultPlan
from pipegcn_tpu.resilience.storage import (
    FAULTY_IO,
    IO_KINDS,
    FaultyIO,
    write_text_atomic,
)
from pipegcn_tpu.utils.checkpoint import (
    CheckpointCorrupt,
    disk_preflight,
    latest_checkpoint_path,
    peek_epoch,
    save_checkpoint,
    verify_checkpoint,
)

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _always_disarm():
    """The shim is process-wide: no test may leak an armed fault."""
    yield
    FAULTY_IO.disarm_all()


def _state(v=1.0):
    return {"params": {"w": np.full((4, 3), v, np.float32)},
            "opt": {"t": np.array(7, np.int64)}}


# ---------------- the shim -------------------------------------------


def test_faulty_io_arm_disarm():
    fio = FaultyIO()
    assert fio.armed_kinds() == ()
    fio.arm("enospc")
    fio.arm("slow-fs", ms=5)
    assert fio.active("enospc") and fio.active("slow-fs")
    assert fio.armed_kinds() == ("enospc", "slow-fs")
    assert fio.disarm("enospc") is True
    assert fio.disarm("enospc") is False
    assert fio.disarm_all() == ("slow-fs",)
    with pytest.raises(ValueError, match="unknown IO fault kind"):
        fio.arm("disk-on-fire")


def test_gate_semantics(tmp_path):
    fio = FaultyIO()
    # unarmed: every seam is a no-op
    for op in ("open", "write", "fsync", "rename"):
        fio.gate("x", op)
    fio.arm("ro-dir")
    with pytest.raises(OSError) as ei:
        fio.gate("x", "open")
    assert ei.value.errno == errno.EROFS
    fio.gate("x", "write")  # ro-dir only guards open-for-write
    fio.disarm_all()
    fio.arm("enospc")
    fio.gate("x", "open")  # a full disk still lets you open
    for op in ("write", "fsync"):
        with pytest.raises(OSError) as ei:
            fio.gate("x", op)
        assert ei.value.errno == errno.ENOSPC


def test_slow_fs_sleeps():
    fio = FaultyIO()
    fio.arm("slow-fs", ms=30)
    t0 = time.perf_counter()
    fio.gate("x", "write")
    assert time.perf_counter() - t0 >= 0.025


def test_write_text_atomic_roundtrip_and_torn(tmp_path):
    path = str(tmp_path / "a.json")
    write_text_atomic(path, '{"v": 1}')
    assert json.load(open(path)) == {"v": 1}
    FAULTY_IO.arm("torn-write")
    with pytest.raises(OSError) as ei:
        write_text_atomic(path, '{"v": 2}')
    assert ei.value.errno == errno.EIO
    # the torn write is indistinguishable from an absent one: the
    # destination still holds the PREVIOUS content, no temp remains
    assert json.load(open(path)) == {"v": 1}
    assert os.listdir(tmp_path) == ["a.json"]
    FAULTY_IO.disarm_all()
    write_text_atomic(path, '{"v": 3}', fsync=False)
    assert json.load(open(path)) == {"v": 3}


def test_write_text_atomic_enospc_and_ro_dir(tmp_path):
    path = str(tmp_path / "b.txt")
    for kind, eno in (("enospc", errno.ENOSPC), ("ro-dir", errno.EROFS)):
        FAULTY_IO.arm(kind)
        with pytest.raises(OSError) as ei:
            write_text_atomic(path, "x")
        assert ei.value.errno == eno
        assert not os.path.exists(path)
        FAULTY_IO.disarm_all()


# ---------------- the fault-plan grammar -----------------------------


def test_fault_grammar_io_kinds():
    p = FaultPlan.parse("enospc@4,slow-fs@3:20,torn-write@6,ro-dir@2")
    # remaining() round-trips entries (epoch-sorted), args included
    assert p.remaining() == ["ro-dir@2", "slow-fs@3:20",
                             "enospc@4", "torn-write@6"]
    # due_arg is at-or-after + single-shot, like every boundary kind
    assert p.due_arg("slow-fs", 2) is None
    assert p.due_arg("slow-fs", 5) == 20
    assert p.due_arg("slow-fs", 5) is None
    # argless kinds report 0 when due
    assert p.due_arg("enospc", 4) == 0
    assert p.due_arg("enospc", 4) is None
    # bare numeric args are slow-fs-only: ":20" on any other kind is a
    # typo'd rank/member filter, not a silent no-op
    with pytest.raises(ValueError, match="slow-fs"):
        FaultPlan.parse("enospc@4:20")


def test_io_kinds_retired_on_resume():
    # a resumed run must not re-live an IO window it already outlived
    p = FaultPlan.parse("enospc@4,torn-write@8")
    p.skip_before(6)
    assert p.remaining() == ["torn-write@8"]


# ---------------- checkpoint degradation -----------------------------


def test_checkpoint_enospc_keeps_previous_generation(tmp_path):
    ck = str(tmp_path / "ck")
    save_checkpoint(ck, _state(1.0), 2, keep=0)
    FAULTY_IO.arm("enospc")
    with pytest.raises(OSError) as ei:
        save_checkpoint(ck, _state(2.0), 4, keep=0)
    assert ei.value.errno == errno.ENOSPC
    # the previous generation is untouched and still authoritative
    assert peek_epoch(ck) == 2
    assert verify_checkpoint(latest_checkpoint_path(ck)) == 2
    FAULTY_IO.disarm_all()
    save_checkpoint(ck, _state(2.0), 4, keep=0)
    assert peek_epoch(ck) == 4


def test_checkpoint_torn_write_leaves_destination_absent(tmp_path):
    ck = str(tmp_path / "ck")
    save_checkpoint(ck, _state(1.0), 2, keep=0)
    FAULTY_IO.arm("torn-write")
    with pytest.raises(OSError):
        save_checkpoint(ck, _state(2.0), 4, keep=0)
    FAULTY_IO.disarm_all()
    # torn mid-rename: state-00000004.npz never appeared, and the walk
    # back lands on the intact generation
    assert not os.path.exists(os.path.join(ck, "state-00000004.npz"))
    assert verify_checkpoint(latest_checkpoint_path(ck)) == 2


def test_verify_checkpoint_rejects_corruption(tmp_path):
    from pipegcn_tpu.resilience import corrupt_latest_checkpoint

    ck = str(tmp_path / "ck")
    save_checkpoint(ck, _state(), 2, keep=0)
    path = latest_checkpoint_path(ck)
    assert verify_checkpoint(path) == 2
    corrupt_latest_checkpoint(ck)
    with pytest.raises(CheckpointCorrupt):
        verify_checkpoint(path)


def test_disk_preflight_tight_disk_skips_rotation(tmp_path, monkeypatch):
    import shutil as _shutil

    import pipegcn_tpu.utils.checkpoint as ckpt_mod

    ck = str(tmp_path / "ck")
    for e in (2, 4, 6):
        save_checkpoint(ck, _state(float(e)), e, keep=1)
    # keep=1 pruned the older generations under normal headroom
    assert len([f for f in os.listdir(ck)
                if f.startswith("state-")]) == 1
    assert disk_preflight(ck, _state()) is True
    # simulate a nearly-full volume: preflight warns loudly and the
    # rotation-deletion is skipped (never delete what might be the
    # last good copy when the new write may not land)
    real_usage = _shutil.disk_usage
    monkeypatch.setattr(ckpt_mod.shutil, "disk_usage",
                        lambda p: real_usage(p)._replace(free=1024))
    assert disk_preflight(ck, _state()) is False
    with pytest.warns(UserWarning, match="preflight"):
        save_checkpoint(ck, _state(8.0), 8, keep=1)
    kept = [f for f in os.listdir(ck) if f.startswith("state-")]
    assert len(kept) == 2  # epoch-6 generation NOT rotated away
    assert peek_epoch(ck) == 8


# ---------------- metrics sink degradation ---------------------------


def test_metrics_ring_buffer_degrade_and_redrain(tmp_path):
    path = str(tmp_path / "m.jsonl")
    m = MetricsLogger(path)
    m.fault(kind="injected", epoch=0, reason="warmup")
    FAULTY_IO.arm("enospc")
    with pytest.warns(UserWarning, match="io-degraded") as warned:
        m.fault(kind="injected", epoch=1, reason="one")
        m.fault(kind="injected", epoch=2, reason="two")
    # ONE deduped warning for the whole degraded episode
    assert len([w for w in warned
                if "io-degraded" in str(w.message)]) == 1
    assert m.degraded
    FAULTY_IO.disarm_all()
    m.fault(kind="injected", epoch=3, reason="three")  # triggers drain
    assert not m.degraded
    m.close()
    recs = [json.loads(l) for l in open(path)]
    faults = [r for r in recs if r.get("event") == "fault"]
    # nothing silently lost: the buffered records re-drained in order
    assert [r["reason"] for r in faults] == ["warmup", "one", "two",
                                             "three"]
    rec = [r for r in recs if r.get("event") == "recovery"
           and r.get("kind") == "io-degraded"]
    assert len(rec) == 1 and rec[0]["redrained"] == 2
    assert rec[0]["dropped"] == 0


def test_metrics_close_warns_when_still_degraded(tmp_path):
    path = str(tmp_path / "m.jsonl")
    m = MetricsLogger(path)
    FAULTY_IO.arm("enospc")
    with pytest.warns(UserWarning):
        m.fault(kind="injected", epoch=1, reason="x")
    with pytest.warns(UserWarning, match="lost"):
        m.close()  # still armed: buffered records cannot land
    FAULTY_IO.disarm_all()


def test_metrics_stringio_sink_never_degrades():
    buf = io.StringIO()
    m = MetricsLogger(buf)
    m.fault(kind="injected", epoch=0, reason="x")
    m.hard_flush()  # fileno() raising UnsupportedOperation is benign
    assert not m.degraded
    assert '"injected"' in buf.getvalue()


# ---------------- other durable writers ------------------------------


def test_ledger_append_enospc_keeps_last_durable(tmp_path):
    from pipegcn_tpu.resilience import MembershipLedger, plan_assignment

    led = MembershipLedger(str(tmp_path))
    a = plan_assignment(2, [0])
    led.append(generation=0, members=[0], assignment=a, trigger="start")
    FAULTY_IO.arm("enospc")
    with pytest.raises(OSError):
        led.append(generation=1, members=[0], assignment=a,
                   trigger="restart-all")
    FAULTY_IO.disarm_all()
    # the failed generation never half-landed; the durable one rules
    assert led.generations() == [0]
    assert led.latest()["generation"] == 0
    led.append(generation=1, members=[0], assignment=a,
               trigger="restart-all")
    assert led.generations() == [0, 1]


def test_delta_files_atomic_under_torn_write(tmp_path):
    from pipegcn_tpu.graph import synthetic_graph
    from pipegcn_tpu.graph.synthetic import synthetic_delta_schedule
    from pipegcn_tpu.stream.deltas import load_deltas, save_deltas

    g = synthetic_graph(num_nodes=80, avg_degree=4, n_feat=4, n_class=2,
                        seed=0)
    batches = synthetic_delta_schedule(g, n_batches=1, edges_per_batch=3,
                                       dels_per_batch=1,
                                       nodes_per_batch=0, seed=0)
    for ext in ("jsonl", "npz"):
        path = str(tmp_path / f"d.{ext}")
        save_deltas(path, batches)
        FAULTY_IO.arm("torn-write")
        with pytest.raises(OSError):
            save_deltas(path, batches)
        FAULTY_IO.disarm_all()
        # destination untouched by the torn overwrite: still loads
        assert len(load_deltas(path)) == 1


def test_tuning_sidecar_atomic_under_enospc(tmp_path):
    from pipegcn_tpu.ops.tuner import TUNER_FORMAT, load_tuning, save_tuning

    rec = {"tuner_format": TUNER_FORMAT, "winner": {"impl": "xla"},
           "costs": {}}
    save_tuning(str(tmp_path), rec)
    before, reason = load_tuning(str(tmp_path))
    assert reason is None
    FAULTY_IO.arm("enospc")
    with pytest.raises(OSError):
        save_tuning(str(tmp_path), {"tuner_format": TUNER_FORMAT,
                                    "winner": {"impl": "block"},
                                    "costs": {}})
    FAULTY_IO.disarm_all()
    after, reason = load_tuning(str(tmp_path))
    assert reason is None and after == before


# ---------------- soak harness (resilience/soak.py) ------------------


soak = pytest.mark.soak


@soak
def test_compose_schedule_deterministic_and_constrained():
    from pipegcn_tpu.resilience.soak import (
        SOFT_KINDS,
        TERMINAL_KINDS,
        SoakConfig,
        compose_schedule,
    )

    cfg = SoakConfig(seed=3, episodes=1)
    for ep in range(20):
        sched, stream_epoch = compose_schedule(cfg, ep)
        assert (sched, stream_epoch) == compose_schedule(cfg, ep)
        last_term = 0
        for entry in sched:
            kind, rest = entry.split("@", 1)
            epoch = int(rest.split(":", 1)[0])
            assert kind in TERMINAL_KINDS + SOFT_KINDS
            assert 0 < epoch < cfg.n_epochs
            if kind in TERMINAL_KINDS or kind == "corrupt-ckpt":
                # boundary-kind retirement on resume only stops a
                # re-fire when the fault lands ON a checkpoint boundary
                assert epoch % cfg.checkpoint_every == 0
            if kind in TERMINAL_KINDS:
                last_term = max(last_term, epoch)
        # delta placement is unconstrained now that the WAL journal
        # replays deltas across restart boundaries — only the epoch
        # range is pinned
        assert 0 < stream_epoch < cfg.n_epochs
        FaultPlan.parse(",".join(sched))  # every schedule parses
    forced = SoakConfig(seed=3, force_faults=("enospc@4",))
    assert compose_schedule(forced, 0)[0][0] == "enospc@4"


@soak
def test_soak_invariant_checkers(tmp_path):
    from pipegcn_tpu.resilience.soak import (
        check_checkpoint,
        check_metrics,
    )

    ck = str(tmp_path / "ck")
    save_checkpoint(ck, _state(1.0), 4, keep=0)
    save_checkpoint(ck, _state(2.0), 6, keep=0)
    assert check_checkpoint(ck, want_epoch=6)["ok"]
    assert not check_checkpoint(ck, want_epoch=8)["ok"]
    # a corrupt newest generation walks back to the valid one
    from pipegcn_tpu.resilience import corrupt_latest_checkpoint

    corrupt_latest_checkpoint(ck)
    r = check_checkpoint(ck, want_epoch=4)
    assert r["ok"] and r["epoch"] == 4

    def _ep(e):
        return json.dumps({"event": "epoch", "epoch": e}) + "\n"

    a = tmp_path / "metrics.g0.m0.jsonl"
    a.write_text(_ep(0) + _ep(1) + '{"event": "epo')  # SIGKILL tail
    b = tmp_path / "metrics-resume.jsonl"
    b.write_text(_ep(2) + _ep(3))
    r = check_metrics([str(a), str(b)], 4)
    assert r["ok"] and r["torn_tails"] == 1
    assert not check_metrics([str(a), str(b)], 5)["ok"]  # gap: epoch 4
    c = tmp_path / "bad.jsonl"
    c.write_text('NOT JSON\n' + _ep(0))  # torn NON-tail line is red
    assert not check_metrics([str(c)], 1)["ok"]


@soak
@pytest.mark.slow
def test_soak_episode_end_to_end(tmp_path):
    """One full subprocess episode: the seeded enospc schedule must
    come back green with every invariant checked for real."""
    from pipegcn_tpu.resilience.soak import SoakConfig, run_episode

    cfg = SoakConfig(seed=0, episodes=1,
                     out_dir=str(tmp_path / "soak"),
                     episode_timeout_s=480.0)
    rec = run_episode(cfg, 1)  # seed-0 episode 1 = enospc@1
    assert rec["verdict"] == "green", rec
    assert any(e.startswith("enospc@") for e in rec["schedule"])
    assert rec["invariants"]["checkpoint"]["epoch"] == cfg.n_epochs
