"""Elastic membership tests (resilience/elastic.py + cli/elastic.py):
assignment math, the CRC-guarded membership ledger, the restart policy
(backoff / max-restarts / storm breaker), the kill/rejoin fault-plan
grammar, generation-keyed heartbeats, per-partition carry keying, and
the supervisor loop against a scripted fake fleet.

Everything here is marked `faults` and stays tier-1-cheap except the
crash-loop drill (additionally `slow`): a real cli.elastic subprocess
whose train config SIGKILLs itself every generation, which must stop
at --max-restarts with the last good checkpoint intact.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from pipegcn_tpu.graph import synthetic_graph
from pipegcn_tpu.models import ModelConfig
from pipegcn_tpu.obs import read_metrics, validate_record
from pipegcn_tpu.parallel import Trainer, TrainConfig
from pipegcn_tpu.partition import ShardedGraph, partition_graph
from pipegcn_tpu.resilience import (
    EXIT_PREEMPTED,
    Assignment,
    ElasticConfig,
    ElasticSupervisor,
    FaultPlan,
    HeartbeatWatchdog,
    LedgerCorrupt,
    MembershipLedger,
    RestartPolicy,
    classify_exit,
    plan_assignment,
)
from pipegcn_tpu.resilience.elastic import (
    GENERATION_ENV,
    MEMBER_ENV,
    _member_metrics_path,
)
from pipegcn_tpu.utils.checkpoint import (
    latest_checkpoint_path,
    load_checkpoint_carry,
    peek_epoch,
    save_checkpoint,
)

pytestmark = pytest.mark.faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------- assignment math -------------------------------------


def test_plan_assignment_even_and_ragged():
    a = plan_assignment(4, [0, 1])
    assert (a.parts_per_node, a.n_nodes) == (2, 2)
    assert a.parts_of_node(0) == (0, 1)
    assert a.parts_of_node(1) == (2, 3)
    assert a.active_members() == (0, 1)
    # ragged tail: 5 parts over 3 members -> 2+2+1
    b = plan_assignment(5, [0, 1, 2])
    assert (b.parts_per_node, b.n_nodes) == (2, 3)
    assert b.parts_of_node(2) == (4,)
    # one survivor inherits everything
    c = plan_assignment(4, [3])
    assert (c.parts_per_node, c.n_nodes) == (4, 1)
    assert c.parts_of_node(0) == (0, 1, 2, 3)


def test_plan_assignment_idle_spares_and_errors():
    # more members than ceil-division needs: the tail idles
    a = plan_assignment(3, [10, 11, 12, 13, 14])
    assert (a.parts_per_node, a.n_nodes) == (1, 3)
    assert a.active_members() == (10, 11, 12)
    assert a.node_rank_of(12) == 2
    assert a.node_rank_of(13) is None  # idle this generation
    assert a.as_json()["idle"] == [13, 14]
    # members are dedup'd + sorted (ledger identity, not launch order)
    assert plan_assignment(2, [7, 7, 3]).members == (3, 7)
    with pytest.raises(ValueError, match="zero members"):
        plan_assignment(2, [])
    with pytest.raises(ValueError, match="n_parts"):
        plan_assignment(0, [0])


def test_plan_assignment_always_covers_all_parts():
    """Property: for any (P, R) the active nodes' blocks are a disjoint
    cover of range(P) — the invariant that makes a redistribution safe
    to resume from a full-carry checkpoint."""
    for n_parts in (1, 2, 3, 4, 5, 8):
        for n_members in (1, 2, 3, 4, 5):
            a = plan_assignment(n_parts, range(n_members))
            got = [p for i in range(a.n_nodes)
                   for p in a.parts_of_node(i)]
            assert got == list(range(n_parts)), (n_parts, n_members)
            j = a.as_json()
            assert sorted(sum(j["parts"].values(), [])) == \
                list(range(n_parts))


# ---------------- membership ledger -----------------------------------


def test_ledger_roundtrip_and_monotonic(tmp_path):
    led = MembershipLedger(str(tmp_path))
    assert led.latest_generation() == -1 and led.latest() is None
    a0 = plan_assignment(4, [0, 1])
    led.append(generation=0, members=[0, 1], assignment=a0,
               trigger="start")
    led.append(generation=1, members=[0], assignment=plan_assignment(
        4, [0]), trigger="rank-death", restart_latency_s=2.5)
    assert led.generations() == [0, 1]
    rec = led.read(1)
    assert rec["members"] == [0]
    assert rec["trigger"] == "rank-death"
    assert rec["restart_latency_s"] == pytest.approx(2.5)
    assert rec["assignment"]["parts"] == {"0": [0, 1, 2, 3]}
    assert led.read(0)["restart_latency_s"] is None \
        if "restart_latency_s" in led.read(0) else True
    # monotonic ACROSS ledger objects: the counter lives on disk
    led2 = MembershipLedger(str(tmp_path))
    with pytest.raises(ValueError, match="monotonic"):
        led2.append(generation=1, members=[0], assignment=a0,
                    trigger="start")
    led2.append(generation=5, members=[0], assignment=a0, trigger="x")
    assert led.latest_generation() == 5


def test_ledger_crc_rejects_tamper_and_falls_back(tmp_path):
    led = MembershipLedger(str(tmp_path))
    a = plan_assignment(2, [0, 1])
    led.append(generation=0, members=[0, 1], assignment=a,
               trigger="start")
    led.append(generation=1, members=[0], assignment=a,
               trigger="rank-death")
    # flip a payload byte in gen 1 without touching the stored CRC
    path = led.path_for(1)
    rec = json.load(open(path))
    rec["payload"]["trigger"] = "tampered"
    json.dump(rec, open(path, "w"))
    with pytest.raises(LedgerCorrupt, match="CRC"):
        led.read(1)
    # latest() walks back past the corrupt generation
    assert led.latest()["generation"] == 0
    # unparseable JSON is corrupt too, not a crash
    with open(path, "w") as f:
        f.write("{not json")
    with pytest.raises(LedgerCorrupt):
        led.read(1)


def test_ledger_truncated_generation_walks_back(tmp_path):
    """A half-written membership-<gen>.json (a torn write that somehow
    landed, e.g. a crash in a pre-atomic-writer version, or filesystem
    rot) must read as LedgerCorrupt and walk back — never crash the
    supervisor or resurrect a phantom generation."""
    led = MembershipLedger(str(tmp_path))
    a = plan_assignment(2, [0, 1])
    led.append(generation=0, members=[0, 1], assignment=a,
               trigger="start")
    led.append(generation=1, members=[0], assignment=a,
               trigger="rank-death")
    path = led.path_for(1)
    full = open(path).read()
    with open(path, "w") as f:
        f.write(full[:len(full) // 2])  # truncate mid-record
    with pytest.raises(LedgerCorrupt):
        led.read(1)
    assert led.latest()["generation"] == 0
    # monotonicity still counts the torn file: gen 1 is burned, the
    # next append must go to 2 (a fresh gen-1 could be mistaken for
    # the torn one by a reader holding its path)
    assert led.latest_generation() == 1
    led.append(generation=2, members=[0], assignment=a,
               trigger="restart-all")
    assert led.latest()["generation"] == 2


def test_supervisor_ledger_pending_retries_on_next_event(tmp_path):
    """Satellite: LEDGER WRITE FAILED -> the last durable generation
    stays authoritative, the failed append queues, and the next
    membership event drains the queue in generation order."""
    from pipegcn_tpu.resilience.storage import FAULTY_IO, IO_DEGRADED

    logs = []
    sup = ElasticSupervisor(_train_argv(tmp_path), _fast_cfg(),
                            log=logs.append)
    a = plan_assignment(4, [0, 1])
    sup._record(0, [0, 1], a, "start", None)
    assert sup.ledger.generations() == [0]
    FAULTY_IO.arm("enospc")
    try:
        sup._record(1, [0], a, "rank-death", 1.0)
    finally:
        FAULTY_IO.disarm_all()
    # nothing half-landed; generation 0 is still the durable truth
    assert sup.ledger.generations() == [0]
    assert sup.ledger.latest()["generation"] == 0
    assert any("LEDGER WRITE FAILED" in s for s in logs)
    # disk recovered: the next event drains gen 1 THEN appends gen 2
    sup._record(2, [0], a, "restart-all", None)
    assert sup.ledger.generations() == [0, 1, 2]
    assert sup.ledger.read(1)["trigger"] == "rank-death"
    sup._metrics_logger().close()
    recs = read_metrics(os.path.join(sup.coord_dir, "membership.jsonl"))
    kinds = [(r["event"], r.get("kind")) for r in recs
             if r["event"] in ("fault", "recovery")]
    assert (("fault", IO_DEGRADED) in kinds
            and ("recovery", IO_DEGRADED) in kinds)


def test_ledger_rejoin_requests(tmp_path):
    led = MembershipLedger(str(tmp_path))
    assert led.pending_rejoins() == []
    led.request_rejoin(2)
    led.request_rejoin(0)
    assert led.pending_rejoins() == [0, 2]
    led.clear_rejoin(2)
    led.clear_rejoin(2)  # idempotent
    assert led.pending_rejoins() == [0]


# ---------------- restart policy --------------------------------------


def test_restart_policy_backoff_doubles_and_caps():
    now = [0.0]
    pol = RestartPolicy(max_restarts=100, backoff_base_s=1.0,
                        backoff_max_s=4.0, storm_threshold=100,
                        stable_s=60.0, clock=lambda: now[0])
    delays = []
    for _ in range(5):
        now[0] += 1000.0  # far apart: the storm window never fills
        d = pol.decide()
        assert d.action == "restart"
        delays.append(d.delay_s)
    assert delays == [1.0, 2.0, 4.0, 4.0, 4.0]
    # a stable generation resets the exponent, not the total
    pol.note_stable(120.0)
    now[0] += 1000.0
    assert pol.decide().delay_s == 1.0
    assert pol.total == 6
    # a short-lived generation does NOT reset
    pol.note_stable(5.0)
    now[0] += 1000.0
    assert pol.decide().delay_s == 2.0


def test_restart_policy_max_restarts_and_storm():
    now = [0.0]
    pol = RestartPolicy(max_restarts=2, storm_threshold=100,
                        clock=lambda: now[0])
    for _ in range(2):
        now[0] += 1000.0
        assert pol.decide().action == "restart"
    now[0] += 1000.0
    d = pol.decide()
    assert (d.action, d.reason) == ("stop", "max-restarts")
    # storm breaker: quick successive failures trip below the hard cap
    pol2 = RestartPolicy(max_restarts=100, storm_window_s=60.0,
                         storm_threshold=3, clock=lambda: now[0])
    assert pol2.decide().action == "restart"
    now[0] += 1.0
    assert pol2.decide().action == "restart"
    now[0] += 1.0
    d2 = pol2.decide()
    assert (d2.action, d2.reason) == ("stop", "restart-storm")
    # ...but the same 3 failures spread past the window restart fine
    pol3 = RestartPolicy(max_restarts=100, storm_window_s=60.0,
                         storm_threshold=3, clock=lambda: now[0])
    for _ in range(3):
        now[0] += 100.0
        assert pol3.decide().action == "restart"


def test_classify_exit():
    assert classify_exit(0) == "completed"
    assert classify_exit(EXIT_PREEMPTED) == "resumable"
    assert classify_exit(1) == "dead"
    assert classify_exit(-9) == "dead"  # SIGKILL


# ---------------- kill / rejoin grammar -------------------------------


def test_kill_rejoin_grammar_and_schedule():
    p = FaultPlan.parse("kill@6:r1,rejoin@2,rejoin@3:r5", rank=1)
    # schedule() is the supervisor's NON-consuming all-ranks view
    assert p.schedule("rejoin") == [(2, None), (3, 5)]
    assert p.schedule("rejoin") == [(2, None), (3, 5)]
    # kill is a boundary kind with the at-or-after + single-shot rules
    assert not p.due("kill", 5)
    assert p.due("kill", 6)
    assert not p.due("kill", 6)
    # rank targeting: a :r1 kill is inert on rank 0
    q = FaultPlan.parse("kill@6:r1", rank=0)
    assert not q.due("kill", 100)
    with pytest.raises(ValueError, match="kind@epoch"):
        FaultPlan.parse("kill@@6")


def test_kill_boundary_resume_retirement():
    """kill@E fires at the START of epoch E, so a resume at start_epoch
    >= E retires it; a resume before E keeps it live (the crash-loop
    drill depends on the latter: checkpoint-every 2 + kill@5 resumes at
    epoch 4 and re-fires every generation)."""
    p = FaultPlan.parse("kill@5")
    p.skip_before(5)
    assert p.remaining() == []
    q = FaultPlan.parse("kill@5")
    q.skip_before(4)
    assert q.remaining() == ["kill@5"]


# ---------------- generation-keyed heartbeats -------------------------


def test_heartbeat_files_are_generation_keyed(tmp_path):
    """Stale-heartbeat poisoning fix: a gen-1 watchdog neither reads
    nor writes gen-0 files, so a relaunched fleet can't be tripped by
    ghosts of the previous incarnation (nor keep a dead rank 'alive'
    via its leftover file)."""
    g0 = HeartbeatWatchdog(str(tmp_path), rank=0, n_ranks=2,
                           timeout_s=5.0, generation=0)
    g1 = HeartbeatWatchdog(str(tmp_path), rank=0, n_ranks=2,
                           timeout_s=5.0, generation=1)
    legacy = HeartbeatWatchdog(str(tmp_path), rank=0, n_ranks=2,
                               timeout_s=5.0)
    assert g0.path_for(1).endswith("heartbeat-g0-r1")
    assert g1.path_for(1).endswith("heartbeat-g1-r1")
    assert legacy.path_for(1).endswith("heartbeat-r1")
    assert g0.path_for(1) != g1.path_for(1)
    g0.beat()
    assert os.path.exists(g0.path_for(0))
    assert not os.path.exists(g1.path_for(0))


# ---------------- per-partition carry keying --------------------------


def test_carry_remap_parity(tmp_path):
    """A full-state checkpoint row-slices into ANY partition subset:
    the rows a post-redistribution process loads for its inherited
    partitions are bit-identical to the writer's full carry, and the
    trainer refuses a partial carry at restore (elastic restores must
    go through the full [P, ...] form)."""
    import jax

    g = synthetic_graph(num_nodes=300, avg_degree=6, n_feat=8,
                        n_class=3, seed=2)
    parts = partition_graph(g, 4, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=4)
    cfg = ModelConfig(layer_sizes=(8, 16, 3), dropout=0.0,
                      train_size=sg.n_train_global)
    t = Trainer(sg, cfg, TrainConfig(n_epochs=3, enable_pipeline=True,
                                     log_every=50))
    t.fit(eval_graphs=None, log_fn=lambda s: None)
    hs = t.host_state()
    leaves_full = jax.tree_util.tree_leaves(hs["comm"])
    assert leaves_full and all(l.shape[0] == 4 for l in leaves_full)
    ck = str(tmp_path / "ck")
    save_checkpoint(ck, hs, 3)
    # a survivor that inherits partitions {2, 3} after a membership
    # change slices exactly those rows out of the full checkpoint
    comm23, epoch = load_checkpoint_carry(ck, hs["comm"], [2, 3])
    assert epoch == 3
    for full, sub in zip(leaves_full,
                         jax.tree_util.tree_leaves(comm23)):
        np.testing.assert_array_equal(np.asarray(full)[[2, 3]], sub)
    # the identity slice reproduces the writer's carry bit-for-bit
    comm_all, _ = load_checkpoint_carry(ck, hs["comm"],
                                        list(range(4)))
    for full, sub in zip(leaves_full,
                         jax.tree_util.tree_leaves(comm_all)):
        np.testing.assert_array_equal(np.asarray(full), sub)
    # restore_state validates the full-carry invariant loudly
    assert t.local_partition_ids() == [0, 1, 2, 3]
    partial = dict(hs)
    partial["comm"] = comm23
    with pytest.raises(ValueError, match="full partition count"):
        t.restore_state(partial)
    t.restore_state(hs)  # the full form round-trips


# ---------------- supervisor loop (fake fleet) ------------------------


class _FakeHandle:
    def __init__(self, rc):
        self.returncode = None
        self._rc = rc

    def poll(self):
        self.returncode = self._rc
        return self._rc

    def send_signal(self, sig):
        pass


class _FakeFleet:
    """Scripted popen: hands out exit codes in launch order and records
    every (cmd, env, log_path) the supervisor constructed."""

    def __init__(self, rcs):
        self.rcs = list(rcs)
        self.launches = []

    def popen(self, cmd, env, log_path):
        self.launches.append(
            {"cmd": list(cmd), "env": dict(env), "log": log_path})
        return _FakeHandle(self.rcs.pop(0))


def _train_argv(tmp_path, n_parts=4, ppn=2, extra=()):
    return [
        "--dataset", "synthetic:300:6:8:3",
        "--n-partitions", str(n_parts),
        "--parts-per-node", str(ppn),
        "--n-epochs", "6", "--n-hidden", "8", "--dropout", "0.0",
        "--no-eval", "--fix-seed", "--seed", "7",
        "--partition-dir", str(tmp_path / "parts"),
        "--checkpoint-dir", str(tmp_path / "ck"),
        "--metrics-out", str(tmp_path / "metrics.jsonl"),
        *extra,
    ]


def _fast_cfg(**kw):
    kw.setdefault("backoff_base_s", 0.0)
    kw.setdefault("backoff_max_s", 0.0)
    kw.setdefault("poll_s", 0.01)
    kw.setdefault("storm_threshold", 1000)
    return ElasticConfig(**kw)


def test_supervisor_requires_checkpoint_dir(tmp_path):
    argv = _train_argv(tmp_path)
    argv = [a for i, a in enumerate(argv)
            if argv[i - 1] != "--checkpoint-dir"
            and a != "--checkpoint-dir"]
    with pytest.raises(ValueError, match="--checkpoint-dir"):
        ElasticSupervisor(argv, _fast_cfg())


def test_supervisor_redistributes_after_rank_death(tmp_path):
    """The acceptance loop in miniature: gen 0 launches 2 members over
    4 partitions; member 1 dies (SIGKILL rc) while member 0 exits 75;
    gen 1 relaunches member 0 alone owning all 4 partitions and
    completes. The ledger and membership metrics record both
    generations."""
    # gen 0: member 0 -> 75 (resumable), member 1 -> -9 (dead);
    # gen 1: member 0 -> 0 (completed)
    fleet = _FakeFleet([EXIT_PREEMPTED, -9, 0])
    logs = []
    sup = ElasticSupervisor(_train_argv(tmp_path), _fast_cfg(),
                            popen=fleet.popen, log=logs.append)
    assert sup.run() == 0
    assert len(fleet.launches) == 3

    led = MembershipLedger(sup.coord_dir)
    assert led.generations() == [0, 1]
    g0, g1 = led.read(0), led.read(1)
    assert g0["trigger"] == "start" and g0["members"] == [0, 1]
    assert g0["assignment"]["parts_per_node"] == 2
    assert g1["trigger"] == "rank-death" and g1["members"] == [0]
    assert g1["assignment"]["parts"] == {"0": [0, 1, 2, 3]}
    assert g1["restart_latency_s"] >= 0.0

    # the gen-1 child argv/env reflect the redistribution
    last = fleet.launches[-1]
    cmd = last["cmd"]
    assert cmd[cmd.index("--parts-per-node") + 1] == "4"
    assert cmd[cmd.index("--node-rank") + 1] == "0"
    assert "--resume" not in cmd  # no checkpoint was ever written
    assert last["env"][GENERATION_ENV] == "1"
    assert last["env"][MEMBER_ENV] == "0"
    mo = cmd[cmd.index("--metrics-out") + 1]
    assert mo.endswith(".g1.m0.jsonl")

    # membership metrics mirror the ledger and validate against v6
    recs = [r for r in read_metrics(
        os.path.join(sup.coord_dir, "membership.jsonl"))
        if r.get("event") == "membership"]
    assert [r["generation"] for r in recs] == [0, 1]
    for r in recs:
        validate_record(r)
    assert recs[1]["trigger"] == "rank-death"


def test_supervisor_stops_at_max_restarts(tmp_path):
    """A config that kills every generation must stop resumable at the
    cap, recording the stop in the membership stream — not thrash
    forever."""
    fleet = _FakeFleet([-9] * 10)
    sup = ElasticSupervisor(
        _train_argv(tmp_path, n_parts=2, ppn=2),
        _fast_cfg(max_restarts=2), popen=fleet.popen,
        log=lambda s: None)
    assert sup.run() == EXIT_PREEMPTED
    # gens 0, 1, 2 launched (2 restarts), then the cap stops gen 3
    assert len(fleet.launches) == 3
    led = MembershipLedger(sup.coord_dir)
    assert led.generations() == [0, 1, 2]
    # the sole member dying wholesale is a full-fleet retry
    assert led.read(1)["trigger"] == "restart-all"
    recs = [r for r in read_metrics(
        os.path.join(sup.coord_dir, "membership.jsonl"))
        if r.get("event") == "membership"]
    assert recs[-1]["trigger"] == "max-restarts"
    for r in recs:
        validate_record(r)


def test_supervisor_storm_breaker_stops(tmp_path):
    fleet = _FakeFleet([-9] * 10)
    sup = ElasticSupervisor(
        _train_argv(tmp_path, n_parts=2, ppn=2),
        _fast_cfg(max_restarts=100, storm_threshold=2,
                  storm_window_s=3600.0),
        popen=fleet.popen, log=lambda s: None)
    assert sup.run() == EXIT_PREEMPTED
    assert len(fleet.launches) == 2
    recs = [r for r in read_metrics(
        os.path.join(sup.coord_dir, "membership.jsonl"))
        if r.get("event") == "membership"]
    assert recs[-1]["trigger"] == "restart-storm"


def test_supervisor_resumes_ledger_membership(tmp_path):
    """A restarted supervisor resumes at latest-generation + 1 with the
    last recorded membership — the generation counter lives in the
    ledger filenames, not in any process."""
    coord = str(tmp_path / "parts" / "coord-elastic")
    led = MembershipLedger(coord)
    led.append(generation=0, members=[0, 1], assignment=plan_assignment(
        4, [0, 1]), trigger="start")
    led.append(generation=1, members=[1], assignment=plan_assignment(
        4, [1]), trigger="rank-death")
    fleet = _FakeFleet([0])
    sup = ElasticSupervisor(_train_argv(tmp_path), _fast_cfg(),
                            popen=fleet.popen, log=lambda s: None)
    assert sup.run() == 0
    assert led.generations() == [0, 1, 2]
    g2 = led.read(2)
    assert g2["trigger"] == "supervisor-resume"
    assert g2["members"] == [1]
    assert fleet.launches[0]["env"][GENERATION_ENV] == "2"
    assert fleet.launches[0]["env"][MEMBER_ENV] == "1"


def test_supervisor_folds_in_scheduled_rejoin(tmp_path):
    """rejoin@1:r2 in the fault plan: generation 1's assignment folds
    member 2 back in after a preempt-resume event, rebalancing
    (4 parts over 3 members -> 2 active nodes x 2 parts, member 2
    idle spare)."""
    fleet = _FakeFleet([EXIT_PREEMPTED, EXIT_PREEMPTED, 0, 0])
    sup = ElasticSupervisor(
        _train_argv(tmp_path, extra=("--fault-plan", "rejoin@1:r2")),
        _fast_cfg(), popen=fleet.popen, log=lambda s: None)
    assert sup.run() == 0
    led = MembershipLedger(sup.coord_dir)
    g1 = led.read(1)
    assert g1["trigger"] == "rejoin"
    assert g1["members"] == [0, 1, 2]
    assert g1["assignment"]["idle"] == [2]
    assert len(fleet.launches) == 4  # 2 + 2 (spare stays unlaunched)


def test_supervisor_ledger_rejoin_request(tmp_path):
    """A returning rank's on-disk rejoin-r<k>.json request is consumed
    at the next membership event and cleared."""
    coord = str(tmp_path / "parts" / "coord-elastic")
    MembershipLedger(coord).request_rejoin(7)
    fleet = _FakeFleet([EXIT_PREEMPTED, EXIT_PREEMPTED, 0, 0])
    sup = ElasticSupervisor(_train_argv(tmp_path), _fast_cfg(),
                            popen=fleet.popen, log=lambda s: None)
    assert sup.run() == 0
    led = MembershipLedger(coord)
    assert led.read(1)["members"] == [0, 1, 7]
    assert led.pending_rejoins() == []


def test_supervisor_clears_stale_heartbeats(tmp_path):
    """Launch hygiene half of the poisoning fix: heartbeat files from a
    previous incarnation are unlinked before every generation."""
    coord = str(tmp_path / "parts" / "coord-elastic")
    os.makedirs(coord, exist_ok=True)
    stale = os.path.join(coord, "heartbeat-r1")
    open(stale, "w").close()
    fleet = _FakeFleet([0, 0])
    sup = ElasticSupervisor(_train_argv(tmp_path), _fast_cfg(),
                            popen=fleet.popen, log=lambda s: None)
    assert sup.run() == 0
    assert not os.path.exists(stale)


def test_member_metrics_path_naming():
    assert _member_metrics_path("/x/m.jsonl", 2, 1) == "/x/m.g2.m1.jsonl"
    assert _member_metrics_path("/x/m", 0, 3) == "/x/m.g0.m3.jsonl"


def test_elastic_cli_requires_separator(capsys):
    from pipegcn_tpu.cli.elastic import main as elastic_main

    assert elastic_main(["--max-restarts", "3"]) == 2
    assert "--" in capsys.readouterr().err


# ---------------- crash-loop drill (subprocess, slow) ------------------


@pytest.mark.slow
def test_crash_loop_stops_at_max_restarts_with_checkpoint(tmp_path):
    """Acceptance: a crash-looping config (kill@5 with checkpoint-every
    2: the resume restarts at epoch 4 < 5, so the kill re-fires every
    generation) stops at --max-restarts with rc 75, a clean resumable
    epoch-4 checkpoint, and a ledger recording every generation."""
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "PYTHONPATH": REPO,
        "PYTHONUNBUFFERED": "1",
    }
    ck = str(tmp_path / "ck")
    cmd = [
        sys.executable, "-m", "pipegcn_tpu.cli.elastic",
        "--max-restarts", "2", "--backoff-base", "0.1",
        "--metrics-out", str(tmp_path / "sup.jsonl"),
        "--",
        "--dataset", "synthetic:300:6:8:3",
        "--n-partitions", "2", "--parts-per-node", "2",
        "--n-epochs", "10", "--n-hidden", "8", "--dropout", "0.0",
        "--log-every", "1000", "--fix-seed", "--seed", "7", "--no-eval",
        "--partition-dir", str(tmp_path / "parts"),
        "--checkpoint-dir", ck, "--checkpoint-every", "2",
        "--fault-plan", "kill@5",
        "--metrics-out", str(tmp_path / "metrics.jsonl"),
    ]
    proc = subprocess.run(cmd, env=env, cwd=REPO, timeout=560,
                          capture_output=True, text=True)
    tail = (proc.stdout + proc.stderr)[-3000:]
    assert proc.returncode == EXIT_PREEMPTED, tail
    # gen 0 + 2 restarts, then the cap; the checkpoint survives at the
    # last boundary the kill allows (epoch 4)
    coord = str(tmp_path / "parts" / "coord-elastic")
    led = MembershipLedger(coord)
    assert led.generations() == [0, 1, 2], tail
    assert led.read(0)["trigger"] == "start"
    assert led.read(1)["trigger"] == "restart-all"
    assert latest_checkpoint_path(ck) is not None
    assert peek_epoch(ck) == 4
    recs = [r for r in read_metrics(tmp_path / "sup.jsonl")
            if r.get("event") == "membership"]
    assert recs[-1]["trigger"] == "max-restarts"
    assert "max-restarts" in tail
