"""Deep performance observability (docs/OBSERVABILITY.md):

  - profiling windows: a REAL jax.profiler trace captured on the CPU
    mesh during fit(), folded against the compiled step's HLO into
    measured per-phase device time + a comm/compute overlap fraction;
  - staleness probes: per-layer relative drift between the stale halo
    features the pipelined step consumed and the fresh ones it shipped;
  - epoch anatomy: per-phase FLOP/byte attribution of the compiled
    step (>= 90% of FLOPs must land in named phases);
  - cross-rank timeline CLI: two ranks' metrics JSONL merged into one
    structurally-valid Chrome-trace file;
  - report CLI: measured vs estimated overlap side by side + the
    pinned --json shape;
  - flush-on-death: the final fault record survives an os._exit(75)
    (subprocess-proven);
  - TPU-window preflight: entries with missing artifacts are skipped
    loudly instead of burning window time.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from pipegcn_tpu.cli.parser import create_parser
from pipegcn_tpu.cli.report import main as report_main
from pipegcn_tpu.cli.timeline import main as timeline_main
from pipegcn_tpu.obs import MetricsLogger, read_metrics, validate_record
from pipegcn_tpu.obs.profiler import (
    classify_op,
    fold_trace,
    hlo_op_map,
    parse_profile_epochs,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------- pure parser units ---------------------------------------

def test_parse_profile_epochs():
    assert parse_profile_epochs("1:3") == (1, 3)
    assert parse_profile_epochs(" 10:20 ") == (10, 20)
    with pytest.raises(ValueError, match="A:B"):
        parse_profile_epochs("3")
    with pytest.raises(ValueError, match="empty"):
        parse_profile_epochs("5:5")


def test_classify_op_phases():
    assert classify_op("jit(step)/layer0/spmm/dot_general") == "spmm"
    assert classify_op("jit(step)/layer1/dense/dot_general") == "dense"
    assert classify_op("jit(step)/layer0/halo_exchange/ppermute") \
        == "halo_comm"
    assert classify_op("transpose(jvp(f))/layer0/bgrad_return/x") \
        == "halo_comm"
    assert classify_op("jit(step)/grad_reduce/psum") == "grad_reduce"
    assert classify_op("jit(step)/adam_update/mul") == "optimizer"
    assert classify_op("jit(step)/layer0/dropout/threefry") \
        == "dropout_rng"
    assert classify_op("", "collective-permute") == "halo_comm"
    assert classify_op("jit(step)/something_else/add") == "other"


def test_fold_trace_overlap_math():
    """Synthetic timeline: comm [0, 10] with compute covering [0, 6] on
    the same pid -> 60% overlap; phases fold by classified scope."""
    op_map = {"cp.1": ("jit(s)/layer0/halo_exchange/ppermute",
                       "collective-permute"),
              "dot.1": ("jit(s)/layer0/spmm/dot_general", "dot"),
              "dot.2": ("jit(s)/layer0/dense/dot_general", "dot")}
    events = [
        {"ph": "X", "pid": 1, "tid": 7, "ts": 0.0, "dur": 10.0,
         "name": "cp.1", "args": {"hlo_op": "cp.1"}},
        {"ph": "X", "pid": 1, "tid": 8, "ts": 0.0, "dur": 4.0,
         "name": "dot.1", "args": {"hlo_op": "dot.1"}},
        {"ph": "X", "pid": 1, "tid": 9, "ts": 4.0, "dur": 2.0,
         "name": "dot.2", "args": {"hlo_op": "dot.2"}},
        # a different pid's compute must NOT count toward pid 1's comm
        {"ph": "X", "pid": 2, "tid": 1, "ts": 0.0, "dur": 100.0,
         "name": "dot.1", "args": {"hlo_op": "dot.1"}},
    ]
    out = fold_trace(events, op_map)
    assert out["overlap_fraction"] == pytest.approx(0.6)
    assert out["comm_s"] == pytest.approx(10.0 / 1e6)
    assert out["phases"]["halo_comm"] == pytest.approx(10.0 / 1e6)
    assert out["phases"]["spmm"] == pytest.approx(104.0 / 1e6)
    assert out["phases"]["dense"] == pytest.approx(2.0 / 1e6)
    assert out["n_device_events"] == 4


def test_hlo_op_map_parses_metadata():
    txt = (
        'HloModule jit_step, entry_computation_layout={()->f32[2]}\n\n'
        'ENTRY %main.5 () -> f32[2] {\n'
        '  %dot.1 = f32[2]{0} dot(f32[2,3]{1,0} %a, f32[3]{0} %b), '
        'lhs_contracting_dims={1}, rhs_contracting_dims={0}, '
        'metadata={op_name="jit(step)/layer0/spmm/dot_general" '
        'source_file="x.py" source_line=1}\n'
        '  ROOT %cp.2 = f32[2]{0} collective-permute(f32[2]{0} %dot.1), '
        'metadata={op_name="jit(step)/layer0/halo_exchange/ppermute"}\n'
        '}\n')
    m = hlo_op_map(txt)
    assert m["dot.1"] == ("jit(step)/layer0/spmm/dot_general", "dot")
    assert m["cp.2"][1] == "collective-permute"
    from pipegcn_tpu.obs.profiler import module_name
    assert module_name(txt) == "jit_step"


# ---------------- end-to-end CPU-mesh smoke (the acceptance gate) ---------

def _cli_args(tmp_path, extra):
    base = [
        "--dataset", "synthetic:600:8:16:4",
        "--n-partitions", "4",
        "--n-epochs", "2",
        "--n-layers", "2",
        "--n-hidden", "32",
        "--dropout", "0.2",
        "--log-every", "5",
        "--fix-seed", "--seed", "7",
        "--no-eval",
        "--partition-dir", str(tmp_path / "partitions"),
        "--model-dir", str(tmp_path / "model"),
        "--results-dir", str(tmp_path / "results"),
    ]
    return create_parser().parse_args(base + extra)


@pytest.fixture(scope="module")
def profiled_run(tmp_path_factory):
    """One pipelined 2-epoch CLI run capturing a REAL jax.profiler
    trace over epochs [1, 2) with staleness probes every epoch and an
    anatomy record — shared by the record-content, report-CLI and
    timeline tests below."""
    from pipegcn_tpu.cli.main import run

    tmp_path = tmp_path_factory.mktemp("profiled")
    mpath = tmp_path / "metrics.jsonl"
    args = _cli_args(tmp_path, [
        "--enable-pipeline",
        "--metrics-out", str(mpath),
        "--profile-dir", str(tmp_path / "trace"),
        "--profile-epochs", "1:2",
        "--staleness-probe-every", "1",
        "--anatomy",
    ])
    res = run(args)
    return tmp_path, mpath, res


@pytest.mark.profile
def test_profile_smoke_all_record_kinds(profiled_run):
    """The tier-1 acceptance gate: a 2-epoch CPU-mesh fit with
    --profile-epochs 1:2 + --staleness-probe-every 1 + --anatomy emits
    every new record kind, schema-valid."""
    tmp_path, mpath, _ = profiled_run
    recs = read_metrics(mpath)
    for r in recs:
        validate_record(r)
    kinds = {r["event"] for r in recs}
    assert {"run", "epoch", "summary",
            "profile", "anatomy", "staleness"} <= kinds
    # the trace really hit the disk in TensorBoard layout
    sessions = os.listdir(os.path.join(tmp_path, "trace", "plugins",
                                       "profile"))
    assert sessions


@pytest.mark.profile
def test_profile_record_measures_overlap(profiled_run):
    """The profile record carries a measured overlap fraction in
    [0, 1], a phase decomposition with real device time in the comm
    phases (P=4 -> halo collectives exist), and the capture window."""
    _, mpath, res = profiled_run
    profs = [r for r in read_metrics(mpath) if r["event"] == "profile"]
    assert len(profs) == 1
    p = profs[0]
    assert 0.0 <= p["overlap_fraction"] <= 1.0
    assert p["comm_s"] > 0          # P=4: collective-permutes ran
    assert p["compute_s"] > 0
    assert p["phases"].get("halo_comm", 0) > 0
    assert sum(p["phases"].values()) == pytest.approx(
        p["comm_s"] + p["compute_s"], rel=1e-6)
    assert (p["epoch_start"], p["epoch_end"]) == (1, 2)
    assert p["n_matched_events"] > 0
    # the same record rides the fit result
    assert res is not None


@pytest.mark.profile
def test_staleness_records_per_layer_drift(profiled_run):
    """Probe epochs log per-layer relative drift: 1.0 at epoch 0 (the
    carry is zeros, drift is total) and a finite value once warm."""
    _, mpath, _ = profiled_run
    stale = [r for r in read_metrics(mpath)
             if r["event"] == "staleness"]
    by_epoch = {r["epoch"]: r for r in stale}
    assert set(by_epoch) == {0, 1}
    for r in stale:
        assert set(r["layers"]) == {"0", "1"}  # both graph layers
        for v in r["layers"].values():
            assert np.isfinite(v["rel_drift"])
            assert v["rel_drift"] >= 0
        assert r["max_rel_drift"] == pytest.approx(
            max(v["rel_drift"] for v in r["layers"].values()))
    assert by_epoch[0]["max_rel_drift"] == pytest.approx(1.0)
    assert 0.0 < by_epoch[1]["max_rel_drift"] < 10.0


@pytest.mark.profile
def test_anatomy_attributes_flops(profiled_run):
    """>= 90% of the compiled step's estimated FLOPs land in a named
    (non-'other') phase, and the spmm+dense phases dominate."""
    _, mpath, _ = profiled_run
    recs = [r for r in read_metrics(mpath) if r["event"] == "anatomy"]
    assert len(recs) == 1
    a = recs[0]
    assert a["attributed_flops_fraction"] >= 0.90
    assert a["est_flops"] > 0
    ph = a["phases"]
    assert ph["dense"]["flops"] > 0 and ph["spmm"]["flops"] > 0
    # XLA's own total rides along on backends that expose it
    assert a["flops"] is None or a["flops"] > 0


@pytest.mark.profile
def test_report_prints_measured_vs_estimated(profiled_run, capsys):
    _, mpath, _ = profiled_run
    assert report_main([str(mpath)]) == 0
    out = capsys.readouterr().out
    assert "overlap (measured)" in out
    assert "staleness rel drift" in out
    assert "anatomy flop shares" in out


def test_report_json_shape_pinned(profiled_run, capsys):
    """The --json summary is a single JSON object whose key set is a
    consumable contract for benches/CI: pin the core keys."""
    _, mpath, _ = profiled_run
    assert report_main([str(mpath), "--json"]) == 0
    s = json.loads(capsys.readouterr().out)
    required = {
        "file", "n_epoch_records", "n_eval_records", "schema_version",
        "device", "n_devices", "pipeline", "median_epoch_s",
        "loss_first", "loss_last", "loss_delta", "grad_norm_last",
        "halo_bytes_per_epoch", "staleness_age_max",
        "measured_overlap_fraction", "profile_phases", "profile_comm_s",
        "profile_compute_s", "profile_window",
        "staleness_probes", "staleness_max_rel_drift",
        "staleness_last_rel_drift",
        "anatomy_attributed_flops_fraction", "anatomy_flop_shares",
        "n_epochs", "best_val",
    }
    missing = required - set(s)
    assert not missing, f"--json summary lost keys: {sorted(missing)}"
    assert 0.0 <= s["measured_overlap_fraction"] <= 1.0
    assert s["staleness_probes"] == 2
    # estimated + measured exist together -> the divergence verdict too
    if "overlapped_comm_fraction" in s or "comm_fraction" in s:
        assert "overlap_divergence" in s


# ---------------- timeline CLI --------------------------------------------

def _write_rank_jsonl(path, rank, n_epochs=4, fault_at=None):
    with MetricsLogger(path) as ml:
        ml.run_header(config={}, device={}, mesh={"n_parts": 2})
        for e in range(n_epochs):
            rec = {"event": "epoch", "epoch": e,
                   "step_time_s": 0.5 + 0.05 * rank,
                   "loss": 1.0 - 0.1 * e, "grad_norm": 0.5,
                   "halo_bytes": 64, "staleness_age": int(e > 0),
                   "memory": None, "rank": rank}
            ml.write(rec)  # no time_unix: exercises dispatch alignment
        if fault_at is not None:
            ml.fault(kind="divergence", epoch=fault_at, rank=rank,
                     reason="synthetic")
            ml.recovery(kind="divergence", epoch=fault_at + 1,
                        rank=rank)
        ml.staleness(epoch=2, layers={"0": {"rel_drift": 0.4,
                                            "fresh_norm": 2.0}},
                     max_rel_drift=0.4, rank=rank)
        ml.profile(phases={"spmm": 0.3, "halo_comm": 0.1}, comm_s=0.1,
                   compute_s=0.4, overlap_fraction=0.75,
                   epoch_start=1, epoch_end=3, rank=rank)


def test_timeline_merges_two_ranks_chrome_valid(tmp_path, capsys):
    """Two synthetic rank streams -> one structurally-valid Chrome
    trace: sorted ts, X events with numeric dur >= 0, both ranks as
    processes, faults as instants, profile spans inside the window."""
    r0 = tmp_path / "r0.jsonl"
    r1 = tmp_path / "r1.jsonl"
    _write_rank_jsonl(r0, 0, fault_at=2)
    _write_rank_jsonl(r1, 1)
    out = tmp_path / "trace.json"
    assert timeline_main([str(r0), str(r1), "--out", str(out)]) == 0
    obj = json.load(open(out))
    assert set(obj) >= {"traceEvents", "displayTimeUnit"}
    evs = obj["traceEvents"]
    assert isinstance(evs, list) and evs
    meta = [e for e in evs if e.get("ph") == "M"]
    body = [e for e in evs if e.get("ph") != "M"]
    # both ranks present as named processes
    pnames = {e["args"]["name"] for e in meta
              if e.get("name") == "process_name"}
    assert pnames == {"rank 0", "rank 1"}
    # structural validity (the chrome://tracing loader's hard rules)
    last_ts = -1.0
    for e in body:
        assert e.get("ph") in ("X", "i", "C")
        assert isinstance(e.get("ts"), (int, float)) and e["ts"] >= 0
        if e["ph"] == "X":
            assert isinstance(e.get("dur"), (int, float))
            assert e["dur"] >= 0
        assert e["ts"] >= last_ts
        last_ts = e["ts"]
    assert {e["pid"] for e in body} == {0, 1}
    # epochs aligned at dispatch boundaries: both ranks' epoch 1 starts
    # at the same ts (the slower rank sets the boundary)
    e1 = [e for e in body if e.get("name") == "epoch 1"]
    assert len(e1) == 2
    assert e1[0]["ts"] == pytest.approx(e1[1]["ts"])
    # fault instant + profile spans made it
    assert any(e["ph"] == "i" and "fault" in e["name"] for e in body)
    assert any(e.get("tid") == 2 and e["ph"] == "X" for e in body)


def test_timeline_cli_rank_override_and_errors(tmp_path, capsys):
    r0 = tmp_path / "a.jsonl"
    _write_rank_jsonl(r0, 0)
    out = tmp_path / "t.json"
    assert timeline_main([str(r0), "--ranks", "5",
                          "--out", str(out)]) == 0
    obj = json.load(open(out))
    names = {e["args"]["name"] for e in obj["traceEvents"]
             if e.get("name") == "process_name"}
    assert names == {"rank 5"}
    capsys.readouterr()
    assert timeline_main([str(r0), "--ranks", "1,2",
                          "--out", str(out)]) == 2
    assert timeline_main([str(tmp_path / "nope.jsonl")]) == 1


# ---------------- flush-on-death ------------------------------------------

def test_fault_record_survives_hard_exit(tmp_path):
    """PR 3's watchdog exits via os._exit(75), which skips atexit and
    io teardown: the final fault record explaining the death must
    already be fsynced to disk when the process dies."""
    mpath = tmp_path / "death.jsonl"
    code = (
        "import os, sys\n"
        "sys.path.insert(0, {repo!r})\n"
        "from pipegcn_tpu.obs import MetricsLogger\n"
        "ml = MetricsLogger({path!r})\n"
        "ml.run_header(config={{}}, device={{}}, mesh={{}})\n"
        "ml.fault(kind='peer-lost', epoch=7, rank=0, peer_rank=1,\n"
        "         silent_s=61.0, hard_deadline=True)\n"
        "os._exit(75)\n"
    ).format(repo=REPO, path=str(mpath))
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, timeout=120)
    assert r.returncode == 75, r.stderr.decode()
    recs = read_metrics(mpath)
    faults = [x for x in recs if x["event"] == "fault"]
    assert len(faults) == 1
    assert faults[0]["kind"] == "peer-lost"
    assert faults[0]["epoch"] == 7
    for x in recs:
        validate_record(x)


def test_hard_flush_tolerates_stringio():
    import io

    ml = MetricsLogger(io.StringIO())
    ml.fault(kind="divergence", epoch=1, rank=0)  # auto hard_flush
    ml.hard_flush()  # explicit call: no fileno -> still fine


# ---------------- TPU-window preflight ------------------------------------

def _load_tpu_window():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "tpu_window", os.path.join(REPO, "scripts", "tpu_window.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_window_preflight_skips_missing_artifacts(tmp_path):
    """Dry-run against an emptied partitions/: entries that declare the
    bench artifact are skipped; self-building entries stay runnable."""
    tw = _load_tpu_window()
    repo = str(tmp_path)
    os.makedirs(os.path.join(repo, "partitions"))  # empty
    queue = [
        ("needs_part", ["x"], 10, ["partitions/bench-reddit-1-c2-s1024"]),
        ("self_building", ["y"], 10, []),
        ("glob_ok", ["z"], 10, ["partitions/*"]),
    ]
    skipped = tw.preflight_queue(queue, repo=repo)
    assert set(skipped) == {"needs_part", "glob_ok"}
    assert skipped["needs_part"] == ["partitions/bench-reddit-1-c2-s1024"]
    # the artifact appearing flips the verdict
    os.makedirs(os.path.join(repo, "partitions",
                             "bench-reddit-1-c2-s1024"))
    assert tw.preflight_queue(queue, repo=repo) == {}


def test_window_queue_declares_requirements():
    """The real queue's Reddit-shape probes must declare the bench
    artifact (the two burned windows the preflight exists to prevent);
    every entry is a 4-tuple."""
    tw = _load_tpu_window()
    by_name = {name: req for name, _, _, req in tw.QUEUE}
    for step in ("epoch_anatomy", "rem_probe", "spmm_tune",
                 "bench_auto_tuned"):
        assert tw._BENCH_PART in by_name[step]
    # the round-4/5 gaters keep first claim on the window, and the
    # on-chip tuner warm runs before the auto-dispatch bench
    order = [name for name, _, _, _ in tw.QUEUE]
    assert order.index("epoch_anatomy") < order.index("spmm_tune")
    assert order.index("rem_probe") < order.index("spmm_tune")
    assert order.index("spmm_tune") < order.index("bench_auto_tuned")
