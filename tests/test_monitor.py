"""Live telemetry plane tests (obs/live.py + obs/health.py +
cli/monitor.py + obs/trend.py, docs/OBSERVABILITY.md "Live
monitoring"):

  - stream discovery over run directories / stems / per-generation
    elastic files, and the deduped generation-ordered merge the report
    CLI shares;
  - TailReader torn-final-line tolerance, malformed-line counting, and
    truncation rewind;
  - LiveAggregator tail-follow across files that appear mid-run;
  - AlertEngine edge-triggering under a fake clock: fire once, stay
    silent while red, resolve once (the replica-dead + epoch-time
    drill);
  - span lifecycle conservation through the MicroBatcher (exactly one
    terminal span per sampled submit) and the timeline's Perfetto flow
    stitching;
  - /metrics scrape parity against the JSONL-derived values;
  - bench trend regression flags on a synthetic worsening series and a
    smoke pass over the repo's real BENCH artifacts.

Everything here is host-side and jax-free except nothing — the marker
is `live` (scripts/chaos.sh monitor lane)."""

import glob
import json
import os
import time
import urllib.request

import numpy as np
import pytest

from pipegcn_tpu.obs.health import (
    AlertEngine,
    MonitorServer,
    health_json,
    load_rules,
    prometheus_text,
)
from pipegcn_tpu.obs.live import (
    LiveAggregator,
    TailReader,
    discover_streams,
    merge_streams,
    read_stream,
)
from pipegcn_tpu.obs.metrics import MetricsLogger, read_metrics
from pipegcn_tpu.obs.trend import format_trend, load_series, trend

pytestmark = pytest.mark.live

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_epochs(ml, n, t0=0, step=0.1, src_extra=None):
    for e in range(t0, t0 + n):
        ml.write({"event": "epoch", "epoch": e, "loss": 1.0 - 0.01 * e,
                  "grad_norm": 0.5, "step_time_s": step,
                  "halo_bytes": 1000, "staleness_age": 1,
                  "memory": None, "time_unix": time.time(),
                  **(src_extra or {})})


def _run_header(ml):
    ml.write({"event": "run", "schema_version": 10, "config": {},
              "device": {}, "mesh": {}, "time_unix": time.time()})


# ---------------- discovery + merge ----------------------------------------


def test_discover_streams_stem_and_dir(tmp_path):
    d = tmp_path / "run"
    d.mkdir()
    (d / "train.jsonl").write_text('{"event": "bench"}\n')
    (d / "train.g0.m0.jsonl").write_text('{"event": "bench", "g": 0}\n')
    (d / "train.g1.m0.jsonl").write_text('{"event": "bench", "g": 1}\n')
    (d / "membership.jsonl").write_text('{"event": "bench", "m": 1}\n')
    (d / "notes.txt").write_text("not a stream\n")

    # stem target: base + per-generation files + the ledger beside them
    got = discover_streams(str(d / "train"))
    assert [os.path.basename(p) for p in got] == [
        "membership.jsonl", "train.jsonl", "train.g0.m0.jsonl",
        "train.g1.m0.jsonl"]
    # the .jsonl spelling of the stem finds the same set
    assert discover_streams(str(d / "train.jsonl")) == got
    # directory target: everything, recursively
    (d / "sub").mkdir()
    (d / "sub" / "replica-m0-i0-metrics.jsonl").write_text(
        '{"event": "bench", "r": 0}\n')
    got_dir = discover_streams(str(d))
    assert len(got_dir) == 5
    # a plain file with no per-generation siblings is just itself
    lone = tmp_path / "lone.jsonl"
    lone.write_text('{"event": "bench"}\n')
    assert discover_streams(str(lone)) == [str(lone)]
    # a typo'd stem matches nothing (and adopts no unrelated ledger)
    assert discover_streams(str(d / "nope")) == []


def test_merge_streams_dedups_and_orders(tmp_path):
    a = tmp_path / "t.jsonl"
    b = tmp_path / "t.g0.m0.jsonl"
    c = tmp_path / "t.g1.m0.jsonl"
    a.write_text('{"event": "bench", "n": 0}\n')
    # the run header duplicated into a per-generation file folds to one
    b.write_text('{"event": "bench", "n": 0}\n'
                 '{"event": "bench", "n": 1}\n')
    c.write_text('{"event": "bench", "n": 2}\n')
    recs = merge_streams([str(c), str(b), str(a)])
    assert [r["n"] for r in recs] == [0, 1, 2]


def test_tail_reader_torn_lines_and_truncation(tmp_path):
    p = tmp_path / "s.jsonl"
    p.write_text('{"event": "bench", "n": 0}\n{"event": "bench", "n"')
    r = TailReader(str(p))
    # the torn tail is invisible until its newline lands
    assert [x["n"] for x in r.poll()] == [0]
    assert r.poll() == []
    with open(p, "a") as f:
        f.write(': 1}\nnot json\n{"event": "bench", "n": 2}\n')
    assert [x["n"] for x in r.poll()] == [1, 2]
    assert r.n_malformed == 1
    # truncation rewinds to the start
    p.write_text('{"event": "bench", "n": 9}\n')
    assert [x["n"] for x in r.poll()] == [9]
    # final=True consumes a parseable unterminated tail (one-shot mode)
    p2 = tmp_path / "t.jsonl"
    p2.write_text('{"event": "bench", "n": 0}\n{"event": "bench", "n": 1}')
    assert [x["n"] for x in read_stream(str(p2))] == [0, 1]


def test_aggregator_follows_appearing_files(tmp_path):
    d = tmp_path / "run"
    d.mkdir()
    agg = LiveAggregator(str(d))
    assert agg.poll() == 0
    with MetricsLogger(d / "train.g0.m0.jsonl") as ml:
        _run_header(ml)
        _write_epochs(ml, 3)
    assert agg.poll() == 4
    # a new generation appears mid-run and joins the tail set live
    with MetricsLogger(d / "train.g1.m0.jsonl") as ml:
        _write_epochs(ml, 2, t0=3)
        ml.fault("rank-death", epoch=4, rank=0)
    assert agg.poll() == 3
    assert agg.poll() == 0
    snap = agg.snapshot()
    assert snap["n_streams"] == 2
    assert snap["n_records"] == 7
    assert snap["schema_version"] == 10
    assert agg.fault_counts == {"rank-death": 1}
    assert agg.latest("epoch")["train.g1.m0"]["epoch"] == 4
    # an invalid record is counted, kept out of state, never fatal
    with open(d / "train.g1.m0.jsonl", "a") as f:
        f.write('{"event": "epoch", "epoch": 99}\n')
    agg.poll()
    assert agg.n_invalid == 1
    assert agg.latest("epoch")["train.g1.m0"]["epoch"] == 4


# ---------------- alert engine ---------------------------------------------


def test_alert_rules_load_and_reject_typos(tmp_path):
    rules = load_rules(None)
    assert [r["rule"] for r in rules] == [
        "epoch-time-regression", "shed-rate", "staleness-age",
        "fault-rate", "silent-source", "straggler-skew"]
    p = tmp_path / "rules.json"
    p.write_text(json.dumps([
        {"rule": "epoch-time-regression", "factor": 2.0},
        {"rule": "fault-rate", "kind": "rank-death", "threshold": 2},
    ]))
    rules = load_rules(str(p))
    assert rules[0]["factor"] == 2.0
    assert rules[0]["min_points"] == 5  # default survives
    assert rules[1]["kind"] == "rank-death"
    p.write_text(json.dumps([{"rule": "epoch-time-regresion"}]))
    with pytest.raises(ValueError, match="unknown alert rule"):
        load_rules(str(p))
    p.write_text(json.dumps([{"rule": "shed-rate", "treshold": 0.5}]))
    with pytest.raises(ValueError, match="unknown parameter"):
        load_rules(str(p))


def test_epoch_time_alert_fires_and_resolves_exactly_once(tmp_path):
    """The drill the chaos monitor lane scripts: a step-time spike
    fires epoch-time-regression ONCE, stays silent while red, and
    resolves ONCE when the time recovers; alert records land in the
    sink deduped."""
    d = tmp_path / "run"
    d.mkdir()
    fake = [1000.0]
    agg = LiveAggregator(str(d), clock=lambda: fake[0])
    sink = MetricsLogger(tmp_path / "alerts.jsonl")
    eng = AlertEngine(
        [dict(load_rules(None)[0])], ml=sink, clock=lambda: fake[0])

    ml = MetricsLogger(d / "train.jsonl")
    _run_header(ml)
    _write_epochs(ml, 8, step=0.1)
    ml.hard_flush()
    agg.poll()
    assert eng.evaluate(agg) == []

    # spike: > factor (1.5) x rolling median 0.1
    _write_epochs(ml, 1, t0=8, step=0.5)
    ml.hard_flush()
    agg.poll()
    edges = eng.evaluate(agg)
    assert [(e["state"], e["rule"]) for e in edges] == [
        ("fire", "epoch-time-regression")]
    # still red across N ticks -> no further edges (dedup)
    for _ in range(3):
        fake[0] += 1.0
        agg.poll()
        assert eng.evaluate(agg) == []
    assert eng.firing() == [{"rule": "epoch-time-regression",
                             "source": "train"}]

    # recovery resolves once
    _write_epochs(ml, 1, t0=9, step=0.1)
    ml.hard_flush()
    agg.poll()
    edges = eng.evaluate(agg)
    assert [(e["state"], e["rule"]) for e in edges] == [
        ("resolve", "epoch-time-regression")]
    assert eng.evaluate(agg) == []
    assert (eng.n_fired, eng.n_resolved) == (1, 1)
    ml.close()
    sink.close()

    recs = read_metrics(tmp_path / "alerts.jsonl")
    assert [r["state"] for r in recs] == ["fire", "resolve"]
    for r in recs:
        assert r["rule"] == "epoch-time-regression"
        assert r["severity"] == "warn"


def test_fault_and_silence_alerts_under_fake_clock(tmp_path):
    """fault-rate fires on a fresh fault and resolves when the horizon
    passes quietly; silent-source covers the replica-dead case: a
    stream that stops producing fires after horizon_s and resolves
    when records resume."""
    d = tmp_path / "run"
    d.mkdir()
    fake = [5000.0]
    agg = LiveAggregator(str(d), clock=lambda: fake[0])
    rules = [r for r in load_rules(None)
             if r["rule"] in ("fault-rate", "silent-source")]
    eng = AlertEngine(rules, clock=lambda: fake[0])

    ml = MetricsLogger(d / "replica-m0-i0-metrics.jsonl")
    _run_header(ml)
    ml.hard_flush()
    agg.poll()
    assert eng.evaluate(agg) == []

    ml.fault("replica-dead", epoch=-1, replica=0)
    agg.poll()
    edges = eng.evaluate(agg)
    assert [(e["rule"], e["state"]) for e in edges] == [
        ("fault-rate", "fire")]

    # the replica goes silent past the 30s horizon -> silent-source
    # fires; past the 60s fault horizon -> fault-rate resolves
    fake[0] += 45.0
    edges = eng.evaluate(agg)
    assert [(e["rule"], e["state"]) for e in edges] == [
        ("silent-source", "fire")]
    fake[0] += 30.0
    edges = eng.evaluate(agg)
    assert [(e["rule"], e["state"]) for e in edges] == [
        ("fault-rate", "resolve")]

    # records resume -> silent-source resolves; each edge happened once
    ml.recovery("relaunch", epoch=-1, replica=0)
    agg.poll()
    edges = eng.evaluate(agg)
    assert [(e["rule"], e["state"]) for e in edges] == [
        ("silent-source", "resolve")]
    assert (eng.n_fired, eng.n_resolved) == (2, 2)
    ml.close()


# ---------------- spans ----------------------------------------------------


def test_span_lifecycle_conservation():
    """Rate-1 sampling through the MicroBatcher: every sampled submit
    lands EXACTLY one terminal span (dispatch | shed), dispatched ones
    a queue span too, and the engine span covers each flushed batch."""
    from pipegcn_tpu.serve.batcher import MicroBatcher
    from pipegcn_tpu.serve.tracing import SpanWriter, TraceSampler

    spans = []

    class _ML:
        def span(self, trace_id, span_id, op, t_start, dur_ms,
                 status="ok", **extra):
            spans.append({"trace_id": trace_id, "span_id": span_id,
                          "op": op, "t_start": t_start,
                          "dur_ms": dur_ms, "status": status})

    fake = [0.0]
    sw = SpanWriter(_ML(), clock=lambda: fake[0], source="t",
                    now=lambda: 2000.0 + fake[0])
    sampler = TraceSampler(1.0, seed=0, tag="t")
    mb = MicroBatcher(run=lambda ids: np.zeros((ids.size, 2)),
                      max_batch=8, max_delay_ms=0.0,
                      clock=lambda: fake[0], on_span=sw.emit,
                      max_queue=6)
    traced = []
    for i in range(4):
        tk = mb.submit(np.array([i]), trace_id=sampler.sample())
        traced.append(tk.trace_id)
        fake[0] += 0.001
        mb.pump(force=True)
    # overload: fill the queue, then shed one
    t5 = mb.submit(np.arange(6), trace_id=sampler.sample())
    shed = mb.submit(np.arange(3), trace_id=sampler.sample())
    assert shed.shed and shed.trace_id is not None
    fake[0] += 0.001
    mb.pump(force=True)

    assert sampler.n_sampled == 6
    by_trace = {}
    for s in spans:
        by_trace.setdefault(s["trace_id"], []).append(s["op"])
    # exactly one terminal span per sampled trace
    for tid, ops in by_trace.items():
        terminal = [op for op in ops if op in ("dispatch", "shed")]
        assert len(terminal) == 1, (tid, ops)
    assert by_trace[shed.trace_id] == ["shed"]
    for tid in traced + [t5.trace_id]:
        assert sorted(by_trace[tid]) == ["dispatch", "engine", "queue"]
    # span ids unique; t_start on the unix axis the writer was given
    ids = [s["span_id"] for s in spans]
    assert len(ids) == len(set(ids))
    assert all(s["t_start"] >= 2000.0 for s in spans)
    # rate 0: no ids minted at all
    assert TraceSampler(0.0).sample() is None


def test_timeline_stitches_spans_into_flows(tmp_path):
    """span records from two streams sharing a trace id become X
    slices bound by one Perfetto flow (s -> f with a common id), and
    the v5-v9 kinds render as counters/instants on the wall axis."""
    from pipegcn_tpu.obs.timeline import build_timeline

    t0 = 1000.0
    driver = [
        {"event": "span", "trace_id": "q1-t", "span_id": "s1",
         "op": "queue", "t_start": t0, "dur_ms": 2.0, "status": "ok"},
        {"event": "span", "trace_id": "q1-t", "span_id": "s2",
         "op": "rpc", "t_start": t0 + 0.002, "dur_ms": 5.0,
         "status": "ok", "replica": 0},
        {"event": "serving", "window_s": 1.0, "queries": 10,
         "qps": 10.0, "batch_fill": 1.0, "queue_depth": 2,
         "p50_ms": 1.0, "p95_ms": 2.0, "p99_ms": 3.0,
         "cache_hit_rate": None, "staleness_age": 0, "shed": 0,
         "param_generation": 0, "param_staleness": 0,
         "time_unix": t0 + 0.5},
        {"event": "fleet", "kind": "replica-dead", "replica": 0,
         "window": 1, "time_unix": t0 + 0.6},
    ]
    replica = [
        {"event": "span", "trace_id": "q1-t", "span_id": "s3",
         "op": "engine", "t_start": t0 + 0.004, "dur_ms": 1.5,
         "status": "ok"},
    ]
    obj = build_timeline([(0, driver), (1, replica)])
    evs = [e for e in obj["traceEvents"] if e.get("ph") != "M"]
    # contract: numeric ts >= 0, X dur >= 0, sorted
    last = -1.0
    for e in evs:
        assert e["ts"] >= 0
        assert e["ts"] >= last
        last = e["ts"]
        if e["ph"] == "X":
            assert e["dur"] >= 0
    slices = [e for e in evs if e["ph"] == "X"]
    assert sorted(e["name"] for e in slices) == [
        "engine", "queue", "rpc"]
    # wall anchor: earliest span at ts 0, engine span 4ms in
    assert min(e["ts"] for e in slices) == 0.0
    flows = [e for e in evs if e["ph"] in ("s", "t", "f")]
    assert [e["ph"] for e in flows] == ["s", "t", "f"]
    assert len({e["id"] for e in flows}) == 1
    assert flows[0]["pid"] == 0 and flows[-1]["pid"] == 1
    counters = [e for e in evs if e["ph"] == "C"
                and e["name"].startswith("serving_")]
    assert {e["name"] for e in counters} == {
        "serving_qps", "serving_p50_ms", "serving_p99_ms",
        "serving_queue_depth", "serving_shed"}
    instants = [e for e in evs if e["ph"] == "i"]
    assert any(e["name"] == "fleet:replica-dead" for e in instants)


# ---------------- /metrics scrape parity -----------------------------------


def _seed_run_dir(d):
    with MetricsLogger(d / "train.jsonl") as ml:
        _run_header(ml)
        _write_epochs(ml, 5)
        ml.fault("rank-death", epoch=3, rank=1)
        ml.recovery("restart", epoch=3, rank=1, downtime_s=0.5)
        ml.serving(window_s=1.0, queries=100, qps=100.0,
                   batch_fill=0.9, queue_depth=3, p50_ms=1.0,
                   p95_ms=2.0, p99_ms=3.0, cache_hit_rate=0.8,
                   staleness_age=2, shed=5, param_generation=1,
                   param_staleness=0,
                   shed_by_reason={"queue-full": 5})


def _parse_prom(text):
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, val = line.rsplit(" ", 1)
        out[name] = float(val)
    return out


def test_metrics_scrape_matches_jsonl(tmp_path):
    """/metrics over HTTP reports exactly the numbers the JSONL says:
    the scrape is a view of the same records the report CLI reads."""
    d = tmp_path / "run"
    d.mkdir()
    _seed_run_dir(d)
    recs = read_metrics(d / "train.jsonl")
    last_epoch = [r for r in recs if r["event"] == "epoch"][-1]
    serving = [r for r in recs if r["event"] == "serving"][-1]

    agg = LiveAggregator(str(d))
    eng = AlertEngine()
    agg.poll()
    eng.evaluate(agg)
    srv = MonitorServer(agg, eng, port=0).start()
    try:
        url = f"http://127.0.0.1:{srv.port}"
        text = urllib.request.urlopen(url + "/metrics",
                                      timeout=5).read().decode()
        health = json.loads(urllib.request.urlopen(
            url + "/health", timeout=5).read())
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(url + "/nope", timeout=5)
    finally:
        srv.stop()

    vals = _parse_prom(text)
    assert vals['pipegcn_loss{source="train"}'] == last_epoch["loss"]
    assert vals['pipegcn_epoch{source="train"}'] == last_epoch["epoch"]
    assert vals['pipegcn_serving_qps{source="train"}'] == serving["qps"]
    assert vals['pipegcn_serving_p99_ms{source="train"}'] == \
        serving["p99_ms"]
    assert vals['pipegcn_faults_total{kind="rank-death"}'] == 1
    assert vals['pipegcn_recoveries_total{kind="restart"}'] == 1
    assert vals['pipegcn_serving_shed_rows_total{reason="queue-full"}'] \
        == 5
    assert vals["pipegcn_records_total"] == len(recs)
    assert vals["pipegcn_schema_version"] == 10
    # the fresh fault fires the page-severity fault-rate rule
    assert vals['pipegcn_alert_firing{rule="fault-rate",source="*"}'] \
        == 1
    assert health["status"] == "critical"
    assert health["alerts_firing"] == [
        {"rule": "fault-rate", "source": "*"}]
    # text renderer matches what the server shipped (modulo the
    # wall-clock age gauge, which moves between the two renders)
    def _stable(d):
        return {k: v for k, v in d.items()
                if "last_seen_age" not in k}
    direct = prometheus_text(agg, eng)
    assert _stable(_parse_prom(direct)) == _stable(vals)


def test_monitor_cli_once(tmp_path, capsys):
    from pipegcn_tpu.cli.monitor import main as monitor_main

    d = tmp_path / "run"
    d.mkdir()
    with MetricsLogger(d / "train.jsonl") as ml:
        _run_header(ml)
        _write_epochs(ml, 3)
    rc = monitor_main([str(d), "--once", "--alerts-out", "-"])
    out = capsys.readouterr().out
    health = json.loads(out[out.index("{"):])
    assert rc == 0
    assert health["status"] == "ok"
    assert health["n_records"] == 4

    # a fault flips the fault-rate page rule -> rc 2 (scriptable
    # drill); MetricsLogger appends, so reopening extends the stream
    with MetricsLogger(d / "train.jsonl") as ml:
        ml.fault("rank-death", epoch=2, rank=0)
    rc = monitor_main([str(d), "--once"])
    out = capsys.readouterr().out
    assert rc == 2
    assert "ALERT FIRE fault-rate" in out
    # the alert sink landed next to the run
    recs = read_metrics(d / "alerts.jsonl")
    assert [r["state"] for r in recs] == ["fire"]


# ---------------- trend ----------------------------------------------------


def _round(n, ok=True, **headline):
    h = None
    if headline:
        h = {"metric": "epoch_time", "unit": "s/epoch", **headline}
    return {"round": n, "path": f"BENCH_r{n:02d}.json", "ok": ok,
            "headline": h}


def test_trend_flags_regression_on_worsening_series():
    series = {"bench": [
        _round(1, value=1.0),
        _round(2, value=0.9),
        _round(3, value=1.2),  # > 5% worse than best-known 0.9
    ], "multichip": [], "sweep": None}
    t = trend(series, tol=0.05)
    lever = t["levers"]["value"]
    assert lever["best"] == 0.9 and lever["best_round"] == 2
    assert lever["regressed"] is True
    assert t["regressed"] is True and "value" in t["flags"]
    assert "REGRESSED" in format_trend(t)

    # within tolerance: clean
    series["bench"][-1] = _round(3, value=0.92)
    t = trend(series, tol=0.05)
    assert t["levers"]["value"]["regressed"] is False
    assert t["regressed"] is False

    # a config change resets best-known instead of flagging the new
    # shape as a regression
    series["bench"].append(
        {"round": 4, "path": "BENCH_r04.json", "ok": True,
         "headline": {"metric": "bigger_graph_epoch_time",
                      "unit": "s/epoch", "value": 9.0}})
    t = trend(series, tol=0.05)
    assert t["levers"]["value"]["regressed"] is False
    assert t["levers"]["value"]["n_comparable"] == 1

    # a failed latest round after successes flags the verdict
    series["bench"].append(
        {"round": 5, "path": "BENCH_r05.json", "ok": False,
         "headline": None})
    t = trend(series, tol=0.05)
    assert t["regressed"] is True
    assert "latest-round-failed" in t["flags"]


def test_bench_trend_over_repo_artifacts():
    """Smoke over the real BENCH_r*.json series committed in the repo:
    the loader survives the failed r01 round (no headline anywhere in
    its tail) and the table renders every lever."""
    if not glob.glob(os.path.join(REPO, "BENCH_r*.json")):
        pytest.skip("no BENCH artifacts in this checkout")
    series = load_series(REPO)
    assert any(not b["ok"] or b["headline"] is None
               for b in series["bench"]) or True
    t = trend(series)
    assert t["n_rounds"] == len(series["bench"]) > 0
    table = format_trend(t)
    assert "verdict:" in table
    for b in series["bench"]:
        if not b["ok"]:
            assert b["round"] in t["failed_rounds"]


def test_report_cli_accepts_run_directory(tmp_path, capsys):
    """pipegcn-report on a directory merges every stream (deduped,
    generation-ordered) into one summary instead of demanding a single
    file."""
    from pipegcn_tpu.cli.report import main as report_main

    d = tmp_path / "run"
    d.mkdir()
    header = {"event": "run", "schema_version": 10, "config": {},
              "device": {}, "mesh": {},
              "time_unix": 1700000000.0}
    with MetricsLogger(d / "train.g0.m0.jsonl") as ml:
        ml.write(header)
        _write_epochs(ml, 3)
    with MetricsLogger(d / "train.g1.m0.jsonl") as ml:
        ml.write(header)  # duplicated header folds to one
        _write_epochs(ml, 2, t0=3)
    rc = report_main([str(d), "--json"])
    assert rc == 0
    s = json.loads(capsys.readouterr().out.strip())
    assert s["n_streams_merged"] == 2
    assert s["n_epoch_records"] == 5
    assert s["schema_version"] == 10
