"""Locality-aware reorder + slab-gather layout (round 9).

Covers the layout contract end to end: the reorder permutation
round-trips against the base layout and is validated on load, old
(pre-reorder) artifacts still load, training/eval semantics are
layout-invariant (losses within float-accumulation noise, eval
bit-parity), the slab-gather streaming path is numerically identical
to the plain clipped-take path (including adversarial all-scattered
streams, where no plan must be emitted), the fallback ladder's new
slab-off rung fires before any impl downgrade, the tuner signature
keys on the layout, and the bench/report plumbing surfaces
gather_contiguity with a pinned --json shape.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from pipegcn_tpu.graph.csr import Graph
from pipegcn_tpu.models import ModelConfig
from pipegcn_tpu.parallel import Trainer, TrainConfig
from pipegcn_tpu.partition import ShardedGraph, partition_graph
from pipegcn_tpu.partition.partitioner import (
    REORDER_MODES,
    reorder_key,
    reorder_suffix,
)


def _mesh_graph(n=20, n_feat=12, n_class=4, seed=0):
    """n x n 2D mesh (400 nodes at the default): regular structure so
    BFS renumbering produces predictable locality, with CONTIGUOUS
    train/val/test segments (an alternating mask would interleave the
    train-first base layout and destroy every gather run)."""
    ii, jj = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    nid = ii * n + jj
    right = np.stack([nid[:, :-1].ravel(), nid[:, 1:].ravel()])
    down = np.stack([nid[:-1, :].ravel(), nid[1:, :].ravel()])
    und = np.concatenate([right, down], axis=1)
    N = n * n
    rng = np.random.default_rng(seed)
    ar = np.arange(N)
    return Graph(
        num_nodes=N,
        src=np.concatenate([und[0], und[1]]),
        dst=np.concatenate([und[1], und[0]]),
        ndata={
            "feat": rng.normal(size=(N, n_feat)).astype(np.float32),
            "label": rng.integers(0, n_class, size=N).astype(np.int64),
            "train_mask": ar < N // 2,
            "val_mask": (ar >= N // 2) & (ar < 3 * N // 4),
            "test_mask": ar >= 3 * N // 4,
        })


def _window_graph(n=256, deg=12, n_feat=12, n_class=4, seed=0):
    """Every node aggregates a contiguous id window below it — the
    slab-friendly stream shape (runs >= SLAB_RUN survive the bucket
    table build), again with contiguous mask segments."""
    src, dst = [], []
    for i in range(n):
        for j in range(max(0, i - deg), i):
            src.append(j)
            dst.append(i)
    rng = np.random.default_rng(seed)
    ar = np.arange(n)
    return Graph(
        num_nodes=n,
        src=np.asarray(src, np.int64), dst=np.asarray(dst, np.int64),
        ndata={
            "feat": rng.normal(size=(n, n_feat)).astype(np.float32),
            "label": rng.integers(0, n_class, size=n).astype(np.int64),
            "train_mask": ar < n // 2,
            "val_mask": (ar >= n // 2) & (ar < 3 * n // 4),
            "test_mask": ar >= 3 * n // 4,
        })


@pytest.fixture(scope="module")
def mesh():
    return _mesh_graph()


@pytest.fixture(scope="module")
def mesh_layouts(mesh):
    parts = partition_graph(mesh, 2, seed=0)
    sg_b = ShardedGraph.build(mesh, parts, n_parts=2)
    sg_r = ShardedGraph.build(mesh, parts, n_parts=2,
                              reorder="degree-bfs")
    return sg_b, sg_r


# ---------------------------------------------------------------------
# reorder keys + artifact naming


def test_reorder_key_modes_and_suffix(mesh):
    assert reorder_key(mesh, "none") is None
    for mode in ("degree", "bfs", "degree-bfs"):
        assert mode in REORDER_MODES
        key = reorder_key(mesh, mode)
        assert key.shape == (mesh.num_nodes,)
        assert key.dtype == np.int64
    # bfs renumbering is a permutation-derived key: all values distinct
    assert len(np.unique(reorder_key(mesh, "bfs"))) == mesh.num_nodes
    with pytest.raises(ValueError, match="unknown reorder mode"):
        reorder_key(mesh, "hilbert")
    assert reorder_suffix("none") == ""
    assert reorder_suffix("degree-bfs") == "-rdegree-bfs"
    with pytest.raises(ValueError, match="unknown reorder mode"):
        reorder_suffix("hilbert")


# ---------------------------------------------------------------------
# permutation round-trip against the base layout


def test_permutation_round_trip(mesh_layouts):
    sg_b, sg_r = mesh_layouts
    assert sg_b.reorder == "none" and sg_b.reorder_perm is None
    assert sg_r.reorder == "degree-bfs"
    assert sg_r.layout_version == ShardedGraph.LAYOUT_VERSION
    sg_r.validate_layout()  # must not raise
    for r in range(sg_r.num_parts):
        ic = int(sg_r.inner_count[r])
        assert ic == int(sg_b.inner_count[r])
        perm = np.asarray(sg_r.reorder_perm[r, :ic])
        inv = np.asarray(sg_r.reorder_inv[r, :ic])
        # mutually inverse permutations of [0, ic)
        np.testing.assert_array_equal(np.sort(perm), np.arange(ic))
        np.testing.assert_array_equal(inv[perm], np.arange(ic))
        # every node array round-trips through the permutation:
        # reordered local id l is base local id perm[l]
        for arr in ("global_nid", "feat", "label", "in_deg",
                    "train_mask"):
            np.testing.assert_array_equal(
                np.asarray(getattr(sg_r, arr))[r, :ic],
                np.asarray(getattr(sg_b, arr))[r, perm], err_msg=arr)
        # padding rows of the permutation are -1
        assert (np.asarray(sg_r.reorder_perm[r, ic:]) == -1).all()
    # train-first invariant survives the reorder sort key
    for r in range(sg_r.num_parts):
        t = int(sg_r.train_count[r])
        assert sg_r.train_mask[r, :t].all()
        assert not sg_r.train_mask[r, t:].any()


def test_reordered_artifact_roundtrip_and_validation(mesh_layouts,
                                                     tmp_path):
    _, sg_r = mesh_layouts
    path = str(tmp_path / "part_r")
    sg_r.save(path)
    sg2 = ShardedGraph.load(path)  # load() validates reordered layouts
    assert sg2.reorder == "degree-bfs"
    np.testing.assert_array_equal(sg2.reorder_perm, sg_r.reorder_perm)
    np.testing.assert_array_equal(sg2.reorder_inv, sg_r.reorder_inv)


def test_old_artifact_backward_compat(mesh_layouts, tmp_path):
    """A pre-reorder (layout v1) artifact — no reorder keys in the
    manifest, no permutation arrays — must load as reorder='none'."""
    sg_b, _ = mesh_layouts
    path = str(tmp_path / "part_v1")
    sg_b.save(path)
    mpath = os.path.join(path, "manifest.json")
    with open(mpath) as f:
        manifest = json.load(f)
    manifest.pop("reorder", None)
    manifest.pop("layout_version", None)
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    sg2 = ShardedGraph.load(path)
    assert sg2.reorder == "none"
    assert sg2.layout_version == 1
    assert sg2.reorder_perm is None and sg2.reorder_inv is None
    for k in ShardedGraph._ARRAYS:
        np.testing.assert_array_equal(getattr(sg2, k), getattr(sg_b, k))


def test_validate_layout_named_errors(mesh_layouts):
    sg_b, sg_r = mesh_layouts
    # reorder tag without permutation arrays: metadata inconsistency
    broken = dataclasses.replace(sg_b, reorder="degree-bfs")
    with pytest.raises(ValueError,
                       match="boundary-slot validation.*inconsistent"):
        broken.validate_layout()
    # permutation arrays that are not mutually inverse
    perm = np.array(sg_r.reorder_perm)
    perm[0, 0], perm[0, 1] = perm[0, 1], perm[0, 0]
    with pytest.raises(ValueError,
                       match="boundary-slot validation.*inverse"):
        dataclasses.replace(sg_r, reorder_perm=perm).validate_layout()
    # a send list naming a non-inner local id
    idx = np.array(sg_r.send_idx)
    assert sg_r.send_counts[0, 0] > 0  # the mesh has a real boundary
    idx[0, 0, 0] = 10**6
    with pytest.raises(ValueError,
                       match="boundary-slot validation.*send_idx"):
        dataclasses.replace(sg_r, send_idx=idx).validate_layout()


# ---------------------------------------------------------------------
# training/eval semantics are layout-invariant


def _trainer(sg, g, **cfg_kw):
    cfg = ModelConfig(
        layer_sizes=(g.ndata["feat"].shape[1], 16,
                     int(g.ndata["label"].max()) + 1),
        dropout=0.0, train_size=int(g.ndata["train_mask"].sum()),
        **cfg_kw)
    return Trainer(sg, cfg, TrainConfig(seed=3, eval=False))


def test_eval_bit_parity_and_training_losses(mesh, mesh_layouts):
    sg_b, sg_r = mesh_layouts
    t_b = _trainer(sg_b, mesh)
    t_r = _trainer(sg_r, mesh)
    # identical init (layout-independent): full-graph eval logits are
    # bit-identical, and the SHARDED eval — which runs through the
    # reordered layout's halo exchange — produces the exact same
    # integer counts
    h_b = t_b.eval_dispatch(mesh, "val_mask")
    h_r = t_r.eval_dispatch(mesh, "val_mask")
    np.testing.assert_array_equal(np.asarray(h_b[2]),
                                  np.asarray(h_r[2]))
    s_b = t_b.eval_dispatch(mesh, "val_mask", sharded=True)
    s_r = t_r.eval_dispatch(mesh, "val_mask", sharded=True)
    np.testing.assert_array_equal(np.asarray(s_b[2]),
                                  np.asarray(s_r[2]))
    # training is an ordering-insensitive computation up to float
    # accumulation order: per-epoch losses agree to rtol 1e-5
    l_b = [t_b.train_epoch(e) for e in range(4)]
    l_r = [t_r.train_epoch(e) for e in range(4)]
    np.testing.assert_allclose(l_b, l_r, rtol=1e-5)
    # and the trained models evaluate to the same accuracy
    a_b = t_b.evaluate(mesh, "val_mask")
    a_r = t_r.evaluate(mesh, "val_mask")
    assert abs(a_b - a_r) < 0.02


def test_two_process_mesh_reorder(tmp_path):
    """Halo correctness under reorder across a REAL two-process CPU
    mesh (test_multihost's localhost rendezvous): both processes drive
    one partition each of the same SPMD job under the base and the
    reordered layout; losses must agree across layouts AND be
    identical across ranks (same SPMD program)."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    driver = tmp_path / "driver.py"
    driver.write_text(
        "import sys\n"
        "import numpy as np\n"
        "import jax\n"
        "jax.config.update('jax_cpu_collectives_implementation',"
        " 'gloo')\n"
        f"jax.distributed.initialize('127.0.0.1:{port}', 2,"
        " int(sys.argv[1]))\n"
        "from tests.test_reorder import _mesh_graph, _trainer\n"
        "from pipegcn_tpu.partition import ShardedGraph, "
        "partition_graph\n"
        "g = _mesh_graph(14)\n"
        "parts = partition_graph(g, 2, seed=0)\n"
        "losses = {}\n"
        "for mode in ('none', 'degree-bfs'):\n"
        "    sg = ShardedGraph.build(g, parts, n_parts=2, reorder=mode)\n"
        "    t = _trainer(sg, g)\n"
        "    losses[mode] = [round(float(t.train_epoch(e)), 6)\n"
        "                    for e in range(3)]\n"
        "np.testing.assert_allclose(losses['none'],"
        " losses['degree-bfs'], rtol=1e-5)\n"
        "print('LOSSES', losses['none'])\n")
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PYTHONPATH": repo,
    }
    procs = [subprocess.Popen(
        [sys.executable, str(driver), str(rank)],
        env=env, cwd=repo, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True) for rank in (0, 1)]
    outs = [p.communicate(timeout=240)[0] for p in procs]
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-2000:]}"
    tails = [[ln for ln in o.splitlines() if ln.startswith("LOSSES")]
             for o in outs]
    assert tails[0] and tails[0] == tails[1], outs


# ---------------------------------------------------------------------
# slab-gather plans: build-time detection + numerical parity


def test_slab_plan_adversarial_all_scattered():
    """A stream with NO +1-consecutive runs must produce no plan at
    all — the residue path alone is the whole gather."""
    from pipegcn_tpu.ops.bucket_spmm import build_slab_plan

    sentinel = 4096
    # strided indices: flat stream 0, 2, 4, ... — never consecutive
    tbl = (2 * np.arange(16 * 8)).reshape(1, 16, 8).astype(np.int32)
    assert build_slab_plan(tbl, sentinel) is None
    # all-sentinel (fully padded bucket): no plan either
    pad = np.full((1, 16, 8), sentinel, np.int32)
    assert build_slab_plan(pad, sentinel) is None


def test_slab_gather_sum_matches_plain_take():
    """Device-side parity on a mixed stream: long contiguous runs
    (slab-covered), short runs and scattered residue, and sentinel
    padding — the streaming path must reproduce the plain clipped-take
    row sums up to f32 reduction-order noise."""
    import jax.numpy as jnp

    from pipegcn_tpu.ops.bucket_spmm import (
        SLAB_RUN,
        _slab_gather_sum,
        build_slab_plan,
    )

    rng = np.random.default_rng(7)
    n_src, w, cap, f = 512, 8, 24, 6
    sentinel = n_src
    tbl = np.full((1, cap, w), sentinel, np.int32)
    flat = tbl.reshape(1, -1)
    # rows 0..11: one long contiguous stream (covered by slabs)
    flat[0, : 12 * w] = np.arange(12 * w) + 40
    # rows 12..17: scattered residue, runs shorter than SLAB_RUN
    flat[0, 12 * w: 18 * w] = rng.choice(
        np.arange(0, n_src, 3), size=6 * w, replace=False)
    # rows 18..: left as sentinel padding
    plan = build_slab_plan(tbl, sentinel)
    assert plan is not None
    assert plan["cnt"][0] >= (12 * w) // SLAB_RUN - 1
    # slab-covered residue entries were replaced by the sentinel
    assert int((plan["res"] == sentinel).sum()) > int(
        (tbl == sentinel).sum())

    fbuf_pad = np.concatenate(
        [rng.normal(size=(n_src, f)).astype(np.float32),
         np.zeros((1, f), np.float32)])
    want = fbuf_pad[tbl[0]].sum(axis=1)
    got = np.asarray(_slab_gather_sum(
        jnp.asarray(fbuf_pad),
        {k: jnp.asarray(v[0]) for k, v in plan.items()},
        cap, w, f))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    # and a pure-numpy emulation of the streaming writes agrees exactly
    buf = fbuf_pad[np.minimum(plan["res"][0].reshape(-1), sentinel)]
    buf = np.concatenate([buf, np.zeros((SLAB_RUN, f), np.float32)])
    for i in range(plan["src"].shape[1]):
        s0, p0 = int(plan["src"][0][i]), int(plan["pos"][0][i])
        buf[p0:p0 + SLAB_RUN] = fbuf_pad[s0:s0 + SLAB_RUN]
    np.testing.assert_array_equal(
        buf[:cap * w].reshape(cap, w, f).sum(axis=1), want)


def test_slab_trainer_parity_and_fallback():
    """End-to-end on the slab-friendly window graph: tables carry slab
    plans, slab=on training/eval is numerically identical to slab=off,
    and an injected kernel crash takes the slab-off rung FIRST (same
    impl) before any impl downgrade."""
    from pipegcn_tpu.ops.bucket_spmm import (
        build_sharded_bucket_tables,
        gather_contiguity,
    )

    g = _window_graph()
    sg = ShardedGraph.build(g, np.zeros(g.num_nodes, np.int32),
                            n_parts=1)
    tabs = build_sharded_bucket_tables(sg, slab=True)
    assert any("res_" in k for k in tabs)  # plans were emitted
    stats = gather_contiguity(tabs, sg.n_max + sg.halo_size)
    assert stats["mean_run_len"] > 2.0
    assert 0.0 < stats["slab_frac"] <= 1.0

    t_on = _trainer(sg, g, spmm_impl="bucket", slab="on")
    assert t_on._slab_active()
    t_off = _trainer(sg, g, spmm_impl="bucket", slab="off")
    assert not t_off._slab_active()
    l_on = [t_on.train_epoch(e) for e in range(3)]
    l_off = [t_off.train_epoch(e) for e in range(3)]
    np.testing.assert_allclose(l_on, l_off, rtol=1e-6)
    assert t_on.evaluate(g, "val_mask") == t_off.evaluate(g, "val_mask")

    # fallback ladder: slab-off rung first, impl rung only after
    t = _trainer(sg, g, spmm_impl="bucket", slab="on")
    t._inject_kernel_crash = True
    t.train_epoch(0)
    assert t.fallbacks[0]["reason"].startswith("slab-off:")
    assert t.fallbacks[0]["from_impl"] == "bucket"
    assert t.fallbacks[0]["to_impl"] == "bucket"
    assert t.cfg.slab == "off" and not t._slab_active()
    t._inject_kernel_crash = True
    t.train_epoch(1)  # second crash: now the impl ladder moves
    assert t.fallbacks[-1]["to_impl"] != "bucket"


# ---------------------------------------------------------------------
# tuner signature + artifact resolution


def test_tuner_signature_keys_on_layout(tmp_path):
    from pipegcn_tpu.ops import tuner

    base = dict(width=16, block_tile=256, bucket_merge=0,
                chunk_edges=None)
    sig_old = tuner.signature_for(**base)
    assert sig_old["reorder"] == "none"
    assert sig_old["layout_version"] == 1
    sig_new = tuner.signature_for(**base, reorder="degree-bfs",
                                  layout_version=2)
    assert sig_new != sig_old
    # a tuning.json persisted for one layout is rejected for another
    # (forces exactly one re-tune instead of trusting stale timings)
    tuner.save_tuning(str(tmp_path), {
        "tuner_format": tuner.TUNER_FORMAT,
        "signature": sig_old, "source_edge_checksum": 1,
        "winner": {"name": "bucket", "impl": "bucket"}, "table": []})
    rec, why = tuner.load_tuning(str(tmp_path), expect_checksum=1,
                                 signature=sig_new)
    assert rec is None and "signature" in why
    rec, why = tuner.load_tuning(str(tmp_path), expect_checksum=1,
                                 signature=sig_old)
    assert rec is not None and why is None


def test_slab_candidates_in_grid():
    from pipegcn_tpu.ops.tuner import candidate_grid

    names = [c["name"] for c in candidate_grid(slab="auto")]
    slabbed = [n for n in names if "slab" in n]
    assert slabbed  # the tuner measures the slab twins...
    assert len(set(names)) == len(names)
    # ...and slab=off removes them (explicit pin wins)
    assert not any("slab" in c["name"]
                   for c in candidate_grid(slab="off"))


def test_resolve_reorder_prefers_existing_artifacts(tmp_path):
    from pipegcn_tpu.partition.bench_artifact import (
        artifact_path,
        resolve_reorder,
    )

    root = str(tmp_path)
    # concrete modes pass through untouched, artifact or not
    assert resolve_reorder(1, 1024, True, root, "degree",
                           log=lambda m: None) == "degree"
    # auto with no artifacts on disk would fall to measurement; with a
    # reordered artifact present it must reuse it (cheapest path)
    p = artifact_path(1, 1024, True, root, "degree-bfs")
    os.makedirs(p)
    with open(os.path.join(p, "manifest.json"), "w") as f:
        json.dump({}, f)
    assert resolve_reorder(1, 1024, True, root, "auto",
                           log=lambda m: None) == "degree-bfs"


# ---------------------------------------------------------------------
# report plumbing: contiguity next to the anatomy floor, pinned --json


def test_report_surfaces_contiguity(tmp_path, capsys):
    from pipegcn_tpu.cli.report import main as report_main
    from pipegcn_tpu.cli.report import summarize_run
    from pipegcn_tpu.obs import MetricsLogger, read_metrics

    p = tmp_path / "bench.jsonl"
    with MetricsLogger(p) as ml:
        ml.run_header(config={}, device={}, mesh={})
        ml.event("bench", metric="small_epoch_time", value=1.25,
                 unit="s/epoch", vs_baseline=1.0,
                 reorder="degree-bfs",
                 gather_contiguity={"mean_run_len": 7.5,
                                    "slab_frac": 0.61},
                 reorder_delta_s=0.12, slab_delta_s=-0.03)
    s = summarize_run(read_metrics(p))
    # the pinned --json shape the bench trajectory consumes
    assert s["reorder"] == "degree-bfs"
    assert s["gather_mean_run_len"] == pytest.approx(7.5)
    assert s["gather_slab_frac"] == pytest.approx(0.61)
    assert s["reorder_delta_s"] == pytest.approx(0.12)
    assert s["slab_delta_s"] == pytest.approx(-0.03)
    assert report_main([str(p), "--json"]) == 0
    js = json.loads(capsys.readouterr().out)
    for k in ("reorder", "gather_mean_run_len", "gather_slab_frac",
              "reorder_delta_s", "slab_delta_s"):
        assert k in js, k
    assert report_main([str(p)]) == 0
    human = capsys.readouterr().out
    assert "gather contiguity" in human
    assert "reorder delta" in human
