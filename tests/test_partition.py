import collections

import numpy as np
import pytest

from pipegcn_tpu.graph import karate_club, synthetic_graph
from pipegcn_tpu.partition import ShardedGraph, partition_graph
from pipegcn_tpu.partition.partitioner import comm_volume, edge_cut


@pytest.fixture(scope="module")
def medium_graph():
    return synthetic_graph(num_nodes=2000, avg_degree=12, n_feat=16,
                           n_class=5, seed=7)


def test_partition_balanced_and_total(medium_graph):
    g = medium_graph
    for method in ("metis", "random"):
        parts = partition_graph(g, 4, method=method, seed=1)
        assert parts.shape == (g.num_nodes,)
        sizes = np.bincount(parts, minlength=4)
        assert sizes.sum() == g.num_nodes
        assert (sizes > 0).all()
        assert sizes.max() <= 1.10 * g.num_nodes / 4  # balance


def test_metis_beats_random(medium_graph):
    g = medium_graph
    metis = partition_graph(g, 4, method="metis", obj="cut", seed=0)
    rand = partition_graph(g, 4, method="random", seed=0)
    assert edge_cut(g, metis) < 0.7 * edge_cut(g, rand)


def test_vol_objective_reduces_volume(medium_graph):
    g = medium_graph
    vol = partition_graph(g, 4, method="metis", obj="vol", seed=0)
    rand = partition_graph(g, 4, method="random", seed=0)
    assert comm_volume(g, vol) < comm_volume(g, rand)


def test_partition_one_part(medium_graph):
    parts = partition_graph(medium_graph, 1)
    assert (parts == 0).all()


def test_partition_errors(medium_graph):
    with pytest.raises(ValueError):
        partition_graph(medium_graph, 0)
    with pytest.raises(ValueError):
        partition_graph(medium_graph, 4, method="spectral")
    with pytest.raises(ValueError):
        partition_graph(medium_graph, 4, obj="area")


def _reconstruct_edges(sg: ShardedGraph):
    """Map every real local edge back to global (src, dst) pairs via the
    halo layout; padded slots are skipped."""
    edges = []
    P = sg.num_parts
    for r in range(P):
        for e in range(sg.edge_count[r]):
            s, d = int(sg.edge_src[r, e]), int(sg.edge_dst[r, e])
            dst_g = int(sg.global_nid[r, d])
            if s < sg.n_max:
                src_g = int(sg.global_nid[r, s])
            else:
                slot = s - sg.n_max
                dist = slot // sg.b_max + 1
                k = slot % sg.b_max
                q = (r - dist) % P
                src_g = int(sg.global_nid[q, sg.send_idx[q, dist - 1, k]])
            edges.append((src_g, dst_g))
    return edges


@pytest.mark.parametrize("n_parts", [2, 3, 4])
def test_sharded_graph_edge_conservation(n_parts):
    g = karate_club()
    parts = partition_graph(g, n_parts, seed=3)
    sg = ShardedGraph.build(g, parts)
    got = collections.Counter(_reconstruct_edges(sg))
    want = collections.Counter(zip(g.src.tolist(), g.dst.tolist()))
    assert got == want


def test_sharded_graph_invariants():
    g = synthetic_graph(num_nodes=500, avg_degree=8, n_feat=12, n_class=4,
                        seed=2)
    P = 4
    parts = partition_graph(g, P, seed=0)
    sg = ShardedGraph.build(g, parts)

    assert sg.inner_count.sum() == g.num_nodes
    assert sg.edge_count.sum() == g.num_edges
    assert sg.n_train_global == g.ndata["train_mask"].sum()
    # train-first: on each device train nodes occupy local ids [0, t)
    for r in range(P):
        t = sg.train_count[r]
        assert sg.train_mask[r, :t].all()
        assert not sg.train_mask[r, t:].any()
    # node data round-trips through global_nid
    for r in range(P):
        nids = sg.global_nid[r, : sg.inner_count[r]]
        assert (nids >= 0).all()
        np.testing.assert_allclose(
            sg.feat[r, : sg.inner_count[r]], g.ndata["feat"][nids]
        )
        np.testing.assert_array_equal(
            sg.label[r, : sg.inner_count[r]], g.ndata["label"][nids]
        )
        np.testing.assert_allclose(
            sg.in_deg[r, : sg.inner_count[r]],
            g.in_degrees()[nids].astype(np.float32),
        )
    # padding rows are inert: never marked train
    assert not sg.train_mask[sg.global_nid < 0].any()
    # send lists: indices are valid inner nodes of the sender
    for r in range(P):
        for d in range(P - 1):
            c = sg.send_counts[r, d]
            assert sg.send_mask[r, d, :c].all()
            assert not sg.send_mask[r, d, c:].any()
            assert (sg.send_idx[r, d, :c] < sg.inner_count[r]).all()


def _simulate_aggregation(sg: ShardedGraph):
    """Numpy mean-aggregation over the sharded layout: exchange halos, then
    segment-sum per device. Returns [P, n_max, F]."""
    P, F = sg.num_parts, sg.feat.shape[-1]
    out = np.zeros((P, sg.n_max, F), np.float32)
    for r in range(P):
        fbuf = np.zeros((sg.n_max + sg.halo_size, F), np.float32)
        fbuf[: sg.n_max] = sg.feat[r]
        for dist in range(1, P):
            q = (r - dist) % P
            block = sg.feat[q][sg.send_idx[q, dist - 1]]
            block[~sg.send_mask[q, dist - 1]] = 0
            s = sg.n_max + (dist - 1) * sg.b_max
            fbuf[s : s + sg.b_max] = block
        acc = np.zeros((sg.n_max + 1, F), np.float32)
        np.add.at(acc, sg.edge_dst[r], fbuf[sg.edge_src[r]])
        out[r] = acc[: sg.n_max] / sg.in_deg[r][:, None]
    return out


def test_sharded_aggregation_matches_global():
    g = synthetic_graph(num_nodes=300, avg_degree=6, n_feat=8, n_class=3,
                        seed=5)
    P = 3
    parts = partition_graph(g, P, seed=1)
    sg = ShardedGraph.build(g, parts)

    # global reference: mean over in-edges
    acc = np.zeros((g.num_nodes, 8), np.float32)
    np.add.at(acc, g.dst, g.ndata["feat"][g.src])
    ref = acc / g.in_degrees()[:, None]

    got = _simulate_aggregation(sg)
    for r in range(P):
        nids = sg.global_nid[r, : sg.inner_count[r]]
        np.testing.assert_allclose(
            got[r, : sg.inner_count[r]], ref[nids], rtol=1e-5, atol=1e-5
        )


def test_cluster_reorder_preserves_semantics():
    """Cluster renumbering is an ordering choice only: every invariant
    holds, and per-global-node aggregation results are identical to the
    unordered build."""
    from pipegcn_tpu.partition import locality_clusters

    g = synthetic_graph(num_nodes=600, avg_degree=8, n_feat=8, n_class=4,
                        homophily=0.9, seed=5)
    P = 3
    parts = partition_graph(g, P, seed=1)
    cluster = locality_clusters(g, target_size=64, seed=0)
    assert cluster.shape == (g.num_nodes,)
    sg_plain = ShardedGraph.build(g, parts, n_parts=P)
    sg_clust = ShardedGraph.build(g, parts, n_parts=P, cluster=cluster)

    for sg in (sg_plain, sg_clust):
        # train-first invariant survives the extra sort key
        for r in range(P):
            tm = sg.train_mask[r, : sg.inner_count[r]]
            assert tm[: sg.train_count[r]].all()
            assert not tm[sg.train_count[r]:].any()
        # per-device CSR order
        for r in range(P):
            ed = sg.edge_dst[r][: sg.edge_count[r]]
            assert (np.diff(ed) >= 0).all()

    # same nodes per device, different order
    for r in range(P):
        a = np.sort(sg_plain.global_nid[r, : sg_plain.inner_count[r]])
        b = np.sort(sg_clust.global_nid[r, : sg_clust.inner_count[r]])
        np.testing.assert_array_equal(a, b)

    # aggregation result per GLOBAL node id identical for both layouts
    got_p = _simulate_aggregation(sg_plain)
    got_c = _simulate_aggregation(sg_clust)
    for r in range(P):
        n_r = sg_plain.inner_count[r]
        order_p = np.argsort(sg_plain.global_nid[r, :n_r])
        order_c = np.argsort(sg_clust.global_nid[r, :n_r])
        np.testing.assert_allclose(
            got_p[r, :n_r][order_p], got_c[r, :n_r][order_c],
            rtol=1e-5, atol=1e-5,
        )

    # cluster locality actually materializes: mean local-id distance
    # across edges shrinks vs the unordered layout on a homophilous graph
    def mean_edge_span(sg):
        spans = []
        for r in range(P):
            e = sg.edge_count[r]
            src, dst = sg.edge_src[r][:e], sg.edge_dst[r][:e]
            inner = src < sg.n_max
            spans.append(np.abs(src[inner].astype(np.int64)
                                - dst[inner].astype(np.int64)).mean())
        return np.mean(spans)

    assert mean_edge_span(sg_clust) < mean_edge_span(sg_plain)


def test_artifact_roundtrip(tmp_path):
    g = karate_club()
    parts = partition_graph(g, 2, seed=0)
    sg = ShardedGraph.build(g, parts)
    path = str(tmp_path / "part")
    assert not ShardedGraph.exists(path)
    sg.save(path)
    assert ShardedGraph.exists(path)
    sg2 = ShardedGraph.load(path)
    for k in ShardedGraph._ARRAYS:
        np.testing.assert_array_equal(getattr(sg, k), getattr(sg2, k))
    assert sg2.num_parts == sg.num_parts
    assert sg2.multilabel == sg.multilabel


def test_artifact_roundtrip_mmap_v3(tmp_path):
    """The v3 (per-array .npy, mmap-loaded) layout must roundtrip
    identically to v2 and stay usable as lazily-sliced memmaps —
    the papers100M-class loading path (one rank's slice touched,
    not the whole artifact)."""
    g = karate_club()
    parts = partition_graph(g, 2, seed=0)
    sg = ShardedGraph.build(g, parts)
    path = str(tmp_path / "part_v3")
    sg.save(path, mmap=True)
    assert ShardedGraph.exists(path)
    sg2 = ShardedGraph.load(path)
    for k in ShardedGraph._ARRAYS:
        assert isinstance(getattr(sg2, k), np.memmap), k
        np.testing.assert_array_equal(getattr(sg, k), getattr(sg2, k))
    assert sg2.num_parts == sg.num_parts
    # per-rank slice is a plain in-RAM copy
    rank0_feat = np.asarray(sg2.feat[0])
    np.testing.assert_array_equal(rank0_feat, sg.feat[0])

    # trim_edges variant: per-rank trimmed edge files, identical up to
    # each rank's real edge count; whole-array access fails loudly
    tpath = str(tmp_path / "part_v3_trim")
    sg.save(tpath, mmap=True, trim_edges=True)
    sg3 = ShardedGraph.load(tpath)
    for r in range(sg.num_parts):
        e = int(sg.edge_count[r])
        np.testing.assert_array_equal(sg3.edge_src[r][:e],
                                      sg.edge_src[r][:e])
        np.testing.assert_array_equal(sg3.edge_dst[r][:e],
                                      sg.edge_dst[r][:e])
    with pytest.raises(AttributeError, match="trim_edges"):
        sg3.edge_src.astype(np.int32)
    with pytest.raises(TypeError, match="trim_edges"):
        np.asarray(sg3.edge_src)
    with pytest.raises(IndexError):
        sg3.edge_src[sg.num_parts]
    with pytest.raises(ValueError, match="mmap"):
        sg.save(str(tmp_path / "bad"), trim_edges=True)


def test_build_chunked_bit_identical():
    """build_chunked must reproduce build() EXACTLY — every array, every
    scalar — including cluster layouts, multilabel data, memmap-like
    sliced sources, and chunk sizes that force many partial chunks."""
    from pipegcn_tpu.partition import locality_clusters

    for kwargs, seed in (
        (dict(num_nodes=300, avg_degree=6, n_feat=9, n_class=4), 11),
        (dict(num_nodes=240, avg_degree=5, n_feat=7, n_class=5,
              multilabel=True), 13),
    ):
        g = synthetic_graph(**kwargs, seed=seed)
        parts = partition_graph(g, 4, seed=0)
        for cluster in (None, locality_clusters(g, seed=0)):
            ref = ShardedGraph.build(g, parts, n_parts=4, cluster=cluster)
            # edge_chunk 257: dozens of ragged chunks over ~2k edges
            chk = ShardedGraph.build_chunked(g, parts, n_parts=4,
                                             cluster=cluster,
                                             edge_chunk=257,
                                             node_chunk=77)
            for k in ShardedGraph._ARRAYS:
                np.testing.assert_array_equal(
                    getattr(ref, k), getattr(chk, k), err_msg=k)
            for k in ("num_parts", "n_max", "b_max", "e_max",
                      "n_train_global", "n_feat", "n_class", "multilabel",
                      "source_edge_checksum"):
                assert getattr(ref, k) == getattr(chk, k), k


def test_partitioner_grid_quality():
    """Absolute quality gate (results/partition_quality.md): on a 2D
    grid the P-way strip cut is analytic; the FM-refined multilevel
    partitioner must stay METIS-class (<= 1.3x the strip bound — the
    pre-FM greedy landed at 1.9x)."""
    from pipegcn_tpu import native
    from pipegcn_tpu.graph.csr import Graph
    from pipegcn_tpu.partition.partitioner import edge_cut

    if not native.available():
        pytest.skip("FM refinement lives in the native partitioner; "
                    "the numpy fallback has no quality gate")

    n = 128
    ii, jj = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    nid = ii * n + jj
    right = np.stack([nid[:, :-1].ravel(), nid[:, 1:].ravel()])
    down = np.stack([nid[:-1, :].ravel(), nid[1:, :].ravel()])
    und = np.concatenate([right, down], axis=1)
    g = Graph(num_nodes=n * n,
              src=np.concatenate([und[0], und[1]]),
              dst=np.concatenate([und[1], und[0]]))
    for P in (2, 4):
        parts = partition_graph(g, P, seed=0)
        cut = edge_cut(g, parts) // 2
        assert cut <= 1.3 * (P - 1) * n, (P, cut, (P - 1) * n)
        sizes = np.bincount(parts, minlength=P)
        assert sizes.max() <= 1.1 * sizes.mean()


def test_chunked_csr_adjacency_matches_scipy():
    """The RAM-bounded counting-sort adjacency builder must produce the
    same per-row neighbor SETS as the scipy symmetrize path (the
    chunked path keeps parallel unit-weight entries instead of
    deduping — a uniform weight scale), for both the mirroring and the
    symmetric=True trusted-input modes, across ragged chunks."""
    from pipegcn_tpu.graph.csr import Graph
    from pipegcn_tpu.partition.partitioner import (
        _csr_adjacency_chunked, _sym_adj)

    g = synthetic_graph(num_nodes=900, avg_degree=9, n_feat=4, n_class=3,
                        seed=7)
    ip, ix = _csr_adjacency_chunked(g, chunk=257)
    adj = _sym_adj(g)
    for u in range(g.num_nodes):
        assert set(ix[ip[u]:ip[u + 1]].tolist()) == \
            set(adj.indices[adj.indptr[u]:adj.indptr[u + 1]].tolist()), u
    gm = Graph(num_nodes=g.num_nodes,
               src=np.concatenate([g.src, g.dst]),
               dst=np.concatenate([g.dst, g.src]))
    ip2, ix2 = _csr_adjacency_chunked(gm, symmetric=True, chunk=257)
    for u in range(g.num_nodes):
        assert set(ix2[ip2[u]:ip2[u + 1]].tolist()) == \
            set(ix[ip[u]:ip[u + 1]].tolist()), u
    # end-to-end: symmetric partition of the mirrored graph is sane
    parts = partition_graph(gm, 4, seed=0, symmetric=True)
    sizes = np.bincount(parts, minlength=4)
    assert sizes.min() > 0 and sizes.max() <= 1.15 * sizes.mean()
