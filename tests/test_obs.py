"""Telemetry subsystem tests: schema round-trip + drift guard, the
JSONL sink, PhaseTimer/CommTimer semantics, byte-exact reference log
formats, the CLI --metrics-out end-to-end path, and the report CLI."""

import json
import re

import numpy as np
import pytest

from pipegcn_tpu.cli.main import result_file_name, run
from pipegcn_tpu.cli.parser import create_parser
from pipegcn_tpu.cli.report import main as report_main
from pipegcn_tpu.cli.report import summarize_run
from pipegcn_tpu.obs import (
    MetricsLogger,
    PhaseTimer,
    read_metrics,
    validate_record,
)
from pipegcn_tpu.obs import schema as obs_schema
from pipegcn_tpu.obs.format import (
    epoch_line,
    reference_eval_line,
    reference_train_line,
)
from pipegcn_tpu.utils.timer import CommTimer

# ---------------- schema -------------------------------------------------

# FROZEN copy of the v7 contract (v6 + the fleet kind and the
# serving shed/param-generation fields the serving-fleet PR added,
# bumping the version to 7). If any assert below fires, a field was
# removed or retyped without bumping SCHEMA_VERSION — consumers
# (bench trajectory, report CLI, timeline CLI, scripts) would break
# silently.
_V7_FIELDS = {
    "run": {
        "event": "string", "schema_version": "integer",
        "time_unix": "number", "config": "object", "device": "object",
        "mesh": "object",
    },
    "epoch": {
        "event": "string", "epoch": "integer", "step_time_s": "number",
        "loss": "number", "grad_norm": "number", "halo_bytes": "integer",
        "staleness_age": "integer", "memory": "object?",
    },
    "eval": {
        "event": "string", "epoch": "integer", "eval_time_s": "number",
        "val_acc": "number",
    },
    "summary": {
        "event": "string", "n_epochs": "integer",
        "epoch_time_s": "number?", "best_val": "number",
    },
    "fault": {
        "event": "string", "kind": "string", "epoch": "integer",
    },
    "recovery": {
        "event": "string", "kind": "string", "epoch": "integer",
    },
    "profile": {
        "event": "string", "phases": "object", "comm_s": "number",
        "compute_s": "number", "overlap_fraction": "number",
    },
    "anatomy": {
        "event": "string", "phases": "object", "est_flops": "number",
        "flops": "number?", "attributed_flops_fraction": "number?",
    },
    "staleness": {
        "event": "string", "epoch": "integer", "layers": "object",
        "max_rel_drift": "number",
    },
    "numerics": {
        "event": "string", "kind": "string", "epoch": "integer",
    },
    "fallback": {
        "event": "string", "epoch": "integer", "from_impl": "string",
        "to_impl": "string",
    },
    "tuning": {
        "event": "string", "winner": "object", "source": "string",
        "costs": "array",
    },
    "serving": {
        "event": "string", "window_s": "number", "queries": "integer",
        "qps": "number", "batch_fill": "number?",
        "queue_depth": "integer", "p50_ms": "number?",
        "p95_ms": "number?", "p99_ms": "number?",
        "cache_hit_rate": "number?", "staleness_age": "integer",
        "shed": "integer", "param_generation": "integer",
        "param_staleness": "integer",
    },
    "membership": {
        "event": "string", "generation": "integer",
        "assignment": "object", "trigger": "string",
        "restart_latency_s": "number?",
    },
    "fleet": {
        "event": "string", "kind": "string", "replica": "integer",
        "window": "integer",
    },
}


def test_schema_v7_drift_guard():
    current = {"run": obs_schema.RUN_FIELDS,
               "epoch": obs_schema.EPOCH_FIELDS,
               "eval": obs_schema.EVAL_FIELDS,
               "summary": obs_schema.SUMMARY_FIELDS,
               "fault": obs_schema.FAULT_FIELDS,
               "recovery": obs_schema.RECOVERY_FIELDS,
               "profile": obs_schema.PROFILE_FIELDS,
               "anatomy": obs_schema.ANATOMY_FIELDS,
               "staleness": obs_schema.STALENESS_FIELDS,
               "numerics": obs_schema.NUMERICS_FIELDS,
               "fallback": obs_schema.FALLBACK_FIELDS,
               "tuning": obs_schema.TUNING_FIELDS,
               "serving": obs_schema.SERVING_FIELDS,
               "membership": obs_schema.MEMBERSHIP_FIELDS,
               "fleet": obs_schema.FLEET_FIELDS}
    if obs_schema.SCHEMA_VERSION == 7:
        for kind, fields in _V7_FIELDS.items():
            for name, tag in fields.items():
                assert current[kind].get(name) == tag, (
                    f"schema field {kind}.{name} removed or retyped "
                    f"without bumping SCHEMA_VERSION")
    else:
        # a bump legitimizes any field change; the contract is that the
        # version moved WITH the change
        assert obs_schema.SCHEMA_VERSION > 7


# FROZEN copy of the v8 additions (v7 + the `stream` kind the
# streaming-graphs PR added, bumping the version to 8). Same contract
# as the v7 guard: removing/retyping a field without bumping
# SCHEMA_VERSION fires the assert.
_V8_STREAM_FIELDS = {
    "event": "string", "epoch": "integer", "seq": "integer",
    "edges_added": "integer", "edges_deleted": "integer",
    "nodes_added": "integer", "patch_ms": "number",
    "tables_rebuilt": "integer", "repadded": "boolean",
    "slack_remaining": "object", "drift": "number?",
}


def test_schema_v8_drift_guard():
    if obs_schema.SCHEMA_VERSION == 8:
        for name, tag in _V8_STREAM_FIELDS.items():
            assert obs_schema.STREAM_FIELDS.get(name) == tag, (
                f"schema field stream.{name} removed or retyped "
                f"without bumping SCHEMA_VERSION")
    else:
        assert obs_schema.SCHEMA_VERSION > 8


# FROZEN copy of the v9 additions (v8 + the `soak` kind the storage-
# fault PR added, bumping the version to 9; the same PR added the
# io-degraded fault/recovery kind, which needs no new fields). Same
# contract as the earlier guards.
_V9_SOAK_FIELDS = {
    "event": "string", "episode": "integer", "seed": "integer",
    "schedule": "array", "invariants": "object", "verdict": "string",
}


def test_schema_v9_drift_guard():
    if obs_schema.SCHEMA_VERSION == 9:
        for name, tag in _V9_SOAK_FIELDS.items():
            assert obs_schema.SOAK_FIELDS.get(name) == tag, (
                f"schema field soak.{name} removed or retyped "
                f"without bumping SCHEMA_VERSION")
    else:
        assert obs_schema.SCHEMA_VERSION > 9


# frozen copies of the v10 contracts (the live-monitoring PR added the
# alert record — the SLO rule engine's edge-triggered fire/resolve log
# — and the span record carrying the sampled serving-path traces that
# cli.timeline stitches into Perfetto flows). Same contract as the
# earlier guards.
_V10_ALERT_FIELDS = {
    "event": "string", "rule": "string", "state": "string",
    "severity": "string", "source": "string", "value": "number?",
    "threshold": "number?", "message": "string",
}
_V10_SPAN_FIELDS = {
    "event": "string", "trace_id": "string", "span_id": "string",
    "op": "string", "t_start": "number", "dur_ms": "number",
    "status": "string",
}


def test_schema_v10_drift_guard():
    if obs_schema.SCHEMA_VERSION == 10:
        for name, tag in _V10_ALERT_FIELDS.items():
            assert obs_schema.ALERT_FIELDS.get(name) == tag, (
                f"schema field alert.{name} removed or retyped "
                f"without bumping SCHEMA_VERSION")
        for name, tag in _V10_SPAN_FIELDS.items():
            assert obs_schema.SPAN_FIELDS.get(name) == tag, (
                f"schema field span.{name} removed or retyped "
                f"without bumping SCHEMA_VERSION")
    else:
        assert obs_schema.SCHEMA_VERSION > 10


_V14_TRACESYNC_FIELDS = {
    "event": "string", "rank": "integer", "epoch": "integer",
    "t_anchor": "number", "generation": "integer",
}


def test_schema_v14_drift_guard():
    if obs_schema.SCHEMA_VERSION == 14:
        for name, tag in _V14_TRACESYNC_FIELDS.items():
            assert obs_schema.TRACESYNC_FIELDS.get(name) == tag, (
                f"schema field tracesync.{name} removed or retyped "
                f"without bumping SCHEMA_VERSION")
    else:
        assert obs_schema.SCHEMA_VERSION > 14


# FROZEN copy of the v15 additions (v14 + the `journal` kind the
# crash-consistent-streaming PR added: the write-ahead delta journal's
# append/watermark/replay/truncate/verify/degraded/recovered/skew
# lifecycle). Same contract as the earlier guards.
_V15_JOURNAL_FIELDS = {
    "event": "string", "op": "string", "seq": "integer",
    "topo_generation": "integer", "n_records": "integer",
    "source": "string",
}


def test_schema_v15_drift_guard():
    if obs_schema.SCHEMA_VERSION == 15:
        for name, tag in _V15_JOURNAL_FIELDS.items():
            assert obs_schema.JOURNAL_FIELDS.get(name) == tag, (
                f"schema field journal.{name} removed or retyped "
                f"without bumping SCHEMA_VERSION")
    else:
        assert obs_schema.SCHEMA_VERSION > 15


def test_validate_record():
    validate_record({"event": "epoch", "epoch": 0, "step_time_s": 0.1,
                     "loss": 1.0, "grad_norm": 0.5, "halo_bytes": 128,
                     "staleness_age": 1, "memory": None})
    with pytest.raises(ValueError, match="missing field"):
        validate_record({"event": "epoch", "epoch": 0})
    with pytest.raises(ValueError, match="expected integer"):
        validate_record({"event": "epoch", "epoch": 0.5,
                         "step_time_s": 0.1, "loss": 1.0,
                         "grad_norm": 0.5, "halo_bytes": 128,
                         "staleness_age": 1, "memory": None})
    # bool must not pass as an integer count
    with pytest.raises(ValueError, match="bool"):
        validate_record({"event": "epoch", "epoch": True,
                         "step_time_s": 0.1, "loss": 1.0,
                         "grad_norm": 0.5, "halo_bytes": 128,
                         "staleness_age": 1, "memory": None})
    # unknown event kinds are free-form
    validate_record({"event": "bench", "whatever": [1, 2]})


def test_validate_tuning_record():
    validate_record({"event": "tuning",
                     "winner": {"name": "block-u4-bf16",
                                "impl": "block"},
                     "source": "artifact", "costs": [],
                     "stale_reason": None})
    with pytest.raises(ValueError, match="winner"):
        validate_record({"event": "tuning", "source": "live",
                         "costs": []})
    with pytest.raises(ValueError, match="expected array"):
        validate_record({"event": "tuning", "winner": {},
                         "source": "live", "costs": {}})


def test_validate_serving_record():
    validate_record({"event": "serving", "window_s": 2.0, "queries": 40,
                     "qps": 20.0, "batch_fill": 0.5, "queue_depth": 0,
                     "p50_ms": 1.2, "p95_ms": 3.4, "p99_ms": 5.6,
                     "cache_hit_rate": 1.0, "staleness_age": 0,
                     "shed": 0, "param_generation": -1,
                     "param_staleness": 0})
    # empty windows carry nullable latency/fill fields
    validate_record({"event": "serving", "window_s": 2.0, "queries": 0,
                     "qps": 0.0, "batch_fill": None, "queue_depth": 0,
                     "p50_ms": None, "p95_ms": None, "p99_ms": None,
                     "cache_hit_rate": None, "staleness_age": 0,
                     "shed": 4, "param_generation": 7,
                     "param_staleness": 1})
    with pytest.raises(ValueError, match="missing field"):
        validate_record({"event": "serving", "window_s": 2.0})


def test_validate_fleet_record():
    validate_record({"event": "fleet", "kind": "replica-dead",
                     "replica": 1, "window": 3})
    # hot-swap records ride with free extras (swap_ms, incarnation, …)
    validate_record({"event": "fleet", "kind": "hot-swap", "replica": 0,
                     "window": -1, "param_generation": 2,
                     "swap_ms": 12.5, "incarnation": 0})
    with pytest.raises(ValueError, match="missing field"):
        validate_record({"event": "fleet", "kind": "failover"})
    with pytest.raises(ValueError, match="expected integer"):
        validate_record({"event": "fleet", "kind": "relaunch",
                         "replica": "one", "window": 0})


# ---------------- sink ---------------------------------------------------

def test_metrics_logger_roundtrip(tmp_path):
    p = tmp_path / "m.jsonl"
    with MetricsLogger(p) as ml:
        ml.run_header(config={"lr": 0.01}, device={"platform": "cpu"},
                      mesh={"n_parts": 4})
        assert ml.header_written
        # numpy scalars/arrays must serialize transparently
        ml.epoch(epoch=np.int64(0), step_time_s=np.float32(0.25),
                 loss=np.float32(1.5), grad_norm=np.float64(0.1),
                 halo_bytes=np.int64(4096), staleness_age=0,
                 memory={"bytes_in_use": None,
                         "peak_bytes_in_use": None})
        ml.eval_record(9, 0.01, 0.9, test_acc=0.88)
        ml.summary(n_epochs=10, epoch_time_s=0.25, best_val=0.9,
                   comm_cost={"comm": 0.1, "reduce": 0.2})
    recs = read_metrics(p)
    assert [r["event"] for r in recs] == ["run", "epoch", "eval",
                                          "summary"]
    for r in recs:
        validate_record(r)  # the file round-trips through the schema
    assert recs[0]["schema_version"] == obs_schema.SCHEMA_VERSION
    assert recs[1]["loss"] == pytest.approx(1.5)
    assert isinstance(recs[1]["halo_bytes"], int)
    assert recs[2]["test_acc"] == pytest.approx(0.88)

    # validation rejects a bad record at write time
    with MetricsLogger(tmp_path / "bad.jsonl") as ml:
        with pytest.raises(ValueError):
            ml.write({"event": "epoch", "epoch": 1})

    # a torn final line is reported, not silently dropped
    with open(p, "a") as f:
        f.write('{"event": "epo')
    with pytest.raises(ValueError, match="malformed"):
        read_metrics(p)


# ---------------- timers --------------------------------------------------

def test_phase_timer_exception_safety_and_accumulation():
    pt = PhaseTimer()
    with pytest.raises(KeyError):
        with pt.phase("outer"):
            with pt.phase("inner"):  # nesting is free
                pass
            raise KeyError("boom")
    # the raising span still recorded its duration
    assert pt.durations()["outer"] >= pt.durations()["inner"] >= 0.0
    # repeated keys accumulate instead of raising
    with pt.phase("inner"):
        pass
    assert pt.counts()["inner"] == 2
    pt.clear()
    assert pt.tot_time() == 0.0 and pt.counts() == {}


def test_comm_timer_records_on_exception():
    t = CommTimer()
    with pytest.raises(KeyError):
        with t.timer("forward_0"):
            raise KeyError("device loss mid-span")
    assert "forward_0" in t.durations()  # recorded despite the raise
    with pytest.raises(RuntimeError, match="duplicate"):
        with t.timer("forward_0"):
            pass


# ---------------- reference log-format byte parity ------------------------

def test_reference_log_lines_byte_exact():
    """The pre-refactor f-strings, pinned byte-for-byte: the formatters
    must never drift (tooling parses these lines)."""
    assert reference_train_line(0, 9, 0.1234, 0.015, 0.002, 1.5) == (
        "Process 000 | Epoch 00009 | Time(s) 0.1234 | Comm(s) 0.0150 | "
        "Reduce(s) 0.0020 | Loss 1.5000")
    assert reference_eval_line(9, 0.95) == "Epoch 00009 | Accuracy 95.00%"
    assert reference_eval_line(19, 0.9512, 0.9401) == (
        "Epoch 00019 | Validation Accuracy 95.12% | "
        "Test Accuracy 94.01%")
    assert epoch_line(10, 0.0312, 0.6931) == (
        "Epoch 00010 | Time(s) 0.0312 | Loss 0.6931")
    assert epoch_line(10, 0.0312, 0.6931, 0.875) == (
        "Epoch 00010 | Time(s) 0.0312 | Loss 0.6931 | Val 0.8750")


# ---------------- CLI end-to-end ------------------------------------------

_TRAIN_RE = re.compile(
    r"Process \d{3} \| Epoch \d{5} \| Time\(s\) \d+\.\d{4} \| "
    r"Comm\(s\) \d+\.\d{4} \| Reduce\(s\) \d+\.\d{4} \| Loss \d+\.\d{4}")
_EVAL_RE = re.compile(
    r"Epoch (\d{5}) \| Validation Accuracy (\d+\.\d{2})% \| "
    r"Test Accuracy (\d+\.\d{2})%")


def _cli_args(tmp_path, extra):
    base = [
        "--dataset", "synthetic:600:8:16:4",
        "--n-partitions", "4",
        "--n-epochs", "12",
        "--n-layers", "2",
        "--n-hidden", "32",
        "--dropout", "0.2",
        "--log-every", "5",
        "--fix-seed", "--seed", "7",
        "--partition-dir", str(tmp_path / "partitions"),
        "--model-dir", str(tmp_path / "model"),
        "--results-dir", str(tmp_path / "results"),
    ]
    return create_parser().parse_args(base + extra)


@pytest.fixture(scope="module")
def cli_metrics_run(tmp_path_factory):
    """One pipelined CLI smoke run with --metrics-out, shared by the
    telemetry-content, report-CLI and reference-log tests."""
    tmp_path = tmp_path_factory.mktemp("obs_cli")
    mpath = tmp_path / "metrics.jsonl"
    args = _cli_args(tmp_path, ["--enable-pipeline",
                                "--metrics-out", str(mpath)])
    res = run(args)
    return tmp_path, mpath, args, res


def test_cli_metrics_end_to_end(cli_metrics_run):
    tmp_path, mpath, args, res = cli_metrics_run
    assert res["metrics_out"] == str(mpath)
    recs = read_metrics(mpath)
    for r in recs:
        validate_record(r)

    header = recs[0]
    assert header["event"] == "run"
    assert header["schema_version"] == obs_schema.SCHEMA_VERSION
    assert header["config"]["enable_pipeline"] is True
    assert header["mesh"]["n_parts"] == 4
    assert header["device"].get("platform") == "cpu"

    epochs = [r for r in recs if r["event"] == "epoch"]
    assert [r["epoch"] for r in epochs] == list(range(12))
    for r in epochs:
        assert r["step_time_s"] > 0
        assert np.isfinite(r["loss"])
        assert r["grad_norm"] > 0
        assert r["halo_bytes"] > 0  # P=4: real halo traffic
        assert set(r["memory"]) >= {"bytes_in_use", "peak_bytes_in_use"}
    # staleness-1 pipelining: epoch 0 consumes zero-initialized buffers
    assert epochs[0]["staleness_age"] == 0
    assert all(r["staleness_age"] == 1 for r in epochs[1:])
    # the pipelined loss still goes down on this easy graph
    assert epochs[-1]["loss"] < epochs[0]["loss"]

    evals = [r for r in recs if r["event"] == "eval"]
    assert evals and all(0 <= r["val_acc"] <= 1 for r in evals)
    assert "test_acc" in evals[0]  # transductive eval scores test too

    summ = [r for r in recs if r["event"] == "summary"]
    assert len(summ) == 1
    assert summ[0]["n_epochs"] == 12
    assert summ[0]["best_val"] == pytest.approx(res["best_val"])
    assert summ[0]["comm_cost"]["comm"] > 0  # measure_comm_cost path


def test_cli_reference_logs_unchanged(cli_metrics_run):
    """--reference-logs output must stay byte-identical through the
    telemetry refactor: every result-file line matches the reference
    format exactly, and re-rendering the parsed values through the
    pinned formatter reproduces each line byte-for-byte."""
    tmp_path, mpath, args, res = cli_metrics_run
    rfile = result_file_name(args)
    lines = open(rfile).read().strip().splitlines()
    assert lines
    for line in lines:
        m = _EVAL_RE.fullmatch(line)
        assert m, f"reference-format line drifted: {line!r}"
        rebuilt = reference_eval_line(int(m.group(1)),
                                      float(m.group(2)) / 100.0,
                                      float(m.group(3)) / 100.0)
        assert rebuilt == line


def test_cli_stdout_train_lines_reference_format(tmp_path, capsys):
    """The Process/Comm/Reduce stdout lines keep the reference's exact
    field layout (train.py:369-371)."""
    args = _cli_args(tmp_path, ["--no-eval"])
    run(args)
    out = capsys.readouterr().out
    train_lines = [ln for ln in out.splitlines()
                   if ln.startswith("Process")]
    assert train_lines  # 12 epochs -> the epoch-9 boundary logs once
    for ln in train_lines:
        assert _TRAIN_RE.fullmatch(ln), f"drifted: {ln!r}"


# ---------------- report CLI ----------------------------------------------

def test_report_cli_summarizes_run(cli_metrics_run, capsys):
    _, mpath, _, res = cli_metrics_run
    rc = report_main([str(mpath)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "median epoch" in out
    assert "best val" in out
    # --json emits a machine-readable summary
    rc = report_main([str(mpath), "--json"])
    assert rc == 0
    s = json.loads(capsys.readouterr().out)
    assert s["n_epoch_records"] == 12
    assert s["pipeline"] is True
    assert s["median_epoch_s"] > 0
    assert s["best_val"] == pytest.approx(res["best_val"])
    assert s["loss_delta"] < 0
    assert 0 < s["comm_fraction"] <= 1
    assert s["overlapped_comm_fraction"] == s["comm_fraction"]
    assert s["halo_bytes_per_epoch"] > 0
    assert s["staleness_age_max"] == 1


def test_report_json_pins_floor_share_and_halo_compression(tmp_path,
                                                           capsys):
    """--json shape pin for the round-8 floor fields: compressed-halo
    runs expose before/after wire bytes + ratio, and anatomy-bearing
    runs expose the non-SpMM floor share (1 - spmm phase shares)."""
    p = tmp_path / "floor.jsonl"
    with MetricsLogger(p) as ml:
        ml.run_header(config={}, device={}, mesh={})
        for e in range(3):
            ml.epoch(epoch=e, step_time_s=0.5, loss=1.0 - 0.1 * e,
                     grad_norm=0.5, halo_bytes=250, staleness_age=1,
                     memory=None, halo_bytes_uncompressed=1000)
        ml.anatomy(
            phases={"spmm_fwd": {"flops": 60.0},
                    "spmm_bwd": {"flops": 20.0},
                    "dense": {"flops": 15.0},
                    "norm": {"flops": 5.0}},
            est_flops=100.0, attributed_flops_fraction=0.9)
    rc = report_main([str(p), "--json"])
    assert rc == 0
    s = json.loads(capsys.readouterr().out)
    assert s["halo_bytes_per_epoch"] == 250
    assert s["halo_bytes_uncompressed_per_epoch"] == 1000
    assert s["halo_compression_ratio"] == pytest.approx(4.0)
    assert s["anatomy_non_spmm_share"] == pytest.approx(0.2)
    # human-readable lines render the same facts
    rc = report_main([str(p)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "halo wire compression" in out
    assert "non-SpMM floor share" in out


def test_report_json_pins_serving_summary(tmp_path, capsys):
    """--json shape pin for the round-10 serving fields: windowed
    `serving` records roll up to total QPS, query-weighted latency
    percentiles / batch fill / cache hit rate, and a drained flag off
    the hard-flushed final record."""
    p = tmp_path / "serve.jsonl"
    with MetricsLogger(p) as ml:
        ml.run_header(config={}, device={}, mesh={})
        ml.serving(window_s=2.0, queries=40, qps=20.0, batch_fill=0.5,
                   queue_depth=1, p50_ms=1.0, p95_ms=2.0, p99_ms=3.0,
                   cache_hit_rate=1.0, staleness_age=0, shed=3,
                   param_generation=1, param_staleness=1)
        ml.serving(window_s=2.0, queries=120, qps=60.0, batch_fill=0.75,
                   queue_depth=3, p50_ms=2.0, p95_ms=4.0, p99_ms=6.0,
                   cache_hit_rate=0.5, staleness_age=2, shed=5,
                   param_generation=2, param_staleness=0, final=True)
    rc = report_main([str(p), "--json"])
    assert rc == 0
    s = json.loads(capsys.readouterr().out)
    assert s["n_serving_records"] == 2
    assert s["serving_queries"] == 160
    assert s["serving_qps"] == pytest.approx(40.0)
    # query-weighted means: (40*1 + 120*2) / 160
    assert s["serving_p50_ms"] == pytest.approx(1.75)
    assert s["serving_p99_ms"] == pytest.approx(5.25)
    assert s["serving_batch_fill"] == pytest.approx(0.6875)
    assert s["serving_cache_hit_rate"] == pytest.approx(0.625)
    assert s["serving_staleness_age_max"] == 2
    assert s["serving_queue_depth_max"] == 3
    # v7 rollups: total shed rows, last served generation, worst lag
    assert s["serving_shed_total"] == 8
    assert s["serving_param_generation_last"] == 2
    assert s["serving_param_staleness_max"] == 1
    assert s["serving_drained"] is True
    # human-readable lines render the same facts
    rc = report_main([str(p)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "serving QPS" in out
    assert "serving latency" in out
    # without a final record the report flags the shutdown
    q = tmp_path / "undrained.jsonl"
    with MetricsLogger(q) as ml:
        ml.run_header(config={}, device={}, mesh={})
        ml.serving(window_s=2.0, queries=10, qps=5.0, batch_fill=None,
                   queue_depth=0, p50_ms=None, p95_ms=None, p99_ms=None,
                   cache_hit_rate=None, staleness_age=0)
    summ = summarize_run(read_metrics(q))
    assert summ["serving_drained"] is False
    assert report_main([str(q)]) == 0
    assert "!! serving shutdown" in capsys.readouterr().out


def test_report_json_pins_stream_summary(tmp_path, capsys):
    """--json shape pin for the v8 stream fields: `stream` records roll
    up to delta totals, median/max patch cost, max/last probe drift, a
    re-pad count, and the last slack headroom snapshot."""
    p = tmp_path / "stream.jsonl"
    with MetricsLogger(p) as ml:
        ml.run_header(config={}, device={}, mesh={})
        ml.stream(epoch=4, seq=0, edges_added=10, edges_deleted=2,
                  nodes_added=1, patch_ms=1.5, tables_rebuilt=4,
                  repadded=False,
                  slack_remaining={"n": 9, "b": 5, "e": 80},
                  drift=0.31)
        ml.stream(epoch=8, seq=1, edges_added=6, edges_deleted=4,
                  nodes_added=0, patch_ms=2.5, tables_rebuilt=12,
                  repadded=True,
                  slack_remaining={"n": 20, "b": 11, "e": 150},
                  drift=0.12)
    rc = report_main([str(p), "--json"])
    assert rc == 0
    s = json.loads(capsys.readouterr().out)
    assert s["n_stream_records"] == 2
    assert s["stream_edges_added"] == 16
    assert s["stream_edges_deleted"] == 6
    assert s["stream_nodes_added"] == 1
    assert s["stream_patch_ms_median"] == pytest.approx(2.0)
    assert s["stream_patch_ms_max"] == pytest.approx(2.5)
    assert s["stream_drift_max"] == pytest.approx(0.31)
    assert s["stream_drift_last"] == pytest.approx(0.12)
    assert s["stream_tables_rebuilt"] == 16
    assert s["stream_repads"] == 1
    assert s["stream_slack_remaining_last"] == {"n": 20, "b": 11,
                                                "e": 150}
    # human-readable lines render the same facts, incl. the loud
    # slack-exhaustion flag
    rc = report_main([str(p)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "stream deltas" in out
    assert "stream patch cost" in out
    assert "!! stream re-pads" in out


def test_report_json_pins_fleet_summary(tmp_path, capsys):
    """--json shape pin for the round-12 fleet fields: `fleet` records
    roll up to a per-kind event count, the max measured hot-swap
    latency, and the last swapped generation; a death without a rejoin
    prints the degraded warning."""
    p = tmp_path / "fleet.jsonl"
    with MetricsLogger(p) as ml:
        ml.run_header(config={}, device={}, mesh={})
        ml.fleet("hot-swap", 0, window=-1, param_generation=2,
                 swap_ms=12.5, incarnation=0)
        ml.fleet("hot-swap", 1, window=-1, param_generation=2,
                 swap_ms=30.25, incarnation=0)
        ml.fleet("replica-dead", 1, window=3, reason="heartbeat-stale")
        ml.fleet("failover", 0, window=3, n_retried=16, attempts=2)
        ml.fleet("relaunch", 1, window=3, incarnation=1, delay_s=0.5)
    rc = report_main([str(p), "--json"])
    assert rc == 0
    s = json.loads(capsys.readouterr().out)
    assert s["n_fleet_records"] == 5
    assert s["fleet_events"] == {"hot-swap": 2, "replica-dead": 1,
                                 "failover": 1, "relaunch": 1}
    assert s["fleet_param_swap_ms_max"] == pytest.approx(30.25)
    assert s["fleet_param_generation_last"] == 2
    # human-readable lines render the same facts + the degraded flag
    # (1 death, 0 rejoins)
    rc = report_main([str(p)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "fleet" in out
    assert "replica-dead=1" in out
    assert "!! fleet degraded" in out


def test_membership_record_roundtrip(tmp_path):
    """MetricsLogger.membership writes a hard-flushed v6 record that
    validates, carrying the supervisor's assignment verbatim."""
    from pipegcn_tpu.resilience.elastic import plan_assignment

    a = plan_assignment(4, [0, 1])
    p = tmp_path / "m.jsonl"
    with MetricsLogger(p) as ml:
        ml.membership(generation=0, assignment=a.as_json(),
                      trigger="start", n_members=2)
        ml.membership(generation=1,
                      assignment=plan_assignment(4, [0]).as_json(),
                      trigger="rank-death", restart_latency_s=3.25,
                      n_members=1)
    recs = [r for r in read_metrics(p) if r["event"] == "membership"]
    assert len(recs) == 2
    for r in recs:
        validate_record(r)
    assert recs[0]["restart_latency_s"] is None
    assert recs[0]["assignment"]["parts"] == {"0": [0, 1], "1": [2, 3]}
    assert recs[1]["trigger"] == "rank-death"
    assert recs[1]["assignment"]["parts"] == {"0": [0, 1, 2, 3]}
    # contract violations are loud
    bad = dict(recs[0], generation="zero")
    with pytest.raises(ValueError):
        validate_record(bad)


def test_report_json_pins_membership_summary(tmp_path, capsys):
    """--json shape pin for the round-11 membership fields: the ledger's
    generation records roll up to a timeline, the max restart latency,
    and a stopped flag when the supervisor gave up."""
    from pipegcn_tpu.resilience.elastic import plan_assignment

    p = tmp_path / "elastic.jsonl"
    with MetricsLogger(p) as ml:
        ml.run_header(config={}, device={}, mesh={})
        ml.membership(generation=0,
                      assignment=plan_assignment(2, [0, 1]).as_json(),
                      trigger="start", n_members=2)
        ml.membership(generation=1,
                      assignment=plan_assignment(2, [0]).as_json(),
                      trigger="rank-death", restart_latency_s=7.5,
                      n_members=1)
        ml.membership(generation=1,
                      assignment=plan_assignment(2, [0]).as_json(),
                      trigger="max-restarts", n_members=1)
    rc = report_main([str(p), "--json"])
    assert rc == 0
    s = json.loads(capsys.readouterr().out)
    assert s["n_membership_records"] == 3
    assert s["membership_last_generation"] == 1
    tl = s["membership_timeline"]
    assert [t["generation"] for t in tl] == [0, 1, 1]
    assert tl[0]["trigger"] == "start"
    assert tl[0]["n_members"] == 2
    assert tl[0]["parts_per_node"] == 1
    assert tl[1]["restart_latency_s"] == pytest.approx(7.5)
    assert s["restart_latency_max_s"] == pytest.approx(7.5)
    assert s["membership_stopped"] == "max-restarts"
    # human-readable lines render the same facts
    rc = report_main([str(p)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "membership" in out
    assert "rank-death" in out
    assert "!! supervisor stopped" in out


def test_report_cli_tolerates_partial_files(tmp_path, capsys):
    """A crashed run's file (header + some epochs, no summary) still
    summarizes; a missing file errors with rc=1, not a traceback."""
    p = tmp_path / "partial.jsonl"
    with MetricsLogger(p) as ml:
        ml.run_header(config={}, device={}, mesh={})
        for e in range(3):
            ml.epoch(epoch=e, step_time_s=0.5 + e, loss=1.0 - 0.1 * e,
                     grad_norm=0.5, halo_bytes=0, staleness_age=0,
                     memory=None)
    assert report_main([str(p)]) == 0
    s_out = capsys.readouterr().out
    assert "epochs recorded" in s_out
    summ = summarize_run(read_metrics(p))
    assert summ["n_epoch_records"] == 3
    assert summ["median_epoch_s"] == pytest.approx(1.5)
    assert summ["loss_delta"] == pytest.approx(-0.2)
    assert report_main([str(tmp_path / "nope.jsonl")]) == 1


# ---------------- sequential runner records -------------------------------

def test_sequential_runner_emits_epoch_records(tmp_path):
    from pipegcn_tpu.graph import synthetic_graph
    from pipegcn_tpu.models import ModelConfig
    from pipegcn_tpu.parallel import SequentialRunner, TrainConfig
    from pipegcn_tpu.partition import ShardedGraph, partition_graph

    g = synthetic_graph(num_nodes=400, avg_degree=6, n_feat=8,
                        n_class=3, seed=3)
    parts = partition_graph(g, 4, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=4)
    cfg = ModelConfig(layer_sizes=(8, 16, 3), dropout=0.0,
                      train_size=sg.n_train_global, spmm_impl="bucket")
    mpath = tmp_path / "seq.jsonl"
    with MetricsLogger(mpath) as ml:
        ml.run_header(config={"runner": "sequential"}, device={},
                      mesh={"n_parts": 4})
        runner = SequentialRunner(
            sg, cfg, TrainConfig(n_epochs=2, enable_pipeline=True),
            metrics=ml)
        for e in range(2):
            runner.run_epoch(e)
    recs = read_metrics(mpath)
    epochs = [r for r in recs if r["event"] == "epoch"]
    assert len(epochs) == 2
    for r in epochs:
        validate_record(r)
        assert r["grad_norm"] > 0 and r["halo_bytes"] > 0
    assert epochs[0]["staleness_age"] == 0
    assert epochs[1]["staleness_age"] == 1


def test_alert_and_span_records_roundtrip(tmp_path):
    """MetricsLogger.alert (hard-flushed) and .span write v10 records
    that validate and read back; stats() exposes the sink's record
    count and io-degradation state for the monitor exporter."""
    p = tmp_path / "a.jsonl"
    with MetricsLogger(p) as ml:
        ml.alert(rule="fault-rate", state="fire", severity="page",
                 source="*", value=3.0, threshold=1.0,
                 message="3 fault(s) in the last 60s")
        ml.alert(rule="fault-rate", state="resolve", severity="page",
                 source="*", value=None, threshold=None,
                 message="resolved")
        ml.span(trace_id="q1-serve", span_id="s1", op="queue",
                t_start=1234.5, dur_ms=2.25, status="ok", rows=4)
        st = ml.stats()
        assert st["records"] == 3
        assert st["degraded"] is False
        assert st["dropped"] == 0
    recs = read_metrics(p)
    assert [r["event"] for r in recs] == ["alert", "alert", "span"]
    for r in recs:
        validate_record(r)
    assert recs[0]["state"] == "fire"
    assert recs[1]["value"] is None
    assert recs[2]["trace_id"] == "q1-serve"
    # contract violations are loud
    with pytest.raises(ValueError):
        validate_record(dict(recs[2], dur_ms="fast"))
    with pytest.raises(ValueError):
        validate_record({k: v for k, v in recs[0].items()
                         if k != "message"})
