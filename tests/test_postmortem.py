"""Forensics suite: flight recorder, postmortem engine, fail-fast gate.

Covers the black-box plane end to end (docs/OBSERVABILITY.md
"Postmortem & flight recorder"):

  ring          bounded breadcrumb memory, span stack/annotation,
                env kill-switch, singleton identity under configure()
  dump          schema-v11 ``blackbox`` validation, atomic path,
                survival across a REAL ``os._exit(75)`` (subprocess
                drill through the coordinator's hard-deadline path)
  stalls        StallDetector fires once per episode and re-arms
  rules         one synthetic bundle per verdict class; ranking,
                deterministic tagging and clean-exit-beats-recovered
                are all pinned
  CLI           ``pipegcn-debug explain`` exit codes (0 / 4 / 1),
                --json, --out sink
  supervisor    deterministic verdicts stop after ONE gated retry
                (rc 1, ledger trigger ``deterministic:<class>``);
                transient verdicts keep the restart policy
  grammar       ``hang@E[:rN][:<ms>]`` parse/round-trip/rejection
  surfaces      LiveAggregator dump counting, /metrics gauge, report
                summary keys, soak invariant #6 helpers
  drill         (faults+slow) two-process ``hang@6:r1``: the wedged
                rank AND the survivor both leave black-box dumps and
                the explain CLI returns wedged-collective

Everything except the subprocess drills is tier-1-safe;
scripts/chaos.sh runs the ``forensics`` marker standalone.
"""

import json
import os
import socket
import subprocess
import sys
import time

import pytest

from pipegcn_tpu.obs import flight, read_metrics, validate_record
from pipegcn_tpu.obs import postmortem
from pipegcn_tpu.obs.flight import FlightRecorder, StallDetector
from pipegcn_tpu.obs.live import LiveAggregator
from pipegcn_tpu.obs.health import prometheus_text
from pipegcn_tpu.obs.metrics import MetricsLogger
from pipegcn_tpu.cli import debug as debug_cli
from pipegcn_tpu.cli.report import summarize_run
from pipegcn_tpu.resilience.faults import FaultPlan
from pipegcn_tpu.resilience.soak import check_diagnosis, expected_classes

pytestmark = pytest.mark.forensics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------- breadcrumb ring --------------------------------------


def test_ring_bounded_and_evicts_oldest():
    rec = FlightRecorder(capacity=8, rank=3, enabled=True)
    for i in range(20):
        rec.crumb("boundary", epoch=i)
    crumbs = rec.crumbs()
    assert len(crumbs) == 8  # bounded: the ring never grows past cap
    assert [c["epoch"] for c in crumbs] == list(range(12, 20))
    assert crumbs[-1] is not None and rec.last_crumb()["epoch"] == 19
    st = rec.stats()
    assert st["ring_depth"] == 8 and st["n_crumbs_total"] == 20
    assert st["enabled"] is True and st["dumps"] == 0


def test_env_kill_switch_disables_everything(monkeypatch, tmp_path):
    monkeypatch.setenv("PIPEGCN_FLIGHT", "0")
    rec = FlightRecorder(capacity=8)
    assert rec.enabled is False
    assert rec.crumb("boundary", epoch=1) is None
    assert rec.enter("collective", phase="x") is None
    assert rec.dump("manual", directory=str(tmp_path)) is None
    assert rec.crumbs() == [] and rec.open_spans() == []
    assert not os.listdir(tmp_path)


def test_span_stack_and_annotation():
    rec = FlightRecorder(capacity=32, enabled=True)
    rec.crumb("fit-start", epoch=0)
    rec.enter("dispatch", epoch=5)
    rec.enter("collective", phase="transition", epoch=5)
    # annotation = innermost OPEN span: the phase a hang would name
    ann = rec.annotation()
    assert ann["kind"] == "collective-enter"
    assert ann["phase"] == "transition" and ann["epoch"] == 5
    rec.exit("collective")
    assert rec.annotation()["kind"] == "dispatch-enter"
    rec.exit("dispatch")
    # nothing open -> fall back to the newest crumb
    assert rec.annotation()["kind"] == "dispatch-exit"
    assert rec.open_spans() == []
    # the span context manager records the exception on the exit crumb
    with pytest.raises(RuntimeError):
        with rec.span("checkpoint", epoch=6):
            raise RuntimeError("boom")
    assert rec.open_spans() == []
    last = rec.last_crumb()
    assert last["kind"] == "checkpoint-exit"
    assert "RuntimeError: boom" in last["error"]


def test_capture_stacks_names_last_breadcrumb():
    rec = FlightRecorder(capacity=16, enabled=True)
    rec.enter("collective", phase="fault-hang", epoch=11, peer=1)
    text = flight.capture_stacks(rec)
    head = text.splitlines()[0]
    assert head.startswith("# last breadcrumb:")
    assert "phase=fault-hang" in head and "epoch=11" in head
    # faulthandler really captured this (the running test frame)
    assert "test_postmortem" in text


def test_configure_preserves_singleton_identity():
    rec = flight.get_recorder()
    saved = (rec.rank, rec.dump_dir, rec.capacity, rec.enabled)
    try:
        rec2 = flight.configure(rank=5, capacity=max(rec.capacity, 16))
        assert rec2 is rec  # instrumentation holds references: identity
        assert rec.rank == 5
        rec.crumb("cfg-probe", epoch=1)
        # a capacity change re-bounds in place, keeping newest crumbs
        flight.configure(capacity=4)
        assert rec.capacity == 4
        assert any(c["kind"] == "cfg-probe" for c in rec.crumbs())
    finally:
        flight.configure(rank=saved[0], dump_dir=saved[1] or None,
                         capacity=saved[2], enabled=saved[3])


# ---------------- dumping ----------------------------------------------


def test_dump_validates_as_blackbox_record(tmp_path):
    rec = FlightRecorder(capacity=16, rank=3, enabled=True)
    rec.crumb("fit-start", epoch=0)
    rec.enter("collective", phase="transition", epoch=7)
    path = rec.dump("watchdog", directory=str(tmp_path),
                    stacks=flight.capture_stacks(rec), peer_rank=1)
    assert path == str(tmp_path / "blackbox-r3.json")
    assert rec.dumps == [path]
    with open(path) as fh:
        payload = json.load(fh)
    validate_record(payload)  # schema-v11 ``blackbox`` kind
    assert payload["event"] == "blackbox"
    assert payload["rank"] == 3 and payload["reason"] == "watchdog"
    assert payload["peer_rank"] == 1
    assert payload["open_spans"][0]["phase"] == "transition"
    assert payload["annotation"]["epoch"] == 7
    assert "# last breadcrumb:" in payload["stacks"]
    assert any(c["kind"] == "fit-start" for c in payload["crumbs"])


def test_dump_failure_never_propagates(tmp_path):
    rec = FlightRecorder(capacity=8, rank=0, enabled=True)
    rec.crumb("x")
    target = tmp_path / "not-a-dir"
    target.write_text("a file where the dump dir should be")
    assert rec.dump("fault", directory=str(target)) is None
    assert rec.stats()["dump_failures"] == 1 and rec.dumps == []


def test_dump_survives_hard_exit_subprocess(tmp_path):
    """The acceptance drill in miniature: the coordinator's watchdog
    hard-deadline path dumps the black box and then REALLY calls
    ``os._exit(75)`` — the file must be on disk afterwards, stacks
    annotated with the wedged phase."""
    d = str(tmp_path)
    script = (
        "import os, sys\n"
        "sys.path.insert(0, sys.argv[2])\n"
        "from pipegcn_tpu.resilience.coord import Coordinator, CoordConfig\n"
        "from pipegcn_tpu.obs.metrics import MetricsLogger\n"
        "from pipegcn_tpu.obs import flight\n"
        "d = sys.argv[1]\n"
        "flight.configure(rank=0, dump_dir=d)\n"
        "rec = flight.get_recorder()\n"
        "rec.crumb('fit-start', epoch=0)\n"
        "rec.enter('collective', phase='transition', epoch=8)\n"
        "c = Coordinator(rank=0, n_ranks=2, cfg=CoordConfig(dir=d),\n"
        "                metrics=MetricsLogger(os.path.join(d, 'm.jsonl')),\n"
        "                log=lambda s: print(s), force_active=True)\n"
        "c.note_progress(8)\n"
        "c._on_hard_deadline(1, 12.5)\n"
        "print('UNREACHABLE')\n"
    )
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
           "PYTHONPATH": REPO}
    proc = subprocess.run([sys.executable, "-c", script, d, REPO],
                          env=env, capture_output=True, text=True,
                          timeout=180)
    assert proc.returncode == 75, proc.stdout + proc.stderr
    assert "UNREACHABLE" not in proc.stdout  # _exit really fired
    box = tmp_path / "blackbox-r0.json"
    assert box.exists(), os.listdir(d)
    payload = json.loads(box.read_text())
    validate_record(payload)
    assert payload["reason"] == "watchdog" and payload["peer_rank"] == 1
    assert "phase=transition" in payload["stacks"]
    # the peer-lost fault record was hard-flushed before the exit
    recs = read_metrics(tmp_path / "m.jsonl")
    assert any(r.get("event") == "fault" and r.get("kind") == "peer-lost"
               for r in recs)
    # and the postmortem over the dir names the wedge from these two
    v = postmortem.diagnose_run(d)
    assert v["verdict"] == "wedged-collective"
    assert v["confidence"] >= 0.9 and len(v["evidence"]) >= 3


def test_stall_detector_fires_once_then_rearms(tmp_path):
    rec = FlightRecorder(capacity=16, rank=0, enabled=True)
    rec.crumb("fit-start", epoch=0)
    det = StallDetector(rec, threshold_s=0.15, poll_s=0.03,
                        directory=str(tmp_path)).start()
    try:
        deadline = time.time() + 10.0
        while det.stalls == 0 and time.time() < deadline:
            time.sleep(0.02)
        assert det.stalls == 1
        time.sleep(0.4)  # still stalled: must NOT fire again
        assert det.stalls == 1
        rec.crumb("boundary", epoch=1)  # progress re-arms
        deadline = time.time() + 10.0
        while det.stalls == 1 and time.time() < deadline:
            time.sleep(0.02)
        assert det.stalls == 2
    finally:
        det.stop()
    payload = json.loads((tmp_path / "blackbox-r0.json").read_text())
    validate_record(payload)
    assert payload["reason"] == "stall"
    assert any(c["kind"] == "stall-detected" for c in payload["crumbs"])


# ---------------- rule engine (synthetic bundles) ----------------------


def _bundle(records=(), blackboxes=(), log_tails=None):
    return {"run_dir": "/bundle", "collected_unix": 2_000_000.0,
            "blackboxes": list(blackboxes), "records": list(records),
            "log_tails": dict(log_tails or {}), "checkpoints": [],
            "streams": [], "fingerprint": {}}


def _box(reason, rank=0, t=1_000_000.0, **extra):
    data = {"event": "blackbox", "rank": rank, "reason": reason,
            "time_unix": t, "crumbs": [], "last_crumb": None,
            "open_spans": [], "stacks": None, **extra}
    return {"path": f"blackbox-r{rank}.json", "data": data}


def test_verdict_wedged_collective():
    b = _bundle(
        records=[{"event": "fault", "kind": "peer-lost", "epoch": 8,
                  "peer_rank": 1, "hard_deadline": True,
                  "time_unix": 1_000_000.0}],
        blackboxes=[_box("watchdog",
                         annotation={"phase": "transition", "epoch": 8},
                         stacks="# last breadcrumb: phase=transition",
                         open_spans=[{"kind": "collective-enter",
                                      "phase": "transition",
                                      "epoch": 8}])])
    v = postmortem.diagnose(b)
    assert v["verdict"] == "wedged-collective"
    assert v["confidence"] == pytest.approx(0.9)
    assert v["deterministic"] is False
    assert len(v["evidence"]) >= 3  # dump + stacks + fault + open span
    assert any("peer-lost" in e for e in v["evidence"])
    assert any("never exited" in e for e in v["evidence"])
    validate_record(v)  # schema-v11 ``diagnosis`` kind


def test_verdict_oom():
    b = _bundle(log_tails={"rank-g0-m1.log":
                           "E0807 RESOURCE_EXHAUSTED: Out of memory "
                           "allocating 2.1G\n"})
    v = postmortem.diagnose(b)
    assert v["verdict"] == "oom" and v["deterministic"] is False
    assert any("RESOURCE_EXHAUSTED" in e for e in v["evidence"])


def test_verdict_fallback_exhausted_is_deterministic():
    b = _bundle(
        records=[{"event": "fallback", "from_impl": "block",
                  "to_impl": "xla", "epoch": 4,
                  "time_unix": 1_000_000.0}],
        log_tails={"rank.log": "KernelFallbackError: every rung of the "
                               "kernel fallback ladder failed\n"})
    v = postmortem.diagnose(b)
    assert v["verdict"] == "fallback-exhausted"
    assert v["deterministic"] is True
    assert any("fallback record" in e for e in v["evidence"])


def test_verdict_corrupt_artifact_is_deterministic():
    b = _bundle(log_tails={"sup.log": "CheckpointCorrupt: digest "
                                      "mismatch for params/w0\n"})
    v = postmortem.diagnose(b)
    assert v["verdict"] == "corrupt-artifact"
    assert v["deterministic"] is True


def test_verdict_config_error_beats_generic_crash():
    # reason="exception" also matches the crash rule (0.65): the
    # config rule (0.8) must win the ranking
    b = _bundle(blackboxes=[_box("exception",
                                 error="ValueError: --n-partitions "
                                       "must divide the mesh")])
    v = postmortem.diagnose(b)
    assert v["verdict"] == "config-error" and v["deterministic"] is True
    cands = {c["verdict"] for c in v["candidates"]}
    assert "crash" in cands  # considered, outranked


def test_verdict_desync_and_storage_fault():
    v = postmortem.diagnose(_bundle(
        records=[{"event": "fault", "kind": "desync", "epoch": 6,
                  "source_rank": 1, "time_unix": 1_000_000.0}]))
    assert v["verdict"] == "desync"
    assert v["confidence"] == pytest.approx(0.8)
    v = postmortem.diagnose(_bundle(
        records=[{"event": "fault", "kind": "io-degraded", "epoch": 5,
                  "component": "checkpoint",
                  "time_unix": 1_000_000.0}]))
    assert v["verdict"] == "storage-fault"
    assert v["confidence"] == pytest.approx(0.8)
    assert v["deterministic"] is False


def test_verdict_divergence_when_retries_exhausted():
    b = _bundle(
        records=[{"event": "fault", "kind": "divergence", "epoch": 9,
                  "retry": 3, "reason": "nan-loss",
                  "time_unix": 1_000_000.0}],
        log_tails={"rank.log": "DivergenceError: retries were "
                               "exhausted\n"})
    v = postmortem.diagnose(b)
    assert v["verdict"] == "divergence"
    assert v["confidence"] == pytest.approx(0.85)


def test_verdict_preemption_and_crash():
    v = postmortem.diagnose(_bundle(blackboxes=[_box("preemption",
                                                     epoch=12)]))
    assert v["verdict"] == "preemption"
    v = postmortem.diagnose(_bundle(
        blackboxes=[_box("exception", error="RuntimeError: boom")],
        log_tails={"r.log": "Traceback (most recent call last):\n"
                            "RuntimeError: boom\n"}))
    assert v["verdict"] == "crash" and v["deterministic"] is False


def test_verdict_recompile_storm_needs_three_citations():
    repad = [{"event": "stream", "seq": i, "repadded": True,
              "epoch": 2 + i, "time_unix": 1_000_000.0 + i}
             for i in range(3)]
    assert postmortem.diagnose(
        _bundle(records=repad))["verdict"] == "recompile-storm"
    # two citations are not enough: stays unknown
    assert postmortem.diagnose(
        _bundle(records=repad[:2]))["verdict"] == "unknown"


def test_clean_exit_beats_recovered_faults_but_not_later_dumps():
    recovered = [
        {"event": "fault", "kind": "divergence", "epoch": 5,
         "time_unix": 1_000_000.0},
        {"event": "recovery", "kind": "divergence", "epoch": 5,
         "time_unix": 1_000_100.0},
        {"event": "summary", "time_unix": 1_000_500.0},
    ]
    v = postmortem.diagnose(_bundle(records=recovered))
    assert v["verdict"] == "clean-exit"
    assert v["confidence"] == pytest.approx(0.9)
    assert any("recovered" in e for e in v["evidence"])
    # a dump NEWER than the last summary means something died after:
    # clean-exit must stand down
    v = postmortem.diagnose(_bundle(
        records=recovered,
        blackboxes=[_box("watchdog", t=1_000_900.0,
                         stacks="# last breadcrumb: phase=transition")]))
    assert v["verdict"] == "wedged-collective"
    # ... but a trailing STALL dump is non-terminal by design (the
    # detector captures stacks and the run keeps going): a completed
    # run with one must still diagnose clean-exit
    v = postmortem.diagnose(_bundle(
        records=recovered,
        blackboxes=[_box("stall", t=1_000_900.0)]))
    assert v["verdict"] == "clean-exit"


def test_unknown_on_empty_bundle_and_timeline_renders():
    v = postmortem.diagnose(_bundle())
    assert v["verdict"] == "unknown" and v["confidence"] == 0.0
    assert v["deterministic"] is False and v["evidence"]
    # timeline merges records and crumbs, newest-relative
    b = _bundle(
        records=[{"event": "epoch", "epoch": 3, "loss": 0.5,
                  "time_unix": 1_000_000.0}],
        blackboxes=[_box("watchdog", t=1_000_010.0,
                         crumbs=[{"kind": "boundary", "epoch": 3,
                                  "t": 1_000_005.0, "seq": 1}])])
    v = postmortem.diagnose(b)
    tl = v["timeline"]
    assert any("epoch 3" in ln for ln in tl)
    assert any("crumb boundary" in ln for ln in tl)
    assert "BLACKBOX DUMP r0" in tl[-1]
    text = postmortem.render(v)
    assert "verdict:" in text and "last-minutes timeline:" in text


def test_deterministic_classes_are_exactly_the_contract():
    assert postmortem.DETERMINISTIC_CLASSES == (
        "corrupt-artifact", "config-error", "fallback-exhausted")
    for cls in postmortem.DETERMINISTIC_CLASSES:
        assert any(name == cls for name, _ in postmortem._RULES)


def test_broken_rule_cannot_kill_diagnosis(monkeypatch):
    def _explode(b):
        raise RuntimeError("rule bug")
    monkeypatch.setattr(postmortem, "_RULES",
                        [("exploder", _explode)]
                        + list(postmortem._RULES))
    b = _bundle(log_tails={"r.log": "RESOURCE_EXHAUSTED\n"})
    v = postmortem.diagnose(b)
    assert v["verdict"] == "oom"  # the healthy rules still ran


def test_collect_bundle_tolerates_corrupt_artifacts(tmp_path):
    (tmp_path / "blackbox-r0.json").write_text("{not json")
    (tmp_path / "rank.log").write_text("x" * 10_000 + "\nlast line\n")
    ml = MetricsLogger(str(tmp_path / "metrics.jsonl"))
    ml.summary(4, 0.1, 0.5)
    ml.close()
    b = postmortem.collect_bundle(str(tmp_path))
    assert b["blackboxes"][0].get("error")  # tolerated, not raised
    assert len(b["log_tails"]["rank.log"]) <= 4001  # tail-bounded
    assert b["log_tails"]["rank.log"].endswith("last line\n")
    assert any(r.get("event") == "summary" for r in b["records"])
    assert b["fingerprint"].get("schema_version")
    assert postmortem.diagnose(b)["verdict"] == "clean-exit"


# ---------------- explain CLI ------------------------------------------


def test_explain_cli_diagnosed_and_unknown_exit_codes(tmp_path, capsys):
    run = tmp_path / "run"
    run.mkdir()
    ml = MetricsLogger(str(run / "metrics.jsonl"))
    ml.summary(4, 0.1, 0.7)
    ml.close()
    assert debug_cli.main(["explain", str(run)]) == 0
    out = capsys.readouterr().out
    assert "verdict: clean-exit" in out and "confidence 0.90" in out
    # --json emits the contracted record
    assert debug_cli.main(["explain", str(run), "--json"]) == 0
    v = json.loads(capsys.readouterr().out)
    validate_record(v)
    assert v["verdict"] == "clean-exit"
    # --out appends a schema-valid diagnosis record to a metrics sink
    sink = tmp_path / "diag.jsonl"
    assert debug_cli.main(["explain", str(run), "--json",
                           "--out", str(sink)]) == 0
    capsys.readouterr()
    recs = read_metrics(sink)
    assert recs and recs[-1]["event"] == "diagnosis"
    validate_record(recs[-1])
    # nothing to go on -> exit 4 (EXIT_UNKNOWN)
    empty = tmp_path / "empty"
    empty.mkdir()
    assert debug_cli.main(["explain", str(empty)]) == debug_cli.EXIT_UNKNOWN
    capsys.readouterr()
    # not a directory -> usage error 1
    assert debug_cli.main(["explain", str(tmp_path / "nope")]) == 1


def test_debug_is_a_console_script():
    with open(os.path.join(REPO, "pyproject.toml")) as fh:
        text = fh.read()
    assert 'pipegcn-debug = "pipegcn_tpu.cli.debug:main"' in text


# ---------------- schema v11 drift pin ---------------------------------


def test_schema_v11_blackbox_and_diagnosis_pin():
    from pipegcn_tpu.obs import schema
    if schema.SCHEMA_VERSION == 11:
        assert set(schema.BLACKBOX_FIELDS) == {
            "event", "rank", "reason", "crumbs", "last_crumb",
            "open_spans", "stacks"}
        assert set(schema.DIAGNOSIS_FIELDS) == {
            "event", "verdict", "confidence", "evidence",
            "remediation", "deterministic"}
    else:
        # growing the schema is fine; silently shrinking v11 is not
        assert schema.SCHEMA_VERSION > 11
    assert "blackbox" in schema._BY_EVENT
    assert "diagnosis" in schema._BY_EVENT


# ---------------- supervisor fail-fast gate ----------------------------


class _FakeHandle:
    def __init__(self, rc):
        self.returncode = None
        self._rc = rc

    def poll(self):
        self.returncode = self._rc
        return self._rc

    def send_signal(self, sig):
        pass


class _FakeFleet:
    def __init__(self, rcs):
        self.rcs = list(rcs)
        self.launches = []

    def popen(self, cmd, env, log_path):
        self.launches.append(list(cmd))
        return _FakeHandle(self.rcs.pop(0))


def _sup(tmp_path, fleet, diagnose, max_restarts=5, monkeypatch=None):
    from pipegcn_tpu.resilience.elastic import (ElasticConfig,
                                                ElasticSupervisor)
    argv = [
        "--dataset", "synthetic:300:6:8:3",
        "--n-partitions", "2", "--parts-per-node", "2",
        "--n-epochs", "6", "--no-eval", "--fix-seed",
        "--partition-dir", str(tmp_path / "parts"),
        "--checkpoint-dir", str(tmp_path / "ck"),
    ]
    cfg = ElasticConfig(max_restarts=max_restarts, backoff_base_s=0.0,
                        backoff_max_s=0.0, poll_s=0.01,
                        storm_threshold=1000)
    sup = ElasticSupervisor(argv, cfg, popen=fleet.popen,
                            log=lambda s: None)
    monkeypatch.setattr(type(sup), "_diagnose_death",
                        lambda self, gen, victim: diagnose(gen, victim))
    return sup


def test_supervisor_fails_fast_after_one_gated_retry(tmp_path,
                                                     monkeypatch):
    """A deterministic verdict gets exactly ONE relaunch; when the
    retry dies the same way the supervisor stops HARD (rc 1, not 75)
    with the verdict in the ledger — no burning --max-restarts."""
    from pipegcn_tpu.resilience.elastic import MembershipLedger
    fleet = _FakeFleet([-9] * 10)
    seen = []

    def diagnose(gen, victim):
        seen.append((gen, victim))
        return {"verdict": "config-error", "confidence": 0.8,
                "deterministic": True, "evidence": ["e1"],
                "remediation": "fix the flag"}

    sup = _sup(tmp_path, fleet, diagnose, monkeypatch=monkeypatch)
    assert sup.run() == 1
    # gen 0 + the single gated retry (gen 1): two launches, not six
    assert len(fleet.launches) == 2
    assert seen == [(0, 0), (1, 0)]
    led = MembershipLedger(sup.coord_dir)
    final = led.latest()
    assert final["trigger"] == "deterministic:config-error"
    assert final["diagnosis"]["verdict"] == "config-error"
    assert final["diagnosis"]["deterministic"] is True
    # the retry generation's own record carries the diagnosis too
    assert led.read(1)["diagnosis"]["verdict"] == "config-error"
    recs = [r for r in read_metrics(
        os.path.join(sup.coord_dir, "membership.jsonl"))
        if r.get("event") == "membership"]
    assert recs[-1]["trigger"] == "deterministic:config-error"
    assert recs[-1]["diagnosis"] == "config-error"
    for r in recs:
        validate_record(r)


def test_supervisor_transient_verdict_keeps_restart_policy(tmp_path,
                                                           monkeypatch):
    from pipegcn_tpu.resilience import EXIT_PREEMPTED
    fleet = _FakeFleet([-9] * 10)

    def diagnose(gen, victim):
        return {"verdict": "crash", "confidence": 0.65,
                "deterministic": False, "evidence": [],
                "remediation": "read the cited error"}

    sup = _sup(tmp_path, fleet, diagnose, max_restarts=2,
               monkeypatch=monkeypatch)
    assert sup.run() == EXIT_PREEMPTED
    assert len(fleet.launches) == 3  # gens 0..2: the policy governed


def test_supervisor_diagnosis_failure_is_not_fatal(tmp_path,
                                                   monkeypatch):
    from pipegcn_tpu.resilience import EXIT_PREEMPTED
    fleet = _FakeFleet([-9] * 10)
    sup = _sup(tmp_path, fleet, lambda g, v: None, max_restarts=1,
               monkeypatch=monkeypatch)
    assert sup.run() == EXIT_PREEMPTED  # policy path, no crash


# ---------------- fault grammar: hang@E[:rN][:<ms>] --------------------


def test_hang_grammar_parses_and_round_trips():
    plan = FaultPlan.parse("hang@6:r1:250", rank=1)
    assert plan.remaining() == ["hang@6:r1:250"]
    assert plan.due_arg("hang", 6) == 250  # bounded stall, ms
    assert plan.due_arg("hang", 6) is None  # single-shot
    # unqualified ms arg
    assert FaultPlan.parse("hang@3:250").due_arg("hang", 3) == 250
    # no arg -> 0: the full wedge
    assert FaultPlan.parse("hang@6:r1", rank=1).due_arg("hang", 6) == 0
    # wrong rank never fires
    assert FaultPlan.parse("hang@6:r1", rank=0).due_arg("hang", 9) is None
    # slow-fs keeps its ms grammar
    assert FaultPlan.parse("slow-fs@3:500").due_arg("slow-fs", 3) == 500


def test_hang_grammar_rejections():
    with pytest.raises(ValueError, match="only valid for"):
        FaultPlan.parse("nan-loss@5:250")  # arg on a non-arg kind
    with pytest.raises(ValueError, match="at most one"):
        FaultPlan.parse("hang@6:250:9")
    with pytest.raises(ValueError, match="bad fault-plan entry"):
        FaultPlan.parse("hang@6:r1:250:9")


# ---------------- observability surfaces -------------------------------


def test_aggregator_counts_dumps_and_exports_gauge(tmp_path):
    rec = FlightRecorder(capacity=8, rank=0, enabled=True)
    rec.crumb("x")
    rec.dump("stall", directory=str(tmp_path))
    sub = tmp_path / "coord"
    sub.mkdir()
    rec2 = FlightRecorder(capacity=8, rank=1, enabled=True)
    rec2.crumb("y")
    rec2.dump("watchdog", directory=str(sub))
    ml = MetricsLogger(str(tmp_path / "metrics.jsonl"))
    ml.diagnosis(verdict="wedged-collective", confidence=0.9,
                 evidence=["e"], remediation="r", deterministic=False)
    ml.close()
    agg = LiveAggregator(str(tmp_path))
    agg.poll()
    assert agg.n_blackbox_dumps == 2  # recursive: subdirs count too
    snap = agg.snapshot()
    assert snap["n_blackbox_dumps"] == 2
    src = next(iter(snap["diagnosis"]))
    assert snap["diagnosis"][src]["verdict"] == "wedged-collective"
    text = prometheus_text(agg)
    assert "pipegcn_blackbox_dumps_total 2" in text
    assert ('pipegcn_diagnosis_confidence{deterministic="false",'
            'source="metrics",verdict="wedged-collective"} 0.9') in text


def test_report_surfaces_diagnosis(tmp_path):
    import io
    buf = io.StringIO()
    ml = MetricsLogger(buf)
    ml.diagnosis(verdict="storage-fault", confidence=0.8,
                 evidence=["fault record: io-degraded at epoch 5"],
                 remediation="free space, then --resume",
                 deterministic=False)
    ml.close()
    recs = [json.loads(ln) for ln in buf.getvalue().splitlines()]
    recs.append({"event": "blackbox", "rank": 0, "reason": "stall",
                 "crumbs": [], "last_crumb": None, "open_spans": [],
                 "stacks": None})
    s = summarize_run(recs)
    assert s["diagnosis_verdict"] == "storage-fault"
    assert s["diagnosis_confidence"] == pytest.approx(0.8)
    assert s["diagnosis_deterministic"] is False
    assert s["diagnosis_remediation"] == "free space, then --resume"
    assert s["n_blackbox_records"] == 1
    assert s["blackbox_reasons"] == {"stall": 1}


def test_soak_expected_classes_and_check_diagnosis(tmp_path):
    assert expected_classes(["hang@6:r1", "enospc@5"]) == [
        "storage-fault", "wedged-collective"]
    assert expected_classes(["corrupt-ckpt@4"]) == ["corrupt-artifact"]
    assert expected_classes([]) == ["crash"]
    assert expected_classes(["made-up@1"]) == ["crash"]
    # green episode: a summary record must diagnose clean-exit
    ml = MetricsLogger(str(tmp_path / "metrics.jsonl"))
    ml.summary(6, 0.1, 0.6)
    ml.close()
    inv = check_diagnosis(str(tmp_path), "green", ["nan-loss@5"])
    assert inv["ok"] is True and inv["verdict"] == "clean-exit"
    # red episode whose artifacts say corrupt-artifact, as scheduled
    red = tmp_path / "red"
    red.mkdir()
    (red / "rank.log").write_text(
        "CheckpointCorrupt: digest mismatch for params/w0\n")
    inv = check_diagnosis(str(red), "red", ["corrupt-ckpt@4"])
    assert inv["ok"] is True and inv["verdict"] == "corrupt-artifact"
    assert inv["deterministic"] is True
    # mismatch is reported, not raised
    inv = check_diagnosis(str(red), "red", ["sigterm@8"])
    assert inv["ok"] is False and "not in" in inv["error"]


def test_recorder_is_host_side_only():
    """Steady-state cost pin: recording crumbs/spans and dumping must
    not trigger a single trace — the serving engine's compile counters
    are the canary."""
    from pipegcn_tpu.serve.engine import trace_counts
    c0 = dict(trace_counts())
    rec = FlightRecorder(capacity=64, enabled=True)
    for i in range(200):
        with rec.span("dispatch", epoch=i):
            rec.crumb("boundary", epoch=i)
    flight.capture_stacks(rec)
    assert dict(trace_counts()) == c0


# ---------------- the two-process hang drill (faults + slow) -----------


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_rank(rank, port, tmp_path, extra, n_epochs, env_extra=None):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PYTHONPATH": REPO,
        "PYTHONUNBUFFERED": "1",
        **(env_extra or {}),
    }
    cmd = [
        sys.executable, os.path.join(REPO, "main.py"),
        "--dataset", "synthetic:400:6:8:3",
        "--n-partitions", "2", "--parts-per-node", "1",
        "--node-rank", str(rank),
        "--master-addr", "127.0.0.1", "--port", str(port),
        "--n-epochs", str(n_epochs), "--n-hidden", "16",
        "--dropout", "0.0", "--log-every", "1000",
        "--fix-seed", "--seed", "7", "--no-eval",
        "--partition-dir", str(tmp_path / "parts"),
        "--model-dir", str(tmp_path / f"model{rank}"),
        "--results-dir", str(tmp_path / f"results{rank}"),
        "--metrics-out", str(tmp_path / f"metrics{rank}.jsonl"),
    ] + extra
    return subprocess.Popen(cmd, env=env, cwd=REPO,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


def _communicate(proc, timeout):
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        out = (out or "") + "\n<<TIMED OUT>>"
    return out


@pytest.mark.faults
@pytest.mark.slow
def test_two_process_hang_drill_leaves_dumps_and_diagnoses(tmp_path):
    """Acceptance: ``hang@6:r1`` wedges rank 1 inside a fake collective
    (heartbeats suspended). Rank 1's stall detector (PIPEGCN_STALL_S)
    dumps stacks naming the wedged phase WHILE STILL WEDGED; the
    survivor's watchdog then converts its own dead collective into
    exit 75 + a watchdog dump. (When the leader exits, the wedged
    rank's jax runtime hard-aborts within milliseconds — the stall
    dump is already durable by then, which is exactly why the
    sub-watchdog path exists.) BOTH ranks leave
    ``blackbox-r<k>.json`` and ``pipegcn-debug explain`` over the run
    dir returns ``wedged-collective`` citing >= 3 artifacts."""
    from pipegcn_tpu.resilience import EXIT_PREEMPTED
    port = _free_port()
    wd_timeout = 6.0
    coord = tmp_path / "coord"
    flags = ["--checkpoint-dir", str(tmp_path / "ck"),
             "--checkpoint-every", "2000",
             "--watchdog-timeout", str(wd_timeout),
             "--watchdog-dir", str(coord),
             "--sentinel-snapshot-every", "10",
             "--fault-plan", "hang@6:r1"]
    procs = [_spawn_rank(r, port, tmp_path, flags, n_epochs=200000,
                         env_extra={"PIPEGCN_STALL_S": "2"})
             for r in (0, 1)]
    try:
        out0 = _communicate(procs[0], timeout=wd_timeout * 10 + 120)
        out1 = _communicate(procs[1], timeout=wd_timeout * 10 + 120)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert "fault-injected hang at epoch 6" in out1, out1[-3000:]
    assert procs[0].returncode == EXIT_PREEMPTED, \
        f"rank 0 exited {procs[0].returncode}:\n{out0[-3000:]}"
    # the wedged rank dies abnormally (jax hard-abort once the leader
    # is gone) — the point is that its forensics are already on disk
    assert procs[1].returncode != 0, out1[-3000:]
    # BOTH ranks left a black box
    for r in (0, 1):
        box = coord / f"blackbox-r{r}.json"
        assert box.exists(), \
            f"missing {box}; coord dir: {os.listdir(coord)}"
        payload = json.loads(box.read_text())
        validate_record(payload)
        assert payload["stacks"]
    # the survivor's dump is the watchdog trip
    p0 = json.loads((coord / "blackbox-r0.json").read_text())
    assert p0["reason"] == "watchdog"
    # the wedged rank's stall dump names the hung phase and epoch
    p1 = json.loads((coord / "blackbox-r1.json").read_text())
    assert p1["reason"] == "stall"
    assert any(sp.get("kind") == "collective-enter"
               and sp.get("phase") == "fault-hang"
               and sp.get("epoch") == 6
               for sp in p1["open_spans"]), p1["open_spans"]
    assert "phase=fault-hang" in p1["stacks"]
    # the explain CLI reaches the verdict with >= 3 evidence citations
    proc = subprocess.run(
        [sys.executable, "-m", "pipegcn_tpu.cli.debug", "explain",
         str(tmp_path), "--json"],
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO},
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    v = json.loads(proc.stdout)
    assert v["verdict"] == "wedged-collective"
    assert v["confidence"] >= 0.9
    assert len(v["evidence"]) >= 3
    assert v["deterministic"] is False  # restartable, not fail-fast


@pytest.mark.faults
@pytest.mark.slow
def test_single_process_bounded_stall_dumps_without_dying(tmp_path):
    """``hang@2:300`` (ms-bounded) + PIPEGCN_STALL_S: the stall
    detector leaves a reason="stall" dump while the run completes
    rc=0 — sub-watchdog forensics, no death."""
    coord = tmp_path / "coord"
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "PYTHONPATH": REPO,
        "PIPEGCN_STALL_S": "0.15",
    }
    cmd = [
        sys.executable, os.path.join(REPO, "main.py"),
        "--dataset", "synthetic:120:4:8:3",
        "--n-partitions", "2", "--parts-per-node", "2",
        "--n-epochs", "4", "--n-hidden", "8", "--dropout", "0.0",
        "--fix-seed", "--seed", "7", "--no-eval",
        "--partition-dir", str(tmp_path / "parts"),
        "--model-dir", str(tmp_path / "model"),
        "--results-dir", str(tmp_path / "results"),
        "--watchdog-dir", str(coord),
        "--fault-plan", "hang@2:300",
    ]
    proc = subprocess.run(cmd, env=env, cwd=REPO, timeout=420,
                          capture_output=True, text=True)
    tail = (proc.stdout + proc.stderr)[-3000:]
    assert proc.returncode == 0, tail
    assert "fault-injected 300 ms stall at epoch 2" in proc.stdout
    box = coord / "blackbox-r0.json"
    assert box.exists(), os.listdir(coord)
    payload = json.loads(box.read_text())
    validate_record(payload)
    assert payload["reason"] == "stall"
    crumbs = [c["kind"] for c in payload["crumbs"]]
    assert "stall-injected" in crumbs
