"""Numerical-robustness tests (docs/RESILIENCE.md "Numerics"): the
in-graph non-finite tripwire and its provenance, the loss-scale state
machine (backoff / step-skip / regrowth), the kernel fallback ladder,
the amax-clamped fp8 transport cast, and the products-shape NaN
regression — all tier-1-safe on the CPU mesh.
"""

import io
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipegcn_tpu.graph import synthetic_graph
from pipegcn_tpu.models import ModelConfig
from pipegcn_tpu.obs import MetricsLogger, validate_record
from pipegcn_tpu.ops.bucket_spmm import (
    amax_transport_cast,
    transport_cast,
)
from pipegcn_tpu.parallel import Trainer, TrainConfig
from pipegcn_tpu.partition import ShardedGraph, partition_graph
from pipegcn_tpu.resilience import (
    DivergenceSentinel,
    FaultPlan,
    KernelFallbackError,
    LossScaleConfig,
    LossScaler,
    SentinelConfig,
)
from pipegcn_tpu.resilience.numerics import (
    PHASES,
    epoch_nonfinite_counts,
    fallback_ladder,
    first_nonfinite_phase,
    is_kernel_error,
    sanitize_for_sentinel,
    summarize_numerics,
)

pytestmark = pytest.mark.numerics


@pytest.fixture(scope="module")
def sharded():
    g = synthetic_graph(num_nodes=300, avg_degree=6, n_feat=8, n_class=3,
                        seed=1)
    parts = partition_graph(g, 2, seed=0)
    return ShardedGraph.build(g, parts, n_parts=2)


def _trainer(sg, *, mkw=None, **tkw):
    mkw = dict(mkw or {})
    mkw.setdefault("layer_sizes", (sg.n_feat, 16, sg.n_class))
    mkw.setdefault("dropout", 0.0)
    mkw.setdefault("train_size", sg.n_train_global)
    tkw.setdefault("n_epochs", 10)
    tkw.setdefault("log_every", 50)
    return Trainer(sg, ModelConfig(**mkw), TrainConfig(**tkw))


# ---------------- loss-scale state machine (host) ---------------------


def test_loss_scale_config_parse():
    assert not LossScaleConfig.parse("off").enabled
    assert not LossScaleConfig.parse("").enabled
    auto = LossScaleConfig.parse("auto")
    assert auto.mode == "auto" and auto.enabled
    stat = LossScaleConfig.parse("1024")
    assert stat.mode == "static" and stat.init_scale == 1024.0
    with pytest.raises(ValueError, match="auto"):
        LossScaleConfig.parse("warp9")
    with pytest.raises(ValueError, match="positive"):
        LossScaleConfig.parse("-4")
    with pytest.raises(ValueError, match="positive"):
        LossScaleConfig.parse("inf")


def test_loss_scaler_backoff_skip_and_regrow():
    s = LossScaler(LossScaleConfig(mode="auto", init_scale=1024.0,
                                   growth_interval=3))
    assert s.scale == 1024.0
    # clean epochs: no events until the growth interval fills
    assert s.update(0, [0, 0]) == []
    # an overflow halves the scale and counts the skipped step
    evs = s.update(2, [1])
    assert [e["kind"] for e in evs] == ["overflow"]
    assert evs[0]["skipped"] and evs[0]["new_scale"] == 512.0
    assert s.scale == 512.0 and s.n_skipped == 1 and s.n_backoffs == 1
    # the overflow reset the clean streak; 3 clean epochs regrow
    evs = s.update(3, [0, 0, 0])
    assert [e["kind"] for e in evs] == ["growth"]
    assert s.scale == 1024.0 and s.n_growths == 1
    # static mode: skips counted, scale never moves
    st = LossScaler(LossScaleConfig(mode="static", init_scale=64.0))
    evs = st.update(0, [1])
    assert evs[0]["kind"] == "overflow" and "new_scale" not in evs[0]
    assert st.scale == 64.0 and st.n_skipped == 1
    # disabled: flags are ignored entirely
    off = LossScaler(LossScaleConfig())
    assert off.update(0, [1, 1]) == [] and off.scale == 1.0


def test_loss_scaler_respects_scale_bounds():
    s = LossScaler(LossScaleConfig(mode="auto", init_scale=2.0,
                                   min_scale=1.0, max_scale=4.0,
                                   growth_interval=1))
    s.update(0, [1])          # 2 -> 1
    assert s.scale == 1.0
    evs = s.update(1, [1])    # would go below min: skip counted, no halve
    assert evs[0]["kind"] == "overflow" and "new_scale" not in evs[0]
    assert s.scale == 1.0
    s.update(2, [0])          # 1 -> 2
    s.update(3, [0])          # 2 -> 4
    s.update(4, [0])          # at max: stays
    assert s.scale == 4.0


def test_sanitize_for_sentinel_masks_overflow_epochs():
    losses = [1.0, np.nan, 0.8]
    gn = [0.5, np.inf, 0.4]
    sl, sg_ = sanitize_for_sentinel(losses, gn, [0, 1, 0])
    assert np.isfinite(sl).all() and np.isfinite(sg_).all()
    assert sl[1] == 1.0 and sg_[1] == 0.5   # nearest preceding clean
    # a block that STARTS flagged borrows the first clean value
    sl, _ = sanitize_for_sentinel([np.nan, 2.0], [np.inf, 1.0], [1, 0])
    assert sl[0] == 2.0
    # fully-flagged block: nothing for the sentinel to check
    assert sanitize_for_sentinel([np.nan], [np.nan], [1]) == (None, None)


# ---------------- tripwire provenance (host helpers) ------------------


def test_first_nonfinite_phase_dataflow_order():
    assert first_nonfinite_phase({}) is None
    assert first_nonfinite_phase({ph: 0 for ph in PHASES}) is None
    # contamination cascades downstream; the FIRST phase is the cause
    assert first_nonfinite_phase(
        {"loss": 1, "spmm": 12, "dense": 3, "grads": 99}) == "spmm"
    assert first_nonfinite_phase({"grads": 4}) == "grads"
    # fused-block [k]-arrays count as tripped when any epoch tripped
    assert first_nonfinite_phase({"dense": [0, 2, 0]}) == "dense"


def test_epoch_nonfinite_counts_slices_fused_blocks():
    nm = {"spmm": [0, 7, 0], "loss": [0, 1, 0], "dense": 0}
    assert epoch_nonfinite_counts(nm, 1) == {"spmm": 7, "loss": 1}
    assert epoch_nonfinite_counts(nm, 0) == {}


# ---------------- kernel fallback ladder (host helpers) ---------------


def test_fallback_ladder_order():
    assert fallback_ladder("block") == ["bucket", "xla"]
    # unknown/retired kernel names degrade straight to the workhorse
    assert fallback_ladder("pallas") == ["xla"]
    assert fallback_ladder("bucket") == ["xla"]
    assert fallback_ladder("gat-bucket") == ["xla"]
    assert fallback_ladder("xla") == []


def test_is_kernel_error_classification():
    assert is_kernel_error(RuntimeError("INTERNAL: TPU backend error"))
    assert is_kernel_error(RuntimeError("RESOURCE EXHAUSTED: vmem"))
    assert is_kernel_error(RuntimeError(
        "fault-injected kernel dispatch failure"))
    assert not is_kernel_error(ValueError("bad flag"))
    assert not is_kernel_error(KeyboardInterrupt())


# ---------------- amax-clamped fp8 cast -------------------------------


def test_amax_cast_avoids_saturation_and_underflow():
    # large activations: the static e4m3 clamp saturates at +-448 and
    # biases the mean; the amax cast rescales into range
    x = jnp.asarray(np.linspace(-4000.0, 4000.0, 64, dtype=np.float32))
    y_static = transport_cast(x, jnp.float8_e4m3fn).astype(jnp.float32)
    assert float(jnp.max(jnp.abs(y_static))) <= 448.0  # saturated
    y, inv = amax_transport_cast(x, jnp.float8_e4m3fn)
    back = y.astype(jnp.float32) * inv
    assert np.allclose(np.asarray(back), np.asarray(x), rtol=0.08)
    # tiny cotangents: e5m2's smallest subnormal is ~1.5e-5 — the
    # static cast flushes to zero, the amax cast preserves them
    t = jnp.asarray(np.full(8, 3e-7, np.float32))
    flushed = transport_cast(t, jnp.float8_e5m2).astype(jnp.float32)
    assert float(jnp.max(jnp.abs(flushed))) == 0.0
    y, inv = amax_transport_cast(t, jnp.float8_e5m2)
    back = np.asarray(y.astype(jnp.float32) * inv)
    assert np.all(back > 0) and np.allclose(back, 3e-7, rtol=0.3)
    # degenerate inputs stay degenerate, never a NaN scale
    z, invz = amax_transport_cast(jnp.zeros(4), jnp.float8_e4m3fn)
    assert float(invz) == 1.0 and not np.any(np.asarray(z))
    n, _ = amax_transport_cast(jnp.asarray([np.nan, 1.0]),
                               jnp.float8_e4m3fn)
    assert np.isnan(np.asarray(n.astype(jnp.float32))[0])
    # non-fp8 targets fall back to the plain saturating cast
    b, invb = amax_transport_cast(x, jnp.bfloat16)
    assert invb is None and b.dtype == jnp.bfloat16


# ---------------- tripwire in the jitted step -------------------------


def test_tripwire_counts_ride_step_metrics(sharded):
    t = _trainer(sharded, enable_pipeline=True)
    t.train_epoch(0)
    nm = {k: int(v) for k, v in t._last_metrics["numerics"].items()}
    assert set(nm) == set(PHASES)
    assert all(v == 0 for v in nm.values())
    # fused blocks carry [k]-arrays of counts
    t.train_epochs(1, 3)
    nm = t._last_metrics["numerics"]
    assert all(np.asarray(v).shape == (3,) for v in nm.values())


def test_tripwire_names_birth_phase_on_poisoned_input(sharded):
    t = _trainer(sharded, enable_pipeline=True)
    feat = np.array(np.asarray(t.data["feat"]))
    feat[0, 3, 1] = np.nan
    t.data["feat"] = jax.device_put(jnp.asarray(feat), t._shard)
    loss = t.train_epoch(0)
    assert not np.isfinite(loss)
    nm = {k: int(v) for k, v in t._last_metrics["numerics"].items()}
    assert nm["input"] == 1            # exactly the poisoned element
    assert nm["loss"] >= 1 and nm["grads"] >= 1
    assert first_nonfinite_phase(nm) == "input"


def test_tripwire_off_drops_counts(sharded):
    t = _trainer(sharded, numerics_tripwire=False)
    t.train_epoch(0)
    assert "numerics" not in t._last_metrics


def test_fit_fault_record_names_phase(sharded):
    """A REAL in-graph NaN (not an injected host-side one) trips the
    sentinel AND the fault record carries the tripwire's birth phase,
    plus a contracted `numerics` kind="tripwire" record."""
    t = _trainer(sharded, enable_pipeline=True, n_epochs=6)
    feat = np.array(np.asarray(t.data["feat"]))
    feat[1, 2, 0] = np.inf
    t.data["feat"] = jax.device_put(jnp.asarray(feat), t._shard)
    buf = io.StringIO()
    with pytest.raises(Exception):  # retries re-hit the poisoned input
        t.fit(eval_graphs=None, log_fn=lambda s: None,
              metrics=MetricsLogger(buf),
              sentinel=DivergenceSentinel(SentinelConfig(max_retries=1)))
    recs = [json.loads(line) for line in buf.getvalue().splitlines()]
    faults = [r for r in recs if r["event"] == "fault"]
    assert faults and faults[0]["phase"] == "input"
    trip = [r for r in recs if r["event"] == "numerics"
            and r["kind"] == "tripwire"]
    assert trip and trip[0]["phase"] == "input"
    assert trip[0]["counts"].get("input") == 1
    for r in trip:
        validate_record(r)
    assert summarize_numerics(recs)["first_nan_phase"] == "input"


# ---------------- loss scaling in the jitted step ---------------------


def test_static_loss_scale_matches_unscaled_trajectory(sharded):
    """Scaling multiplies the loss before backward and divides the
    reduced grads after — in f32, a power-of-two scale must reproduce
    the unscaled trajectory almost exactly."""
    t0 = _trainer(sharded, enable_pipeline=True, seed=3)
    t1 = _trainer(sharded, enable_pipeline=True, seed=3,
                  loss_scale="1024")
    for e in range(4):
        l0 = t0.train_epoch(e)
        l1 = t1.train_epoch(e)
        assert abs(l0 - l1) < 1e-4 * max(1.0, abs(l0))
    assert int(t1._last_metrics["overflow"]) == 0


def test_overflow_skips_step_and_backs_off_in_fit(sharded):
    """Injected overflow: the scaler halves the scale, counts the
    skip, emits a contracted `numerics` record — and the sentinel does
    NOT mistake the handled overflow for divergence."""
    t = _trainer(sharded, enable_pipeline=True, n_epochs=8,
                 loss_scale="auto")
    buf = io.StringIO()
    logs = []
    t.fit(eval_graphs=None, log_fn=logs.append,
          metrics=MetricsLogger(buf),
          sentinel=DivergenceSentinel(SentinelConfig()),
          fault_plan=FaultPlan.parse("overflow@3"))
    recs = [json.loads(line) for line in buf.getvalue().splitlines()]
    ovf = [r for r in recs if r["event"] == "numerics"
           and r["kind"] == "overflow"]
    assert len(ovf) == 1 and ovf[0]["epoch"] == 3
    assert ovf[0]["skipped"] and ovf[0]["new_scale"] == ovf[0]["scale"] / 2
    for r in ovf:
        validate_record(r)
    # no divergence fault, no rollback — the overflow was handled
    assert not any(r["event"] == "fault" for r in recs)
    assert t.loss_scaler.n_skipped == 1
    assert t.loss_scaler.scale == LossScaleConfig.parse("auto").init_scale / 2
    s = summarize_numerics(recs)
    assert s["loss_scale_skips"] == 1 and s["loss_scale_backoffs"] == 1
    assert any("step skipped" in line for line in logs)


# ---------------- kernel fallback ladder (trainer) --------------------


def test_kernel_crash_downgrades_and_completes(sharded, tmp_path):
    """Acceptance: a simulated kernel-dispatch failure completes
    training via an automatic logged fallback instead of crashing."""
    t = _trainer(sharded, mkw={"spmm_impl": "block", "block_tile": 16},
                 enable_pipeline=True, n_epochs=6)
    assert t._current_impl() == "block"
    buf = io.StringIO()
    logs = []
    res = t.fit(eval_graphs=None, log_fn=logs.append,
                metrics=MetricsLogger(buf),
                fault_plan=FaultPlan.parse("kernel-crash@2"))
    assert t._current_impl() == "bucket"     # one rung down, not two
    assert t.last_epoch == t.tcfg.n_epochs
    assert res["history"] or True
    recs = [json.loads(line) for line in buf.getvalue().splitlines()]
    falls = [r for r in recs if r["event"] == "fallback"]
    assert len(falls) == 1
    assert falls[0]["from_impl"] == "block"
    assert falls[0]["to_impl"] == "bucket"
    assert "fault-injected" in falls[0]["reason"]
    for r in falls:
        validate_record(r)
    # every epoch record is finite: the downgraded kernel trained on
    losses = [r["loss"] for r in recs if r["event"] == "epoch"]
    assert len(losses) == 6 and np.isfinite(losses).all()
    assert any("kernel fallback: block -> bucket" in line
               for line in logs)
    assert summarize_numerics(recs)["kernel_fallbacks"] == \
        ["block->bucket"]


def test_fallback_ladder_exhaustion_raises(sharded):
    t = _trainer(sharded, mkw={"spmm_impl": "xla"})
    t._inject_kernel_crash = True
    with pytest.raises(KernelFallbackError, match="no fallback rung"):
        t.train_epoch(0)


def test_downgraded_trainer_keeps_trajectory(sharded):
    """The fallback rebuilds tables + step but restores the
    pre-dispatch state: the downgraded run's losses stay finite and
    the retried epoch re-runs (bucket and block kernels are
    numerically equivalent formulations of the same mean)."""
    ref = _trainer(sharded, mkw={"spmm_impl": "bucket"},
                   enable_pipeline=True, seed=11)
    ref_losses = [ref.train_epoch(e) for e in range(3)]
    t = _trainer(sharded, mkw={"spmm_impl": "block", "block_tile": 16},
                 enable_pipeline=True, seed=11)
    t._inject_kernel_crash = True
    losses = [t.train_epoch(e) for e in range(3)]
    assert t._current_impl() == "bucket"
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)


# ---------------- kernel-table bounds validation ----------------------


def test_bucket_table_validation_catches_oob(sharded):
    """The kernels gather with mode='clip' on the strength of the
    host-side bounds check: an out-of-bounds index (build bug, rotted
    cache) must raise a NAMED error at build/load time — under the old
    fill-mode gathers it minted NaN silently mid-epoch."""
    from pipegcn_tpu.ops.bucket_spmm import (
        build_sharded_bucket_tables,
        validate_bucket_tables,
    )

    sg = sharded
    tables = build_sharded_bucket_tables(sg)  # validates internally
    n_src = sg.n_max + sg.halo_size
    validate_bucket_tables(tables, sg.n_max, n_src)
    bad = {k: np.array(v) for k, v in tables.items()}
    key = next(k for k in bad
               if k.startswith("bkt_fwd_") and not k.endswith("inv"))
    bad[key].reshape(-1)[0] = n_src + 7
    with pytest.raises(ValueError, match="out-of-bounds"):
        validate_bucket_tables(bad, sg.n_max, n_src)
    bad[key].reshape(-1)[0] = -3
    with pytest.raises(ValueError, match="out-of-bounds"):
        validate_bucket_tables(bad, sg.n_max, n_src)


# ---------------- products-shape NaN regression -----------------------


@pytest.fixture(scope="module")
def products_shape():
    """Reduced-node-count synthetic with the ogbn-products SHAPE
    statistics (deg ~51, F=100, 47 classes) — the config family whose
    full-scale run trained to loss=nan on chip (VERDICT r5)."""
    g = synthetic_graph(num_nodes=6000, avg_degree=51, n_feat=100,
                        n_class=47, seed=0)
    parts = partition_graph(g, 1, seed=0)
    return ShardedGraph.build(g, parts, n_parts=1)


def test_products_shape_f8_config_trains_finite(products_shape):
    """Regression pin for the products-shape NaN config: use_pp + bf16
    + fp8 remainder + bucket kernel, hidden 128 — must train with
    finite, DECREASING loss, with the tripwire confirming every phase
    finite."""
    sg = products_shape
    cfg = ModelConfig(
        layer_sizes=(sg.n_feat, 128, 128, sg.n_class),
        use_pp=True, norm="layer", dropout=0.3,
        train_size=sg.n_train_global, spmm_chunk=2_097_152,
        dtype="bfloat16", spmm_impl="bucket", rem_dtype="float8",
    )
    tcfg = TrainConfig(lr=0.003, n_epochs=8, enable_pipeline=True,
                       eval=False, fused_epochs=1)
    t = Trainer(sg, cfg, tcfg)
    losses = [t.train_epoch(e) for e in range(8)]
    assert np.isfinite(losses).all(), f"non-finite losses: {losses}"
    assert losses[-1] < losses[0]
    nm = {k: int(np.sum(np.asarray(v)))
          for k, v in t._last_metrics["numerics"].items()}
    assert first_nonfinite_phase(nm) is None, nm
