"""Online serving runtime (pipegcn_tpu/serve/, docs/SERVING.md).

These tests pin the round-10 serving contracts:
  - micro-batcher policy + power-of-two padding ladder (pure host unit
    tests on a fake clock);
  - compiled-once query engine: served logits match the single-device
    full-graph eval oracle, and steady-state traffic across every
    ladder bucket replays compiled code (trace-time compile counter —
    a jit cache hit never increments it);
  - incremental halo freshness: the dirty-row-only send-list replay is
    BIT-IDENTICAL to a full boundary re-exchange (graphsage AND the
    gcn pre-scaled send view);
  - layer-0 cache invalidation off the send-lists vs a brute-force
    slot enumeration;
  - the staleness ledger (age = update batches not yet in served
    logits) and the use_pp guard;
  - end-to-end: run_serving_loop emits schema-valid `serving` records
    and drains; the SIGTERM kill drill (marked slow, chaos lane) pins
    that a live `python -m pipegcn_tpu.cli.serve` drains and lands a
    hard-flushed final record before exiting 0.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from pipegcn_tpu.graph import synthetic_graph
from pipegcn_tpu.models import ModelConfig
from pipegcn_tpu.parallel import Trainer, TrainConfig
from pipegcn_tpu.partition import ShardedGraph, partition_graph
from pipegcn_tpu.serve import (
    Layer0Cache,
    MicroBatcher,
    OpenLoopGenerator,
    ServingEngine,
    ServingStats,
    bucket_for,
    bucket_ladder,
    run_serving_loop,
    trace_counts,
)

pytestmark = pytest.mark.serving


def _trainer(model="graphsage", use_pp=False, n_parts=4, seed=31,
             epochs=2):
    g = synthetic_graph(num_nodes=400, avg_degree=8, n_feat=12,
                        n_class=5, seed=seed)
    parts = partition_graph(g, n_parts, seed=0)
    sg = ShardedGraph.build(g, parts, n_parts=n_parts)
    cfg = ModelConfig(
        layer_sizes=(sg.n_feat, 16, 16, sg.n_class), model=model,
        norm="layer", dropout=0.0, train_size=sg.n_train_global,
        use_pp=use_pp,
    )
    t = Trainer(sg, cfg, TrainConfig(seed=3, enable_pipeline=True))
    for e in range(epochs):
        t.train_epoch(e)
    return t, g


@pytest.fixture(scope="module")
def served():
    """Read-only trainer+engine shared by the oracle/recompile tests.
    Tests that MUTATE features (apply_updates) must use `mutable`."""
    t, g = _trainer()
    eng = ServingEngine.for_trainer(t, max_batch=64, ladder_min=8)
    eng.warmup()
    return t, g, eng


@pytest.fixture(scope="module")
def mutable():
    """Engine the freshness/loop tests may patch features on (one
    trainer build amortized across them; the tests only rely on
    invariants — bit-identity, ledger deltas, finiteness — never on
    specific pre-update feature values)."""
    t, _ = _trainer(epochs=1)
    eng = ServingEngine.for_trainer(t)
    eng.warmup()
    return eng


# ---------------- padding ladder + micro-batcher (host-only) ----------


def test_bucket_ladder_semantics():
    assert bucket_ladder(8, 64) == [8, 16, 32, 64]
    assert bucket_ladder(8, 100) == [8, 16, 32, 64, 128]
    assert bucket_for(1, [8, 16]) == 8
    assert bucket_for(8, [8, 16]) == 8
    assert bucket_for(9, [8, 16]) == 16
    with pytest.raises(ValueError):
        bucket_for(17, [8, 16])


def test_microbatcher_policy_fake_clock():
    now = [0.0]
    batches = []

    def run(ids):
        batches.append(np.asarray(ids).copy())
        return np.stack([ids, ids * 2], axis=1).astype(np.float32)

    fills = []
    mb = MicroBatcher(run, max_batch=8, max_delay_ms=5.0, ladder_min=2,
                      clock=lambda: now[0],
                      observer=lambda b, n, lats: fills.append((b, n)))
    t1 = mb.submit(np.array([3, 4]))
    assert mb.queue_depth == 2
    # below max_batch and under the delay: not flushed yet
    assert mb.pump(now[0]) == 0
    assert not t1.done
    now[0] += 0.006  # past max_delay
    assert mb.pump(now[0]) == 1
    assert t1.done and mb.queue_depth == 0
    np.testing.assert_array_equal(t1.result[:, 0], [3, 4])
    assert t1.latency_s == pytest.approx(0.006)
    # a full batch flushes immediately, no waiting
    t2 = mb.submit(np.arange(8))
    assert mb.due(now[0])
    assert mb.pump(now[0]) == 1
    assert t2.done
    # two tickets coalesce into one run() call
    ta = mb.submit(np.array([1]))
    tb = mb.submit(np.array([2, 3]))
    now[0] += 0.010
    assert mb.pump(now[0]) == 1
    assert ta.done and tb.done
    np.testing.assert_array_equal(ta.result[:, 0], [1])
    np.testing.assert_array_equal(tb.result[:, 0], [2, 3])
    assert len(batches) == 3 and batches[-1].size == 3
    # drain flushes leftovers regardless of the clock
    tc = mb.submit(np.array([5]))
    mb.drain()
    assert tc.done and mb.queue_depth == 0
    # observer saw (bucket, valid-rows) per batch
    assert fills == [(2, 2), (8, 8), (4, 3), (2, 1)]
    # oversized submissions are rejected (callers chunk upstream)
    with pytest.raises(ValueError):
        mb.submit(np.arange(9))


def test_microbatcher_load_shedding_fake_clock():
    """Bounded-queue + deadline load shedding (docs/SERVING.md "Load
    shedding"): overload is answered 'no' immediately, expired tickets
    are shed at flush time, and the conservation invariant
    submitted == served + shed + queue_depth holds throughout."""
    now = [0.0]
    stats = ServingStats(clock=lambda: now[0])
    served = []

    def run(ids):
        served.append(np.asarray(ids).copy())
        return np.stack([ids, ids], axis=1).astype(np.float32)

    mb = MicroBatcher(run, max_batch=8, max_delay_ms=5.0, ladder_min=2,
                      clock=lambda: now[0], max_queue=4,
                      ticket_deadline_ms=20.0,
                      observer=stats.note_batch, on_shed=stats.note_shed)
    t1 = mb.submit(np.array([1, 2, 3]))
    assert not t1.shed
    # a submit that lands exactly AT the bound is accepted...
    t2 = mb.submit(np.array([4]))
    assert not t2.shed and mb.queue_depth == 4
    # ...one row past it is shed immediately with an explicit reason
    t3 = mb.submit(np.array([5]))
    assert t3.done and t3.shed and t3.shed_reason == "queue-full"
    assert t3.result is None and mb.queue_depth == 4
    # tickets that outwait the deadline are shed at flush, not served
    now[0] += 0.021
    assert mb.pump(now[0], force=True) == 0
    assert t1.shed and t1.shed_reason == "deadline"
    assert t2.shed and t2.shed_reason == "deadline"
    assert served == []  # nothing uselessly late ever ran
    # a fresh ticket inside the deadline still serves normally
    t4 = mb.submit(np.array([6, 7]))
    now[0] += 0.006
    assert mb.pump(now[0]) == 1
    assert t4.done and not t4.shed
    np.testing.assert_array_equal(t4.result[:, 0], [6, 7])
    # conservation: every submitted row is served, shed, or queued
    assert mb.n_submitted_rows == 7
    assert mb.n_served_rows == 2 and mb.n_shed_rows == 5
    assert mb.n_shed_tickets == 3
    assert mb.n_submitted_rows == (mb.n_served_rows + mb.n_shed_rows
                                   + mb.queue_depth)
    # the shed count lands in the serving record via on_shed
    assert stats.snapshot()["shed"] == 5


def test_serving_stats_snapshot():
    now = [100.0]
    st = ServingStats(clock=lambda: now[0])
    st.note_batch(8, 4, [0.001, 0.001, 0.002, 0.010])
    st.note_serve(4, hit=True, staleness_age=0)
    now[0] += 2.0
    rec = st.snapshot(queue_depth=3)
    assert rec["queries"] == 4
    assert rec["qps"] == pytest.approx(2.0)
    assert rec["batch_fill"] == pytest.approx(0.5)
    assert rec["queue_depth"] == 3
    assert rec["p50_ms"] == pytest.approx(1.5)
    assert rec["p99_ms"] <= 10.0 and rec["p99_ms"] > rec["p50_ms"]
    assert rec["cache_hit_rate"] == pytest.approx(1.0)
    assert rec["staleness_age"] == 0
    # snapshot(reset=True) starts a fresh window
    now[0] += 1.0
    empty = st.snapshot()
    assert empty["queries"] == 0 and empty["p50_ms"] is None
    assert empty["batch_fill"] is None and empty["cache_hit_rate"] is None


def test_open_loop_generator_deterministic():
    a = OpenLoopGenerator(100, qps=50, duration_s=2.0, seed=7)
    b = OpenLoopGenerator(100, qps=50, duration_s=2.0, seed=7)
    np.testing.assert_array_equal(a.arrivals, b.arrivals)
    np.testing.assert_array_equal(a.queries, b.queries)
    assert np.all(np.diff(a.arrivals) >= 0)  # open loop: fixed up front
    assert a.arrivals[-1] <= 2.0
    assert a.queries.min() >= 0 and a.queries.max() < 100


# ---------------- query engine ----------------------------------------


def test_query_matches_full_eval_oracle(served):
    t, g, eng = served
    handle = t.eval_dispatch(g, "val_mask")
    assert handle[0] == "full"
    full = np.asarray(handle[2])
    ids = np.arange(g.num_nodes, dtype=np.int64)
    out = eng.query(ids)
    assert out.shape == (g.num_nodes, eng.n_class)
    np.testing.assert_allclose(out, full[ids], atol=1e-5)


def test_zero_recompiles_after_warmup(served):
    t, _, eng = served
    # warmup ran in the fixture; the engine is cached per-trainer
    assert ServingEngine.for_trainer(t, max_batch=64, ladder_min=8) \
        is eng
    c0 = dict(trace_counts())
    rng = np.random.default_rng(0)
    for n in (1, 3, 8, 17, 33, 64, 200):  # 200 chunks over the top
        ids = rng.integers(0, eng.num_global_nodes, n).astype(np.int64)
        out = eng.query(ids)
        assert out.shape == (n, eng.n_class)
        assert np.isfinite(out).all()
    assert dict(trace_counts()) == c0, (
        "steady-state queries recompiled a serving program")


def test_tracing_adds_zero_recompiles_and_conserves_spans(
        served, tmp_path):
    """--trace-sample-rate 1.0 through the full serving loop: every
    query mints a trace id, spans land in the metrics stream with one
    terminal (dispatch|shed) each, and NO serving program retraces —
    the tracing is host-side clock arithmetic only. At rate 0 the
    sampler mints nothing."""
    from pipegcn_tpu.obs.metrics import MetricsLogger, read_metrics
    from pipegcn_tpu.obs.schema import validate_record
    from pipegcn_tpu.serve.tracing import TraceSampler

    _, _, eng = served
    eng.warmup()
    c0 = dict(trace_counts())
    mpath = tmp_path / "traced.jsonl"
    with MetricsLogger(mpath) as ml:
        ml.run_header(config={}, device={}, mesh={})
        summary = run_serving_loop(
            eng, duration_s=0.8, qps=60.0, max_delay_ms=2.0,
            report_every_s=0.4, refresh_every_s=0.0,
            update_every_s=0.0, seed=0, ml=ml,
            trace_sample_rate=1.0)
    assert dict(trace_counts()) == c0, (
        "tracing recompiled a serving program")
    assert summary["n_traced"] == summary["n_queries"] > 0
    assert summary["n_spans"] > 0
    spans = [r for r in read_metrics(mpath) if r.get("event") == "span"]
    assert len(spans) == summary["n_spans"]
    by_trace = {}
    for s in spans:
        validate_record(s)
        assert s["dur_ms"] >= 0 and s["t_start"] > 0
        by_trace.setdefault(s["trace_id"], []).append(s["op"])
    assert len(by_trace) == summary["n_traced"]
    for tid, ops in by_trace.items():
        term = [op for op in ops if op in ("dispatch", "shed")]
        assert len(term) == 1, (tid, ops)
    # rate 0 is the default and mints nothing
    assert TraceSampler(0.0).sample() is None


def test_query_rejects_out_of_range(served):
    _, _, eng = served
    with pytest.raises(ValueError, match="out of range"):
        eng.query(np.array([eng.num_global_nodes], dtype=np.int64))
    with pytest.raises(ValueError, match="out of range"):
        eng.query(np.array([-1], dtype=np.int64))


# ---------------- incremental freshness --------------------------------


def _assert_incremental_bit_identical(eng, model):
    rng = np.random.default_rng(1)
    before = None
    for round_i in range(3):  # repeated update/refresh cycles stay exact
        n = 10 + 5 * round_i
        ids = rng.integers(0, eng.num_global_nodes, n).astype(np.int64)
        vals = rng.normal(size=(n, eng.n_feat_raw)).astype(np.float32)
        if before is None:
            before = eng.query(ids[:4])
            probe = ids[:4]
        eng.apply_updates(ids, vals)
        assert eng.staleness_age >= 1 and not eng.fully_fresh
        eng.refresh_boundary()
        ref = np.asarray(eng.full_boundary_exchange())
        got = np.asarray(eng._halo0)
        assert got.dtype == ref.dtype and got.shape == ref.shape
        assert np.array_equal(ref, got), (
            f"{model}: incremental refresh != full re-exchange "
            f"(round {round_i})")
    # updates actually reach served logits after refresh()
    eng.refresh()
    assert eng.fully_fresh
    after = eng.query(probe)
    assert np.isfinite(after).all()
    assert not np.allclose(before, after)


def test_incremental_freshness_bit_identical(mutable):
    """The dirty-row send-list replay must land boundary slots
    BIT-IDENTICAL to rebuilding the whole halo from scratch."""
    _assert_incremental_bit_identical(mutable, "graphsage")


def test_incremental_freshness_bit_identical_gcn():
    """Same contract for gcn, whose send view pre-scales features by
    1/sqrt(deg) before shipping — the exchange input is NOT the raw
    feature row, so the patch/exchange op ordering must match the
    training forward exactly."""
    t, _ = _trainer(model="gcn", epochs=1)
    eng = ServingEngine.for_trainer(t)
    eng.warmup()
    _assert_incremental_bit_identical(eng, "gcn")


def test_refresh_boundary_noop_when_clean(served):
    _, _, eng = served  # never dirtied: no dispatch, returns 0
    assert eng.refresh_boundary() == 0


def test_staleness_ledger_and_use_pp_guard(mutable):
    eng = mutable
    # the bit-identity test (runs earlier in this file) leaves the
    # engine fully refreshed; independent of ordering, settle it first
    eng.refresh_boundary()
    eng.refresh()
    assert eng.staleness_age == 0 and eng.fully_fresh
    rng = np.random.default_rng(2)
    ids = rng.integers(0, eng.num_global_nodes, 8).astype(np.int64)
    vals = rng.normal(size=(8, eng.n_feat_raw)).astype(np.float32)
    eng.apply_updates(ids, vals)
    assert eng.staleness_age == 1
    eng.apply_updates(ids, vals)
    assert eng.staleness_age == 2
    eng.refresh_boundary()
    eng.refresh()
    assert eng.staleness_age == 0 and eng.fully_fresh
    # refresh() WITHOUT a boundary refresh leaves the halo lag visible
    eng.apply_updates(ids, vals)
    eng.refresh()
    assert eng.staleness_age == eng._halo_lag
    # shape/range validation
    with pytest.raises(ValueError, match="values must be"):
        eng.apply_updates(ids, vals[:, :2])
    with pytest.raises(ValueError, match="out of range"):
        eng.apply_updates(np.array([eng.num_global_nodes]), vals[:1])
    # use_pp folds raw features into the precompute: updates refused
    t_pp, _ = _trainer(use_pp=True, epochs=1, seed=37)
    eng_pp = ServingEngine.for_trainer(t_pp)
    eng_pp.warmup()
    assert np.isfinite(eng_pp.query(ids)).all()  # read path still fine
    with pytest.raises(ValueError, match="use_pp"):
        eng_pp.apply_updates(ids, vals)


# ---------------- layer-0 cache ---------------------------------------


def test_cache_invalidation_matches_brute_force():
    P, B = 4, 3
    rng = np.random.default_rng(0)
    send_idx = rng.integers(0, 50, (P, P - 1, B)).astype(np.int32)
    send_mask = rng.random((P, P - 1, B)) < 0.7
    cache = Layer0Cache(send_idx, send_mask)
    assert cache.n_stale == 0
    parts = np.array([0, 0, 2], dtype=np.int64)
    rows = np.array([send_idx[0, 0, 1], send_idx[0, 2, 0],
                     send_idx[2, 1, 2]], dtype=np.int64)
    touched = cache.invalidate_rows(parts, rows)
    # brute force: slot (d-1)*B+k on receiver q=(p+d)%P goes stale iff
    # partition p's send list at distance d ships a dirty row there
    expect = np.zeros((P, (P - 1) * B), bool)
    dirty = {(int(p), int(r)) for p, r in zip(parts, rows)}
    for p in range(P):
        for d in range(1, P):
            q = (p + d) % P
            for k in range(B):
                if send_mask[p, d - 1, k] and \
                        (p, int(send_idx[p, d - 1, k])) in dirty:
                    expect[q, (d - 1) * B + k] = True
    np.testing.assert_array_equal(cache.stale, expect)
    assert touched == int(expect.sum()) and cache.n_stale == touched
    for q in range(P):
        np.testing.assert_array_equal(cache.stale_slots(q),
                                      np.nonzero(expect[q])[0])
    cache.mark_fresh()
    assert cache.n_stale == 0
    # interior (never-sent) rows invalidate nothing
    interior = np.array([49], dtype=np.int64)
    masked = send_idx[3][send_mask[3]]
    if 49 not in masked:
        assert cache.invalidate_rows(np.array([3]), interior) == 0
    # hit accounting
    cache.record_queries(8, hit=True)
    cache.record_queries(2, hit=False)
    assert cache.hit_rate == pytest.approx(0.8)


# ---------------- end-to-end loop + records ----------------------------


def test_serving_loop_emits_valid_records(tmp_path, mutable):
    from pipegcn_tpu.obs.metrics import MetricsLogger, read_metrics
    from pipegcn_tpu.obs.schema import validate_record

    eng = mutable
    mpath = tmp_path / "serve.jsonl"
    with MetricsLogger(mpath) as ml:
        ml.run_header(config={}, device={}, mesh={})
        summary = run_serving_loop(
            eng, duration_s=1.2, qps=80.0, max_delay_ms=2.0,
            report_every_s=0.4, refresh_every_s=0.2,
            update_every_s=0.3, update_rows=8, seed=0, ml=ml)
    assert summary["n_queries"] > 0
    assert summary["qps"] > 0
    assert summary["p50_ms"] is not None and summary["p50_ms"] > 0
    assert summary["drained"] is True
    assert not summary["stopped_early"]
    recs = [r for r in read_metrics(mpath) if r.get("event") == "serving"]
    assert len(recs) == summary["n_records"] and recs
    for r in recs:
        validate_record(r)
        assert r["queries"] >= 0 and r["queue_depth"] >= 0
    assert recs[-1].get("final") is True
    total = sum(r["queries"] for r in recs)
    assert total == summary["n_queries"]


def test_serving_loop_stop_flag_drains(mutable):
    eng = mutable
    calls = [0]

    def stop():
        calls[0] += 1
        return calls[0] > 10  # stop almost immediately

    summary = run_serving_loop(eng, duration_s=30.0, qps=50.0,
                               report_every_s=1.0, seed=0, stop=stop)
    assert summary["stopped_early"] is True
    assert summary["drained"] is True
    assert summary["duration_s"] < 30.0


# ---------------- cli preflight + kill drill ---------------------------


def test_serve_cli_artifact_preflight_times_out(tmp_path):
    """Without --serve-build and without an artifact, cli.serve waits
    (bounded) for process 0's partition build instead of crashing with
    FileNotFoundError — and raises TimeoutError at the deadline."""
    from pipegcn_tpu.cli.serve import _load_partition, build_parser

    args = build_parser().parse_args([
        "--dataset", "synthetic:200:6:8:3", "--n-partitions", "4",
        "--partition-dir", str(tmp_path),
        "--serve-artifact-timeout", "0.3",
    ])
    t0 = time.monotonic()
    with pytest.raises(TimeoutError, match="partition artifact"):
        _load_partition(args)
    assert time.monotonic() - t0 < 30.0


@pytest.mark.slow
def test_serve_cli_kill_drill(tmp_path):
    """Chaos-lane drill: SIGTERM a live serve process mid-load; it must
    drain accepted queries and land a hard-flushed final `serving`
    record (final: true) before exiting 0."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    mpath = tmp_path / "metrics.jsonl"
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PYTHONPATH": repo,
        "PIPEGCN_PLATFORM": "cpu",
    }
    proc = subprocess.Popen(
        [sys.executable, "-m", "pipegcn_tpu.cli.serve",
         "--dataset", "synthetic:600:8:16:4", "--n-partitions", "4",
         "--n-hidden", "16", "--n-layers", "2", "--fix-seed",
         "--partition-dir", str(tmp_path / "parts"), "--serve-build",
         "--metrics-out", str(mpath),
         "--serve-duration", "300", "--serve-qps", "40",
         "--serve-report-every", "0.5", "--serve-refresh-every", "0.5",
         "--serve-update-every", "0.4"],
        env=env, cwd=repo, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)

    def n_serving_records():
        if not mpath.exists():
            return 0
        n = 0
        with open(mpath) as fh:
            for line in fh:
                try:
                    if json.loads(line).get("event") == "serving":
                        n += 1
                except json.JSONDecodeError:
                    pass  # mid-write line
        return n

    try:
        deadline = time.monotonic() + 240
        while n_serving_records() < 1:
            assert proc.poll() is None, (
                "serve exited before first record:\n"
                + proc.communicate()[0][-2000:])
            assert time.monotonic() < deadline, "no serving record"
            time.sleep(0.5)
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, out[-2000:]
    recs = []
    with open(mpath) as fh:
        for line in fh:
            r = json.loads(line)  # post-exit: every line complete
            if r.get("event") == "serving":
                recs.append(r)
    assert recs and recs[-1].get("final") is True
    # the stdout summary reports a clean drain
    tail = [ln for ln in out.splitlines() if '"serve": true' in ln]
    assert tail, out[-2000:]
    summ = json.loads(tail[-1])
    assert summ["drained"] is True and summ["stopped_early"] is True
    # no silently dropped tickets: every accepted row was served or
    # explicitly shed before the final record landed
    assert summ["conserved"] is True
    assert summ["n_submitted"] == summ["n_served"] + summ["n_shed"]
