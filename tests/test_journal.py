"""Crash-consistent streaming tests (docs/STREAMING.md "Durability &
replay"; docs/RESILIENCE.md journal row).

The load-bearing contracts:

  * WAL mechanics — CRC-guarded segment rotation, reopen rescan, torn
    tails tolerated (and HEALED) only at the newest segment's end,
    corruption anywhere else loud, ENOSPC degrade-not-lose pending
    queue with order-preserving drain, watermark rollback
    (``truncate_after``).
  * Resume semantics — ``replay_for_resume`` prefers the journal's
    copy, re-derives torn-away seqs from the plan, rolls back
    uncommitted entries; ``StreamPlan.skip_journaled`` retires exactly
    the replayed batches (never dropping pre-resume deltas on the
    floor like the legacy ``skip_before``).
  * The kill-mid-stream drill — a process killed between a delta apply
    and the next checkpoint resumes via journal replay to a trajectory
    BITWISE-identical (device tables, params, optimizer state, losses)
    to the uninterrupted run, on the xla and bucket SpMM paths; same
    for the ``journal-torn`` fault (newest segment truncated, lost
    suffix re-derived from the plan).
  * Fleet topology recovery — the router routes around a replica whose
    reported ``topo_generation`` trails the fleet (zero tickets lost),
    refuses the health-probe heal path while it is stale, and folds it
    back in on catch-up; a restarted ReplicaServer replays its journal
    BEFORE publishing readiness.
"""

import json
import os
import threading
import time

import jax
import numpy as np
import pytest

from pipegcn_tpu.graph.synthetic import (synthetic_delta_schedule,
                                         synthetic_graph)
from pipegcn_tpu.models import ModelConfig
from pipegcn_tpu.parallel import Trainer, TrainConfig
from pipegcn_tpu.partition.halo import ShardedGraph
from pipegcn_tpu.partition.partitioner import partition_graph
from pipegcn_tpu.resilience.storage import FaultyIO
from pipegcn_tpu.stream import (DeltaJournal, GraphPatcher, JournalCorrupt,
                                StreamPlan, replay_for_resume, save_deltas,
                                verify_against_rebuild)
from pipegcn_tpu.utils.checkpoint import (load_checkpoint, peek_watermark,
                                          save_checkpoint)

pytestmark = [pytest.mark.stream, pytest.mark.journal]

P = 4


def _batches(n=5, seed=2):
    g = synthetic_graph(num_nodes=80, avg_degree=4, n_feat=4, n_class=2,
                        seed=1)
    return synthetic_delta_schedule(g, n_batches=n, edges_per_batch=3,
                                    dels_per_batch=1, nodes_per_batch=1,
                                    seed=seed)


def _assert_batches_equal(a, b):
    assert a.seq == b.seq
    for f in ("add_edges", "del_edges", "node_feat", "node_label"):
        assert np.array_equal(getattr(a, f), getattr(b, f)), f
    assert len(a.node_nbrs) == len(b.node_nbrs)
    for x, y in zip(a.node_nbrs, b.node_nbrs):
        assert np.array_equal(x, y)


# ---------------- WAL mechanics --------------------------------------


def test_journal_roundtrip_rotation_and_reopen(tmp_path):
    """Appends rotate segments at segment_max_records; a reopen rescans
    to the same last_seq/last_generation and entries round-trip every
    batch bit-exactly."""
    bs = _batches(5)
    d = str(tmp_path / "j")
    j = DeltaJournal(d, segment_max_records=2)
    for i, b in enumerate(bs):
        assert j.append(b, i + 1) is True
    assert j.last_seq() == 4 and j.last_generation() == 5
    segs = sorted(n for n in os.listdir(d) if n.startswith("journal-"))
    assert segs == ["journal-00000000.jsonl", "journal-00000002.jsonl",
                    "journal-00000004.jsonl"]
    j2 = DeltaJournal(d, segment_max_records=2)
    assert j2.last_seq() == 4 and j2.last_generation() == 5
    ents = j2.entries()
    assert [g for g, _ in ents] == [1, 2, 3, 4, 5]
    for (_, got), want in zip(ents, bs):
        _assert_batches_equal(got, want)
    # replay() slices by seq
    assert [b.seq for _, b in j2.replay(2)] == [0, 1, 2]


def test_sealed_segment_corruption_is_loud(tmp_path):
    """A bad record in a SEALED position (not the newest segment's
    tail) is real corruption: the journal refuses to open rather than
    replaying through it."""
    bs = _batches(4)
    d = str(tmp_path / "j")
    j = DeltaJournal(d, segment_max_records=2)
    for i, b in enumerate(bs):
        j.append(b, i + 1)
    first = os.path.join(d, "journal-00000000.jsonl")
    with open(first) as f:
        lines = f.read().splitlines()
    rec = json.loads(lines[1])
    rec["add_edges"] = [[0, 1]]  # payload edit, stale crc
    lines[1] = json.dumps(rec, sort_keys=True)
    with open(first, "w") as f:
        f.write("\n".join(lines) + "\n")
    with pytest.raises(JournalCorrupt, match="sealed"):
        DeltaJournal(d, segment_max_records=2)


def test_torn_tail_tolerated_healed_and_appendable(tmp_path):
    """A half-written last line of the NEWEST segment (crash
    mid-append) is dropped at scan time, the file is healed back to its
    good prefix, and subsequent appends land cleanly after it — no
    record welding onto the torn garbage."""
    bs = _batches(4)
    d = str(tmp_path / "j")
    j = DeltaJournal(d)
    for i, b in enumerate(bs[:3]):
        j.append(b, i + 1)
    path = j._seg_path
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 40)  # cuts into the last record's line
    j2 = DeltaJournal(d)
    assert j2.last_seq() == 1  # seq 2 torn away
    # healed: the torn suffix is gone from disk
    with open(path, "rb") as f:
        assert f.read().endswith(b"\n")
    assert j2.append(bs[3], 9) is True
    assert [b.seq for _, b in j2.entries()] == [0, 1, 3]
    # ...and a reopen still parses every line
    assert DeltaJournal(d).last_seq() == 3


def test_enospc_pending_queue_preserves_order(tmp_path):
    """Degrade-not-lose: appends under an armed enospc seam queue in
    arrival order (nothing overtakes a queued batch, nothing is lost),
    and drain_pending makes them durable in order once the disk
    recovers."""
    bs = _batches(4)
    io = FaultyIO()
    j = DeltaJournal(str(tmp_path / "j"), io=io)
    assert j.append(bs[0], 1) is True
    io.arm("enospc")
    assert j.append(bs[1], 2) is False
    assert j.append(bs[2], 3) is False
    assert j.pending_count == 2
    assert j.last_seq() == 0  # nothing durable past seq 0
    assert j.drain_pending() == []  # still failing
    io.disarm("enospc")
    # order preserved even after recovery: a fresh append may not
    # overtake the queue
    assert j.append(bs[3], 4) is False
    assert j.pending_count == 3
    drained = j.drain_pending()
    assert [b.seq for b, _ in drained] == [1, 2, 3]
    assert [g for _, g in drained] == [2, 3, 4]
    assert j.pending_count == 0
    assert [b.seq for _, b in j.entries()] == [0, 1, 2, 3]


def test_truncate_after_rolls_back_across_segments(tmp_path):
    """WAL rollback drops every record past the watermark, rewriting
    segments atomically — including across a rotation boundary, and
    down to an empty journal that stays appendable."""
    bs = _batches(5)
    d = str(tmp_path / "j")
    j = DeltaJournal(d, segment_max_records=2)
    for i, b in enumerate(bs):
        j.append(b, i + 1)
    assert j.truncate_after(10) == 0  # nothing past the watermark
    assert j.truncate_after(2) == 2
    assert j.last_seq() == 2 and j.last_generation() == 3
    assert [b.seq for _, b in j.entries()] == [0, 1, 2]
    assert DeltaJournal(d, segment_max_records=2).last_seq() == 2
    # roll back everything: the journal empties but keeps working
    assert j.truncate_after(-1) == 3
    assert j.last_seq() == -1 and j.entries() == []
    assert j.append(bs[0], 1) is True
    assert j.last_seq() == 0


def test_tear_newest_segment_fault_hook(tmp_path):
    """The ``journal-torn@E`` drill hook: the newest segment loses its
    byte-level tail, the loss count is reported, and the journal
    remains scannable (recovery re-derives the lost seqs from the
    plan)."""
    bs = _batches(5)
    d = str(tmp_path / "j")
    j = DeltaJournal(d, segment_max_records=2)
    for i, b in enumerate(bs):
        j.append(b, i + 1)
    lost = j.tear_newest_segment()
    assert lost >= 1
    assert j.last_seq() < 4
    assert DeltaJournal(d, segment_max_records=2).last_seq() == j.last_seq()


# ---------------- plan resume semantics ------------------------------


def test_skip_journaled_retires_by_seq_not_epoch(tmp_path):
    """The PR-20 resume fix: ``skip_journaled`` retires exactly the
    batches WAL replay re-applied (seq <= watermark); a batch scheduled
    at a pre-resume epoch but past the watermark stays live and is
    re-delivered at the first boundary (the legacy ``skip_before``
    would have dropped it on the floor)."""
    b0, b1, b2 = _batches(3)
    plan = StreamPlan([(1, b0), (2, b1), (3, b2)])
    assert [b.seq for b in plan.batches_upto(1)] == [0, 1]
    assert plan.skip_journaled(0) == 1
    assert plan.remaining() == 2
    # resume at epoch 5: due() catches up the passed-epoch entries
    assert [b.seq for b in plan.due(5)] == [1, 2]
    assert plan.remaining() == 0
    # contrast: skip_before would have retired ALL of them silently
    plan2 = StreamPlan([(1, b0), (2, b1), (3, b2)])
    plan2.skip_before(5)
    assert plan2.remaining() == 0 and plan2.due(5) == []


def test_checkpoint_watermark_roundtrip(tmp_path):
    """Checkpoints stamp the journal watermark; ``peek_watermark``
    reads it without touching state arrays and defaults to the nominal
    graph (-1, 0)."""
    d = str(tmp_path / "ck")
    assert peek_watermark(d) == (-1, 0)
    state = {"x": np.arange(6, dtype=np.float32)}
    save_checkpoint(d, state, epoch=3,
                    extra={"__stream_seq__": 4, "__topo_generation__": 5})
    assert peek_watermark(d) == (4, 5)
    got, epoch, extras = load_checkpoint(
        d, {"x": np.zeros(6, np.float32)}, with_extras=True)
    assert epoch == 3
    assert int(extras["__stream_seq__"]) == 4
    assert int(extras["__topo_generation__"]) == 5
    assert np.array_equal(got["x"], state["x"])


def test_replay_for_resume_prefers_journal_rederives_truncates(tmp_path):
    """The resume helper applies every seq <= watermark in order —
    journal copy first, plan fallback for torn-away seqs — and rolls
    the journal back past the watermark."""
    bs = _batches(3)
    d = str(tmp_path / "j")
    j = DeltaJournal(d)
    j.append(bs[0], 1)
    j.append(bs[1], 2)  # seq 2 never made it to the journal (torn)
    plan = StreamPlan([(1, bs[0]), (2, bs[1]), (3, bs[2])])
    applied = []
    stats = replay_for_resume(j, 2, lambda b: applied.append(b.seq),
                              plan=plan)
    assert applied == [0, 1, 2]
    assert stats == {"replayed": 2, "rederived": 1, "truncated": 0,
                     "skipped": 0, "topo_generation": 3}
    # uncommitted entries past the watermark are rolled back
    j2 = DeltaJournal(str(tmp_path / "j2"))
    for i, b in enumerate(bs):
        j2.append(b, i + 1)
    applied2 = []
    stats2 = replay_for_resume(j2, 0, lambda b: applied2.append(b.seq))
    assert applied2 == [0]
    assert stats2["replayed"] == 1 and stats2["truncated"] == 2
    assert j2.last_seq() == 0


# ---------------- kill-mid-stream drill (bitwise) --------------------


def _stack(seed=6, n=240, slack=0.25, spmm="xla", n_epochs=6):
    g = synthetic_graph(num_nodes=n, avg_degree=6, n_feat=10, n_class=4,
                        seed=seed)
    parts = partition_graph(g, P)
    sg = ShardedGraph.build(g, parts, n_parts=P, slack=slack)
    cfg = ModelConfig(layer_sizes=(10, 12, 4), norm="layer",
                      dropout=0.0, model="graphsage",
                      train_size=sg.n_train_global, spmm_impl=spmm)
    tcfg = TrainConfig(seed=3, enable_pipeline=False, n_epochs=n_epochs,
                       log_every=10_000, fused_epochs=1)
    t = Trainer(sg, cfg, tcfg)
    patcher = GraphPatcher(g, sg, parts, slack=slack)
    t.enable_stream(patcher)
    return g, parts, sg, cfg, tcfg, t, patcher


def _assert_data_bit_identical(t, t2):
    d1 = jax.device_get(t.data)
    d2 = jax.device_get(t2.data)
    assert set(d1) == set(d2)
    for k in sorted(d1):
        a, b = np.asarray(d1[k]), np.asarray(d2[k])
        assert a.shape == b.shape, (k, a.shape, b.shape)
        assert a.dtype == b.dtype, (k, a.dtype, b.dtype)
        assert np.array_equal(a, b), (
            k, np.argwhere(a != b)[:5] if a.shape else (a, b))


def _assert_state_bit_identical(t, t2):
    s1 = jax.device_get(t.host_state())
    s2 = jax.device_get(t2.host_state())
    flat1 = jax.tree_util.tree_flatten_with_path(s1)[0]
    flat2 = dict(jax.tree_util.tree_flatten_with_path(s2)[0])
    assert len(flat1) == len(flat2)
    for path, v in flat1:
        a, b = np.asarray(v), np.asarray(flat2[path])
        assert a.shape == b.shape and a.dtype == b.dtype, path
        assert np.array_equal(a, b), path


@pytest.mark.faults
@pytest.mark.parametrize("spmm", ["xla", "bucket"])
def test_kill_mid_stream_resume_is_bitwise(tmp_path, spmm):
    """THE acceptance drill: a process SIGKILLed between delta applies
    and the next checkpoint resumes via journal replay + WAL rollback +
    plan re-delivery to a trajectory bitwise-identical to the
    uninterrupted run — device tables, params, optimizer moments, and
    losses — on both SpMM paths."""
    ck = str(tmp_path / "ck")
    jdir = str(tmp_path / "journal")

    # -- the doomed process: delta 0 applied + checkpointed; deltas 1,2
    # journaled + applied, then SIGKILL before any further checkpoint
    g, parts, sg, cfg, tcfg, t, patcher = _stack(spmm=spmm)
    batches = synthetic_delta_schedule(g, n_batches=3, edges_per_batch=4,
                                       dels_per_batch=2,
                                       nodes_per_batch=1, seed=21)
    dpath = str(tmp_path / "d.jsonl")
    save_deltas(dpath, batches)
    j = DeltaJournal(jdir)
    assert j.append(batches[0], 1) is True       # WAL-first
    assert not t.apply_graph_deltas(batches[0]).repadded
    assert np.isfinite(t.train_epoch(0))
    save_checkpoint(ck, t.host_state(), epoch=1,
                    extra={"__stream_seq__": 0, "__topo_generation__": 1})
    for gen, b in ((2, batches[1]), (3, batches[2])):
        assert j.append(b, gen) is True
        assert not t.apply_graph_deltas(b).repadded
    del t, j  # SIGKILL: no further checkpoint, no clean shutdown

    # -- the resumed process: NOMINAL rebuild, replay to the watermark,
    # roll back the uncommitted tail, restore state, live re-delivery
    g2, parts2, sg2, cfg2, tcfg2, t2, patcher2 = _stack(spmm=spmm)
    wm_seq, wm_gen = peek_watermark(ck)
    assert (wm_seq, wm_gen) == (0, 1)
    j2 = DeltaJournal(jdir)
    assert j2.last_seq() == 2  # the un-checkpointed applies survived
    plan = StreamPlan.parse(f"{dpath}@0")  # seqs 0,1,2 at epochs 0,1,2
    stats = replay_for_resume(j2, wm_seq, t2.apply_graph_deltas,
                              plan=plan)
    assert stats["replayed"] == 1 and stats["rederived"] == 0
    assert stats["truncated"] == 2  # past-watermark entries rolled back
    assert t2.topo_generation == wm_gen
    assert plan.skip_journaled(wm_seq) == 1
    host, start_epoch = load_checkpoint(ck, t2.host_state())
    t2.restore_state(host)
    assert start_epoch == 1
    resumed_losses = []
    for e in range(start_epoch, 3):
        for b in plan.due(e):  # rolled-back deltas re-deliver live
            assert j2.append(b, t2.topo_generation + 1) is True
            assert not t2.apply_graph_deltas(b).repadded
        resumed_losses.append(float(t2.train_epoch(e)))
    assert t2.topo_generation == 3 and j2.last_seq() == 2

    # -- the uninterrupted oracle: same schedule, never killed
    g3, parts3, sg3, cfg3, tcfg3, t3, patcher3 = _stack(spmm=spmm)
    oracle_losses = []
    for e in range(3):
        assert not t3.apply_graph_deltas(batches[e]).repadded
        oracle_losses.append(float(t3.train_epoch(e)))

    _assert_data_bit_identical(t2, t3)
    _assert_state_bit_identical(t2, t3)
    np.testing.assert_allclose(resumed_losses, oracle_losses[1:],
                               rtol=1e-6)
    # the packaged oracle agrees: replayed tables == from-scratch build
    audit = verify_against_rebuild(patcher2)
    assert audit["tables_match"], audit["mismatch"]


@pytest.mark.faults
def test_journal_torn_resume_rederives_from_plan_bitwise(tmp_path):
    """The ``journal-torn`` drill end-to-end: the newest segment is
    truncated after the checkpoint covered its records, so resume walks
    back to the surviving prefix and re-derives the torn-away seq from
    the plan — still bitwise-identical to the uninterrupted run."""
    ck = str(tmp_path / "ck")
    jdir = str(tmp_path / "journal")

    g, parts, sg, cfg, tcfg, t, patcher = _stack()
    batches = synthetic_delta_schedule(g, n_batches=3, edges_per_batch=4,
                                       dels_per_batch=2,
                                       nodes_per_batch=1, seed=21)
    dpath = str(tmp_path / "d.jsonl")
    save_deltas(dpath, batches)
    j = DeltaJournal(jdir, segment_max_records=2)
    for i, b in enumerate(batches):
        assert j.append(b, i + 1) is True
        assert not t.apply_graph_deltas(b).repadded
    assert np.isfinite(t.train_epoch(0))
    save_checkpoint(ck, t.host_state(), epoch=1,
                    extra={"__stream_seq__": 2, "__topo_generation__": 3})
    assert j.tear_newest_segment() == 1  # seq 2's record is gone
    del t, j

    g2, parts2, sg2, cfg2, tcfg2, t2, patcher2 = _stack()
    wm_seq, wm_gen = peek_watermark(ck)
    assert (wm_seq, wm_gen) == (2, 3)
    j2 = DeltaJournal(jdir, segment_max_records=2)
    assert j2.last_seq() == 1
    plan = StreamPlan.parse(f"{dpath}@0")
    stats = replay_for_resume(j2, wm_seq, t2.apply_graph_deltas,
                              plan=plan)
    assert stats["replayed"] == 2 and stats["rederived"] == 1
    assert stats["truncated"] == 0
    assert t2.topo_generation == 3 == wm_gen
    assert plan.skip_journaled(wm_seq) == 3
    host, start_epoch = load_checkpoint(ck, t2.host_state())
    t2.restore_state(host)
    resumed_losses = [float(t2.train_epoch(e))
                      for e in range(start_epoch, 3)]

    g3, parts3, sg3, cfg3, tcfg3, t3, patcher3 = _stack()
    for b in batches:
        assert not t3.apply_graph_deltas(b).repadded
    oracle_losses = [float(t3.train_epoch(e)) for e in range(3)]

    _assert_data_bit_identical(t2, t3)
    _assert_state_bit_identical(t2, t3)
    np.testing.assert_allclose(resumed_losses, oracle_losses[1:],
                               rtol=1e-6)
    assert verify_against_rebuild(patcher2)["tables_match"]


def test_fit_journals_deltas_and_torn_fault_stays_scannable(tmp_path):
    """fit() integration: every plan-delivered delta is journaled
    (op="append" records with the watermark lag), checkpoints stamp the
    watermark, and the ``journal-torn@E`` fault tears the newest
    segment loudly while leaving the journal scannable (healed tail)."""
    from pipegcn_tpu.obs.metrics import MetricsLogger, read_metrics
    from pipegcn_tpu.resilience.faults import FaultPlan

    ck = str(tmp_path / "ck")
    jdir = str(tmp_path / "journal")
    g, parts, sg, cfg, tcfg, t, patcher = _stack(n_epochs=6)
    batches = synthetic_delta_schedule(g, n_batches=2, edges_per_batch=4,
                                       dels_per_batch=2,
                                       nodes_per_batch=1, seed=9)
    dpath = str(tmp_path / "d.jsonl")
    save_deltas(dpath, batches)
    plan = StreamPlan.parse(f"{dpath}@2")  # epochs 2, 3
    j = DeltaJournal(jdir)
    mpath = str(tmp_path / "m.jsonl")
    with MetricsLogger(mpath) as m:
        t.fit(None, log_fn=lambda *_: None, metrics=m, stream_plan=plan,
              fault_plan=FaultPlan.parse("journal-torn@4"), journal=j,
              checkpoint_dir=ck, checkpoint_every=2)
    recs = read_metrics(mpath)
    appends = [r for r in recs if r["event"] == "journal"
               and r["op"] == "append"]
    assert [r["seq"] for r in appends] == [0, 1]
    assert [r["topo_generation"] for r in appends] == [1, 2]
    assert all(r["source"] == "trainer" for r in appends)
    faults = [r for r in recs if r["event"] == "fault"]
    assert any(r.get("reason") == "journal-torn" for r in faults)
    # the watermark made it into the final checkpoint
    wm_seq, wm_gen = peek_watermark(ck)
    assert wm_gen == 2 and wm_seq == 1
    # torn journal reopens cleanly (possibly with records lost — that
    # is what the plan re-derivation path is for)
    assert DeltaJournal(jdir).last_seq() <= 1


# ---------------- fleet topology recovery ----------------------------


class _FakeClient:
    def __init__(self):
        self.served = 0

    def query(self, ids):
        ids = np.asarray(ids)
        self.served += int(ids.size)
        return np.stack([ids, ids * 2], axis=1).astype(np.float32)


def test_router_topo_skew_routes_around_then_rejoins():
    """Satellite: a replica whose topo_generation trails the fleet max
    is routed around (one ``topo-skew:`` fault record edge), the
    health-probe mark_up heal path cannot route it back in, traffic
    lands on the caught-up survivor with zero tickets lost, and the
    replica rejoins on the catch-up edge after journal replay."""
    from pipegcn_tpu.serve.router import Router

    clients = {0: _FakeClient(), 1: _FakeClient()}
    faults = []
    r = Router(clients, policy="least-queue",
               on_fault=lambda rid, why: faults.append((rid, why)))
    assert r.note_topo_generation(0, 3) is None
    assert r.note_topo_generation(1, 3) is None
    assert r.note_topo_generation(0, 5) is None  # fleet advances
    # replica 1 reports again, still at 3: skew DOWN edge
    assert r.note_topo_generation(1, 3) is True
    assert not r.is_up(1)
    assert len(faults) == 1 and faults[0][0] == 1
    assert faults[0][1].startswith("topo-skew:")
    assert "generation 3" in faults[0][1] and "fleet at 5" in faults[0][1]
    # the manager's health-probe heal path must NOT route it back in
    assert r.mark_up(1) is False
    assert not r.is_up(1)
    # zero tickets lost: every batch lands on the fresh survivor
    ids = np.arange(8, dtype=np.int64)
    out, rid = r.dispatch(ids)
    assert rid == 0 and out.shape == (8, 2)
    assert r.n_failovers == 0 and clients[1].served == 0
    # duplicate stale report: no second edge
    assert r.note_topo_generation(1, 3) is None
    assert len(faults) == 1
    # journal replay caught the replica up: UP edge, back in rotation
    assert r.note_topo_generation(1, 5) is False
    assert r.is_up(1)
    assert r.topo_generations() == {0: 5, 1: 5}
    r.remove_replica(1)
    assert r.topo_generations() == {0: 5}


def test_fleet_manager_note_topo_emits_skew_records(tmp_path):
    """FleetManager.note_topo folds reported generations into the
    router and emits exactly one contracted ``fleet`` record per edge:
    ``topo-skew`` (with the fleet generation) and ``topo-caught-up``."""
    from pipegcn_tpu.obs.metrics import MetricsLogger, read_metrics
    from pipegcn_tpu.obs.schema import validate_record
    from pipegcn_tpu.serve.fleet import FleetManager
    from pipegcn_tpu.serve.router import Router

    router = Router({0: _FakeClient(), 1: _FakeClient()})
    mpath = str(tmp_path / "m.jsonl")
    with MetricsLogger(mpath) as ml:
        mgr = FleetManager(str(tmp_path / "fleet"), 2, [], ml=ml,
                           log=lambda m: None)
        assert mgr.note_topo(0, 2, router) is None
        assert mgr.note_topo(1, 2, router) is None
        assert mgr.note_topo(0, 4, router) is None
        assert mgr.note_topo(1, 2, router) is True   # skew edge
        assert mgr.note_topo(1, 2, router) is None   # no duplicate
        assert mgr.note_topo(1, 4, router) is False  # caught up
        assert mgr.note_topo(1, None, router) is None
    fleet = [r for r in read_metrics(mpath) if r.get("event") == "fleet"]
    assert [r["kind"] for r in fleet] == ["topo-skew", "topo-caught-up"]
    assert fleet[0]["replica"] == 1
    assert fleet[0]["topo_generation"] == 2
    assert fleet[0]["fleet_generation"] == 4
    assert fleet[1]["topo_generation"] == 4
    for r in fleet:
        validate_record(r)


def test_replica_server_replays_journal_before_readiness(tmp_path):
    """A restarted serving replica replays its journal BEFORE
    publishing readiness: the ready file carries the post-replay
    topo_generation, and the replay audit record is emitted."""
    from pipegcn_tpu.obs.metrics import MetricsLogger, read_metrics
    from pipegcn_tpu.serve.fleet import (ReplicaServer, TcpReplicaClient,
                                         _read_ready)

    class Eng:
        fully_fresh = True
        staleness_age = 0
        param_generation = 0
        param_staleness = 0
        topo_generation = 0

        def query(self, ids, stats=None):
            ids = np.asarray(ids)
            return np.stack([ids, ids * 2], axis=1).astype(np.float32)

    eng = Eng()
    order = []

    def replay():
        order.append("replay")
        eng.topo_generation = 7  # journal replay advanced the graph
        return 3

    mpath = str(tmp_path / "m.jsonl")
    ml = MetricsLogger(mpath)
    srv = ReplicaServer(eng, str(tmp_path), 0, incarnation=2, ml=ml,
                        replay=replay, heartbeat_interval_s=0.05,
                        swap_poll_s=30.0, report_every_s=30.0,
                        log=lambda m: None)
    th = threading.Thread(target=srv.serve_forever, daemon=True)
    th.start()
    info, deadline = None, time.monotonic() + 30
    while info is None and time.monotonic() < deadline:
        info = _read_ready(str(tmp_path), 0)
        time.sleep(0.01)
    try:
        assert info is not None, "replica never published readiness"
        # replay ran before the publish, and readiness reports the
        # POST-replay generation
        assert order == ["replay"]
        assert info["topo_generation"] == 7
        cl = TcpReplicaClient("127.0.0.1", info["port"], 0)
        try:
            _, meta = cl.query(np.array([1, 2]))
            assert meta["topo_generation"] == 7
            assert cl.health()["topo_generation"] == 7
            cl.stop()
            th.join(timeout=10)
            assert not th.is_alive()
        finally:
            cl.close()
    finally:
        srv.request_stop()
        ml.close()
    recs = read_metrics(mpath)
    rep = [r for r in recs if r.get("event") == "journal"
           and r.get("op") == "replay"]
    assert len(rep) == 1
    assert rep[0]["n_records"] == 3
    assert rep[0]["topo_generation"] == 7
    assert rep[0]["source"] == "replica-m0"


# ---------------- soak invariant #9 + postmortem verdict -------------


def test_soak_check_journal_invariant(tmp_path):
    """Invariant #9 passes only when the resume stream carries a
    journal op="verify" record with tables_match at the nominal
    topo_generation."""
    from pipegcn_tpu.resilience.soak import check_journal

    p = str(tmp_path / "resume.jsonl")

    def write(recs):
        with open(p, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")

    good = {"event": "journal", "op": "verify", "seq": 0,
            "topo_generation": 1, "n_records": 0, "source": "resume",
            "tables_match": True, "mismatch": []}
    write([{"event": "journal", "op": "replay", "seq": 0,
            "topo_generation": 1, "n_records": 1, "source": "resume"},
           good])
    assert check_journal(p, n_batches=1)["ok"]
    # no verify record: the journaled resume did not run
    write([])
    assert not check_journal(p, n_batches=1)["ok"]
    # tables diverge
    write([{**good, "tables_match": False, "mismatch": ["edge_src"]}])
    assert not check_journal(p, n_batches=1)["ok"]
    # wrong generation: a delta was lost or double-applied
    write([{**good, "topo_generation": 2}])
    assert not check_journal(p, n_batches=1)["ok"]
    assert check_journal(str(tmp_path / "missing.jsonl"),
                         n_batches=1)["ok"] is False


def test_postmortem_topo_rollback_verdict():
    """The explain CLI's ``topo-rollback`` rule fires on watermark
    rollback records, citing the gap, and stays quiet otherwise."""
    from pipegcn_tpu.obs.postmortem import _RULES, _rule_topo_rollback

    assert "topo-rollback" in [name for name, _ in _RULES]
    b = {"records": [
        {"event": "journal", "op": "truncate", "seq": 3,
         "topo_generation": 4, "n_records": 2, "source": "resume"},
        {"event": "journal", "op": "replay", "seq": 3,
         "topo_generation": 4, "n_records": 4, "rederived": 1,
         "source": "resume"},
    ]}
    v = _rule_topo_rollback(b)
    assert v is not None and v["confidence"] == pytest.approx(0.6)
    ev = " ".join(v["evidence"])
    assert "rolled back" in ev and "watermark seq 3" in ev
    assert "re-derived" in ev
    # no rollback, no verdict (a zero-drop truncate is bookkeeping)
    assert _rule_topo_rollback({"records": [
        {"event": "journal", "op": "truncate", "seq": 3,
         "topo_generation": 4, "n_records": 0, "source": "resume"},
    ]}) is None
    assert _rule_topo_rollback({"records": []}) is None


# ---------------- elastic inheritance drill (subprocess, slow) -------


@pytest.mark.slow
@pytest.mark.faults
def test_elastic_successor_inherits_journaled_deltas(tmp_path):
    """Two OS processes under the elastic supervisor: generation 0
    applies a scheduled delta live (journaled under the shared
    checkpoint dir) and is preempted; the generation-1 successor — a
    fresh process that NEVER applied that delta live — inherits the
    partitions, replays the journal to the crash checkpoint's
    watermark before training, and finishes with the post-run rebuild
    audit green. The supervisor's membership record carries the
    watermark the relaunched fleet replayed to."""
    import subprocess
    import sys

    from pipegcn_tpu.obs import read_metrics

    from pipegcn_tpu.graph import load_data

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # Same dataset string the child trains on (synthetic loads are
    # seed-stable), so the batch is valid against the child's graph.
    g = load_data("synthetic:240:6:10:4")
    batches = synthetic_delta_schedule(g, n_batches=1, edges_per_batch=4,
                                       dels_per_batch=2,
                                       nodes_per_batch=1, seed=21)
    dpath = str(tmp_path / "d.jsonl")
    save_deltas(dpath, batches)
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PYTHONPATH": repo,
        "PYTHONUNBUFFERED": "1",
    }
    ck = str(tmp_path / "ck")
    cmd = [
        sys.executable, "-m", "pipegcn_tpu.cli.elastic",
        "--max-restarts", "3", "--backoff-base", "0.1",
        "--metrics-out", str(tmp_path / "sup.jsonl"),
        "--",
        "--dataset", "synthetic:240:6:10:4",
        "--n-partitions", "2", "--parts-per-node", "2",
        "--n-epochs", "8", "--n-hidden", "12", "--dropout", "0.0",
        "--log-every", "1000", "--fix-seed", "--seed", "7", "--no-eval",
        "--partition-dir", str(tmp_path / "parts"),
        "--checkpoint-dir", ck, "--checkpoint-every", "2",
        "--stream-plan", f"{dpath}@3", "--local-reorder", "none",
        "--fault-plan", "sigterm@5",
        "--metrics-out", str(tmp_path / "m.jsonl"),
    ]
    proc = subprocess.run(cmd, env=env, cwd=repo, timeout=560,
                          capture_output=True, text=True)
    tail = (proc.stdout + proc.stderr)[-3000:]
    assert proc.returncode == 0, tail
    # the journal survived under the shared checkpoint dir at seq 0
    assert DeltaJournal(os.path.join(ck, "journal")).last_seq() == 0
    # the successor's metrics stream: replay audit + rebuild verify
    resume = read_metrics(tmp_path / "m.g1.m0.jsonl")
    journal = [r for r in resume if r.get("event") == "journal"]
    replays = [r for r in journal if r["op"] == "replay"]
    assert replays and replays[0]["n_records"] == 1, tail
    assert replays[0]["source"] == "resume"
    verify = [r for r in journal if r["op"] == "verify"]
    assert verify, tail
    assert verify[-1]["tables_match"] is True
    assert verify[-1]["topo_generation"] == 1
    # the replan membership record surfaces the inherited watermark
    membership = [r for r in read_metrics(tmp_path / "sup.jsonl")
                  if r.get("event") == "membership"]
    resumed = [r for r in membership
               if r.get("trigger") == "preempt-resume"]
    assert resumed, tail
    assert resumed[0].get("stream_seq") == 0
    assert resumed[0].get("topo_generation") == 1
