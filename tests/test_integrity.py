"""Silent-data-corruption defense (resilience/integrity.py,
docs/RESILIENCE.md "Silent data corruption").

Pins the round-18 contracts:
  - fletcher digests: host numpy and the jitted device program agree
    bit-exactly on every dtype width, any single flipped bit changes
    the digest with certainty, and the construction is order
    independent (so XLA's reduction order never matters);
  - the ``bitflip@E[:rN]:<class>`` fault grammar: required target
    class, one-shot consumption, rank gating, loud rejection of
    malformed entries;
  - quarantine request markers: durable round-trip, fail-closed on an
    unreadable marker, operator clear;
  - the v13 ``integrity`` record kind validates against the schema;
  - the IntegrityPlane in isolation: static-table scrub attributes the
    dirty shard and the dirty-shard rebuild clears it; the dynamic
    params digest catches a boundary flip; Freivalds passes clean on
    both SpMM families;
  - the seeded bitflip-detection matrix THROUGH fit(): every target
    class x kernel family is injected, detected within the cadence,
    attributed to the right class in a contracted record, and the run
    still completes (recovery worked);
  - the serving wire guard: with --integrity-check-every armed the
    dirty-row exchange stays bit-identical to a full re-exchange and
    never recompiles (the checksum lane is a trace-time choice);
  - ``pipegcn-debug scrub``: exit 0 on a clean run dir, exit 2 when a
    checkpoint or ledger generation is tampered;
  - the elastic supervisor honors quarantine markers (member excluded
    at the next replan) and the explicit-rejoin release valve (marker
    cleared, member folded back in);
  - the slow two-member drill: recurring SDC on rank 1 writes the
    marker, the supervisor relaunches without it, and training
    completes on the survivor.
"""

import io
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from pipegcn_tpu.graph import synthetic_graph
from pipegcn_tpu.models import ModelConfig
from pipegcn_tpu.obs import (
    SCHEMA_VERSION,
    MetricsLogger,
    read_metrics,
    validate_record,
)
from pipegcn_tpu.parallel import Trainer, TrainConfig
from pipegcn_tpu.partition import ShardedGraph, partition_graph
from pipegcn_tpu.resilience import (
    EXIT_PREEMPTED,
    ElasticConfig,
    ElasticSupervisor,
    FaultPlan,
    MembershipLedger,
)
from pipegcn_tpu.resilience.integrity import (
    QUARANTINE_STRIKES,
    SDC_CODES,
    TARGETS,
    IntegrityPlane,
    clear_quarantine,
    digest_tree,
    flip_bit,
    host_digest,
    quarantine_marker_path,
    read_quarantines,
    request_quarantine,
    shard_digests,
)

pytestmark = pytest.mark.integrity

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def sharded():
    g = synthetic_graph(num_nodes=300, avg_degree=6, n_feat=8, n_class=3,
                        seed=1)
    parts = partition_graph(g, 2, seed=0)
    return ShardedGraph.build(g, parts, n_parts=2)


def _trainer(sg, impl="xla", **tkw):
    cfg = ModelConfig(layer_sizes=(sg.n_feat, 16, sg.n_class),
                      dropout=0.0, train_size=sg.n_train_global,
                      spmm_impl=impl)
    tkw.setdefault("n_epochs", 8)
    tkw.setdefault("log_every", 50)
    return Trainer(sg, cfg, TrainConfig(**tkw))


# ---------------- fletcher digests ------------------------------------


@pytest.mark.parametrize("arr", [
    np.linspace(-3, 3, 97).astype(np.float32),
    np.arange(-40, 40, dtype=np.int32).reshape(8, 10),
    np.arange(256, dtype=np.uint8),
    (np.arange(30) % 2 == 0),
    np.linspace(0, 1, 64).astype(np.float16),
], ids=["f32", "i32", "u8", "bool", "f16"])
def test_digest_host_device_bit_parity(arr):
    """The host numpy digest and the jitted device digest must agree
    bit-exactly for every dtype width — that equality is what lets the
    scrubber compare device state against host-built references."""
    import jax.numpy as jnp

    from pipegcn_tpu.resilience.integrity import device_digest

    h = host_digest(arr)
    d = np.asarray(device_digest(jnp.asarray(arr)))
    assert h.dtype == np.uint32 and h.shape == (2,)
    assert np.array_equal(h, d), (h, d)
    # 8-byte dtypes never exist on the CPU mesh (jax x64 is off), so
    # the parity contract stops at 4 bytes; the host digest still
    # folds them (checkpoint-side references)
    h64 = host_digest(np.linspace(-1, 1, 33))
    assert h64.shape == (2,) and not np.array_equal(
        h64, host_digest(flip_bit(np.linspace(-1, 1, 33), bit=9)))


def test_digest_single_flip_sensitivity_and_involution():
    rng = np.random.default_rng(0)
    a = rng.normal(size=(50, 4)).astype(np.float32)
    ref = host_digest(a)
    for bit, index in [(0, 0), (11, 37), (31, 199), (23, 73)]:
        b = flip_bit(a, bit=bit, index=index)
        assert not np.array_equal(host_digest(b), ref), (bit, index)
        # flipping the same bit twice is the identity
        c = flip_bit(b, bit=bit, index=index)
        assert np.array_equal(c, a)
    # the chaos lane's params flip (bit 11, mid-mantissa) stays finite:
    # wrong-but-finite is the SDC model, not a NaN the tripwire catches
    assert np.isfinite(flip_bit(a, bit=11, index=5)).all()


def test_wire_sum_order_independent_and_flip_sensitive():
    import jax.numpy as jnp

    from pipegcn_tpu.parallel.halo import wire_sum

    rng = np.random.default_rng(1)
    a = rng.normal(size=257).astype(np.float32)
    s = np.asarray(wire_sum(jnp.asarray(a)))
    # integer wraparound addition commutes: any permutation agrees
    p = rng.permutation(a)
    assert np.array_equal(np.asarray(wire_sum(jnp.asarray(p))), s)
    bad = flip_bit(a, bit=7, index=100)
    assert not np.array_equal(np.asarray(wire_sum(jnp.asarray(bad))), s)
    # digest matches the integrity plane's plain sum (shared construction)
    assert int(s) == int(host_digest(a)[0])


def test_shard_digests_attribute_the_dirty_shard():
    import jax.numpy as jnp

    a = np.arange(3 * 20, dtype=np.float32).reshape(3, 20)
    ref = shard_digests(jnp.asarray(a))
    assert ref.shape == (3, 2)
    b = flip_bit(a, bit=3, index=25)  # flat 25 -> shard 1
    cur = shard_digests(jnp.asarray(b))
    changed = np.nonzero(np.any(cur != ref, axis=-1))[0]
    assert changed.tolist() == [1]


def test_digest_tree_names_leaves():
    import jax.numpy as jnp

    tree = {"w": jnp.ones((4, 4)), "b": {"inner": jnp.zeros(3)}}
    d = digest_tree(tree)
    assert len(d) == 2
    assert all(v.shape == (2,) and v.dtype == np.uint32
               for v in d.values())
    assert any("w" in k for k in d) and any("inner" in k for k in d)


# ---------------- fault grammar ---------------------------------------


def test_bitflip_grammar_one_shot_and_rank_gating():
    p = FaultPlan.parse("bitflip@3:params,bitflip@5:r1:tables")
    assert p.due_str_arg("bitflip", 3) == "params"
    assert p.due_str_arg("bitflip", 3) is None  # consumed
    # the r1 entry never fires on rank 0
    assert p.due_str_arg("bitflip", 5) is None
    q = FaultPlan.parse("bitflip@5:r1:tables", rank=1)
    assert q.due_str_arg("bitflip", 5) == "tables"
    # the class argument is REQUIRED and must be a legal class
    with pytest.raises(ValueError, match="target class"):
        FaultPlan.parse("bitflip@3")
    with pytest.raises(ValueError, match="target class"):
        FaultPlan.parse("bitflip@3:meteor")
    # word arguments are bitflip-only
    with pytest.raises(ValueError, match="word argument"):
        FaultPlan.parse("sigterm@3:params")


def test_sdc_codes_cover_targets():
    assert set(SDC_CODES) == set(TARGETS)
    assert sorted(SDC_CODES.values()) == [1, 2, 3, 4]  # 0 = none


# ---------------- quarantine markers ----------------------------------


def test_quarantine_marker_roundtrip(tmp_path):
    d = str(tmp_path)
    path = request_quarantine(d, 3, reason="recurring SDC", strikes=2,
                              targets=["params", "params"])
    assert path == quarantine_marker_path(d, 3)
    q = read_quarantines(d)
    assert set(q) == {3}
    assert q[3]["reason"] == "recurring SDC"
    assert q[3]["strikes"] == 2 and q[3]["targets"] == ["params"]
    # an unreadable marker still quarantines (fail-closed)
    with open(quarantine_marker_path(d, 7), "w") as f:
        f.write("{torn")
    q = read_quarantines(d)
    assert set(q) == {3, 7}
    assert "unreadable" in q[7]["reason"]
    assert clear_quarantine(d, 3) and not clear_quarantine(d, 3)
    assert set(read_quarantines(d)) == {7}


# ---------------- schema contract -------------------------------------


def test_integrity_record_validates_and_schema_pin():
    assert SCHEMA_VERSION == 15
    buf = io.StringIO()
    ml = MetricsLogger(buf)
    ml.run_header(config={}, device={}, mesh={})
    ml.integrity(epoch=4, check="scrub", outcome="mismatch",
                 target="tables", cadence=2, overhead_s=0.001,
                 detail="digest mismatch in spmm_rows",
                 dirty_shards=[1])
    ml.close()
    recs = [json.loads(line) for line in buf.getvalue().splitlines()]
    rec = [r for r in recs if r["event"] == "integrity"][0]
    validate_record(rec)
    assert rec["target"] == "tables" and rec["outcome"] == "mismatch"
    assert rec["cadence"] == 2 and rec["dirty_shards"] == [1]


# ---------------- the plane, in isolation -----------------------------


def test_plane_scrub_detects_table_flip_and_rebuild_clears(sharded):
    t = _trainer(sharded, n_epochs=2)
    integ = IntegrityPlane(1, log=lambda s: None)
    integ.baseline(t)
    assert integ.scrub_static(t).outcome == "ok"
    assert t._inject_bitflip("tables", 0, lambda s: None)
    res = integ.scrub_static(t)
    assert res.outcome == "mismatch" and res.target == "tables"
    assert res.dirty_shards  # attribution names the rotten shard(s)
    assert "digest mismatch" in res.detail
    # recovery: rebuild the dirty shard's tables from the host artifact
    t._rebuild_static_data(res.dirty_shards)
    assert integ.scrub_static(t).outcome == "ok"


def test_plane_dynamic_digest_catches_params_flip(sharded):
    t = _trainer(sharded, n_epochs=2)
    integ = IntegrityPlane(1, log=lambda s: None)
    integ.note_dynamic(t)
    assert all(r.outcome == "ok" for r in integ.verify_dynamic(t))
    assert t._inject_bitflip("params", 0, lambda s: None)
    results = integ.verify_dynamic(t)
    bad = [r for r in results if r.outcome == "mismatch"]
    assert [r.target for r in bad] == ["params"]
    assert "digest mismatch" in bad[0].detail
    # rollback/restore legitimately replaces state: drop the baselines
    integ.drop_dynamic()
    assert integ.verify_dynamic(t) == []


@pytest.mark.parametrize("impl", ["xla", "bucket"])
def test_freivalds_passes_clean(sharded, impl):
    t = _trainer(sharded, impl=impl, n_epochs=2)
    t.train_epoch(0)
    integ = IntegrityPlane(1, log=lambda s: None)
    res = integ.freivalds(t, 1)
    assert res is not None
    assert res.check == "freivalds" and res.outcome == "ok"


# ---------------- detection matrix through fit() ----------------------


def _assert_detected(sg, impl, targets):
    """One trainer per kernel family (compiles once), one fit per
    target class: the flip at epoch 3 must be injected, detected no
    later than epoch 3 + cadence with the right attribution, and the
    run must still reach n_epochs (recovery worked)."""
    cadence = 2
    t = _trainer(sg, impl=impl, enable_pipeline=True,
                 integrity_check_every=cadence, n_epochs=8)
    for target in targets:
        buf = io.StringIO()
        res = t.fit(eval_graphs=None, log_fn=lambda s: None,
                    metrics=MetricsLogger(buf),
                    fault_plan=FaultPlan.parse(f"bitflip@3:{target}"))
        recs = [json.loads(line)
                for line in buf.getvalue().splitlines()]
        injected = [r for r in recs if r["event"] == "fault"
                    and r.get("kind") == "injected"
                    and r.get("reason") == f"bitflip:{target}"]
        assert injected and injected[0]["epoch"] == 3, (impl, target)
        hits = [r for r in recs if r["event"] == "integrity"
                and r["outcome"] == "mismatch"
                and r.get("target") == target]
        assert hits, (impl, target,
                      [r for r in recs if r["event"] == "integrity"])
        assert all(3 <= r["epoch"] <= 3 + cadence for r in hits)
        for r in hits:
            validate_record(r)
            assert r["cadence"] == cadence
        # recovery let the run finish with finite numbers
        assert t.last_epoch == t.tcfg.n_epochs, (impl, target)
        if res["history"]:
            assert np.isfinite(res["history"][-1][1])


def test_bitflip_detection_matrix_xla(sharded):
    _assert_detected(sharded, "xla", TARGETS)


def test_bitflip_detection_matrix_bucket(sharded):
    # the full four-class sweep rides the xla family; bucket pins the
    # table-heavy classes its gather plans add (plus params for the
    # consensus-rollback path under a different kernel)
    _assert_detected(sharded, "bucket", ("tables", "params"))


# ---------------- serving wire guard ----------------------------------


def test_serving_wire_guard_bit_identical_and_no_recompile(sharded):
    """With --integrity-check-every armed the serving engine's dirty
    row exchange carries the checksum lane: results stay bit-identical
    to a full re-exchange, no mismatches fire on a clean wire, and the
    guarded program still traces exactly once (trace-time choice)."""
    from pipegcn_tpu.serve import ServingEngine, trace_counts

    t = _trainer(sharded, enable_pipeline=True, integrity_check_every=1,
                 n_epochs=2)
    t.train_epoch(0)
    eng = ServingEngine.for_trainer(t)
    assert eng._wire_guard
    eng.warmup()
    rng = np.random.default_rng(3)
    c0 = None
    for round_i in range(3):
        ids = rng.integers(0, eng.num_global_nodes, 12).astype(np.int64)
        vals = rng.normal(size=(12, eng.n_feat_raw)).astype(np.float32)
        eng.apply_updates(ids, vals)
        eng.refresh_boundary()
        ref = np.asarray(eng.full_boundary_exchange())
        got = np.asarray(eng._halo0)
        assert np.array_equal(ref, got), round_i
        if c0 is None:
            c0 = dict(trace_counts())  # steady state after round 0
    assert dict(trace_counts()) == c0, (
        "wire guard recompiled a serving program")
    assert eng.wire_bad_total == 0


# ---------------- debug scrub CLI -------------------------------------


def test_debug_scrub_clean_then_tampered(tmp_path):
    from pipegcn_tpu.cli.debug import EXIT_CORRUPT, main
    from pipegcn_tpu.resilience import plan_assignment
    from pipegcn_tpu.utils.checkpoint import save_checkpoint

    run = tmp_path / "run"
    ck = run / "ck"
    state = {"params": {"w": np.arange(8, dtype=np.float32)}}
    save_checkpoint(str(ck), state, 4)
    led = MembershipLedger(str(run / "coord-elastic"))
    led.append(generation=0, members=[0, 1],
               assignment=plan_assignment(2, [0, 1]), trigger="start")
    assert main(["scrub", str(run)]) == 0
    assert main(["scrub", str(run), "--json"]) == 0
    # tamper a checkpoint byte: scrub must exit 2, not crash
    npz = sorted(ck.glob("state-*.npz"))[0]
    blob = bytearray(npz.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    npz.write_bytes(bytes(blob))
    assert main(["scrub", str(run)]) == EXIT_CORRUPT
    # heal the checkpoint, rot the ledger payload instead
    save_checkpoint(str(ck), state, 6)
    npz.unlink()
    assert main(["scrub", str(run)]) == 0
    path = led.path_for(0)
    rec = json.load(open(path))
    rec["payload"]["trigger"] = "tampered"
    json.dump(rec, open(path, "w"))
    assert main(["scrub", str(run)]) == EXIT_CORRUPT


# ---------------- supervisor: quarantine + release valve ---------------


class _FakeHandle:
    def __init__(self, rc):
        self.returncode = None
        self._rc = rc

    def poll(self):
        self.returncode = self._rc
        return self._rc

    def send_signal(self, sig):
        pass


class _FakeFleet:
    def __init__(self, rcs):
        self.rcs = list(rcs)
        self.launches = []

    def popen(self, cmd, env, log_path):
        self.launches.append(
            {"cmd": list(cmd), "env": dict(env), "log": log_path})
        return _FakeHandle(self.rcs.pop(0))


def _train_argv(tmp_path, n_parts=4, ppn=2):
    return [
        "--dataset", "synthetic:300:6:8:3",
        "--n-partitions", str(n_parts),
        "--parts-per-node", str(ppn),
        "--n-epochs", "6", "--n-hidden", "8", "--dropout", "0.0",
        "--no-eval", "--fix-seed", "--seed", "7",
        "--partition-dir", str(tmp_path / "parts"),
        "--checkpoint-dir", str(tmp_path / "ck"),
        "--metrics-out", str(tmp_path / "metrics.jsonl"),
    ]


def _fast_cfg(**kw):
    kw.setdefault("backoff_base_s", 0.0)
    kw.setdefault("backoff_max_s", 0.0)
    kw.setdefault("poll_s", 0.01)
    kw.setdefault("storm_threshold", 1000)
    return ElasticConfig(**kw)


def test_supervisor_excludes_quarantined_then_rejoin_releases(tmp_path):
    """A pre-existing quarantine marker keeps member 1 out of gen 0
    (trigger 'quarantine', sole survivor owns everything); the pending
    explicit rejoin request is the operator release valve — at the
    next membership event it clears the marker and folds 1 back in."""
    coord = str(tmp_path / "parts" / "coord-elastic")
    request_quarantine(coord, 1, reason="recurring silent data "
                       "corruption", strikes=QUARANTINE_STRIKES,
                       targets=["params"])
    MembershipLedger(coord).request_rejoin(1)
    # gen 0: member 0 alone -> 75 (resumable); gen 1: members 0+1 -> 0
    fleet = _FakeFleet([EXIT_PREEMPTED, 0, 0])
    logs = []
    sup = ElasticSupervisor(_train_argv(tmp_path), _fast_cfg(),
                            popen=fleet.popen, log=logs.append)
    assert sup.run() == 0
    assert len(fleet.launches) == 3
    led = MembershipLedger(coord)
    assert led.generations() == [0, 1]
    g0, g1 = led.read(0), led.read(1)
    assert g0["trigger"] == "quarantine" and g0["members"] == [0]
    assert g0["assignment"]["parts"] == {"0": [0, 1, 2, 3]}
    assert g1["trigger"] == "rejoin" and g1["members"] == [0, 1]
    # the release valve consumed both the marker and the request
    assert not os.path.exists(quarantine_marker_path(coord, 1))
    assert led.pending_rejoins() == []
    assert any("quarantine" in line for line in logs)
    assert any("released from quarantine" in line for line in logs)


def test_supervisor_never_quarantines_everyone(tmp_path):
    """Quarantining EVERY member keeps the full set (training on
    nothing helps nobody) with a loud log."""
    coord = str(tmp_path / "parts" / "coord-elastic")
    for m in (0, 1):
        request_quarantine(coord, m, reason="sdc", strikes=2,
                           targets=["tables"])
    fleet = _FakeFleet([0, 0])
    logs = []
    sup = ElasticSupervisor(_train_argv(tmp_path), _fast_cfg(),
                            popen=fleet.popen, log=logs.append)
    assert sup.run() == 0
    led = MembershipLedger(coord)
    assert led.read(0)["trigger"] == "start"
    assert led.read(0)["members"] == [0, 1]
    assert any("every member" in line for line in logs)
    # markers survive: an operator must clear them explicitly
    assert set(read_quarantines(coord)) == {0, 1}


# ---------------- the two-member quarantine drill (slow) ---------------


@pytest.mark.slow
@pytest.mark.faults
def test_recurring_sdc_quarantines_rank_and_fleet_recovers(tmp_path):
    """Acceptance: rank 1 suffers two scheduled bit flips (cadence 1,
    so each is detected immediately -> QUARANTINE_STRIKES reached), it
    writes the quarantine marker and exits resumable; the supervisor
    replans WITHOUT it and the survivor finishes all 10 epochs owning
    both partitions."""
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PYTHONPATH": REPO,
        "PYTHONUNBUFFERED": "1",
    }
    ck = str(tmp_path / "ck")
    cmd = [
        sys.executable, "-m", "pipegcn_tpu.cli.elastic",
        "--max-restarts", "3", "--backoff-base", "0.1",
        "--metrics-out", str(tmp_path / "sup.jsonl"),
        "--",
        "--dataset", "synthetic:300:6:8:3",
        "--n-partitions", "2", "--parts-per-node", "1",
        "--n-epochs", "10", "--n-hidden", "8", "--dropout", "0.0",
        "--log-every", "1000", "--fix-seed", "--seed", "7", "--no-eval",
        "--partition-dir", str(tmp_path / "parts"),
        "--checkpoint-dir", ck, "--checkpoint-every", "2",
        "--integrity-check-every", "1",
        "--fault-plan", "bitflip@3:r1:params,bitflip@5:r1:params",
        "--metrics-out", str(tmp_path / "metrics.jsonl"),
    ]
    proc = subprocess.run(cmd, env=env, cwd=REPO, timeout=560,
                          capture_output=True, text=True)
    tail = (proc.stdout + proc.stderr)[-4000:]
    assert proc.returncode == 0, tail
    coord = str(tmp_path / "parts" / "coord-elastic")
    # the marker is durable evidence — it outlives the run
    q = read_quarantines(coord)
    assert 1 in q, (q, tail)
    assert q[1]["strikes"] >= QUARANTINE_STRIKES
    led = MembershipLedger(coord)
    gens = led.generations()
    assert len(gens) >= 2, tail
    quarantined = [led.read(g) for g in gens
                   if led.read(g)["trigger"] == "quarantine"]
    assert quarantined and quarantined[0]["members"] == [0], tail
    # the survivor really trained: detection records from rank 1's
    # generation-0 stream name the params class
    mfiles = [os.path.join(tmp_path, f) for f in os.listdir(tmp_path)
              if f.startswith("metrics.")]
    hits = []
    for mf in mfiles:
        hits += [r for r in read_metrics(mf)
                 if r.get("event") == "integrity"
                 and r.get("outcome") == "mismatch"
                 and r.get("target") == "params"]
    assert hits, tail
