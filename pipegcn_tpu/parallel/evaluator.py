"""Sharded full-graph evaluation over the training mesh.

The reference evaluates the FULL graph single-process on rank 0's host
CPU (train.py:20-61, README requires >=120 GB host RAM for papers100M);
the round-1 port evaluated on one accelerator's HBM — neither scales.
This evaluator runs the eval forward through the same shard_map layout
as training: each device computes logits for its own partition (with a
synchronous halo exchange per layer — exact, no staleness), then the
accuracy statistic is reduced with psum. No device (or host) ever holds
the full graph, so eval scales with the mesh exactly like training.

Metric reduction (train/metrics.py semantics, reference train.py:11-17):
  single-label: counts = [correct, total, 0]        -> correct/total
  multi-label:  counts = [tp, fp, fn] (pred=logits>0) -> 2tp/(2tp+fp+fn)

The counts come back as ONE tiny replicated device array, so `counts()`
is non-blocking — fit() dispatches evaluation and harvests the scalar a
log-period later, keeping eval off the critical path (the TPU analogue
of the reference's background-thread eval, train.py:327-328, 377-389).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from ..graph.csr import Graph
from ..models.sage import forward
from ..obs.trace import named_phase
from .halo import halo_exchange
from .mesh import PARTS_AXIS


# bumped at TRACE time inside eval_fn: a delta of zero across repeated
# evaluator constructions proves the cached program was reused instead
# of recompiled (tests/test_eval.py pins this)
EVAL_TRACE_COUNT = 0


def _program_key(sg, dev_data, use_tables: bool, multilabel: bool):
    """Cache key for the compiled sharded-eval program: everything the
    traced computation depends on besides the trainer-fixed cfg/mesh —
    graph shapes, the data pytree signature (keys + shapes + dtypes,
    which also encodes the kernel impl via its table arrays), and the
    metric flavor."""
    return (
        sg.n_max, sg.halo_size, bool(use_tables), bool(multilabel),
        tuple(sorted((k, tuple(v.shape), str(v.dtype))
                     for k, v in dev_data.items())),
    )


def _covers_exactly(sg, g: Graph) -> bool:
    """True iff the training partitions were built from exactly graph
    `g` (the transductive case: the trainer's sharded data IS the eval
    graph, so its arrays can be reused without a rebuild). Node-ID cover
    alone is not sufficient — an eval graph can share the node set with
    different edges — so the source edge checksum must match too (old
    artifacts without one conservatively rebuild)."""
    nid = sg.global_nid[sg.global_nid >= 0]
    if nid.size != g.num_nodes:
        return False
    if not np.array_equal(np.sort(nid), np.arange(g.num_nodes)):
        return False
    if getattr(sg, "source_edge_checksum", -1) == -1:
        return False
    if int(sg.edge_count.sum()) != g.num_edges:
        return False
    from ..partition.halo import ShardedGraph

    return sg.source_edge_checksum == ShardedGraph.edge_checksum(g)


class ShardedEvaluator:
    """Evaluates one graph through a Trainer's mesh.

    Use `ShardedEvaluator.for_graph(trainer, g)`: reuses the trainer's
    device-resident arrays when `g` is the training graph (transductive),
    else partitions `g` across the same devices and uploads its shards
    (inductive val/test graphs, or any external graph).
    """

    def __init__(self, trainer, sg, data: Dict[str, jax.Array],
                 use_tables: bool = False):
        self.trainer = trainer
        self.sg = sg
        # the fixed traced input of _run (pytree structure must not
        # change between calls); lazily-added masks live in self.data
        self._dev_data = dict(data)
        self.data = dict(data)
        self._cfg = trainer.cfg  # already has sorted_edges=True
        P = trainer.P
        n_max = sg.n_max
        multilabel = sg.multilabel
        self.multilabel = multilabel

        # the compiled program is shared across evaluator instances
        # through the trainer: repeated eval of same-signature graphs
        # (convergence-study legs, serving warmup, foreign val/test
        # graphs of one shape) pays compile once, not per construction
        prog_key = _program_key(sg, self._dev_data, use_tables,
                                multilabel)
        cached = getattr(trainer, "_eval_program_cache", None)
        if cached is not None and prog_key in cached:
            self._run = cached[prog_key]
            return

        def eval_fn(params, norm, data_in, mask):
            global EVAL_TRACE_COUNT
            EVAL_TRACE_COUNT += 1
            d = {k: v[0] for k, v in data_in.items()}
            label, mask = d["label"], mask[0]

            def comm_update(i, h):
                return halo_exchange(h, d["send_idx"], d["send_mask"],
                                     PARTS_AXIS, P)

            # aggregate through kernel tables when the data carries them
            # (use_tables): the trainer's own tables for the
            # transductive covers-exactly case, or bucket tables built
            # for a foreign (inductive) eval graph — both beat the
            # raw-edge gather path. Shapes come from THIS sg, which may
            # be sharded differently from the training graph.
            # transport=False: evaluation is one-shot and metric-
            # bearing — it must not inherit the narrowed per-epoch
            # gather transport (rem_dtype), and with use_pp=False its
            # first layer aggregates RAW features
            spmm = trainer.make_device_spmm_closure(
                d, n_max=n_max, n_src_rows=n_max + sg.halo_size,
                transport=False,
            ) if use_tables else None
            # GAT aggregates through the attention-bucket closure (its
            # tables ride in the data exactly like the mean kernels')
            gat = trainer.make_device_gat_closure(
                d, n_max=n_max, n_src_rows=n_max + sg.halo_size,
                transport=False,
            ) if use_tables else None
            with named_phase("eval"):
                logits, _ = forward(
                    params, self._cfg, d["feat"], d["edge_src"],
                    d["edge_dst"], d["in_deg"], n_max,
                    training=False, halo_eval=True,
                    comm_update=comm_update,
                    norm_state=norm, spmm_fn=spmm, gat_fn=gat,
                )
            if multilabel:
                pred = logits > 0
                lab = label > 0.5
                m = mask[:, None]
                # int32 counts: exact up to 2.1e9 elements (f32 would
                # round above 2^24, well within papers100M's range)
                tp = jnp.sum(pred & lab & m, dtype=jnp.int32)
                fp = jnp.sum(pred & ~lab & m, dtype=jnp.int32)
                fn = jnp.sum(~pred & lab & m, dtype=jnp.int32)
                counts = jnp.stack([tp, fp, fn])
            else:
                correct = jnp.sum((jnp.argmax(logits, -1) == label) & mask,
                                  dtype=jnp.int32)
                total = jnp.sum(mask, dtype=jnp.int32)
                counts = jnp.stack([correct, total,
                                    jnp.zeros((), jnp.int32)])
            with named_phase("eval_metric_reduce"):
                return jax.lax.psum(counts, PARTS_AXIS)

        spec = PartitionSpec(PARTS_AXIS)
        repl = PartitionSpec()
        params_spec = jax.tree_util.tree_map(
            lambda _: repl, trainer.state["params"])
        norm_spec = jax.tree_util.tree_map(
            lambda _: repl, trainer.state["norm"])
        data_spec = jax.tree_util.tree_map(lambda _: spec, self._dev_data)
        self._run = jax.jit(jax.shard_map(
            eval_fn,
            mesh=trainer.mesh,
            in_specs=(params_spec, norm_spec, data_spec, spec),
            out_specs=repl,
        ))
        if cached is not None:
            cached[prog_key] = self._run

    # ------------------------------------------------------------------
    @staticmethod
    def for_graph(trainer, g: Graph,
                  parts: Optional[np.ndarray] = None) -> "ShardedEvaluator":
        if _covers_exactly(trainer.sg, g):
            # transductive: reuse the trainer's device arrays, kernel
            # tables included — no re-upload even when the trainer
            # trimmed the raw edge list from HBM
            return ShardedEvaluator(trainer, trainer.sg, trainer.data,
                                    use_tables=trainer._edges_trimmed)

        from ..partition.halo import ShardedGraph
        from ..partition.partitioner import partition_graph

        if parts is None:
            parts = partition_graph(g, trainer.P, method="metis",
                                    obj="vol", seed=0)
        from .trainer import _pad_cols

        sg = ShardedGraph.build(g, parts, n_parts=trainer.P)
        arrs = {
            # lane_pad trainers rewrote layer_sizes[0]; this foreign
            # graph's features must be padded to the same width
            "feat": _pad_cols(sg.feat, getattr(trainer, "_feat_pad", 0)),
            "label": sg.label,
            "in_deg": sg.in_deg,
            "edge_src": sg.edge_src.astype(np.int32),
            "edge_dst": sg.edge_dst.astype(np.int32),
            "send_idx": sg.send_idx.astype(np.int32),
            "send_mask": sg.send_mask,
            "val_mask": sg.val_mask,
            "test_mask": sg.test_mask,
            "train_mask": sg.train_mask,
        }
        use_tables = False
        if trainer._edges_trimmed:
            # the training step aggregates through kernel tables, so
            # repeated evals of this foreign graph deserve the same:
            # build tables for ITS shards — the attention-bucket tables
            # for GAT (forward() ignores spmm_fn there), else the
            # general-purpose mean bucket tables
            if trainer.cfg.model == "gat":
                from ..ops.gat_bucket import build_sharded_gat_tables

                arrs.update(build_sharded_gat_tables(sg))
            else:
                from ..ops.bucket_spmm import build_sharded_bucket_tables

                arrs.update(build_sharded_bucket_tables(sg))
            use_tables = True
            # the pp precompute also aggregates through the tables, so
            # the raw edge arrays never need to reach the device
            # (mirrors Trainer._put_data skip_edges)
            dummy = np.zeros((trainer.P, 8), np.int32)
            arrs["edge_src"] = dummy
            arrs["edge_dst"] = dummy
        data = {
            k: jax.device_put(jnp.asarray(v), trainer._shard)
            for k, v in arrs.items()
        }
        if trainer.cfg.use_pp:
            # layer 0 consumes the precomputed [feat, mean_neigh] concat;
            # rebuild it for this graph's own edges/degrees (through the
            # kernel tables when present)
            data["feat"] = trainer._precompute_pp(sg, data)
        return ShardedEvaluator(trainer, sg, data, use_tables=use_tables)

    # ------------------------------------------------------------------
    def _mask(self, mask_key: str) -> jax.Array:
        m = self.data.get(mask_key)
        if m is None:  # trainer data carries masks under sg arrays
            m = jax.device_put(
                jnp.asarray(getattr(self.sg, mask_key)),
                self.trainer._shard)
            self.data[mask_key] = m
        return m

    def counts(self, mask_key: str, params=None, norm=None) -> jax.Array:
        """Dispatch the sharded eval; returns the [3] reduced counts as a
        device array WITHOUT blocking (jax async dispatch)."""
        t = self.trainer
        return self._run(
            params if params is not None else t.state["params"],
            norm if norm is not None else t.state["norm"],
            self._dev_data,
            self._mask(mask_key),
        )

    def finish(self, counts) -> float:
        """Turn dispatched counts into the scalar metric (blocks only if
        the computation hasn't completed yet)."""
        c = np.asarray(counts)
        if self.multilabel:
            tp, fp, fn = float(c[0]), float(c[1]), float(c[2])
            denom = 2 * tp + fp + fn
            return 2 * tp / denom if denom else 0.0
        return float(c[0]) / float(c[1]) if c[1] else 0.0

    def accuracy(self, mask_key: str, params=None, norm=None) -> float:
        return self.finish(self.counts(mask_key, params, norm))
