"""Device-side halo (boundary node) exchange.

The TPU-native replacement for the reference's entire comm stack —
ring-staggered gloo isend/irecv with pinned CPU staging, CUDA streams,
events and message tags (helper/feature_buffer.py:165-206) — expressed as
gather -> `lax.ppermute` -> concat inside `shard_map`. XLA differentiates
it (gather transposes to scatter-add, ppermute to the reverse ring), so
the vanilla path needs no hand-written backward; race-freedom is by
construction, and the event/stream/tag apparatus disappears.

Functions here run *inside* shard_map: array args are per-device blocks.

Ring layout (see partition.halo.ShardedGraph): at distance d, device r
sends `h[send_idx[d-1]]` to (r+d) mod P and receives the block whose rows
belong to owner (r-d) mod P; received blocks concatenate behind the inner
rows in distance order, matching the precomputed halo slot numbering.

`make_stale_concat` is the pipelined (staleness-1) variant: consuming
last epoch's halo features, injecting last epoch's boundary gradients
into this epoch's backward (reference feature_buffer.py:153-163,228-236),
and exposing this epoch's halo cotangent through a probe input so the
train step can ship it to owners for the next epoch.

Compressed transport (`--halo-dtype`): the ppermute payloads may travel
in a narrower dtype than the compute dtype — the same bf16/fp8
machinery the SpMM gather transport uses (ops/bucket_spmm.py
transport_dtypes/transport_cast) applied to the ICI wire itself. Each
distance block is cast on the sender, permuted narrow, and decoded
back to the compute dtype on the receiver; fp8 payloads ship a
per-block power-of-two inverse scale alongside (amax_transport_cast,
the PR 5 range guard), so large activations are never statically
saturated nor small cotangents flushed. Pipelined-mode only: the
exchange there sits behind stop_gradient / an explicit cotangent ship,
so the cast never lands inside a differentiated path.
"""

from __future__ import annotations

import contextlib
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.bucket_spmm import amax_transport_cast, transport_dtypes


def halo_transport_dtypes(halo_dtype: Optional[str]) -> Tuple:
    """(feature, bgrad) wire dtypes for a --halo-dtype spec, following
    the SpMM transport convention: activations e4m3, cotangents e5m2,
    bf16 for both, None = uncompressed (the compute dtype)."""
    if halo_dtype in (None, "", "none"):
        return None, None
    # reuse the rem-transport mapping ('bfloat16' | 'float8')
    return transport_dtypes(halo_dtype)


def _ensure_varying(x: jax.Array, axis_name: str) -> jax.Array:
    """Mark x device-varying over axis_name unless it already is (pcast
    rejects varying->varying)."""
    try:
        if axis_name in jax.typeof(x).vma:
            return x
    except (AttributeError, TypeError):
        pass
    return jax.lax.pcast(x, axis_name, to="varying")


def _fwd_perm(num_parts: int, d: int):
    return [(r, (r + d) % num_parts) for r in range(num_parts)]


def _bwd_perm(num_parts: int, d: int):
    return [(r, (r - d) % num_parts) for r in range(num_parts)]


# Trace-time switch for the exposed-wait measurement ONLY
# (scripts/overlap_study.py): with identity_collectives() active, the
# ring ppermutes become identity — same shapes/dtypes/gather/concat
# structure, zero inter-device traffic. The reference's Comm(s) metric
# is the per-epoch wait its hooks EXPOSE (helper/timer/comm_timer.py,
# train.py:366-371); timing a step traced with vs without the permutes
# yields that exposed cost directly (total - hidden), which HLO def-use
# structure alone cannot. Data semantics are wrong (each device keeps
# its own boundary rows) — never use while training for real.
_IDENTITY_COLLECTIVES = False


@contextlib.contextmanager
def identity_collectives():
    global _IDENTITY_COLLECTIVES
    prev = _IDENTITY_COLLECTIVES
    _IDENTITY_COLLECTIVES = True
    try:
        yield
    finally:
        _IDENTITY_COLLECTIVES = prev


def _ring_permute(blk: jax.Array, axis_name: str, perm) -> jax.Array:
    if _IDENTITY_COLLECTIVES:
        return _ensure_varying(blk, axis_name)
    return jax.lax.ppermute(blk, axis_name, perm)


def wire_sum(x: jax.Array) -> jax.Array:
    """uint32 wraparound sum of an array's raw bits — the wire-
    integrity checksum (resilience/integrity.py uses the same
    order-independent construction for its at-rest digests; a single
    flipped bit shifts the sum by +-2^k != 0 mod 2^32, so one-flip
    detection is certain). Jittable; any dtype."""
    x = x.reshape(-1)
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.uint8)
    size = jnp.dtype(x.dtype).itemsize
    if size == 1:
        u = jax.lax.bitcast_convert_type(x, jnp.uint8).astype(jnp.uint32)
    elif size == 2:
        u = jax.lax.bitcast_convert_type(x, jnp.uint16).astype(jnp.uint32)
    else:
        u = jax.lax.bitcast_convert_type(x, jnp.uint32).reshape(-1)
    if u.shape[0] == 0:
        return jnp.zeros((), jnp.uint32)
    return jnp.sum(u, dtype=jnp.uint32)


def _permute_compressed(blk: jax.Array, axis_name: str, perm,
                        transport_dt, guard: bool = False):
    """Ring-permute one distance block, optionally in a narrow wire
    dtype. fp8 payloads use the amax-clamped cast and ship the sender's
    power-of-two inverse scale through the SAME permutation, so the
    receiver decodes with its peer's scale — never its own. The result
    is always back in blk's original dtype.

    guard=True adds the wire-integrity checksum lane: the sender's
    :func:`wire_sum` of the exact permuted payload rides the SAME
    permutation (like the fp8 inverse scale), the receiver recomputes
    it on what arrived, and the return becomes ``(blk, bad)`` with
    ``bad`` an int32 0/1 mismatch flag. The lane is a trace-time
    choice: guard=False compiles the byte-identical program this
    module always built."""

    def _guarded(payload):
        s = _ring_permute(wire_sum(payload), axis_name, perm)
        rx = _ring_permute(payload, axis_name, perm)
        return rx, (wire_sum(rx) != s).astype(jnp.int32)

    if transport_dt is None:
        if not guard:
            return _ring_permute(blk, axis_name, perm)
        return _guarded(blk)
    out_dt = blk.dtype
    y, inv = amax_transport_cast(blk, transport_dt)
    bad = None
    if guard:
        y, bad = _guarded(y)
    else:
        y = _ring_permute(y, axis_name, perm)
    if inv is None:
        # bf16 wire: a straight cast round-trips through the permute
        out = y.astype(out_dt)
        return (out, bad) if guard else out
    if guard:
        inv, bad_inv = _guarded(jnp.asarray(inv, jnp.float32))
        bad = jnp.maximum(bad, bad_inv)
    else:
        inv = _ring_permute(jnp.asarray(inv, jnp.float32), axis_name,
                            perm)
    out = (y.astype(jnp.float32) * inv).astype(out_dt)
    return (out, bad) if guard else out


def exchange_blocks(
    h: jax.Array,
    send_idx: jax.Array,
    send_mask: jax.Array,
    axis_name: str,
    num_parts: int,
    transport_dt=None,
    guard: bool = False,
):
    """Gather boundary rows and ring-exchange them.

    h: [N, F] inner rows; send_idx/mask: [P-1, B]. Returns the halo block
    [(P-1)*B, F]: distance-d rows hold features owned by (r-d) mod P.
    `transport_dt` (optional) narrows the ppermute payload to that wire
    dtype (decoded back to h.dtype on arrival) — pipelined-mode halo
    compression; leave None on differentiated paths.

    guard=True (trace-time) threads the wire-integrity checksum lane
    through every distance block and returns ``(halo, bad)`` — ``bad``
    an int32 count of distance blocks whose received payload failed
    the sender's checksum (0 on a healthy wire).

    The whole gather->permute->concat runs under the "halo_exchange"
    named scope so --profile-dir traces attribute the ring collectives
    (and their backward scatters) to the phase, not anonymous fusions.
    """
    with jax.named_scope("halo_exchange"):
        blocks = []
        bad = jnp.zeros((), jnp.int32)
        for d in range(1, num_parts):
            blk = jnp.take(h, send_idx[d - 1], axis=0, mode="clip")
            blk = jnp.where(send_mask[d - 1][:, None], blk, 0.0)
            out = _permute_compressed(blk, axis_name,
                                      _fwd_perm(num_parts, d),
                                      transport_dt, guard=guard)
            if guard:
                out, b = out
                bad = bad + b
            blocks.append(out)
        if not blocks:
            # P=1: no halo, but the empty result must still be marked
            # device-varying so it types consistently as carry state
            # (e.g. in the fused-epoch scan)
            empty = _ensure_varying(
                jnp.zeros((0, h.shape[-1]), h.dtype), axis_name
            )
            if guard:
                return empty, _ensure_varying(bad, axis_name)
            return empty
        halo = jnp.concatenate(blocks, axis=0)
        return (halo, bad) if guard else halo


def halo_exchange(
    h: jax.Array,
    send_idx: jax.Array,
    send_mask: jax.Array,
    axis_name: str,
    num_parts: int,
) -> jax.Array:
    """[N, F] -> [N + (P-1)*B, F]: inner rows followed by halo rows.
    Fully differentiable (synchronous/vanilla mode,
    reference feature_buffer.py:145-152)."""
    if num_parts == 1:
        return h
    return jnp.concatenate(
        [h, exchange_blocks(h, send_idx, send_mask, axis_name, num_parts)],
        axis=0,
    )


def return_blocks(
    halo_grad: jax.Array,
    axis_name: str,
    num_parts: int,
    b_max: int,
    transport_dt=None,
    guard: bool = False,
):
    """Route halo cotangents back to their owners.

    halo_grad: [(P-1)*B, F] in distance order. The distance-d block came
    from owner (r-d); after the reverse permute, the device holds — in the
    same [(P-1)*B, F] layout — the gradients its peers computed for the
    rows listed in its own send_idx (block d-1 <- peer (r+d)).
    `transport_dt` narrows the wire payload like exchange_blocks — use
    the cotangent dtype (e5m2 under float8) for gradient range.
    guard=True returns ``(blocks, bad)`` like exchange_blocks."""
    with jax.named_scope("bgrad_return"):
        outs = []
        bad = jnp.zeros((), jnp.int32)
        for d in range(1, num_parts):
            blk = jax.lax.dynamic_slice_in_dim(
                halo_grad, (d - 1) * b_max, b_max, axis=0
            )
            out = _permute_compressed(blk, axis_name,
                                      _bwd_perm(num_parts, d),
                                      transport_dt, guard=guard)
            if guard:
                out, b = out
                bad = bad + b
            outs.append(out)
        if not outs:
            # P=1 empty case: keep the varying type (see exchange_blocks)
            empty = _ensure_varying(jnp.zeros_like(halo_grad), axis_name)
            if guard:
                return empty, _ensure_varying(bad, axis_name)
            return empty
        ret = jnp.concatenate(outs, axis=0)
        return (ret, bad) if guard else ret


def make_stale_concat(send_idx: jax.Array, send_mask: jax.Array, n_dst: int):
    """Build the staleness-1 concat op for one graph layer.

    f(h, stale_halo, stale_bgrad, probe) -> [N + H, F] buffer equal to
    concat(h, stale_halo + probe), with a custom VJP:

      d_h     = g[:N] + scatter_add(send positions, stale_bgrad)
                  (inject *last* epoch's boundary grads — reference
                   feature_buffer.py:228-236 / __update_grad :208-217)
      d_probe = g[N:]  (this epoch's halo cotangent, for the caller to
                   ship to owners; probe itself is zeros)
      d_stale_halo = d_stale_bgrad = 0  (stale values are carry state,
                   not differentiation targets)

    send_idx/mask: [P-1, B] for this device; their flattened order matches
    the [(P-1)*B] halo/bgrad row order.
    """
    flat_idx = send_idx.reshape(-1)
    flat_mask = send_mask.reshape(-1)

    @jax.custom_vjp
    def stale_concat(h, stale_halo, stale_bgrad, probe):
        return jnp.concatenate([h, stale_halo + probe], axis=0)

    def fwd(h, stale_halo, stale_bgrad, probe):
        return stale_concat(h, stale_halo, stale_bgrad, probe), (stale_bgrad,)

    def bwd(res, g):
        (stale_bgrad,) = res
        inj = jnp.where(flat_mask[:, None], stale_bgrad, 0.0)
        d_h = g[:n_dst].at[flat_idx].add(inj)
        d_probe = g[n_dst:]
        return (
            d_h,
            jnp.zeros_like(d_probe),
            jnp.zeros_like(stale_bgrad),
            d_probe,
        )

    stale_concat.defvjp(fwd, bwd)
    return stale_concat
