"""RAM-bounded sequential execution of the pipelined SPMD step.

Runs the SAME staleness-1 training step as the shard_map Trainer
(trainer.py:671-792) one rank at a time on a single device, with the
collectives replaced by host-side routing. This is exact, not an
approximation, because PipeGCN-style pipelining (reference
feature_buffer.py:153-163, 219-236) makes every cross-rank input to
epoch e an output of epoch e-1:

  - layer halo features consumed at epoch e were exchanged at e-1
    (the staleness-1 carry), so rank r's epoch-e compute never needs a
    peer's epoch-e activations;
  - the boundary gradients injected at e are the probe cotangents the
    peers computed at e-1;
  - the only intra-epoch collective is psum(grads) — an associative
    reduction the host performs after the per-rank backward passes.

Peak memory is therefore ONE rank's tables + activations regardless of
P, which makes papers100M-class 64-part configs (reference
helper/utils.py:17-30; BASELINE.json multi-host grid) trainable on a
single host for validation — the role dgl's per-part files + a >=120 GB
host play for the reference (README.md:29-30).

Routing mirrors parallel/halo.py exactly:
  exchange_blocks: receiver r's distance-d halo block is owner
    (r-d) mod P's send block for distance d (_fwd_perm);
  return_blocks: owner o's distance-d bgrad block is the probe
    cotangent computed by peer (o+d) mod P at its distance-d slot
    (_bwd_perm).
tests/test_sequential.py pins loss-trajectory equality against the
shard_map Trainer on a multi-device CPU mesh.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.sage import ModelConfig, forward, init_norm_state, init_params
from ..obs.metrics import memory_snapshot
from ..train.losses import bce_logits_sum, cross_entropy_sum
from ..train.optim import adam_init, adam_update
from .halo import make_stale_concat
from .trainer import TrainConfig


def _ladder_caps(edge_src_by_rank, edge_dst_by_rank, P, n_max,
                 n_src_rows):
    """Shared bucket ladders + per-bucket row caps WITHOUT building any
    tables: one cheap degree-histogram pass per rank (the streamed
    analogue of build_sharded_bucket_tables's cap scan)."""
    from ..ops.bucket_spmm import _bucket_widths

    max_in = max_out = 1
    hists = []
    for r in range(P):
        src = np.asarray(edge_src_by_rank(r))
        dst = np.asarray(edge_dst_by_rank(r))
        real = dst < n_max
        di = np.bincount(dst[real], minlength=n_max)
        do = np.bincount(src[real], minlength=n_src_rows)
        max_in = max(max_in, int(di.max(initial=1)))
        max_out = max(max_out, int(do.max(initial=1)))
        hists.append((di, do))
    fw = _bucket_widths(max_in)
    bw = _bucket_widths(max_out)

    def counts(deg, widths):
        w = np.asarray(widths, np.int64)
        bid = np.minimum(np.searchsorted(w, np.maximum(deg, 1)),
                         len(widths) - 1)
        real = deg > 0
        return np.bincount(bid[real], minlength=len(widths))

    fwd_caps = np.zeros(len(fw), np.int64)
    bwd_caps = np.zeros(len(bw), np.int64)
    for di, do in hists:
        fwd_caps = np.maximum(fwd_caps, counts(di, fw))
        bwd_caps = np.maximum(bwd_caps, counts(do, bw))
    return fw, bw, fwd_caps.tolist(), bwd_caps.tolist()


def _rank_bucket_tables(edge_src, edge_dst, n_max, n_src_rows, fw, bw,
                        fwd_caps, bwd_caps):
    """One rank's bucket tables padded to the shared caps — same
    layout/keys as build_sharded_bucket_tables minus the leading device
    axis, so one traced program serves every rank."""
    from ..ops.bucket_spmm import BucketPlan

    p = BucketPlan(edge_src, edge_dst, n_max, n_src_rows,
                   fwd_widths=fw, bwd_widths=bw)

    def pad_to_cap(mat, cap, sentinel):
        if mat.shape[0] == cap:
            return mat
        return np.pad(mat, ((0, cap - mat.shape[0]), (0, 0)),
                      constant_values=sentinel)

    def reoffset_inv(inv, cnts, caps):
        inv = inv.astype(np.int64)
        out = np.full_like(inv, sum(caps))
        off_old = off_new = 0
        for n_b, cap in zip(cnts, caps):
            in_b = (inv >= off_old) & (inv < off_old + n_b)
            out[in_b] = inv[in_b] - off_old + off_new
            off_old += n_b
            off_new += cap
        return out.astype(np.int32)

    t = {
        "bkt_fwd_inv": reoffset_inv(p.fwd_inv, p.fwd_counts, fwd_caps),
        "bkt_bwd_inv": reoffset_inv(p.bwd_inv, p.bwd_counts, bwd_caps),
    }
    for b in range(len(fw)):
        if fwd_caps[b]:
            t[f"bkt_fwd_{b:02d}"] = pad_to_cap(p.fwd_mats[b],
                                               fwd_caps[b], n_src_rows)
    for b in range(len(bw)):
        if bwd_caps[b]:
            t[f"bkt_bwd_{b:02d}"] = pad_to_cap(p.bwd_mats[b],
                                               bwd_caps[b], n_max)
    return t


class SequentialRunner:
    """One-rank-at-a-time executor of the pipelined training step.

    sg: a ShardedGraph (arrays may be v3 memmaps — only rank slices are
    materialized). feat_fn/label_fn(rank) optionally synthesize the
    rank's [n_max, F] features / [n_max] labels instead of reading
    sg.feat/sg.label (papers100M-scale artifacts store topology only).
    """

    def __init__(self, sg, cfg: ModelConfig, tcfg: TrainConfig,
                 feat_fn: Optional[Callable[[int], np.ndarray]] = None,
                 label_fn: Optional[Callable[[int], np.ndarray]] = None,
                 table_cache: Optional[Dict[int, dict]] = None,
                 compact_halo: bool = False,
                 keep_carry: bool = True,
                 log: Callable[[str], None] = lambda s: None,
                 metrics=None,
                 check_finite: bool = True,
                 fault_plan=None,
                 staleness_probe_every: int = 0):
        if not tcfg.enable_pipeline:
            raise ValueError("SequentialRunner implements the pipelined "
                             "(staleness-1) step; vanilla mode has "
                             "intra-epoch halo dependencies between "
                             "ranks and needs the mesh trainer")
        if cfg.norm == "batch":
            raise ValueError("SyncBatchNorm needs intra-epoch psum of "
                             "activations; use norm='layer' or None")
        if cfg.model == "gat":
            raise ValueError("gat is not wired into SequentialRunner")
        if cfg.use_pp:
            raise ValueError("use_pp's one-shot precompute is a "
                             "cross-rank exchange; run with use_pp=False")
        self.sg = sg
        self.cfg = dataclasses.replace(cfg, sorted_edges=True)
        self.tcfg = tcfg
        self.P = sg.num_parts
        self.n_max = sg.n_max
        self.b_max = sg.b_max
        self.H = sg.halo_size
        self.n_train = float(sg.n_train_global)
        self._feat_fn = feat_fn
        self._label_fn = label_fn
        # caching every rank's tables would break the O(one-rank) RAM
        # bound this class exists for (P=64 at papers100M scale is tens
        # of GB of tables) — it is therefore strictly opt-in: pass a
        # dict (can be lru-like) only when the graph is small enough
        self._table_cache = table_cache
        self._log = log
        # optional obs.MetricsLogger: run_epoch appends one epoch
        # record per completed epoch (same schema the mesh trainer
        # emits, obs/schema.py), so full-scale sequential validation
        # runs feed the same report CLI
        self._metrics = metrics
        # resilience wiring (docs/RESILIENCE.md): a multi-hour
        # sequential epoch must not keep burning ranks after the loss
        # went non-finite — run_epoch raises DivergenceError (emitting
        # a fault record) and the caller decides rollback; the host
        # holds params/opt, so any checkpoint discipline works.
        # fault_plan (resilience.FaultPlan) supports nan-loss injection
        # for chaos-testing that path.
        self._check_finite = check_finite
        self._fault_plan = fault_plan
        # staleness probes (same contract as Trainer.fit's
        # staleness_probe_every; obs/schema.py 'staleness' records): on
        # probe epochs run_epoch compares the stale halo rows each rank
        # consumed against the fresh ones it routed — host arrays here,
        # so the drift is a plain numpy reduction over ranks
        self._probe_every = max(int(staleness_probe_every), 0)
        if self._probe_every and not keep_carry:
            raise ValueError("staleness probes need keep_carry=True "
                             "(one-shot mode has no carry to compare)")

        self._glayers = [str(i) for i in range(cfg.n_graph_layers)]
        self._widths = {k: cfg.layer_sizes[int(k)] for k in self._glayers}

        # compact_halo: replace the mesh trainer's uniform per-distance
        # pad (b_max = global max over ALL (owner, dest) pairs) with
        # per-distance caps B_d = max over owners of send_counts[:, d-1].
        # On power-law graphs (papers100M class) the uniform pad wastes
        # ~10x halo rows — locality puts huge send lists at distance 1
        # and small ones everywhere else. Exact for dropout=0 (dropped
        # pad rows are zero-feature, zero-edge); with dropout>0 the
        # [N+H, F] mask shape changes, so trajectories differ from the
        # mesh trainer by dropout noise only.
        self.compact = compact_halo
        # keep_carry=False: one-shot mode — run epoch 0 (stale buffers
        # are zeros by definition) without routing or storing the next
        # carry. The carry for ALL ranks is inherently distributed state
        # (P x layers x 2 x [H, F] — hundreds of GB at papers100M
        # scale); a single-host full-scale validation step cannot hold
        # it, and does not need to for one step.
        self.keep_carry = keep_carry
        if self.compact and self.P > 1:
            caps = [int(np.max(np.asarray(sg.send_counts)[:, dd]))
                    for dd in range(self.P - 1)]
            # round to 8 for layout friendliness but never beyond the
            # artifact's own pad (send_idx is only b_max wide)
            caps = [min(-(-c // 8) * 8, self.b_max) if c else 0
                    for c in caps]
            self._b_caps = caps
            self._b_off = np.concatenate(
                [[0], np.cumsum(caps)]).astype(np.int64)
            self.H = int(self._b_off[-1])
        else:
            self.compact = False
            self._b_caps = [self.b_max] * max(self.P - 1, 0)
            self._b_off = np.arange(self.P) * self.b_max
        n_src_rows = self.n_max + self.H
        self._ladder = _ladder_caps(
            lambda r: self._remap_src(r, np.asarray(
                sg.edge_src[r][:int(sg.edge_count[r])])),
            lambda r: np.asarray(sg.edge_dst[r][:int(sg.edge_count[r])]),
            self.P, self.n_max, n_src_rows)
        self._n_src_rows = n_src_rows
        # telemetry: host-routed halo traffic per epoch (forward rows +
        # returned cotangents for every rank) — the sequential analogue
        # of Trainer.est_halo_bytes_per_epoch
        item = jnp.dtype(self.cfg.compute_dtype).itemsize
        self._halo_bytes = 0 if self.P == 1 else int(sum(
            2 * self.P * self.H * w * item
            for w in self._widths.values()))

        rng = jax.random.PRNGKey(tcfg.seed)
        self.params = init_params(rng, self.cfg)
        self.opt = adam_init(self.params)
        self.norm = init_norm_state(self.cfg)

        cdt = self.cfg.compute_dtype
        zeros = lambda dt: {
            k: np.zeros((self.H, self._widths[k]), dt)
            for k in self._glayers}
        # per-rank receiver-side carry, exactly Trainer._init_comm
        self.comm = [
            {"halo": zeros(cdt), "bgrad": zeros(cdt),
             **({"favg": zeros(np.float32)} if tcfg.feat_corr else {}),
             **({"bavg": zeros(np.float32)} if tcfg.grad_corr else {})}
            for _ in range(self.P)
        ] if keep_carry else None
        self.last_epoch = 0
        self._jit_rank = jax.jit(self._make_rank_step())
        self._jit_adam = jax.jit(
            lambda g, o, p: adam_update(g, o, p, lr=tcfg.lr,
                                        weight_decay=tcfg.weight_decay))

    # ---------------- per-rank data ----------------------------------
    def _remap_src(self, r: int, src: np.ndarray) -> np.ndarray:
        """Map halo slots from the artifact's uniform-b_max numbering
        (n_max + (d-1)*b_max + k, partition/halo.py _localize_edges) to
        the compact per-distance layout. Identity when not compact."""
        if not self.compact:
            return src
        halo = src >= self.n_max
        slot = src[halo].astype(np.int64) - self.n_max
        dd = slot // self.b_max          # distance-1 index (d-1)
        k = slot % self.b_max
        out = src.astype(np.int64).copy()
        out[halo] = self.n_max + self._b_off[dd] + k
        return out

    def _compact_send(self, r: int):
        """Flattened send idx/mask in the compact per-distance layout
        ([H] each; rows beyond a distance's real send count masked)."""
        sg = self.sg
        idx = np.zeros(self.H, np.int32)
        mask = np.zeros(self.H, bool)
        for dd in range(self.P - 1):
            c = self._b_caps[dd]
            if not c:
                continue
            o = int(self._b_off[dd])
            idx[o:o + c] = np.asarray(sg.send_idx[r, dd, :c])
            mask[o:o + c] = np.asarray(sg.send_mask[r, dd, :c])
        return idx, mask

    def _rank_data(self, r: int) -> Dict[str, np.ndarray]:
        sg = self.sg
        e = int(sg.edge_count[r])
        src = self._remap_src(r, np.asarray(sg.edge_src[r][:e]))
        dst = np.asarray(sg.edge_dst[r][:e])
        if self._table_cache is not None and r in self._table_cache:
            tables = self._table_cache[r]
        else:
            fw, bw, fc, bc = self._ladder
            tables = _rank_bucket_tables(src, dst, self.n_max,
                                         self._n_src_rows, fw, bw, fc, bc)
            if self._table_cache is not None:
                self._table_cache[r] = tables
        feat = (self._feat_fn(r) if self._feat_fn is not None
                else np.asarray(sg.feat[r]))
        label = (self._label_fn(r) if self._label_fn is not None
                 else np.asarray(sg.label[r]))
        if self.compact:
            sidx, smask = self._compact_send(r)
        else:
            sidx = np.asarray(sg.send_idx[r]).astype(np.int32).reshape(-1)
            smask = np.asarray(sg.send_mask[r]).reshape(-1)
        d = {
            "feat": feat.astype(self.cfg.compute_dtype),
            "label": label,
            "train_mask": np.asarray(sg.train_mask[r]),
            "in_deg": np.asarray(sg.in_deg[r]),
            # flat [H] in both layouts (the uniform layout's flattened
            # [P-1, B] order IS the halo slot order)
            "send_idx": sidx,
            "send_mask": smask,
            "row_mask": (np.arange(self.n_max)
                         < int(sg.inner_count[r])).astype(np.float32),
        }
        d.update(tables)
        return d

    # ---------------- the jitted per-rank step ------------------------
    def _make_rank_step(self):
        cfg, tcfg = self.cfg, self.tcfg
        n_max, H = self.n_max, self.H
        glayers, widths = self._glayers, self._widths
        multilabel = self.sg.multilabel
        cdt = cfg.compute_dtype
        keep_carry = self.keep_carry

        def rank_step(params, norm, rng, d, stale_halo, stale_bgrad):
            """stale_halo/stale_bgrad: {layer: [H, F]} in compute dtype —
            already the corrected (EMA) buffers when corr is on; the
            host picks them, mirroring trainer.py:697-706."""
            from ..ops.bucket_spmm import make_device_bucket_spmm_fn
            from ..resilience.numerics import PHASES

            probes = {k: jnp.zeros((H, widths[k]), cdt) for k in glayers}
            sends = {}

            def comm_update(i, h):
                k = str(i)
                op = make_stale_concat(d["send_idx"], d["send_mask"],
                                       n_max)
                fbuf = op(h, stale_halo[k], stale_bgrad[k], probes_in[k])
                if keep_carry:
                    hs = jax.lax.stop_gradient(h)
                    # this epoch's send rows [H, F], routed by the host
                    # in halo slot order (exchange_blocks's pre-permute
                    # payload, flattened)
                    blk = jnp.take(hs, d["send_idx"], axis=0,
                                   mode="clip")
                    sends[k] = jnp.where(d["send_mask"][:, None], blk,
                                         0.0)
                return fbuf

            spmm_fn = make_device_bucket_spmm_fn(
                d, d["in_deg"], self._n_src_rows,
                chunk_edges=cfg.spmm_chunk, rem_dtype=cfg.rem_dtype)
            edge_dummy = jnp.zeros((8,), jnp.int32)

            def loss_fn(params, probes_arg):
                nonlocal probes_in
                probes_in = probes_arg
                # numerics tripwire: same per-phase non-finite counts
                # the mesh trainer harvests (resilience/numerics.py),
                # summed across ranks by run_epoch
                counts = {ph: jnp.zeros((), jnp.int32) for ph in PHASES}

                def nf_probe(name, x):
                    counts[name] = counts[name] + jnp.sum(
                        ~jnp.isfinite(x), dtype=jnp.int32)

                logits, new_norm = forward(
                    params, cfg, d["feat"], edge_dummy, edge_dummy,
                    d["in_deg"], n_max, training=True, rng=rng,
                    comm_update=comm_update, norm_state=norm,
                    psum=lambda x: x, row_mask=d["row_mask"],
                    spmm_fn=spmm_fn, gat_fn=None,
                    probe=nf_probe,
                )
                if multilabel:
                    loss = bce_logits_sum(logits, d["label"],
                                          d["train_mask"])
                else:
                    loss = cross_entropy_sum(logits, d["label"],
                                             d["train_mask"])
                counts["loss"] = counts["loss"] + jnp.sum(
                    ~jnp.isfinite(loss), dtype=jnp.int32)
                return loss, (new_norm, counts)

            probes_in = probes
            if keep_carry:
                (loss, (new_norm, counts)), (pgrads, probe_grads) = \
                    jax.value_and_grad(loss_fn, argnums=(0, 1),
                                       has_aux=True)(params, probes)
                return loss, pgrads, probe_grads, sends, new_norm, counts
            # one-shot mode: no next-epoch carry, so neither the probe
            # cotangents nor the send rows are fetched (XLA drops the
            # dead halo-cotangent extraction)
            (loss, (new_norm, counts)), pgrads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, probes)
            return loss, pgrads, {}, {}, new_norm, counts

        return rank_step

    # ---------------- epoch loop --------------------------------------
    def run_epoch(self, epoch: int,
                  state_path: Optional[str] = None) -> float:
        """state_path (one-shot mode only): checkpoint the grad
        accumulator + rank cursor after every rank, so a multi-hour
        full-scale epoch survives interruption — the partial sums are
        exact (host psum is associative) and a restart resumes at the
        next rank."""
        import os
        import pickle

        t_start = time.perf_counter()
        tcfg, P, H = self.tcfg, self.P, self.H
        cdt = self.cfg.compute_dtype
        if state_path is not None and self.keep_carry:
            raise ValueError("per-rank resume requires keep_carry=False "
                             "(the carry would need checkpointing too)")
        if tcfg.rng_impl != "threefry":
            base = jax.random.key(tcfg.seed + 17, impl=tcfg.rng_impl)
        else:
            base = jax.random.PRNGKey(tcfg.seed + 17)
        rng_e = jax.random.fold_in(base, epoch)

        tm = jax.tree_util.tree_map
        loss_sum = 0.0
        grad_sum = None
        start_rank = 0
        # crash-consistency: the per-rank state is only resumable
        # against the SAME topology generation it was summed over —
        # partial gradients straddling a graph delta are silently
        # wrong, so a generation mismatch restarts the epoch at rank 0
        gen = int(getattr(self, "topo_generation", 0))
        if state_path is not None and os.path.exists(state_path):
            with open(state_path, "rb") as f:
                st = pickle.load(f)
            if (st["epoch"] == epoch
                    and int(st.get("topo_generation", 0)) == gen):
                start_rank = st["next_rank"]
                loss_sum = st["loss_sum"]
                grad_sum = st["grad_sum"]
                self._log(f"resuming epoch {epoch} at rank {start_rank}")
            elif st["epoch"] == epoch:
                self._log(
                    f"discarding per-rank state: summed at "
                    f"topo_generation {st.get('topo_generation', 0)}, "
                    f"graph now at {gen} — restarting epoch {epoch} "
                    f"at rank 0")
        sends_all, probes_all = [], []
        new_norm0 = None
        nf_counts: Dict[str, int] = {}
        zero_stale = {k: np.zeros((H, self._widths[k]), cdt)
                      for k in self._glayers} if self.comm is None else None
        for r in range(start_rank, P):
            d = self._rank_data(r)
            if self.comm is None:  # one-shot: epoch-0 staleness = zeros
                stale_halo = stale_bgrad = zero_stale
            else:
                c = self.comm[r]
                stale_halo = {
                    k: (c["favg"][k].astype(cdt) if tcfg.feat_corr
                        else c["halo"][k]) for k in self._glayers}
                stale_bgrad = {
                    k: (c["bavg"][k].astype(cdt) if tcfg.grad_corr
                        else c["bgrad"][k]) for k in self._glayers}
            rng_r = jax.random.fold_in(rng_e, r)
            loss, pgrads, probe_grads, sends, new_norm, counts = \
                jax.device_get(
                    self._jit_rank(self.params, self.norm, rng_r, d,
                                   stale_halo, stale_bgrad))
            for k, v in counts.items():
                nf_counts[k] = nf_counts.get(k, 0) + int(v)
            loss_sum += float(loss)
            grad_sum = (pgrads if grad_sum is None
                        else tm(np.add, grad_sum, pgrads))
            sends_all.append(sends)
            probes_all.append(probe_grads)
            if new_norm0 is None:
                new_norm0 = new_norm
            if state_path is not None:
                with open(state_path + ".tmp", "wb") as f:
                    pickle.dump({"epoch": epoch, "next_rank": r + 1,
                                 "loss_sum": loss_sum,
                                 "grad_sum": grad_sum,
                                 "topo_generation": gen}, f)
                os.replace(state_path + ".tmp", state_path)
            self._log(f"rank {r}: loss_sum {loss_sum:.4f}")

        # ---- host-side collectives ----
        pgrads = tm(lambda g: (g / self.n_train).astype(np.float32),
                    grad_sum)
        self.params, self.opt = jax.device_get(
            self._jit_adam(pgrads, self.opt, self.params))
        if new_norm0 is not None:  # resumed-at-P restarts keep norm
            self.norm = new_norm0

        probe_due = (self._probe_every > 0
                     and epoch % self._probe_every == 0
                     and self.comm is not None)
        drift_sq = {k: 0.0 for k in self._glayers}
        fresh_sq = {k: 0.0 for k in self._glayers}
        if self.comm is not None:
            for r in range(P):
                c = self.comm[r]
                for k in self._glayers:
                    halo_next = np.zeros((H, self._widths[k]), cdt)
                    bgrad_next = np.zeros((H, self._widths[k]), cdt)
                    for dd in range(1, P):
                        sl = slice(int(self._b_off[dd - 1]),
                                   int(self._b_off[dd - 1])
                                   + self._b_caps[dd - 1])
                        # _fwd_perm: r receives owner (r-d)'s
                        # distance-d send rows (same slot range)
                        halo_next[sl] = sends_all[(r - dd) % P][k][sl]
                        # _bwd_perm: r's send rows were consumed by (r+d)
                        bgrad_next[sl] = probes_all[(r + dd) % P][k][sl]
                    if probe_due:
                        # stale = the carry consumed this epoch, fresh
                        # = what the ranks just routed; aggregate the
                        # squared norms over every rank
                        d = (halo_next.astype(np.float64)
                             - c["halo"][k].astype(np.float64))
                        drift_sq[k] += float(np.sum(d * d))
                        fresh_sq[k] += float(np.sum(
                            halo_next.astype(np.float64) ** 2))
                    c["halo"][k] = halo_next
                    c["bgrad"][k] = bgrad_next
                    m = tcfg.corr_momentum
                    if tcfg.feat_corr:
                        c["favg"][k] = (
                            m * c["favg"][k]
                            + (1 - m) * halo_next.astype(np.float32))
                    if tcfg.grad_corr:
                        c["bavg"][k] = (
                            m * c["bavg"][k]
                            + (1 - m) * bgrad_next.astype(np.float32))
        self.last_epoch = epoch + 1
        mean_loss = loss_sum / self.n_train
        # grad norm over the reduced (psum'd / n_train) gradient —
        # telemetry AND the finiteness guard below
        gnorm = float(np.sqrt(sum(
            float(np.sum(np.square(np.asarray(g, np.float64))))
            for g in jax.tree_util.tree_leaves(pgrads))))
        if self._fault_plan is not None and \
                self._fault_plan.due("nan-loss", epoch):
            self._log(f"fault-injected nan loss at epoch {epoch}")
            mean_loss = float("nan")
        if self._metrics is not None:
            # same record shape as the mesh trainer's (obs/schema.py)
            self._metrics.epoch(
                epoch=epoch,
                step_time_s=time.perf_counter() - t_start,
                loss=float(mean_loss),
                grad_norm=gnorm,
                halo_bytes=self._halo_bytes,
                staleness_age=int(1 if epoch > 0 else 0),
                memory=memory_snapshot(),
            )
        if probe_due:
            layers = {}
            max_rel = 0.0
            for k in self._glayers:
                dn = float(np.sqrt(drift_sq[k]))
                fn = float(np.sqrt(fresh_sq[k]))
                rel = dn / fn if fn > 0 else (0.0 if dn == 0.0 else 1.0)
                layers[k] = {"rel_drift": rel, "fresh_norm": fn}
                max_rel = max(max_rel, rel)
            if self._metrics is not None:
                self._metrics.staleness(epoch=epoch, layers=layers,
                                        max_rel_drift=max_rel)
            self._log(f"staleness probe epoch {epoch}: max relative "
                      f"drift {max_rel:.4f}")
        if self._check_finite and not (np.isfinite(mean_loss)
                                       and np.isfinite(gnorm)):
            from ..resilience import DivergenceError
            from ..resilience.numerics import first_nonfinite_phase

            reason = (f"non-finite loss {mean_loss!r}"
                      if not np.isfinite(mean_loss)
                      else f"non-finite grad norm {gnorm!r}")
            # tripwire provenance: the per-rank counts name the phase
            # where the non-finite value was born
            phase = first_nonfinite_phase(nf_counts)
            extra = {"phase": phase} if phase else {}
            if phase:
                reason += f" (first non-finite phase: {phase})"
            if self._metrics is not None:
                self._metrics.fault(kind="divergence", epoch=epoch,
                                    reason=reason, **extra)
            raise DivergenceError(
                f"sequential epoch {epoch}: {reason}; the caller holds "
                f"the host-side state and decides rollback")
        return mean_loss
