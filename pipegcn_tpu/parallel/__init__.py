from ..compat import ensure_jax_compat

ensure_jax_compat()  # older jax: alias shard_map/pcast before any use

from .mesh import make_mesh, PARTS_AXIS
from .halo import halo_exchange, exchange_blocks, return_blocks, make_stale_concat
from .trainer import Trainer, TrainConfig
from .evaluator import ShardedEvaluator
from .sequential import SequentialRunner

__all__ = [
    "make_mesh",
    "PARTS_AXIS",
    "halo_exchange",
    "exchange_blocks",
    "return_blocks",
    "make_stale_concat",
    "Trainer",
    "TrainConfig",
    "ShardedEvaluator",
    "SequentialRunner",
]
